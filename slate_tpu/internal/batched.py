"""Ragged batched factorization drivers over the batched Pallas panels.

The serving layer packs mixed-size problems into one identity-augmented
bucket stack (serve/server.py pad_square/pad_tall): problem i of size
s_i occupies the top-left s_i x s_i of its [n, n] slot, the rest of the
diagonal is I, and filler slots are whole identity (or zero for QR row
padding).  The vmapped XLA cores then factor every slot at the FULL
bucket size — `bench_serve_mixed` records the padding-waste% that burns.

These drivers are the ragged alternative: a left-looking blocked loop
over the bucket's block columns where every panel step is ONE batched
Pallas call (pallas_chol.chol_panel_batched / pallas_lu.lu_panel_batched
/ pallas_qr.qr_panel_batched) whose grid carries the per-problem sizes
via scalar prefetch — each problem computes only its own live tiles and
identity-completes the rest EXACTLY (dead tiles copy their input
through, which for identity-augmented packing IS their factor), so the
batched factor is bit-identical in the padding region to factoring the
augmented matrix whole and numerically equal on the live region.

Raggedness granularity: Cholesky/LU skip per row TILE (k + i >=
ceil(s_i / nb)); QR skips per PROBLEM only — its identity-augmented
padding columns own real reflectors, so a live problem factors its
whole bucket panel while zero-row filler slots pass through.

ABFT: batch_potrf re-uses the exact checksum rungs of the single-shot
driver (robust/abft.py chol_tile_check + left_product_check), vmapped
over the batch against the pre-factor panels the kernel emits — a
transient post_panel strike is detected and repaired in-batch.  The
block-column gemm checksum rung (sum_check) is not replicated: a fault
inside the fused rank-k update surfaces as a stale factored element
that the tile/panel rungs see, matching the fused single-shot path's
coverage argument (drivers/cholesky.py potrf_nopiv).

Selection is the tune/ plan cache's job: serve/batched.py routes here
only when `tune.resolve_plan` hands back a Pallas plan for the
`batch_potrf`/`batch_getrf`/`batch_geqrf` ops (SEAM011) — nothing else
imports these drivers for dispatch.

Real f32 or bf16 storage (the Pallas panels' contract); callers gate on
dtype via the serving route's normalized check.  On bf16 input every
panel accumulates in f32 inside the kernel, and the XLA glue between
panels (U12 solves, WY trailing updates, the solve readers) promotes
factor blocks to f32, computes, and demotes only the values stored back
in bf16 — solves against a bf16 factor always RETURN f32 (the refine
side of the factor-low/refine-high split; robust/precision.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..robust import abft as _abft
from ..robust import faults
from .pallas_chol import chol_panel_batched
from .pallas_lu import lu_panel_batched
from .pallas_qr import qr_panel_batched

_HI = lax.Precision.HIGHEST


def _f32(x):
    """Promote a factor block to f32 for the XLA glue between panels —
    a no-op on f32 input, so the f32 route's numerics are unchanged."""
    return x.astype(jnp.float32)


def tile_counts(sizes, nb: int):
    """Per-problem live tile counts ceil(sizes / nb), int32 [B]."""
    return ((sizes + (nb - 1)) // nb).astype(jnp.int32)


def batch_potrf(a, sizes, *, nb: int, bw: int = 8, interpret: bool = False,
                abft: bool = False):
    """Ragged batched Cholesky: lower factors of identity-augmented SPD
    slots ``a`` [B, n, n] with live sizes ``sizes`` [B], n % nb == 0.

    Returns ``(fa, counts)``: ``fa`` carries L in its lower triangle
    (the strict upper triangle keeps input values, as the single-shot
    blocked driver leaves it); ``counts`` is a batched AbftCounts —
    zeros unless ``abft``.
    """
    bsz, n, _ = a.shape
    tiles = tile_counts(sizes, nb)
    counts = jax.vmap(lambda _: _abft.zero_counts())(jnp.arange(bsz))
    fa = a
    for k in range(n // nb):
        k0, k1 = k * nb, (k + 1) * nb
        col = fa[:, k0:, k0:k1]
        left = fa[:, k0:, :k0]
        lead = jnp.swapaxes(fa[:, k0:k1, :k0], 1, 2)
        upd, fac = chol_panel_batched(col, left, lead, tiles, k=k, bw=bw,
                                      interpret=interpret)
        if abft:
            fac = faults.maybe_corrupt("post_panel", fac)
            lkk, det, cor = jax.vmap(
                lambda h, l: _abft.chol_tile_check(h, l, n_ctx=n))(
                    upd[:, :nb], fac[:, :nb])
            fac = fac.at[:, :nb].set(lkk)
            counts = _abft.add_counts(counts, jax.vmap(
                lambda d, c: _abft.count_event(d, c, k, k))(det, cor))
            if k1 < n:
                # panel X solves X L^T = R; transpose into the canonical
                # left product L X^T = R^T, verified via R's checksums
                xh, det, cor, _, pj = jax.vmap(
                    lambda l, x, rr, rc: _abft.left_product_check(
                        l, x, rr, rc, unit=False, n_ctx=n))(
                            lkk, jnp.swapaxes(fac[:, nb:], 1, 2),
                            jnp.sum(upd[:, nb:], axis=1),
                            jnp.sum(upd[:, nb:], axis=2))
                fac = fac.at[:, nb:].set(jnp.swapaxes(xh, 1, 2))
                counts = _abft.add_counts(counts, jax.vmap(
                    lambda d, c, p: _abft.count_event(
                        d, c, (k1 + p) // nb, k))(det, cor, pj))
        fa = fa.at[:, k0:, k0:k1].set(fac)
    return fa, counts


def batch_getrf(a, sizes, *, nb: int, bw: int = 8,
                interpret: bool = False):
    """Ragged batched no-pivot LU: packed L\\U of identity-augmented
    slots ``a`` [B, n, n] with live sizes ``sizes`` [B], n % nb == 0.
    Unit lower implied, same packing as getrf.panel_lu_nopiv."""
    bsz, n, _ = a.shape
    tiles = tile_counts(sizes, nb)
    fa = a
    for k in range(n // nb):
        k0, k1 = k * nb, (k + 1) * nb
        col = fa[:, k0:, k0:k1]
        left = fa[:, k0:, :k0]
        lead = fa[:, :k0, k0:k1]
        _, fac = lu_panel_batched(col, left, lead, tiles, k=k, bw=bw,
                                  interpret=interpret)
        fa = fa.at[:, k0:, k0:k1].set(fac)
        if k1 < n:
            # U12 row block: padding rows of r are exactly zero (zero A
            # rows, zero L10 rows) and the unit-lower solve against the
            # block-diagonal L11 never mixes padding and live rows, so
            # the padding region stays exactly 0.
            r = _f32(fa[:, k0:k1, k1:]) - jnp.matmul(
                _f32(fa[:, k0:k1, :k0]), _f32(fa[:, :k0, k1:]),
                precision=_HI)
            u12 = lax.linalg.triangular_solve(
                _f32(fac[:, :nb]), r, left_side=True, lower=True,
                unit_diagonal=True)
            fa = fa.at[:, k0:k1, k1:].set(u12.astype(a.dtype))
    return fa


def batch_getrs(fa, b):
    """Solve with a batched packed no-pivot L\\U: unit-lower forward
    substitution then upper back substitution.  fa [B, n, n], b
    [B, n, k].  A bf16 factor is promoted and solved in f32 (the result
    follows ``b``'s dtype, the refine-side precision)."""
    fh = _f32(fa)
    y = lax.linalg.triangular_solve(fh, _f32(b), left_side=True, lower=True,
                                    unit_diagonal=True)
    x = lax.linalg.triangular_solve(fh, y, left_side=True, lower=False)
    return x.astype(b.dtype)


def batch_geqrf(a, rows, *, nb: int, interpret: bool = False):
    """Ragged batched Householder QR of ``a`` [B, mb, n] with per-problem
    live row counts ``rows`` [B] (zero marks a filler slot), n % w == 0
    for w = min(nb, n), mb >= n.

    Returns ``(packed, ts)``: per-problem packed panels (R in/above the
    diagonal, Householder vectors below, unit diagonal implied) and the
    stacked compact-WY triangles ts [B, n//w, w, w].  Q = prod_j
    (I - V_j T_j V_j^T) over the panels in order."""
    bsz, mb, n = a.shape
    w = min(nb, n)
    packed = a
    ts = []
    for j in range(n // w):
        j0, j1 = j * w, (j + 1) * w
        m = mb - j0
        pk, t = qr_panel_batched(packed[:, j0:, j0:j1], rows,
                                 interpret=interpret)
        packed = packed.at[:, j0:, j0:j1].set(pk)
        ts.append(t)
        if j1 < n:
            v = _f32(jnp.tril(pk, -1)) + jnp.eye(m, w, dtype=jnp.float32)[None]
            c = _f32(packed[:, j0:, j1:])
            g = jnp.matmul(jnp.swapaxes(v, 1, 2), c, precision=_HI)
            g = jnp.matmul(jnp.swapaxes(_f32(t), 1, 2), g, precision=_HI)
            packed = packed.at[:, j0:, j1:].set(
                (c - jnp.matmul(v, g, precision=_HI)).astype(a.dtype))
    return packed, jnp.stack(ts, axis=1)


def batch_gels(a, b, rows, *, nb: int, interpret: bool = False):
    """Ragged batched least squares via batch_geqrf: minimize
    ||a_i x_i - b_i|| per problem.  a [B, mb, n], b [B, mb, k], returns
    ``(x [B, n, k], packed)`` with x = R^-1 (Q^T b)[:n].  A bf16 factor
    applies Q^T and solves against R in f32 (x follows ``b``'s dtype)."""
    bsz, mb, n = a.shape
    packed, ts = batch_geqrf(a, rows, nb=nb, interpret=interpret)
    w = ts.shape[2]
    y = _f32(b)
    for j in range(n // w):
        j0 = j * w
        m = mb - j0
        pk = packed[:, j0:, j0:j0 + w]
        v = _f32(jnp.tril(pk, -1)) + jnp.eye(m, w, dtype=jnp.float32)[None]
        t = _f32(ts[:, j])
        c = y[:, j0:]
        g = jnp.matmul(jnp.swapaxes(v, 1, 2), c, precision=_HI)
        g = jnp.matmul(jnp.swapaxes(t, 1, 2), g, precision=_HI)
        y = y.at[:, j0:].set(c - jnp.matmul(v, g, precision=_HI))
    x = lax.linalg.triangular_solve(_f32(packed[:, :n, :n]), y[:, :n],
                                    left_side=True, lower=False)
    return x.astype(b.dtype), packed


def batch_chol_health(fa):
    """Batched HealthInfo for batch_potrf factors, built with the same
    helper the single-shot driver uses (drivers/cholesky._chol_health):
    padding diagonal entries are exactly 1, so they never win the
    min-pivot argmin away from a genuine failure."""
    from ..drivers.cholesky import _chol_health

    def one(f):
        d = jnp.abs(jnp.diagonal(f))
        d = jnp.where(jnp.isnan(d), jnp.zeros_like(d), d)
        mi = jnp.argmin(d)
        # tril: the loop never writes the strict upper triangle, which
        # still holds input values (same as the single-shot driver)
        return _chol_health(jnp.tril(f), d[mi], mi)

    return jax.vmap(one)(fa)


def batch_lu_health(a, fa):
    """Batched HealthInfo for batch_getrf factors via
    drivers/lu._lu_health (zero/NaN pivot -> info, growth = max|L\\U| /
    max|A|; padding contributes 1s to both, never masking a blow-up)."""
    from ..drivers.lu import _lu_health

    def one(ai, fi):
        ud = jnp.abs(jnp.diagonal(fi))
        mi = jnp.argmin(ud)
        return _lu_health(fi, ud[mi], mi, jnp.max(jnp.abs(ai)))

    return jax.vmap(one)(a, fa)
