"""Shared in-kernel triangular inverse for the fused Pallas panels.

The fused panel kernels (pallas_chol / pallas_lu) turn their TRSM stage
into one MXU gemm per row tile by materializing U^-1 once on the
diagonal tile.  Inside a Mosaic kernel there is no triangular_solve, so
the inverse is built from the factorization U = D (I + N) with D the
diagonal and N strictly upper — N is nilpotent, hence

    (I + N)^-1 = (I - N)(I + N^2)(I + N^4) ...   (log2(n) MXU dots)

is EXACT in exact arithmetic (same trick as pallas_lu's deferred
trailing update, just at tile scale).  U^-1 = (I + N)^-1 D^-1.

Everything here is plain jnp on values (no refs), so the helper runs
unchanged inside a Pallas kernel, under interpret=True, or in a host
test.  Masks use iota comparisons rather than tril/triu so Mosaic never
sees a bool vector cross a loop boundary.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

_HI = lax.Precision.HIGHEST


def upper_tri_inv(u):
    """Inverse of an upper-triangular [n, n] (nonzero diagonal; entries
    below the diagonal are ignored)."""
    n = u.shape[0]
    dt = u.dtype
    r = lax.broadcasted_iota(jnp.int32, (n, n), 0)
    c = lax.broadcasted_iota(jnp.int32, (n, n), 1)
    eye = (r == c).astype(dt)
    u = jnp.where(r <= c, u, 0.0)
    dcol = jnp.sum(jnp.where(r == c, u, 0), axis=1, keepdims=True)  # [n, 1]
    drow = jnp.sum(jnp.where(r == c, u, 0), axis=0, keepdims=True)  # [1, n]
    N = u * (1.0 / dcol) - eye                   # strictly upper, nilpotent
    inv = eye - N
    N2 = jnp.dot(N, N, preferred_element_type=dt, precision=_HI)
    steps = 1
    while 2 * steps < n:
        inv = jnp.dot(inv, eye + N2, preferred_element_type=dt,
                      precision=_HI)
        N2 = jnp.dot(N2, N2, preferred_element_type=dt, precision=_HI)
        steps *= 2
    return inv * (1.0 / drow)                    # (I + N)^-1 D^-1
