"""Pallas TPU kernel: Cholesky of one diagonal tile, VMEM-resident.

The reference factors diagonal tiles with vendor LAPACK potrf
(ref: src/internal/internal_potrf.cc:132).  XLA's TPU Cholesky runs a
per-column While loop — measured 2.07 ms for a 512x512 f32 tile
(docs/ceiling.jsonl xla_cholesky_512), which times 32 sequential panel
steps is the single largest cost in a 16k potrf.  This kernel keeps the
whole tile in VMEM for the entire factorization.

Formulation: the UPPER factor U with A = U^T U, processed in ``bw``-ROW
panels — Mosaic only allows dynamic slicing in 128-multiples along the
lane (last) dimension, but sublane (row) slices may move in multiples of
8, so an 8-row panel keeps every sequential step's operand at one vreg
row [8, n] instead of a [n, 128] half-tile.  The diagonal block is
mirrored into a [bw, bw] array via a one-hot MXU contraction (no lane
slicing), scalars come from mask+reduce, and the inter-panel trailing
update is a single MXU dot P^T P.  The caller transposes U once to
return the conventional lower L.

Fused panel variant (chol_panel_fused): one pallas_call grid performs
the whole left-looking panel step — the rank-k update from the already
factored block row, the diagonal-tile factorization, and the TRSM that
forms L21 — without the panel ever leaving VMEM between stages.  Grid
(Mt, Kc) walks row tiles (major) x K chunks (minor, auto double-buffered
HBM->VMEM by the BlockSpec pipeline); an accumulator scratch carries the
updated tile across K chunks, and a second scratch carries U^-1 from the
diagonal tile (row tile 0) to every trailing row tile, whose TRSM is
then a single MXU gemm A21 U^-1 (pallas_tri.upper_tri_inv).  Both the
pre-factor update (for the ABFT checksum rungs) and the factored panel
are emitted.

Ragged batched variant (chol_panel_batched): the same fused panel step
with a leading batch grid dimension and a per-problem size-in-tiles
vector delivered via scalar prefetch (PrefetchScalarGridSpec) — each
problem computes only its own live tiles; dead tiles identity-complete
by copying their input through, so a bucket of mixed-size problems
never burns MXU cycles on padding.

Real f32 tiles everywhere; the batched variant additionally accepts
bf16 storage with fp32 accumulation — every MXU dot carries
``preferred_element_type=f32``, the VMEM accumulator and the factor
scratch are f32, and only the final panel write demotes back to the
input dtype (the MXU's native bf16xbf16->f32 contract; the certified
acceptance story lives in serve/batched.py + robust/precision.py).
Complex/f64 tiles use the XLA fallback (potrf_tile).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_tri import upper_tri_inv

_HI = lax.Precision.HIGHEST


def _chol_factor_in_place(o_ref, *, bw: int):
    """Factor the SPD tile held in ``o_ref`` into its UPPER factor U
    (A = U^T U, lower triangle exactly zero), in bw-row panels."""
    n = o_ref.shape[0]
    dt = o_ref.dtype
    rows = lax.broadcasted_iota(jnp.int32, (n, n), 0)
    pr = lax.broadcasted_iota(jnp.int32, (bw, n), 0)
    cn = lax.broadcasted_iota(jnp.int32, (1, n), 1)
    br = lax.broadcasted_iota(jnp.int32, (bw, bw), 0)
    bc = lax.broadcasted_iota(jnp.int32, (bw, bw), 1)
    bc1 = lax.broadcasted_iota(jnp.int32, (1, bw), 1)

    def block_step(b, _):
        j0 = b * bw
        P = o_ref[pl.ds(j0, bw), :]                  # [bw, n] row panel
        selT = (lax.broadcasted_iota(jnp.int32, (n, bw), 0)
                == j0 + lax.broadcasted_iota(jnp.int32, (n, bw), 1))
        D = jnp.dot(P, selT.astype(dt), preferred_element_type=dt,
                    precision=_HI)                   # P[:, j0:j0+bw]

        def col_step(i, PD):
            P, D = PD
            j = j0 + i
            piv = jnp.sqrt(jnp.sum(jnp.where((br == i) & (bc == i), D, 0)))
            inv = 1.0 / piv
            drow = jnp.sum(jnp.where(br == i, D, 0), axis=0,
                           keepdims=True)            # [1, bw] row i of D
            # u_j = row j of U: row i of P scaled, left-of-diag zeroed
            prow = jnp.sum(jnp.where(pr == i, P, 0), axis=0, keepdims=True)
            urow = jnp.where(cn < j, 0.0, prow * inv)
            # block-row couplings: u_j restricted to this panel's columns
            ublk = jnp.where(bc1 == i, piv, drow * inv)
            ublk = jnp.where(bc1 < i, 0.0, ublk)     # [1, bw]
            coefT = ublk.reshape(bw, 1)
            P = jnp.where(pr == i, urow,
                          jnp.where(pr > i, P - coefT * urow, P))
            D = jnp.where(br == i, ublk,
                          jnp.where(br > i, D - coefT * ublk, D))
            return P, D

        P, _ = lax.fori_loop(0, bw, col_step, (P, D))
        o_ref[pl.ds(j0, bw), :] = P
        # trailing rows: A -= P^T P (contract the panel-row axis)
        upd = lax.dot_general(P, P, (((0,), (0,)), ((), ())),
                              preferred_element_type=dt, precision=_HI)
        av = o_ref[:]
        o_ref[:] = jnp.where(rows >= j0 + bw, av - upd, av)
        return 0

    lax.fori_loop(0, n // bw, block_step, 0)


def _chol_kernel(a_ref, o_ref, *, bw: int):
    o_ref[:] = a_ref[:]
    _chol_factor_in_place(o_ref, bw=bw)


def _chol_panel_kernel(col_ref, left_ref, lead_ref, upd_ref, fac_ref,
                       acc_ref, uinv_ref, *, bw: int):
    i = pl.program_id(0)
    j = pl.program_id(1)
    kc = pl.num_programs(1)
    nb = col_ref.shape[0]
    dt = col_ref.dtype

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = col_ref[:]

    # left-looking rank-k chunk: acc -= A[i-tile, chunk] @ lead[chunk]
    acc_ref[:] = acc_ref[:] - jnp.dot(left_ref[:], lead_ref[:],
                                      preferred_element_type=dt,
                                      precision=_HI)

    @pl.when(j == kc - 1)
    def _finish():
        upd_ref[:] = acc_ref[:]              # pre-factor tile (ABFT rungs)

        @pl.when(i == 0)
        def _factor():
            _chol_factor_in_place(acc_ref, bw=bw)
            u = acc_ref[:]
            eye = (lax.broadcasted_iota(jnp.int32, (nb, nb), 0)
                   == lax.broadcasted_iota(jnp.int32, (nb, nb), 1))
            # L00 = U^T via one-hot MXU contraction (no transpose op)
            fac_ref[:] = lax.dot_general(u, eye.astype(dt),
                                         (((0,), (0,)), ((), ())),
                                         preferred_element_type=dt,
                                         precision=_HI)
            uinv_ref[:] = upper_tri_inv(u)

        @pl.when(i != 0)
        def _trsm():
            # L21 solves L21 L00^T = A21, i.e. L21 = A21 U^-1 (U = L00^T)
            fac_ref[:] = jnp.dot(acc_ref[:], uinv_ref[:],
                                 preferred_element_type=dt, precision=_HI)


@functools.partial(jax.jit, static_argnames=("bw", "interpret"))
def chol_panel_fused(col, left, lead, bw: int = 8, interpret: bool = False):
    """Fused left-looking Cholesky panel step.

    col:  [M, nb] trailing block column A[k0:, k0:k0+nb]
    left: [M, K]  factored block row A[k0:, :k0] (K == 0 on panel 0)
    lead: [K, nb] conj(A[k0:k0+nb, :k0])^T

    Returns (upd, fac): ``upd`` = col - left @ lead, the pre-factor panel
    the ABFT checksum rungs verify; ``fac`` = [L00; L21], the factored
    panel.  Caller guarantees f32, M % nb == 0, nb % bw == 0, M >= nb.
    """
    m, nb = col.shape
    k = left.shape[1]
    kb = nb
    kp = max(kb, -(-k // kb) * kb)
    if k != kp:                              # pad K chunks with zeros
        left = jnp.pad(left, ((0, 0), (0, kp - k)))
        lead = jnp.pad(lead, ((0, kp - k), (0, 0)))
    upd, fac = pl.pallas_call(
        functools.partial(_chol_panel_kernel, bw=bw),
        grid=(m // nb, kp // kb),
        in_specs=[pl.BlockSpec((nb, nb), lambda i, j: (i, 0)),
                  pl.BlockSpec((nb, kb), lambda i, j: (i, j)),
                  pl.BlockSpec((kb, nb), lambda i, j: (j, 0))],
        out_specs=[pl.BlockSpec((nb, nb), lambda i, j: (i, 0)),
                   pl.BlockSpec((nb, nb), lambda i, j: (i, 0))],
        out_shape=[jax.ShapeDtypeStruct((m, nb), col.dtype),
                   jax.ShapeDtypeStruct((m, nb), col.dtype)],
        scratch_shapes=[pltpu.VMEM((nb, nb), col.dtype),
                        pltpu.VMEM((nb, nb), col.dtype)],
        interpret=interpret,
    )(col, left, lead)
    return upd, fac


def _chol_panel_batched_kernel(tiles_ref, col_ref, left_ref, lead_ref,
                               upd_ref, fac_ref, acc_ref, uinv_ref,
                               *, k: int, bw: int):
    b = pl.program_id(0)
    i = pl.program_id(1)
    j = pl.program_id(2)
    kc = pl.num_programs(2)
    nb = col_ref.shape[1]
    dt = col_ref.dtype
    f32 = jnp.float32
    # Row tile i of this panel is global tile k + i of problem b; tiles
    # past the problem's own count are DEAD — identity-augmented packing
    # makes their factor exactly the input tile (I on the diagonal, 0
    # off it), so they skip every MXU dot and just copy through.
    live = k + i < tiles_ref[b]

    @pl.when(j == 0)
    def _init():
        # accumulate in f32 regardless of storage dtype (bf16 inputs ride
        # the MXU's native bf16xbf16->f32 path; f32 inputs are unchanged)
        acc_ref[:] = col_ref[0].astype(f32)

    @pl.when(live)
    def _update():
        # left-looking rank-k chunk: acc -= A[b, i-tile, chunk] @ lead
        acc_ref[:] = acc_ref[:] - jnp.dot(left_ref[0], lead_ref[0],
                                          preferred_element_type=f32,
                                          precision=_HI)

    @pl.when(j == kc - 1)
    def _finish():
        @pl.when(live)
        def _live():
            upd_ref[0] = acc_ref[:].astype(dt)   # pre-factor tile (ABFT)

            @pl.when(i == 0)
            def _factor():
                _chol_factor_in_place(acc_ref, bw=bw)
                u = acc_ref[:]
                eye = (lax.broadcasted_iota(jnp.int32, (nb, nb), 0)
                       == lax.broadcasted_iota(jnp.int32, (nb, nb), 1))
                fac_ref[0] = lax.dot_general(u, eye.astype(f32),
                                             (((0,), (0,)), ((), ())),
                                             preferred_element_type=f32,
                                             precision=_HI).astype(dt)
                uinv_ref[:] = upper_tri_inv(u)

            @pl.when(i != 0)
            def _trsm():
                fac_ref[0] = jnp.dot(acc_ref[:], uinv_ref[:],
                                     preferred_element_type=f32,
                                     precision=_HI).astype(dt)

        @pl.when(jnp.logical_not(live))
        def _dead():
            upd_ref[0] = col_ref[0]
            fac_ref[0] = col_ref[0]


@functools.partial(jax.jit, static_argnames=("k", "bw", "interpret"))
def chol_panel_batched(col, left, lead, tiles, k: int = 0, bw: int = 8,
                       interpret: bool = False):
    """Ragged batched fused Cholesky panel step.

    col:   [B, M, nb] trailing block columns A[:, k0:, k0:k0+nb]
    left:  [B, M, K]  factored block rows A[:, k0:, :k0]
    lead:  [B, K, nb] conj(A[:, k0:k0+nb, :k0])^T per problem
    tiles: [B] int32 per-problem live tile counts ceil(size / nb)
    k:     static panel index (number of block columns already factored)

    Per-problem-size grids via scalar prefetch: the ``tiles`` vector
    rides ahead of the grid, row tiles at or past a problem's own count
    copy their (identity/zero) input through untouched, and the LEFT
    operand's index map clamps dead tiles onto the last live row so
    their HBM->VMEM streams are never issued for fresh data.  Outputs
    are never clamped — every block is written (dead blocks with the
    exact identity-completion values), keeping HBM initialized.

    Returns (upd, fac) stacked over B, same per-problem contract as
    chol_panel_fused.  Caller guarantees real f32 OR bf16 storage
    (accumulation is f32 either way), M % nb == 0, nb % bw == 0.
    """
    bsz, m, nb = col.shape
    kk = left.shape[2]
    kb = nb
    kp = max(kb, -(-kk // kb) * kb)
    if kk != kp:                             # pad K chunks with zeros
        left = jnp.pad(left, ((0, 0), (0, 0), (0, kp - kk)))
        lead = jnp.pad(lead, ((0, 0), (0, kp - kk), (0, 0)))
    upd, fac = pl.pallas_call(
        functools.partial(_chol_panel_batched_kernel, k=k, bw=bw),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(bsz, m // nb, kp // kb),
            in_specs=[
                pl.BlockSpec((1, nb, nb), lambda b, i, j, tiles: (b, i, 0)),
                pl.BlockSpec(
                    (1, nb, kb),
                    lambda b, i, j, tiles: (
                        b,
                        jnp.minimum(i, jnp.maximum(tiles[b] - k, 1) - 1),
                        j)),
                pl.BlockSpec((1, kb, nb), lambda b, i, j, tiles: (b, j, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, nb, nb), lambda b, i, j, tiles: (b, i, 0)),
                pl.BlockSpec((1, nb, nb), lambda b, i, j, tiles: (b, i, 0)),
            ],
            scratch_shapes=[pltpu.VMEM((nb, nb), jnp.float32),
                            pltpu.VMEM((nb, nb), jnp.float32)],
        ),
        out_shape=[jax.ShapeDtypeStruct((bsz, m, nb), col.dtype),
                   jax.ShapeDtypeStruct((bsz, m, nb), col.dtype)],
        interpret=interpret,
    )(tiles, col, left, lead)
    return upd, fac


@functools.partial(jax.jit, static_argnames=("bw", "interpret"))
def chol_tile_pallas(a, bw: int = 8, interpret: bool = False):
    """Lower Cholesky factor of an SPD tile [n, n], n % bw == 0,
    bw % 8 == 0."""
    n = a.shape[0]
    u = pl.pallas_call(
        functools.partial(_chol_kernel, bw=bw),
        out_shape=jax.ShapeDtypeStruct((n, n), a.dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=interpret,
    )(a)
    return u.T
