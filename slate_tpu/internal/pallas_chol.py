"""Pallas TPU kernel: Cholesky of one diagonal tile, VMEM-resident.

The reference factors diagonal tiles with vendor LAPACK potrf
(ref: src/internal/internal_potrf.cc:132).  XLA's TPU Cholesky runs a
per-column While loop — measured 2.07 ms for a 512x512 f32 tile
(docs/ceiling.jsonl xla_cholesky_512), which times 32 sequential panel
steps is the single largest cost in a 16k potrf.  This kernel keeps the
whole tile in VMEM for the entire factorization.

Formulation: the UPPER factor U with A = U^T U, processed in ``bw``-ROW
panels — Mosaic only allows dynamic slicing in 128-multiples along the
lane (last) dimension, but sublane (row) slices may move in multiples of
8, so an 8-row panel keeps every sequential step's operand at one vreg
row [8, n] instead of a [n, 128] half-tile.  The diagonal block is
mirrored into a [bw, bw] array via a one-hot MXU contraction (no lane
slicing), scalars come from mask+reduce, and the inter-panel trailing
update is a single MXU dot P^T P.  The caller transposes U once to
return the conventional lower L.

Real f32 only; complex/f64 tiles use the XLA fallback (potrf_tile).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_HI = lax.Precision.HIGHEST


def _chol_kernel(a_ref, o_ref, *, bw: int):
    n = a_ref.shape[0]
    dt = a_ref.dtype
    rows = lax.broadcasted_iota(jnp.int32, (n, n), 0)
    pr = lax.broadcasted_iota(jnp.int32, (bw, n), 0)
    cn = lax.broadcasted_iota(jnp.int32, (1, n), 1)
    br = lax.broadcasted_iota(jnp.int32, (bw, bw), 0)
    bc = lax.broadcasted_iota(jnp.int32, (bw, bw), 1)
    bc1 = lax.broadcasted_iota(jnp.int32, (1, bw), 1)
    o_ref[:] = a_ref[:]

    def block_step(b, _):
        j0 = b * bw
        P = o_ref[pl.ds(j0, bw), :]                  # [bw, n] row panel
        selT = (lax.broadcasted_iota(jnp.int32, (n, bw), 0)
                == j0 + lax.broadcasted_iota(jnp.int32, (n, bw), 1))
        D = jnp.dot(P, selT.astype(dt), preferred_element_type=dt,
                    precision=_HI)                   # P[:, j0:j0+bw]

        def col_step(i, PD):
            P, D = PD
            j = j0 + i
            piv = jnp.sqrt(jnp.sum(jnp.where((br == i) & (bc == i), D, 0)))
            inv = 1.0 / piv
            drow = jnp.sum(jnp.where(br == i, D, 0), axis=0,
                           keepdims=True)            # [1, bw] row i of D
            # u_j = row j of U: row i of P scaled, left-of-diag zeroed
            prow = jnp.sum(jnp.where(pr == i, P, 0), axis=0, keepdims=True)
            urow = jnp.where(cn < j, 0.0, prow * inv)
            # block-row couplings: u_j restricted to this panel's columns
            ublk = jnp.where(bc1 == i, piv, drow * inv)
            ublk = jnp.where(bc1 < i, 0.0, ublk)     # [1, bw]
            coefT = ublk.reshape(bw, 1)
            P = jnp.where(pr == i, urow,
                          jnp.where(pr > i, P - coefT * urow, P))
            D = jnp.where(br == i, ublk,
                          jnp.where(br > i, D - coefT * ublk, D))
            return P, D

        P, _ = lax.fori_loop(0, bw, col_step, (P, D))
        o_ref[pl.ds(j0, bw), :] = P
        # trailing rows: A -= P^T P (contract the panel-row axis)
        upd = lax.dot_general(P, P, (((0,), (0,)), ((), ())),
                              preferred_element_type=dt, precision=_HI)
        av = o_ref[:]
        o_ref[:] = jnp.where(rows >= j0 + bw, av - upd, av)
        return 0

    lax.fori_loop(0, n // bw, block_step, 0)


@functools.partial(jax.jit, static_argnames=("bw", "interpret"))
def chol_tile_pallas(a, bw: int = 8, interpret: bool = False):
    """Lower Cholesky factor of an SPD tile [n, n], n % bw == 0,
    bw % 8 == 0."""
    n = a.shape[0]
    u = pl.pallas_call(
        functools.partial(_chol_kernel, bw=bw),
        out_shape=jax.ShapeDtypeStruct((n, n), a.dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=interpret,
    )(a)
    return u.T
