"""Pallas TPU kernel: Householder QR panel + compact-WY T, VMEM-resident.

geqrf's panel step previously stitched XLA-level tile ops (qr.py
householder_panel: one dynamic-slice rank-1 update per column, each a
round trip through HBM for the whole [mm, w] panel).  This kernel keeps
the panel AND the growing T triangle in VMEM for all w columns: column
extraction is mask+reduce (no lane slicing), the trailing update and the
T recursion are MXU dots, and the output is byte-compatible with
(householder_panel, build_t) — R in/above the diagonal, Householder
vectors below (unit diagonal implied), T the larft Forward/Columnwise
triangle with tau on its diagonal.  Q = I - V T V^T.

The larfg scalar math mirrors qr.py _larfg exactly (beta =
-copysign(mu, alpha); dead columns with mu == 0 get tau = 0 and keep
their column), so parity tests compare against the XLA panel directly.

Ragged batched variant (qr_panel_batched): one panel per grid step over
a leading batch dimension, per-problem live row counts via scalar
prefetch.  Unlike Cholesky/LU, padding columns carry real reflectors
(the identity augmentation must be annihilated), so raggedness is
problem-granular: only zero-row filler slots skip the factorization.

Real f32, mm >= w; the batched variant additionally accepts bf16
storage — the panel is upcast once into VMEM, the whole column loop
(larfg scalars, trailing updates, T recursion) runs in f32, and only
the final packed/T writes demote back (see pallas_chol.py for the
accumulation contract).  Other panels use the XLA path (qr.geqrf_panel).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_HI = lax.Precision.HIGHEST


def _qr_panel_steps(a):
    """Pure column loop shared by the single-panel kernel and the
    batched grid: packed Householder panel + T of ``a`` [mm, w], carried
    through the fori_loop as VALUES so it can run under pl.when."""
    mm, w = a.shape
    dt = a.dtype
    rows = lax.broadcasted_iota(jnp.int32, (mm, w), 0)
    cols = lax.broadcasted_iota(jnp.int32, (mm, w), 1)
    rc = lax.broadcasted_iota(jnp.int32, (mm, 1), 0)
    cn = lax.broadcasted_iota(jnp.int32, (1, w), 1)
    tc = lax.broadcasted_iota(jnp.int32, (w, w), 1)
    trc = lax.broadcasted_iota(jnp.int32, (w, 1), 0)

    def col_step(j, AT):
        A, T = AT
        colj = jnp.sum(jnp.where(cols == j, A, 0), axis=1, keepdims=True)
        alpha = jnp.sum(jnp.where(rc == j, colj, 0))
        x = jnp.where(rc > j, colj, 0.0)
        mu = jnp.sqrt(alpha * alpha + jnp.sum(x * x))
        live = mu > 0
        beta = jnp.where(alpha >= 0, -mu, mu)
        sb = jnp.where(live, beta, 1.0)
        tau = jnp.where(live, (sb - alpha) / sb, 0.0)
        scale = 1.0 / jnp.where(live, alpha - sb, 1.0)
        v = jnp.where(rc == j, 1.0, x * scale)       # [mm, 1], v[:j] = 0
        v = jnp.where(rc < j, 0.0, v)
        # trailing update: A[:, j+1:] -= tau v (v^T A)
        wrow = lax.dot_general(v, A, (((0,), (0,)), ((), ())),
                               preferred_element_type=dt, precision=_HI)
        wrow = jnp.where(cn > j, wrow, 0.0)          # [1, w]
        A = A - tau * v * wrow
        # write column j: R above+diag(beta), v strictly below
        newc = jnp.where(rc == j, beta, jnp.where(rc < j, colj, x * scale))
        newc = jnp.where(live, newc, colj)           # mu==0: leave column
        A = jnp.where(cols == j, newc, A)
        # T column j: -tau T (V^T v), diag tau (larft recursion)
        V = jnp.where((rows > cols) & (cols < j), A, 0.0)
        V = V + jnp.where((rows == cols) & (cols < j), 1.0, 0.0)
        g = lax.dot_general(V, v, (((0,), (0,)), ((), ())),
                            preferred_element_type=dt, precision=_HI)
        tcol = -tau * jnp.dot(T, g, preferred_element_type=dt,
                              precision=_HI)         # [w, 1]
        tcol = jnp.where(trc == j, tau, jnp.where(trc < j, tcol, 0.0))
        T = jnp.where(tc == j, tcol, T)
        return A, T

    return lax.fori_loop(0, w, col_step, (a, jnp.zeros((w, w), dt)))


def _qr_panel_kernel(a_ref, p_ref, t_ref):
    packed, t = _qr_panel_steps(a_ref[:])
    p_ref[:] = packed
    t_ref[:] = t


def _qr_panel_batched_kernel(rows_ref, a_ref, p_ref, t_ref):
    b = pl.program_id(0)
    w = t_ref.shape[1]
    dt = a_ref.dtype
    # QR raggedness is problem-granular: identity-augmented padding
    # COLUMNS carry nontrivial reflectors (the augmented unit diagonal
    # must be annihilated), so only problems with zero live rows —
    # filler slots — skip the panel entirely (packed = input, T = 0).
    live = rows_ref[b] > 0

    @pl.when(live)
    def _panel():
        # column loop in f32 (bf16 panels upcast once into registers);
        # the packed/T writes demote back to the storage dtype
        packed, t = _qr_panel_steps(a_ref[0].astype(jnp.float32))
        p_ref[0] = packed.astype(dt)
        t_ref[0] = t.astype(dt)

    @pl.when(jnp.logical_not(live))
    def _dead():
        p_ref[0] = a_ref[0]
        t_ref[0] = jnp.zeros((w, w), dt)


@functools.partial(jax.jit, static_argnames=("interpret",))
def qr_panel_pallas(a, interpret: bool = False):
    """Packed Householder panel + T of ``a`` [mm, w], mm >= w.

    Returns (packed, T) with householder_panel's packing and build_t's
    T — drop-in for householder_panel_blocked on f32 panels."""
    mm, w = a.shape
    packed, t = pl.pallas_call(
        _qr_panel_kernel,
        out_shape=[jax.ShapeDtypeStruct((mm, w), a.dtype),
                   jax.ShapeDtypeStruct((w, w), a.dtype)],
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                   pl.BlockSpec(memory_space=pltpu.VMEM)],
        interpret=interpret,
    )(a)
    return packed, t


@functools.partial(jax.jit, static_argnames=("interpret",))
def qr_panel_batched(a, rows, interpret: bool = False):
    """Ragged batched Householder panel: packed panels + Ts of ``a``
    [B, mm, w], mm >= w, with per-problem live row counts ``rows`` [B]
    int32 delivered via scalar prefetch.

    Raggedness is problem-granular only (unlike the Cholesky/LU tile
    grids): identity-augmented padding columns own real reflectors, so a
    live problem factors its whole bucket panel; a problem with
    rows[b] == 0 (a filler slot) passes its input through with T = 0.
    Accepts real f32 or bf16 storage (the column loop runs in f32 either
    way).  Returns (packed [B, mm, w], T [B, w, w])."""
    bsz, mm, w = a.shape
    packed, t = pl.pallas_call(
        _qr_panel_batched_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(bsz,),
            in_specs=[pl.BlockSpec((1, mm, w), lambda b, rows: (b, 0, 0))],
            out_specs=[pl.BlockSpec((1, mm, w), lambda b, rows: (b, 0, 0)),
                       pl.BlockSpec((1, w, w), lambda b, rows: (b, 0, 0))],
        ),
        out_shape=[jax.ShapeDtypeStruct((bsz, mm, w), a.dtype),
                   jax.ShapeDtypeStruct((bsz, w, w), a.dtype)],
        interpret=interpret,
    )(rows, a)
    return packed, t
