"""internal::gemm — one trailing-update step on local tiles.

Analog of the reference's batched tile gemm (ref:
src/internal/internal_gemm.cc:383-688).  The reference flattens the trailing
tiles into <=4 `blas::batch::gemm` calls per device (interior / bottom row /
right col / corner, to handle ragged boundary tiles).  On TPU the pad-to-zero
invariant makes all tiles uniform mb*nb, so the four regions collapse into a
single einsum contraction that XLA lowers onto the MXU as one batched matmul
— the whole point of the blocked-with-padding layout.
"""

from __future__ import annotations

import jax.numpy as jnp


def tile_outer_product(a_col, b_row):
    """C[i, j] += A[i] @ B[j] over tile batches.

    a_col: [mtl, mb, kb] — one broadcast block column of A
    b_row: [ntl, kb, nb] — one broadcast block row of B
    returns [mtl, ntl, mb, nb]

    This is the SUMMA rank-kb update; one XLA dot_general, MXU-shaped.
    """
    return jnp.einsum("iab,jbc->ijac", a_col, b_row,
                      preferred_element_type=a_col.dtype)


def blocked_gemm(a_tiles, b_tiles):
    """Full blocked product over canonical tile arrays.

    a_tiles: [Mt, Kt, mb, kb], b_tiles: [Kt, Nt, kb, nb]
    returns  [Mt, Nt, mb, nb]

    Single-device analog of the reference's per-device batch loop
    (internal_gemm.cc:614-688): one contraction over (k, kb), which XLA
    tiles onto the MXU without materialising intermediates.
    """
    return jnp.einsum("ikab,kjbc->ijac", a_tiles, b_tiles,
                      preferred_element_type=a_tiles.dtype)


def tile_product_row_sums(a_tiles, b_tiles):
    """Row checksums of the blocked product ``sum_k A[i,k] B[k,j]``
    computed WITHOUT forming it: ``A (B e)`` at O(tiles * nb^2) — the
    Huang-Abraham checksum shadow of :func:`blocked_gemm` (a rank-1 tile
    pair ``a_col[:, None] / b_row[None]`` gives the shadow of
    :func:`tile_outer_product`).  robust/abft.py verifies results
    against these and repairs single corrupted elements."""
    be = jnp.sum(b_tiles, axis=-1)
    return jnp.einsum("ikab,kjb->ija", a_tiles, be)


def tile_product_col_sums(a_tiles, b_tiles):
    """Column checksums of the blocked product: ``(e^T A) B``."""
    ea = jnp.sum(a_tiles, axis=-2)
    return jnp.einsum("ikb,kjbc->ijc", ea, b_tiles)
