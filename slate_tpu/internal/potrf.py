"""internal::potrf — diagonal-tile Cholesky factor.

Analog of the reference's internal_potrf.cc:132 (lapack::potrf on the
diagonal tile, host or device).  The reference delegates the tile factor to
vendor LAPACK; we delegate to XLA's native blocked Cholesky, which on TPU
lowers to MXU-shaped HLO — same division of labour, different vendor.
"""

from __future__ import annotations

import jax.numpy as jnp


def potrf_tile(a):
    """Factor one Hermitian positive-definite tile: returns lower L."""
    return jnp.linalg.cholesky(a)
