"""internal::potrf — diagonal-tile Cholesky factor.

Analog of the reference's internal_potrf.cc:132 (lapack::potrf on the
diagonal tile, host or device).  The reference delegates the tile factor
to vendor LAPACK; on TPU the vendor seam (XLA's Cholesky) runs a
per-column While loop — 2.07 ms per 512 f32 tile (docs/ceiling.jsonl).
A VMEM-resident Pallas kernel (internal/pallas_chol.py) exists but
measures the same per-column latency on this chip generation
(docs/PERF.md), so XLA remains the default; set SLATE_PALLAS=1 to route
real-TPU f32 tiles through the Pallas kernel instead.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp

_PALLAS_TPU = None


def _pallas_ok() -> bool:
    global _PALLAS_TPU
    if _PALLAS_TPU is None:
        # opt-in: at bench shapes the kernel currently only ties XLA's
        # per-column cost (4.4 us/col vs 4.0 — docs/PERF.md), so the
        # proven XLA path stays the default
        if os.environ.get("SLATE_PALLAS") != "1":
            _PALLAS_TPU = False
        else:
            try:
                d = jax.devices()[0]
                _PALLAS_TPU = "tpu" in (d.platform + d.device_kind).lower()
            except Exception:  # noqa: BLE001 — no backend: stay on XLA
                _PALLAS_TPU = False
    return _PALLAS_TPU


def potrf_tile(a):
    """Factor one Hermitian positive-definite tile: returns lower L."""
    n = a.shape[-1]
    if (a.ndim == 2 and a.dtype == jnp.float32 and n % 128 == 0
            and 128 <= n <= 1024 and _pallas_ok()):
        from .pallas_chol import chol_tile_pallas
        return chol_tile_pallas(a)
    return jnp.linalg.cholesky(a)
