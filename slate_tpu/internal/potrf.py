"""internal::potrf — diagonal-tile Cholesky factor + fused panel seam.

Analog of the reference's internal_potrf.cc:132 (lapack::potrf on the
diagonal tile, host or device).  The reference delegates the tile factor
to vendor LAPACK; on TPU the vendor seam (XLA's Cholesky) runs a
per-column While loop — 2.07 ms per 512 f32 tile (docs/ceiling.jsonl).

Kernel choice is now a TUNED decision: both the single-tile factor
(potrf_tile) and the fused panel step (potrf_panel_fused: rank-k update
+ tile factor + TRSM in one pallas_call — internal/pallas_chol.py)
consult slate_tpu.tune.resolve_plan at trace time, keyed by
(op, n, dtype, chip).  Shipped plans default to XLA everywhere; run
``python -m slate_tpu.tune`` on a new chip (docs/TUNING.md).

The old ``SLATE_PALLAS=1`` env gate this module used to read directly
is DEPRECATED: the tune resolver still honors it for one release as a
force-on/force-off override of the cached plan.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..tune import resolve_plan


@functools.lru_cache(maxsize=None)
def _interpret() -> bool:
    """Pallas plans run interpret=True off-TPU (same results, CPU-traced)
    so tuned paths stay testable everywhere."""
    try:
        return jax.default_backend() != "tpu"
    except Exception:  # noqa: BLE001 — no backend: interpret
        return True


def _tile_plan_ok(dtype, n: int) -> bool:
    if not (dtype == jnp.float32 and n % 128 == 0 and 128 <= n <= 1024):
        return False
    return resolve_plan("potrf_tile", n, "float32").kernel == "pallas"


def potrf_tile(a):
    """Factor one Hermitian positive-definite tile: returns lower L.

    Routed through the tuned plan for ("potrf_tile", n): the Pallas
    VMEM-resident kernel when the plan says so (f32, 128 <= n <= 1024,
    n % 128 == 0), XLA's Cholesky otherwise."""
    n = a.shape[-1]
    if a.ndim == 2 and _tile_plan_ok(a.dtype, n):
        from .pallas_chol import chol_tile_pallas
        plan = resolve_plan("potrf_tile", n, "float32")
        return chol_tile_pallas(a, bw=plan.bw, interpret=_interpret())
    return jnp.linalg.cholesky(a)


def potrf_panel_ok(dtype, m: int, w: int, nb: int) -> bool:
    """True when the fused Pallas panel step serves this panel: tuned
    plan says pallas, f32, full-width panel, MXU-aligned nb that fits
    VMEM (the [nb, nb] accumulator + U^-1 scratches cap nb at 512)."""
    if not (dtype == jnp.float32 and w == nb and m >= nb
            and nb % 128 == 0 and 128 <= nb <= 512):
        return False
    return resolve_plan("potrf_panel", m, "float32").kernel == "pallas"


# ---- out-of-core panel-step kernels (drivers/cholesky.py potrf_ooc) ----
# Each step of the OOC left-looking loop is a pure jitted function of the
# device windows the TileMap streams in; jit's shape-keyed cache gives one
# executable per (panel width, remaining height), reused across steps AND
# across a checkpoint resume — a load-bearing property: bit-identical
# resume relies on the resumed run dispatching the exact same kernels on
# the exact same bytes as the uninterrupted one.

@jax.jit
def ooc_chol_update(acc, left, lead):
    """One streamed left-looking accumulation: subtract the contribution
    of a previous block column.  ``acc`` [m-k0, w] is the running panel,
    ``left`` = A[k0:, j0:j1], ``lead`` = A[k0:k1, j0:j1]."""
    return acc - left @ jnp.conj(lead).T


@jax.jit
def ooc_chol_panel(upd):
    """Factor the fully-accumulated [m-k0, w] panel: returns [L00; L21]
    with the diagonal tile routed through the tuned potrf_tile and the
    rows below one MXU gemm against the inverted L00 (same seam as the
    in-core blocked loop in drivers/cholesky.py)."""
    from .trsm import tri_inv_lower
    w = upd.shape[1]
    lkk = potrf_tile(upd[:w])
    tail = upd[w:] @ jnp.conj(tri_inv_lower(lkk)).T
    return jnp.concatenate([lkk, tail], axis=0)


def potrf_panel_fused(col, left, lead):
    """Fused left-looking panel step (see pallas_chol.chol_panel_fused):
    returns (upd, fac) = (pre-factor panel for the ABFT rungs,
    [L00; L21]).  Caller gates with potrf_panel_ok; ragged row counts
    are zero-padded to a tile multiple here and sliced back."""
    from .pallas_chol import chol_panel_fused
    m, nb = col.shape
    plan = resolve_plan("potrf_panel", m, "float32")
    mp = -(-m // nb) * nb
    if mp != m:                       # zero rows factor to zero L21 rows
        col = jnp.pad(col, ((0, mp - m), (0, 0)))
        left = jnp.pad(left, ((0, mp - m), (0, 0)))
    upd, fac = chol_panel_fused(col, left, lead, bw=plan.bw,
                                interpret=_interpret())
    return upd[:m], fac[:m]
