"""Pallas TPU kernel: partial-pivot row selection for one LU panel chunk.

The CALU tournament (internal/getrf.py panel_lu_tournament) needs each
row block's nb partial-pivot rows (ref: internal_getrf_tntpiv.cc round-1
LUs).  XLA's pivoted LU streams the whole [W, nb] chunk from HBM once
per column — measured 31 us/column at [4096, 512] (docs/ceiling.jsonl
xla_lu_4096x512), i.e. 15.8 ms for work whose flops cost ~0.1 ms.  This
kernel keeps the chunk in VMEM TRANSPOSED ([nb, W]: columns of A on
sublanes, rows of A on lanes) so each elimination step touches one 8-row
slab; pivoted rows are MASKED out of the search instead of physically
swapped, and each slab's trailing update is two MXU dots against the
recorded multiplier/selection slabs.

Output: the pivot ROW indices [1, nb] int32, in elimination order —
exactly lax.linalg.lu's perm[:nb] for the same chunk (up to argmax tie
order).  Round 1 of the tournament needs nothing else: the candidate
values it forwards are the ORIGINAL rows, gathered by these indices.

Real f32 only; the XLA LU remains the fallback (and the test oracle).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_HI = lax.Precision.HIGHEST


def _lu_select_kernel(at_ref, mask_ref, piv_ref, ws_ref, lbuf_ref,
                      sbuf_ref, *, bw: int):
    nb, W = at_ref.shape
    dt = at_ref.dtype
    ws_ref[:] = at_ref[:]
    lane = lax.broadcasted_iota(jnp.int32, (1, W), 1)
    lane_nb = lax.broadcasted_iota(jnp.int32, (1, nb), 1)
    sl = lax.broadcasted_iota(jnp.int32, (bw, W), 0)
    rows_nb = lax.broadcasted_iota(jnp.int32, (nb, W), 0)
    piv_ref[:] = jnp.zeros((1, nb), jnp.int32)
    # allowed lanes: live rows only (caller masks ragged padding).
    # Kept as an f32 0/1 mask — Mosaic cannot carry bool vectors through
    # its loop lowering ("failed to legalize scf.for").
    allowed0 = mask_ref[:]

    def slab_step(b, allowed):
        j0 = b * bw
        slab = ws_ref[pl.ds(j0, bw), :]              # [bw, W]
        lbuf = jnp.zeros((bw, W), dt)                # multiplier rows
        sbuf = jnp.zeros((bw, W), dt)                # one-hot pivot rows

        def col_step(i, carry):
            slab, lbuf, sbuf, allowed = carry
            mrow = jnp.sum(jnp.where(sl == i, slab, 0), axis=0,
                           keepdims=True)            # [1, W]
            cand = jnp.where(allowed > 0, jnp.abs(mrow), -1.0)
            p = jnp.argmax(cand)                     # scalar lane index
            onehot = lane == p
            pivval = jnp.sum(jnp.where(onehot, mrow, 0))
            safe = jnp.where(pivval == 0, 1.0, pivval)
            lmask = (allowed > 0) & ~onehot
            l = jnp.where(lmask & (pivval != 0), mrow / safe, 0.0)
            # eliminate within the slab: rows r > i lose their p-lane
            # coupling times l
            colp = jnp.sum(jnp.where(onehot, slab, 0), axis=1,
                           keepdims=True)            # [bw, 1]
            slab = jnp.where(sl > i, slab - colp * l, slab)
            lbuf = jnp.where(sl == i, l, lbuf)
            sbuf = jnp.where(sl == i, jnp.where(onehot, 1.0, 0.0), sbuf)
            piv_ref[:] = jnp.where(lane_nb == j0 + i,
                                   p.astype(jnp.int32), piv_ref[:])
            return slab, lbuf, sbuf, jnp.where(onehot, 0.0, allowed)

        slab, lbuf, sbuf, allowed = lax.fori_loop(
            0, bw, col_step, (slab, lbuf, sbuf, allowed))
        ws_ref[pl.ds(j0, bw), :] = slab
        lbuf_ref[:] = lbuf
        sbuf_ref[:] = sbuf
        # Deferred trailing update.  A trailing row's pivot-lane values
        # EVOLVE during the slab (lane p_k is updated by steps i < k), so
        # the one-shot coefficients are u = (I + N)^-1 c0 with
        # N[k, i] = l_i[p_k] strictly lower (nilpotent), c0 the pivot-lane
        # values at slab start — then ws[r, :] -= sum_i u_i l_i.
        eye = (lax.broadcasted_iota(jnp.int32, (bw, bw), 0)
               == lax.broadcasted_iota(jnp.int32, (bw, bw), 1)).astype(dt)
        B = lax.dot_general(lbuf, sbuf, (((1,), (1,)), ((), ())),
                            preferred_element_type=dt, precision=_HI)
        N = jnp.where(lax.broadcasted_iota(jnp.int32, (bw, bw), 0)
                      > lax.broadcasted_iota(jnp.int32, (bw, bw), 1),
                      B.T, 0.0)
        # (I + N)^-1 = (I - N)(I + N^2)(I + N^4) ... (N nilpotent)
        inv = eye - N
        N2 = jnp.dot(N, N, preferred_element_type=dt, precision=_HI)
        steps = 1
        while 2 * steps < bw:
            inv = jnp.dot(inv, eye + N2, preferred_element_type=dt,
                          precision=_HI)
            N2 = jnp.dot(N2, N2, preferred_element_type=dt, precision=_HI)
            steps *= 2
        wsv = ws_ref[:]
        c0 = lax.dot_general(wsv, sbuf_ref[:], (((1,), (1,)), ((), ())),
                             preferred_element_type=dt, precision=_HI)
        u = jnp.dot(c0, inv.T, preferred_element_type=dt, precision=_HI)
        upd = jnp.dot(u, lbuf_ref[:], preferred_element_type=dt,
                      precision=_HI)                 # [nb, W]
        ws_ref[:] = jnp.where(rows_nb > j0 + bw - 1, wsv - upd, wsv)
        return allowed

    lax.fori_loop(0, nb // bw, slab_step, allowed0)


@functools.partial(jax.jit, static_argnames=("bw", "interpret"))
def lu_select_pallas(chunk, nrows: jax.Array | None = None, bw: int = 8,
                     interpret: bool = False):
    """Pivot row indices [nb] of a chunk [W, nb] (W % 128 == 0 after the
    caller's padding; ``nrows`` masks the live rows, default all)."""
    W, nb = chunk.shape
    at = chunk.T
    live = (jnp.arange(W, dtype=jnp.int32)[None, :]
            < (W if nrows is None else nrows)).astype(jnp.float32)
    piv = pl.pallas_call(
        functools.partial(_lu_select_kernel, bw=bw),
        out_shape=jax.ShapeDtypeStruct((1, nb), jnp.int32),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                  pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[pltpu.VMEM((nb, W), chunk.dtype),
                        pltpu.VMEM((bw, W), chunk.dtype),
                        pltpu.VMEM((bw, W), chunk.dtype)],
        interpret=interpret,
    )(at, live)
    return piv[0]
