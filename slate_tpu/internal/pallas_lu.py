"""Pallas TPU kernel: partial-pivot row selection for one LU panel chunk.

The CALU tournament (internal/getrf.py panel_lu_tournament) needs each
row block's nb partial-pivot rows (ref: internal_getrf_tntpiv.cc round-1
LUs).  XLA's pivoted LU streams the whole [W, nb] chunk from HBM once
per column — measured 31 us/column at [4096, 512] (docs/ceiling.jsonl
xla_lu_4096x512), i.e. 15.8 ms for work whose flops cost ~0.1 ms.  This
kernel keeps the chunk in VMEM TRANSPOSED ([nb, W]: columns of A on
sublanes, rows of A on lanes) so each elimination step touches one 8-row
slab; pivoted rows are MASKED out of the search instead of physically
swapped, and each slab's trailing update is two MXU dots against the
recorded multiplier/selection slabs.

Output: the pivot ROW indices [1, nb] int32, in elimination order —
exactly lax.linalg.lu's perm[:nb] for the same chunk (up to argmax tie
order).  Round 1 of the tournament needs nothing else: the candidate
values it forwards are the ORIGINAL rows, gathered by these indices.

Fused panel variant (lu_panel_fused): the unpivoted panel factor plus
the TRSM that scales every row tile below it, one pallas_call grid over
[nb, nb] row tiles.  Tile 0 is factored in place in bw-row slabs (panel
rows eliminate against themselves; the rows below the slab inside the
tile get a block solve against the slab's Ub^-1 plus one MXU trailing
update), then the full-tile U^-1 (pallas_tri.upper_tri_inv) rides a
scratch to the remaining tiles, whose TRSM is one gemm each — matching
getrf.panel_lu_nopiv's semantics (packed L\\U, unit lower implied).

Ragged batched variant (lu_panel_batched): the fused left-looking panel
step (rank-k update + tile factor + TRSM) with a leading batch grid
dimension and per-problem tile counts via scalar prefetch — dead tiles
identity-complete by copying their input through, so mixed-size batches
skip the padding work entirely.

Real f32 everywhere; the batched variant additionally accepts bf16
storage with fp32 accumulation (f32 VMEM accumulator + factor scratch,
``preferred_element_type=f32`` on every MXU dot, demote on the final
panel write — see pallas_chol.py for the contract).  The XLA LU remains
the fallback (and the test oracle).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_tri import upper_tri_inv

_HI = lax.Precision.HIGHEST


def _lu_select_kernel(at_ref, mask_ref, piv_ref, ws_ref, lbuf_ref,
                      sbuf_ref, *, bw: int):
    nb, W = at_ref.shape
    dt = at_ref.dtype
    ws_ref[:] = at_ref[:]
    lane = lax.broadcasted_iota(jnp.int32, (1, W), 1)
    lane_nb = lax.broadcasted_iota(jnp.int32, (1, nb), 1)
    sl = lax.broadcasted_iota(jnp.int32, (bw, W), 0)
    rows_nb = lax.broadcasted_iota(jnp.int32, (nb, W), 0)
    piv_ref[:] = jnp.zeros((1, nb), jnp.int32)
    # allowed lanes: live rows only (caller masks ragged padding).
    # Kept as an f32 0/1 mask — Mosaic cannot carry bool vectors through
    # its loop lowering ("failed to legalize scf.for").
    allowed0 = mask_ref[:]

    def slab_step(b, allowed):
        j0 = b * bw
        slab = ws_ref[pl.ds(j0, bw), :]              # [bw, W]
        lbuf = jnp.zeros((bw, W), dt)                # multiplier rows
        sbuf = jnp.zeros((bw, W), dt)                # one-hot pivot rows

        def col_step(i, carry):
            slab, lbuf, sbuf, allowed = carry
            mrow = jnp.sum(jnp.where(sl == i, slab, 0), axis=0,
                           keepdims=True)            # [1, W]
            cand = jnp.where(allowed > 0, jnp.abs(mrow), -1.0)
            p = jnp.argmax(cand)                     # scalar lane index
            onehot = lane == p
            pivval = jnp.sum(jnp.where(onehot, mrow, 0))
            safe = jnp.where(pivval == 0, 1.0, pivval)
            lmask = (allowed > 0) & ~onehot
            l = jnp.where(lmask & (pivval != 0), mrow / safe, 0.0)
            # eliminate within the slab: rows r > i lose their p-lane
            # coupling times l
            colp = jnp.sum(jnp.where(onehot, slab, 0), axis=1,
                           keepdims=True)            # [bw, 1]
            slab = jnp.where(sl > i, slab - colp * l, slab)
            lbuf = jnp.where(sl == i, l, lbuf)
            sbuf = jnp.where(sl == i, jnp.where(onehot, 1.0, 0.0), sbuf)
            piv_ref[:] = jnp.where(lane_nb == j0 + i,
                                   p.astype(jnp.int32), piv_ref[:])
            return slab, lbuf, sbuf, jnp.where(onehot, 0.0, allowed)

        slab, lbuf, sbuf, allowed = lax.fori_loop(
            0, bw, col_step, (slab, lbuf, sbuf, allowed))
        ws_ref[pl.ds(j0, bw), :] = slab
        lbuf_ref[:] = lbuf
        sbuf_ref[:] = sbuf
        # Deferred trailing update.  A trailing row's pivot-lane values
        # EVOLVE during the slab (lane p_k is updated by steps i < k), so
        # the one-shot coefficients are u = (I + N)^-1 c0 with
        # N[k, i] = l_i[p_k] strictly lower (nilpotent), c0 the pivot-lane
        # values at slab start — then ws[r, :] -= sum_i u_i l_i.
        eye = (lax.broadcasted_iota(jnp.int32, (bw, bw), 0)
               == lax.broadcasted_iota(jnp.int32, (bw, bw), 1)).astype(dt)
        B = lax.dot_general(lbuf, sbuf, (((1,), (1,)), ((), ())),
                            preferred_element_type=dt, precision=_HI)
        N = jnp.where(lax.broadcasted_iota(jnp.int32, (bw, bw), 0)
                      > lax.broadcasted_iota(jnp.int32, (bw, bw), 1),
                      B.T, 0.0)
        # (I + N)^-1 = (I - N)(I + N^2)(I + N^4) ... (N nilpotent)
        inv = eye - N
        N2 = jnp.dot(N, N, preferred_element_type=dt, precision=_HI)
        steps = 1
        while 2 * steps < bw:
            inv = jnp.dot(inv, eye + N2, preferred_element_type=dt,
                          precision=_HI)
            N2 = jnp.dot(N2, N2, preferred_element_type=dt, precision=_HI)
            steps *= 2
        wsv = ws_ref[:]
        c0 = lax.dot_general(wsv, sbuf_ref[:], (((1,), (1,)), ((), ())),
                             preferred_element_type=dt, precision=_HI)
        u = jnp.dot(c0, inv.T, preferred_element_type=dt, precision=_HI)
        upd = jnp.dot(u, lbuf_ref[:], preferred_element_type=dt,
                      precision=_HI)                 # [nb, W]
        ws_ref[:] = jnp.where(rows_nb > j0 + bw - 1, wsv - upd, wsv)
        return allowed

    lax.fori_loop(0, nb // bw, slab_step, allowed0)


def _lu_factor_in_place(o_ref, *, bw: int):
    """Unpivoted LU of the square [nb, nb] tile in ``o_ref``, in place:
    packed L\\U (multipliers strictly below the diagonal, unit lower
    implied), processed in bw-row slabs."""
    nb = o_ref.shape[0]
    dt = o_ref.dtype
    lane = lax.broadcasted_iota(jnp.int32, (1, nb), 1)
    pr = lax.broadcasted_iota(jnp.int32, (bw, nb), 0)
    pc = lax.broadcasted_iota(jnp.int32, (bw, nb), 1)
    slr = lax.broadcasted_iota(jnp.int32, (bw, 1), 0)
    rows = lax.broadcasted_iota(jnp.int32, (nb, nb), 0)
    cols = lax.broadcasted_iota(jnp.int32, (nb, nb), 1)

    def slab_step(b, _):
        j0 = b * bw
        P = o_ref[pl.ds(j0, bw), :]                  # [bw, nb]

        def col_step(i, P):
            j = j0 + i
            prow = jnp.sum(jnp.where(pr == i, P, 0), axis=0, keepdims=True)
            piv = jnp.sum(jnp.where(lane == j, prow, 0))
            safe = jnp.where(piv == 0, 1.0, piv)
            cpan = jnp.sum(jnp.where(pc == j, P, 0), axis=1,
                           keepdims=True)            # [bw, 1] column j
            l = jnp.where(slr > i, cpan / safe, 0.0)
            urow = jnp.where(lane > j, prow, 0.0)
            P = jnp.where(pr > i, P - l * urow, P)
            return jnp.where((pr > i) & (pc == j), l, P)

        P = lax.fori_loop(0, bw, col_step, P)
        o_ref[pl.ds(j0, bw), :] = P
        # rows below the slab (within the tile): block solve + trailing
        A = o_ref[:]
        selT = (lax.broadcasted_iota(jnp.int32, (nb, bw), 0)
                == j0 + lax.broadcasted_iota(jnp.int32, (nb, bw), 1))
        sel = (j0 + lax.broadcasted_iota(jnp.int32, (bw, nb), 0)
               == lax.broadcasted_iota(jnp.int32, (bw, nb), 1))
        C = jnp.dot(A, selT.astype(dt), preferred_element_type=dt,
                    precision=_HI)                   # [nb, bw] slab cols
        D = jnp.dot(P, selT.astype(dt), preferred_element_type=dt,
                    precision=_HI)                   # [bw, bw] diag block
        l21 = jnp.dot(C, upper_tri_inv(D), preferred_element_type=dt,
                      precision=_HI)                 # [nb, bw]
        u12 = jnp.where(lane >= j0 + bw, P, 0.0)     # [bw, nb] slab U rows
        upd = jnp.dot(l21, u12, preferred_element_type=dt, precision=_HI)
        scat = jnp.dot(l21, sel.astype(dt), preferred_element_type=dt,
                       precision=_HI)                # l21 into slab lanes
        below = rows >= j0 + bw
        inblk = (cols >= j0) & (cols < j0 + bw)
        o_ref[:] = jnp.where(below, jnp.where(inblk, scat, A - upd), A)
        return 0

    lax.fori_loop(0, nb // bw, slab_step, 0)


def _lu_panel_kernel(p_ref, o_ref, uinv_ref, *, bw: int):
    i = pl.program_id(0)
    nb = p_ref.shape[0]
    dt = p_ref.dtype

    @pl.when(i == 0)
    def _top():
        o_ref[:] = p_ref[:]
        _lu_factor_in_place(o_ref, bw=bw)
        uinv_ref[:] = upper_tri_inv(o_ref[:])        # triu of packed tile

    @pl.when(i != 0)
    def _body():
        o_ref[:] = jnp.dot(p_ref[:], uinv_ref[:], preferred_element_type=dt,
                           precision=_HI)            # L21 = A21 U^-1


@functools.partial(jax.jit, static_argnames=("bw", "interpret"))
def lu_panel_fused(panel, bw: int = 8, interpret: bool = False):
    """Fused unpivoted LU panel: packed L\\U of [W, nb], W % nb == 0.

    Row tile 0 gets the in-place tile factor; every tile below it is one
    MXU gemm against the broadcast U^-1.  Same contract as
    getrf.panel_lu_nopiv's value output (unit lower implied)."""
    w, nb = panel.shape
    return pl.pallas_call(
        functools.partial(_lu_panel_kernel, bw=bw),
        grid=(w // nb,),
        in_specs=[pl.BlockSpec((nb, nb), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((nb, nb), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((w, nb), panel.dtype),
        scratch_shapes=[pltpu.VMEM((nb, nb), panel.dtype)],
        interpret=interpret,
    )(panel)


def _lu_panel_batched_kernel(tiles_ref, col_ref, left_ref, lead_ref,
                             upd_ref, fac_ref, acc_ref, uinv_ref,
                             *, k: int, bw: int):
    b = pl.program_id(0)
    i = pl.program_id(1)
    j = pl.program_id(2)
    kc = pl.num_programs(2)
    dt = col_ref.dtype
    f32 = jnp.float32
    # Tiles past problem b's own count are DEAD: identity-augmented
    # packing makes their no-pivot LU exactly the input tile (the
    # diagonal tile is I = its own packed L\\U, off-diagonal tiles are
    # 0), so they copy through without touching the MXU.
    live = k + i < tiles_ref[b]

    @pl.when(j == 0)
    def _init():
        # f32 accumulation regardless of storage dtype (bf16 inputs ride
        # the MXU's native bf16xbf16->f32 path; f32 inputs unchanged)
        acc_ref[:] = col_ref[0].astype(f32)

    @pl.when(live)
    def _update():
        # left-looking rank-k chunk: acc -= L[b, i-tile, chunk] @ U chunk
        acc_ref[:] = acc_ref[:] - jnp.dot(left_ref[0], lead_ref[0],
                                          preferred_element_type=f32,
                                          precision=_HI)

    @pl.when(j == kc - 1)
    def _finish():
        @pl.when(live)
        def _live():
            upd_ref[0] = acc_ref[:].astype(dt)   # pre-factor tile

            @pl.when(i == 0)
            def _factor():
                _lu_factor_in_place(acc_ref, bw=bw)
                fac_ref[0] = acc_ref[:].astype(dt)
                uinv_ref[:] = upper_tri_inv(acc_ref[:])

            @pl.when(i != 0)
            def _trsm():
                fac_ref[0] = jnp.dot(acc_ref[:], uinv_ref[:],
                                     preferred_element_type=f32,
                                     precision=_HI).astype(dt)
                # L21 = A21 U^-1

        @pl.when(jnp.logical_not(live))
        def _dead():
            upd_ref[0] = col_ref[0]
            fac_ref[0] = col_ref[0]


@functools.partial(jax.jit, static_argnames=("k", "bw", "interpret"))
def lu_panel_batched(col, left, lead, tiles, k: int = 0, bw: int = 8,
                     interpret: bool = False):
    """Ragged batched fused no-pivot LU panel step.

    col:   [B, M, nb] trailing block columns A[:, k0:, k0:k0+nb]
    left:  [B, M, K]  packed L block rows A[:, k0:, :k0]
    lead:  [B, K, nb] packed U block column A[:, :k0, k0:k0+nb]
    tiles: [B] int32 per-problem live tile counts ceil(size / nb)
    k:     static panel index

    Same scalar-prefetch raggedness as chol_panel_batched: the grid adds
    a leading batch dimension, dead row tiles (k + i >= tiles[b]) copy
    their identity/zero input straight to both outputs, and the LEFT
    stream's index map clamps dead tiles onto the last live row so no
    fresh HBM->VMEM copies are issued for them.  Returns (upd, fac) with
    lu_panel_fused's packed L\\U contract per problem (unit lower
    implied).  Caller guarantees real f32 OR bf16 storage (accumulation
    is f32 either way), M % nb == 0, nb % bw == 0.
    """
    bsz, m, nb = col.shape
    kk = left.shape[2]
    kb = nb
    kp = max(kb, -(-kk // kb) * kb)
    if kk != kp:                             # pad K chunks with zeros
        left = jnp.pad(left, ((0, 0), (0, 0), (0, kp - kk)))
        lead = jnp.pad(lead, ((0, 0), (0, kp - kk), (0, 0)))
    upd, fac = pl.pallas_call(
        functools.partial(_lu_panel_batched_kernel, k=k, bw=bw),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(bsz, m // nb, kp // kb),
            in_specs=[
                pl.BlockSpec((1, nb, nb), lambda b, i, j, tiles: (b, i, 0)),
                pl.BlockSpec(
                    (1, nb, kb),
                    lambda b, i, j, tiles: (
                        b,
                        jnp.minimum(i, jnp.maximum(tiles[b] - k, 1) - 1),
                        j)),
                pl.BlockSpec((1, kb, nb), lambda b, i, j, tiles: (b, j, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, nb, nb), lambda b, i, j, tiles: (b, i, 0)),
                pl.BlockSpec((1, nb, nb), lambda b, i, j, tiles: (b, i, 0)),
            ],
            scratch_shapes=[pltpu.VMEM((nb, nb), jnp.float32),
                            pltpu.VMEM((nb, nb), jnp.float32)],
        ),
        out_shape=[jax.ShapeDtypeStruct((bsz, m, nb), col.dtype),
                   jax.ShapeDtypeStruct((bsz, m, nb), col.dtype)],
        interpret=interpret,
    )(tiles, col, left, lead)
    return upd, fac


@functools.partial(jax.jit, static_argnames=("bw", "interpret"))
def lu_select_pallas(chunk, nrows: jax.Array | None = None, bw: int = 8,
                     interpret: bool = False):
    """Pivot row indices [nb] of a chunk [W, nb] (W % 128 == 0 after the
    caller's padding; ``nrows`` masks the live rows, default all)."""
    W, nb = chunk.shape
    at = chunk.T
    live = (jnp.arange(W, dtype=jnp.int32)[None, :]
            < (W if nrows is None else nrows)).astype(jnp.float32)
    piv = pl.pallas_call(
        functools.partial(_lu_select_kernel, bw=bw),
        out_shape=jax.ShapeDtypeStruct((1, nb), jnp.int32),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                  pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[pltpu.VMEM((nb, W), chunk.dtype),
                        pltpu.VMEM((bw, W), chunk.dtype),
                        pltpu.VMEM((bw, W), chunk.dtype)],
        interpret=interpret,
    )(at, live)
    return piv[0]
