"""internal::getrf — LU panel factorizations.

Analog of the reference's threaded+MPI LU panels:

- partial pivoting panel (ref: src/internal/internal_getrf.cc:20-119 +
  Tile_getrf.hh:99-444): `MaxPanelThreads` host threads cooperate over the
  local tiles of one panel column, with an MPI_Allreduce(MAXLOC) per column
  across the panel ranks and a bcast of the pivot row.  On TPU the panel is
  skinny (W x nb) and per-chip compute is enormous, so the panel is gathered
  and factored REPLICATED on every rank with XLA's native partially-pivoted
  LU — trading a few redundant kilo-FLOPs for the elimination of nb
  latency-bound MAXLOC rounds per panel (the reference's known bottleneck).
- no-pivot panel (ref: internal_getrf_nopiv.cc + Tile_getrf_nopiv.hh).
- tournament pivoting / CALU (ref: internal_getrf_tntpiv.cc:837 +
  Tile_getrf_tntpiv.hh): blocks of rows are factored independently, each
  contributes its nb pivot-candidate rows, and a reduction tree selects the
  final pivot set before one clean factorization.  Here the tournament tree
  is computed on the (already gathered) panel — the pivot SELECTION is the
  CALU algorithm with identical numerics, while the communication shape it
  was invented for is already optimal under replication.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .trsm import tri_inv_lower, tri_inv_upper


def panel_lu(panel):
    """Partially-pivoted LU of a gathered panel [W, nb].

    Returns (lu, perm) with panel[perm] = L @ U (L unit lower incl. rows
    below the square part; U upper nb x nb).
    """
    lu, _, perm = lax.linalg.lu(panel)
    return lu, perm


# ---- out-of-core step kernels (drivers/lu.py getrf_ooc) ----
# Pure jitted functions of the device windows the TileMap streams in;
# jit's shape-keyed cache reuses one executable per window shape across
# steps and across a checkpoint resume, which is what makes a resumed
# run bit-identical to the uninterrupted one.

@jax.jit
def ooc_lu_panel(panel):
    """Partially-pivoted LU of the gathered current panel [W, nb]."""
    return panel_lu(panel)


@jax.jit
def ooc_lu_trailing(colj, lu, perm):
    """One streamed right-looking trailing update: apply the panel's row
    permutation to trailing block column ``colj`` [W, wj], solve the U12
    strip against unit-L11 and subtract the L21 @ U12 contribution.
    Returns the updated [U12; trailing] column."""
    w = lu.shape[1]
    colj = colj[perm]
    u12 = tri_inv_lower(lu[:w, :w], unit_diag=True) @ colj[:w]
    tail = colj[w:] - lu[w:, :w] @ u12
    return jnp.concatenate([u12, tail], axis=0)


def _nopiv_fused_ok(dtype, w: int, nb: int) -> bool:
    """True when the tuned plan routes this no-pivot panel through the
    fused Pallas kernel (internal/pallas_lu.py lu_panel_fused): f32,
    MXU-aligned nb small enough for the [nb, nb] U^-1 scratch."""
    if not (dtype == jnp.float32 and w >= nb
            and nb % 128 == 0 and 128 <= nb <= 512):
        return False
    from ..tune import resolve_plan
    return resolve_plan("getrf_panel", w, "float32").kernel == "pallas"


def panel_lu_nopiv(panel):
    """No-pivot LU of a panel [W, nb] (ref: Tile_getrf_nopiv.hh).

    Routed through the tuned plan for ("getrf_panel", W): the fused
    Pallas panel (tile factor + per-row-tile TRSM in one pallas_call)
    when the plan says so, else the XLA composition — square top block
    factored unpivoted, rows below one MXU gemm against the inverted U
    (tri_inv_upper) instead of a per-column substitution loop.
    """
    nb = panel.shape[1]
    # slate-lint: disable=TRC001 -- capability probe: reads only static shape/dtype/plan, never tracer data
    if _nopiv_fused_ok(panel.dtype, panel.shape[0], nb):
        from ..tune import resolve_plan
        from .pallas_lu import lu_panel_fused
        from .potrf import _interpret
        w = panel.shape[0]
        plan = resolve_plan("getrf_panel", w, "float32")
        wp = -(-w // nb) * nb
        pp = jnp.pad(panel, ((0, wp - w), (0, 0))) if wp != w else panel
        lu = lu_panel_fused(pp, bw=plan.bw, interpret=_interpret())[:w]
        return lu, jnp.arange(w)
    top = panel[:nb]
    lu_top = _lu_nopiv_square(top)
    u = jnp.triu(lu_top)
    below = panel[nb:] @ tri_inv_upper(u)
    lu = jnp.concatenate([lu_top, below], axis=0)
    perm = jnp.arange(panel.shape[0])
    return lu, perm


def _lu_nopiv_base(a):
    """Unpivoted LU of a small square block via fori_loop elimination."""
    n = a.shape[0]

    def body(j, a):
        col = a[:, j]
        pivot = col[j]
        idx = jnp.arange(n)
        l = jnp.where(idx > j, col / pivot, jnp.zeros_like(col))
        a = a - jnp.outer(l, jnp.where(idx > j, a[j], 0.0))
        a = a.at[:, j].set(jnp.where(idx > j, l, col))
        return a

    return lax.fori_loop(0, n, body, a)


def _lu_nopiv_square(a, base: int = 64):
    """Unpivoted LU of a square block, recursively blocked: the rank-1
    elimination loop only ever runs on <= base-wide blocks; everything
    between is tri_inv-powered MXU gemms (same discipline as the blocked
    Householder panel, internal/qr.py)."""
    n = a.shape[0]
    if n <= base:
        return _lu_nopiv_base(a)
    h = n // 2
    a11 = _lu_nopiv_square(a[:h, :h], base)
    l11 = jnp.tril(a11, -1) + jnp.eye(h, dtype=a.dtype)
    u11 = jnp.triu(a11)
    u12 = tri_inv_lower(l11, unit_diag=True) @ a[:h, h:]
    l21 = a[h:, :h] @ tri_inv_upper(u11)
    a22 = _lu_nopiv_square(a[h:, h:] - l21 @ u12, base)
    top = jnp.concatenate([a11, u12], axis=1)
    bot = jnp.concatenate([l21, a22], axis=1)
    return jnp.concatenate([top, bot], axis=0)


def panel_lu_threshold(panel, tau):
    """Threshold-pivoted LU of a panel [W, nb] (ref: Option::PivotThreshold,
    enums.hh:91 'threshold for pivoting, >= 0, <= 1'; used by the reference
    getrf panel to prefer the diagonal when it is within ``tau`` of the
    column max, trading a bounded growth factor for fewer row swaps).

    One fori_loop of masked rank-1 steps; returns (lu, perm) like
    :func:`panel_lu`.
    """
    W, nb = panel.shape
    rows = jnp.arange(W)
    tau = jnp.asarray(tau, jnp.real(panel).dtype)

    def body(j, carry):
        a, perm = carry
        col = lax.dynamic_index_in_dim(a, j, axis=1, keepdims=False)
        mag = jnp.where(rows >= j, jnp.abs(col), -jnp.ones_like(
            jnp.abs(col)))
        cmax = jnp.max(mag)
        diag = jnp.abs(col[j])
        pos = jnp.where(diag >= tau * cmax, j, jnp.argmax(mag))
        # swap rows j <-> pos
        rj, rp = a[j], a[pos]
        a = a.at[j].set(rp).at[pos].set(rj)
        pj, pp = perm[j], perm[pos]
        perm = perm.at[j].set(pp).at[pos].set(pj)
        # eliminate below the diagonal
        colj = lax.dynamic_index_in_dim(a, j, axis=1, keepdims=False)
        piv = colj[j]
        safe = jnp.where(piv == 0, jnp.ones_like(piv), piv)
        l = jnp.where((rows > j) & (piv != 0), colj / safe,
                      jnp.zeros_like(colj))
        cols = jnp.arange(nb)
        rowj = jnp.where(cols > j, a[j], jnp.zeros_like(a[j]))
        a = a - jnp.outer(l, rowj)
        a = a.at[:, j].set(jnp.where(rows > j, l, colj))
        return a, perm

    lu, perm = lax.fori_loop(0, min(W, nb), body,
                             (panel, jnp.arange(W)))
    return lu, perm


def _lu_select_ok(blocks, nb: int) -> bool:
    """Route tournament pivot selection through the Pallas kernel
    (internal/pallas_lu.py) when the tuned plan for ("lu_select", W)
    says so.  The old direct SLATE_PALLAS=1 gate is deprecated — the
    tune resolver honors the env var for one release as a force
    override (docs/TUNING.md)."""
    from ..tune import resolve_plan
    W = blocks.shape[1]
    return (resolve_plan("lu_select", W, "float32").kernel == "pallas"
            and blocks.dtype == jnp.float32
            and nb % 128 == 0 and W % 128 == 0 and W <= 4096)


def panel_lu_tournament(panel, block_rows: int, arity: int = 2):
    """CALU tournament pivot selection + clean factorization
    (ref: internal_getrf_tntpiv.cc, Tile_getrf_tntpiv.hh).

    Round 1: factor every block of ``block_rows`` rows in ONE batched
    (vmapped) pivoted LU and keep each block's nb pivot rows.  Reduction
    rounds: merge ``arity`` candidate sets at a time (Option.Depth — the
    fan-in), again one batched LU per LEVEL — the tree is latency-bound,
    and XLA's batched LU amortizes its per-column While latency across
    the whole batch (measured 5.4x, docs/ceiling.jsonl xla_lu batch32).
    Finally the chosen rows move to the top via a VECTORIZED permutation
    that displaces at most 2 nb rows (the bound the distributed bundle
    exchange relies on), and the permuted panel is factored with no
    further pivoting across blocks — CALU's defining step.
    Returns (lu, perm) like :func:`panel_lu`.
    """
    arity = max(2, int(arity))
    W, nb = panel.shape
    iota = jnp.arange(W)
    if W <= nb:
        lu, _, perm = lax.linalg.lu(panel)
        return lu, perm
    block_rows = max(block_rows, nb)
    nch = -(-W // block_rows)
    Wp = nch * block_rows
    pp = jnp.pad(panel, ((0, Wp - W), (0, 0)))
    # pad rows carry sentinel index W; all-zero, they lose every pivot
    # contest against any nonzero row
    gidx = jnp.concatenate([iota, jnp.full((Wp - W,), W, iota.dtype)])
    cand = pp.reshape(nch, block_rows, nb)
    cidx = gidx.reshape(nch, block_rows)

    def keep_best(blocks, idx):
        # slate-lint: disable=TRC001 -- capability probe: reads only static shape/dtype/env, never tracer data
        if _lu_select_ok(blocks, nb):
            from ..tune import resolve_plan
            from .pallas_lu import lu_select_pallas
            from .potrf import _interpret
            bw = resolve_plan("lu_select", blocks.shape[1], "float32").bw
            take = jax.vmap(lambda b: lu_select_pallas(
                b, bw=bw, interpret=_interpret()))(blocks)
        else:
            _, _, pb = jax.vmap(lax.linalg.lu)(blocks)
            take = pb[:, :nb]
        return (jnp.take_along_axis(blocks, take[:, :, None], axis=1),
                jnp.take_along_axis(idx, take, axis=1))

    if block_rows > nb:
        cand, cidx = keep_best(cand, cidx)
    while cand.shape[0] > 1:
        g = cand.shape[0]
        gp = -(-g // arity) * arity
        if gp > g:
            cand = jnp.concatenate(
                [cand, jnp.zeros((gp - g,) + cand.shape[1:], cand.dtype)])
            cidx = jnp.concatenate(
                [cidx, jnp.full((gp - g, cidx.shape[1]), W, cidx.dtype)])
        rows_per = cand.shape[1]
        cand = cand.reshape(gp // arity, arity * rows_per, nb)
        cidx = cidx.reshape(gp // arity, arity * rows_per)
        cand, cidx = keep_best(cand, cidx)
    chosen = cidx[0, :nb]
    # sentinel guard (only reachable for a singular panel): fill sentinel
    # slots with the smallest NOT-chosen rows so `chosen` stays a set of
    # nb DISTINCT in-range rows (a naive slot-index fallback can collide
    # with a genuinely chosen row and silently drop a matrix row)
    valid = chosen < W
    # scatter sentinels OUT of range (mode="drop") — aliasing them to a
    # real index races a True and a False onto that slot
    in_ch0 = jnp.zeros((W,), jnp.bool_).at[
        jnp.where(valid, chosen, W)].set(True, mode="drop")
    free = jnp.sort(jnp.where(in_ch0, W + iota, iota))
    kfree = jnp.cumsum(~valid) - 1
    chosen = jnp.where(valid, chosen,
                       free[jnp.clip(kfree, 0, W - 1)].astype(chosen.dtype))

    # Vectorized pivot placement: perm[j] = chosen[j] for j < nb, and the
    # displaced top rows fill the holes the chosen rows left (both in
    # ascending order) — a permutation displacing <= 2 nb rows, with no
    # nb-step transposition loop.
    in_ch = jnp.zeros((W,), jnp.bool_).at[chosen].set(True)
    s1 = (~in_ch) & (iota < nb)              # top rows pushed out
    s2 = in_ch & (iota >= nb)                # holes left below
    idx1 = jnp.sort(jnp.where(s1, iota, W + iota))[:nb]
    r2 = jnp.cumsum(s2.astype(jnp.int32)) - 1
    fill = idx1[jnp.clip(r2, 0, nb - 1)]
    perm = iota.at[:nb].set(chosen)
    perm = jnp.where(s2, jnp.where(fill < W, fill, iota), perm)
    lu, _ = panel_lu_nopiv(panel[perm])
    return lu, perm
