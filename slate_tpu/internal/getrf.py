"""internal::getrf — LU panel factorizations.

Analog of the reference's threaded+MPI LU panels:

- partial pivoting panel (ref: src/internal/internal_getrf.cc:20-119 +
  Tile_getrf.hh:99-444): `MaxPanelThreads` host threads cooperate over the
  local tiles of one panel column, with an MPI_Allreduce(MAXLOC) per column
  across the panel ranks and a bcast of the pivot row.  On TPU the panel is
  skinny (W x nb) and per-chip compute is enormous, so the panel is gathered
  and factored REPLICATED on every rank with XLA's native partially-pivoted
  LU — trading a few redundant kilo-FLOPs for the elimination of nb
  latency-bound MAXLOC rounds per panel (the reference's known bottleneck).
- no-pivot panel (ref: internal_getrf_nopiv.cc + Tile_getrf_nopiv.hh).
- tournament pivoting / CALU (ref: internal_getrf_tntpiv.cc:837 +
  Tile_getrf_tntpiv.hh): blocks of rows are factored independently, each
  contributes its nb pivot-candidate rows, and a reduction tree selects the
  final pivot set before one clean factorization.  Here the tournament tree
  is computed on the (already gathered) panel — the pivot SELECTION is the
  CALU algorithm with identical numerics, while the communication shape it
  was invented for is already optimal under replication.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def panel_lu(panel):
    """Partially-pivoted LU of a gathered panel [W, nb].

    Returns (lu, perm) with panel[perm] = L @ U (L unit lower incl. rows
    below the square part; U upper nb x nb).
    """
    lu, _, perm = lax.linalg.lu(panel)
    return lu, perm


def panel_lu_nopiv(panel):
    """No-pivot LU of a panel [W, nb] (ref: Tile_getrf_nopiv.hh).

    Square top block factored unpivoted; rows below solved against U.
    """
    nb = panel.shape[1]
    top = panel[:nb]
    lu_top = _lu_nopiv_square(top)
    u = jnp.triu(lu_top)
    below = lax.linalg.triangular_solve(
        u, panel[nb:], left_side=False, lower=False)
    lu = jnp.concatenate([lu_top, below], axis=0)
    perm = jnp.arange(panel.shape[0])
    return lu, perm


def _lu_nopiv_square(a):
    """Unpivoted LU of a square block via fori_loop Gaussian elimination."""
    n = a.shape[0]

    def body(j, a):
        col = a[:, j]
        pivot = col[j]
        idx = jnp.arange(n)
        l = jnp.where(idx > j, col / pivot, jnp.zeros_like(col))
        a = a - jnp.outer(l, jnp.where(idx > j, a[j], 0.0))
        a = a.at[:, j].set(jnp.where(idx > j, l, col))
        return a

    return lax.fori_loop(0, n, body, a)


def panel_lu_threshold(panel, tau):
    """Threshold-pivoted LU of a panel [W, nb] (ref: Option::PivotThreshold,
    enums.hh:91 'threshold for pivoting, >= 0, <= 1'; used by the reference
    getrf panel to prefer the diagonal when it is within ``tau`` of the
    column max, trading a bounded growth factor for fewer row swaps).

    One fori_loop of masked rank-1 steps; returns (lu, perm) like
    :func:`panel_lu`.
    """
    W, nb = panel.shape
    rows = jnp.arange(W)
    tau = jnp.asarray(tau, jnp.real(panel).dtype)

    def body(j, carry):
        a, perm = carry
        col = lax.dynamic_index_in_dim(a, j, axis=1, keepdims=False)
        mag = jnp.where(rows >= j, jnp.abs(col), -jnp.ones_like(
            jnp.abs(col)))
        cmax = jnp.max(mag)
        diag = jnp.abs(col[j])
        pos = jnp.where(diag >= tau * cmax, j, jnp.argmax(mag))
        # swap rows j <-> pos
        rj, rp = a[j], a[pos]
        a = a.at[j].set(rp).at[pos].set(rj)
        pj, pp = perm[j], perm[pos]
        perm = perm.at[j].set(pp).at[pos].set(pj)
        # eliminate below the diagonal
        colj = lax.dynamic_index_in_dim(a, j, axis=1, keepdims=False)
        piv = colj[j]
        safe = jnp.where(piv == 0, jnp.ones_like(piv), piv)
        l = jnp.where((rows > j) & (piv != 0), colj / safe,
                      jnp.zeros_like(colj))
        cols = jnp.arange(nb)
        rowj = jnp.where(cols > j, a[j], jnp.zeros_like(a[j]))
        a = a - jnp.outer(l, rowj)
        a = a.at[:, j].set(jnp.where(rows > j, l, colj))
        return a, perm

    lu, perm = lax.fori_loop(0, min(W, nb), body,
                             (panel, jnp.arange(W)))
    return lu, perm


def panel_lu_tournament(panel, block_rows: int, arity: int = 2):
    """CALU tournament pivot selection + clean factorization
    (ref: internal_getrf_tntpiv.cc, Tile_getrf_tntpiv.hh).

    Round 1: factor each block of ``block_rows`` rows independently and keep
    its nb pivot rows.  Reduction rounds: merge ``arity`` candidate sets at
    a time (Option.Depth — the reduction-tree fan-in) with another LU until
    one set remains.  Finally permute the chosen rows to the top and factor
    the whole panel without further pivoting across blocks.
    Returns (lu, perm) like :func:`panel_lu`.
    """
    arity = max(2, int(arity))
    W, nb = panel.shape
    rows = jnp.arange(W)

    def best_rows(block, idx):
        """nb pivot-candidate rows of a block and their global indices."""
        _, _, p = lax.linalg.lu(block)
        return block[p[:nb]], idx[p[:nb]]

    # round 1 over static row blocks
    cands, cidx = [], []
    for s in range(0, W, block_rows):
        e = min(s + block_rows, W)
        blk = panel[s:e]
        if e - s < nb:  # tiny tail: keep all its rows as candidates
            cands.append(blk)
            cidx.append(rows[s:e])
        else:
            b, i = best_rows(blk, rows[s:e])
            cands.append(b)
            cidx.append(i)
    # reduction tree, fan-in = arity
    while len(cands) > 1:
        nxt_c, nxt_i = [], []
        for t in range(0, len(cands), arity):
            grp_c = cands[t: t + arity]
            grp_i = cidx[t: t + arity]
            if len(grp_c) == 1:
                nxt_c.append(grp_c[0])
                nxt_i.append(grp_i[0])
            else:
                merged = jnp.concatenate(grp_c, axis=0)
                midx = jnp.concatenate(grp_i)
                b, i = best_rows(merged, midx)
                nxt_c.append(b)
                nxt_i.append(i)
        cands, cidx = nxt_c, nxt_i
    chosen = cidx[0][:nb]                     # global rows chosen as pivots

    # Bring chosen[j] to row j via nb TRANSPOSITIONS (so the composed perm
    # displaces <= 2 nb rows — the bound the distributed row exchange relies
    # on, same as partial pivoting's ipiv products), then factor the
    # permuted panel with NO further pivoting: that is CALU's defining step
    # (ref: getrf_tntpiv applies the tournament pivots then an unpivoted
    # panel factorization).
    def bring(j, arr):
        pos = jnp.argmax(arr == chosen[j])
        vj, vp = arr[j], arr[pos]
        return arr.at[j].set(vp).at[pos].set(vj)

    perm = lax.fori_loop(0, nb, bring, jnp.arange(W))
    lu, _ = panel_lu_nopiv(panel[perm])
    return lu, perm
