"""internal::herk / syrk — rank-k trailing update on local tiles.

Analog of the reference's internal_herk.cc:843 / internal_syrk.cc:836:
diagonal tiles get a true herk, off-diagonal tiles a gemm, all batched.
On TPU both collapse into one einsum over the tile batch; the diagonal
tiles' redundant strictly-upper work is masked by consumers (triangular
reads) rather than skipped — trading ~nb^2/2 FLOPs per diagonal tile for
one uniform MXU contraction.
"""

from __future__ import annotations

import jax.numpy as jnp


def herk_panel_update(prow, pcol, conj: bool = True):
    """C[i, j] -= P[i] @ op(P[j]) for tile batches.

    prow: [S, mb, kb] panel tiles for the rows being updated
    pcol: [T, nb, kb] panel tiles for the columns being updated
    returns the SUBTRACTED term [S, T, mb, nb] (caller applies sign/beta).
    """
    pc = jnp.conj(pcol) if conj else pcol
    return jnp.einsum("iab,jcb->ijac", prow, pc,
                      preferred_element_type=prow.dtype)
