"""internal::trsm — triangular solve against one diagonal tile, batched over
a tile column/row.

Analog of the reference's internal_trsm.cc:481 / internal_trsmA.cc (single
block row/col solve, batched on device via blas::batch::trsm).  Here the
batch is a vmapped XLA triangular_solve over the [batch, mb, nb] tile array.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..types import Op


def apply_op_tile(t, op: Op):
    if op is Op.Trans:
        return t.swapaxes(-1, -2)
    if op is Op.ConjTrans:
        return jnp.conj(t).swapaxes(-1, -2)
    return t


def trsm_tile_batch(tri, b_batch, *, left: bool, lower: bool,
                    unit_diag: bool = False, op_tri: Op = Op.NoTrans):
    """Solve op(T) X = B (left) or X op(T) = B (right) for each tile in
    b_batch [batch, mb, nb] against one triangular tile T."""
    t = apply_op_tile(tri, op_tri)
    low = lower if op_tri is Op.NoTrans else not lower
    return jax.vmap(lambda b: lax.linalg.triangular_solve(
        t, b, left_side=left, lower=low, unit_diagonal=unit_diag))(b_batch)


def tri_inv_lower(L, unit_diag: bool = False, base: int = 32):
    """Explicit inverse of a lower-triangular [n, n] block in LOG depth.

    The reference's trsm panels run forward substitution (one column of
    latency per step); on TPU a [15872, 512] triangular_solve measured
    675 GFLOP/s (docs/ceiling.jsonl) because the per-column While loop
    cannot feed the MXU.  The MAGMA-style alternative is to invert the
    nb x nb diagonal block once and turn every panel solve into one MXU
    gemm.  This inversion is itself log-depth and fully batched:

      inv([[A, 0], [C, B]]) = [[inv(A), 0], [-inv(B) C inv(A), inv(B)]]

    All ``base``-sized diagonal blocks are inverted in ONE batched
    triangular_solve, then each doubling level merges all sibling pairs
    with two batched matmuls — ~log2(n/base) * 3 device ops total, vs n
    sequential column steps.  Pads to a power-of-two multiple of ``base``
    with an identity diagonal (exact: the inverse of blockdiag(L, I) is
    blockdiag(inv(L), I))."""
    n = L.shape[0]
    dt = L.dtype
    if n <= base:
        return lax.linalg.triangular_solve(
            L, jnp.eye(n, dtype=dt), left_side=True, lower=True,
            unit_diagonal=unit_diag)
    n2 = base
    while n2 < n:
        n2 *= 2
    if n2 > n:
        r = jnp.arange(n, n2)
        Lp = jnp.zeros((n2, n2), dt).at[:n, :n].set(L).at[r, r].set(1)
    else:
        Lp = L
    m = n2 // base
    i = jnp.arange(m)
    d = Lp.reshape(m, base, m, base)[i, :, i, :]       # [m, base, base]
    eye = jnp.eye(base, dtype=dt)
    X = jax.vmap(lambda t: lax.linalg.triangular_solve(
        t, eye, left_side=True, lower=True,
        unit_diagonal=unit_diag))(d)
    s = base
    while s < n2:
        m2 = X.shape[0] // 2
        A, B = X[0::2], X[1::2]
        Ls = Lp.reshape(n2 // s, s, n2 // s, s)
        j = jnp.arange(m2)
        C = Ls[2 * j + 1, :, 2 * j, :]                 # [m2, s, s]
        off = -jnp.einsum("bij,bjk,bkl->bil", B, C, A)
        top = jnp.concatenate([A, jnp.zeros_like(A)], axis=2)
        bot = jnp.concatenate([off, B], axis=2)
        X = jnp.concatenate([top, bot], axis=1)
        s *= 2
    return X[0][:n, :n]


def tri_inv_upper(U, unit_diag: bool = False, base: int = 32):
    """inv(U) for upper-triangular U via the lower-triangular engine:
    inv(U) = inv(U^T)^T."""
    return tri_inv_lower(U.T, unit_diag=unit_diag, base=base).T


def _diag_tiles(ad, K: int, nb: int):
    """[K, nb, nb] diagonal blocks of a [K nb, K nb] dense matrix."""
    i = jnp.arange(K)
    return ad.reshape(K, nb, K, nb)[i, :, i, :]


def _pad_tri(ad, nb: int):
    """Identity-augment a triangular [n, n] up to the next multiple of nb.

    blockdiag(A, I) is triangular whichever triangle A lives in (the pad's
    off-diagonal blocks are zero) and the identity diagonal is invariant
    under transpose/conjugate, so padding BEFORE the op is exact:
    solving against blockdiag(op(A), I) with zero-padded B rows/cols
    yields the unpadded solution in the leading n slice."""
    n = ad.shape[0]
    n2 = -(-n // nb) * nb
    if n2 == n:
        return ad, n
    r = jnp.arange(n, n2)
    return (jnp.zeros((n2, n2), ad.dtype).at[:n, :n].set(ad)
            .at[r, r].set(1)), n


def _checksum_repair(a_op, x, bd, *, eff_lower: bool, unit: bool):
    """Verify the finished solve ``a_op @ x == bd`` through bd's
    Huang-Abraham checksums and repair ONE corrupted element of x in
    place (robust/abft.py, lazy import — robust pulls in the driver
    layer at package init).  The upper-triangular case is index-reversed
    into the canonical lower-left product: ``P A P`` is lower for the
    reversal permutation P, column sums are P-invariant and row sums
    P-equivariant."""
    from ..robust.abft import left_product_check
    r_row = jnp.sum(bd, axis=1)
    r_col = jnp.sum(bd, axis=0)
    if eff_lower:
        x2, _, _, _, _ = left_product_check(a_op, x, r_row, r_col,
                                            unit=unit)
        return x2
    x2, _, _, _, _ = left_product_check(a_op[::-1, ::-1], x[::-1],
                                        r_row[::-1], r_col, unit=unit)
    return x2[::-1]


def trsm_left_blocked(ad, bd, *, lower: bool, trans: bool, conj: bool,
                      unit: bool, nb: int, check: bool = False):
    """Solve op(A) X = B, A triangular [n, n], by block substitution with
    ALL diagonal blocks inverted in one batched log-depth pass
    (tri_inv_lower) — each step is then two MXU gemms.  A ragged n (not a
    multiple of nb) is identity-augmented to the next block boundary
    (exact; see _pad_tri).

    XLA's monolithic triangular_solve runs a per-column While loop
    (measured 4.1 TFLOP/s on [16384, 256], docs/ceiling.jsonl); this is
    the reference's work_trsm block sweep (ref: work/work_trsm.cc)
    reshaped so every op is a matmul."""
    ad, n0 = _pad_tri(ad, nb)
    n = ad.shape[0]
    if n > n0:
        bd = jnp.zeros((n, bd.shape[1]), bd.dtype).at[:n0].set(bd)
    K = n // nb
    a_op = jnp.conj(ad) if conj else ad
    if trans:
        a_op = a_op.T
    eff_lower = lower != trans
    d = _diag_tiles(a_op, K, nb)
    if eff_lower:
        dinv = jax.vmap(lambda t: tri_inv_lower(t, unit_diag=unit))(d)
    else:
        dinv = jax.vmap(lambda t: tri_inv_upper(t, unit_diag=unit))(d)
    xs = [None] * K
    order = range(K) if eff_lower else range(K - 1, -1, -1)
    for k in order:
        k0, k1 = k * nb, (k + 1) * nb
        acc = bd[k0:k1]
        if eff_lower and k > 0:
            x_done = jnp.concatenate(xs[:k], axis=0)
            acc = acc - a_op[k0:k1, :k0] @ x_done
        elif not eff_lower and k < K - 1:
            x_done = jnp.concatenate(xs[k + 1:], axis=0)
            acc = acc - a_op[k0:k1, k1:] @ x_done
        xs[k] = dinv[k] @ acc
    x = jnp.concatenate(xs, axis=0)
    if check:
        x = _checksum_repair(a_op, x, bd, eff_lower=eff_lower, unit=unit)
    return x[:n0]


def trsm_right_blocked(ad, bd, *, lower: bool, trans: bool, conj: bool,
                       unit: bool, nb: int, check: bool = False):
    """Solve X op(A) = B by block substitution over block columns (right
    side twin of trsm_left_blocked; ragged n identity-augmented)."""
    ad, n0 = _pad_tri(ad, nb)
    n = ad.shape[0]
    if n > n0:
        bd = jnp.zeros((bd.shape[0], n), bd.dtype).at[:, :n0].set(bd)
    K = n // nb
    a_op = jnp.conj(ad) if conj else ad
    if trans:
        a_op = a_op.T
    eff_lower = lower != trans
    d = _diag_tiles(a_op, K, nb)
    if eff_lower:
        dinv = jax.vmap(lambda t: tri_inv_lower(t, unit_diag=unit))(d)
    else:
        dinv = jax.vmap(lambda t: tri_inv_upper(t, unit_diag=unit))(d)
    xs = [None] * K
    # X_k depends on later X_j for lower (B_k - sum_{j>k} X_j A[j,k]),
    # earlier for upper
    order = range(K - 1, -1, -1) if eff_lower else range(K)
    for k in order:
        k0, k1 = k * nb, (k + 1) * nb
        acc = bd[:, k0:k1]
        if eff_lower and k < K - 1:
            x_done = jnp.concatenate(xs[k + 1:], axis=1)
            acc = acc - x_done @ a_op[k1:, k0:k1]
        elif not eff_lower and k > 0:
            x_done = jnp.concatenate(xs[:k], axis=1)
            acc = acc - x_done @ a_op[:k0, k0:k1]
        xs[k] = acc @ dinv[k]
    x = jnp.concatenate(xs, axis=1)
    if check:
        # X op(A) = B  <=>  op(A)^T X^T = B^T: the left check transposed
        x = _checksum_repair(a_op.T, x.T, bd.T,
                             eff_lower=not eff_lower, unit=unit).T
    return x[:, :n0]
