"""internal::trsm — triangular solve against one diagonal tile, batched over
a tile column/row.

Analog of the reference's internal_trsm.cc:481 / internal_trsmA.cc (single
block row/col solve, batched on device via blas::batch::trsm).  Here the
batch is a vmapped XLA triangular_solve over the [batch, mb, nb] tile array.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..types import Op


def apply_op_tile(t, op: Op):
    if op is Op.Trans:
        return t.swapaxes(-1, -2)
    if op is Op.ConjTrans:
        return jnp.conj(t).swapaxes(-1, -2)
    return t


def trsm_tile_batch(tri, b_batch, *, left: bool, lower: bool,
                    unit_diag: bool = False, op_tri: Op = Op.NoTrans):
    """Solve op(T) X = B (left) or X op(T) = B (right) for each tile in
    b_batch [batch, mb, nb] against one triangular tile T."""
    t = apply_op_tile(tri, op_tri)
    low = lower if op_tri is Op.NoTrans else not lower
    return jax.vmap(lambda b: lax.linalg.triangular_solve(
        t, b, left_side=left, lower=low, unit_diagonal=unit_diag))(b_batch)
