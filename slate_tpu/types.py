"""Scalar/structure type vocabulary.

Analog of the reference's blaspp-derived enums (Op/Uplo/Diag/Layout/Side/Norm)
used throughout include/slate (ref: include/slate/Tile.hh:40-90 transpose
views, include/slate/types.hh:103-144 mpi_type mapping).  The mpi_type<T>
table maps here to jax dtype handling: collectives are dtype-generic, so the
table reduces to helpers for real/complex introspection and precision pairs
(used by the mixed-precision solvers).
"""

from __future__ import annotations

import enum

import jax.numpy as jnp
import numpy as np


class Op(enum.Enum):
    NoTrans = "n"
    Trans = "t"
    ConjTrans = "c"


class Uplo(enum.Enum):
    Lower = "l"
    Upper = "u"
    General = "g"


class Diag(enum.Enum):
    NonUnit = "n"
    Unit = "u"


class Side(enum.Enum):
    Left = "l"
    Right = "r"


class Layout(enum.Enum):
    ColMajor = "c"
    RowMajor = "r"


class Norm(enum.Enum):
    One = "1"
    Inf = "i"
    Max = "m"
    Fro = "f"


class TileKind(enum.Enum):
    """Ownership of a tile buffer (ref: Tile.hh TileKind).

    On TPU all tiles of a matrix live in one XLA-owned buffer; the ownership
    distinction survives as provenance metadata (user-imported vs framework
    allocated vs transient workspace) used by the debug/print layer.
    """

    SlateOwned = "owned"
    UserOwned = "user"
    Workspace = "workspace"


def compose_op(a: Op, b: Op) -> Op:
    """op composition for stacked transpose views (ref: Tile.hh:40-90)."""
    if b is Op.NoTrans:
        return a
    if a is Op.NoTrans:
        return b
    if a is b:
        return Op.NoTrans
    # Trans ∘ ConjTrans = Conj — the reference forbids this too.
    raise ValueError("unsupported op composition (conj-only view)")


def is_complex(dtype) -> bool:
    return jnp.issubdtype(jnp.dtype(dtype), jnp.complexfloating)


def real_dtype(dtype):
    return jnp.finfo(jnp.dtype(dtype)).dtype if not is_complex(dtype) \
        else jnp.zeros((), dtype).real.dtype


def lower_precision(dtype):
    """Factorisation precision for mixed solvers (f64->f32, c128->c64).

    On TPU this is the key lever: the MXU is natively fast in f32/bf16 while
    f64 is emulated, so gesv_mixed-style solvers (ref:
    src/gesv_mixed_gmres.cc:24-117) are the TPU-native high-precision path.
    """
    d = jnp.dtype(dtype)
    table = {np.dtype(np.float64): jnp.float32,
             np.dtype(np.complex128): jnp.complex64,
             np.dtype(np.float32): jnp.bfloat16}
    return table.get(d, d)


def eps(dtype) -> float:
    return float(jnp.finfo(real_dtype(dtype)).eps)
