"""Version info (ref: src/version.cc, include/slate/slate.hh:30)."""

__version__ = "2026.07.00"


def version() -> int:
    """Integer version YYYYMMRR (ref: slate::version)."""
    return 2026_07_00


def id() -> str:
    """Source identifier (ref: slate::id)."""
    return f"slate_tpu {__version__}"
