"""ctypes binding to the native host runtime (native/slate_tpu_native.cc).

The TPU compute path is JAX/XLA; the native library covers the HOST
runtime around it — the analog of the reference's C++ storage/layout
layer and C API tier (ref: MatrixStorage.hh, Tile.hh:707 layoutConvert,
src/c_api/wrappers.cc): packing user LAPACK column-major buffers into the
2D block-cyclic tile layout at memory bandwidth (OpenMP across tiles),
the inverse unpack, and ScaLAPACK descriptor arithmetic.

Build once with ``make -C native``; everything degrades to the pure
numpy fallback when the .so is absent (the reference's no-MPI stub
discipline, src/stubs/)."""

from __future__ import annotations

import ctypes
import os

import numpy as np

_LIB = None


def _load():
    global _LIB
    if _LIB is not None:
        return _LIB
    path = os.path.join(os.path.dirname(__file__), "_native.so")
    if not os.path.exists(path):
        _LIB = False
        return False
    try:
        lib = ctypes.CDLL(path)
    except OSError:
        _LIB = False
        return False
    i64 = ctypes.c_int64
    lib.slate_tpu_native_version.restype = i64
    lib.slate_tpu_numroc.restype = i64
    lib.slate_tpu_numroc.argtypes = [i64] * 5
    for name, ct in (("f64", ctypes.c_double), ("f32", ctypes.c_float)):
        for op in ("pack", "unpack"):
            fn = getattr(lib, f"slate_tpu_{op}_tiles_{name}")
            fn.restype = None
            fn.argtypes = [ctypes.POINTER(ct)] + [i64] * 7 + \
                          [ctypes.POINTER(ct)]
    _LIB = lib
    return lib


def available() -> bool:
    return bool(_load())


def supports(dtype) -> bool:
    """Whether the native pack/unpack kernels handle this dtype."""
    return np.dtype(dtype) in _CTYPES


def version() -> int | None:
    lib = _load()
    return int(lib.slate_tpu_native_version()) if lib else None


def numroc(n: int, nb: int, iproc: int, isrcproc: int, nprocs: int) -> int:
    """ScaLAPACK numroc via the native library; the fallback IS the compat
    tier's pure-Python implementation (single source of the arithmetic)."""
    lib = _load()
    if lib:
        return int(lib.slate_tpu_numroc(n, nb, iproc, isrcproc, nprocs))
    from .compat.scalapack import numroc as _py_numroc
    return _py_numroc(n, nb, iproc, isrcproc, nprocs)


_CTYPES = {np.dtype(np.float64): ("f64", ctypes.c_double),
           np.dtype(np.float32): ("f32", ctypes.c_float)}


def pack_tiles(a: np.ndarray, mb: int, nb: int, p: int, q: int):
    """Host pack: numpy [m, n] (row-major) -> cyclic tile array
    [p*mtl, q*ntl, mb, nb], one memory pass, no transpose copies.
    Returns None when the native path cannot take this input (caller
    falls back to the jnp layout ops)."""
    lib = _load()
    if not lib or a.ndim != 2 or a.dtype not in _CTYPES:
        return None
    m, n = a.shape
    Mt, Nt = -(-m // mb), -(-n // nb)
    mtl, ntl = -(-Mt // p), -(-Nt // q)
    sfx, ct = _CTYPES[a.dtype]
    src = np.ascontiguousarray(a)          # no-op for numpy's default order
    out = np.empty((p * mtl, q * ntl, mb, nb), a.dtype)
    fn = getattr(lib, f"slate_tpu_pack_tiles_{sfx}")
    fn(src.ctypes.data_as(ctypes.POINTER(ct)), m, n, n, mb, nb, p, q,
       out.ctypes.data_as(ctypes.POINTER(ct)))
    return out


def unpack_tiles(tiles: np.ndarray, m: int, n: int, p: int, q: int):
    """Cyclic tile array -> numpy [m, n] (row-major), one memory pass."""
    lib = _load()
    if not lib or tiles.dtype not in _CTYPES:
        return None
    mb, nb = tiles.shape[2], tiles.shape[3]
    sfx, ct = _CTYPES[tiles.dtype]
    src = np.ascontiguousarray(tiles)
    out = np.empty((m, n), tiles.dtype)
    fn = getattr(lib, f"slate_tpu_unpack_tiles_{sfx}")
    fn(src.ctypes.data_as(ctypes.POINTER(ct)), m, n, n, mb, nb, p, q,
       out.ctypes.data_as(ctypes.POINTER(ct)))
    return out
