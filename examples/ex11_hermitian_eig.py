"""ex11: Hermitian eigensolver (ref: ex11_hermitian_eig.cc) — two-stage
reduction + tridiagonal solve, values-only and full vectors."""

import _common
from _common import report, rng

import jax
import numpy as np
import slate_tpu as st
from slate_tpu import api


def main():
    r = rng()
    n, nb = 32, 8
    a = r.standard_normal((n, n))
    sym = (a + a.T) / 2
    H = st.HermitianMatrix.from_numpy(sym, nb)

    lam = api.eig_vals(H)
    lam_ref = np.linalg.eigvalsh(np.tril(sym) + np.tril(sym, -1).T)
    report("ex11 eig_vals", float(np.abs(np.asarray(lam) - lam_ref).max() /
                                  np.abs(lam_ref).max()))

    w, Z = api.eig(H)
    zd = Z.to_numpy()
    hd = np.tril(sym) + np.tril(sym, -1).T
    report("ex11 eig residual", float(np.abs(
        hd @ zd - zd * np.asarray(w)[None, :]).max() /
        np.abs(lam_ref).max()), 1e-9)
    report("ex11 eig orthonormal", float(np.abs(
        zd.T @ zd - np.eye(n)).max()), 1e-9)


if __name__ == "__main__":
    main()
