"""ex12: generalized Hermitian-definite eigenproblem A x = lambda B x
(ref: ex12_generalized_hermitian_eig.cc -> hegv)."""

import _common
from _common import report, rng

import jax
import numpy as np
import scipy.linalg
import slate_tpu as st


def main():
    r = rng()
    n, nb = 24, 6
    a = r.standard_normal((n, n))
    sym = (a + a.T) / 2
    c = r.standard_normal((n, n))
    spd = c @ c.T + n * np.eye(n)
    A = st.HermitianMatrix.from_numpy(sym, nb)
    B = st.HermitianMatrix.from_numpy(spd, nb)

    w, X = st.hegv(A, B)
    w_ref = scipy.linalg.eigh(sym, spd, eigvals_only=True)
    report("ex12 hegv values", float(np.abs(np.asarray(w) - w_ref).max() /
                                     np.abs(w_ref).max()))

    xd = X.to_numpy()
    report("ex12 hegv residual", float(np.abs(
        sym @ xd - spd @ xd * np.asarray(w)[None, :]).max() /
        (np.abs(w_ref).max() * np.linalg.norm(spd))), 1e-10)


if __name__ == "__main__":
    main()
