"""ex06: LU linear systems (ref: ex06_linear_system_lu.cc) — lu_solve,
factor/solve split, tournament pivoting, mixed precision."""

import _common
from _common import report, rng

import jax
import numpy as np
import slate_tpu as st
from slate_tpu import api


def main():
    r = rng()
    grid = st.Grid(2, 2, devices=jax.devices()[:4])
    n, nb = 32, 8
    a = r.standard_normal((n, n)) + n * np.eye(n)
    b = r.standard_normal((n, 4))
    A = st.Matrix.from_numpy(a, nb, nb, grid)
    B = st.Matrix.from_numpy(b, nb, nb, grid)

    X = api.lu_solve(A, B)
    report("ex06 lu_solve", float(np.linalg.norm(a @ X.to_numpy() - b) /
                                  np.linalg.norm(b)))

    F = api.lu_factor(A)
    X2 = api.lu_solve_using_factor(F, B)
    report("ex06 factor+solve", float(np.linalg.norm(
        a @ X2.to_numpy() - b) / np.linalg.norm(b)))

    opts = {st.Option.MethodLU: st.MethodLU.CALU}
    _, X3 = st.gesv(A, B, opts)
    report("ex06 CALU (tntpiv)", float(np.linalg.norm(
        a @ X3.to_numpy() - b) / np.linalg.norm(b)))

    # mixed precision: f32 factor + f64 refinement (the TPU-native path)
    res = st.gesv_mixed(st.Matrix.from_numpy(a, nb),
                        st.Matrix.from_numpy(b, nb))
    assert bool(res.converged)
    report("ex06 gesv_mixed", float(np.linalg.norm(
        a @ res.X.to_numpy() - b) / np.linalg.norm(b)))

    Ainv = api.lu_inverse_using_factor_out_of_place(A)
    report("ex06 inverse", float(np.linalg.norm(
        Ainv.to_numpy() @ a - np.eye(n))), 1e-8)


if __name__ == "__main__":
    main()
