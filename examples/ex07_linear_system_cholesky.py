"""ex07: Cholesky linear systems (ref: ex07_linear_system_cholesky.cc) —
chol_solve, factor/solve split, inverse, condition estimate."""

import _common
from _common import report, rng

import jax
import numpy as np
import slate_tpu as st
from slate_tpu import api


def main():
    r = rng()
    grid = st.Grid(2, 2, devices=jax.devices()[:4])
    n, nb = 32, 8
    a = r.standard_normal((n, n))
    spd = a @ a.T + n * np.eye(n)
    b = r.standard_normal((n, 3))
    H = st.HermitianMatrix.from_numpy(spd, nb, grid=grid)
    B = st.Matrix.from_numpy(b, nb, nb, grid)

    X = api.chol_solve(H, B)
    report("ex07 chol_solve", float(np.linalg.norm(
        spd @ X.to_numpy() - b) / np.linalg.norm(b)))

    L = api.chol_factor(H)
    X2 = api.chol_solve_using_factor(L, B)
    report("ex07 factor+solve", float(np.linalg.norm(
        spd @ X2.to_numpy() - b) / np.linalg.norm(b)))

    Hinv = api.chol_inverse_using_factor(L)
    report("ex07 potri", float(np.linalg.norm(
        Hinv.to_numpy() @ spd - np.eye(n))), 1e-7)

    F = st.getrf(st.Matrix.from_numpy(spd, nb, nb, grid))
    rcond = float(st.gecondest(F, st.norm(st.Norm.One,
                                          st.Matrix.from_numpy(spd, nb))))
    true_rcond = 1.0 / np.linalg.cond(spd, 1)
    # 1-norm estimator is within a small factor of truth
    assert 0.05 * true_rcond < rcond <= 3 * true_rcond + 1e-30
    print(f"ex07 gecondest rcond {rcond:.3e} (true {true_rcond:.3e})  PASS")


if __name__ == "__main__":
    main()
