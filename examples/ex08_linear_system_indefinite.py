"""ex08: Hermitian-indefinite solve via Aasen's factorization
(ref: ex08_linear_system_indefinite.cc -> hesv)."""

import _common
from _common import report, rng

import jax
import numpy as np
import slate_tpu as st
from slate_tpu import api


def main():
    r = rng()
    n, nb = 32, 8
    a = r.standard_normal((n, n))
    sym = a + a.T                           # indefinite symmetric
    b = r.standard_normal((n, 2))
    H = st.HermitianMatrix.from_numpy(sym, nb)
    B = st.Matrix.from_numpy(b, nb)

    X = api.indefinite_solve(H, B)
    report("ex08 indefinite_solve", float(np.linalg.norm(
        sym @ X.to_numpy() - b) / np.linalg.norm(b)), 1e-8)

    F = api.indefinite_factor(H)
    X2 = api.indefinite_solve_using_factor(F, B)
    report("ex08 factor+solve", float(np.linalg.norm(
        sym @ X2.to_numpy() - b) / np.linalg.norm(b)), 1e-8)


if __name__ == "__main__":
    main()
