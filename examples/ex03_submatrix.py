"""ex03: submatrix and transpose views (ref: ex03_submatrix.cc).

sub() selects a tile-aligned block; transpose/conj_transpose are
metadata-only op flips, exactly the reference's view semantics."""

import _common
from _common import report, rng

import jax
import numpy as np
import slate_tpu as st


def main():
    r = rng()
    grid = st.Grid(2, 2, devices=jax.devices()[:4])
    m, n, nb = 32, 32, 8
    a = r.standard_normal((m, n))
    A = st.Matrix.from_numpy(a, nb, nb, grid)

    S = A.sub(1, 2, 0, 1)                  # tile rows 1:2, tile cols 0:1
    report("ex03 sub view", float(np.abs(
        S.to_numpy() - a[8:24, 0:16]).max()))

    T = A.transpose()
    report("ex03 transpose view", float(np.abs(T.to_numpy() - a.T).max()))

    # views compose with compute: gemm on a transposed view
    C = st.gemm(1.0, A.transpose(), A)
    report("ex03 gemm(A^T, A)", float(np.abs(C.to_numpy() - a.T @ a).max()),
           1e-9)


if __name__ == "__main__":
    main()
