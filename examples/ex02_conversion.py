"""ex02: conversions between matrix types (ref: ex02_conversion.cc).

Reinterpret a general matrix's triangle as Triangular/Symmetric/Hermitian
(metadata-only views), convert structured back to general, and do a
precision-converting copy."""

import _common
from _common import report, rng

import jax
import numpy as np
import slate_tpu as st


def main():
    r = rng()
    grid = st.Grid(2, 2, devices=jax.devices()[:4])
    n, nb = 24, 6
    a = r.standard_normal((n, n))
    A = st.Matrix.from_numpy(a, nb, nb, grid)

    L = A.triangular(st.Uplo.Lower)
    report("ex02 triangular view", float(np.abs(
        L.to_numpy() - np.tril(a)).max()))

    H = A.hermitian(st.Uplo.Lower)
    hd = np.tril(a) + np.tril(a, -1).T
    report("ex02 hermitian expand", float(np.abs(H.to_numpy() - hd).max()))

    G = H.general()                         # materialized general copy
    assert type(G) is st.Matrix
    report("ex02 general()", float(np.abs(G.to_numpy() - hd).max()))

    # precision-converting copy (ref: slate::copy f64 -> f32)
    B32 = st.Matrix.zeros(n, n, nb, nb, grid, np.float32)
    B32 = st.copy(A, B32)
    report("ex02 f64->f32 copy", float(np.abs(
        B32.to_numpy() - a.astype(np.float32)).max()), 1e-6)


if __name__ == "__main__":
    main()
