"""ex09: least squares (ref: ex09_least_squares.cc) — gels via QR and
CholQR, plus an explicit qr_factor / multiply_by_q."""

import _common
from _common import report, rng

import jax
import numpy as np
import slate_tpu as st
from slate_tpu import api


def main():
    r = rng()
    grid = st.Grid(2, 2, devices=jax.devices()[:4])
    m, n, nb = 48, 16, 8
    a = r.standard_normal((m, n))
    b = r.standard_normal((m, 2))
    A = st.Matrix.from_numpy(a, nb, nb, grid)
    B = st.Matrix.from_numpy(b, nb, nb, grid)
    x_ref = np.linalg.lstsq(a, b, rcond=None)[0]

    X = api.least_squares_solve(A, B)
    report("ex09 least_squares_solve", float(np.linalg.norm(
        X.to_numpy()[:n] - x_ref) / np.linalg.norm(x_ref)), 1e-8)

    opts = {st.Option.MethodGels: st.MethodGels.CholQR}
    X2 = st.gels(A, B, opts)
    report("ex09 gels CholQR", float(np.linalg.norm(
        X2.to_numpy()[:n] - x_ref) / np.linalg.norm(x_ref)), 1e-8)

    F = api.qr_factor(A)
    QtB = api.qr_multiply_by_q(st.Side.Left, "c", F, B)
    # R x = Q^H b gives the same LS solution
    Rd = np.triu(F.QR.to_numpy()[:n, :n])
    x3 = np.linalg.solve(Rd, QtB.to_numpy()[:n])
    report("ex09 qr_factor path", float(np.linalg.norm(
        x3 - x_ref) / np.linalg.norm(x_ref)), 1e-8)


if __name__ == "__main__":
    main()
