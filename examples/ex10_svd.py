"""ex10: singular value decomposition (ref: ex10_svd.cc)."""

import _common
from _common import report, rng

import jax
import numpy as np
import slate_tpu as st
from slate_tpu import api


def main():
    r = rng()
    m, n, nb = 40, 24, 8
    a = r.standard_normal((m, n))
    A = st.Matrix.from_numpy(a, nb)

    s = api.svd_vals(A)
    s_ref = np.linalg.svd(a, compute_uv=False)
    report("ex10 svd_vals", float(np.abs(np.asarray(s) - s_ref).max() /
                                  s_ref[0]))

    s2, U, V = api.svd(A)
    ud, vd = U.to_numpy(), V.to_numpy()
    recon = ud[:, :n] @ np.diag(np.asarray(s2)) @ vd[:, :n].T.conj()
    report("ex10 svd reconstruct", float(np.abs(recon - a).max() /
                                         s_ref[0]), 1e-9)


if __name__ == "__main__":
    main()
