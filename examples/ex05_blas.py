"""ex05: parallel BLAS-3 (ref: ex05_blas.cc:13-42 — gemm, hemm, herk,
trsm on distributed matrices), through the simplified API verbs."""

import _common
from _common import report, rng

import jax
import numpy as np
import slate_tpu as st
from slate_tpu import api


def main():
    r = rng()
    grid = st.Grid(2, 2, devices=jax.devices()[:4])
    n, nb = 32, 8
    a = r.standard_normal((n, n))
    b = r.standard_normal((n, n))
    A = st.Matrix.from_numpy(a, nb, nb, grid)
    B = st.Matrix.from_numpy(b, nb, nb, grid)

    C = api.multiply(1.0, A, B)                     # gemm
    report("ex05 multiply (gemm)", float(np.abs(C.to_numpy() - a @ b).max()),
           1e-9)

    H = st.HermitianMatrix.from_numpy(a, nb, grid=grid)
    hd = np.tril(a) + np.tril(a, -1).T
    C2 = api.multiply(1.0, H, B)                    # hemm dispatch
    report("ex05 multiply (hemm)", float(np.abs(C2.to_numpy() - hd @ b).max()),
           1e-9)

    Csym = st.HermitianMatrix.from_numpy(np.zeros((n, n)), nb, grid=grid)
    C3 = api.rank_k_update(1.0, A, 0.0, Csym)       # herk
    report("ex05 rank_k_update", float(np.abs(
        C3.to_numpy() - a @ a.T).max()), 1e-9)

    spd = a @ a.T + n * np.eye(n)
    L = np.linalg.cholesky(spd)
    Lt = st.TriangularMatrix.from_numpy(L, nb, uplo=st.Uplo.Lower, grid=grid)
    X = api.triangular_solve(1.0, Lt, B)            # trsm
    report("ex05 triangular_solve", float(np.abs(
        L @ X.to_numpy() - b).max()), 1e-9)


if __name__ == "__main__":
    main()
