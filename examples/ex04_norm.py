"""ex04: matrix norms across types (ref: ex04_norm.cc)."""

import _common
from _common import report, rng

import jax
import numpy as np
import slate_tpu as st


def main():
    r = rng()
    grid = st.Grid(2, 4, devices=jax.devices()[:8])
    m, n, nb = 36, 28, 8
    a = r.standard_normal((m, n))
    A = st.Matrix.from_numpy(a, nb, nb, grid)

    checks = [
        ("Max", st.Norm.Max, np.abs(a).max()),
        ("One", st.Norm.One, np.abs(a).sum(axis=0).max()),
        ("Inf", st.Norm.Inf, np.abs(a).sum(axis=1).max()),
        ("Fro", st.Norm.Fro, np.linalg.norm(a)),
    ]
    for name, nt, ref in checks:
        got = float(st.norm(nt, A))
        report(f"ex04 ge norm {name}", abs(got - ref) / ref)

    h = a[:28, :28]
    H = st.HermitianMatrix.from_numpy(h, nb, grid=grid)
    hd = np.tril(h) + np.tril(h, -1).T
    report("ex04 he norm One",
           abs(float(st.norm(st.Norm.One, H)) -
               np.abs(hd).sum(axis=0).max()) / np.abs(hd).sum())

    cn = st.col_norms(A)
    report("ex04 col_norms", float(np.abs(
        np.asarray(cn) - np.abs(a).max(axis=0)).max()))


if __name__ == "__main__":
    main()
