"""Shared setup for the examples: force an 8-device virtual CPU mesh so
every example exercises the distributed path on any machine (the analog of
the reference running examples under mpirun -np 4, ref:
examples/run_tests.py, docs/usage.md:32-42).

Virtual devices only exist if the flag lands before jax's backend
initializes, and site hooks may import/initialize jax before any example
code runs — so importing this module RE-EXECS the script in a child
process with a scrubbed environment (same recipe as __graft_entry__.py),
then the child imports jax normally.  Import _common FIRST in every
example."""

import os
import subprocess
import sys

_MARKER = "_SLATE_TPU_EXAMPLES_CHILD"

if os.environ.get(_MARKER) != "1":
    env = dict(os.environ)
    env[_MARKER] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    env["JAX_ENABLE_X64"] = "1"
    env.pop("PALLAS_AXON_POOL_IPS", None)   # site hook would re-add TPU
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if "host_platform_device_count" not in f]
    flags.append("--xla_force_host_platform_device_count=8")
    env["XLA_FLAGS"] = " ".join(flags)
    res = subprocess.run([sys.executable] + sys.argv, env=env)
    raise SystemExit(res.returncode)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402


def rng():
    return np.random.default_rng(1234)


def report(name: str, resid: float, tol: float = 1e-10):
    status = "PASS" if resid < tol else "FAIL"
    print(f"{name:<34s} resid {resid:9.2e}  {status}")
    if resid >= tol:
        raise SystemExit(f"{name} failed: {resid} >= {tol}")
