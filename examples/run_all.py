"""Run every example in-process (the smoke-test tier; ref:
examples/run_tests.py run in CI, .github/workflows/test.sh:46-61).

One jax runtime (8 virtual CPU devices) is shared across all examples, so
the whole sweep costs one backend init + per-example compiles."""

import importlib
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import _common  # noqa: F401  (forces CPU/8-device/x64 before jax inits)

EXAMPLES = [
    "ex01_matrix",
    "ex02_conversion",
    "ex03_submatrix",
    "ex04_norm",
    "ex05_blas",
    "ex06_linear_system_lu",
    "ex07_linear_system_cholesky",
    "ex08_linear_system_indefinite",
    "ex09_least_squares",
    "ex10_svd",
    "ex11_hermitian_eig",
    "ex12_generalized_hermitian_eig",
    "ex13_non_uniform_block_size",
    "ex14_scalapack_gemm",
]


def main():
    t0 = time.time()
    failed = []
    for name in EXAMPLES:
        t = time.time()
        try:
            importlib.import_module(name).main()
            print(f"== {name} ok ({time.time() - t:.1f}s)")
        except SystemExit as e:
            failed.append(name)
            print(f"== {name} FAILED: {e}")
        except Exception as e:  # noqa: BLE001
            failed.append(name)
            print(f"== {name} ERROR: {type(e).__name__}: {e}")
    print(f"\n{len(EXAMPLES) - len(failed)}/{len(EXAMPLES)} examples passed "
          f"in {time.time() - t0:.1f}s")
    if failed:
        raise SystemExit(f"failed: {', '.join(failed)}")


if __name__ == "__main__":
    main()
