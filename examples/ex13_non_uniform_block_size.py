"""ex13: ragged tile sizes (ref: ex13_non_uniform_block_size.cc).

The reference supports arbitrary per-tile sizes via tileMb/tileNb lambdas;
here tile sizes are uniform with a ragged LAST tile (the padding-discipline
design, core/storage.py) — this example proves computations are exact when
no dimension divides the tile size."""

import _common
from _common import report, rng

import jax
import numpy as np
import slate_tpu as st


def main():
    r = rng()
    grid = st.Grid(2, 2, devices=jax.devices()[:4])
    m, n, k, nb = 37, 29, 23, 8            # nothing divides 8
    a = r.standard_normal((m, k))
    b = r.standard_normal((k, n))
    A = st.Matrix.from_numpy(a, nb, nb, grid)
    B = st.Matrix.from_numpy(b, nb, nb, grid)
    C = st.gemm(1.0, A, B)
    report("ex13 ragged gemm", float(np.abs(C.to_numpy() - a @ b).max()),
           1e-10)

    sq = r.standard_normal((37, 37)) + 37 * np.eye(37)
    bb = r.standard_normal((37, 3))
    _, X = st.gesv(st.Matrix.from_numpy(sq, 7, 7, grid),
                   st.Matrix.from_numpy(bb, 7, 7, grid))
    report("ex13 ragged gesv", float(np.linalg.norm(
        sq @ X.to_numpy() - bb) / np.linalg.norm(bb)), 1e-10)


if __name__ == "__main__":
    main()
