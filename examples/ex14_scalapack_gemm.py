"""ex14: ScaLAPACK interop (ref: ex14_scalapack_gemm.cc — PDGEMM wrapper).

A legacy app hands over its per-process block-cyclic local arrays + array
descriptor; the framework assembles them, multiplies, and hands back
ScaLAPACK-layout results."""

import _common
from _common import report, rng

import jax
import numpy as np
import slate_tpu as st
from slate_tpu.compat import descinit, from_scalapack, numroc, to_scalapack


def main():
    r = rng()
    grid = st.Grid(2, 2, devices=jax.devices()[:4])
    m, n, k, mb, nb = 36, 28, 20, 8, 8
    a = r.standard_normal((m, k))
    b = r.standard_normal((k, n))

    # the "legacy app": chop a into ScaLAPACK local pieces by hand
    desc_a, locals_a = to_scalapack(st.Matrix.from_numpy(a, mb, nb, grid))
    desc_b, locals_b = to_scalapack(st.Matrix.from_numpy(b, mb, nb, grid))
    assert desc_a[2:6] == (m, k, mb, nb)
    ml = numroc(m, mb, 0, 0, grid.p)
    assert locals_a[(0, 0)].shape[0] == ml

    # import -> compute -> export
    A = from_scalapack(desc_a, locals_a, grid)
    B = from_scalapack(desc_b, locals_b, grid)
    report("ex14 from_scalapack", float(np.abs(A.to_numpy() - a).max()))
    C = st.gemm(1.0, A, B)
    desc_c, locals_c = to_scalapack(C)
    # reassemble what the legacy app would hold
    C2 = from_scalapack(desc_c, locals_c, grid)
    report("ex14 pdgemm round-trip", float(np.abs(
        C2.to_numpy() - a @ b).max()), 1e-10)

    d2 = descinit(m, n, mb, nb, grid)
    assert d2[8] == numroc(m, mb, 0, 0, grid.p)  # LLD = max local rows


if __name__ == "__main__":
    main()
