"""ex01: creating distributed matrices (ref: examples/ex01_matrix.cc).

Build matrices from host data onto a 2D process grid, inspect the
block-cyclic tile map, and round-trip back to host."""

import _common
from _common import report, rng

import jax
import numpy as np
import slate_tpu as st


def main():
    r = rng()
    grid = st.Grid(2, 4, devices=jax.devices()[:8])
    m, n, nb = 40, 28, 8
    a = r.standard_normal((m, n))

    A = st.Matrix.from_numpy(a, nb, nb, grid)
    assert (A.m, A.n) == (m, n)
    assert (A.mt, A.nt) == (5, 4)          # ceil(40/8), ceil(28/8)
    # distribution lambdas (ref: MatrixStorage tileRank/tileMb)
    assert A.storage.tile_mb(4) == 8 and A.storage.tile_nb(3) == 4
    assert A.storage.tile_rank(0, 0) == 0
    report("ex01 from_numpy round-trip", float(np.abs(A.to_numpy() - a).max()))

    Z = st.Matrix.zeros(16, 16, 4, 4, grid, a.dtype)
    assert np.all(Z.to_numpy() == 0)
    print(f"ex01 tile map: {A.storage}")


if __name__ == "__main__":
    main()
