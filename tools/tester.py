#!/usr/bin/env python
"""Parameter-sweep tester: the testsweeper/tester analog.

Mirrors the reference's integration tester (ref: test/test.cc:43-80 routine
sections, test/test_gemm.cc:50-270 params + residual checks, test/run_tests.py
sweep driver): sweeps {routine, n, nb, grid, dtype, method} combinations,
checks residuals against numpy/scipy identities, and prints a
gflops/time/error table with pass/fail per line.

Usage:
  python tools/tester.py gemm posv gesv --dims 64,128 --nb 16 \
      --grids 1x1,2x2 --type d
  python tools/tester.py all --quick
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import jax  # noqa: E402

if os.environ.get("SLATE_TESTER_BACKEND", "cpu") == "cpu":
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402

import slate_tpu as st  # noqa: E402
from slate_tpu.util.generator import (  # noqa: E402
    generate_hermitian, generate_matrix)

DTYPES = {"s": np.float32, "d": np.float64,
          "c": np.complex64, "z": np.complex128}
_TCODE = {np.float32: "s", np.float64: "d",
          np.complex64: "c", np.complex128: "z"}


def _grid(spec: str) -> st.Grid | None:
    p, q = (int(x) for x in spec.split("x"))
    if p * q == 1:
        return None
    return st.Grid(p, q, devices=jax.devices()[: p * q])


def _gflop(routine, n, nb=16):
    kd = max(2, nb // 2)                     # run_pbsv's bandwidth choice
    return {"gemm": 2 * n ** 3, "posv": n ** 3 / 3 + 2 * n ** 2,
            "gesv": 2 * n ** 3 / 3 + 2 * n ** 2,
            "gesv_tntpiv": 2 * n ** 3 / 3 + 2 * n ** 2,
            "hesv": n ** 3 / 3 + 2 * n ** 2,
            "trsm": 2 * n ** 2 * 6, "herk": n ** 2 * (n // 2 + 1),
            "pbsv": n * kd * (kd + 2) + 4 * n * kd * 4,
            "getri": 2 * n ** 3,
            "norm": n ** 2, "geqrf": 10 * n ** 3 / 3,  # runner is 2n x n
            "gels": 4 * n ** 3 / 3,
            "heev": 4 * n ** 3 / 3, "svd": 4 * n ** 3 / 3}.get(routine,
                                                               n ** 3) / 1e9


# ---- per-routine runners: return (error, ok) ----

def run_gemm(n, nb, grid, dtype):
    A = generate_matrix("randn", n, n, nb, seed=1, dtype=dtype, grid=grid)
    B = generate_matrix("randn", n, n, nb, seed=2, dtype=dtype, grid=grid)
    C = st.gemm(1.0, A, B)
    ref = A.to_numpy() @ B.to_numpy()
    err = np.linalg.norm(C.to_numpy() - ref) / (np.linalg.norm(ref) + 1)
    return err, err < 1e-5 if dtype in (np.float32, np.complex64) \
        else err < 1e-13


def run_posv(n, nb, grid, dtype):
    A = generate_hermitian("poev", n, nb, seed=1, dtype=dtype, cond=100.0,
                           grid=grid)
    B = generate_matrix("randn", n, 8, nb, seed=2, dtype=dtype, grid=grid)
    _, X = st.posv(A, B)
    a, b, x = A.to_numpy(), B.to_numpy(), X.to_numpy()
    err = np.linalg.norm(a @ x - b) / (np.linalg.norm(a) *
                                       np.linalg.norm(x) * n)
    return err, err < (1e-4 if dtype in (np.float32, np.complex64) else 1e-14)


def run_gesv(n, nb, grid, dtype):
    A = generate_matrix("rand_dominant", n, n, nb, seed=1, dtype=dtype,
                        grid=grid)
    B = generate_matrix("randn", n, 8, nb, seed=2, dtype=dtype, grid=grid)
    _, X = st.gesv(A, B)
    a, b, x = A.to_numpy(), B.to_numpy(), X.to_numpy()
    err = np.linalg.norm(a @ x - b) / (np.linalg.norm(a) *
                                       np.linalg.norm(x) * n)
    return err, err < (1e-4 if dtype in (np.float32, np.complex64) else 1e-14)


def run_norm(n, nb, grid, dtype):
    A = generate_matrix("randn", n, n, nb, seed=1, dtype=dtype, grid=grid)
    err = abs(float(st.norm(st.Norm.One, A)) -
              np.abs(A.to_numpy()).sum(axis=0).max())
    return err, err < 1e-8


def _f64(dtype):
    return dtype in (np.float64, np.complex128)


def run_gesv_tntpiv(n, nb, grid, dtype):
    A = generate_matrix("rand_dominant", n, n, nb, seed=1, dtype=dtype,
                        grid=grid)
    B = generate_matrix("randn", n, 8, nb, seed=2, dtype=dtype, grid=grid)
    _, X = st.gesv(A, B, {st.Option.MethodLU: st.MethodLU.CALU})
    a, b, x = A.to_numpy(), B.to_numpy(), X.to_numpy()
    err = np.linalg.norm(a @ x - b) / (np.linalg.norm(a) *
                                       np.linalg.norm(x) * n)
    return err, err < (1e-14 if _f64(dtype) else 1e-4)


def run_hesv(n, nb, grid, dtype):
    A = generate_hermitian("heev", n, nb, seed=1, dtype=dtype, cond=50.0,
                           grid=grid)
    B = generate_matrix("randn", n, 4, nb, seed=2, dtype=dtype, grid=grid)
    _, X = st.hesv(A, B)
    a, b, x = A.to_numpy(), B.to_numpy(), X.to_numpy()
    err = np.linalg.norm(a @ x - b) / (np.linalg.norm(a) *
                                       np.linalg.norm(x) * n)
    return err, err < (1e-11 if _f64(dtype) else 1e-3)


def run_trsm(n, nb, grid, dtype):
    A = generate_matrix("randn", n, n, nb, seed=1, dtype=dtype, grid=grid)
    T = st.Matrix.from_numpy(
        np.tril(A.to_numpy()) + n * np.eye(n, dtype=dtype), nb, nb,
        grid).triangular(st.Uplo.Lower)
    B = generate_matrix("randn", n, 6, nb, seed=2, dtype=dtype, grid=grid)
    X = st.trsm("l", 1.0, T, B)
    t, b, x = T.to_numpy(), B.to_numpy(), X.to_numpy()
    err = np.linalg.norm(t @ x - b) / (np.linalg.norm(t) *
                                       np.linalg.norm(x) + 1)
    return err, err < (1e-14 if _f64(dtype) else 1e-5)


def run_herk(n, nb, grid, dtype):
    A = generate_matrix("randn", n, n // 2 + 1, nb, seed=1, dtype=dtype,
                        grid=grid)
    C0 = generate_hermitian("poev", n, nb, seed=2, dtype=dtype, cond=10.0,
                            grid=grid)
    C = st.herk(1.0, A, 0.5, C0)
    a, c0 = A.to_numpy(), C0.to_numpy()
    ref = a @ a.conj().T + 0.5 * c0
    err = np.linalg.norm(C.general().to_numpy() - ref) / (
        np.linalg.norm(ref) + 1)
    return err, err < (1e-13 if _f64(dtype) else 1e-5)


def run_geqrf(n, nb, grid, dtype):
    m = 2 * n
    A = generate_matrix("randn", m, n, nb, seed=1, dtype=dtype, grid=grid)
    F = st.geqrf(A)
    Q = st.qr_multiply(F).to_numpy()
    R = np.triu(F.QR.to_numpy()[:n, :n])
    a = A.to_numpy()
    err = np.linalg.norm(Q @ R - a) / (np.linalg.norm(a) + 1)
    orth = np.linalg.norm(Q.conj().T @ Q - np.eye(n))
    err = max(err, orth / n)
    return err, err < (1e-13 if _f64(dtype) else 1e-5)


def run_pbsv(n, nb, grid, dtype):
    if grid is not None:
        return None                          # packed band is single-device
    kd = max(2, nb // 2)
    rng = np.random.default_rng(3)
    a = np.zeros((n, n), dtype)
    for d in range(kd + 1):
        v = rng.standard_normal(n - d).astype(dtype) * 0.1
        a += np.diag(v, -d)
    a = a + a.conj().T + (2 * kd + 4) * np.eye(n, dtype=dtype)
    A = st.HermitianBandMatrix.from_numpy(a, kd, nb)
    b = rng.standard_normal((n, 4)).astype(dtype)
    B = st.Matrix.from_numpy(b, nb, nb)
    _, X = st.pbsv(A, B)
    x = X.to_numpy()
    err = np.linalg.norm(a @ x - b) / (np.linalg.norm(a) *
                                       np.linalg.norm(x) * n)
    return err, err < (1e-14 if _f64(dtype) else 1e-5)


def run_getri(n, nb, grid, dtype):
    A = generate_matrix("rand_dominant", n, n, nb, seed=1, dtype=dtype,
                        grid=grid)
    X = st.getriOOP(A)
    a, x = A.to_numpy(), X.to_numpy()
    err = np.linalg.norm(a @ x - np.eye(n)) / n
    return err, err < (1e-12 if _f64(dtype) else 1e-4)


RUNNERS = {"gemm": run_gemm, "posv": run_posv, "gesv": run_gesv,
           "gesv_tntpiv": run_gesv_tntpiv, "hesv": run_hesv,
           "trsm": run_trsm, "herk": run_herk, "geqrf": run_geqrf,
           "pbsv": run_pbsv, "getri": run_getri, "norm": run_norm}


# ---- scipy reference-library cross-checks (the testsweeper --ref mode:
# compare RESULTS against the reference library, not just residual
# identities; ref: test/run_tests.py --ref) ----

def ref_gesv(n, nb, grid, dtype):
    import scipy.linalg
    A = generate_matrix("rand_dominant", n, n, nb, seed=1, dtype=dtype,
                        grid=grid)
    B = generate_matrix("randn", n, 8, nb, seed=2, dtype=dtype, grid=grid)
    _, X = st.gesv(A, B)
    xr = scipy.linalg.solve(A.to_numpy(), B.to_numpy())
    err = np.linalg.norm(X.to_numpy() - xr) / (np.linalg.norm(xr) + 1)
    return err, err < (1e-11 if _f64(dtype) else 1e-3)


def ref_heev(n, nb, grid, dtype):
    import scipy.linalg
    A = generate_hermitian("heev", n, nb, seed=1, dtype=dtype, cond=100.0,
                           grid=grid)
    lam, _ = st.heev(A)
    wr = scipy.linalg.eigh(A.to_numpy(), eigvals_only=True)
    err = np.max(np.abs(np.sort(np.asarray(lam)) - wr)) / (
        np.abs(wr).max() + 1e-300)
    return err, err < (1e-11 if _f64(dtype) else 1e-4)


def ref_svd(n, nb, grid, dtype):
    import scipy.linalg
    A = generate_matrix("svd", n, n, nb, seed=1, dtype=dtype, cond=100.0,
                        grid=grid)
    s = st.svd_vals(A)
    sr = scipy.linalg.svdvals(A.to_numpy())
    err = np.max(np.abs(np.sort(np.asarray(s))[::-1] - sr)) / (
        sr.max() + 1e-300)
    return err, err < (1e-11 if _f64(dtype) else 1e-4)


def ref_gels(n, nb, grid, dtype):
    import scipy.linalg
    m = 2 * n
    A = generate_matrix("randn", m, n, nb, seed=1, dtype=dtype, grid=grid)
    B = generate_matrix("randn", m, 4, nb, seed=2, dtype=dtype, grid=grid)
    X = st.gels(A, B)
    xr = scipy.linalg.lstsq(A.to_numpy(), B.to_numpy())[0]
    err = np.linalg.norm(X.to_numpy()[:n] - xr) / (np.linalg.norm(xr) + 1)
    return err, err < (1e-9 if _f64(dtype) else 1e-3)


REF_RUNNERS = {"gesv": ref_gesv, "heev": ref_heev, "svd": ref_svd,
               "gels": ref_gels}


def _late_runners():
    """Routines registered once the corresponding drivers exist."""
    extra = {}
    if hasattr(st, "gels"):
        def run_gels(n, nb, grid, dtype):
            m = 2 * n
            A = generate_matrix("randn", m, n, nb, seed=1, dtype=dtype,
                                grid=grid)
            B = generate_matrix("randn", m, 4, nb, seed=2, dtype=dtype,
                                grid=grid)
            X = st.gels(A, B)
            a, b, x = A.to_numpy(), B.to_numpy(), X.to_numpy()[:n]
            # normal-equations residual: A^H (A x - b) ~ 0
            err = np.linalg.norm(a.conj().T @ (a @ x - b)) / (
                np.linalg.norm(a) ** 2 * np.linalg.norm(x) + 1e-300)
            return err, err < (1e-4 if dtype in (np.float32, np.complex64)
                               else 1e-12)
        extra["gels"] = run_gels
    if hasattr(st, "heev"):
        def run_heev(n, nb, grid, dtype):
            A = generate_hermitian("heev", n, nb, seed=1, dtype=dtype,
                                   cond=100.0, grid=grid)
            lam, Z = st.heev(A)
            a = A.to_numpy()
            lam_np = np.linalg.eigvalsh(a)
            err = np.max(np.abs(np.sort(np.asarray(lam)) - lam_np)) / (
                np.abs(lam_np).max() + 1e-300)
            return err, err < 1e-10
        extra["heev"] = run_heev
    if hasattr(st, "svd"):
        def run_svd(n, nb, grid, dtype):
            A = generate_matrix("svd", n, n, nb, seed=1, dtype=dtype,
                                cond=100.0, grid=grid)
            s = st.svd_vals(A)
            s_np = np.linalg.svd(A.to_numpy(), compute_uv=False)
            err = np.max(np.abs(np.sort(np.asarray(s))[::-1] - s_np)) / (
                s_np.max() + 1e-300)
            return err, err < 1e-10
        extra["svd"] = run_svd
    return extra


def main(argv=None):
    # @file arguments are testsweeper-style per-routine parameter files
    # (one flag/argument per line; see tools/params/*.txt)
    ap = argparse.ArgumentParser(fromfile_prefix_chars="@")
    ap.add_argument("routines", nargs="+")
    ap.add_argument("--dims", default="64,128")
    ap.add_argument("--nb", default="16")
    ap.add_argument("--grids", default="1x1,2x2")
    ap.add_argument("--type", default="d", help="s,d,c,z")
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--ref", action="store_true",
                    help="cross-check RESULTS against scipy (the "
                         "reference-library comparison mode) where a "
                         "ref runner exists")
    args = ap.parse_args(argv)

    RUNNERS.update(_late_runners())
    if args.ref:
        for name, fn in REF_RUNNERS.items():
            RUNNERS[name] = fn
    routines = list(RUNNERS) if args.routines == ["all"] else args.routines
    dims = [int(x) for x in args.dims.split(",")]
    nbs = [int(x) for x in args.nb.split(",")]
    grids = args.grids.split(",")
    dtypes = [DTYPES[t] for t in args.type.split(",")]
    if args.quick:
        dims, nbs, grids = dims[:1], nbs[:1], grids[:2]

    hdr = (f"{'routine':8} {'type':4} {'n':>6} {'nb':>4} {'grid':>5} "
           f"{'time(s)':>9} {'gflops':>9} {'error':>10}  status")
    print(hdr)
    print("-" * len(hdr))
    failures = 0
    for routine in routines:
        fn = RUNNERS[routine]
        for dtype in dtypes:
            for n in dims:
                for nb in nbs:
                    for gspec in grids:
                        grid = _grid(gspec)
                        t0 = time.perf_counter()
                        try:
                            res = fn(n, nb, grid, dtype)
                        except Exception as e:  # noqa: BLE001
                            print(f"{routine:8} {_TCODE[dtype]:4} "
                                  f"{n:6} {nb:4} {gspec:>5} "
                                  f"{'-':>9} {'-':>9} {'-':>10}  "
                                  f"ERROR {type(e).__name__}: {e}")
                            failures += 1
                            continue
                        if res is None:      # config not applicable
                            print(f"{routine:8} {_TCODE[dtype]:4} {n:6} "
                                  f"{nb:4} {gspec:>5} {'-':>9} {'-':>9} "
                                  f"{'-':>10}  skip")
                            continue
                        err, ok = res
                        dt = time.perf_counter() - t0
                        gf = _gflop(routine, n, nb) / dt
                        status = "pass" if ok else "FAILED"
                        failures += 0 if ok else 1
                        print(f"{routine:8} {_TCODE[dtype]:4} {n:6} "
                              f"{nb:4} {gspec:>5} {dt:9.3f} {gf:9.2f} "
                              f"{err:10.2e}  {status}")
    print(f"\n{failures} failure(s)")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
