#!/usr/bin/env python
"""Parameter-sweep tester: the testsweeper/tester analog.

Mirrors the reference's integration tester (ref: test/test.cc:43-80 routine
sections, test/test_gemm.cc:50-270 params + residual checks, test/run_tests.py
sweep driver): sweeps {routine, n, nb, grid, dtype, method} combinations,
checks residuals against numpy/scipy identities, and prints a
gflops/time/error table with pass/fail per line.

Usage:
  python tools/tester.py gemm posv gesv --dims 64,128 --nb 16 \
      --grids 1x1,2x2 --type d
  python tools/tester.py all --quick
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import jax  # noqa: E402

if os.environ.get("SLATE_TESTER_BACKEND", "cpu") == "cpu":
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402

import slate_tpu as st  # noqa: E402
from slate_tpu.util.generator import (  # noqa: E402
    generate_hermitian, generate_matrix)

DTYPES = {"s": np.float32, "d": np.float64,
          "c": np.complex64, "z": np.complex128}
_TCODE = {np.float32: "s", np.float64: "d",
          np.complex64: "c", np.complex128: "z"}


def _grid(spec: str) -> st.Grid | None:
    p, q = (int(x) for x in spec.split("x"))
    if p * q == 1:
        return None
    return st.Grid(p, q, devices=jax.devices()[: p * q])


def _gflop(routine, n):
    return {"gemm": 2 * n ** 3, "posv": n ** 3 / 3 + 2 * n ** 2,
            "gesv": 2 * n ** 3 / 3 + 2 * n ** 2,
            "norm": n ** 2, "geqrf": 4 * n ** 3 / 3,
            "gels": 4 * n ** 3 / 3,
            "heev": 4 * n ** 3 / 3, "svd": 4 * n ** 3 / 3}.get(routine,
                                                               n ** 3) / 1e9


# ---- per-routine runners: return (error, ok) ----

def run_gemm(n, nb, grid, dtype):
    A = generate_matrix("randn", n, n, nb, seed=1, dtype=dtype, grid=grid)
    B = generate_matrix("randn", n, n, nb, seed=2, dtype=dtype, grid=grid)
    C = st.gemm(1.0, A, B)
    ref = A.to_numpy() @ B.to_numpy()
    err = np.linalg.norm(C.to_numpy() - ref) / (np.linalg.norm(ref) + 1)
    return err, err < 1e-5 if dtype in (np.float32, np.complex64) \
        else err < 1e-13


def run_posv(n, nb, grid, dtype):
    A = generate_hermitian("poev", n, nb, seed=1, dtype=dtype, cond=100.0,
                           grid=grid)
    B = generate_matrix("randn", n, 8, nb, seed=2, dtype=dtype, grid=grid)
    _, X = st.posv(A, B)
    a, b, x = A.to_numpy(), B.to_numpy(), X.to_numpy()
    err = np.linalg.norm(a @ x - b) / (np.linalg.norm(a) *
                                       np.linalg.norm(x) * n)
    return err, err < (1e-4 if dtype in (np.float32, np.complex64) else 1e-14)


def run_gesv(n, nb, grid, dtype):
    A = generate_matrix("rand_dominant", n, n, nb, seed=1, dtype=dtype,
                        grid=grid)
    B = generate_matrix("randn", n, 8, nb, seed=2, dtype=dtype, grid=grid)
    _, X = st.gesv(A, B)
    a, b, x = A.to_numpy(), B.to_numpy(), X.to_numpy()
    err = np.linalg.norm(a @ x - b) / (np.linalg.norm(a) *
                                       np.linalg.norm(x) * n)
    return err, err < (1e-4 if dtype in (np.float32, np.complex64) else 1e-14)


def run_norm(n, nb, grid, dtype):
    A = generate_matrix("randn", n, n, nb, seed=1, dtype=dtype, grid=grid)
    err = abs(float(st.norm(st.Norm.One, A)) -
              np.abs(A.to_numpy()).sum(axis=0).max())
    return err, err < 1e-8


RUNNERS = {"gemm": run_gemm, "posv": run_posv, "gesv": run_gesv,
           "norm": run_norm}


def _late_runners():
    """Routines registered once the corresponding drivers exist."""
    extra = {}
    if hasattr(st, "gels"):
        def run_gels(n, nb, grid, dtype):
            m = 2 * n
            A = generate_matrix("randn", m, n, nb, seed=1, dtype=dtype,
                                grid=grid)
            B = generate_matrix("randn", m, 4, nb, seed=2, dtype=dtype,
                                grid=grid)
            X = st.gels(A, B)
            a, b, x = A.to_numpy(), B.to_numpy(), X.to_numpy()[:n]
            # normal-equations residual: A^H (A x - b) ~ 0
            err = np.linalg.norm(a.conj().T @ (a @ x - b)) / (
                np.linalg.norm(a) ** 2 * np.linalg.norm(x) + 1e-300)
            return err, err < (1e-4 if dtype in (np.float32, np.complex64)
                               else 1e-12)
        extra["gels"] = run_gels
    if hasattr(st, "heev"):
        def run_heev(n, nb, grid, dtype):
            A = generate_hermitian("heev", n, nb, seed=1, dtype=dtype,
                                   cond=100.0, grid=grid)
            lam, Z = st.heev(A)
            a = A.to_numpy()
            lam_np = np.linalg.eigvalsh(a)
            err = np.max(np.abs(np.sort(np.asarray(lam)) - lam_np)) / (
                np.abs(lam_np).max() + 1e-300)
            return err, err < 1e-10
        extra["heev"] = run_heev
    if hasattr(st, "svd"):
        def run_svd(n, nb, grid, dtype):
            A = generate_matrix("svd", n, n, nb, seed=1, dtype=dtype,
                                cond=100.0, grid=grid)
            s = st.svd_vals(A)
            s_np = np.linalg.svd(A.to_numpy(), compute_uv=False)
            err = np.max(np.abs(np.sort(np.asarray(s))[::-1] - s_np)) / (
                s_np.max() + 1e-300)
            return err, err < 1e-10
        extra["svd"] = run_svd
    return extra


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("routines", nargs="+")
    ap.add_argument("--dims", default="64,128")
    ap.add_argument("--nb", default="16")
    ap.add_argument("--grids", default="1x1,2x2")
    ap.add_argument("--type", default="d", help="s,d,c,z")
    ap.add_argument("--quick", action="store_true")
    args = ap.parse_args(argv)

    RUNNERS.update(_late_runners())
    routines = list(RUNNERS) if args.routines == ["all"] else args.routines
    dims = [int(x) for x in args.dims.split(",")]
    nbs = [int(x) for x in args.nb.split(",")]
    grids = args.grids.split(",")
    dtypes = [DTYPES[t] for t in args.type.split(",")]
    if args.quick:
        dims, nbs, grids = dims[:1], nbs[:1], grids[:2]

    hdr = (f"{'routine':8} {'type':4} {'n':>6} {'nb':>4} {'grid':>5} "
           f"{'time(s)':>9} {'gflops':>9} {'error':>10}  status")
    print(hdr)
    print("-" * len(hdr))
    failures = 0
    for routine in routines:
        fn = RUNNERS[routine]
        for dtype in dtypes:
            for n in dims:
                for nb in nbs:
                    for gspec in grids:
                        grid = _grid(gspec)
                        t0 = time.perf_counter()
                        try:
                            err, ok = fn(n, nb, grid, dtype)
                        except Exception as e:  # noqa: BLE001
                            print(f"{routine:8} {_TCODE[dtype]:4} "
                                  f"{n:6} {nb:4} {gspec:>5} "
                                  f"{'-':>9} {'-':>9} {'-':>10}  "
                                  f"ERROR {type(e).__name__}: {e}")
                            failures += 1
                            continue
                        dt = time.perf_counter() - t0
                        gf = _gflop(routine, n) / dt
                        status = "pass" if ok else "FAILED"
                        failures += 0 if ok else 1
                        print(f"{routine:8} {_TCODE[dtype]:4} {n:6} "
                              f"{nb:4} {gspec:>5} {dt:9.3f} {gf:9.2f} "
                              f"{err:10.2e}  {status}")
    print(f"\n{failures} failure(s)")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
