"""Call-graph / reachability pass: which functions are TRACED.

A function is traced when jax traces it rather than running it eagerly:

- **direct entries** — decorated with ``@jax.jit`` / ``@jit`` /
  ``@partial(jit, ...)``, or passed as the callable to ``jax.jit(f)``,
  ``jax.shard_map(f, ...)``, ``shard_map_unchecked(f, ...)`` (the compat
  shim in ``util/compat_jax.py``), ``pl.pallas_call(kernel, ...)`` or
  ``pl.pallas_call(partial(kernel, bw=bw), ...)`` (partial keywords are
  static parameters of the kernel entry; when the call carries an inline
  ``grid_spec=pltpu.PrefetchScalarGridSpec(num_scalar_prefetch=N, ...)``
  the kernel's first N parameters are the scalar-prefetch operand refs —
  grid-shaping data the BlockSpec index maps consume, recorded static),
  or ``jax.vmap(f)`` — a vmapped
  function runs under a batching trace, so everything it reaches is
  traced exactly as under jit (the serving layer's batched cores enter
  drivers this way);
- **transitively traced** — reachable from a traced function through the
  lexically-resolvable call graph: direct calls, bare function references
  (e.g. a body handed to ``lax.fori_loop`` / ``lax.scan``), and nested
  ``def``\\ s of traced functions.

Resolution is lexical and best-effort: a ``Name`` resolves through the
enclosing-function chain, then module-level ``def``\\ s, then the module's
import map (``from ..internal import gemm`` makes ``gemm.fn`` resolvable).
Two formerly-documented false-negative edges are now resolved through the
call-graph layer (callgraph.py): re-exports (``serve.solve_core`` where
``serve/__init__.py`` imports it from ``batched``) follow import maps
recursively, and module-level dict-dispatch tables (``serve.CORES``)
contribute every table value as a possible callee — both at direct call
sites (``CORES[op](...)``) and through local aliases
(``core = CORES[op]; core(...)``, including traced-lambda closures).
Remaining false-negative edges (``getattr``, tables built at runtime)
are documented in docs/STATIC_ANALYSIS.md.

Entries created with ``jax.jit(lambda ...: f(...))`` contribute their
lambda body's resolvable callees as traced roots (the lambda itself is
not modelled as a function).
"""

from __future__ import annotations

import ast
from . import callgraph as _cg
from .loader import Project, SourceModule

#: wrappers whose first callable argument becomes a traced entry
ENTRY_WRAPPERS = {"jit", "shard_map", "shard_map_unchecked", "pallas_call",
                  "vmap"}
#: jit-like wrappers that honour static_argnames
JIT_LIKE = {"jit"}


class FuncInfo:
    """One ``def`` in the project, with resolution results."""

    def __init__(self, key: str, node: ast.FunctionDef,
                 module: SourceModule, parent: "FuncInfo | None"):
        self.key = key              # "<rel>::<dotted nesting path>"
        self.node = node
        self.module = module
        self.parent = parent
        self.children: dict[str, "FuncInfo"] = {}
        self.is_entry = False
        self.static_params: set[str] = set()
        self.resolved_calls: set[str] = set()   # keys of called functions
        self.resolved_refs: set[str] = set()    # keys of referenced functions

    @property
    def name(self) -> str:
        return self.node.name

    @property
    def qual(self) -> str:
        return self.key.split("::", 1)[1]

    def params(self) -> list[ast.arg]:
        a = self.node.args
        return [*a.posonlyargs, *a.args, *a.kwonlyargs]


def _nested_defs(fn_node: ast.AST):
    """Yield the defs whose NEAREST enclosing def is ``fn_node`` (deeper
    nesting is indexed recursively under its own parent)."""
    stack = list(ast.iter_child_nodes(fn_node))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node
            continue
        stack.extend(ast.iter_child_nodes(node))


def own_nodes(fn_node: ast.AST):
    """Walk a function body without descending into nested ``def``\\ s
    (those are separate FuncInfos); lambda bodies ARE included."""
    stack = list(ast.iter_child_nodes(fn_node))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _import_map(mod: SourceModule) -> dict[str, str]:
    """Local name -> dotted target for module-level imports."""
    parts = mod.dotted.split(".")
    is_pkg = mod.rel.endswith("__init__.py")
    pkg = parts if is_pkg else parts[:-1]
    out: dict[str, str] = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                out[alias.asname or alias.name.split(".")[0]] = (
                    alias.name if alias.asname else alias.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom):
            if node.level:
                base = pkg[: len(pkg) - (node.level - 1)]
                prefix = ".".join(base + (node.module.split(".")
                                          if node.module else []))
            else:
                prefix = node.module or ""
            for alias in node.names:
                if alias.name == "*":
                    continue
                out[alias.asname or alias.name] = (
                    f"{prefix}.{alias.name}" if prefix else alias.name)
    return out


class Reachability:
    def __init__(self, project: Project):
        self.project = project
        self.functions: dict[str, FuncInfo] = {}
        self.module_funcs: dict[str, dict[str, str]] = {}  # rel -> name->key
        self.imports: dict[str, dict[str, str]] = {}       # rel -> name->dotted
        self.entries: set[str] = set()
        self.entry_kinds: dict[str, set[str]] = {}  # key -> wrapper names
        self.traced: set[str] = set()
        self._alias_memo: dict[str, dict[str, tuple[str, ...]]] = {}
        self._index()
        # rel -> {NAME: (fn keys)} module-level dict-dispatch tables; needs
        # the function index, feeds call-site resolution below
        self.dispatch_tables = _cg.collect_dispatch_tables(self)
        self._resolve_and_find_entries()
        self._closure()

    # ---- indexing -----------------------------------------------------

    def _index(self):
        for rel, mod in self.project.modules.items():
            self.imports[rel] = _import_map(mod)
            table: dict[str, str] = {}

            def add(node, parent: FuncInfo | None, prefix: str):
                qual = f"{prefix}{node.name}" if prefix else node.name
                info = FuncInfo(f"{rel}::{qual}", node, mod, parent)
                self.functions[info.key] = info
                if parent is None:
                    table[node.name] = info.key
                else:
                    parent.children[node.name] = info
                for child in _nested_defs(node):
                    add(child, info, f"{qual}.")
                return info

            for node in mod.tree.body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    add(node, None, "")
            self.module_funcs[rel] = table

    # ---- name resolution ---------------------------------------------

    def resolve_name(self, name: str, scope: FuncInfo | None,
                     rel: str) -> str | None:
        """Resolve a bare name at a scope to a function key."""
        fn = scope
        while fn is not None:
            if name in fn.children:
                return fn.children[name].key
            fn = fn.parent
        if name in self.module_funcs.get(rel, ()):
            return self.module_funcs[rel][name]
        dotted = self.imports.get(rel, {}).get(name)
        if dotted:
            return self._resolve_dotted(dotted)
        return None

    def resolve_attr(self, base: str, attr: str, rel: str) -> str | None:
        """Resolve ``base.attr`` where base is an imported module alias."""
        dotted = self.imports.get(rel, {}).get(base)
        if dotted:
            return self._resolve_dotted(f"{dotted}.{attr}")
        return None

    def _resolve_dotted(self, dotted: str,
                        _seen: set[str] | None = None) -> str | None:
        """``pkg.mod.fn`` -> key, when pkg.mod is a project module.

        When the named module does not DEFINE the function, its import
        map is followed recursively: ``serve.solve_core`` resolves even
        though ``serve/__init__.py`` only re-exports it from
        ``serve.batched`` (the re-export edge callgraph.py documents).
        Cycle-guarded; intermediate-module aliasing chains resolve too."""
        if dotted in self.project.by_dotted:  # a module, not a function
            return None
        mod_name, _, fn_name = dotted.rpartition(".")
        mod = self.project.by_dotted.get(mod_name)
        if mod is None:
            return None
        key = self.module_funcs.get(mod.rel, {}).get(fn_name)
        if key is not None:
            return key
        fwd = self.imports.get(mod.rel, {}).get(fn_name)
        if fwd and fwd != dotted:
            seen = _seen if _seen is not None else set()
            if dotted not in seen:
                seen.add(dotted)
                return self._resolve_dotted(fwd, seen)
        return None

    def resolve_call_target(self, call: ast.Call, scope: FuncInfo | None,
                            rel: str) -> str | None:
        f = call.func
        if isinstance(f, ast.Name):
            return self.resolve_name(f.id, scope, rel)
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
            return self.resolve_attr(f.value.id, f.attr, rel)
        return None

    # ---- dict-dispatch resolution ------------------------------------

    def dispatch_table(self, expr: ast.AST, scope: FuncInfo | None,
                       rel: str) -> tuple[str, ...] | None:
        """Function keys of the dispatch table ``expr`` names, if any:
        a module-level table in this module, ``mod.TABLE`` through the
        import map, or a re-exported table through ``__init__``."""
        if isinstance(expr, ast.Name):
            tab = self.dispatch_tables.get(rel, {}).get(expr.id)
            if tab:
                return tab
            dotted = self.imports.get(rel, {}).get(expr.id)
            if dotted:
                return self._dotted_table(dotted)
        if isinstance(expr, ast.Attribute) and isinstance(expr.value,
                                                          ast.Name):
            dotted = self.imports.get(rel, {}).get(expr.value.id)
            if dotted:
                return self._dotted_table(f"{dotted}.{expr.attr}")
        return None

    def _dotted_table(self, dotted: str,
                      _seen: set[str] | None = None
                      ) -> tuple[str, ...] | None:
        mod_name, _, name = dotted.rpartition(".")
        mod = self.project.by_dotted.get(mod_name)
        if mod is None:
            return None
        tab = self.dispatch_tables.get(mod.rel, {}).get(name)
        if tab:
            return tab
        fwd = self.imports.get(mod.rel, {}).get(name)
        if fwd and fwd != dotted:
            seen = _seen if _seen is not None else set()
            if dotted not in seen:
                seen.add(dotted)
                return self._dotted_table(fwd, seen)
        return None

    def _dispatch_aliases(self, scope: FuncInfo | None
                          ) -> dict[str, tuple[str, ...]]:
        """Local name -> table keys for ``core = CORES[op]``-style
        assignments in the enclosing-function chain (memoized)."""
        if scope is None:
            return {}
        cached = self._alias_memo.get(scope.key)
        if cached is None:
            cached = dict(self._dispatch_aliases(scope.parent))
            rel = scope.module.rel
            for node in own_nodes(scope.node):
                if isinstance(node, ast.Assign) and \
                        isinstance(node.value, ast.Subscript):
                    tab = self.dispatch_table(node.value.value, scope, rel)
                    if tab:
                        for t in node.targets:
                            if isinstance(t, ast.Name):
                                cached[t.id] = tab
            self._alias_memo[scope.key] = cached
        return cached

    def resolve_call_targets(self, call: ast.Call, scope: FuncInfo | None,
                             rel: str) -> set[str]:
        """Every function key a call may reach: the single lexical
        target plus dict-dispatch edges (``CORES[op](...)`` and the
        ``core = CORES[op]; core(...)`` alias form)."""
        out: set[str] = set()
        single = self.resolve_call_target(call, scope, rel)
        if single:
            out.add(single)
        f = call.func
        if isinstance(f, ast.Subscript):
            tab = self.dispatch_table(f.value, scope, rel)
            if tab:
                out.update(tab)
        elif isinstance(f, ast.Name) and single is None:
            tab = self._dispatch_aliases(scope).get(f.id)
            if tab:
                out.update(tab)
        return out

    # ---- entry discovery ---------------------------------------------

    @staticmethod
    def _callable_name(expr: ast.AST) -> str | None:
        if isinstance(expr, ast.Name):
            return expr.id
        if isinstance(expr, ast.Attribute):
            return expr.attr
        return None

    @staticmethod
    def _static_argnames(keywords) -> set[str]:
        out: set[str] = set()
        for kw in keywords:
            if kw.arg == "static_argnames":
                for c in ast.walk(kw.value):
                    if isinstance(c, ast.Constant) and isinstance(c.value,
                                                                  str):
                        out.add(c.value)
        return out

    def _mark_entry(self, key: str | None, static: set[str] = frozenset(),
                    kind: str = "jit"):
        """Mark ``key`` as a traced entry.  ``static`` is the set of its
        parameters that are trace-time-static AT THIS ENTRY SITE; a
        parameter is recorded static only if it is static at EVERY site
        (intersection), since any one traced binding makes it traced.
        ``kind`` records the wrapper (``entry_kinds``) so the collective-
        sequence pass can pick out mesh entries (shard_map*)."""
        if key is None:
            return
        info = self.functions[key]
        if info.is_entry:
            info.static_params &= set(static)
        else:
            info.is_entry = True
            info.static_params = set(static)
        self.entries.add(key)
        self.entry_kinds.setdefault(key, set()).add(kind)

    def _resolve_and_find_entries(self):
        for key, info in self.functions.items():
            rel = info.module.rel
            # decorators
            for dec in info.node.decorator_list:
                name = self._callable_name(dec)
                if name in JIT_LIKE:
                    self._mark_entry(key)
                elif isinstance(dec, ast.Call):
                    cname = self._callable_name(dec.func)
                    if cname in JIT_LIKE:
                        self._mark_entry(key,
                                         self._static_argnames(dec.keywords))
                    elif cname == "partial" and dec.args:
                        inner = self._callable_name(dec.args[0])
                        if inner in JIT_LIKE:
                            self._mark_entry(
                                key, self._static_argnames(dec.keywords))
            # body: calls, references, wrapper args
            for node in own_nodes(info.node):
                if isinstance(node, ast.Call):
                    info.resolved_calls.update(
                        self.resolve_call_targets(node, info, rel))
                    wname = self._callable_name(node.func)
                    if wname in ENTRY_WRAPPERS and node.args:
                        self._wrapper_entry(node, info, rel, wname)
                elif isinstance(node, ast.Name) and isinstance(
                        node.ctx, ast.Load):
                    target = self.resolve_name(node.id, info, rel)
                    if target:
                        info.resolved_refs.add(target)
                elif (isinstance(node, ast.Attribute)
                      and isinstance(node.ctx, ast.Load)
                      and isinstance(node.value, ast.Name)):
                    target = self.resolve_attr(node.value.id, node.attr, rel)
                    if target:
                        info.resolved_refs.add(target)
        # module-level wrapper calls (entry built at import time);
        # own_nodes skips def bodies — those were handled above
        for rel, mod in self.project.modules.items():
            for node in own_nodes(mod.tree):
                if isinstance(node, ast.Call):
                    wname = self._callable_name(node.func)
                    if wname in ENTRY_WRAPPERS and node.args:
                        self._wrapper_entry(node, None, rel, wname)

    @staticmethod
    def _prefetch_count(call: ast.Call) -> int:
        """``num_scalar_prefetch`` of a pallas_call's INLINE
        ``grid_spec=PrefetchScalarGridSpec(...)``; 0 when absent or not a
        literal.  The spec must be constructed inside the call for the
        count to be visible — the repo's kernel style."""
        for kw in call.keywords:
            if kw.arg != "grid_spec" or not isinstance(kw.value, ast.Call):
                continue
            if (Reachability._callable_name(kw.value.func)
                    != "PrefetchScalarGridSpec"):
                continue
            for skw in kw.value.keywords:
                if (skw.arg == "num_scalar_prefetch"
                        and isinstance(skw.value, ast.Constant)
                        and isinstance(skw.value.value, int)):
                    return skw.value.value
        return 0

    def _prefetch_params(self, key: str | None, count: int) -> set[str]:
        """The kernel's first ``count`` parameter names: the scalar-
        prefetch operand refs, which carry grid-shaping scalars (consumed
        by BlockSpec index maps), not traced tile data."""
        if key is None or count <= 0:
            return set()
        names = [a.arg for a in self.functions[key].params()]
        return set(names[:count])

    def _wrapper_entry(self, call: ast.Call, scope: FuncInfo | None,
                       rel: str, wname: str):
        static = (self._static_argnames(call.keywords)
                  if wname in JIT_LIKE else set())
        prefetch = (self._prefetch_count(call) if wname == "pallas_call"
                    else 0)
        target = call.args[0]
        if isinstance(target, ast.Name):
            key = self.resolve_name(target.id, scope, rel)
            if key is None:
                # jax.vmap(core) where ``core = CORES[op]``: every table
                # value is a possible entry, all params traced
                for tkey in self._dispatch_aliases(scope).get(target.id, ()):
                    self._mark_entry(tkey, kind=wname)
                return
            self._mark_entry(key,
                             static | self._prefetch_params(key, prefetch),
                             kind=wname)
        elif (isinstance(target, ast.Call)
              and self._callable_name(target.func) == "partial"
              and target.args and isinstance(target.args[0], ast.Name)):
            # pallas_call(partial(_kernel, bw=bw), ...): the kernel is the
            # traced entry; partial's keyword bindings are closure values
            # fixed at trace time, hence static parameters of the kernel.
            key = self.resolve_name(target.args[0].id, scope, rel)
            self._mark_entry(
                key,
                {kw.arg for kw in target.keywords if kw.arg is not None}
                | self._prefetch_params(key, prefetch),
                kind=wname)
        elif isinstance(target, ast.Lambda):
            # the lambda body is traced: its resolvable callees are roots
            # (including dict-dispatch aliases — the serving layer's
            # ``vmap(lambda ai, bi: core(ai, bi, opts))`` idiom).
            # Only arguments fed from the LAMBDA'S OWN parameters are
            # traced; closure-bound arguments (``Nt=Nt``, ``lower=lower``
            # — the shard_map static-config idiom) are trace-time-static.
            lam_params = {a.arg for a in (*target.args.posonlyargs,
                                          *target.args.args,
                                          *target.args.kwonlyargs)}
            for node in ast.walk(target.body):
                if isinstance(node, ast.Call):
                    for key in self.resolve_call_targets(node, scope, rel):
                        self._mark_entry(
                            key, self._lambda_statics(node, key, lam_params),
                            kind=wname)

    def _lambda_statics(self, call: ast.Call, key: str,
                        lam_params: set[str]) -> set[str]:
        """Callee parameters bound (or defaulted) to closure values rather
        than to the traced lambda parameters."""
        def feeds_traced(expr: ast.AST) -> bool:
            return any(isinstance(n, ast.Name) and n.id in lam_params
                       for n in ast.walk(expr))

        callee = self.functions[key]
        names = [a.arg for a in callee.params()]
        traced: set[str] = set()
        has_starred = False
        for i, arg in enumerate(call.args):
            if isinstance(arg, ast.Starred):
                has_starred = True
                if feeds_traced(arg.value):
                    traced.update(names[i:])
            elif feeds_traced(arg) and i < len(names):
                traced.add(names[i])
        for kw in call.keywords:
            if kw.arg is None:  # **kwargs: can't map, be conservative
                if feeds_traced(kw.value):
                    traced.update(names)
            elif feeds_traced(kw.value):
                traced.add(kw.arg)
        if has_starred and callee.node.args.vararg:
            return set()  # positions unknowable: keep everything traced
        return set(names) - traced

    # ---- transitive closure ------------------------------------------

    def _closure(self):
        frontier = list(self.entries)
        self.traced = set(frontier)
        while frontier:
            key = frontier.pop()
            info = self.functions[key]
            nxt = (info.resolved_calls | info.resolved_refs
                   | {c.key for c in info.children.values()})
            for t in nxt:
                if t not in self.traced:
                    self.traced.add(t)
                    frontier.append(t)

    # ---- taint seeding policy ----------------------------------------

    def taint_all_params(self, info: FuncInfo) -> bool:
        """Entry functions and nested defs of traced functions run with
        every (non-static) parameter traced; transitively-traced
        module-level functions may also take static config, so only their
        array-annotated parameters seed taint (dataflow.py)."""
        if info.is_entry:
            return True
        return (info.key in self.traced and info.parent is not None
                and info.parent.key in self.traced)


def compute(project: Project) -> Reachability:
    if "reachability" not in project.cache:
        project.cache["reachability"] = Reachability(project)
    return project.cache["reachability"]
