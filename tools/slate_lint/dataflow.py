"""Taint analysis: which local names hold TRACED array values.

The per-function analysis (:class:`TaintAnalysis`) is intraprocedural;
:func:`taints` lifts it to the whole traced set by propagating tainted
CALL-SITE ARGUMENTS to callee parameters across the call graph —
including the dict-dispatch and re-export edges callgraph.py resolves —
to a fixpoint.  A transitively-traced module-level function whose array
parameter carries no annotation is still seeded when any traced caller
feeds it a tainted value.

Seeds
-----
- results of ``jnp.*`` / ``lax.*`` / ``jax.numpy.*`` calls (minus the
  :data:`STATIC_JNP_FNS` whose results are trace-time-static python
  values: dtype queries, finfo, ...);
- parameters, by the reachability pass's policy: every non-static
  parameter of a direct entry or of a nested def inside a traced
  function; only array-annotated parameters (``jax.Array``, ``Array``,
  ``jnp.ndarray``, ``ArrayLike``) of transitively-traced module-level
  functions — those may legitimately take static config ints;
- free variables tainted in the enclosing function (closures: a
  ``fori_loop`` body reads the traced carry of its builder).

Propagation
-----------
Assignments taint their targets when the RHS is tainted; taint flows
through subscripts, arithmetic, ``.T``/``.astype``-style attribute and
method chains, and calls with tainted arguments.  It does NOT flow
through the trace-time-static escape hatches: ``.shape`` / ``.ndim`` /
``.size`` / ``.dtype`` attribute reads and the :data:`STATIC_JNP_FNS`.

The analysis is flow-insensitive (a fixpoint over the function body),
which overtaints across re-bindings — fine for lint, where the cost of a
false positive is one explicit suppression.
"""

from __future__ import annotations

import ast

from . import reachability
from .reachability import FuncInfo, own_nodes

#: module aliases whose attribute calls produce traced arrays
ARRAY_NS_DOTTED = {"jax.numpy", "jax.lax", "jnp", "lax"}
#: jnp/lax functions returning trace-time-static python values
STATIC_JNP_FNS = {
    "issubdtype", "isdtype", "iinfo", "finfo", "result_type",
    "promote_types", "dtype", "ndim", "shape", "size", "can_cast",
    "iscomplexobj", "isrealobj",  # dtype queries: static even on tracers
}
#: builtins whose RESULT is always a host value even on traced args
#: (len/isinstance/getattr never call __bool__ on a tracer)
STATIC_RESULT_BUILTINS = {
    "len", "isinstance", "issubclass", "getattr", "hasattr", "type",
    "range", "enumerate", "callable", "id", "repr", "str",
}
#: attribute reads on a tracer that are static at trace time.  Beyond
#: the jax array surface, this includes the Matrix/TileStorage wrapper
#: metadata (core/matrix.py): those pytrees carry traced tile DATA in
#: ``.storage``/``.tiles``/``.data`` but their dims, tile sizes, grid and
#: view flags are __init__-time host ints/enums — branching on them is
#: the repo's standard trace-time dispatch.
STATIC_ATTRS = {"shape", "ndim", "size", "dtype", "itemsize", "sharding",
                "m", "n", "mt", "nt", "Mt", "Nt", "mb", "nb", "io", "jo",
                "grid", "op", "kind", "uplo", "diag", "source"}
#: method calls on a wrapper that return host metadata, never tracers
STATIC_METHODS = {"is_root_view", "is_traced", "tile_mb", "tile_nb",
                  "tile_rank"}
#: python builtins that force concretization of their argument
CONCRETIZERS = {"bool", "float", "int", "complex"}
#: method calls that force concretization of their receiver
CONCRETIZING_METHODS = {"item", "tolist", "__bool__", "__float__",
                        "__int__"}
#: annotations marking a parameter as an array for taint seeding
ARRAY_ANNOTATIONS = {"Array", "jax.Array", "jnp.ndarray", "ndarray",
                     "ArrayLike", "jax.typing.ArrayLike"}


def _ann_text(ann: ast.AST | None) -> str:
    if ann is None:
        return ""
    try:
        return ast.unparse(ann)
    except Exception:  # pragma: no cover - unparse is total on valid ast
        return ""


def array_namespace_aliases(imports: dict[str, str]) -> set[str]:
    """Names bound to jax.numpy / jax.lax in a module (jnp, lax, ...)."""
    out = {name for name, dotted in imports.items()
           if dotted in ARRAY_NS_DOTTED}
    out.update(n for n in ("jnp", "lax") if n in imports or n in out)
    return out


class TaintAnalysis:
    """Taint for one function; ``tainted`` is the fixpoint name set."""

    def __init__(self, info: FuncInfo, ns_aliases: set[str],
                 direct_fns: set[str], taint_all_params: bool,
                 inherited: frozenset[str] = frozenset(),
                 extra_seeds: frozenset[str] = frozenset(),
                 summary=None):
        self.info = info
        self.ns = ns_aliases          # jnp/lax-style module aliases
        self.direct_fns = direct_fns  # names imported straight from jnp/lax
        #: optional interprocedural return-taint oracle:
        #: call -> bool | [bool per tuple element] | None (unknown)
        self.summary = summary
        self.tainted: set[str] = set(inherited)
        self._seed_params(taint_all_params)
        # interprocedural seeds: params fed tainted values at a call site
        self.tainted.update(extra_seeds)
        self._fixpoint()

    # ---- seeding ------------------------------------------------------

    def _seed_params(self, all_params: bool):
        defaulted = self._defaulted_params()
        for arg in self.info.params():
            if arg.arg in self.info.static_params:
                continue
            if all_params:
                # non-entry nested defs (fori_loop/scan bodies): a
                # defaulted parameter is the static-capture idiom
                # (``def step(k, c, W0=W0)``) — the loop combinator only
                # ever feeds the non-defaulted ones
                if arg.arg in defaulted and not self.info.is_entry:
                    continue
                self.tainted.add(arg.arg)
            elif any(a in _ann_text(arg.annotation)
                     for a in ARRAY_ANNOTATIONS):
                self.tainted.add(arg.arg)

    def _defaulted_params(self) -> set[str]:
        a = self.info.node.args
        pos = [*a.posonlyargs, *a.args]
        out = {arg.arg for arg in pos[len(pos) - len(a.defaults):]}
        out.update(arg.arg for arg, d in zip(a.kwonlyargs, a.kw_defaults)
                   if d is not None)
        return out

    # ---- expression taint --------------------------------------------

    def is_array_ns(self, expr: ast.AST) -> bool:
        """Is ``expr`` (a call's func) a jnp/lax-namespace function?"""
        if isinstance(expr, ast.Name):
            return expr.id in self.direct_fns
        if isinstance(expr, ast.Attribute):
            if expr.attr in STATIC_JNP_FNS:
                return False
            base = expr.value
            if isinstance(base, ast.Name) and base.id in self.ns:
                return True
            # jax.numpy.fn / jax.lax.fn spelled in full
            if (isinstance(base, ast.Attribute)
                    and isinstance(base.value, ast.Name)
                    and base.value.id == "jax"
                    and base.attr in ("numpy", "lax")):
                return True
        return False

    def expr_tainted(self, expr: ast.AST | None) -> bool:
        if expr is None:
            return False
        if isinstance(expr, ast.Name):
            return expr.id in self.tainted
        if isinstance(expr, ast.Attribute):
            if expr.attr in STATIC_ATTRS:
                return False
            return self.expr_tainted(expr.value)
        if isinstance(expr, ast.Call):
            return self.call_tainted(expr)
        if isinstance(expr, ast.Lambda):
            return False  # a function object, not a value
        if isinstance(expr, ast.Compare) and \
                all(isinstance(op, (ast.Is, ast.IsNot)) for op in expr.ops):
            return False  # identity tests never concretize (`x is None`)
        if isinstance(expr, (ast.ListComp, ast.SetComp, ast.DictComp,
                             ast.GeneratorExp)):
            return any(self.expr_tainted(g.iter) for g in expr.generators)
        return any(self.expr_tainted(c)
                   for c in ast.iter_child_nodes(expr)
                   if isinstance(c, ast.expr))

    def call_tainted(self, call: ast.Call) -> bool:
        f = call.func
        if self.is_array_ns(f):
            return True
        if isinstance(f, ast.Attribute) and f.attr in STATIC_JNP_FNS:
            return False
        if isinstance(f, ast.Attribute) and f.attr in STATIC_METHODS:
            return False  # host-metadata method on a wrapper/HealthInfo
        if self.summary is not None:
            known = self.summary(call)
            if known is not None:
                return (any(known) if isinstance(known, list) else
                        bool(known))
        if isinstance(f, ast.Name):
            if f.id in CONCRETIZERS:  # host scalar out (and a sink)
                return False
            if f.id in STATIC_RESULT_BUILTINS:
                return False  # host-level result regardless of args
            if f.id in ("zip", "min", "max", "abs", "sum", "tuple", "list",
                        "dict", "set", "sorted"):
                # value passthrough: traced in -> traced out
                return any(self.expr_tainted(a) for a in call.args)
        if isinstance(f, ast.Attribute):
            if f.attr in CONCRETIZING_METHODS:
                return False  # host value out (and a sink)
            if self.expr_tainted(f.value):  # method on a traced array
                return True
        return (any(self.expr_tainted(a) for a in call.args)
                or any(self.expr_tainted(kw.value) for kw in call.keywords))

    # ---- statement fixpoint ------------------------------------------

    def _assign_targets(self, target: ast.AST):
        if isinstance(target, ast.Name):
            self.tainted.add(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._assign_targets(elt)
        elif isinstance(target, ast.Starred):
            self._assign_targets(target.value)
        # attribute/subscript stores don't create locals

    def _destructured_call(self, node: ast.Assign) -> bool:
        """``a, b = helper(...)`` with an element-wise return summary:
        taint each target from the matching return-tuple element instead
        of the whole-call verdict (``ad, n0 = _pad_tri(ad, nb)`` leaves
        the static ``n0`` clean).  True when handled."""
        if self.summary is None or not isinstance(node.value, ast.Call):
            return False
        if len(node.targets) != 1 or not isinstance(node.targets[0],
                                                    ast.Tuple):
            return False
        elts = node.targets[0].elts
        known = self.summary(node.value)
        if not isinstance(known, list) or len(known) != len(elts):
            return False
        for elt, hot in zip(elts, known):
            if hot:
                self._assign_targets(elt)
        return True

    def _fixpoint(self):
        changed = True
        while changed:
            before = len(self.tainted)
            for node in own_nodes(self.info.node):
                if isinstance(node, ast.Assign):
                    if self._destructured_call(node):
                        continue
                    if self.expr_tainted(node.value):
                        for t in node.targets:
                            self._assign_targets(t)
                elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
                    if self.expr_tainted(node.value):
                        self._assign_targets(node.target)
                elif isinstance(node, ast.NamedExpr):
                    if self.expr_tainted(node.value):
                        self._assign_targets(node.target)
                elif isinstance(node, ast.For):
                    if self.expr_tainted(node.iter):
                        self._assign_targets(node.target)
                elif isinstance(node, (ast.ListComp, ast.SetComp,
                                       ast.DictComp, ast.GeneratorExp)):
                    for gen in node.generators:
                        if self.expr_tainted(gen.iter):
                            self._assign_targets(gen.target)
                elif isinstance(node, ast.withitem):
                    if node.optional_vars is not None and \
                            self.expr_tainted(node.context_expr):
                        self._assign_targets(node.optional_vars)
            changed = len(self.tainted) != before


def analyze(info: FuncInfo, imports: dict[str, str],
            taint_all_params: bool,
            inherited: frozenset[str] = frozenset(),
            extra_seeds: frozenset[str] = frozenset(),
            summary=None) -> TaintAnalysis:
    ns = array_namespace_aliases(imports)
    direct = {name for name, dotted in imports.items()
              if any(dotted == f"{m}.{name.split('.')[-1]}" or
                     dotted.startswith(f"{m}.")
                     for m in ("jax.numpy", "jax.lax"))
              and dotted.rsplit(".", 1)[-1] not in STATIC_JNP_FNS}
    return TaintAnalysis(info, ns, direct, taint_all_params, inherited,
                         extra_seeds, summary)


# ---- interprocedural lifting ---------------------------------------------

#: modules whose functions never receive interprocedural taint seeds:
#: the host-only obs layer (jaxpr-identity contract — every tracer it is
#: handed is guarded by ``is_traced()`` checks and recorded as None) and
#: the registered eager policy seams, whose tracer handling is the
#: designed trace-time behaviour (guarded raises, config resolution).
TAINT_BARRIER_MODULES = {
    "slate_tpu/obs/events.py",
    "slate_tpu/obs/flops.py",
    "slate_tpu/obs/sentinel.py",
    "slate_tpu/robust/health.py",
    "slate_tpu/robust/recovery.py",
    "slate_tpu/exceptions.py",
    "slate_tpu/options.py",
}

#: cap on reanalyses of one function during the interprocedural fixpoint
#: — return summaries can refine non-monotonically, so a hard bound
#: guarantees termination (never reached on the repo; pure safety net)
_MAX_REBUILDS = 8


def _seedable_params(callee: FuncInfo) -> list[str | None]:
    """Positional parameter slots open to interprocedural seeding: a
    parameter annotated with a NON-array type (``opts: Options``,
    ``n: int``) declares itself host config and is never seeded; array
    annotations and bare parameters are eligible."""
    out: list[str | None] = []
    for arg in callee.params():
        ann = _ann_text(arg.annotation)
        eligible = not ann or any(a in ann for a in ARRAY_ANNOTATIONS)
        out.append(arg.arg if eligible else None)
    return out


def _args_to_params(ta: TaintAnalysis, call: ast.Call,
                    callee: FuncInfo) -> set[str]:
    """Seedable callee parameter names bound to TAINTED arguments."""
    names = _seedable_params(callee)
    out: set[str] = set()
    for i, arg in enumerate(call.args):
        if isinstance(arg, ast.Starred):
            if ta.expr_tainted(arg.value):
                out.update(n for n in names[i:] if n)
        elif i < len(names) and names[i] and ta.expr_tainted(arg):
            out.add(names[i])
    for kw in call.keywords:
        if kw.arg is None:  # **kwargs: positions unknowable
            if ta.expr_tainted(kw.value):
                out.update(n for n in names if n)
        elif kw.arg in {n for n in names if n} and ta.expr_tainted(kw.value):
            out.add(kw.arg)
    return out


def taints(project) -> tuple:
    """``(reach, {key: TaintAnalysis})`` for every traced function.

    Built parents-before-children so closures inherit the enclosing
    function's tainted names, then driven to an interprocedural fixpoint
    over the call graph (dispatch-table and re-export edges included):

    - tainted call-site ARGUMENTS seed the receiving callee parameters
      (unless the callee's annotation declares host config, the callee
      is a taint-barrier module, or the seeding policy already taints
      everything), and the callee is reanalyzed;
    - callee RETURN taint flows back: each analysis consults an oracle
      mapping a resolvable call to its callee's return-expression taint,
      element-wise for tuple returns, so ``ad, n0 = _pad_tri(ad, nb)``
      taints ``ad`` but leaves the shape-derived ``n0`` clean.

    Reanalysis is capped per function (:data:`_MAX_REBUILDS`) so the
    refinement loop terminates even on adversarial cycles.  Cached on
    the project (``cache['taints']``)."""
    if "taints" in project.cache:
        return project.cache["taints"]
    reach = reachability.compute(project)
    memo: dict[str, TaintAnalysis] = {}
    extra: dict[str, set[str]] = {}
    callers: dict[str, set[str]] = {}
    rebuilds: dict[str, int] = {}

    def summary_for(info: FuncInfo):
        rel = info.module.rel

        def oracle(call: ast.Call):
            targets = reach.resolve_call_targets(call, info, rel)
            if len(targets) != 1:
                return None
            (tkey,) = targets
            ta = memo.get(tkey)
            if ta is None:
                return None
            rets = [n for n in own_nodes(ta.info.node)
                    if isinstance(n, ast.Return)]
            if not rets:
                return False
            shapes: list[list[bool] | bool] = []
            for r in rets:
                if isinstance(r.value, ast.Tuple):
                    shapes.append([ta.expr_tainted(e)
                                   for e in r.value.elts])
                else:
                    shapes.append(ta.expr_tainted(r.value))
            first = shapes[0]
            if all(isinstance(s, list) and isinstance(first, list)
                   and len(s) == len(first) for s in shapes):
                return [any(s[i] for s in shapes)
                        for i in range(len(first))]
            return any(any(s) if isinstance(s, list) else s
                       for s in shapes)

        return oracle

    def build(key: str) -> TaintAnalysis:
        info = reach.functions[key]
        inherited = frozenset()
        if info.parent is not None and info.parent.key in memo:
            inherited = frozenset(memo[info.parent.key].tainted)
        memo[key] = analyze(
            info, reach.imports[info.module.rel],
            reach.taint_all_params(info), inherited,
            frozenset(extra.get(key, ())), summary_for(info))
        return memo[key]

    def get(key: str) -> TaintAnalysis:
        if key in memo:
            return memo[key]
        info = reach.functions[key]
        if info.parent is not None and info.parent.key in reach.traced:
            get(info.parent.key)
        return build(key)

    for key in sorted(reach.traced):
        if key in reach.functions:
            get(key)
    # second pass: the first build of a function that sorts BEFORE its
    # callees ran with a cold oracle (whole-call fallback).  Now that
    # every function is in the memo, rebuild each once so return-taint
    # summaries apply everywhere (parents sort before their children, so
    # closure inheritance stays consistent).
    for key in sorted(memo):
        build(key)

    def rebuild(key: str, worklist: list[str]):
        if rebuilds.get(key, 0) >= _MAX_REBUILDS:
            return
        rebuilds[key] = rebuilds.get(key, 0) + 1
        before = set(memo[key].tainted)
        build(key)
        worklist.append(key)
        if memo[key].tainted != before:
            # return summary changed: callers must re-ANALYZE (a bare
            # worklist append would only rescan their call sites against
            # the stale analysis)
            for c in callers.get(key, ()):
                rebuild(c, worklist)
        for child in reach.functions[key].children.values():
            if child.key in memo:
                rebuild(child.key, worklist)

    worklist = sorted(memo)
    seen_pass = set()
    while worklist:
        key = worklist.pop()
        ta = memo[key]
        info = reach.functions[key]
        rel = info.module.rel
        first_visit = key not in seen_pass
        seen_pass.add(key)
        for node in own_nodes(info.node):
            if not isinstance(node, ast.Call):
                continue
            for tkey in reach.resolve_call_targets(node, info, rel):
                if tkey not in memo:
                    continue
                callee = reach.functions[tkey]
                if first_visit:
                    callers.setdefault(tkey, set()).add(key)
                if reach.taint_all_params(callee):
                    continue  # policy already taints every parameter
                if callee.module.rel in TAINT_BARRIER_MODULES:
                    continue  # host-only / eager-seam boundary
                new = (_args_to_params(ta, node, callee)
                       - callee.static_params - memo[tkey].tainted)
                if new:
                    extra.setdefault(tkey, set()).update(new)
                    rebuild(tkey, worklist)

    project.cache["taints"] = (reach, memo)
    return project.cache["taints"]
