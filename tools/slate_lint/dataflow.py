"""Intraprocedural taint: which local names hold TRACED array values.

Seeds
-----
- results of ``jnp.*`` / ``lax.*`` / ``jax.numpy.*`` calls (minus the
  :data:`STATIC_JNP_FNS` whose results are trace-time-static python
  values: dtype queries, finfo, ...);
- parameters, by the reachability pass's policy: every non-static
  parameter of a direct entry or of a nested def inside a traced
  function; only array-annotated parameters (``jax.Array``, ``Array``,
  ``jnp.ndarray``, ``ArrayLike``) of transitively-traced module-level
  functions — those may legitimately take static config ints;
- free variables tainted in the enclosing function (closures: a
  ``fori_loop`` body reads the traced carry of its builder).

Propagation
-----------
Assignments taint their targets when the RHS is tainted; taint flows
through subscripts, arithmetic, ``.T``/``.astype``-style attribute and
method chains, and calls with tainted arguments.  It does NOT flow
through the trace-time-static escape hatches: ``.shape`` / ``.ndim`` /
``.size`` / ``.dtype`` attribute reads and the :data:`STATIC_JNP_FNS`.

The analysis is flow-insensitive (a fixpoint over the function body),
which overtaints across re-bindings — fine for lint, where the cost of a
false positive is one explicit suppression.
"""

from __future__ import annotations

import ast

from .reachability import FuncInfo, own_nodes

#: module aliases whose attribute calls produce traced arrays
ARRAY_NS_DOTTED = {"jax.numpy", "jax.lax", "jnp", "lax"}
#: jnp/lax functions returning trace-time-static python values
STATIC_JNP_FNS = {
    "issubdtype", "isdtype", "iinfo", "finfo", "result_type",
    "promote_types", "dtype", "ndim", "shape", "size", "can_cast",
    "iscomplexobj", "isrealobj",  # dtype queries: static even on tracers
}
#: builtins whose RESULT is always a host value even on traced args
#: (len/isinstance/getattr never call __bool__ on a tracer)
STATIC_RESULT_BUILTINS = {
    "len", "isinstance", "issubclass", "getattr", "hasattr", "type",
    "range", "enumerate", "callable", "id", "repr", "str",
}
#: attribute reads on a tracer that are static at trace time
STATIC_ATTRS = {"shape", "ndim", "size", "dtype", "itemsize", "sharding"}
#: python builtins that force concretization of their argument
CONCRETIZERS = {"bool", "float", "int", "complex"}
#: method calls that force concretization of their receiver
CONCRETIZING_METHODS = {"item", "tolist", "__bool__", "__float__",
                        "__int__"}
#: annotations marking a parameter as an array for taint seeding
ARRAY_ANNOTATIONS = {"Array", "jax.Array", "jnp.ndarray", "ndarray",
                     "ArrayLike", "jax.typing.ArrayLike"}


def _ann_text(ann: ast.AST | None) -> str:
    if ann is None:
        return ""
    try:
        return ast.unparse(ann)
    except Exception:  # pragma: no cover - unparse is total on valid ast
        return ""


def array_namespace_aliases(imports: dict[str, str]) -> set[str]:
    """Names bound to jax.numpy / jax.lax in a module (jnp, lax, ...)."""
    out = {name for name, dotted in imports.items()
           if dotted in ARRAY_NS_DOTTED}
    out.update(n for n in ("jnp", "lax") if n in imports or n in out)
    return out


class TaintAnalysis:
    """Taint for one function; ``tainted`` is the fixpoint name set."""

    def __init__(self, info: FuncInfo, ns_aliases: set[str],
                 direct_fns: set[str], taint_all_params: bool,
                 inherited: frozenset[str] = frozenset()):
        self.info = info
        self.ns = ns_aliases          # jnp/lax-style module aliases
        self.direct_fns = direct_fns  # names imported straight from jnp/lax
        self.tainted: set[str] = set(inherited)
        self._seed_params(taint_all_params)
        self._fixpoint()

    # ---- seeding ------------------------------------------------------

    def _seed_params(self, all_params: bool):
        defaulted = self._defaulted_params()
        for arg in self.info.params():
            if arg.arg in self.info.static_params:
                continue
            if all_params:
                # non-entry nested defs (fori_loop/scan bodies): a
                # defaulted parameter is the static-capture idiom
                # (``def step(k, c, W0=W0)``) — the loop combinator only
                # ever feeds the non-defaulted ones
                if arg.arg in defaulted and not self.info.is_entry:
                    continue
                self.tainted.add(arg.arg)
            elif any(a in _ann_text(arg.annotation)
                     for a in ARRAY_ANNOTATIONS):
                self.tainted.add(arg.arg)

    def _defaulted_params(self) -> set[str]:
        a = self.info.node.args
        pos = [*a.posonlyargs, *a.args]
        out = {arg.arg for arg in pos[len(pos) - len(a.defaults):]}
        out.update(arg.arg for arg, d in zip(a.kwonlyargs, a.kw_defaults)
                   if d is not None)
        return out

    # ---- expression taint --------------------------------------------

    def is_array_ns(self, expr: ast.AST) -> bool:
        """Is ``expr`` (a call's func) a jnp/lax-namespace function?"""
        if isinstance(expr, ast.Name):
            return expr.id in self.direct_fns
        if isinstance(expr, ast.Attribute):
            if expr.attr in STATIC_JNP_FNS:
                return False
            base = expr.value
            if isinstance(base, ast.Name) and base.id in self.ns:
                return True
            # jax.numpy.fn / jax.lax.fn spelled in full
            if (isinstance(base, ast.Attribute)
                    and isinstance(base.value, ast.Name)
                    and base.value.id == "jax"
                    and base.attr in ("numpy", "lax")):
                return True
        return False

    def expr_tainted(self, expr: ast.AST | None) -> bool:
        if expr is None:
            return False
        if isinstance(expr, ast.Name):
            return expr.id in self.tainted
        if isinstance(expr, ast.Attribute):
            if expr.attr in STATIC_ATTRS:
                return False
            return self.expr_tainted(expr.value)
        if isinstance(expr, ast.Call):
            return self.call_tainted(expr)
        if isinstance(expr, ast.Lambda):
            return False  # a function object, not a value
        if isinstance(expr, ast.Compare) and \
                all(isinstance(op, (ast.Is, ast.IsNot)) for op in expr.ops):
            return False  # identity tests never concretize (`x is None`)
        if isinstance(expr, (ast.ListComp, ast.SetComp, ast.DictComp,
                             ast.GeneratorExp)):
            return any(self.expr_tainted(g.iter) for g in expr.generators)
        return any(self.expr_tainted(c)
                   for c in ast.iter_child_nodes(expr)
                   if isinstance(c, ast.expr))

    def call_tainted(self, call: ast.Call) -> bool:
        f = call.func
        if self.is_array_ns(f):
            return True
        if isinstance(f, ast.Attribute) and f.attr in STATIC_JNP_FNS:
            return False
        if isinstance(f, ast.Name):
            if f.id in CONCRETIZERS:  # host scalar out (and a sink)
                return False
            if f.id in STATIC_RESULT_BUILTINS:
                return False  # host-level result regardless of args
            if f.id in ("zip", "min", "max", "abs", "sum", "tuple", "list",
                        "dict", "set", "sorted"):
                # value passthrough: traced in -> traced out
                return any(self.expr_tainted(a) for a in call.args)
        if isinstance(f, ast.Attribute):
            if f.attr in CONCRETIZING_METHODS:
                return False  # host value out (and a sink)
            if self.expr_tainted(f.value):  # method on a traced array
                return True
        return (any(self.expr_tainted(a) for a in call.args)
                or any(self.expr_tainted(kw.value) for kw in call.keywords))

    # ---- statement fixpoint ------------------------------------------

    def _assign_targets(self, target: ast.AST):
        if isinstance(target, ast.Name):
            self.tainted.add(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._assign_targets(elt)
        elif isinstance(target, ast.Starred):
            self._assign_targets(target.value)
        # attribute/subscript stores don't create locals

    def _fixpoint(self):
        changed = True
        while changed:
            before = len(self.tainted)
            for node in own_nodes(self.info.node):
                if isinstance(node, ast.Assign):
                    if self.expr_tainted(node.value):
                        for t in node.targets:
                            self._assign_targets(t)
                elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
                    if self.expr_tainted(node.value):
                        self._assign_targets(node.target)
                elif isinstance(node, ast.NamedExpr):
                    if self.expr_tainted(node.value):
                        self._assign_targets(node.target)
                elif isinstance(node, ast.For):
                    if self.expr_tainted(node.iter):
                        self._assign_targets(node.target)
                elif isinstance(node, (ast.ListComp, ast.SetComp,
                                       ast.DictComp, ast.GeneratorExp)):
                    for gen in node.generators:
                        if self.expr_tainted(gen.iter):
                            self._assign_targets(gen.target)
                elif isinstance(node, ast.withitem):
                    if node.optional_vars is not None and \
                            self.expr_tainted(node.context_expr):
                        self._assign_targets(node.optional_vars)
            changed = len(self.tainted) != before


def analyze(info: FuncInfo, imports: dict[str, str],
            taint_all_params: bool,
            inherited: frozenset[str] = frozenset()) -> TaintAnalysis:
    ns = array_namespace_aliases(imports)
    direct = {name for name, dotted in imports.items()
              if any(dotted == f"{m}.{name.split('.')[-1]}" or
                     dotted.startswith(f"{m}.")
                     for m in ("jax.numpy", "jax.lax"))
              and dotted.rsplit(".", 1)[-1] not in STATIC_JNP_FNS}
    return TaintAnalysis(info, ns, direct, taint_all_params, inherited)
