"""Cross-module call graph: the shared spine of the interprocedural passes.

The reachability pass (reachability.py) resolves *lexical* edges — direct
calls, bare references, nested defs.  This module adds the two edge kinds
that used to be documented false negatives (docs/STATIC_ANALYSIS.md) and
packages everything as one queryable graph:

- **dict-dispatch tables** — module-level ``NAME = {"k": fn, ...}`` maps
  of resolvable functions (the ``serve.CORES`` idiom).  A call through a
  table (``CORES[op](...)``, or the two-step ``core = CORES[op];
  core(...)`` alias, or a traced lambda closing over such an alias) may
  reach ANY value of the table, so every value becomes an edge.
- **re-exports** — ``pkg.fn`` where ``pkg/__init__.py`` (or any
  intermediate module) merely imports ``fn`` from a submodule.  Dotted
  resolution follows the import map of the resolved module recursively
  (cycle-guarded) until it lands on a real ``def``.

Dispatch-table collection lives here; re-export following is implemented
inside ``Reachability._resolve_dotted`` (it IS dotted resolution) and
documented here because this module is the call-graph surface.

:class:`CallGraph` is the facade the concurrency pass builds on: forward
(``callees``) and reverse (``callers``) edges over every indexed module
function, plus a separate index of CLASS METHODS (``<rel>::<Class>.<m>``)
— reachability deliberately does not model methods (jax entries are
functions), but lock-discipline analysis must see ``self.helper()``
chains inside ``Server`` / ``ExecutableCache``.
"""

from __future__ import annotations

import ast

from .loader import Project, SourceModule


def collect_dispatch_tables(reach) -> dict[str, dict[str, tuple[str, ...]]]:
    """``rel -> {table_name: (function keys...)}`` for module-level
    dict-dispatch tables.  A table is recorded when at least one value
    resolves to a project function; unresolvable values (e.g. imported
    third-party callables) are skipped, keeping the edge set a
    best-effort under-approximation rather than a guess."""
    tables: dict[str, dict[str, tuple[str, ...]]] = {}
    for rel, mod in reach.project.modules.items():
        per: dict[str, tuple[str, ...]] = {}
        for node in mod.tree.body:
            if isinstance(node, ast.Assign):
                targets = [t for t in node.targets if isinstance(t, ast.Name)]
                value = node.value
            elif isinstance(node, ast.AnnAssign) and \
                    isinstance(node.target, ast.Name):
                targets = [node.target]
                value = node.value
            else:
                continue
            if not targets or not isinstance(value, ast.Dict):
                continue
            keys: list[str] = []
            for v in value.values:
                k = None
                if isinstance(v, ast.Name):
                    k = reach.resolve_name(v.id, None, rel)
                elif isinstance(v, ast.Attribute) and \
                        isinstance(v.value, ast.Name):
                    k = reach.resolve_attr(v.value.id, v.attr, rel)
                if k:
                    keys.append(k)
            if keys:
                for t in targets:
                    per[t.id] = tuple(dict.fromkeys(keys))
        if per:
            tables[rel] = per
    return tables


class MethodInfo:
    """One class method: enough context for lock-discipline analysis."""

    def __init__(self, key: str, node: ast.FunctionDef,
                 module: SourceModule, cls: str):
        self.key = key              # "<rel>::<Class>.<method>"
        self.node = node
        self.module = module
        self.cls = cls

    @property
    def name(self) -> str:
        return self.node.name


def _iter_class_methods(module: SourceModule):
    for node in module.tree.body:
        if not isinstance(node, ast.ClassDef):
            continue
        for sub in node.body:
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield node.name, sub


class CallGraph:
    """Forward/reverse edges over module functions and class methods.

    Keys are reachability function keys (``<rel>::<qual>``) plus method
    keys (``<rel>::<Class>.<method>``).  Edges are the reachability
    pass's resolved calls/refs (which already include dispatch-table and
    re-export targets) plus, for methods, ``self.other()`` calls within
    the same class and lexically-resolvable module-level calls."""

    def __init__(self, project: Project):
        from . import reachability  # local: reachability imports us too
        self.reach = reach = reachability.compute(project)
        self.project = project
        self.methods: dict[str, MethodInfo] = {}
        for rel, mod in project.modules.items():
            for cls, node in _iter_class_methods(mod):
                mi = MethodInfo(f"{rel}::{cls}.{node.name}", node, mod, cls)
                self.methods[mi.key] = mi
        self.nodes: dict[str, object] = {**reach.functions, **self.methods}
        self.edges: dict[str, set[str]] = {}
        for key, info in reach.functions.items():
            self.edges[key] = (set(info.resolved_calls)
                               | set(info.resolved_refs)
                               | {c.key for c in info.children.values()})
        for key, mi in self.methods.items():
            self.edges[key] = self._method_edges(mi)
        self.rev: dict[str, set[str]] = {k: set() for k in self.edges}
        for src, dsts in self.edges.items():
            for dst in dsts:
                self.rev.setdefault(dst, set()).add(src)

    def _method_edges(self, mi: MethodInfo) -> set[str]:
        from .reachability import own_nodes
        reach, rel = self.reach, mi.module.rel
        out: set[str] = set()
        for node in own_nodes(mi.node):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Attribute) and \
                    isinstance(f.value, ast.Name) and f.value.id == "self":
                mkey = f"{rel}::{mi.cls}.{f.attr}"
                if mkey in self.methods:
                    out.add(mkey)
                    continue
            out.update(reach.resolve_call_targets(node, None, rel))
        return out

    def callees(self, key: str) -> set[str]:
        return self.edges.get(key, set())

    def callers(self, key: str) -> set[str]:
        return self.rev.get(key, set())


def compute(project: Project) -> CallGraph:
    if "callgraph" not in project.cache:
        project.cache["callgraph"] = CallGraph(project)
    return project.cache["callgraph"]
