"""Project loading: discover source files, parse, collect suppressions.

The default scan set is the *checked repo surface*: ``slate_tpu/``,
``tools/``, and ``bench.py`` under the project root.  ``tests/`` and
``examples/`` are deliberately excluded — rule fixtures live there and
must be allowed to violate rules on purpose.

Everything is pure stdlib (``ast`` + ``tokenize``): the analyzer never
imports the code it checks, so it runs on machines without jax.
"""

from __future__ import annotations

import ast
import io
import tokenize
from pathlib import Path

from .model import parse_suppressions

DEFAULT_TARGETS = ("slate_tpu", "tools", "bench.py")


class SourceModule:
    """One parsed file: AST, dotted module name, per-line suppressions."""

    def __init__(self, root: Path, path: Path, text: str):
        self.path = path
        self.rel = path.relative_to(root).as_posix()
        self.text = text
        self.tree = ast.parse(text, filename=str(path))
        self.dotted = self.rel[:-3].replace("/", ".")  # a/b/c.py -> a.b.c
        if self.dotted.endswith(".__init__"):
            self.dotted = self.dotted[: -len(".__init__")]
        self.suppressions = parse_suppressions(_comments(text))

    def suppressed(self, line: int, rule: str) -> bool:
        rules = self.suppressions.get(line, ())
        return rule in rules or "all" in rules


def _comments(text: str) -> list[tuple[int, str, bool]]:
    """(lineno, comment, standalone?) for every comment token.  tokenize
    (not a regex) so ``#`` inside string literals is never misread."""
    out = []
    lines = text.splitlines()
    try:
        for tok in tokenize.generate_tokens(io.StringIO(text).readline):
            if tok.type == tokenize.COMMENT:
                lineno, col = tok.start
                src_line = lines[lineno - 1] if lineno <= len(lines) else ""
                standalone = not src_line[:col].strip()
                out.append((lineno, tok.string, standalone))
    except tokenize.TokenError:  # unterminated strings etc: best effort
        pass
    return out


class Project:
    """The loaded repo: modules by repo-relative path, plus a scratch cache
    rules share (reachability results, seam scans)."""

    def __init__(self, root: Path, modules: dict[str, SourceModule]):
        self.root = root
        self.modules = modules
        self.by_dotted = {m.dotted: m for m in modules.values()}
        self.cache: dict[str, object] = {}

    def module(self, rel: str) -> SourceModule | None:
        return self.modules.get(rel)


def iter_source_files(root: Path, targets=DEFAULT_TARGETS):
    for target in targets:
        p = root / target
        if p.is_file() and p.suffix == ".py":
            yield p
        elif p.is_dir():
            yield from sorted(p.rglob("*.py"))


def load_project(root: Path | str, targets=DEFAULT_TARGETS) -> Project:
    root = Path(root).resolve()
    modules: dict[str, SourceModule] = {}
    for path in iter_source_files(root, targets):
        try:
            mod = SourceModule(root, path, path.read_text())
        except (SyntaxError, UnicodeDecodeError):
            # unparseable files are invisible to the analyzer; the test
            # suite will catch them long before lint does
            continue
        modules[mod.rel] = mod
    return Project(root, modules)
