"""slate-lint: multi-pass AST analyzer for tpu-slate.

Passes:

1. **reachability** — which functions does jax trace? (entry discovery
   over jit/shard_map/pallas_call + transitive closure; reachability.py)
2. **dataflow** — which values inside a traced function are traced?
   (intraprocedural taint; dataflow.py)
3. **rules** — trace-safety (TRC0xx), collective discipline (COL0xx),
   policy-seam contracts (SEAM0xx); rules/

Pure stdlib: the analyzer parses the repo, it never imports it.
See docs/STATIC_ANALYSIS.md for the rule catalogue.
"""

from .cli import main, run_rules  # noqa: F401
from .loader import load_project  # noqa: F401
from .model import REGISTRY, Finding, Rule, register  # noqa: F401

__all__ = ["main", "run_rules", "load_project", "REGISTRY", "Finding",
           "Rule", "register"]
