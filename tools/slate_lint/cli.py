"""slate-lint CLI.

Usage::

    python -m tools.slate_lint [--root DIR] [--format human|json]
                               [--select RULES] [--baseline FILE]
                               [--update-baseline] [--list-rules]
                               [--cache FILE] [--changed-only]
                               [--output FILE]

Exit codes: 0 clean (no findings outside the baseline), 1 findings,
2 usage / internal error.

The baseline is a JSON list of line-free fingerprints
``[rule, path, message]`` — known findings that are tolerated but must
not grow.  ``--update-baseline`` rewrites it from the current findings;
the checked-in ``tools/slate_lint/baseline.json`` is empty and the repo
is expected to stay clean (suppress intentional sites inline with a
reason instead of baselining them).

``--cache FILE`` (or ``SLATE_LINT_CACHE=FILE``) replays a full run
against an unchanged tree from the per-file content-hash cache
(fscache.py) — sound because ANY file drift forces full re-analysis.
``--changed-only`` reports (and gates the exit code on) findings in
files changed vs git HEAD plus untracked files; the analysis itself
stays whole-project, so interprocedural findings in changed files are
still correct.  ``--output FILE`` writes the JSON report to a file in
every format mode — the tier-1 artifact CI archives.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from pathlib import Path

from . import fscache
from .loader import load_project
from .model import REGISTRY, Finding

DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.json"


def load_rules():
    from . import rules  # noqa: F401  (populates REGISTRY on import)
    return REGISTRY


def run_rules(project, select: set[str] | None = None) -> list[Finding]:
    registry = load_rules()
    findings: list[Finding] = []
    for rule_id, rule in registry.items():
        if select is not None and rule_id not in select:
            continue
        for f in rule.run(project):
            mod = project.module(f.path)
            if mod is not None and mod.suppressed(f.line, f.rule):
                continue
            findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return findings


def changed_files(root: Path) -> set[str] | None:
    """Repo-relative paths changed vs HEAD plus untracked files, or None
    when git is unavailable (no repo, no binary) — callers fall back to
    reporting everything rather than silently hiding findings."""
    out: set[str] = set()
    for cmd in (("diff", "--name-only", "HEAD"),
                ("ls-files", "--others", "--exclude-standard")):
        try:
            res = subprocess.run(["git", "-C", str(root), *cmd],
                                 capture_output=True, text=True, timeout=30)
        except (OSError, subprocess.SubprocessError):
            return None
        if res.returncode != 0:
            return None
        out.update(line.strip() for line in res.stdout.splitlines()
                   if line.strip())
    return out


def read_baseline(path: Path) -> list[tuple[str, str, str]]:
    if not path.exists():
        return []
    data = json.loads(path.read_text() or "[]")
    return [tuple(entry) for entry in data]


def apply_baseline(findings: list[Finding],
                   baseline: list[tuple[str, str, str]]
                   ) -> tuple[list[Finding], list[tuple[str, str, str]]]:
    """Split findings into (new, unmatched-baseline-entries).  Matching is
    multiset-aware: N baselined copies of a fingerprint absorb N findings."""
    budget: dict[tuple[str, str, str], int] = {}
    for fp in baseline:
        budget[fp] = budget.get(fp, 0) + 1
    new: list[Finding] = []
    for f in findings:
        fp = f.fingerprint()
        if budget.get(fp, 0) > 0:
            budget[fp] -= 1
        else:
            new.append(f)
    stale = [fp for fp, n in budget.items() for _ in range(n)]
    return new, stale


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="slate-lint",
        description="AST lint for trace-safety, collective discipline, "
                    "and policy-seam contracts (pure stdlib, no jax).")
    ap.add_argument("--root", default=None,
                    help="repo root (default: parent of tools/)")
    ap.add_argument("--format", choices=("human", "json"), default="human")
    ap.add_argument("--select", default=None,
                    help="comma-separated rule ids to run (default: all)")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline file (default: {DEFAULT_BASELINE.name} "
                         f"next to the package)")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline from current findings and "
                         "exit 0")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--cache", default=None,
                    help="findings cache file (default: $SLATE_LINT_CACHE; "
                         "unset disables).  Full runs against an unchanged "
                         "tree replay from it instead of re-analyzing")
    ap.add_argument("--changed-only", action="store_true",
                    help="report only findings in files changed vs git "
                         "HEAD (plus untracked); analysis stays "
                         "whole-project")
    ap.add_argument("--output", default=None,
                    help="also write the JSON report to this file "
                         "(CI artifact), regardless of --format")
    args = ap.parse_args(argv)

    registry = load_rules()
    if args.list_rules:
        for rule_id, rule in sorted(registry.items()):
            print(f"{rule_id}  {rule.summary}")
        return 0

    root = Path(args.root) if args.root else \
        Path(__file__).resolve().parents[2]
    select = None
    if args.select:
        select = {s.strip() for s in args.select.split(",") if s.strip()}
        unknown = select - registry.keys()
        if unknown:
            print(f"unknown rule ids: {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2

    project = load_project(root)

    # full-run findings cache: sound to replay only when select is None
    # (the cached list IS the full surface) and every file hash matches
    cache_arg = args.cache or os.environ.get("SLATE_LINT_CACHE") or None
    cache_path = Path(cache_arg) if cache_arg else None
    full_run = select is None and not args.update_baseline
    findings = None
    if cache_path is not None and full_run:
        findings = fscache.load(cache_path, project, registry.keys())
    cached = findings is not None
    if findings is None:
        findings = run_rules(project, select)
        if cache_path is not None and full_run:
            fscache.store(cache_path, project, registry.keys(), findings)

    baseline_path = Path(args.baseline) if args.baseline else DEFAULT_BASELINE
    if args.update_baseline:
        baseline_path.write_text(json.dumps(
            [list(f.fingerprint()) for f in findings], indent=1) + "\n")
        print(f"baseline updated: {len(findings)} finding(s) -> "
              f"{baseline_path}")
        return 0

    baseline = read_baseline(baseline_path)
    new, stale = apply_baseline(findings, baseline)

    shown = new
    if args.changed_only:
        changed = changed_files(root)
        if changed is None:
            print("slate-lint: --changed-only: git unavailable, "
                  "reporting all findings", file=sys.stderr)
        else:
            shown = [f for f in new if f.path in changed]

    report = {
        "findings": [f.to_json() for f in shown],
        "baselined": len(findings) - len(new),
        "stale_baseline": [list(fp) for fp in stale],
        "rules": sorted(registry if select is None else select),
        "files": len(project.modules),
        "changed_only": bool(args.changed_only),
        "cached": cached,
    }
    if args.output:
        Path(args.output).write_text(json.dumps(report, indent=1) + "\n")

    if args.format == "json":
        print(json.dumps(report, indent=1))
        return 1 if shown else 0

    for f in shown:
        print(f.render())
    if stale:
        print(f"note: {len(stale)} stale baseline entr"
              f"{'y' if len(stale) == 1 else 'ies'} no longer fire "
              f"(run --update-baseline)", file=sys.stderr)
    if args.changed_only and len(shown) != len(new):
        print(f"note: {len(new) - len(shown)} finding(s) outside the "
              f"changed file set not shown", file=sys.stderr)
    if shown:
        print(f"\nslate-lint: {len(shown)} finding(s) "
              f"({len(findings) - len(new)} baselined)", file=sys.stderr)
        return 1
    print(f"slate-lint OK: {len(registry) if select is None else len(select)}"
          f" rule(s), {len(project.modules)} file(s), "
          f"{len(findings) - len(new)} baselined finding(s)")
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
