"""slate-lint CLI.

Usage::

    python -m tools.slate_lint [--root DIR] [--format human|json]
                               [--select RULES] [--baseline FILE]
                               [--update-baseline] [--list-rules]

Exit codes: 0 clean (no findings outside the baseline), 1 findings,
2 usage / internal error.

The baseline is a JSON list of line-free fingerprints
``[rule, path, message]`` — known findings that are tolerated but must
not grow.  ``--update-baseline`` rewrites it from the current findings;
the checked-in ``tools/slate_lint/baseline.json`` is empty and the repo
is expected to stay clean (suppress intentional sites inline with a
reason instead of baselining them).
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from .loader import load_project
from .model import REGISTRY, Finding

DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.json"


def load_rules():
    from . import rules  # noqa: F401  (populates REGISTRY on import)
    return REGISTRY


def run_rules(project, select: set[str] | None = None) -> list[Finding]:
    registry = load_rules()
    findings: list[Finding] = []
    for rule_id, rule in registry.items():
        if select is not None and rule_id not in select:
            continue
        for f in rule.run(project):
            mod = project.module(f.path)
            if mod is not None and mod.suppressed(f.line, f.rule):
                continue
            findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return findings


def read_baseline(path: Path) -> list[tuple[str, str, str]]:
    if not path.exists():
        return []
    data = json.loads(path.read_text() or "[]")
    return [tuple(entry) for entry in data]


def apply_baseline(findings: list[Finding],
                   baseline: list[tuple[str, str, str]]
                   ) -> tuple[list[Finding], list[tuple[str, str, str]]]:
    """Split findings into (new, unmatched-baseline-entries).  Matching is
    multiset-aware: N baselined copies of a fingerprint absorb N findings."""
    budget: dict[tuple[str, str, str], int] = {}
    for fp in baseline:
        budget[fp] = budget.get(fp, 0) + 1
    new: list[Finding] = []
    for f in findings:
        fp = f.fingerprint()
        if budget.get(fp, 0) > 0:
            budget[fp] -= 1
        else:
            new.append(f)
    stale = [fp for fp, n in budget.items() for _ in range(n)]
    return new, stale


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="slate-lint",
        description="AST lint for trace-safety, collective discipline, "
                    "and policy-seam contracts (pure stdlib, no jax).")
    ap.add_argument("--root", default=None,
                    help="repo root (default: parent of tools/)")
    ap.add_argument("--format", choices=("human", "json"), default="human")
    ap.add_argument("--select", default=None,
                    help="comma-separated rule ids to run (default: all)")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline file (default: {DEFAULT_BASELINE.name} "
                         f"next to the package)")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline from current findings and "
                         "exit 0")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    registry = load_rules()
    if args.list_rules:
        for rule_id, rule in sorted(registry.items()):
            print(f"{rule_id}  {rule.summary}")
        return 0

    root = Path(args.root) if args.root else \
        Path(__file__).resolve().parents[2]
    select = None
    if args.select:
        select = {s.strip() for s in args.select.split(",") if s.strip()}
        unknown = select - registry.keys()
        if unknown:
            print(f"unknown rule ids: {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2

    project = load_project(root)
    findings = run_rules(project, select)

    baseline_path = Path(args.baseline) if args.baseline else DEFAULT_BASELINE
    if args.update_baseline:
        baseline_path.write_text(json.dumps(
            [list(f.fingerprint()) for f in findings], indent=1) + "\n")
        print(f"baseline updated: {len(findings)} finding(s) -> "
              f"{baseline_path}")
        return 0

    baseline = read_baseline(baseline_path)
    new, stale = apply_baseline(findings, baseline)

    if args.format == "json":
        print(json.dumps({
            "findings": [f.to_json() for f in new],
            "baselined": len(findings) - len(new),
            "stale_baseline": [list(fp) for fp in stale],
        }, indent=1))
        return 1 if new else 0

    for f in new:
        print(f.render())
    if stale:
        print(f"note: {len(stale)} stale baseline entr"
              f"{'y' if len(stale) == 1 else 'ies'} no longer fire "
              f"(run --update-baseline)", file=sys.stderr)
    if new:
        print(f"\nslate-lint: {len(new)} finding(s) "
              f"({len(findings) - len(new)} baselined)", file=sys.stderr)
        return 1
    print(f"slate-lint OK: {len(registry) if select is None else len(select)}"
          f" rule(s), {len(project.modules)} file(s), "
          f"{len(findings) - len(new)} baselined finding(s)")
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
