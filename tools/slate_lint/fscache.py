"""Findings cache keyed on per-file content hashes.

The expensive part of a lint run is the interprocedural analysis
(reachability + call graph + taint fixpoint), and its result for ONE
file can change when ANOTHER file changes — a callee's return taint, a
dispatch table, an ``__init__.py`` re-export, a lock-registry edit.  A
per-file *replay* would therefore be unsound.  The cache instead stores
the sha256 of every scanned file plus the rule surface, and replays the
complete findings list only when EVERY hash matches and the file set
and rule set are identical.  Any drift at all means a full re-analysis
(which then refreshes the cache).  This is exactly the CI shape: the
common re-run against an unchanged tree is O(hashing) instead of
O(analysis), and no correctness is traded for it.

Only full runs are cached: ``--select`` subsets and baseline updates
bypass the cache entirely (their findings lists are not the full
surface and must never be replayed as if they were).
"""

from __future__ import annotations

import hashlib
import json
from pathlib import Path

from .loader import Project
from .model import Finding

SCHEMA = 1


def file_digests(project: Project) -> dict[str, str]:
    """``rel -> sha256(content)`` for every scanned module."""
    return {rel: hashlib.sha256(m.text.encode("utf-8")).hexdigest()
            for rel, m in sorted(project.modules.items())}


def cache_key(project: Project, rule_ids) -> dict:
    return {"schema": SCHEMA, "rules": sorted(rule_ids),
            "files": file_digests(project)}


def load(path: Path, project: Project, rule_ids) -> list[Finding] | None:
    """The cached full-run findings, or None on any mismatch/corruption."""
    try:
        data = json.loads(Path(path).read_text())
    except (OSError, ValueError):
        return None
    if not isinstance(data, dict) or \
            data.get("key") != cache_key(project, rule_ids):
        return None
    try:
        return [Finding(f["rule"], f["path"], int(f["line"]), f["message"])
                for f in data["findings"]]
    except (KeyError, TypeError, ValueError):
        return None


def store(path: Path, project: Project, rule_ids,
          findings: list[Finding]) -> None:
    """Best-effort write; an unwritable cache never fails the run."""
    payload = {"key": cache_key(project, rule_ids),
               "findings": [f.to_json() for f in findings]}
    try:
        Path(path).write_text(json.dumps(payload, indent=1) + "\n")
    except OSError:
        pass
