"""Core data model: findings, the rule registry, suppressions.

A *rule* is a plugin: a class with an ``id``, a one-line ``summary``, and
a ``run(project)`` generator of :class:`Finding`.  Rules register
themselves with the :func:`register` decorator; the engine discovers them
through :data:`REGISTRY` (populated by importing ``tools.slate_lint.rules``).

Suppressions are per-line comments::

    x = risky()  # slate-lint: disable=TRC001 -- trace-time shape probe

A standalone suppression comment (a line that is only the comment)
applies to the next statement line instead, so long call chains can be
annotated without breaking the line.  The ``-- reason`` tail is required
policy for intentional suppressions (docs/STATIC_ANALYSIS.md) but not
enforced syntactically.
"""

from __future__ import annotations

import dataclasses
import re

SUPPRESS_RE = re.compile(
    r"#\s*slate-lint:\s*disable=([A-Za-z0-9_,\s]+?)\s*(?:--\s*(?P<reason>.*))?$"
)


@dataclasses.dataclass(frozen=True)
class Finding:
    """One diagnostic: rule id, repo-relative posix path, 1-based line.

    ``legacy`` carries the exact report text of the pre-slate_lint
    ``tools/check_error_contracts.py`` for the migrated seam rules, so the
    shim can reproduce its output byte-for-byte.
    """

    rule: str
    path: str
    line: int
    message: str
    legacy: str | None = None

    def fingerprint(self) -> tuple[str, str, str]:
        """Line-free identity used for baseline matching — stable across
        unrelated edits that only shift line numbers."""
        return (self.rule, self.path, self.message)

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"

    def to_json(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message}


class Rule:
    """Base class for rule plugins.  Subclasses set ``id`` and ``summary``
    and implement ``run``."""

    id: str = ""
    summary: str = ""

    def run(self, project):  # pragma: no cover - interface
        raise NotImplementedError
        yield


#: rule id -> Rule instance, in registration order
REGISTRY: dict[str, Rule] = {}


def register(cls: type[Rule]) -> type[Rule]:
    """Class decorator adding a rule to :data:`REGISTRY`."""
    inst = cls()
    if not inst.id:
        raise ValueError(f"rule {cls.__name__} has no id")
    if inst.id in REGISTRY:
        raise ValueError(f"duplicate rule id {inst.id}")
    REGISTRY[inst.id] = inst
    return cls


def parse_suppressions(comment_lines: list[tuple[int, str, bool]]
                       ) -> dict[int, set[str]]:
    """Map line numbers to the rule ids suppressed there.

    ``comment_lines`` is ``(lineno, comment_text, standalone)`` per comment
    token; a standalone comment suppresses the following line as well (the
    next physical line — put standalone suppressions directly above the
    statement they target).
    """
    out: dict[int, set[str]] = {}
    for lineno, text, standalone in comment_lines:
        m = SUPPRESS_RE.search(text)
        if not m:
            continue
        rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
        out.setdefault(lineno, set()).update(rules)
        if standalone:
            out.setdefault(lineno + 1, set()).update(rules)
    return out
