"""Collective-discipline rules (COL0xx).

The mesh contract (SURVEY §runtime, core/grid.py): there is exactly one
axis vocabulary — the ``AXIS_*`` constants in ``slate_tpu/core/grid.py``
(``AXIS_P = "p"``, ``AXIS_Q = "q"``), the names every ``Mesh`` in the
framework is built with.  Collectives must name axes through those
constants (or through a parameter of a generic wrapper, the
``comm/collectives.py`` pattern) so a rename in grid.py cannot silently
strand a ``psum`` on a dead axis name.

Rules:

- **COL001** — a collective names an axis the analyzer cannot tie to the
  mesh vocabulary (unknown name, non-vocabulary literal, computed expr).
- **COL002** — a collective hard-codes a vocabulary axis name as a string
  literal ("p"/"q") instead of the AXIS_* constant: works today, drifts
  silently when grid.py is renamed.
- **COL003** — a collective appears under exactly one branch of a
  ``lax.cond``/``lax.switch``: if the predicate is not mesh-uniform the
  ranks that take the other branch never enter the collective and the
  mesh deadlocks.  Mesh-uniform predicates (a replicated fori_loop bound)
  are legitimate — suppress with a reason stating WHY the predicate is
  uniform.
- **COL004** — ``io_callback``/``pure_callback`` outside the registered
  fault-consumption module (robust/faults.py): host callbacks are
  ordering hazards inside collective programs and are allowed only at
  the audited fault-injection seam.
"""

from __future__ import annotations

import ast

from .. import reachability
from ..model import Finding, Rule, register

#: lax collective primitives (and the repo's comm/collectives.py wrappers)
#: -> positional index of the axis-name arg
COLLECTIVE_AXIS_ARG = {
    "psum": 1, "pmax": 1, "pmin": 1, "pmean": 1, "ppermute": 1,
    "all_gather": 1, "psum_scatter": 1, "all_to_all": 1, "axis_index": 0,
    "pbroadcast": 1, "pvary": 1,
    # comm/collectives.py wrappers: the axis flows through verbatim
    "bcast_along": 2, "reduce_along": 1, "reduce_scatter_along": 1,
    "allgather_along": 1, "pargmax": 2, "ppermute_shift": 1,
}
#: functions treated as collectives for branch-divergence purposes
COLLECTIVE_NAMES = set(COLLECTIVE_AXIS_ARG)
#: host-callback callables restricted by COL004
CALLBACK_NAMES = {"io_callback", "pure_callback"}
#: the registered fault-consumption module (the only callback seam)
ALLOWED_CALLBACK_MODULES = {"slate_tpu/robust/faults.py"}
#: where the axis vocabulary lives
GRID_MODULE_SUFFIX = "core/grid.py"

_OK, _LITERAL, _UNKNOWN_LITERAL, _UNKNOWN = range(4)


def axis_vocabulary(project) -> tuple[str | None, dict[str, str]]:
    """(grid module dotted name, {AXIS_CONST -> "name"}) read from the
    project's core/grid.py AST."""
    if "axis_vocab" in project.cache:
        return project.cache["axis_vocab"]
    dotted, consts = None, {}
    for rel, mod in project.modules.items():
        if not rel.endswith(GRID_MODULE_SUFFIX):
            continue
        dotted = mod.dotted
        for node in mod.tree.body:
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Constant) and \
                    isinstance(node.value.value, str):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id.startswith("AXIS_"):
                        consts[t.id] = node.value.value
        break
    project.cache["axis_vocab"] = (dotted, consts)
    return dotted, consts


def _collective_call(node: ast.Call) -> str | None:
    f = node.func
    name = (f.id if isinstance(f, ast.Name)
            else f.attr if isinstance(f, ast.Attribute) else None)
    return name if name in COLLECTIVE_NAMES else None


def _axis_expr(node: ast.Call, name: str) -> ast.AST | None:
    for kw in node.keywords:
        if kw.arg == "axis_name":
            return kw.value
    idx = COLLECTIVE_AXIS_ARG[name]
    if len(node.args) > idx:
        return node.args[idx]
    return None


class _AxisClassifier:
    """Classify an axis-name expression at a call site."""

    def __init__(self, project, reach, info: reachability.FuncInfo | None,
                 rel: str):
        self.reach = reach
        self.rel = rel
        self.info = info
        self.grid_dotted, self.consts = axis_vocabulary(project)
        self.vocab = set(self.consts.values())
        # one-level local env: names assigned directly from an AXIS_*
        # constant inside the enclosing function chain count as OK
        self.local_ok: set[str] = set()
        fn = info
        while fn is not None:
            for n in reachability.own_nodes(fn.node):
                if isinstance(n, ast.Assign) and self._is_const(n.value):
                    for t in n.targets:
                        if isinstance(t, ast.Name):
                            self.local_ok.add(t.id)
            fn = fn.parent

    def _is_const(self, expr: ast.AST) -> bool:
        """Is ``expr`` a reference to a vocabulary AXIS_* constant?"""
        if isinstance(expr, ast.Name):
            if expr.id in self.consts and \
                    self.rel.endswith(GRID_MODULE_SUFFIX):
                return True  # inside grid.py itself
            dotted = self.reach.imports.get(self.rel, {}).get(expr.id)
            return bool(
                dotted and self.grid_dotted
                and dotted.startswith(self.grid_dotted + ".")
                and dotted.rsplit(".", 1)[1] in self.consts)
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name):
            dotted = self.reach.imports.get(self.rel, {}).get(expr.value.id)
            return bool(dotted == self.grid_dotted
                        and expr.attr in self.consts)
        return False

    def _is_param(self, name: str) -> bool:
        fn = self.info
        while fn is not None:
            if any(a.arg == name for a in fn.params()):
                return True
            fn = fn.parent
        return False

    def classify(self, expr: ast.AST) -> int:
        if isinstance(expr, (ast.Tuple, ast.List)):
            kinds = [self.classify(e) for e in expr.elts]
            return max(kinds, default=_UNKNOWN)
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            return _LITERAL if expr.value in self.vocab else _UNKNOWN_LITERAL
        if self._is_const(expr):
            return _OK
        if isinstance(expr, ast.Name):
            if self._is_param(expr.id) or expr.id in self.local_ok:
                return _OK
            return _UNKNOWN
        return _UNKNOWN


def _iter_function_scopes(project):
    """(scope FuncInfo or None, module) covering every node exactly once."""
    reach = reachability.compute(project)
    for key in sorted(reach.functions):
        yield reach, reach.functions[key], reach.functions[key].module
    for rel in sorted(project.modules):
        yield reach, None, project.modules[rel]


def _scope_nodes(scope, module):
    root = scope.node if scope is not None else module.tree
    return reachability.own_nodes(root)


@register
class AxisNameUnknown(Rule):
    id = "COL001"
    summary = ("collective names an axis not tied to the mesh vocabulary "
               "in core/grid.py (unknown name, computed expr, or "
               "non-vocabulary literal)")

    def run(self, project):
        for reach, scope, module in _iter_function_scopes(project):
            clf = None
            for node in _scope_nodes(scope, module):
                if not isinstance(node, ast.Call):
                    continue
                cname = _collective_call(node)
                if cname is None:
                    continue
                axis = _axis_expr(node, cname)
                if axis is None:
                    continue
                if clf is None:
                    clf = _AxisClassifier(project, reach, scope, module.rel)
                if clf.classify(axis) in (_UNKNOWN, _UNKNOWN_LITERAL):
                    yield Finding(
                        self.id, module.rel, node.lineno,
                        f"`{cname}` names an axis the analyzer cannot tie "
                        f"to the mesh axis vocabulary "
                        f"({sorted(clf.vocab) or 'none found'}) — use the "
                        f"AXIS_* constants from core/grid.py or a "
                        f"parameter of a generic wrapper")


@register
class AxisNameLiteral(Rule):
    id = "COL002"
    summary = ("collective hard-codes a mesh axis name as a string "
               "literal — use the AXIS_* constants from core/grid.py")

    def run(self, project):
        for reach, scope, module in _iter_function_scopes(project):
            clf = None
            for node in _scope_nodes(scope, module):
                if not isinstance(node, ast.Call):
                    continue
                cname = _collective_call(node)
                if cname is None:
                    continue
                axis = _axis_expr(node, cname)
                if axis is None:
                    continue
                if clf is None:
                    clf = _AxisClassifier(project, reach, scope, module.rel)
                if clf.classify(axis) == _LITERAL:
                    yield Finding(
                        self.id, module.rel, node.lineno,
                        f"`{cname}` hard-codes the axis name — a literal "
                        f"matches the mesh today but drifts silently if "
                        f"core/grid.py renames it; use AXIS_P/AXIS_Q")


class _CollectiveReach:
    """Transitive does-this-function-execute-a-collective memo."""

    def __init__(self, reach):
        self.reach = reach
        self.memo: dict[str, bool] = {}

    def contains(self, key: str) -> bool:
        if key in self.memo:
            return self.memo[key]
        self.memo[key] = False  # cycle guard
        info = self.reach.functions.get(key)
        if info is None:
            return False
        direct = any(
            isinstance(n, ast.Call) and _collective_call(n)
            for n in reachability.own_nodes(info.node))
        result = direct or any(
            self.contains(t)
            for t in (info.resolved_calls | info.resolved_refs
                      | {c.key for c in info.children.values()}))
        self.memo[key] = result
        return result

    def branch_has(self, expr: ast.AST, scope, rel: str) -> bool | None:
        """Does a branch callable execute a collective?  None: can't tell."""
        if isinstance(expr, ast.Lambda):
            if any(isinstance(n, ast.Call) and _collective_call(n)
                   for n in ast.walk(expr)):
                return True
            for n in ast.walk(expr):
                if isinstance(n, ast.Call):
                    t = self.reach.resolve_call_target(n, scope, rel)
                    if t and self.contains(t):
                        return True
            return False
        if isinstance(expr, ast.Name):
            t = self.reach.resolve_name(expr.id, scope, rel)
            return self.contains(t) if t else None
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name):
            t = self.reach.resolve_attr(expr.value.id, expr.attr, rel)
            return self.contains(t) if t else None
        return None


@register
class CollectiveUnderCond(Rule):
    id = "COL003"
    summary = ("collective under exactly one branch of lax.cond/"
               "lax.switch — a non-uniform predicate deadlocks the mesh")

    def run(self, project):
        reach = reachability.compute(project)
        creach = _CollectiveReach(reach)
        for _, scope, module in _iter_function_scopes(project):
            for node in _scope_nodes(scope, module):
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                name = (f.id if isinstance(f, ast.Name)
                        else f.attr if isinstance(f, ast.Attribute)
                        else None)
                branches: list[ast.AST] = []
                if name == "cond" and len(node.args) >= 3:
                    branches = [node.args[1], node.args[2]]
                elif name == "switch" and len(node.args) >= 2 and \
                        isinstance(node.args[1], (ast.List, ast.Tuple)):
                    branches = list(node.args[1].elts)
                if len(branches) < 2:
                    continue
                has = [creach.branch_has(b, scope, module.rel)
                       for b in branches]
                if None in has:
                    continue  # unresolvable branch: stay silent
                if any(has) and not all(has):
                    yield Finding(
                        self.id, module.rel, node.lineno,
                        f"collective under one branch of `{name}` but not "
                        f"the other(s) — ranks taking the collective-free "
                        f"branch would deadlock the mesh unless the "
                        f"predicate is replicated-uniform; restructure, "
                        f"or suppress stating why the predicate is "
                        f"uniform on every rank")


@register
class CallbackOutsideFaultSeam(Rule):
    id = "COL004"
    summary = ("io_callback/pure_callback outside the registered "
               "fault-consumption seam (robust/faults.py)")

    def run(self, project):
        for rel in sorted(project.modules):
            if rel in ALLOWED_CALLBACK_MODULES:
                continue
            module = project.modules[rel]
            for node in ast.walk(module.tree):
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                name = (f.id if isinstance(f, ast.Name)
                        else f.attr if isinstance(f, ast.Attribute)
                        else None)
                if name in CALLBACK_NAMES:
                    yield Finding(
                        self.id, rel, node.lineno,
                        f"`{name}` outside robust/faults.py — host "
                        f"callbacks are restricted to the registered "
                        f"fault-consumption sites so ordering and retrace "
                        f"semantics stay auditable in one place")
