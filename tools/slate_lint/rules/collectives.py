"""Collective-discipline rules (COL0xx).

The mesh contract (SURVEY §runtime, core/grid.py): there is exactly one
axis vocabulary — the ``AXIS_*`` constants in ``slate_tpu/core/grid.py``
(``AXIS_P = "p"``, ``AXIS_Q = "q"``), the names every ``Mesh`` in the
framework is built with.  Collectives must name axes through those
constants (or through a parameter of a generic wrapper, the
``comm/collectives.py`` pattern) so a rename in grid.py cannot silently
strand a ``psum`` on a dead axis name.

Rules:

- **COL001** — a collective names an axis the analyzer cannot tie to the
  mesh vocabulary (unknown name, non-vocabulary literal, computed expr).
- **COL002** — a collective hard-codes a vocabulary axis name as a string
  literal ("p"/"q") instead of the AXIS_* constant: works today, drifts
  silently when grid.py is renamed.
- **COL003** — a collective appears under exactly one branch of a
  ``lax.cond``/``lax.switch``: if the predicate is not mesh-uniform the
  ranks that take the other branch never enter the collective and the
  mesh deadlocks.  Mesh-uniform predicates (a replicated fori_loop bound)
  are legitimate — suppress with a reason stating WHY the predicate is
  uniform.
- **COL004** — ``io_callback``/``pure_callback`` outside the registered
  fault-consumption module (robust/faults.py): host callbacks are
  ordering hazards inside collective programs and are allowed only at
  the audited fault-injection seam.

Collective-sequence abstract interpretation (COL005-COL008): for every
scope the analyzer computes the *abstract collective sequence* — the
source-ordered tree of ``(op, axis)`` events a rank executes, with
``cond`` alternatives and loop bodies kept structural and resolvable
calls (including dict-dispatch and re-export edges) spliced inline.
Ranks of an SPMD mesh deadlock exactly when their sequences diverge, so:

- **COL005** — a collective reachable under a ``lax.cond``/``switch``
  whose predicate derives from TRACED data (interprocedural taint):
  unless the predicate is replicated-uniform, ranks disagree on the
  branch and the collective is entered by a subset of the mesh.
- **COL006** — ``lax.cond``/``switch`` branches that BOTH execute
  collectives but in differing sequences: even a uniform predicate
  cannot save mismatched orders across program versions of one rank
  pairing with another (COL003 owns the some-branch-has-none case).
- **COL007** — a collective inside a loop whose trip count can depend
  on traced data: any ``lax.while_loop`` (its trip count is data-driven
  by construction), or a ``lax.fori_loop`` whose bounds are tainted.
  Ranks that disagree on the trip count execute different collective
  counts and deadlock.
- **COL008** — two ``ppermute``-family sites in one scope on the same
  axis with *different known ring shifts*: a double-buffered pipeline
  must send along ONE consistent ring or the send/recv partners never
  pair up.  Shifts are read from ``ppermute_shift(..., shift=K, ...)``
  constants or the ``[(i, (i +/- K) %% size) ...]`` comprehension idiom;
  unknown shifts stay silent.
"""

from __future__ import annotations

import ast

from .. import dataflow, reachability
from ..model import Finding, Rule, register

#: lax collective primitives (and the repo's comm/collectives.py wrappers)
#: -> positional index of the axis-name arg
COLLECTIVE_AXIS_ARG = {
    "psum": 1, "pmax": 1, "pmin": 1, "pmean": 1, "ppermute": 1,
    "all_gather": 1, "psum_scatter": 1, "all_to_all": 1, "axis_index": 0,
    "pbroadcast": 1, "pvary": 1,
    # comm/collectives.py wrappers: the axis flows through verbatim
    "bcast_along": 2, "reduce_along": 1, "reduce_scatter_along": 1,
    "allgather_along": 1, "pargmax": 2, "ppermute_shift": 1,
}
#: functions treated as collectives for branch-divergence purposes
COLLECTIVE_NAMES = set(COLLECTIVE_AXIS_ARG)
#: host-callback callables restricted by COL004
CALLBACK_NAMES = {"io_callback", "pure_callback"}
#: the registered fault-consumption module (the only callback seam)
ALLOWED_CALLBACK_MODULES = {"slate_tpu/robust/faults.py"}
#: where the axis vocabulary lives
GRID_MODULE_SUFFIX = "core/grid.py"

_OK, _LITERAL, _UNKNOWN_LITERAL, _UNKNOWN = range(4)


def axis_vocabulary(project) -> tuple[str | None, dict[str, str]]:
    """(grid module dotted name, {AXIS_CONST -> "name"}) read from the
    project's core/grid.py AST."""
    if "axis_vocab" in project.cache:
        return project.cache["axis_vocab"]
    dotted, consts = None, {}
    for rel, mod in project.modules.items():
        if not rel.endswith(GRID_MODULE_SUFFIX):
            continue
        dotted = mod.dotted
        for node in mod.tree.body:
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Constant) and \
                    isinstance(node.value.value, str):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id.startswith("AXIS_"):
                        consts[t.id] = node.value.value
        break
    project.cache["axis_vocab"] = (dotted, consts)
    return dotted, consts


def _collective_call(node: ast.Call) -> str | None:
    f = node.func
    name = (f.id if isinstance(f, ast.Name)
            else f.attr if isinstance(f, ast.Attribute) else None)
    return name if name in COLLECTIVE_NAMES else None


def _axis_expr(node: ast.Call, name: str) -> ast.AST | None:
    for kw in node.keywords:
        if kw.arg == "axis_name":
            return kw.value
    idx = COLLECTIVE_AXIS_ARG[name]
    if len(node.args) > idx:
        return node.args[idx]
    return None


class _AxisClassifier:
    """Classify an axis-name expression at a call site."""

    def __init__(self, project, reach, info: reachability.FuncInfo | None,
                 rel: str):
        self.reach = reach
        self.rel = rel
        self.info = info
        self.grid_dotted, self.consts = axis_vocabulary(project)
        self.vocab = set(self.consts.values())
        # one-level local env: names assigned directly from an AXIS_*
        # constant inside the enclosing function chain count as OK
        self.local_ok: set[str] = set()
        fn = info
        while fn is not None:
            for n in reachability.own_nodes(fn.node):
                if isinstance(n, ast.Assign) and self._is_const(n.value):
                    for t in n.targets:
                        if isinstance(t, ast.Name):
                            self.local_ok.add(t.id)
            fn = fn.parent

    def _is_const(self, expr: ast.AST) -> bool:
        """Is ``expr`` a reference to a vocabulary AXIS_* constant?"""
        if isinstance(expr, ast.Name):
            if expr.id in self.consts and \
                    self.rel.endswith(GRID_MODULE_SUFFIX):
                return True  # inside grid.py itself
            dotted = self.reach.imports.get(self.rel, {}).get(expr.id)
            return bool(
                dotted and self.grid_dotted
                and dotted.startswith(self.grid_dotted + ".")
                and dotted.rsplit(".", 1)[1] in self.consts)
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name):
            dotted = self.reach.imports.get(self.rel, {}).get(expr.value.id)
            return bool(dotted == self.grid_dotted
                        and expr.attr in self.consts)
        return False

    def _is_param(self, name: str) -> bool:
        fn = self.info
        while fn is not None:
            if any(a.arg == name for a in fn.params()):
                return True
            fn = fn.parent
        return False

    def classify(self, expr: ast.AST) -> int:
        if isinstance(expr, (ast.Tuple, ast.List)):
            kinds = [self.classify(e) for e in expr.elts]
            return max(kinds, default=_UNKNOWN)
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            return _LITERAL if expr.value in self.vocab else _UNKNOWN_LITERAL
        if self._is_const(expr):
            return _OK
        if isinstance(expr, ast.Name):
            if self._is_param(expr.id) or expr.id in self.local_ok:
                return _OK
            return _UNKNOWN
        return _UNKNOWN

    def normalize(self, expr: ast.AST | None) -> str:
        """Stable string form of an axis expr for sequence comparison:
        vocabulary constants and literals collapse to the axis name,
        parameters/locals to a symbolic ``$name``, anything else "?"."""
        if expr is None:
            return "?"
        if isinstance(expr, (ast.Tuple, ast.List)):
            return "(" + ",".join(self.normalize(e)
                                  for e in expr.elts) + ")"
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            return expr.value
        if self._is_const(expr):
            if isinstance(expr, ast.Attribute):
                return self.consts.get(expr.attr, "?")
            if self.rel.endswith(GRID_MODULE_SUFFIX) and \
                    expr.id in self.consts:
                return self.consts[expr.id]
            dotted = self.reach.imports.get(self.rel, {}).get(expr.id, "")
            return self.consts.get(dotted.rsplit(".", 1)[-1], "?")
        if isinstance(expr, ast.Name):
            return "$" + expr.id
        return "?"


def _iter_function_scopes(project):
    """(scope FuncInfo or None, module) covering every node exactly once."""
    reach = reachability.compute(project)
    for key in sorted(reach.functions):
        yield reach, reach.functions[key], reach.functions[key].module
    for rel in sorted(project.modules):
        yield reach, None, project.modules[rel]


def _scope_nodes(scope, module):
    root = scope.node if scope is not None else module.tree
    return reachability.own_nodes(root)


@register
class AxisNameUnknown(Rule):
    id = "COL001"
    summary = ("collective names an axis not tied to the mesh vocabulary "
               "in core/grid.py (unknown name, computed expr, or "
               "non-vocabulary literal)")

    def run(self, project):
        for reach, scope, module in _iter_function_scopes(project):
            clf = None
            for node in _scope_nodes(scope, module):
                if not isinstance(node, ast.Call):
                    continue
                cname = _collective_call(node)
                if cname is None:
                    continue
                axis = _axis_expr(node, cname)
                if axis is None:
                    continue
                if clf is None:
                    clf = _AxisClassifier(project, reach, scope, module.rel)
                if clf.classify(axis) in (_UNKNOWN, _UNKNOWN_LITERAL):
                    yield Finding(
                        self.id, module.rel, node.lineno,
                        f"`{cname}` names an axis the analyzer cannot tie "
                        f"to the mesh axis vocabulary "
                        f"({sorted(clf.vocab) or 'none found'}) — use the "
                        f"AXIS_* constants from core/grid.py or a "
                        f"parameter of a generic wrapper")


@register
class AxisNameLiteral(Rule):
    id = "COL002"
    summary = ("collective hard-codes a mesh axis name as a string "
               "literal — use the AXIS_* constants from core/grid.py")

    def run(self, project):
        for reach, scope, module in _iter_function_scopes(project):
            clf = None
            for node in _scope_nodes(scope, module):
                if not isinstance(node, ast.Call):
                    continue
                cname = _collective_call(node)
                if cname is None:
                    continue
                axis = _axis_expr(node, cname)
                if axis is None:
                    continue
                if clf is None:
                    clf = _AxisClassifier(project, reach, scope, module.rel)
                if clf.classify(axis) == _LITERAL:
                    yield Finding(
                        self.id, module.rel, node.lineno,
                        f"`{cname}` hard-codes the axis name — a literal "
                        f"matches the mesh today but drifts silently if "
                        f"core/grid.py renames it; use AXIS_P/AXIS_Q")


class _CollectiveReach:
    """Transitive does-this-function-execute-a-collective memo."""

    def __init__(self, reach):
        self.reach = reach
        self.memo: dict[str, bool] = {}

    def contains(self, key: str) -> bool:
        if key in self.memo:
            return self.memo[key]
        self.memo[key] = False  # cycle guard
        info = self.reach.functions.get(key)
        if info is None:
            return False
        direct = any(
            isinstance(n, ast.Call) and _collective_call(n)
            for n in reachability.own_nodes(info.node))
        result = direct or any(
            self.contains(t)
            for t in (info.resolved_calls | info.resolved_refs
                      | {c.key for c in info.children.values()}))
        self.memo[key] = result
        return result

    def branch_has(self, expr: ast.AST, scope, rel: str) -> bool | None:
        """Does a branch callable execute a collective?  None: can't tell."""
        if isinstance(expr, ast.Lambda):
            if any(isinstance(n, ast.Call) and _collective_call(n)
                   for n in ast.walk(expr)):
                return True
            for n in ast.walk(expr):
                if isinstance(n, ast.Call):
                    t = self.reach.resolve_call_target(n, scope, rel)
                    if t and self.contains(t):
                        return True
            return False
        if isinstance(expr, ast.Name):
            t = self.reach.resolve_name(expr.id, scope, rel)
            return self.contains(t) if t else None
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name):
            t = self.reach.resolve_attr(expr.value.id, expr.attr, rel)
            return self.contains(t) if t else None
        return None


@register
class CollectiveUnderCond(Rule):
    id = "COL003"
    summary = ("collective under exactly one branch of lax.cond/"
               "lax.switch — a non-uniform predicate deadlocks the mesh")

    def run(self, project):
        reach = reachability.compute(project)
        creach = _CollectiveReach(reach)
        for _, scope, module in _iter_function_scopes(project):
            for node in _scope_nodes(scope, module):
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                name = (f.id if isinstance(f, ast.Name)
                        else f.attr if isinstance(f, ast.Attribute)
                        else None)
                branches: list[ast.AST] = []
                if name == "cond" and len(node.args) >= 3:
                    branches = [node.args[1], node.args[2]]
                elif name == "switch" and len(node.args) >= 2 and \
                        isinstance(node.args[1], (ast.List, ast.Tuple)):
                    branches = list(node.args[1].elts)
                if len(branches) < 2:
                    continue
                has = [creach.branch_has(b, scope, module.rel)
                       for b in branches]
                if None in has:
                    continue  # unresolvable branch: stay silent
                if any(has) and not all(has):
                    yield Finding(
                        self.id, module.rel, node.lineno,
                        f"collective under one branch of `{name}` but not "
                        f"the other(s) — ranks taking the collective-free "
                        f"branch would deadlock the mesh unless the "
                        f"predicate is replicated-uniform; restructure, "
                        f"or suppress stating why the predicate is "
                        f"uniform on every rank")


@register
class CallbackOutsideFaultSeam(Rule):
    id = "COL004"
    summary = ("io_callback/pure_callback outside the registered "
               "fault-consumption seam (robust/faults.py)")

    def run(self, project):
        for rel in sorted(project.modules):
            if rel in ALLOWED_CALLBACK_MODULES:
                continue
            module = project.modules[rel]
            for node in ast.walk(module.tree):
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                name = (f.id if isinstance(f, ast.Name)
                        else f.attr if isinstance(f, ast.Attribute)
                        else None)
                if name in CALLBACK_NAMES:
                    yield Finding(
                        self.id, rel, node.lineno,
                        f"`{name}` outside robust/faults.py — host "
                        f"callbacks are restricted to the registered "
                        f"fault-consumption sites so ordering and retrace "
                        f"semantics stay auditable in one place")


# --------------------------------------------------------------------------
# Collective-sequence abstract interpretation (COL005-COL008)
# --------------------------------------------------------------------------

def _call_name(node: ast.Call) -> str | None:
    f = node.func
    return (f.id if isinstance(f, ast.Name)
            else f.attr if isinstance(f, ast.Attribute) else None)


def _cond_branches(node: ast.Call) -> tuple[str | None, list[ast.AST]]:
    """(callee name, branch callables) for lax.cond/lax.switch calls."""
    name = _call_name(node)
    if name == "cond" and len(node.args) >= 3:
        return name, [node.args[1], node.args[2]]
    if name == "switch" and len(node.args) >= 2 and \
            isinstance(node.args[1], (ast.List, ast.Tuple)):
        return name, list(node.args[1].elts)
    return name, []


#: loop primitive -> positional indices of the body/cond callables
_LOOP_BODY_ARGS = {"fori_loop": (2,), "while_loop": (0, 1), "scan": (0,)}

#: ring-collective family checked by COL008
_PPERMUTE_FAMILY = {"ppermute", "ppermute_shift"}


class _SeqAnalyzer:
    """Abstract collective sequence of a scope, as a comparable tuple tree.

    Events: ``("c", op, axis)`` — one collective execution with its
    normalized axis; ``("cond", (seq, ...))`` — branch alternatives
    (lax.cond/switch and Python if, whose arms are static program
    versions); ``("loop", seq)`` — a repeated body; ``("?",)`` — an
    unresolvable branch callable; ``("cycle",)`` — recursion cut.
    Resolvable calls (incl. dispatch-table and re-export edges) splice
    the callee's sequence inline, memoized per function."""

    def __init__(self, project, reach):
        self.project = project
        self.reach = reach
        self.fn_memo: dict[str, tuple] = {}
        self._clfs: dict[str, _AxisClassifier] = {}

    def _clf(self, scope, module) -> _AxisClassifier:
        key = scope.key if scope is not None else f"{module.rel}::<module>"
        if key not in self._clfs:
            self._clfs[key] = _AxisClassifier(
                self.project, self.reach, scope, module.rel)
        return self._clfs[key]

    def of_function(self, key: str, stack: frozenset = frozenset()) -> tuple:
        if key in stack:
            return (("cycle",),)
        if key in self.fn_memo:
            return self.fn_memo[key]
        info = self.reach.functions.get(key)
        if info is None:
            return ()
        body = info.node.body
        if isinstance(body, list):
            seq = self._stmts(body, info, info.module, stack | {key})
        else:  # lambda-valued node
            seq = self._walk(body, info, info.module, stack | {key})
        self.fn_memo[key] = seq
        return seq

    def branch_seq(self, expr: ast.AST, scope, module,
                   stack: frozenset = frozenset()):
        """Sequence of a branch/body callable; None when unresolvable."""
        if isinstance(expr, ast.Lambda):
            return self._walk(expr.body, scope, module, stack)
        if isinstance(expr, ast.Call):
            # functools.partial(fn, ...): the wrapped fn's sequence
            if _call_name(expr) == "partial" and expr.args:
                return self.branch_seq(expr.args[0], scope, module, stack)
            return None
        key = None
        if isinstance(expr, ast.Name):
            key = self.reach.resolve_name(expr.id, scope, module.rel)
        elif isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name):
            key = self.reach.resolve_attr(expr.value.id, expr.attr,
                                          module.rel)
        if key:
            return self.of_function(key, stack)
        return None

    def _stmts(self, stmts, scope, module, stack) -> tuple:
        out: list = []
        for s in stmts:
            out.extend(self._walk(s, scope, module, stack))
        return tuple(out)

    def _walk(self, node, scope, module, stack) -> tuple:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            return ()  # executes only when called; spliced at call sites
        if isinstance(node, ast.Call):
            return self._call(node, scope, module, stack)
        if isinstance(node, (ast.If, ast.IfExp)):
            out = list(self._walk(node.test, scope, module, stack))
            if isinstance(node, ast.If):
                alts = (self._stmts(node.body, scope, module, stack),
                        self._stmts(node.orelse, scope, module, stack))
            else:
                alts = (self._walk(node.body, scope, module, stack),
                        self._walk(node.orelse, scope, module, stack))
            if any(alts):
                out.append(("cond", alts))
            return tuple(out)
        if isinstance(node, (ast.For, ast.While)):
            head = node.iter if isinstance(node, ast.For) else node.test
            out = list(self._walk(head, scope, module, stack))
            body = self._stmts(list(node.body) + list(node.orelse),
                               scope, module, stack)
            if body:
                out.append(("loop", body))
            return tuple(out)
        out = []
        for child in ast.iter_child_nodes(node):
            out.extend(self._walk(child, scope, module, stack))
        return tuple(out)

    def _call(self, node: ast.Call, scope, module, stack) -> tuple:
        name, branches = _cond_branches(node)
        if branches and node.args:
            out = list(self._walk(node.args[0], scope, module, stack))
            operands = node.args[3:] if name == "cond" else node.args[2:]
            for a in operands:
                out.extend(self._walk(a, scope, module, stack))
            for kw in node.keywords:
                out.extend(self._walk(kw.value, scope, module, stack))
            alts = []
            for b in branches:
                s = self.branch_seq(b, scope, module, stack)
                alts.append((("?",),) if s is None else s)
            if any(alts):
                out.append(("cond", tuple(alts)))
            return tuple(out)
        if name in _LOOP_BODY_ARGS:
            idxs = _LOOP_BODY_ARGS[name]
            body: list = []
            out = []
            for i, a in enumerate(node.args):
                if i in idxs:
                    body.extend(self.branch_seq(a, scope, module, stack)
                                or ())
                else:
                    out.extend(self._walk(a, scope, module, stack))
            for kw in node.keywords:
                out.extend(self._walk(kw.value, scope, module, stack))
            if body:
                out.append(("loop", tuple(body)))
            return tuple(out)
        cname = _collective_call(node)
        if cname is not None:
            out = []
            for a in node.args:
                out.extend(self._walk(a, scope, module, stack))
            for kw in node.keywords:
                out.extend(self._walk(kw.value, scope, module, stack))
            axis = _axis_expr(node, cname)
            out.append(("c", cname,
                        self._clf(scope, module).normalize(axis)))
            return tuple(out)
        out = []
        for child in ast.iter_child_nodes(node):
            out.extend(self._walk(child, scope, module, stack))
        for t in sorted(self.reach.resolve_call_targets(
                node, scope, module.rel)):
            out.extend(self.of_function(t, stack))
        return tuple(out)


def _fmt_seq(seq) -> str:
    parts = []
    for ev in seq:
        if ev[0] == "c":
            parts.append(f"{ev[1]}@{ev[2]}")
        elif ev[0] == "cond":
            parts.append(
                "cond{" + " | ".join(_fmt_seq(s) for s in ev[1]) + "}")
        elif ev[0] == "loop":
            parts.append("loop[" + _fmt_seq(ev[1]) + "]")
        else:
            parts.append("<" + ev[0] + ">")
    return " ; ".join(parts) if parts else "(none)"


@register
class CollectiveUnderTaintedCond(Rule):
    id = "COL005"
    summary = ("collective under a lax.cond/switch whose predicate "
               "derives from traced data — a rank-varying predicate "
               "splits the mesh at the collective")

    def run(self, project):
        reach, taints = dataflow.taints(project)
        creach = _CollectiveReach(reach)
        for key in sorted(taints):
            info = reach.functions[key]
            ta = taints[key]
            for node in reachability.own_nodes(info.node):
                if not isinstance(node, ast.Call) or not node.args:
                    continue
                name, branches = _cond_branches(node)
                if len(branches) < 2:
                    continue
                if not ta.expr_tainted(node.args[0]):
                    continue
                has = [creach.branch_has(b, info, info.module.rel)
                       for b in branches]
                if any(h is True for h in has):
                    yield Finding(
                        self.id, info.module.rel, node.lineno,
                        f"collective under a `{name}` in `{info.qual}` "
                        f"whose predicate derives from traced data — "
                        f"unless every rank computes the identical "
                        f"predicate, part of the mesh enters the "
                        f"collective and the rest does not; hoist the "
                        f"collective out of the branch, or suppress "
                        f"stating why the predicate is replicated-uniform")


@register
class CondSequenceMismatch(Rule):
    id = "COL006"
    summary = ("lax.cond/switch branches execute DIFFERING collective "
               "sequences — the branch arms are incompatible program "
               "versions for the mesh")

    def run(self, project):
        reach = reachability.compute(project)
        seqa = _SeqAnalyzer(project, reach)
        for _, scope, module in _iter_function_scopes(project):
            for node in _scope_nodes(scope, module):
                if not isinstance(node, ast.Call):
                    continue
                name, branches = _cond_branches(node)
                if len(branches) < 2:
                    continue
                seqs = [seqa.branch_seq(b, scope, module) for b in branches]
                if any(s is None for s in seqs):
                    continue  # unresolvable branch: stay silent
                if all(seqs) and len(set(seqs)) > 1:
                    shown = " vs ".join(_fmt_seq(s)
                                        for s in dict.fromkeys(seqs))
                    yield Finding(
                        self.id, module.rel, node.lineno,
                        f"`{name}` branches execute differing collective "
                        f"sequences ({shown}) — even under a uniform "
                        f"predicate the arms are distinct mesh programs; "
                        f"make the sequences identical, or suppress "
                        f"stating why the divergence is safe")


@register
class CollectiveInDataDependentLoop(Rule):
    id = "COL007"
    summary = ("collective inside a loop whose trip count can depend on "
               "traced data (lax.while_loop, or fori_loop with tainted "
               "bounds) — ranks disagreeing on the count deadlock")

    def run(self, project):
        reach, taints = dataflow.taints(project)
        creach = _CollectiveReach(reach)
        for _, scope, module in _iter_function_scopes(project):
            ta = taints.get(scope.key) if scope is not None else None
            for node in _scope_nodes(scope, module):
                if not isinstance(node, ast.Call):
                    continue
                name = _call_name(node)
                if name == "while_loop" and len(node.args) >= 2:
                    has = [creach.branch_has(node.args[0], scope,
                                             module.rel),
                           creach.branch_has(node.args[1], scope,
                                             module.rel)]
                    if any(h is True for h in has):
                        yield Finding(
                            self.id, module.rel, node.lineno,
                            f"collective inside `lax.while_loop` — the "
                            f"trip count is data-dependent by "
                            f"construction, so ranks can execute "
                            f"different collective counts and deadlock; "
                            f"bound the loop with fori_loop/scan or run "
                            f"the collective outside, or suppress "
                            f"stating why the condition is "
                            f"replicated-uniform")
                elif name == "fori_loop" and len(node.args) >= 3 \
                        and ta is not None:
                    if creach.branch_has(node.args[2], scope,
                                         module.rel) is True and \
                            (ta.expr_tainted(node.args[0])
                             or ta.expr_tainted(node.args[1])):
                        yield Finding(
                            self.id, module.rel, node.lineno,
                            f"collective inside `lax.fori_loop` whose "
                            f"bounds derive from traced data — ranks "
                            f"disagreeing on the trip count execute "
                            f"different collective counts and deadlock; "
                            f"make the bounds static, or suppress "
                            f"stating why the bounds are "
                            f"replicated-uniform")


def _shift_const(expr: ast.AST | None) -> int | None:
    if isinstance(expr, ast.Constant) and isinstance(expr.value, int) \
            and not isinstance(expr.value, bool):
        return expr.value
    if isinstance(expr, ast.UnaryOp) and isinstance(expr.op, ast.USub) and \
            isinstance(expr.operand, ast.Constant) and \
            isinstance(expr.operand.value, int):
        return -expr.operand.value
    return None


def _ring_shift(node: ast.Call, name: str) -> int | None:
    """Known ring shift of a ppermute-family call, else None.

    ``ppermute_shift(x, axis, K, size)`` reads the shift arg directly;
    ``ppermute(x, axis, perm)`` recognises the canonical ring
    comprehension ``[(i, (i +/- K) % size) for i in range(size)]``."""
    expr = None
    want = "shift" if name == "ppermute_shift" else "perm"
    for kw in node.keywords:
        if kw.arg == want:
            expr = kw.value
    if expr is None and len(node.args) > 2:
        expr = node.args[2]
    if name == "ppermute_shift":
        return _shift_const(expr)
    if not isinstance(expr, ast.ListComp) or len(expr.generators) != 1:
        return None
    elt = expr.elt
    if not (isinstance(elt, ast.Tuple) and len(elt.elts) == 2):
        return None
    src, dst = elt.elts
    if not isinstance(src, ast.Name):
        return None
    if isinstance(dst, ast.BinOp) and isinstance(dst.op, ast.Mod):
        inner = dst.left
        if isinstance(inner, ast.BinOp) and \
                isinstance(inner.left, ast.Name) and \
                inner.left.id == src.id and \
                isinstance(inner.right, ast.Constant) and \
                isinstance(inner.right.value, int):
            if isinstance(inner.op, ast.Add):
                return inner.right.value
            if isinstance(inner.op, ast.Sub):
                return -inner.right.value
    return None


@register
class PpermuteRingMismatch(Rule):
    id = "COL008"
    summary = ("two ppermute-family calls in one scope on the same axis "
               "with different known ring shifts — send/recv partners "
               "never pair up")

    def run(self, project):
        for reach, scope, module in _iter_function_scopes(project):
            clf = None
            groups: dict[str, list[tuple[int | None, ast.Call]]] = {}
            for node in _scope_nodes(scope, module):
                if not isinstance(node, ast.Call):
                    continue
                cname = _collective_call(node)
                if cname not in _PPERMUTE_FAMILY:
                    continue
                if clf is None:
                    clf = _AxisClassifier(project, reach, scope, module.rel)
                axis = clf.normalize(_axis_expr(node, cname))
                groups.setdefault(axis, []).append(
                    (_ring_shift(node, cname), node))
            for axis in sorted(groups):
                known = sorted(((s, n) for s, n in groups[axis]
                                if s is not None),
                               key=lambda sn: (sn[1].lineno,
                                               sn[1].col_offset))
                shifts = sorted({s for s, _ in known})
                if len(shifts) < 2:
                    continue
                first = known[0][0]
                anchor = next(n for s, n in known if s != first)
                yield Finding(
                    self.id, module.rel, anchor.lineno,
                    f"ppermute ring partners disagree within one scope "
                    f"on axis `{axis}` (shifts {shifts}) — a "
                    f"double-buffered pipeline must send along ONE "
                    f"consistent ring or sends never meet their "
                    f"receives; unify the shift, or suppress stating "
                    f"why two rings are intended")
