"""Observability rules (OBS0xx).

OBS001 — drivers, internal kernels, and parallel kernels do NOT emit
ad-hoc telemetry: no ``print``, no ``logging`` module, no
``io_callback``/``jax.debug.print``/``jax.debug.callback``.  The repo's
telemetry has exactly one spine (``slate_tpu/obs``): driver boundaries
emit structured events through ``util.trace.annotate`` and phases are
marked with ``util.trace.span`` — both host-side and zero-overhead when
disabled.  A stray ``print`` is invisible to the metrics CLI, and a
traced-side ``io_callback`` changes the jaxpr (breaking the
jaxpr-identity guarantee tests/test_obs.py enforces).

``drivers/printing.py`` is exempt: pretty-printing matrices to stdout is
its entire contract.

OBS002 — every ``@annotate("slate.<op>")``-decorated public driver has a
flops model registered in ``slate_tpu/obs/flops.py`` (the decorator's
``@register("<op>", ...)`` string literals are the source of truth —
the rule reads both sides by AST, never importing jax).  Without a
model, the op's events read ``mfu: n/a`` forever and nobody notices;
with this rule, skipping the model is an EXPLICIT
``# slate-lint: disable=OBS002 -- reason`` on the decorator line (the
band drivers do this: bandwidth is not recoverable from event shapes).
"""

from __future__ import annotations

import ast

from ..model import Finding, Rule, register

#: directories whose modules must stay telemetry-clean
CHECKED_PREFIXES = ("slate_tpu/drivers/", "slate_tpu/internal/",
                    "slate_tpu/parallel/")
#: stdout IS the contract here
EXEMPT_FILES = {"slate_tpu/drivers/printing.py"}

#: call / import names that bypass the obs spine
BANNED_CALLS = {"print", "io_callback", "pure_callback", "debug_print"}
BANNED_MODULES = {"logging"}


def _call_name(node: ast.Call):
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        # jax.debug.print / jax.debug.callback / jax.experimental.io_callback
        if f.attr in ("print", "callback"):
            base = f.value
            if isinstance(base, ast.Attribute) and base.attr == "debug":
                return f"debug.{f.attr}"
            if isinstance(base, ast.Name) and base.id == "debug":
                return f"debug.{f.attr}"
            return None
        return f.attr if f.attr in BANNED_CALLS else None
    return None


@register
class Obs001(Rule):
    id = "OBS001"
    summary = ("drivers/internal/parallel emit no ad-hoc telemetry "
               "(print/logging/io_callback) — observability goes through "
               "the slate_tpu.obs spine (annotate/span/events)")

    def run(self, project):
        for rel in sorted(project.modules):
            if not rel.startswith(CHECKED_PREFIXES) or rel in EXEMPT_FILES:
                continue
            mod = project.modules[rel]
            for node in ast.walk(mod.tree):
                if isinstance(node, (ast.Import, ast.ImportFrom)):
                    mods = ([a.name.split(".")[0] for a in node.names]
                            if isinstance(node, ast.Import)
                            else [(node.module or "").split(".")[0]])
                    hit = BANNED_MODULES.intersection(mods)
                    if hit:
                        yield Finding(
                            self.id, rel, node.lineno,
                            f"imports `{sorted(hit)[0]}` — route telemetry "
                            f"through slate_tpu.obs (annotate/span), not "
                            f"ad-hoc logging")
                    if (isinstance(node, ast.ImportFrom)
                            and any(a.name in ("io_callback",
                                               "pure_callback")
                                    for a in node.names)):
                        yield Finding(
                            self.id, rel, node.lineno,
                            "imports io_callback/pure_callback — recording "
                            "must stay OUTSIDE traced code (obs events are "
                            "host-side; a callback changes the jaxpr)")
                elif isinstance(node, ast.Call):
                    name = _call_name(node)
                    if name in BANNED_CALLS or (
                            name in ("debug.print", "debug.callback")):
                        what = ("`print`" if name == "print"
                                else f"`{name}`")
                        yield Finding(
                            self.id, rel, node.lineno,
                            f"calls {what} — drivers/internal/parallel emit "
                            f"telemetry only through the obs spine "
                            f"(util.trace.annotate / span / obs.events)")


#: the one module whose @register("<op>") literals define the model set
FLOPS_MODULE = "slate_tpu/obs/flops.py"


def _registered_flops_ops(project) -> set | None:
    """Op names registered in FLOPS_MODULE, by AST literal scan; None when
    the module is absent (fixture mini-repos without a flops registry are
    not checked — the live repo always has one)."""
    cached = project.cache.get("obs002:registered")
    if cached is not None:
        return cached or None
    mod = project.modules.get(FLOPS_MODULE)
    if mod is None:
        project.cache["obs002:registered"] = set()
        return None
    ops: set = set()
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        name = f.id if isinstance(f, ast.Name) else (
            f.attr if isinstance(f, ast.Attribute) else None)
        if name != "register":
            continue
        for arg in node.args:
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                ops.add(arg.value)
    project.cache["obs002:registered"] = ops
    return ops


def _annotate_op(dec) -> str | None:
    """The 'slate.<op>' literal of an @annotate decorator Call, if any."""
    if not isinstance(dec, ast.Call) or not dec.args:
        return None
    f = dec.func
    name = f.id if isinstance(f, ast.Name) else (
        f.attr if isinstance(f, ast.Attribute) else None)
    if name != "annotate":
        return None
    arg = dec.args[0]
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str) \
            and arg.value.startswith("slate."):
        return arg.value[len("slate."):]
    return None


@register
class Obs002(Rule):
    id = "OBS002"
    summary = ("every @annotate-decorated public driver has a flops model "
               "registered in obs/flops.py (or an explicit disable) — the "
               "MFU column never silently reads n/a for a new op")

    def run(self, project):
        registered = _registered_flops_ops(project)
        if registered is None:
            return
        for rel in sorted(project.modules):
            if not rel.startswith("slate_tpu/"):
                continue
            mod = project.modules[rel]
            for node in ast.walk(mod.tree):
                if not isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                for dec in node.decorator_list:
                    op = _annotate_op(dec)
                    if op is not None and op not in registered:
                        yield Finding(
                            self.id, rel, dec.lineno,
                            f"driver `{node.name}` (slate.{op}) has no "
                            f"flops model in obs/flops.py — register one "
                            f"(@register(\"{op}\")) or disable with a "
                            f"reason")
