"""Lock-discipline rules (CON0xx), driven by a declared registry of
guarded state.

The framework's cross-thread mutable state — the server's flush/wedge
bookkeeping, the admission queue and its tickets, the SLO latency
governor, the executable cache's store/counters, and the obs event
sinks — is each guarded by one lock (a ``threading.Lock`` or, for the
admission queue, a ``Condition``, which the rules treat identically:
``with self._lock:`` acquires either).  Rather than guess at
lock/state association, the registry below DECLARES it: one
:class:`LockSpec` per lock names the module, the owning class (None
for module-level locks), the lock's attribute/global name, and the
state names it guards.  Growing a new locked subsystem means adding one
registry line; the rules then hold it to the same discipline.

Rules:

- **CON001** — guarded state accessed without holding its lock.  The
  walker tracks the held-lock set through ``with <lock>:`` blocks
  (resetting inside nested ``def``/``lambda``, which run later);
  ``__init__``/``__new__`` and module top level are exempt
  (single-threaded construction/import happens-before publication).
  Designed lock-free fast-path peeks are suppressed inline with a
  reason, which keeps every such peek an audited decision.
- **CON002** — lock-ordering inversion: one code path acquires lock B
  while holding A (directly nested ``with``, or a call whose transitive
  callees acquire B — resolved over the cross-module call graph,
  including ``self.helper()`` method edges) while another path acquires
  A while holding B.  Also fires on a path re-acquiring the lock it
  already holds — ``threading.Lock`` is non-reentrant, so that is a
  self-deadlock, the bug class ``timing()`` would hit if it called
  ``set_timing`` under ``_LOCK``.
- **CON003** — a known-blocking call under a held lock: the jax AOT
  chain (``jit().lower``/``lower().compile``), ``block_until_ready``,
  or ``sleep``.  Compilation takes seconds; doing it under the cache
  lock would serialize every concurrent submit behind one compile
  (cache.py deliberately compiles OUTSIDE the lock and re-checks).
"""

from __future__ import annotations

import ast
from typing import NamedTuple

from .. import callgraph, reachability
from ..model import Finding, Rule, register


class LockSpec(NamedTuple):
    """One declared lock and the state it guards."""
    module: str          # rel path of the declaring module
    cls: str | None      # owning class, None for a module-level lock
    lock: str            # attribute (``self.<lock>``) or global name
    guards: tuple        # state names the lock protects

    @property
    def key(self) -> str:
        scope = f"{self.cls}." if self.cls else ""
        return f"{self.module}::{scope}{self.lock}"


#: the guarded-state registry (docs/STATIC_ANALYSIS.md documents the
#: format).  One line per lock; CON001-CON003 enforce the discipline.
LOCK_REGISTRY: tuple[LockSpec, ...] = (
    LockSpec("slate_tpu/serve/server.py", "Server", "_lock",
             ("_inflight", "_flush_deadline", "_wedged", "_flush_error",
              "_quarantined", "_flusher", "_watchdog", "_ladders",
              "_sizes", "_retunes", "_retuning", "_last_retune")),
    LockSpec("slate_tpu/serve/admission.py", "AdmissionQueue", "_lock",
             ("_items", "_next_id", "_admitted", "_shed", "_closed")),
    LockSpec("slate_tpu/serve/admission.py", "Ticket", "_lock",
             ("_value", "_error")),
    LockSpec("slate_tpu/serve/pool.py", "DevicePool", "_lock",
             ("_members", "_rr", "_failovers", "_quarantines",
              "_readmissions")),
    LockSpec("slate_tpu/obs/slo.py", "LatencyGovernor", "_lock",
             ("_lat", "_dev_lat")),
    LockSpec("slate_tpu/serve/cache.py", "ExecutableCache", "_lock",
             ("_exes", "_hits", "_misses", "_compile_ms")),
    LockSpec("slate_tpu/obs/events.py", None, "_LOCK",
             ("_CFG", "_RING", "_COLLECTORS")),
    LockSpec("slate_tpu/core/storage.py", "TileMap", "_lock",
             ("_res", "_device", "_pending")),
    LockSpec("slate_tpu/robust/checkpoint.py", "CheckpointManager", "_lock",
             ("_seq",)),
)

#: constructors run happens-before publication; module top level is
#: import-time single-threaded.  Both are exempt from CON001.
_EXEMPT_METHODS = {"__init__", "__new__"}


def _acquired_spec(expr: ast.AST, rel: str,
                   cls: str | None) -> LockSpec | None:
    """The registry lock a ``with`` context expression acquires, if any."""
    for spec in LOCK_REGISTRY:
        if spec.module != rel:
            continue
        if spec.cls is None:
            if isinstance(expr, ast.Name) and expr.id == spec.lock:
                return spec
        elif cls == spec.cls:
            if isinstance(expr, ast.Attribute) and \
                    isinstance(expr.value, ast.Name) and \
                    expr.value.id == "self" and expr.attr == spec.lock:
                return spec
    return None


def _is_access(node: ast.AST, spec: LockSpec) -> str | None:
    """The guarded name ``node`` reads/writes, if any."""
    if spec.cls is not None:
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and \
                node.value.id == "self" and node.attr in spec.guards:
            return node.attr
    elif isinstance(node, ast.Name) and node.id in spec.guards:
        return node.id
    return None


def _top_defs(body):
    """Top-level functions and class methods: the roots CON001 checks.
    Nested defs are handled by the walker itself (held-set reset)."""
    for node in body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield None, node
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    yield node.name, sub


def _unlocked_accesses(node, spec: LockSpec, cls: str | None, held: bool):
    """Yield (access node, guarded name) reached with the lock not held."""
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.Lambda)):
        body = node.body if isinstance(node.body, list) else [node.body]
        for s in body:  # runs later: the lock is NOT held then
            yield from _unlocked_accesses(s, spec, cls, False)
        return
    if isinstance(node, (ast.With, ast.AsyncWith)):
        inner = held
        for item in node.items:
            yield from _unlocked_accesses(item.context_expr, spec, cls,
                                          held)
            if _acquired_spec(item.context_expr, spec.module, cls) is spec:
                inner = True
        for s in node.body:
            yield from _unlocked_accesses(s, spec, cls, inner)
        return
    name = _is_access(node, spec)
    if name is not None and not held:
        yield node, name
    for child in ast.iter_child_nodes(node):
        yield from _unlocked_accesses(child, spec, cls, held)


@register
class GuardedStateUnlocked(Rule):
    id = "CON001"
    summary = ("registered guarded state accessed without holding its "
               "lock — wrap in `with <lock>:` or suppress a designed "
               "lock-free peek with a reason")

    def run(self, project):
        for spec in LOCK_REGISTRY:
            mod = project.modules.get(spec.module)
            if mod is None:
                continue
            for cls, fn in _top_defs(mod.tree.body):
                if fn.name in _EXEMPT_METHODS:
                    continue
                if spec.cls is not None and cls != spec.cls:
                    continue
                for stmt in fn.body:
                    for node, name in _unlocked_accesses(
                            stmt, spec, cls, False):
                        lock = (f"self.{spec.lock}" if spec.cls
                                else spec.lock)
                        yield Finding(
                            self.id, spec.module, node.lineno,
                            f"`{name}` is declared guarded by `{lock}` "
                            f"(lock registry, rules/concurrency.py) but "
                            f"`{fn.name}` touches it without holding the "
                            f"lock — a racing thread tears the state; "
                            f"wrap the access in `with {lock}:`, or "
                            f"suppress stating why lock-free access is "
                            f"safe here")


# --------------------------------------------------------------- CON002/3


def _node_cls(info) -> str | None:
    return getattr(info, "cls", None)


def _direct_locks(info) -> set[str]:
    """Lock keys a function/method body may acquire (over-approximate:
    includes nested defs, which its callers can invoke)."""
    rel, cls = info.module.rel, _node_cls(info)
    out: set[str] = set()
    for n in ast.walk(info.node):
        if isinstance(n, (ast.With, ast.AsyncWith)):
            for item in n.items:
                spec = _acquired_spec(item.context_expr, rel, cls)
                if spec is not None:
                    out.add(spec.key)
    return out


def _call_targets(call: ast.Call, info, cg) -> set[str]:
    """Call-graph keys a call site may reach, incl. self.method edges."""
    rel = info.module.rel
    f = call.func
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
            and f.value.id == "self" and \
            isinstance(info, callgraph.MethodInfo):
        mkey = f"{rel}::{info.cls}.{f.attr}"
        if mkey in cg.methods:
            return {mkey}
    scope = info if isinstance(info, reachability.FuncInfo) else None
    return cg.reach.resolve_call_targets(call, scope, rel)


class _AcquireSummary:
    """Transitive may-acquire lock sets over the call graph."""

    def __init__(self, cg):
        self.cg = cg
        self.memo: dict[str, set[str]] = {}

    def of(self, key: str) -> set[str]:
        if key in self.memo:
            return self.memo[key]
        self.memo[key] = set()          # cycle guard
        info = self.cg.nodes.get(key)
        if info is None:
            return set()
        out = _direct_locks(info)
        for callee in self.cg.callees(key):
            out |= self.of(callee)
        self.memo[key] = out
        return out


def _held_pairs(info, cg, summary: _AcquireSummary):
    """Yield (held lock key, acquired lock key, lineno) for every
    acquisition — nested ``with`` or transitive via a call — performed
    while a registry lock is held."""
    rel, cls = info.module.rel, _node_cls(info)

    def walk(node, held):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            body = node.body if isinstance(node.body, list) \
                else [node.body]
            for s in body:
                yield from walk(s, ())
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = list(held)
            for item in node.items:
                yield from walk(item.context_expr, held)
                spec = _acquired_spec(item.context_expr, rel, cls)
                if spec is not None:
                    for h in inner:
                        yield h, spec.key, node.lineno
                    inner.append(spec.key)
            for s in node.body:
                yield from walk(s, tuple(inner))
            return
        if isinstance(node, ast.Call) and held:
            for t in sorted(_call_targets(node, info, cg)):
                for acquired in sorted(summary.of(t)):
                    for h in held:
                        yield h, acquired, node.lineno
        for child in ast.iter_child_nodes(node):
            yield from walk(child, held)

    for stmt in info.node.body:
        yield from walk(stmt, ())


@register
class LockOrderInversion(Rule):
    id = "CON002"
    summary = ("two paths acquire the same two locks in opposite order "
               "(or one path re-acquires a non-reentrant lock) — "
               "deadlock by schedule")

    def run(self, project):
        if not any(s.module in project.modules for s in LOCK_REGISTRY):
            return
        cg = callgraph.compute(project)
        summary = _AcquireSummary(cg)
        pairs: dict = {}                # (held, acquired) -> (rel, line)
        for key in sorted(cg.nodes):
            info = cg.nodes[key]
            for held, acquired, line in _held_pairs(info, cg, summary):
                pairs.setdefault((held, acquired),
                                 (info.module.rel, line))
        for (a, b) in sorted(pairs):
            rel, line = pairs[(a, b)]
            if a == b:
                yield Finding(
                    self.id, rel, line,
                    f"path re-acquires `{a}` while already holding it — "
                    f"threading.Lock is non-reentrant, so this "
                    f"self-deadlocks; release first or restructure the "
                    f"callee to expect the lock held")
            elif a < b and (b, a) in pairs:
                orel, oline = pairs[(b, a)]
                yield Finding(
                    self.id, rel, line,
                    f"lock-order inversion: this path acquires `{b}` "
                    f"while holding `{a}`, but {orel}:{oline} acquires "
                    f"`{a}` while holding `{b}` — two threads "
                    f"interleaving these paths deadlock; pick one global "
                    f"order and restructure the loser")


def _blocking_call(node: ast.Call) -> str | None:
    f = node.func
    name = (f.id if isinstance(f, ast.Name)
            else f.attr if isinstance(f, ast.Attribute) else None)
    # get_or_compile: the serving layer's sanctioned compile entry
    # (SEAM012) — a cold call compiles for seconds, so holding ANY
    # registry lock across it (the device pool's included) is the same
    # bug as an inline jit().lower().compile()
    if name in ("block_until_ready", "sleep", "get_or_compile"):
        return name
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Call):
        vf = f.value.func
        vname = (vf.id if isinstance(vf, ast.Name)
                 else vf.attr if isinstance(vf, ast.Attribute) else None)
        if name == "lower" and vname == "jit":
            return "jit(...).lower"
        if name == "compile" and vname == "lower":
            return "lower(...).compile"
    return None


@register
class BlockingCallUnderLock(Rule):
    id = "CON003"
    summary = ("known-blocking call (jit/lower/compile chain, "
               "block_until_ready, sleep) under a held registry lock — "
               "serializes every other thread behind seconds of wait")

    def run(self, project):
        if not any(s.module in project.modules for s in LOCK_REGISTRY):
            return
        cg = callgraph.compute(project)
        for key in sorted(cg.nodes):
            info = cg.nodes[key]
            yield from self._check(info)

    def _check(self, info):
        rel, cls = info.module.rel, _node_cls(info)

        def walk(node, held):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                body = node.body if isinstance(node.body, list) \
                    else [node.body]
                for s in body:
                    yield from walk(s, None)
                return
            if isinstance(node, (ast.With, ast.AsyncWith)):
                inner = held
                for item in node.items:
                    yield from walk(item.context_expr, held)
                    spec = _acquired_spec(item.context_expr, rel, cls)
                    if spec is not None:
                        inner = spec
                for s in node.body:
                    yield from walk(s, inner)
                return
            if isinstance(node, ast.Call) and held is not None:
                what = _blocking_call(node)
                if what is not None:
                    lock = (f"self.{held.lock}" if held.cls else held.lock)
                    yield Finding(
                        self.id, rel, node.lineno,
                        f"`{what}` under held `{lock}` — compilation/"
                        f"device sync takes seconds and every thread "
                        f"contending for the lock stalls behind it; move "
                        f"the blocking work outside the critical section "
                        f"and re-check state after re-acquiring "
                        f"(cache.py's compile-outside-the-lock pattern)")
            for child in ast.iter_child_nodes(node):
                yield from walk(child, held)

        for stmt in info.node.body:
            yield from walk(stmt, None)
