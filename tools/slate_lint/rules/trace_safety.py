"""Trace-safety rules (TRC0xx): applied ONLY to the traced function set
computed by the reachability pass.

The bug class: Python-level control flow or concretization on a traced
value explodes at trace time (``TracerBoolConversionError``) or — worse —
silently bakes one branch into the compiled program.  The rules flag the
concretization points; values are tracked by the taint analysis in
``dataflow.py``.

``raise`` inside a traced body is allowed only in the *registered eager
boundaries* — the policy-seam modules whose raises are guarded by
``HealthInfo.is_traced()`` checks (robust/health.py, robust/recovery.py)
or are trace-time config validation (exceptions.py, options.py).  A
raise anywhere else in the traced set needs an inline
``# slate-lint: disable=TRC006 -- <why this runs at trace time>``.
"""

from __future__ import annotations

import ast

from .. import dataflow, reachability
from ..model import Finding, Rule, register

#: modules whose raises are the designed eager policy seam.  tune/plans.py
#: qualifies like options.py: resolve_plan/validate_cache run at trace
#: time (tuned dispatch over static shapes) and raise only on malformed
#: host-side plan-cache config.
EAGER_BOUNDARY_MODULES = {
    "slate_tpu/robust/health.py",
    "slate_tpu/robust/recovery.py",
    "slate_tpu/exceptions.py",
    "slate_tpu/options.py",
    "slate_tpu/tune/plans.py",
}


def _numpy_aliases(imports: dict[str, str]) -> set[str]:
    return {name for name, dotted in imports.items()
            if dotted == "numpy" or dotted.startswith("numpy.")}


class _TraceRule(Rule):
    """Shared driver: subclasses implement ``visit`` per traced node.
    Taint comes from the interprocedural builder (dataflow.taints)."""

    def run(self, project):
        reach, taints = dataflow.taints(project)
        for key in sorted(taints):
            info = reach.functions[key]
            ta = taints[key]
            np_aliases = _numpy_aliases(reach.imports[info.module.rel])
            for node in reachability.own_nodes(info.node):
                yield from self.visit(node, ta, info, np_aliases)

    def visit(self, node, ta, info, np_aliases):  # pragma: no cover
        raise NotImplementedError
        yield

    def _finding(self, node, info, message) -> Finding:
        return Finding(self.id, info.module.rel, node.lineno, message)


@register
class TracedBranch(_TraceRule):
    id = "TRC001"
    summary = ("Python `if`/ternary/short-circuit on a traced value — "
               "concretizes at trace time; use jnp.where / lax.cond")

    def visit(self, node, ta, info, np_aliases):
        if isinstance(node, (ast.If, ast.IfExp)) and \
                ta.expr_tainted(node.test):
            yield self._finding(
                node, info,
                f"Python branch on a traced value in `{info.qual}` — "
                f"this concretizes the tracer (TracerBoolConversionError "
                f"under jit); use jnp.where or lax.cond")


@register
class TracedLoop(_TraceRule):
    id = "TRC002"
    summary = ("Python `while`/`for` driven by a traced value — loop "
               "bounds must be static; use lax.fori_loop / lax.scan / "
               "lax.while_loop")

    def visit(self, node, ta, info, np_aliases):
        if isinstance(node, ast.While) and ta.expr_tainted(node.test):
            yield self._finding(
                node, info,
                f"`while` on a traced condition in `{info.qual}` — the "
                f"trip count cannot depend on traced data; use "
                f"lax.while_loop")
        elif isinstance(node, ast.For) and ta.expr_tainted(node.iter):
            yield self._finding(
                node, info,
                f"`for` over a traced iterable in `{info.qual}` — "
                f"iteration unrolls over tracer contents; use lax.scan "
                f"or lax.fori_loop")


@register
class TracedAssert(_TraceRule):
    id = "TRC003"
    summary = ("`assert` on a traced value — stripped under -O and "
               "concretizes the tracer; use checkify or a health check")

    def visit(self, node, ta, info, np_aliases):
        if isinstance(node, ast.Assert) and ta.expr_tainted(node.test):
            yield self._finding(
                node, info,
                f"`assert` on a traced value in `{info.qual}` — "
                f"concretizes at trace time and vanishes under -O; route "
                f"failures through HealthInfo instead")


@register
class TracedConcretize(_TraceRule):
    id = "TRC004"
    summary = ("bool()/float()/int()/.item()/.tolist() on a traced value "
               "— forces a host sync or fails under jit")

    def visit(self, node, ta, info, np_aliases):
        if not isinstance(node, ast.Call):
            return
        f = node.func
        if isinstance(f, ast.Name) and f.id in dataflow.CONCRETIZERS \
                and any(ta.expr_tainted(a) for a in node.args):
            yield self._finding(
                node, info,
                f"{f.id}() on a traced value in `{info.qual}` — "
                f"concretization fails under jit; keep the value as an "
                f"array or resolve it at the eager boundary")
        elif isinstance(f, ast.Attribute) \
                and f.attr in dataflow.CONCRETIZING_METHODS \
                and ta.expr_tainted(f.value):
            yield self._finding(
                node, info,
                f".{f.attr}() on a traced value in `{info.qual}` — "
                f"concretization fails under jit; keep the value as an "
                f"array or resolve it at the eager boundary")


@register
class NumpyOnTraced(_TraceRule):
    id = "TRC005"
    summary = ("host numpy applied to a traced value — silently "
               "concretizes; use jnp (numpy on static shapes/seeds is "
               "fine)")

    def visit(self, node, ta, info, np_aliases):
        if not isinstance(node, ast.Call):
            return
        f = node.func
        base = f.value if isinstance(f, ast.Attribute) else None
        while isinstance(base, ast.Attribute):  # np.linalg.norm chains
            base = base.value
        is_np = isinstance(base, ast.Name) and base.id in np_aliases
        if is_np and (any(ta.expr_tainted(a) for a in node.args)
                      or any(ta.expr_tainted(kw.value)
                             for kw in node.keywords)):
            yield self._finding(
                node, info,
                f"host numpy call on a traced value in `{info.qual}` — "
                f"np.* concretizes tracers; use the jnp equivalent")


@register
class RaiseInTraced(_TraceRule):
    id = "TRC006"
    summary = ("`raise` inside a traced body outside the registered "
               "eager boundaries — failures must flow as data "
               "(HealthInfo / non-finites)")

    def visit(self, node, ta, info, np_aliases):
        if not isinstance(node, ast.Raise):
            return
        if info.module.rel in EAGER_BOUNDARY_MODULES:
            return
        yield self._finding(
            node, info,
            f"`raise` in traced function `{info.qual}` — only the "
            f"registered eager boundaries (robust/health.py, "
            f"robust/recovery.py, exceptions.py, options.py) may raise; "
            f"route failures through HealthInfo, or suppress with a "
            f"reason if this provably runs at trace time")
