"""Policy-seam rules (SEAM0xx): the 10 contract assertions migrated from
``tools/check_error_contracts.py`` (which is now a thin shim over this
pack; see docs/ROBUSTNESS.md for the contracts themselves).

Every finding also carries a ``legacy`` string — the byte-identical
report line the pre-migration checker printed — so the shim's output and
exit codes are unchanged.  The scan runs once per project (cached) and
yields findings in the legacy order; each rule plugin filters its own id
out of the shared scan.

Rule map (old "point" numbers from the shim's docstring):

====== ===============================================================
SEAM001 point 1 — public drivers accept ``opts``
SEAM002 point 2 — checked driver modules import the robust layer
SEAM003 point 3 — ... and actually reference the health machinery
SEAM004 point 4 — internal/rbt.py stays policy-free
SEAM005 point 5 — speculative boundaries resolve_speculate exactly
        once; recovery boundaries route bounded_retry + one finalize
SEAM006 point 6 — Option.Speculate never read in a driver module
SEAM007 point 7 — robust/abft.py policy-free and raise-free
SEAM008 point 8 — ABFT boundaries resolve_abft exactly once
SEAM009 point 9 — maybe_corrupt sites are literals from faults.SITES
SEAM010 point 10 — Option.Abft never read in a driver module
SEAM011 (new, PR 7) — the raw autotuner plan cache (load_cache /
        save_cache / cache_path / record_plan) is only touched inside
        slate_tpu/tune/; everything else goes through resolve_plan
SEAM012 (new, PR 10) — serve/ obtains executables ONLY through the
        serve executable cache: no jax.jit / .lower() / .compile()
        anywhere in slate_tpu/serve/ except serve/cache.py, so every
        serving compile is accounted in ExecutableCache.stats and
        surfaced in per-batch obs events
SEAM013 (new, PR 17) — checkpoint serialization (write_payload /
        read_payload / write_manifest / read_manifest) is only touched
        inside slate_tpu/robust/checkpoint.py — the on-disk format,
        atomic-rename discipline and verification ladder have ONE blast
        radius; everything else goes through CheckpointManager
SEAM014 (new, PR 18) — mixed precision is a certified policy, not an
        ambient cast: (a) no literal low-precision float spelling
        (bfloat16 / float16 / bf16 / fp16) reaches an astype or dtype=
        inside drivers/ or serve/ — storage-precision changes go through
        robust/precision.py (demote / promote / round_through), where
        the f32-accumulation contract lives; (b) the raw
        ``Option.Precision`` knob (exact ``Option`` base match, so
        ``lax.Precision`` never false-positives) is read only inside
        robust/precision.py and options.py; (c) the precision
        boundaries (serve/batched.py make_batched, recovery.py
        posv_with_recovery + gels_with_recovery) call resolve_precision
        EXACTLY once
====== ===============================================================

SEAM011–SEAM014 have no legacy twins (they postdate the migration);
their ``legacy`` strings are the modern ``path:line: msg`` form.
"""

from __future__ import annotations

import ast

from ..model import Finding, Rule, register

# ---- configuration (moved verbatim from tools/check_error_contracts.py)

DRIVERS_DIR = "slate_tpu/drivers"

CHECKED_MODULES = (
    "lu.py", "cholesky.py", "band.py", "mixed.py", "qr.py",
    "heev.py", "svd.py", "stedc.py", "hetrf.py", "inverse.py",
    "condest.py",
)

EXEMPT = {
    "tree_flatten", "tree_unflatten", "lower", "upper",
    "norm1est",
    "stedc_info",
}

HEALTH_NAMES = {"finalize", "finalize_flat", "error_policy", "HealthInfo",
                "from_pivots", "from_result"}

SPECULATIVE_BOUNDARIES = (
    ("slate_tpu/robust/recovery.py",
     ("gesv_with_recovery", "gels_with_recovery", "hesv_with_recovery",
      "posv_with_recovery")),
    (f"{DRIVERS_DIR}/mixed.py", ("gesv_mixed",)),
)
RECOVERY_BOUNDARIES = {"gesv_with_recovery", "gels_with_recovery",
                       "hesv_with_recovery", "posv_with_recovery"}
RBT_MODULE = "slate_tpu/internal/rbt.py"
FINALIZE_NAMES = {"finalize", "_finalize_solve"}

TUNE_DIR = "slate_tpu/tune"
#: raw plan-cache accessors: consuming code must use resolve_plan instead,
#: so a cache-format change (or a corrupt cache file) has ONE blast radius
RAW_PLAN_CACHE_NAMES = {"load_cache", "save_cache", "cache_path",
                        "record_plan"}

SERVE_DIR = "slate_tpu/serve"
SERVE_CACHE_MODULE = f"{SERVE_DIR}/cache.py"
#: compile-producing constructs banned outside the serve executable cache
SERVE_COMPILE_NAMES = {"jit", "lower", "compile", "aot_compile"}

CKPT_MODULE = "slate_tpu/robust/checkpoint.py"
#: raw checkpoint serialization: everyone else uses CheckpointManager,
#: so torn-write semantics and the verify ladder have one blast radius
RAW_CKPT_IO_NAMES = {"write_payload", "read_payload", "write_manifest",
                     "read_manifest"}

PRECISION_MODULE = "slate_tpu/robust/precision.py"
OPTIONS_MODULE = "slate_tpu/options.py"
#: literal low-precision float spellings banned in drivers//serve/ casts:
#: storage-precision changes go through robust/precision.py, which owns
#: the f32-accumulation contract and the one normalize_dtype vocabulary
LOW_PRECISION_SPELLINGS = {"bfloat16", "float16", "bf16", "fp16", "half"}
PRECISION_BOUNDARIES = (
    ("slate_tpu/serve/batched.py", ("make_batched",)),
    ("slate_tpu/robust/recovery.py",
     ("posv_with_recovery", "gels_with_recovery")),
)

ABFT_MODULE = "slate_tpu/robust/abft.py"
FAULTS_MODULE = "slate_tpu/robust/faults.py"
ABFT_BOUNDARIES = (
    (f"{DRIVERS_DIR}/lu.py", ("_getrf",)),
    (f"{DRIVERS_DIR}/cholesky.py", ("potrf",)),
    (f"{DRIVERS_DIR}/blas3.py", ("gemm", "trsm")),
    ("slate_tpu/robust/recovery.py",
     ("gesv_with_recovery", "posv_with_recovery")),
)

# ---- AST helpers (ported) ------------------------------------------------


def _public_functions(tree: ast.Module):
    for node in tree.body:
        if isinstance(node, ast.FunctionDef) and not node.name.startswith("_"):
            yield node


def _accepts_opts(fn: ast.FunctionDef) -> bool:
    names = [a.arg for a in fn.args.args + fn.args.kwonlyargs]
    return "opts" in names or fn.args.kwarg is not None


def _imports_robust(tree: ast.Module) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module:
            mod = node.module
            if "robust" in mod.split("."):
                return True
            if mod.endswith("robust") or ".robust." in f".{mod}.":
                return True
        if isinstance(node, ast.Import):
            if any("robust" in alias.name.split(".")
                   for alias in node.names):
                return True
    return False


def _references_health(tree: ast.Module) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.Attribute) and node.attr in HEALTH_NAMES:
            return True
        if isinstance(node, ast.Name) and node.id in HEALTH_NAMES:
            return True
    return False


def _count_calls(fn: ast.FunctionDef, names: set[str]) -> int:
    c = 0
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Name) and f.id in names:
                c += 1
            elif isinstance(f, ast.Attribute) and f.attr in names:
                c += 1
    return c


def _fault_sites(project) -> set[str]:
    mod = project.module(FAULTS_MODULE)
    if mod is None:
        return set()
    for node in mod.tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = [t.id for t in node.targets
                       if isinstance(t, ast.Name)]
        elif isinstance(node, ast.AnnAssign) and isinstance(node.target,
                                                            ast.Name):
            targets = [node.target.id]
        if "SITES" in targets and node.value is not None:
            return {c.value for c in ast.walk(node.value)
                    if isinstance(c, ast.Constant)
                    and isinstance(c.value, str)}
    return set()


def _driver_modules(project):
    """Checked driver files in sorted-filename order (old glob order)."""
    rels = [r for r in project.modules
            if r.startswith(DRIVERS_DIR + "/") and r.count("/") == 2]
    return sorted(rels)


def _slate_modules(project):
    """slate_tpu/**/*.py in old ``sorted(rglob)`` (path-parts) order."""
    rels = [r for r in project.modules if r.startswith("slate_tpu/")]
    return sorted(rels, key=lambda r: tuple(r.split("/")))


# ---- the ordered scan ----------------------------------------------------


def _mechanism_purity(project, rel, banned_pkgs, legacy_name, legacy_tail,
                      rule_id, *, missing_tail, check_raise=False,
                      raise_tail=""):
    """Shared shape of points 4 and 7: a mechanism module must exist, not
    import the policy layers, and (optionally) never raise."""
    mod = project.module(rel)
    if mod is None:
        yield (rule_id, Finding(
            rule_id, rel, 1, f"missing {missing_tail}",
            legacy=f"{legacy_name}: missing {missing_tail}"))
        return
    for node in ast.walk(mod.tree):
        mods = []
        if isinstance(node, ast.ImportFrom) and node.module:
            mods = node.module.split(".")
        elif isinstance(node, ast.Import):
            mods = [s for a in node.names for s in a.name.split(".")]
        if any(p in mods for p in banned_pkgs):
            yield (rule_id, Finding(
                rule_id, rel, node.lineno, legacy_tail,
                legacy=f"{legacy_name}:{node.lineno}: {legacy_tail}"))
        if check_raise and isinstance(node, ast.Raise):
            yield (rule_id, Finding(
                rule_id, rel, node.lineno, raise_tail,
                legacy=f"{legacy_name}:{node.lineno}: {raise_tail}"))


def seam_scan(project) -> list[tuple[str, Finding]]:
    """All seam findings, in the legacy checker's report order."""
    if "seam_scan" in project.cache:
        return project.cache["seam_scan"]
    out: list[tuple[str, Finding]] = []
    out.extend(_scan_speculation(project))
    out.extend(_scan_abft(project))
    out.extend(_scan_driver_contract(project))
    out.extend(_scan_tune(project))
    out.extend(_scan_serve(project))
    out.extend(_scan_checkpoint(project))
    out.extend(_scan_precision(project))
    project.cache["seam_scan"] = out
    return out


def _scan_speculation(project):
    # point 4: rbt.py stays pure mechanism
    yield from _mechanism_purity(
        project, RBT_MODULE, ("options", "robust"), "internal/rbt.py",
        "imports the options/robust layer — the butterfly mechanism must "
        "stay policy-free (the seam is drivers/lu.py + robust/recovery.py)",
        "SEAM004",
        missing_tail="(the RBT mechanism module the speculative gesv "
                     "path builds on)")
    # point 5: boundaries resolve the knob exactly once
    for rel, fns in SPECULATIVE_BOUNDARIES:
        mod = project.module(rel)
        if mod is None:
            yield ("SEAM005", Finding(
                "SEAM005", rel, 1, "missing speculative boundary module",
                legacy=f"{rel}: missing speculative boundary module"))
            continue
        defs = {n.name: n for n in mod.tree.body
                if isinstance(n, ast.FunctionDef)}
        for fname in fns:
            fn = defs.get(fname)
            if fn is None:
                yield ("SEAM005", Finding(
                    "SEAM005", rel, 1,
                    f"speculative boundary `{fname}` not found",
                    legacy=f"{rel}: speculative boundary "
                           f"`{fname}` not found"))
                continue
            n_res = _count_calls(fn, {"resolve_speculate"})
            if n_res != 1:
                msg = (f"`{fname}` calls resolve_speculate {n_res}x — the "
                       f"knob must be resolved EXACTLY once at the boundary")
                yield ("SEAM005", Finding(
                    "SEAM005", rel, fn.lineno, msg,
                    legacy=f"{rel}:{fn.lineno}: `{fname}` calls "
                           f"resolve_speculate {n_res}x — the knob must be "
                           f"resolved EXACTLY once at the boundary"))
            if fname in RECOVERY_BOUNDARIES:
                if _count_calls(fn, {"bounded_retry"}) < 1:
                    msg = (f"`{fname}` never routes through bounded_retry "
                           f"— speculation has no escalation path")
                    yield ("SEAM005", Finding(
                        "SEAM005", rel, fn.lineno, msg,
                        legacy=f"{rel}:{fn.lineno}: `{fname}` never routes "
                               f"through bounded_retry — speculation has "
                               f"no escalation path"))
                n_fin = _count_calls(fn, FINALIZE_NAMES)
                if n_fin != 1:
                    msg = (f"`{fname}` finalizes {n_fin}x — the (result, "
                           f"HealthInfo) pair must resolve ErrorPolicy "
                           f"exactly once")
                    yield ("SEAM005", Finding(
                        "SEAM005", rel, fn.lineno, msg,
                        legacy=f"{rel}:{fn.lineno}: `{fname}` finalizes "
                               f"{n_fin}x — the (result, HealthInfo) pair "
                               f"must resolve ErrorPolicy exactly once"))
    # point 6: the raw knob never leaks into a driver module
    for rel in _driver_modules(project):
        mod = project.modules[rel]
        fname = rel.rsplit("/", 1)[1]
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Attribute) and node.attr == "Speculate":
                msg = ("reads Option.Speculate directly — drivers consume "
                       "resolve_speculate's boolean, never the raw knob")
                yield ("SEAM006", Finding(
                    "SEAM006", rel, node.lineno, msg,
                    legacy=f"drivers/{fname}:{node.lineno}: reads "
                           f"Option.Speculate directly — drivers consume "
                           f"resolve_speculate's boolean, never the raw "
                           f"knob"))


def _scan_abft(project):
    # point 7: abft.py pure mechanism — no options import, no raises
    purity = list(_mechanism_purity(
        project, ABFT_MODULE, ("options",), "robust/abft.py",
        "imports the options layer — checksum verification must stay "
        "policy-free (the seam is the driver boundary's resolve_abft)",
        "SEAM007",
        missing_tail="(the checksum mechanism module the ABFT layer "
                     "builds on)",
        check_raise=True,
        raise_tail="raises — detection is DATA (AbftCounts folded into "
                   "HealthInfo); policy resolution lives at the driver "
                   "boundary"))
    yield from purity
    if project.module(ABFT_MODULE) is None:
        return  # legacy short-circuit: no boundary checks without abft.py
    # point 8: ABFT boundaries resolve the knob exactly once
    for rel, fns in ABFT_BOUNDARIES:
        mod = project.module(rel)
        if mod is None:
            yield ("SEAM008", Finding(
                "SEAM008", rel, 1, "missing ABFT boundary module",
                legacy=f"{rel}: missing ABFT boundary module"))
            continue
        defs = {n.name: n for n in mod.tree.body
                if isinstance(n, ast.FunctionDef)}
        for fname in fns:
            fn = defs.get(fname)
            if fn is None:
                yield ("SEAM008", Finding(
                    "SEAM008", rel, 1, f"ABFT boundary `{fname}` not found",
                    legacy=f"{rel}: ABFT boundary `{fname}` "
                           f"not found"))
                continue
            n_res = _count_calls(fn, {"resolve_abft"})
            if n_res != 1:
                msg = (f"`{fname}` calls resolve_abft {n_res}x — the knob "
                       f"must be resolved EXACTLY once at the boundary")
                yield ("SEAM008", Finding(
                    "SEAM008", rel, fn.lineno, msg,
                    legacy=f"{rel}:{fn.lineno}: `{fname}` calls "
                           f"resolve_abft {n_res}x — the knob must be "
                           f"resolved EXACTLY once at the boundary"))
    # point 9: every maybe_corrupt call names a site literal in SITES
    sites = _fault_sites(project)
    if not sites:
        yield ("SEAM009", Finding(
            "SEAM009", FAULTS_MODULE, 1, "SITES vocabulary not found",
            legacy="robust/faults.py: SITES vocabulary not found"))
    for rel in _slate_modules(project):
        if rel == FAULTS_MODULE:
            continue
        mod = project.modules[rel]
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            name = (f.id if isinstance(f, ast.Name)
                    else f.attr if isinstance(f, ast.Attribute) else None)
            if name != "maybe_corrupt":
                continue
            if not node.args or not (isinstance(node.args[0], ast.Constant)
                                     and isinstance(node.args[0].value,
                                                    str)):
                msg = ("maybe_corrupt site is not a string literal — sites "
                       "must be a closed, greppable vocabulary")
                yield ("SEAM009", Finding(
                    "SEAM009", rel, node.lineno, msg,
                    legacy=f"{rel}:{node.lineno}: maybe_corrupt site is "
                           f"not a string literal — sites must be a "
                           f"closed, greppable vocabulary"))
            elif sites and node.args[0].value not in sites:
                msg = (f"maybe_corrupt site {node.args[0].value!r} not in "
                       f"faults.SITES")
                yield ("SEAM009", Finding(
                    "SEAM009", rel, node.lineno, msg,
                    legacy=f"{rel}:{node.lineno}: maybe_corrupt site "
                           f"{node.args[0].value!r} not in faults.SITES"))
    # point 10: the raw knob never leaks into a driver module
    for rel in _driver_modules(project):
        mod = project.modules[rel]
        fname = rel.rsplit("/", 1)[1]
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Attribute) and node.attr == "Abft":
                msg = ("reads Option.Abft directly — drivers consume "
                       "resolve_abft's boolean, never the raw knob")
                yield ("SEAM010", Finding(
                    "SEAM010", rel, node.lineno, msg,
                    legacy=f"drivers/{fname}:{node.lineno}: reads "
                           f"Option.Abft directly — drivers consume "
                           f"resolve_abft's boolean, never the raw knob"))


def _scan_driver_contract(project):
    # points 1-3, interleaved per module as the legacy loop did
    for name in CHECKED_MODULES:
        rel = f"{DRIVERS_DIR}/{name}"
        mod = project.module(rel)
        if mod is None:
            yield ("SEAM002", Finding(
                "SEAM002", rel, 1, "missing driver module",
                legacy=f"{name}: missing driver module"))
            continue
        if not _imports_robust(mod.tree):
            msg = ("does not import the robust layer "
                   "(health/faults/recovery) — failures are not routed "
                   "through Option.ErrorPolicy")
            yield ("SEAM002", Finding(
                "SEAM002", rel, 1, msg,
                legacy=f"{name}: does not import the robust layer "
                       f"(health/faults/recovery) — failures are not "
                       f"routed through Option.ErrorPolicy"))
        elif not _references_health(mod.tree):
            msg = ("imports the robust layer but never touches the health "
                   "machinery (finalize/error_policy/HealthInfo) — no "
                   "policy is resolved")
            yield ("SEAM003", Finding(
                "SEAM003", rel, 1, msg,
                legacy=f"{name}: imports the robust layer but never "
                       f"touches the health machinery "
                       f"(finalize/error_policy/HealthInfo) — "
                       f"no policy is resolved"))
        for fn in _public_functions(mod.tree):
            if fn.name in EXEMPT:
                continue
            if not _accepts_opts(fn):
                msg = (f"public driver `{fn.name}` does not accept `opts` "
                       f"— Option.ErrorPolicy cannot reach it")
                yield ("SEAM001", Finding(
                    "SEAM001", rel, fn.lineno, msg,
                    legacy=f"{name}:{fn.lineno}: public driver "
                           f"`{fn.name}` does not accept `opts` — "
                           f"Option.ErrorPolicy cannot reach it"))


def _scan_tune(project):
    # SEAM011: the raw plan cache is tune/'s private substrate.  Drivers
    # and internal kernels consume plans ONLY via resolve_plan (or the
    # plan_override test seam) — never by reading/writing the cache file.
    for rel in _slate_modules(project):
        if rel.startswith(TUNE_DIR + "/") or rel == TUNE_DIR + ".py":
            continue
        mod = project.modules[rel]
        for node in ast.walk(mod.tree):
            name = None
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                name = node.id
            elif isinstance(node, ast.Attribute):
                name = node.attr
            elif isinstance(node, (ast.ImportFrom, ast.Import)):
                aliased = [a.name for a in node.names]
                hits = RAW_PLAN_CACHE_NAMES.intersection(aliased)
                if hits:
                    name = sorted(hits)[0]
            if name in RAW_PLAN_CACHE_NAMES:
                msg = (f"touches the raw autotuner plan cache "
                       f"(`{name}`) outside slate_tpu/tune/ — consume "
                       f"plans via resolve_plan so the cache format has "
                       f"one blast radius")
                yield ("SEAM011", Finding(
                    "SEAM011", rel, node.lineno, msg,
                    legacy=f"{rel}:{node.lineno}: {msg}"))


def _scan_serve(project):
    # SEAM012: serve/ compiles ONLY through serve/cache.py.  The cache is
    # where donation, sentinel suppression, and hit/miss accounting live;
    # a stray jit/lower/compile elsewhere in the package produces
    # executables the obs events never see.
    for rel in _slate_modules(project):
        if not rel.startswith(SERVE_DIR + "/") or rel == SERVE_CACHE_MODULE:
            continue
        mod = project.modules[rel]
        for node in ast.walk(mod.tree):
            name = None
            if isinstance(node, ast.Attribute):
                name = node.attr
            elif isinstance(node, ast.Name) and isinstance(node.ctx,
                                                           ast.Load):
                name = node.id
            elif isinstance(node, (ast.ImportFrom, ast.Import)):
                aliased = [a.name for a in node.names]
                hits = SERVE_COMPILE_NAMES.intersection(aliased)
                if hits:
                    name = sorted(hits)[0]
            if name in SERVE_COMPILE_NAMES:
                msg = (f"compiles directly (`{name}`) inside serve/ — "
                       f"executables come ONLY from serve/cache.py "
                       f"(ExecutableCache.get_or_compile), where donation "
                       f"and compile accounting live")
                yield ("SEAM012", Finding(
                    "SEAM012", rel, node.lineno, msg,
                    legacy=f"{rel}:{node.lineno}: {msg}"))


def _scan_checkpoint(project):
    # SEAM013: checkpoint bytes hit disk ONLY through robust/checkpoint.py.
    # The payload/manifest writers own atomic write-then-rename and the
    # digest computation; the readers own the torn/stale/corrupt refusal
    # ladder.  A driver or tool serializing around them produces snapshots
    # resume() cannot verify.
    for rel in _slate_modules(project):
        if rel == CKPT_MODULE:
            continue
        mod = project.modules[rel]
        for node in ast.walk(mod.tree):
            name = None
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                name = node.id
            elif isinstance(node, ast.Attribute):
                name = node.attr
            elif isinstance(node, (ast.ImportFrom, ast.Import)):
                aliased = [a.name for a in node.names]
                hits = RAW_CKPT_IO_NAMES.intersection(aliased)
                if hits:
                    name = sorted(hits)[0]
            if name in RAW_CKPT_IO_NAMES:
                msg = (f"touches raw checkpoint serialization (`{name}`) "
                       f"outside slate_tpu/robust/checkpoint.py — go "
                       f"through CheckpointManager so the on-disk format "
                       f"and verify ladder have one blast radius")
                yield ("SEAM013", Finding(
                    "SEAM013", rel, node.lineno, msg,
                    legacy=f"{rel}:{node.lineno}: {msg}"))


def _spells_low_precision(node) -> str | None:
    """The low-precision spelling a dtype-expression node carries, if any:
    a string literal ('bfloat16', 'bf16', ...) or a dotted/bare name whose
    terminal attribute is one (jnp.bfloat16, np.float16, ml_dtypes.bfloat16).
    """
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value if node.value.lower() in LOW_PRECISION_SPELLINGS \
            else None
    if isinstance(node, ast.Attribute) and node.attr in \
            LOW_PRECISION_SPELLINGS:
        return node.attr
    if isinstance(node, ast.Name) and node.id in LOW_PRECISION_SPELLINGS:
        return node.id
    return None


def _scan_precision(project):
    # SEAM014a: no literal low-precision cast in drivers/ or serve/ — the
    # precision seam (robust/precision.py demote/promote/round_through) is
    # the only place storage precision changes, so the f32-accumulation
    # contract and the certificate gate cannot be bypassed by a stray
    # .astype(jnp.bfloat16) that silently degrades results.
    for rel in _slate_modules(project):
        if not rel.startswith((DRIVERS_DIR + "/", SERVE_DIR + "/")):
            continue
        mod = project.modules[rel]
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            exprs = []
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr == "astype":
                exprs += node.args[:1]
            exprs += [kw.value for kw in node.keywords
                      if kw.arg == "dtype"]
            for expr in exprs:
                spelling = _spells_low_precision(expr)
                if spelling is not None:
                    msg = (f"casts to low precision (`{spelling}`) inside "
                           f"drivers//serve/ — storage precision changes "
                           f"only through robust/precision.py "
                           f"(demote/promote/round_through), where the "
                           f"f32-accumulation contract lives")
                    yield ("SEAM014", Finding(
                        "SEAM014", rel, node.lineno, msg,
                        legacy=f"{rel}:{node.lineno}: {msg}"))
    # SEAM014b: the raw knob is read only inside the seam and its enum
    # definition.  Exact-match on the `Option` base name so jax's
    # lax.Precision (and any other Precision attribute) never trips it.
    for rel in _slate_modules(project):
        if rel in (PRECISION_MODULE, OPTIONS_MODULE):
            continue
        mod = project.modules[rel]
        for node in ast.walk(mod.tree):
            if (isinstance(node, ast.Attribute)
                    and node.attr == "Precision"
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "Option"):
                msg = ("reads Option.Precision directly — boundaries "
                       "consume resolve_precision's boolean (resolved "
                       "exactly once), never the raw knob")
                yield ("SEAM014", Finding(
                    "SEAM014", rel, node.lineno, msg,
                    legacy=f"{rel}:{node.lineno}: {msg}"))
    # SEAM014c: precision boundaries resolve the knob exactly once, the
    # same resolve-exactly-once contract SEAM005/SEAM008 pin for
    # Speculate and Abft.
    for rel, fns in PRECISION_BOUNDARIES:
        mod = project.module(rel)
        if mod is None:
            yield ("SEAM014", Finding(
                "SEAM014", rel, 1, "missing precision boundary module",
                legacy=f"{rel}: missing precision boundary module"))
            continue
        defs = {n.name: n for n in mod.tree.body
                if isinstance(n, ast.FunctionDef)}
        for fname in fns:
            fn = defs.get(fname)
            if fn is None:
                yield ("SEAM014", Finding(
                    "SEAM014", rel, 1,
                    f"precision boundary `{fname}` not found",
                    legacy=f"{rel}: precision boundary `{fname}` "
                           f"not found"))
                continue
            n_res = _count_calls(fn, {"resolve_precision"})
            if n_res != 1:
                msg = (f"`{fname}` calls resolve_precision {n_res}x — the "
                       f"knob must be resolved EXACTLY once at the "
                       f"boundary")
                yield ("SEAM014", Finding(
                    "SEAM014", rel, fn.lineno, msg,
                    legacy=f"{rel}:{fn.lineno}: {msg}"))


def legacy_report(project) -> list[str]:
    """The pre-migration checker's report lines, in its order, honoring
    per-line suppressions (the legacy checker predates suppressions, so a
    clean repo yields [] under both)."""
    out = []
    for rule_id, f in seam_scan(project):
        mod = project.module(f.path)
        if mod is not None and mod.suppressed(f.line, rule_id):
            continue
        out.append(f.legacy)
    return out


class _SeamRule(Rule):
    def run(self, project):
        for rule_id, finding in seam_scan(project):
            if rule_id == self.id:
                yield finding


def _make(rule_id: str, text: str) -> None:
    cls = type(f"Seam{rule_id[-3:]}", (_SeamRule,),
               {"id": rule_id, "summary": text})
    register(cls)


_make("SEAM001", "public factor/solve drivers accept `opts` — "
      "Option.ErrorPolicy must be routable to every entry point")
_make("SEAM002", "checked driver modules import the robust layer "
      "(health/faults/recovery)")
_make("SEAM003", "checked driver modules reference the health machinery "
      "— an import alone is not a contract")
_make("SEAM004", "internal/rbt.py stays pure mechanism (no options/robust "
      "imports)")
_make("SEAM005", "speculative boundaries resolve_speculate exactly once; "
      "recovery boundaries route bounded_retry + finalize once")
_make("SEAM006", "no driver module reads the raw Option.Speculate knob")
_make("SEAM007", "robust/abft.py stays pure mechanism: no options import, "
      "no raise — detection is data")
_make("SEAM008", "ABFT boundaries resolve_abft exactly once")
_make("SEAM009", "maybe_corrupt sites are string literals from "
      "faults.SITES — a closed, greppable vocabulary")
_make("SEAM010", "no driver module reads the raw Option.Abft knob")
_make("SEAM011", "the raw autotuner plan cache (load/save/cache_path/"
      "record_plan) is only touched inside slate_tpu/tune/ — consumers "
      "go through resolve_plan")
_make("SEAM012", "serve/ obtains executables only through the serve "
      "cache (serve/cache.py) — no jit/lower/compile elsewhere in the "
      "package, so every serving compile is accounted")
_make("SEAM013", "checkpoint serialization (write/read payload+manifest) "
      "only inside robust/checkpoint.py — everyone else goes through "
      "CheckpointManager, so the format and verify ladder have one "
      "blast radius")
_make("SEAM014", "mixed precision is a certified policy: no literal "
      "low-precision cast in drivers//serve/ (the seam is "
      "robust/precision.py), the raw Option.Precision knob is read only "
      "there, and precision boundaries resolve_precision exactly once")
