"""Rule packs.  Importing this package populates the registry."""

from . import collectives, concurrency, obs, seams, trace_safety  # noqa: F401

from ..model import REGISTRY  # noqa: F401  (re-export for convenience)
