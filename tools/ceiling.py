"""Hardware-ceiling + per-piece cost measurements for the headline solvers.

Answers the round-5 profile questions (VERDICT weak #1/#2/#8):
  - raw `jnp.dot` FLOP/s at bench shapes, f32 vs bf16 (is XLA's default f32
    matmul really running at the bf16 MXU rate?)
  - cost of one cyclic<->dense layout round trip at n=16384 (the re-tiling
    overhead the single-target drivers pay per call)
  - per-invocation cost of the XLA panel primitives the blocked solvers
    sequence 32+ times: cholesky(512), triangular_solve(15872x512),
    lu(512x512) single + vmapped over 32 chunks
  - one full potrf step (panel + trsm + trailing syrk) at k=0 vs its gemm

Timing follows bench.py's tunnel discipline: operands as jit args, iters
dependent applications chained in one lax.scan, one scalar fetched.
Each measurement prints one JSON line.  Findings live in docs/PERF.md.
"""

import json
import os
import sys
import time

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

OUT = open(os.path.join(_ROOT, "docs", "ceiling.jsonl"), "a", buffering=1)


def time_chain(body, init, args, iters, reps=3):
    """Seconds per body application (best of reps), chained to be dependent."""

    def chained(c0, *ops):
        c, _ = lax.scan(lambda c, _: (body(c, *ops), None), c0, None,
                        length=iters)
        while getattr(c, "ndim", 0) > 0:
            c = c[(0,) * c.ndim]
        return c

    run = jax.jit(chained)
    np.asarray(jax.device_get(run(init, *args)))
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        np.asarray(jax.device_get(run(init, *args)))
        times.append(time.perf_counter() - t0)
    return min(times) / iters


def emit(name, secs, flops=None, extra=None):
    line = {"probe": name, "ms": round(secs * 1e3, 3)}
    if flops:
        line["gflops"] = round(flops / secs / 1e9, 1)
        line["mfu_vs_197tf"] = round(flops / secs / 197e12, 3)
    if extra:
        line.update(extra)
    print(json.dumps(line), flush=True)
    OUT.write(json.dumps(line) + "\n")


def probe_dot(n, dtype, iters):
    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.standard_normal((n, n)), dtype)
    b = jnp.asarray(rng.standard_normal((n, n)), dtype)

    def body(c, a):
        return (a @ c) * (1.0 / n)

    s = time_chain(body, b, (a,), iters)
    emit(f"dot_n{n}_{jnp.dtype(dtype).name}", s, 2.0 * n**3)


def probe_layout(n, nb, iters):
    """Cost of one dense->tiles->cyclic + back round trip (Grid(1,1))."""
    from slate_tpu.core import layout
    rng = np.random.default_rng(1)
    a = jnp.asarray(rng.standard_normal((n, n)).astype(np.float32))

    def body(c):
        tiles = layout.tile_dense(c, nb, nb)
        cyc = layout.canonical_to_cyclic(tiles, 1, 1)
        can = layout.cyclic_to_canonical(cyc, n // nb, n // nb, 1, 1)
        return layout.untile_dense(can, n, n)

    s = time_chain(lambda c: body(c), a, (), iters)
    emit(f"layout_roundtrip_n{n}_nb{nb}", s,
         extra={"note": "2x pack+unpack passes of n^2 f32"})


def probe_cholesky(nb, iters):
    rng = np.random.default_rng(2)
    a0 = rng.standard_normal((nb, nb)).astype(np.float32)
    a = jnp.asarray(a0 @ a0.T + nb * np.eye(nb, dtype=np.float32))

    def body(c, a):
        l = lax.linalg.cholesky(a * (1 + c * 1e-30))
        return l[0, 0] * 1e-30

    s = time_chain(body, jnp.float32(0.0), (a,), iters)
    emit(f"xla_cholesky_{nb}", s, nb**3 / 3)


def probe_trsm(m, nb, iters):
    rng = np.random.default_rng(3)
    l = jnp.asarray(np.tril(rng.standard_normal((nb, nb))).astype(np.float32)
                    + nb * np.eye(nb, dtype=np.float32))
    b = jnp.asarray(rng.standard_normal((m, nb)).astype(np.float32))

    def body(c, l, b):
        x = lax.linalg.triangular_solve(l, b * (1 + c * 1e-30),
                                        left_side=False, lower=True,
                                        transpose_a=True)
        return x[0, 0] * 1e-30

    s = time_chain(body, jnp.float32(0.0), (l, b), iters)
    emit(f"xla_trsm_{m}x{nb}", s, float(m) * nb * nb)


def probe_lu(nb, batch, iters, rows=None):
    rng = np.random.default_rng(4)
    rows = rows or nb
    shape = (batch, rows, nb) if batch > 1 else (rows, nb)
    a = jnp.asarray(rng.standard_normal(shape).astype(np.float32))

    def body(c, a):
        lu, _, _ = lax.linalg.lu(a * (1 + c * 1e-30))
        return lu[(0,) * lu.ndim] * 1e-30

    s = time_chain(body, jnp.float32(0.0), (a,), iters)
    emit(f"xla_lu_{rows}x{nb}_batch{batch}", s,
         batch * (rows * nb * nb - nb**3 / 3.0))


def probe_full_trsm(n, nrhs, iters):
    """Whole-triangle solve (the potrs/getrs path): L [n, n] vs [n, nrhs]."""
    rng = np.random.default_rng(7)
    l = jnp.asarray((np.tril(rng.standard_normal((n, n)))
                     + n * np.eye(n)).astype(np.float32))
    b = jnp.asarray(rng.standard_normal((n, nrhs)).astype(np.float32))

    def body(c, l, b):
        x = lax.linalg.triangular_solve(l, b * (1 + c * 1e-30),
                                        left_side=True, lower=True)
        return x[0, 0] * 1e-30

    s = time_chain(body, jnp.float32(0.0), (l, b), iters)
    emit(f"xla_full_trsm_{n}x{nrhs}", s, float(n) * n * nrhs)


def probe_full_chol(n, iters):
    """XLA's own monolithic cholesky(n) — the vendor bar for potrf."""
    rng = np.random.default_rng(8)
    a0 = rng.standard_normal((n, n)).astype(np.float32) * 0.001
    a = jnp.asarray(a0 + a0.T + 4 * np.eye(n, dtype=np.float32))

    def body(c, a):
        l = lax.linalg.cholesky(a * (1 + c * 1e-30))
        return l[0, 0] * 1e-30

    s = time_chain(body, jnp.float32(0.0), (a,), iters)
    emit(f"xla_full_cholesky_{n}", s, n**3 / 3)


def probe_full_qr(m, n, iters):
    """XLA's monolithic qr — the vendor bar for geqrf tall-skinny."""
    rng = np.random.default_rng(9)
    a = jnp.asarray(rng.standard_normal((m, n)).astype(np.float32))

    def body(c, a):
        q, r = lax.linalg.qr(a * (1 + c * 1e-30), full_matrices=False)
        return r[0, 0] * 1e-30

    s = time_chain(body, jnp.float32(0.0), (a,), iters)
    emit(f"xla_full_qr_{m}x{n}", s, 2.0 * m * n * n - 2.0 * n**3 / 3)


def probe_potrf_step(n, nb, iters):
    """One right-looking potrf step at k=0: panel chol + trsm + full syrk."""
    rng = np.random.default_rng(5)
    a0 = rng.standard_normal((n, n)).astype(np.float32) * 0.001
    a = jnp.asarray(a0 + a0.T + 4 * np.eye(n, dtype=np.float32))

    def body(c, a):
        a = a * (1 + c * 1e-30)
        lkk = lax.linalg.cholesky(a[:nb, :nb])
        panel = lax.linalg.triangular_solve(
            lkk, a[nb:, :nb], left_side=False, lower=True, transpose_a=True)
        upd = a[nb:, nb:] - panel @ panel.T
        return upd[0, 0] * 1e-30

    s = time_chain(body, jnp.float32(0.0), (a,), iters)
    gemm_flops = 2.0 * (n - nb) ** 2 * nb
    emit(f"potrf_step_n{n}_nb{nb}", s, gemm_flops,
         {"note": "chol+trsm+full-square syrk; flops = syrk as full gemm"})


def probe_qr_panel(m, nb, iters):
    from slate_tpu.internal.qr import householder_panel_blocked
    rng = np.random.default_rng(6)
    a = jnp.asarray(rng.standard_normal((m, nb)).astype(np.float32))

    def body(c, a):
        v, t = householder_panel_blocked(a * (1 + c * 1e-30))
        return v[0, 0] * 1e-30

    s = time_chain(body, jnp.float32(0.0), (a,), iters)
    emit(f"qr_panel_{m}x{nb}", s, 2.0 * m * nb * nb)


GROUPS = {
    "dots": lambda: [probe_dot(n, dt, it)
                     for n, it in ((4096, 30), (8192, 10), (16384, 4))
                     for dt in (jnp.float32, jnp.bfloat16)],
    "panels": lambda: [probe_trsm(15872, 512, 20),
                       probe_lu(512, 1, 30),
                       probe_lu(512, 32, 10)],
    "chols": lambda: [probe_layout(16384, 512, 8),
                      probe_cholesky(512, 50),
                      probe_cholesky(1024, 20)],
    "layouts": lambda: [probe_layout(4096, 256, 30),
                        probe_layout(8192, 512, 10),
                        probe_full_trsm(16384, 256, 6)],
    "fulls": lambda: [probe_layout(16384, 512, 8),
                      probe_full_chol(16384, 3),
                      probe_full_qr(131072, 1024, 3)],
    "lutall": lambda: [probe_lu(512, 1, 6, rows=4096),
                       probe_lu(512, 4, 4, rows=4096),
                       probe_lu(512, 1, 4, rows=16384),
                       probe_lu(1024, 1, 4, rows=16384)],
    "lufull": lambda: [probe_lu(16384, 1, 2)],
    "steps": lambda: [probe_potrf_step(16384, 512, 6),
                      probe_potrf_step(16384, 1024, 6),
                      probe_qr_panel(131072, 256, 10),
                      probe_qr_panel(131072, 512, 10)],
}


def main():
    dev = jax.devices()[0].device_kind
    print(json.dumps({"probe": "device", "kind": dev}), flush=True)
    for name in (sys.argv[1:] or list(GROUPS)):
        GROUPS[name]()


if __name__ == "__main__":
    main()
