#!/usr/bin/env python
"""Static check: every public factor/solve driver honors the robustness
contract (docs/ROBUSTNESS.md).

Three assertions, enforced by AST inspection (no imports, no jax, runs
anywhere):

1. every public driver function in the checked modules accepts an ``opts``
   parameter — Option.ErrorPolicy must be routable to every entry point;
2. every checked module routes failures through the robust layer — it
   imports from ``slate_tpu.robust`` (health / faults / recovery /
   certify) at module level or inside a function body;
3. every checked module actually RESOLVES a policy: it references the
   health machinery (``finalize`` / ``finalize_flat`` / ``error_policy``
   / ``HealthInfo``) somewhere in its body — an import alone is not a
   contract.

Plus the speculation-seam contract (Option.Speculate, docs/ROBUSTNESS.md):

4. ``internal/rbt.py`` stays pure mechanism — it must not import the
   options or robust layers (the policy seam lives in drivers/lu.py and
   robust/recovery.py);
5. every speculative boundary function (recovery.py's
   gesv/gels/hesv_with_recovery, mixed.py's gesv_mixed) calls
   ``resolve_speculate`` EXACTLY once — the knob is resolved at the
   driver boundary like ErrorPolicy, never re-read downstream — and the
   recovery boundaries route through ``bounded_retry`` and finalize the
   (result, HealthInfo) pair exactly once;
6. no driver module reads the raw ``Option.Speculate`` knob — drivers
   consume the resolved boolean, the enum never leaks past the boundary.

Plus the ABFT-seam contract (Option.Abft, docs/ROBUSTNESS.md):

7. ``robust/abft.py`` stays pure mechanism — no options import, no
   ``raise`` statements: detection/correction is data (AbftCounts), the
   driver boundary folds it into HealthInfo and resolves policy;
8. every ABFT boundary (lu._getrf, cholesky.potrf, blas3.gemm/trsm,
   recovery's gesv/posv_with_recovery) calls ``resolve_abft`` EXACTLY
   once — resolved at the boundary like ErrorPolicy and Speculate;
9. every ``maybe_corrupt`` call site names its fault site as a string
   literal that exists in ``faults.SITES`` — injectable sites are a
   closed, greppable vocabulary;
10. no driver module reads the raw ``Option.Abft`` knob.

Runnable as a main (exit 1 + report on violation) and as pytest via
tests/test_error_contracts.py.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
DRIVERS = REPO / "slate_tpu" / "drivers"

# the factor/solve surface: modules whose failures are numerical
CHECKED_MODULES = (
    "lu.py", "cholesky.py", "band.py", "mixed.py", "qr.py",
    # the certified spectral stack
    "heev.py", "svd.py", "stedc.py", "hetrf.py", "inverse.py",
    "condest.py",
)

# public callables that are not drivers (constructors, helpers) or whose
# contract predates opts (factor-object methods)
EXEMPT = {
    "tree_flatten", "tree_unflatten", "lower", "upper",
    # norm1est is an estimator primitive taking raw appliers, not a
    # driver: its failure resolution (inf, never NaN) is value-level
    "norm1est",
    # *_info compute APIs always return (result, HealthInfo) — there is
    # no policy to route, the caller resolves it
    "stedc_info",
}

# names whose presence shows the module resolves ErrorPolicy through the
# health layer rather than merely importing it
HEALTH_NAMES = {"finalize", "finalize_flat", "error_policy", "HealthInfo",
                "from_pivots", "from_result"}


def _public_functions(tree: ast.Module):
    for node in tree.body:
        if isinstance(node, ast.FunctionDef) and not node.name.startswith("_"):
            yield node


def _accepts_opts(fn: ast.FunctionDef) -> bool:
    names = [a.arg for a in fn.args.args + fn.args.kwonlyargs]
    return "opts" in names or fn.args.kwarg is not None


def _imports_robust(tree: ast.Module) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module:
            mod = node.module
            if "robust" in mod.split("."):
                return True
            if mod.endswith("robust") or ".robust." in f".{mod}.":
                return True
        if isinstance(node, ast.Import):
            if any("robust" in alias.name.split(".")
                   for alias in node.names):
                return True
    return False


def _references_health(tree: ast.Module) -> bool:
    """True when the module calls into the health machinery — a Name or
    Attribute access of one of HEALTH_NAMES anywhere in the body."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Attribute) and node.attr in HEALTH_NAMES:
            return True
        if isinstance(node, ast.Name) and node.id in HEALTH_NAMES:
            return True
    return False


# speculation boundaries: file -> functions that must resolve the knob
# exactly once (and, for the recovery ones, retry + finalize exactly once)
SPECULATIVE_BOUNDARIES = {
    REPO / "slate_tpu" / "robust" / "recovery.py":
        ("gesv_with_recovery", "gels_with_recovery", "hesv_with_recovery"),
    DRIVERS / "mixed.py": ("gesv_mixed",),
}
RECOVERY_BOUNDARIES = {"gesv_with_recovery", "gels_with_recovery",
                       "hesv_with_recovery"}
RBT_MODULE = REPO / "slate_tpu" / "internal" / "rbt.py"
FINALIZE_NAMES = {"finalize", "_finalize_solve"}


def _count_calls(fn: ast.FunctionDef, names: set[str]) -> int:
    c = 0
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Name) and f.id in names:
                c += 1
            elif isinstance(f, ast.Attribute) and f.attr in names:
                c += 1
    return c


def _check_speculation() -> list[str]:
    problems = []
    # 4. rbt.py: pure mechanism, policy-free
    if not RBT_MODULE.exists():
        problems.append("internal/rbt.py: missing (the RBT mechanism "
                        "module the speculative gesv path builds on)")
    else:
        tree = ast.parse(RBT_MODULE.read_text(), filename=str(RBT_MODULE))
        for node in ast.walk(tree):
            mods = []
            if isinstance(node, ast.ImportFrom) and node.module:
                mods = node.module.split(".")
            elif isinstance(node, ast.Import):
                mods = [s for a in node.names for s in a.name.split(".")]
            if "options" in mods or "robust" in mods:
                problems.append(
                    f"internal/rbt.py:{node.lineno}: imports the "
                    f"options/robust layer — the butterfly mechanism must "
                    f"stay policy-free (the seam is drivers/lu.py + "
                    f"robust/recovery.py)")
    # 5. boundary functions resolve the knob exactly once
    for path, fns in SPECULATIVE_BOUNDARIES.items():
        rel = path.relative_to(REPO)
        if not path.exists():
            problems.append(f"{rel}: missing speculative boundary module")
            continue
        tree = ast.parse(path.read_text(), filename=str(path))
        defs = {n.name: n for n in tree.body
                if isinstance(n, ast.FunctionDef)}
        for fname in fns:
            fn = defs.get(fname)
            if fn is None:
                problems.append(f"{rel}: speculative boundary "
                                f"`{fname}` not found")
                continue
            n_res = _count_calls(fn, {"resolve_speculate"})
            if n_res != 1:
                problems.append(
                    f"{rel}:{fn.lineno}: `{fname}` calls "
                    f"resolve_speculate {n_res}x — the knob must be "
                    f"resolved EXACTLY once at the boundary")
            if fname in RECOVERY_BOUNDARIES:
                if _count_calls(fn, {"bounded_retry"}) < 1:
                    problems.append(
                        f"{rel}:{fn.lineno}: `{fname}` never routes "
                        f"through bounded_retry — speculation has no "
                        f"escalation path")
                n_fin = _count_calls(fn, FINALIZE_NAMES)
                if n_fin != 1:
                    problems.append(
                        f"{rel}:{fn.lineno}: `{fname}` finalizes "
                        f"{n_fin}x — the (result, HealthInfo) pair must "
                        f"resolve ErrorPolicy exactly once")
    # 6. the raw knob never leaks into a driver module
    for path in sorted(DRIVERS.glob("*.py")):
        tree = ast.parse(path.read_text(), filename=str(path))
        for node in ast.walk(tree):
            if isinstance(node, ast.Attribute) and node.attr == "Speculate":
                problems.append(
                    f"drivers/{path.name}:{node.lineno}: reads "
                    f"Option.Speculate directly — drivers consume "
                    f"resolve_speculate's boolean, never the raw knob")
    return problems


ABFT_MODULE = REPO / "slate_tpu" / "robust" / "abft.py"
FAULTS_MODULE = REPO / "slate_tpu" / "robust" / "faults.py"
ABFT_BOUNDARIES = {
    DRIVERS / "lu.py": ("_getrf",),
    DRIVERS / "cholesky.py": ("potrf",),
    DRIVERS / "blas3.py": ("gemm", "trsm"),
    REPO / "slate_tpu" / "robust" / "recovery.py":
        ("gesv_with_recovery", "posv_with_recovery"),
}


def _fault_sites() -> set[str]:
    """The SITES vocabulary, read from faults.py's AST (no import)."""
    tree = ast.parse(FAULTS_MODULE.read_text(), filename=str(FAULTS_MODULE))
    for node in tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = [t.id for t in node.targets
                       if isinstance(t, ast.Name)]
        elif isinstance(node, ast.AnnAssign) and isinstance(node.target,
                                                            ast.Name):
            targets = [node.target.id]
        if "SITES" in targets and node.value is not None:
            return {c.value for c in ast.walk(node.value)
                    if isinstance(c, ast.Constant)
                    and isinstance(c.value, str)}
    return set()


def _check_abft() -> list[str]:
    problems = []
    # 7. abft.py: pure mechanism — no options import, no raises
    if not ABFT_MODULE.exists():
        problems.append("robust/abft.py: missing (the checksum mechanism "
                        "module the ABFT layer builds on)")
        return problems
    tree = ast.parse(ABFT_MODULE.read_text(), filename=str(ABFT_MODULE))
    for node in ast.walk(tree):
        mods = []
        if isinstance(node, ast.ImportFrom) and node.module:
            mods = node.module.split(".")
        elif isinstance(node, ast.Import):
            mods = [s for a in node.names for s in a.name.split(".")]
        if "options" in mods:
            problems.append(
                f"robust/abft.py:{node.lineno}: imports the options "
                f"layer — checksum verification must stay policy-free "
                f"(the seam is the driver boundary's resolve_abft)")
        if isinstance(node, ast.Raise):
            problems.append(
                f"robust/abft.py:{node.lineno}: raises — detection is "
                f"DATA (AbftCounts folded into HealthInfo); policy "
                f"resolution lives at the driver boundary")
    # 8. ABFT boundaries resolve the knob exactly once
    for path, fns in ABFT_BOUNDARIES.items():
        rel = path.relative_to(REPO)
        if not path.exists():
            problems.append(f"{rel}: missing ABFT boundary module")
            continue
        btree = ast.parse(path.read_text(), filename=str(path))
        defs = {n.name: n for n in btree.body
                if isinstance(n, ast.FunctionDef)}
        for fname in fns:
            fn = defs.get(fname)
            if fn is None:
                problems.append(f"{rel}: ABFT boundary `{fname}` "
                                f"not found")
                continue
            n_res = _count_calls(fn, {"resolve_abft"})
            if n_res != 1:
                problems.append(
                    f"{rel}:{fn.lineno}: `{fname}` calls resolve_abft "
                    f"{n_res}x — the knob must be resolved EXACTLY once "
                    f"at the boundary")
    # 9. every maybe_corrupt call names a site literal from faults.SITES
    sites = _fault_sites()
    if not sites:
        problems.append("robust/faults.py: SITES vocabulary not found")
    for path in sorted((REPO / "slate_tpu").rglob("*.py")):
        ptree = ast.parse(path.read_text(), filename=str(path))
        rel = path.relative_to(REPO)
        for node in ast.walk(ptree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            name = (f.id if isinstance(f, ast.Name)
                    else f.attr if isinstance(f, ast.Attribute) else None)
            if name != "maybe_corrupt" or path == FAULTS_MODULE:
                continue
            if not node.args or not (isinstance(node.args[0], ast.Constant)
                                     and isinstance(node.args[0].value,
                                                    str)):
                problems.append(
                    f"{rel}:{node.lineno}: maybe_corrupt site is not a "
                    f"string literal — sites must be a closed, greppable "
                    f"vocabulary")
            elif sites and node.args[0].value not in sites:
                problems.append(
                    f"{rel}:{node.lineno}: maybe_corrupt site "
                    f"{node.args[0].value!r} not in faults.SITES")
    # 10. the raw knob never leaks into a driver module
    for path in sorted(DRIVERS.glob("*.py")):
        dtree = ast.parse(path.read_text(), filename=str(path))
        for node in ast.walk(dtree):
            if isinstance(node, ast.Attribute) and node.attr == "Abft":
                problems.append(
                    f"drivers/{path.name}:{node.lineno}: reads "
                    f"Option.Abft directly — drivers consume "
                    f"resolve_abft's boolean, never the raw knob")
    return problems


def check() -> list[str]:
    problems = _check_speculation() + _check_abft()
    for name in CHECKED_MODULES:
        path = DRIVERS / name
        if not path.exists():
            problems.append(f"{name}: missing driver module")
            continue
        tree = ast.parse(path.read_text(), filename=str(path))
        if not _imports_robust(tree):
            problems.append(
                f"{name}: does not import the robust layer "
                f"(health/faults/recovery) — failures are not routed "
                f"through Option.ErrorPolicy")
        elif not _references_health(tree):
            problems.append(
                f"{name}: imports the robust layer but never touches the "
                f"health machinery (finalize/error_policy/HealthInfo) — "
                f"no policy is resolved")
        for fn in _public_functions(tree):
            if fn.name in EXEMPT:
                continue
            if not _accepts_opts(fn):
                problems.append(
                    f"{name}:{fn.lineno}: public driver `{fn.name}` "
                    f"does not accept `opts` — Option.ErrorPolicy cannot "
                    f"reach it")
    return problems


def main() -> int:
    problems = check()
    if problems:
        print("error-contract violations:")
        for p in problems:
            print(f"  {p}")
        return 1
    print(f"error contracts OK across {len(CHECKED_MODULES)} driver modules")
    return 0


if __name__ == "__main__":
    sys.exit(main())
