#!/usr/bin/env python
"""Static check: every public factor/solve driver honors the robustness
contract (docs/ROBUSTNESS.md).

This is now a thin shim over the slate-lint seam rule pack
(``tools/slate_lint/rules/seams.py``, rules SEAM001-SEAM010) — the ten
assertions documented there were migrated from this file verbatim, and
the pack preserves this checker's report text and ordering byte-for-byte
(each Finding carries the ``legacy`` string).  Kept because:

- tests/test_error_contracts.py and CI invoke it by this name;
- ``python tools/check_error_contracts.py`` remains the quick
  seam-contract-only entry point (the full analyzer is
  ``python -m tools.slate_lint``).

Exit codes are unchanged: 0 clean, 1 with a violation report.
"""

from __future__ import annotations

import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:  # the test imports this file top-level
    sys.path.insert(0, str(REPO))

from tools.slate_lint.loader import load_project  # noqa: E402
from tools.slate_lint.rules.seams import (  # noqa: E402,F401
    # re-exported configuration (public knobs of the old checker)
    ABFT_BOUNDARIES,
    ABFT_MODULE,
    CHECKED_MODULES,
    EXEMPT,
    FINALIZE_NAMES,
    HEALTH_NAMES,
    RBT_MODULE,
    RECOVERY_BOUNDARIES,
    SPECULATIVE_BOUNDARIES,
    legacy_report,
)


def check() -> list[str]:
    """Violation report lines, [] when every contract holds."""
    return legacy_report(load_project(REPO))


def main() -> int:
    problems = check()
    if problems:
        print("error-contract violations:")
        for p in problems:
            print(f"  {p}")
        return 1
    print(f"error contracts OK across {len(CHECKED_MODULES)} driver modules")
    return 0


if __name__ == "__main__":
    sys.exit(main())
