"""Aasen symmetric-indefinite tests (analog of ref test/test_hesv.cc):
factorization residual P A P^H = L T L^H and solve residual vs numpy."""

import numpy as np
import pytest

import slate_tpu as st


def herm_indef(rng, n, dtype=np.float64):
    a = rng.standard_normal((n, n)).astype(dtype)
    if np.issubdtype(dtype, np.complexfloating):
        a = a + 1j * rng.standard_normal((n, n))
    a = (a + a.conj().T) / 2
    # shift to make it clearly indefinite
    w = np.linalg.eigvalsh(a)
    a -= np.mean(w) * np.eye(n)
    return a


def tridiag(d, e):
    n = len(d)
    T = np.diag(d.astype(complex if np.iscomplexobj(e) else float))
    if n > 1:
        T = T + np.diag(e, -1) + np.diag(np.conj(e), 1)
    return T


@pytest.mark.parametrize("n,nb", [(16, 4), (23, 5), (8, 8), (1, 4), (2, 4)])
def test_hetrf_residual(rng, n, nb):
    a = herm_indef(rng, n)
    A = st.SymmetricMatrix.from_numpy(a, nb)
    F = st.hetrf(A)
    L = np.asarray(F.L)
    T = tridiag(np.asarray(F.d), np.asarray(F.e))
    piv = np.asarray(F.piv)
    ap = a[piv][:, piv]
    np.testing.assert_allclose(L @ T @ L.conj().T, ap, atol=1e-10)
    # L unit lower, first column e_0
    np.testing.assert_allclose(np.triu(L, 1), 0, atol=0)
    np.testing.assert_allclose(np.diagonal(L), 1, atol=1e-14)
    np.testing.assert_allclose(L[1:, 0], 0, atol=0)


def test_hetrf_complex(rng):
    n, nb = 14, 4
    a = herm_indef(rng, n, np.complex128)
    F = st.hetrf(st.HermitianMatrix.from_numpy(a, nb))
    L = np.asarray(F.L)
    T = tridiag(np.asarray(F.d), np.asarray(F.e))
    piv = np.asarray(F.piv)
    np.testing.assert_allclose(L @ T @ L.conj().T, a[piv][:, piv],
                               atol=1e-10)


@pytest.mark.parametrize("n,nb,nrhs", [(16, 4, 3), (25, 8, 1)])
def test_hesv(rng, n, nb, nrhs):
    a = herm_indef(rng, n)
    b = rng.standard_normal((n, nrhs))
    F, X = st.hesv(st.SymmetricMatrix.from_numpy(a, nb),
                   st.Matrix.from_numpy(b, nb, nb))
    np.testing.assert_allclose(a @ X.to_numpy(), b, atol=1e-9)


def test_hesv_complex(rng):
    n, nb = 12, 4
    a = herm_indef(rng, n, np.complex128)
    b = rng.standard_normal((n, 2)) + 1j * rng.standard_normal((n, 2))
    F, X = st.hesv(st.HermitianMatrix.from_numpy(a, nb),
                   st.Matrix.from_numpy(b, nb, nb))
    np.testing.assert_allclose(a @ X.to_numpy(), b, atol=1e-9)


def test_hesv_singularish(rng):
    # pivoting must handle a zero leading principal minor
    n, nb = 8, 4
    a = herm_indef(rng, n)
    a[0, 0] = 0.0
    b = rng.standard_normal((n, 1))
    F, X = st.hesv(st.SymmetricMatrix.from_numpy(a, nb),
                   st.Matrix.from_numpy(b, nb, nb))
    np.testing.assert_allclose(a @ X.to_numpy(), b, atol=1e-8)
