"""Blocked-Aasen symmetric-indefinite tests (analog of ref
test/test_hesv.cc): factorization residual P A P^H = L T L^H with band T,
structure checks, and solve residual vs numpy."""

import numpy as np
import pytest

import slate_tpu as st


def herm_indef(rng, n, dtype=np.float64):
    a = rng.standard_normal((n, n)).astype(dtype)
    if np.issubdtype(dtype, np.complexfloating):
        a = a + 1j * rng.standard_normal((n, n))
    a = (a + a.conj().T) / 2
    # shift to make it clearly indefinite; at n == 1 the shift would
    # annihilate the scalar exactly (mean eigenvalue == the entry) and
    # hetrf rightly refuses a zero pivot — any nonzero 1x1 will do
    if n > 1:
        w = np.linalg.eigvalsh(a)
        a -= np.mean(w) * np.eye(n)
    return a


@pytest.mark.parametrize("n,nb", [
    (16, 4), (2, 4),
    # every distinct (n, nb) costs ~10-60 s of single-core eager
    # compile on the CPU tier; broader shapes run in the slow tier
    pytest.param(23, 5, marks=pytest.mark.slow),
    pytest.param(8, 8, marks=pytest.mark.slow),
    pytest.param(1, 4, marks=pytest.mark.slow),
    pytest.param(40, 8, marks=pytest.mark.slow)])
def test_hetrf_residual(rng, n, nb):
    a = herm_indef(rng, n)
    A = st.SymmetricMatrix.from_numpy(a, nb)
    F = st.hetrf(A)
    L = np.asarray(F.L)
    T = np.asarray(F.T_dense())
    piv = np.asarray(F.piv)
    ap = a[piv][:, piv]
    np.testing.assert_allclose(L @ T @ L.conj().T, ap, atol=1e-10)
    # L unit lower, first block column [I; 0]
    np.testing.assert_allclose(np.triu(L, 1), 0, atol=0)
    np.testing.assert_allclose(np.diagonal(L), 1, atol=1e-14)
    w0 = min(n, nb)
    np.testing.assert_allclose(L[:, :w0], np.eye(n, w0), atol=0)
    # T is a Hermitian band of bandwidth nb with upper-triangular
    # subdiagonal blocks (the panel LU's U factors, ref hetrf.cc)
    np.testing.assert_allclose(T, T.conj().T, atol=1e-12)
    np.testing.assert_allclose(np.tril(T, -(nb + 1)), 0, atol=0)
    if F.Tsub.shape[0] and n > nb:
        for j in range(F.Tdiag.shape[0] - 1):
            np.testing.assert_allclose(
                np.tril(np.asarray(F.Tsub[j]), -1), 0, atol=0)


@pytest.mark.slow
def test_hetrf_complex(rng):
    n, nb = 14, 4
    a = herm_indef(rng, n, np.complex128)
    F = st.hetrf(st.HermitianMatrix.from_numpy(a, nb))
    L = np.asarray(F.L)
    T = np.asarray(F.T_dense())
    piv = np.asarray(F.piv)
    np.testing.assert_allclose(L @ T @ L.conj().T, a[piv][:, piv],
                               atol=1e-10)


@pytest.mark.parametrize("n,nb,nrhs", [
    (16, 4, 3), pytest.param(25, 8, 1, marks=pytest.mark.slow)])
def test_hesv(rng, n, nb, nrhs):
    a = herm_indef(rng, n)
    b = rng.standard_normal((n, nrhs))
    F, X = st.hesv(st.SymmetricMatrix.from_numpy(a, nb),
                   st.Matrix.from_numpy(b, nb, nb))
    np.testing.assert_allclose(a @ X.to_numpy(), b, atol=1e-9)


def test_hesv_complex(rng):
    n, nb = 12, 4
    a = herm_indef(rng, n, np.complex128)
    b = rng.standard_normal((n, 2)) + 1j * rng.standard_normal((n, 2))
    F, X = st.hesv(st.HermitianMatrix.from_numpy(a, nb),
                   st.Matrix.from_numpy(b, nb, nb))
    np.testing.assert_allclose(a @ X.to_numpy(), b, atol=1e-9)


def test_hesv_singularish(rng):
    # pivoting must handle a zero leading principal minor
    n, nb = 8, 4
    a = herm_indef(rng, n)
    a[0, 0] = 0.0
    b = rng.standard_normal((n, 1))
    F, X = st.hesv(st.SymmetricMatrix.from_numpy(a, nb),
                   st.Matrix.from_numpy(b, nb, nb))
    np.testing.assert_allclose(a @ X.to_numpy(), b, atol=1e-8)


@pytest.mark.slow
def test_hesv_moderate_n(rng):
    """Blocked path at a few hundred rows: the hot op is panel gemms, so
    this must run in seconds, with a well-scaled residual."""
    n, nb = 384, 64
    a = herm_indef(rng, n)
    b = rng.standard_normal((n, 4))
    F, X = st.hesv(st.SymmetricMatrix.from_numpy(a, nb),
                   st.Matrix.from_numpy(b, nb, nb))
    resid = np.linalg.norm(a @ X.to_numpy() - b) / (
        np.linalg.norm(a) * np.linalg.norm(X.to_numpy()))
    assert resid < 1e-13


def test_hesv_zero_offdiag_block(rng):
    """Block-diagonal matrix: the panel R is exactly zero, every pivot
    contest ties at 0 — pivots must stay within the live rows (a pad-row
    pick would leak an out-of-range index into piv)."""
    n, nb = 10, 4
    d1 = herm_indef(rng, 6)
    d2 = herm_indef(rng, 4)
    a = np.zeros((n, n))
    a[:6, :6] = d1
    a[6:, 6:] = d2
    b = rng.standard_normal((n, 2))
    F, X = st.hesv(st.SymmetricMatrix.from_numpy(a, nb),
                   st.Matrix.from_numpy(b, nb, nb))
    assert int(np.max(np.asarray(F.piv))) < n
    np.testing.assert_allclose(a @ X.to_numpy(), b, atol=1e-8)


@pytest.mark.parametrize("p,q", [(2, 2), (2, 4)])
@pytest.mark.slow
def test_hesv_mesh(rng, p, q):
    # mesh Aasen: A expanded row-sharded (never replicated), hot gemm
    # row-parallel (ref: src/hetrf.cc distributed panel/update gemms)
    import jax
    n, nb, nrhs = 40, 4, 3
    g = st.Grid(p, q, devices=jax.devices()[:p * q])
    a = rng.standard_normal((n, n))
    a = (a + a.T) / 2
    b = rng.standard_normal((n, nrhs))
    A = st.HermitianMatrix.from_numpy(a, nb, st.Uplo.Lower, g)
    B = st.Matrix.from_numpy(b, nb, nb, g)
    F, X = st.hesv(A, B)
    x = X.to_numpy()
    assert np.abs(a @ x - b).max() / (np.abs(a).max() * n) < 1e-11
