"""vmap-cleanliness parity suite for the hot solve drivers (PR 10).

The serving layer (slate_tpu/serve/) executes shape-bucketed BATCHES by
vmapping the drivers, so gesv / posv / gels must be vmap-clean end to
end: same numbers as a per-problem loop, HealthInfo batched as a
leading-axis pytree (every leaf gains the batch dim — nothing inside a
driver may concretize a traced health value on the way out), and
per-problem ABFT counters.

Also pins the policy-seam regression this PR fixed: gels' direct
Householder-QR route (m < 3n, speculation off) used to return a bare X
under ErrorPolicy.Info instead of (X, h) — unnoticeable eagerly if the
caller ignored health, fatal under vmap where the tuple arity is part
of the batched pytree structure.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import slate_tpu as st
from slate_tpu.core.storage import TileStorage
from slate_tpu.options import Option
from slate_tpu.robust import faults

INFO = {Option.ErrorPolicy: "info"}
NB = 16
HEALTH_LEAVES = 10  # HealthInfo field count (arity change = update serve/)


def _mat(dense):
    return st.Matrix(TileStorage.from_dense(dense, NB, NB))


def _gesv_one(ad, bd):
    F, X, h = st.gesv(_mat(ad), _mat(bd), INFO)
    return X.to_dense(), h


def _posv_one(ad, bd):
    H = st.HermitianMatrix._from_view(_mat(ad), st.Uplo.Lower)
    F, X, h = st.posv(H, _mat(bd), INFO)
    return X.to_dense(), h


def _gels_one(ad, bd):
    X, h = st.gels(_mat(ad), _mat(bd), INFO)
    return X.to_dense(), h


def _problems(rng, op, dtype, batch=3, n=32, k=5):
    a = rng.standard_normal((batch, n, n)).astype(dtype)
    b = rng.standard_normal((batch, n, k)).astype(dtype)
    if op == "posv":
        a = (np.einsum("bij,bkj->bik", a, a) / n
             + np.eye(n, dtype=dtype)[None]).astype(dtype)
    elif op == "gesv":
        a = a + np.eye(n, dtype=dtype)[None] * 4
    else:  # gels: tall
        m = n + 24
        a = rng.standard_normal((batch, m, n)).astype(dtype)
        b = rng.standard_normal((batch, m, k)).astype(dtype)
    return a, b


ONE = {"gesv": _gesv_one, "posv": _posv_one, "gels": _gels_one}


@pytest.mark.parametrize("dtype", [np.float32, np.float64])
@pytest.mark.parametrize("op", ["gesv", "posv", "gels"])
def test_vmap_matches_per_problem_loop(rng, op, dtype):
    """vmap(driver) agrees with [driver(p) for p] — results and health.
    Tolerance is a small multiple of eps: batched GEMMs reassociate, so
    bitwise equality is not on the table, but the error must stay at
    rounding level (same algorithm, same escalation decisions)."""
    a, b = _problems(rng, op, dtype)
    tol = 200 * np.finfo(dtype).eps
    one = ONE[op]
    xv, hv = jax.vmap(one)(jnp.asarray(a), jnp.asarray(b))
    for i in range(a.shape[0]):
        xi, hi = one(jnp.asarray(a[i]), jnp.asarray(b[i]))
        scale = float(np.abs(np.asarray(xi)).max())
        np.testing.assert_allclose(np.asarray(xv[i]), np.asarray(xi),
                                   atol=tol * scale, rtol=0)
        # health: discrete leaves exact, float diagnostics at rounding
        for name, lv, li in zip(hv._fields, hv, hi):
            got, want = np.asarray(lv[i]), np.asarray(li)
            if np.issubdtype(want.dtype, np.floating):
                np.testing.assert_allclose(got, want, rtol=1e-3,
                                           err_msg=name)
            else:
                np.testing.assert_array_equal(got, want, err_msg=name)


@pytest.mark.parametrize("op", ["gesv", "posv", "gels"])
def test_health_batches_as_leading_axis_pytree(rng, op):
    """Every HealthInfo leaf gains the batch dim; .ok stays computable."""
    batch = 4
    a, b = _problems(rng, op, np.float64, batch=batch)
    _, h = jax.vmap(ONE[op])(jnp.asarray(a), jnp.asarray(b))
    leaves = jax.tree_util.tree_leaves(h)
    assert len(leaves) == HEALTH_LEAVES
    for leaf in leaves:
        assert leaf.shape[0] == batch, leaf.shape
    assert np.asarray(h.ok).shape == (batch,)
    assert np.asarray(h.ok).all()


def test_vmap_abft_counters_are_per_problem(rng):
    """Under vmap with a bitflip injected into the factor panel, every
    problem detects and corrects ITS OWN strike: counters (not scalars
    silently shared across the batch) come back with shape (batch,),
    and the repaired results still match the reference solve."""
    n, batch = 32, 3
    a = rng.standard_normal((batch, n, n)) + np.eye(n)[None] * n
    b = rng.standard_normal((batch, n, 8))
    abft_opts = {Option.ErrorPolicy: "info", Option.Abft: "on"}

    def run(ad, bd):
        F, X, h = st.gesv(_mat(ad), _mat(bd), abft_opts)
        return X.to_dense(), h

    x, h = jax.vmap(run)(jnp.asarray(a), jnp.asarray(b))
    assert np.asarray(h.abft_detected).shape == (batch,)
    assert (np.asarray(h.abft_detected) == 0).all()

    plan = faults.FaultPlan("post_panel", kind="bitflip", seed=5,
                            tile=(n // NB - 1, 0), nb=NB)
    with faults.inject(plan):
        x, h = jax.vmap(run)(jnp.asarray(a), jnp.asarray(b))
    np.testing.assert_array_equal(np.asarray(h.abft_detected),
                                  np.ones(batch, dtype=np.int64))
    np.testing.assert_array_equal(np.asarray(h.abft_corrected),
                                  np.ones(batch, dtype=np.int64))
    assert np.asarray(h.ok).all()
    np.testing.assert_allclose(np.asarray(x), np.linalg.solve(a, b),
                               atol=1e-9)


@pytest.mark.parametrize("speculate", ["off", "on"])
def test_gels_qr_route_honors_info_policy(rng, speculate):
    """The direct Householder-QR route of gels (m < 3n so CholQR is not
    selected, speculation off) must return (X, h) under Info exactly as
    the CholQR routes do — the seam regression that broke gels under
    vmap.  With speculation on the same shape takes CholQR2 first; both
    routes must agree on the contract."""
    m, n, k = 40, 32, 4          # m < 3n: method resolution picks QR
    a = rng.standard_normal((m, n))
    b = rng.standard_normal((m, k))
    opts = dict(INFO)
    opts[Option.Speculate] = speculate
    out = st.gels(st.Matrix.from_numpy(a, NB, NB),
                  st.Matrix.from_numpy(b, NB, NB), opts)
    assert isinstance(out, tuple) and len(out) == 2
    X, h = out
    assert isinstance(h, st.HealthInfo)
    assert bool(h.ok)
    ref = np.linalg.lstsq(a, b, rcond=None)[0]
    np.testing.assert_allclose(X.to_numpy()[:n], ref, atol=1e-8)


def test_gels_min_norm_route_honors_info_policy(rng):
    """The m < n minimum-norm route resolves ErrorPolicy too (the second
    bare-return fixed this PR)."""
    m, n, k = 24, 40, 3
    a = rng.standard_normal((m, n))
    b = rng.standard_normal((m, k))
    out = st.gels(st.Matrix.from_numpy(a, NB, NB),
                  st.Matrix.from_numpy(b, NB, NB), INFO)
    assert isinstance(out, tuple) and len(out) == 2
    X, h = out
    assert isinstance(h, st.HealthInfo)
    ref = np.linalg.lstsq(a, b, rcond=None)[0]
    np.testing.assert_allclose(X.to_numpy()[:n], ref, atol=1e-8)


@pytest.mark.parametrize("op", ["gesv", "posv", "gels"])
def test_vmap_composes_with_jit(rng, op):
    """jit(vmap(driver)) — the serving execution shape — stays exact
    against the eager per-problem loop."""
    a, b = _problems(rng, op, np.float64)
    one = ONE[op]
    xv, hv = jax.jit(jax.vmap(one))(jnp.asarray(a), jnp.asarray(b))
    for i in range(a.shape[0]):
        xi, _ = one(jnp.asarray(a[i]), jnp.asarray(b[i]))
        np.testing.assert_allclose(np.asarray(xv[i]), np.asarray(xi),
                                   rtol=1e-12, atol=1e-12)
    assert np.asarray(hv.ok).all()
