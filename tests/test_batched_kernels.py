"""Ragged batched Pallas factorization tests (internal/batched.py and the
batched panel kernels in internal/pallas_{chol,lu,qr}.py), interpret mode
on CPU.

The load-bearing guarantees:

- each batched panel step matches the single-problem fused kernel it
  generalizes (chol_panel_batched vs chol_panel_fused, incl. k > 0);
- the blocked drivers match per-problem XLA references over MIXED live
  sizes — ragged edges inside a tile, size-1 members, full-bucket
  members — and keep the identity-augmented padding region EXACT
  (dead tiles copy their input through: bit-identical, not just close);
- filler slots (size 0) pass through untouched;
- the ABFT checksum rungs detect and repair a single injected strike
  THROUGH a batched panel, and the repaired factor matches the clean run.

The kernels take real f32, plus bf16 storage with f32 accumulation for
the certified serving rung (tests/test_precision.py drills the bf16
numerics; the serve router gates every other dtype); everything here
runs them via ``interpret=True`` so tier-1 covers the exact lowering
the TPU executes.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from slate_tpu.internal import batched

RTOL, ATOL = 2e-4, 2e-3


@pytest.fixture
def rng():
    return np.random.default_rng(0)


def _spd_stack(rng, n, sizes):
    """Identity-augmented SPD slots [B, n, n] (serve pad_square packing)."""
    a = np.zeros((len(sizes), n, n), np.float32)
    for i, s in enumerate(sizes):
        if s:
            g = rng.standard_normal((s, s)).astype(np.float32)
            a[i, :s, :s] = g @ g.T + s * np.eye(s, dtype=np.float32)
        idx = np.arange(s, n)
        if s:                              # size-0 filler slots stay zero
            a[i, idx, idx] = 1.0
    return a


def _dd_stack(rng, n, sizes):
    """Identity-augmented diagonally-dominant slots (NoPiv-LU-safe)."""
    a = np.zeros((len(sizes), n, n), np.float32)
    for i, s in enumerate(sizes):
        if s:
            g = rng.standard_normal((s, s)).astype(np.float32)
            a[i, :s, :s] = g + s * np.eye(s, dtype=np.float32)
            idx = np.arange(s, n)
            a[i, idx, idx] = 1.0
    return a


# ------------------------------------------------- panel-level parity


def test_chol_panel_batched_matches_fused(rng):
    """A full-size batch member's panel step is the single-problem fused
    panel, at k = 0 and at k > 0 (nonzero left history)."""
    from slate_tpu.internal.pallas_chol import (chol_panel_batched,
                                                chol_panel_fused)
    n, nb = 64, 32
    a = _spd_stack(rng, n, [n, n])
    fa = jnp.asarray(a)
    for k in range(n // nb):
        k0, k1 = k * nb, (k + 1) * nb
        col = fa[:, k0:, k0:k1]
        left = fa[:, k0:, :k0]
        lead = jnp.swapaxes(fa[:, k0:k1, :k0], 1, 2)
        tiles = jnp.asarray([n // nb, n // nb], jnp.int32)
        upd, fac = chol_panel_batched(col, left, lead, tiles, k=k, bw=8,
                                      interpret=True)
        for b in range(2):
            ru, rf = chol_panel_fused(col[b], left[b], lead[b], bw=8,
                                      interpret=True)
            np.testing.assert_allclose(np.asarray(upd[b]), np.asarray(ru),
                                       rtol=RTOL, atol=ATOL)
            np.testing.assert_allclose(np.asarray(fac[b]), np.asarray(rf),
                                       rtol=RTOL, atol=ATOL)
        fa = fa.at[:, k0:, k0:k1].set(fac)


# ------------------------------------------------- blocked driver parity


def test_batch_potrf_mixed_sizes(rng):
    """Parity vs per-problem np.linalg.cholesky at ragged sizes (inside a
    tile, size 1, full bucket), EXACT identity padding, exact filler
    passthrough."""
    n, nb = 64, 32
    sizes = [1, 40, 64, 0]
    a = _spd_stack(rng, n, sizes)
    fa, counts = batched.batch_potrf(jnp.asarray(a),
                                     jnp.asarray(sizes, jnp.int32),
                                     nb=nb, bw=8, interpret=True)
    fa = np.asarray(fa)
    for b, s in enumerate(sizes):
        if s == 0:
            np.testing.assert_array_equal(fa[b], a[b])  # filler: untouched
            continue
        ref = np.linalg.cholesky(a[b, :s, :s].astype(np.float64))
        np.testing.assert_allclose(np.tril(fa[b, :s, :s]), ref,
                                   rtol=RTOL, atol=ATOL)
        # padding region of the factor is EXACTLY blockdiag(. , I)
        pad = np.tril(fa[b])[s:, :]
        np.testing.assert_array_equal(pad[:, :s], 0.0)
        np.testing.assert_array_equal(pad[:, s:], np.eye(n - s,
                                                         dtype=np.float32))
    assert int(np.asarray(counts.detected).sum()) == 0


def test_batch_getrf_mixed_sizes(rng):
    """Reconstruction L @ U = A per live problem, exact padding, and
    batch_getrs against np.linalg.solve."""
    n, nb = 64, 32
    sizes = [1, 40, 64, 0]
    a = _dd_stack(rng, n, sizes)
    sz = jnp.asarray(sizes, jnp.int32)
    fa = np.asarray(batched.batch_getrf(jnp.asarray(a), sz, nb=nb, bw=8,
                                        interpret=True))
    b_rhs = rng.standard_normal((len(sizes), n, 3)).astype(np.float32)
    x = np.asarray(batched.batch_getrs(jnp.asarray(fa),
                                       jnp.asarray(b_rhs)))
    for b, s in enumerate(sizes):
        if s == 0:
            np.testing.assert_array_equal(fa[b], a[b])
            continue
        L = np.tril(fa[b], -1) + np.eye(n, dtype=np.float32)
        U = np.triu(fa[b])
        np.testing.assert_allclose(L @ U, a[b], rtol=RTOL,
                                   atol=ATOL * max(s, 1))
        np.testing.assert_array_equal(fa[b, s:, :s], 0.0)
        np.testing.assert_array_equal(fa[b, :s, s:], 0.0)
        np.testing.assert_array_equal(fa[b, s:, s:],
                                      np.eye(n - s, dtype=np.float32))
        ref = np.linalg.solve(a[b].astype(np.float64),
                              b_rhs[b].astype(np.float64))
        np.testing.assert_allclose(x[b], ref, rtol=5e-3, atol=5e-3)


def test_batch_geqrf_gels_mixed_sizes(rng):
    """batch_gels matches per-problem np.linalg.lstsq through the serve
    packing (pad_tall identity augmentation), with zero-row filler slots
    passing through untouched."""
    mb, nbq, w = 24, 16, 8
    # member 0: (m=4, n=3) augmented -> 17 live rows; member 1: full
    # (24, 16); member 2: filler (rows = 0, zero slot)
    probs = [(4, 3), (mb, nbq), None]
    rows = []
    a = np.zeros((len(probs), mb, nbq), np.float32)
    b = np.zeros((len(probs), mb, 2), np.float32)
    for i, p in enumerate(probs):
        if p is None:
            rows.append(0)
            continue
        m, nn = p
        ai = rng.standard_normal((m, nn)).astype(np.float32)
        bi = rng.standard_normal((m, 2)).astype(np.float32)
        a[i, :m, :nn] = ai
        extra = nbq - nn
        a[i, m:m + extra, nn:] = np.eye(extra, dtype=np.float32)
        b[i, :m] = bi
        rows.append(m + extra)
    x, packed = batched.batch_gels(jnp.asarray(a), jnp.asarray(b),
                                   jnp.asarray(rows, jnp.int32),
                                   nb=w, interpret=True)
    x, packed = np.asarray(x), np.asarray(packed)
    for i, p in enumerate(probs):
        if p is None:
            np.testing.assert_array_equal(packed[i], a[i])  # filler
            continue
        m, nn = p
        ref = np.linalg.lstsq(a[i, :rows[i]].astype(np.float64),
                              b[i, :rows[i]].astype(np.float64),
                              rcond=None)[0]
        np.testing.assert_allclose(x[i, :nn], ref[:nn], rtol=5e-3,
                                   atol=5e-3)
        # padding solution components decouple to ~0
        np.testing.assert_allclose(x[i, nn:], 0.0, atol=1e-4)


# --------------------------------------------------------- ABFT in-batch


def test_batch_potrf_abft_single_strike(rng):
    """A transient post_panel bitflip through a BATCHED panel is detected
    and repaired: counters report exactly one event and the factor
    matches the clean run."""
    from slate_tpu.robust import faults
    n, nb = 64, 32
    sizes = [40, 64, 0]
    a = _spd_stack(rng, n, sizes)
    aj = jnp.asarray(a)
    sz = jnp.asarray(sizes, jnp.int32)
    clean, c0 = batched.batch_potrf(aj, sz, nb=nb, bw=8, interpret=True,
                                    abft=True)
    clean = np.asarray(clean)
    assert int(np.asarray(c0.detected).sum()) == 0

    # the transient strike fires on panel 0's factored fac [B, n, nb]; a
    # bitflip on an exact-zero padding/upper-half element is a no-op, so
    # pick the first seed whose flat index lands on a nonzero element
    panel0 = clean[:, :, :nb].ravel()
    seed = next(s for s in range(200) if abs(panel0[
        np.random.default_rng(s).choice(panel0.size, 1,
                                        replace=False)[0]]) > 1e-3)
    plan = faults.FaultPlan("post_panel", kind="bitflip", seed=seed,
                            transient=True)
    with faults.inject(plan):
        hit, counts = batched.batch_potrf(aj, sz, nb=nb, bw=8,
                                          interpret=True, abft=True)
    det = np.asarray(counts.detected)
    cor = np.asarray(counts.corrected)
    assert int(det.sum()) == 1 and int(cor.sum()) == 1
    np.testing.assert_allclose(np.asarray(hit), clean, rtol=1e-4,
                               atol=1e-4)


# ------------------------------------------------------- health helpers


def test_batch_health_mirrors_drivers(rng):
    """Padding diagonal entries are exactly 1 and never mask a genuine
    failure: an indefinite live block reads not-ok, healthy slots ok."""
    n, nb = 64, 32
    sizes = [40, 64]
    a = _spd_stack(rng, n, sizes)
    a[0, 1, 1] = -50.0                      # indefinite -> NaN in L
    fa, _ = batched.batch_potrf(jnp.asarray(a),
                                jnp.asarray(sizes, jnp.int32),
                                nb=nb, bw=8, interpret=True)
    h = batched.batch_chol_health(fa)
    ok = np.asarray(h.ok)
    assert not bool(ok[0]) and bool(ok[1])

    ad = _dd_stack(rng, n, sizes)
    fd = batched.batch_getrf(jnp.asarray(ad), jnp.asarray(sizes,
                                                          jnp.int32),
                             nb=nb, bw=8, interpret=True)
    hd = batched.batch_lu_health(jnp.asarray(ad), fd)
    assert bool(np.asarray(hd.ok).all())
