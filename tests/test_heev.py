"""Two-stage eigensolver tests: eigenvalues vs numpy.linalg.eigvalsh and
||A Z - Z diag(w)|| residuals (analog of ref test/test_heev.cc)."""

import jax
import numpy as np
import pytest

import slate_tpu as st


def herm(rng, n, dtype=np.float64):
    a = rng.standard_normal((n, n)).astype(dtype)
    if np.issubdtype(dtype, np.complexfloating):
        a = a + 1j * rng.standard_normal((n, n))
    return (a + a.conj().T) / 2


@pytest.mark.parametrize("n,nb", [(16, 4), (23, 5), (8, 8), (12, 16)])
def test_heev_values(rng, n, nb):
    a = herm(rng, n)
    A = st.HermitianMatrix.from_numpy(a, nb, st.Uplo.Lower)
    w = st.heev_vals(A)
    np.testing.assert_allclose(np.sort(np.asarray(w)),
                               np.linalg.eigvalsh(a), atol=1e-10)


@pytest.mark.parametrize("n,nb", [(16, 4), (21, 5)])
@pytest.mark.slow
def test_heev_vectors(rng, n, nb):
    a = herm(rng, n)
    A = st.HermitianMatrix.from_numpy(a, nb, st.Uplo.Lower)
    w, Z = st.heev(A)
    w = np.asarray(w)
    z = Z.to_numpy()
    np.testing.assert_allclose(z.conj().T @ z, np.eye(n), atol=1e-11)
    np.testing.assert_allclose(a @ z, z @ np.diag(w), atol=1e-10)
    np.testing.assert_allclose(np.sort(w), np.linalg.eigvalsh(a), atol=1e-10)


@pytest.mark.slow
def test_heev_complex(rng):
    n, nb = 14, 4
    a = herm(rng, n, np.complex128)
    A = st.HermitianMatrix.from_numpy(a, nb, st.Uplo.Lower)
    w, Z = st.heev(A)
    w, z = np.asarray(w), Z.to_numpy()
    assert np.abs(np.imag(w)).max() == 0        # eigenvalues real
    np.testing.assert_allclose(z.conj().T @ z, np.eye(n), atol=1e-11)
    np.testing.assert_allclose(a @ z, z @ np.diag(w), atol=1e-10)


@pytest.mark.slow
def test_heev_mesh(rng):
    n, nb = 20, 4
    g = st.Grid(2, 2, devices=jax.devices()[:4])
    a = herm(rng, n)
    A = st.HermitianMatrix.from_numpy(a, nb, st.Uplo.Lower, g)
    w, Z = st.heev(A)
    w, z = np.asarray(w), Z.to_numpy()
    np.testing.assert_allclose(np.sort(w), np.linalg.eigvalsh(a), atol=1e-10)
    np.testing.assert_allclose(a @ z, z @ np.diag(w), atol=1e-10)


@pytest.mark.slow
def test_heev_mesh_2x4_complex_ragged(rng):
    # distributed stage 1 (dist_he2hb): ragged last tile, complex, vectors
    n, nb = 37, 5
    g = st.Grid(2, 4, devices=jax.devices()[:8])
    a = herm(rng, n, np.complex128)
    A = st.HermitianMatrix.from_numpy(a, nb, st.Uplo.Lower, g)
    w, Z = st.heev(A)
    w, z = np.asarray(w), Z.to_numpy()
    np.testing.assert_allclose(z.conj().T @ z, np.eye(n), atol=1e-10)
    np.testing.assert_allclose(np.sort(w), np.linalg.eigvalsh(a), atol=1e-9)
    np.testing.assert_allclose(a @ z, z @ np.diag(w), atol=1e-9)


@pytest.mark.slow
def test_heev_vals_mesh(rng):
    n, nb = 24, 4
    g = st.Grid(2, 2, devices=jax.devices()[:4])
    a = herm(rng, n)
    A = st.HermitianMatrix.from_numpy(a, nb, st.Uplo.Lower, g)
    w = st.heev_vals(A)
    np.testing.assert_allclose(np.sort(np.asarray(w)),
                               np.linalg.eigvalsh(a), atol=1e-10)


@pytest.mark.slow
def test_heev_mesh_trans_view_complex(rng):
    # Trans view of a complex Hermitian is conj(A) != A: the mesh path must
    # densify (zero-copy would silently factor A instead)
    n, nb = 16, 4
    g = st.Grid(2, 2, devices=jax.devices()[:4])
    a = herm(rng, n, np.complex128)
    A = st.HermitianMatrix.from_numpy(a, nb, st.Uplo.Lower, g)
    At = A.transpose()
    w, Z = st.heev(At)
    w, z = np.asarray(w), Z.to_numpy()
    at = a.T
    np.testing.assert_allclose(at @ z, z @ np.diag(w), atol=1e-10)


@pytest.mark.slow
def test_heev_mesh_upper_view(rng):
    # Upper-stored input exercises the mesh fallback normalisation
    n, nb = 16, 4
    g = st.Grid(2, 2, devices=jax.devices()[:4])
    a = herm(rng, n)
    A = st.HermitianMatrix.from_numpy(a, nb, st.Uplo.Upper, g)
    w = st.heev_vals(A)
    np.testing.assert_allclose(np.sort(np.asarray(w)),
                               np.linalg.eigvalsh(a), atol=1e-10)


@pytest.mark.parametrize("meth", [st.MethodEig.QR, st.MethodEig.DC])
def test_heev_chase_parity(rng, meth):
    # the tridiagonal parity route (hb2st bulge chase) must agree with the
    # default band seam
    n, nb = 21, 5
    a = herm(rng, n)
    A = st.HermitianMatrix.from_numpy(a, nb, st.Uplo.Lower)
    w, Z = st.heev(A, {st.Option.MethodEig: meth})
    w, z = np.asarray(w), Z.to_numpy()
    np.testing.assert_allclose(np.sort(w), np.linalg.eigvalsh(a), atol=1e-10)
    np.testing.assert_allclose(a @ z, z @ np.diag(w), atol=1e-10)


def test_sterf_steqr(rng):
    n = 17
    d = rng.standard_normal(n)
    e = rng.standard_normal(n - 1)
    T = np.diag(d) + np.diag(e, 1) + np.diag(e, -1)
    np.testing.assert_allclose(np.sort(np.asarray(st.sterf(d, e))),
                               np.linalg.eigvalsh(T), atol=1e-12)
    w, Z = st.steqr(d, e)
    w, z = np.asarray(w), np.asarray(Z)
    np.testing.assert_allclose(T @ z, z @ np.diag(w), atol=1e-12)


def test_hb2st_public(rng):
    n, kd, mb = 18, 3, 6
    a = herm(rng, n)
    band = np.where(np.abs(np.subtract.outer(np.arange(n), np.arange(n)))
                    <= kd, a, 0.0)
    HB = st.HermitianBandMatrix.from_numpy(band, kd, mb)
    d, e, Q2 = st.hb2st(HB)
    T = np.diag(np.asarray(d)) + np.diag(np.asarray(e), 1) + \
        np.diag(np.asarray(e), -1)
    q2 = np.asarray(Q2)
    np.testing.assert_allclose(q2 @ T @ q2.conj().T, band, atol=1e-11)


@pytest.mark.parametrize("itype", [1, 2, 3])
def test_hegv(rng, itype):
    # the three generalized problems (ref: src/hegv.cc:22-35, hegst.cc:40-41)
    n, nb = 12, 4
    a = herm(rng, n)
    bmat = rng.standard_normal((n, n))
    b = bmat @ bmat.T + n * np.eye(n)
    A = st.HermitianMatrix.from_numpy(a, nb, st.Uplo.Lower)
    B = st.HermitianMatrix.from_numpy(b, nb, st.Uplo.Lower)
    w, X = st.hegv(A, B, itype=itype)
    w, x = np.asarray(w), X.to_numpy()
    import scipy.linalg
    wref = scipy.linalg.eigh(a, b, type=itype, eigvals_only=True)
    np.testing.assert_allclose(np.sort(w), wref, atol=1e-9)
    if itype == 1:
        np.testing.assert_allclose(a @ x, b @ x @ np.diag(w), atol=1e-9)
    elif itype == 2:
        np.testing.assert_allclose(a @ (b @ x), x @ np.diag(w), atol=1e-8)
    else:
        np.testing.assert_allclose(b @ (a @ x), x @ np.diag(w), atol=1e-8)


def test_heev_uplo_upper(rng):
    n, nb = 12, 4
    a = herm(rng, n)
    A = st.HermitianMatrix.from_numpy(a, nb, st.Uplo.Upper)
    w = st.heev_vals(A)
    np.testing.assert_allclose(np.sort(np.asarray(w)),
                               np.linalg.eigvalsh(a), atol=1e-10)
