"""Distributed print driver (ref: src/print.cc) and trace spans."""

import numpy as np

import slate_tpu as st
from slate_tpu.options import Option


def test_format_verbosity_levels(rng):
    a = rng.standard_normal((6, 5))
    A = st.Matrix.from_numpy(a, 2, 2)
    assert st.format_matrix("A", A, {Option.PrintVerbose: 0}) == ""
    meta = st.format_matrix("A", A, {Option.PrintVerbose: 1})
    assert "Matrix 6x5" in meta and "tiles 2x2" in meta
    full = st.format_matrix("A", A, {Option.PrintVerbose: 4})
    assert "A = [" in full
    assert "..." not in full                # verbose 4 = no ellipsis
    # a representative entry renders at the configured precision
    assert f"{a[0, 0]:.4f}" in full


def test_format_band_and_hermitian(rng):
    n, kd, mb = 8, 2, 4
    h = rng.standard_normal((n, n))
    h = (h + h.T) / 2
    H = st.HermitianMatrix.from_numpy(h, mb)
    s = st.format_matrix("H", H, {Option.PrintVerbose: 1})
    assert "HermitianMatrix" in s and "uplo=Lower" in s
    band = np.where(np.abs(np.subtract.outer(np.arange(n), np.arange(n)))
                    <= kd, h, 0.0)
    HB = st.HermitianBandMatrix.from_numpy(band, kd, mb)
    s2 = st.format_matrix("HB", HB, {Option.PrintVerbose: 1})
    assert "HermitianBandMatrix" in s2 and "kd=2" in s2


def test_print_matrix_stdout(rng, capsys):
    A = st.Matrix.from_numpy(rng.standard_normal((4, 4)), 2, 2)
    st.print_matrix("A", A, {Option.PrintVerbose: 1})
    out = capsys.readouterr().out
    assert "Matrix 4x4" in out


def test_trace_span_names_phases(rng, tmp_path):
    # the annotate/span discipline labels driver phases: a captured jax
    # profile of a solve contains the slate.* names (the Trace.hh analog)
    import glob
    import gzip

    import jax
    a = rng.standard_normal((16, 16))
    spd = a @ a.T + 16 * np.eye(16)
    A = st.HermitianMatrix.from_numpy(spd, 4)
    B = st.Matrix.from_numpy(a[:, :2], 4, 4)
    with jax.profiler.trace(str(tmp_path)):
        _, X = st.posv(A, B)
        X.to_numpy()
    blobs = glob.glob(str(tmp_path / "**" / "*.pb*"), recursive=True) + \
        glob.glob(str(tmp_path / "**" / "*.json*"), recursive=True)
    found = set()
    for f in blobs:
        raw = gzip.open(f, "rb").read() if f.endswith(".gz") else \
            open(f, "rb").read()
        for name in (b"slate.posv", b"slate.potrf", b"slate.trsm"):
            if name in raw:
                found.add(name.decode())
    assert "slate.posv" in found and "slate.potrf" in found, found

def test_debug_tiles_map(rng):
    from slate_tpu.util.debug import (check_pad_invariant, memory_report,
                                      tiles_map)
    a = rng.standard_normal((10, 7))
    A = st.Matrix.from_numpy(a, 4, 4)
    s = tiles_map(A)
    assert "tiles_map 10x7" in s and "r0:" in s
    assert check_pad_invariant(A)
    # break the invariant on purpose: debug must catch it
    bad = st.Matrix(type(A.storage)(
        A.storage.data + 1.0, 10, 7, 4, 4, A.grid))
    assert not check_pad_invariant(bad)
    assert "MB total" in memory_report(A)
