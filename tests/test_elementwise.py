"""Elementwise/aux driver tests incl. uneven last tiles and mesh grids
(analog of ref unit tests for internal_geadd/gecopy/gescale/geset/tz*)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import slate_tpu as st
from slate_tpu.types import Norm


SHAPES = [(16, 16, 4), (10, 7, 4), (9, 13, 5)]


@pytest.mark.parametrize("m,n,mb", SHAPES)
def test_add_general(rng, m, n, mb):
    a = rng.standard_normal((m, n))
    b = rng.standard_normal((m, n))
    A = st.Matrix.from_numpy(a, mb)
    B = st.Matrix.from_numpy(b, mb)
    out = st.add(2.0, A, -1.0, B)
    np.testing.assert_allclose(out.to_numpy(), 2 * a - b, atol=1e-14)


def test_add_trapezoid(rng):
    a = rng.standard_normal((10, 10))
    b = rng.standard_normal((10, 10))
    A = st.TriangularMatrix.from_numpy(a, 4, st.Uplo.Lower)
    B = st.TriangularMatrix.from_numpy(b, 4, st.Uplo.Lower)
    out = st.add(1.0, A, 1.0, B)
    np.testing.assert_allclose(out.to_numpy(), np.tril(a) + np.tril(b),
                               atol=1e-14)
    # storage outside the triangle is untouched
    np.testing.assert_allclose(
        np.triu(np.asarray(out.storage.to_dense()), 1), np.triu(b, 1))


def test_copy_precision(rng):
    a = rng.standard_normal((9, 6))
    A = st.Matrix.from_numpy(a, 4)
    B = st.Matrix.zeros(9, 6, 4, dtype=jnp.float32)
    out = st.copy(A, B)
    assert out.dtype == jnp.float32
    np.testing.assert_allclose(out.to_numpy(), a.astype(np.float32),
                               rtol=1e-6)


def test_scale_and_scale_row_col(rng):
    a = rng.standard_normal((7, 5))
    A = st.Matrix.from_numpy(a, 3)
    out = st.scale(3.0, 2.0, A)
    np.testing.assert_allclose(out.to_numpy(), 1.5 * a, atol=1e-14)
    r = rng.standard_normal(7)
    c = rng.standard_normal(5)
    out2 = st.scale_row_col(r, c, A)
    np.testing.assert_allclose(out2.to_numpy(), a * np.outer(r, c),
                               atol=1e-14)


def test_set_identity(rng):
    A = st.Matrix.zeros(10, 7, 4)
    out = st.set(0.0, 1.0, A)
    np.testing.assert_allclose(out.to_numpy(), np.eye(10, 7), atol=0)
    # pad region still zero
    canon = np.asarray(out.storage.canonical())
    assert np.all(canon[-1, :, 2:, :] == 0)


def test_set_trapezoid():
    A = st.Matrix.zeros(8, 8, 3).triangular(st.Uplo.Upper)
    out = st.set(2.0, 5.0, A)
    ref = np.triu(np.full((8, 8), 2.0), 1) + np.diag(np.full(8, 5.0))
    np.testing.assert_allclose(out.to_numpy(), ref)


@pytest.mark.parametrize("norm_t,npfun", [
    (Norm.Max, lambda a: np.max(np.abs(a))),
    (Norm.One, lambda a: np.max(np.abs(a).sum(axis=0))),
    (Norm.Inf, lambda a: np.max(np.abs(a).sum(axis=1))),
    (Norm.Fro, lambda a: np.linalg.norm(a)),
])
@pytest.mark.parametrize("m,n,mb", SHAPES)
def test_genorm(rng, norm_t, npfun, m, n, mb):
    a = rng.standard_normal((m, n))
    A = st.Matrix.from_numpy(a, mb)
    got = float(st.norm(norm_t, A))
    np.testing.assert_allclose(got, npfun(a), rtol=1e-13)


def test_genorm_mesh(rng):
    g = st.Grid(2, 4, devices=jax.devices()[:8])
    a = rng.standard_normal((30, 22))
    A = st.Matrix.from_numpy(a, 4, 4, g)
    np.testing.assert_allclose(float(st.norm(Norm.One, A)),
                               np.max(np.abs(a).sum(axis=0)), rtol=1e-13)


def test_colnorms(rng):
    a = rng.standard_normal((11, 9))
    A = st.Matrix.from_numpy(a, 4)
    np.testing.assert_allclose(np.asarray(st.col_norms(A)),
                               np.max(np.abs(a), axis=0), rtol=1e-13)


@pytest.mark.parametrize("norm_t", [Norm.Max, Norm.One, Norm.Inf, Norm.Fro])
@pytest.mark.parametrize("uplo", [st.Uplo.Lower, st.Uplo.Upper])
def test_trnorm(rng, norm_t, uplo):
    a = rng.standard_normal((11, 11))
    A = st.TriangularMatrix.from_numpy(a, 4, uplo)
    tri = np.tril(a) if uplo is st.Uplo.Lower else np.triu(a)
    ref = {Norm.Max: np.max(np.abs(tri)),
           Norm.One: np.max(np.abs(tri).sum(axis=0)),
           Norm.Inf: np.max(np.abs(tri).sum(axis=1)),
           Norm.Fro: np.linalg.norm(tri)}[norm_t]
    np.testing.assert_allclose(float(st.norm(norm_t, A)), ref, rtol=1e-13)


@pytest.mark.parametrize("norm_t", [Norm.Max, Norm.One, Norm.Inf, Norm.Fro])
@pytest.mark.parametrize("uplo", [st.Uplo.Lower, st.Uplo.Upper])
def test_synorm(rng, norm_t, uplo):
    a = rng.standard_normal((13, 13))
    A = st.SymmetricMatrix.from_numpy(a, 4, uplo)
    full = A.to_numpy()
    ref = {Norm.Max: np.max(np.abs(full)),
           Norm.One: np.max(np.abs(full).sum(axis=0)),
           Norm.Inf: np.max(np.abs(full).sum(axis=1)),
           Norm.Fro: np.linalg.norm(full)}[norm_t]
    np.testing.assert_allclose(float(st.norm(norm_t, A)), ref, rtol=1e-13)


@pytest.mark.parametrize("norm_t", [Norm.Max, Norm.One, Norm.Fro])
def test_gbnorm(rng, norm_t):
    a = rng.standard_normal((12, 12))
    A = st.BandMatrix.from_numpy(a, 2, 3, 4)
    band = A.to_numpy()
    ref = {Norm.Max: np.max(np.abs(band)),
           Norm.One: np.max(np.abs(band).sum(axis=0)),
           Norm.Fro: np.linalg.norm(band)}[norm_t]
    np.testing.assert_allclose(float(st.norm(norm_t, A)), ref, rtol=1e-13)


def test_norm_of_transpose_view(rng):
    a = rng.standard_normal((9, 5))
    A = st.Matrix.from_numpy(a, 4)
    np.testing.assert_allclose(float(st.norm(Norm.One, A.T)),
                               np.max(np.abs(a.T).sum(axis=0)), rtol=1e-13)


def test_redistribute_roundtrip(rng):
    a = rng.standard_normal((24, 20))
    g1 = st.Grid(2, 4, devices=jax.devices()[:8])
    g2 = st.Grid(4, 2, devices=jax.devices()[:8])
    A = st.Matrix.from_numpy(a, 4, 4, g1)
    B = st.redistribute(A, 6, 5, g2)
    assert B.grid is g2 and B.mb == 6
    np.testing.assert_allclose(B.to_numpy(), a)
    C = st.redistribute(B, 4, 4, g1)
    np.testing.assert_allclose(C.to_numpy(), a)


def test_add_structured_source_to_general(rng):
    """Structure of the SOURCE must be honoured (regression: fast path read
    raw storage of a triangular view)."""
    full = rng.standard_normal((8, 8))
    A = st.Matrix.from_numpy(full, 2).triangular(st.Uplo.Lower)
    B = st.Matrix.zeros(8, 8, 2, dtype=full.dtype)
    out = st.add(1.0, A, 1.0, B)
    np.testing.assert_allclose(out.to_numpy(), np.tril(full))
    S = st.SymmetricMatrix.from_numpy(full, 2, st.Uplo.Lower)
    out2 = st.add(1.0, S, 0.0, B)
    np.testing.assert_allclose(out2.to_numpy(), S.to_numpy())


def test_colnorms_structured(rng):
    full = np.abs(rng.standard_normal((6, 6))) + 1.0
    A = st.Matrix.from_numpy(full, 2).triangular(st.Uplo.Lower)
    got = np.asarray(st.col_norms(A))
    np.testing.assert_allclose(got, np.max(np.abs(np.tril(full)), axis=0))
