"""RBT / speculate-then-certify tests (CPU-exact, no accelerator needed).

Covers: butterfly apply/unapply round trips at both precisions, the
two-sided transform against a dense reference, gesv under Option.Speculate
vs the pivoted oracle on well-conditioned AND adversarial inputs, the
post_rbt fault site provably triggering escalation, the traced (jit)
contract, and the gels/hesv speculation seams.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import slate_tpu as st
from slate_tpu.internal import rbt
from slate_tpu.robust import faults

SPEC = {st.Option.Speculate: "on"}
SPEC_INFO = {st.Option.Speculate: "on", st.Option.ErrorPolicy: "info"}


def _tol(dtype):
    return 200 * np.finfo(dtype).eps


# ------------------------------------------------------------ mechanism

@pytest.mark.parametrize("dtype", [np.float32, np.float64])
@pytest.mark.parametrize("n", [8, 24])
def test_apply_roundtrip(rng, dtype, n):
    u = rbt.generate(n, seed=3, dtype=dtype)
    x = rng.standard_normal((n, 5)).astype(dtype)
    for fwd, inv in [("n", "inv"), ("t", "invt")]:
        y = rbt.apply_axis(u, x, fwd)
        back = np.asarray(rbt.apply_axis(u, y, inv))
        np.testing.assert_allclose(back, x, rtol=_tol(dtype),
                                   atol=_tol(dtype))


@pytest.mark.parametrize("dtype", [np.float32, np.float64])
def test_transform_untransform(rng, dtype):
    n = 16
    u = rbt.generate(n, seed=4, dtype=dtype)
    v = rbt.generate(n, seed=5, dtype=dtype)
    a = rng.standard_normal((n, n)).astype(dtype)
    at = rbt.transform(a, u, v)
    back = np.asarray(rbt.untransform(at, u, v))
    np.testing.assert_allclose(back, a, rtol=_tol(dtype), atol=_tol(dtype))


def test_transform_matches_dense_butterfly(rng):
    """The level representation multiplies out to W = L0 @ L1 exactly."""
    n = 8
    u = rbt.generate(n, seed=6, dtype=np.float64)

    def dense_w(levels):
        s = np.sqrt(0.5)
        W = np.eye(n)
        for lev, (r0, r1) in enumerate(levels):
            nblk = 1 << lev
            half = n // nblk // 2
            L = np.zeros((n, n))
            for b in range(nblk):
                o = b * 2 * half
                d0 = np.asarray(r0)[b * half:(b + 1) * half]
                d1 = np.asarray(r1)[b * half:(b + 1) * half]
                L[o:o + half, o:o + half] = s * np.diag(d0)
                L[o:o + half, o + half:o + 2 * half] = s * np.diag(d1)
                L[o + half:o + 2 * half, o:o + half] = s * np.diag(d0)
                L[o + half:o + 2 * half, o + half:o + 2 * half] = \
                    -s * np.diag(d1)
            W = W @ L
        return W

    W = dense_w(u)
    x = rng.standard_normal((n, 3))
    np.testing.assert_allclose(np.asarray(rbt.apply_left(u, x)), W @ x,
                               rtol=1e-13, atol=1e-13)
    np.testing.assert_allclose(np.asarray(rbt.apply_left_t(u, x)),
                               W.T @ x, rtol=1e-13, atol=1e-13)
    np.testing.assert_allclose(np.asarray(rbt.apply_right(u, x.T)),
                               x.T @ W, rtol=1e-13, atol=1e-13)


def test_generate_validates():
    with pytest.raises(ValueError):
        rbt.generate(6)          # not a multiple of 4 at depth 2
    with pytest.raises(ValueError):
        rbt.generate(0)
    assert rbt.padded_size(13) == 16
    assert rbt.padded_size(16) == 16
    assert rbt.padded_size(1) == 4


# ------------------------------------------------------- gesv speculation

def _wilkinson_growth(n):
    """W = tril(-1) + I with last column 1: partial-pivot growth 2^(n-1),
    the classic growth adversary."""
    a = np.tril(-np.ones((n, n)), -1) + np.eye(n)
    a[:, -1] = 1.0
    return a


@pytest.mark.parametrize("kind", ["random", "symmetric_indefinite",
                                  "wilkinson", "zero_pivot"])
def test_gesv_speculate_matches_oracle(rng, kind):
    n, nb = 24, 8
    if kind == "random":
        a = rng.standard_normal((n, n))
    elif kind == "symmetric_indefinite":
        s = rng.standard_normal((n, n))
        a = (s + s.T) / 2
    elif kind == "wilkinson":
        a = _wilkinson_growth(n)
    else:
        a = rng.standard_normal((n, n)) + n * np.eye(n)
        a[0, 0] = 0.0
    b = rng.standard_normal((n, 3))
    A = st.Matrix.from_numpy(a, nb)
    B = st.Matrix.from_numpy(b, nb)
    F, X, h = st.gesv(A, B, SPEC_INFO)
    assert bool(h.ok)
    np.testing.assert_allclose(X.to_numpy(), np.linalg.solve(a, b),
                               rtol=1e-9, atol=1e-9)


def test_gesv_speculate_ragged(rng):
    """n not a multiple of the butterfly granularity: identity padding."""
    n, nb = 30, 7
    a = rng.standard_normal((n, n)) + n * np.eye(n)
    b = rng.standard_normal((n, 2))
    F, X, h = st.gesv(st.Matrix.from_numpy(a, nb),
                      st.Matrix.from_numpy(b, nb), SPEC_INFO)
    assert bool(h.ok)
    np.testing.assert_allclose(X.to_numpy(), np.linalg.solve(a, b),
                               rtol=1e-10, atol=1e-10)


def test_gesv_speculate_f32(rng):
    n, nb = 24, 8
    a = (rng.standard_normal((n, n)) + n * np.eye(n)).astype(np.float32)
    b = rng.standard_normal((n, 2)).astype(np.float32)
    F, X, h = st.gesv(st.Matrix.from_numpy(a, nb),
                      st.Matrix.from_numpy(b, nb), SPEC_INFO)
    assert bool(h.ok)
    assert X.to_numpy().dtype == np.float32
    np.testing.assert_allclose(
        X.to_numpy(), np.linalg.solve(a.astype(np.float64), b),
        rtol=5e-4, atol=5e-4)


def test_gesv_speculate_jit(rng):
    """The speculative fast path traces into one program; health rides
    along as data (no eager escalation branch under jit)."""
    n, nb = 24, 8
    a = rng.standard_normal((n, n)) + n * np.eye(n)
    b = rng.standard_normal((n, 2))

    @jax.jit
    def solve(ad, bd):
        F, X, h = st.gesv(st.Matrix.from_numpy(ad, nb),
                          st.Matrix.from_numpy(bd, nb), SPEC_INFO)
        return X.to_dense(), h.ok

    x, ok = solve(jnp.asarray(a), jnp.asarray(b))
    assert bool(ok)
    np.testing.assert_allclose(np.asarray(x), np.linalg.solve(a, b),
                               rtol=1e-10, atol=1e-10)


def test_gesv_speculate_off_is_default_path(rng):
    """Speculate.Auto (the default) must leave gesv on the pivoted path —
    the factor object is plain LUFactors, not RBTFactors."""
    n, nb = 16, 8
    a = rng.standard_normal((n, n))
    b = rng.standard_normal((n, 2))
    F, X = st.gesv(st.Matrix.from_numpy(a, nb), st.Matrix.from_numpy(b, nb))
    assert isinstance(F, st.LUFactors)
    F2, X2, h2 = st.gesv(st.Matrix.from_numpy(a, nb),
                         st.Matrix.from_numpy(b, nb), SPEC_INFO)
    assert isinstance(F2, st.RBTFactors)


# --------------------------------------------- certification / escalation

def test_post_rbt_fault_escalates(rng):
    """A persistent bitflip on the transformed matrix yields a finite but
    wrong fast-path solve; the residual certificate must catch it and the
    recovery ladder must escalate to pivoted LU — result still matches
    the oracle and the factor is pivoted."""
    n, nb = 24, 8
    a = rng.standard_normal((n, n)) + n * np.eye(n)
    b = rng.standard_normal((n, 2))
    A = st.Matrix.from_numpy(a, nb)
    B = st.Matrix.from_numpy(b, nb)
    with faults.inject(faults.FaultPlan(site="post_rbt", kind="bitflip")):
        F, X, h = st.gesv(A, B, SPEC_INFO)
    assert isinstance(F, st.LUFactors)      # escalated off the RBT path
    assert bool(h.ok)
    np.testing.assert_allclose(X.to_numpy(), np.linalg.solve(a, b),
                               rtol=1e-10, atol=1e-10)


def test_post_rbt_fault_no_fallback_reports(rng):
    """With the fallback solver disabled, the failed certificate must
    surface in the health (Info) or as the typed exception (Raise)."""
    n, nb = 24, 8
    a = rng.standard_normal((n, n)) + n * np.eye(n)
    b = rng.standard_normal((n, 2))
    A = st.Matrix.from_numpy(a, nb)
    B = st.Matrix.from_numpy(b, nb)
    o = dict(SPEC_INFO)
    o[st.Option.UseFallbackSolver] = False
    with faults.inject(faults.FaultPlan(site="post_rbt", kind="bitflip")):
        F, X, h = st.gesv(A, B, o)
    assert not bool(h.ok)
    assert isinstance(F, st.RBTFactors)     # never left the fast path
    o2 = dict(SPEC)
    o2[st.Option.UseFallbackSolver] = False
    with faults.inject(faults.FaultPlan(site="post_rbt", kind="bitflip")):
        with pytest.raises(st.SlateSingularError):
            st.gesv(A, B, o2)


def test_rbt_transient_fault_certified_clean_retry(rng):
    """A transient post_rbt strike corrupts only the first attempt: the
    pivoted retry sees clean data and certifies."""
    n, nb = 24, 8
    a = rng.standard_normal((n, n)) + n * np.eye(n)
    b = rng.standard_normal((n, 2))
    with faults.inject(faults.FaultPlan(site="post_rbt", kind="nan",
                                        transient=True)):
        F, X, h = st.gesv(st.Matrix.from_numpy(a, nb),
                          st.Matrix.from_numpy(b, nb), SPEC_INFO)
    assert bool(h.ok)
    np.testing.assert_allclose(X.to_numpy(), np.linalg.solve(a, b),
                               rtol=1e-10, atol=1e-10)


def test_getrf_rbt_direct_roundtrip(rng):
    """getrf_rbt + getrs as raw drivers (no recovery layer): the factor
    reconstructs the transformed matrix and the solve matches."""
    n, nb = 16, 8
    a = rng.standard_normal((n, n)) + n * np.eye(n)
    b = rng.standard_normal((n, 3))
    A = st.Matrix.from_numpy(a, nb)
    F, h = st.getrf_rbt(A, {st.Option.ErrorPolicy: "info"})
    assert isinstance(F, st.RBTFactors)
    X = st.getrs(F, st.Matrix.from_numpy(b, nb))
    np.testing.assert_allclose(X.to_numpy(), np.linalg.solve(a, b),
                               rtol=1e-9, atol=1e-9)


# ------------------------------------------------------ gels speculation

def test_gels_speculate_matches_lstsq(rng):
    """m=20, n=10 auto-selects QR (not tall-skinny enough); Speculate
    forces the certified CholQR2 fast path, which must match."""
    m, n, nb = 20, 10, 8
    a = rng.standard_normal((m, n))
    b = rng.standard_normal((m, 2))
    X, h = st.gels(st.Matrix.from_numpy(a, nb),
                   st.Matrix.from_numpy(b, nb), SPEC_INFO)
    assert bool(h.ok)
    xref = np.linalg.lstsq(a, b, rcond=None)[0]
    np.testing.assert_allclose(X.to_numpy(), xref, rtol=1e-10, atol=1e-10)


def test_gels_speculate_illconditioned_escalates(rng):
    """cond(A)^2 beyond f64: the Gram certificate/factor fails and the
    QR fallback must produce the accurate answer."""
    m, n, nb = 20, 10, 8
    u, _ = np.linalg.qr(rng.standard_normal((m, n)))
    v, _ = np.linalg.qr(rng.standard_normal((n, n)))
    s = np.logspace(0, -12, n)
    a = (u * s) @ v.T
    b = rng.standard_normal((m, 2))
    X, h = st.gels(st.Matrix.from_numpy(a, nb),
                   st.Matrix.from_numpy(b, nb), SPEC_INFO)
    xref = np.linalg.lstsq(a, b, rcond=None)[0]
    resid = np.linalg.norm(a.T @ (a @ X.to_numpy() - b))
    resid_ref = np.linalg.norm(a.T @ (a @ xref - b))
    assert resid < 1e-6 + 10 * resid_ref


def test_gels_default_unchanged(rng):
    """Without Speculate the auto heuristic still routes tall-skinny to
    CholQR and near-square to QR, matching lstsq either way."""
    for m, n in [(40, 8), (20, 16)]:
        a = rng.standard_normal((m, n))
        b = rng.standard_normal((m, 2))
        X = st.gels(st.Matrix.from_numpy(a, 8), st.Matrix.from_numpy(b, 8))
        np.testing.assert_allclose(
            X.to_numpy(), np.linalg.lstsq(a, b, rcond=None)[0],
            rtol=1e-9, atol=1e-9)


# ------------------------------------------------------ hesv speculation

def test_hesv_speculate_hpd_first_try(rng):
    n, nb = 24, 8
    s = rng.standard_normal((n, n))
    hpd = s @ s.T + n * np.eye(n)
    b = rng.standard_normal((n, 2))
    A = st.HermitianMatrix.from_numpy(hpd, nb, uplo=st.Uplo.Lower)
    F, X, h = st.hesv(A, st.Matrix.from_numpy(b, nb), SPEC_INFO)
    assert bool(h.ok)
    np.testing.assert_allclose(X.to_numpy(), np.linalg.solve(hpd, b),
                               rtol=1e-10, atol=1e-10)


def test_hesv_speculate_indefinite_falls_back(rng):
    """An indefinite Hermitian input fails the Cholesky speculation and
    must land on the Aasen rung — even with UseFallbackSolver off (the
    Aasen fallback is hesv's baseline contract, not an extra)."""
    n, nb = 24, 8
    s = rng.standard_normal((n, n))
    indef = (s + s.T) / 2
    b = rng.standard_normal((n, 2))
    A = st.HermitianMatrix.from_numpy(indef, nb, uplo=st.Uplo.Lower)
    o = dict(SPEC_INFO)
    o[st.Option.UseFallbackSolver] = False
    F, X, h = st.hesv(A, st.Matrix.from_numpy(b, nb), o)
    assert bool(h.ok)
    np.testing.assert_allclose(X.to_numpy(), np.linalg.solve(indef, b),
                               rtol=1e-9, atol=1e-9)


# -------------------------------------------------------------- mesh path

@pytest.mark.slow
def test_dist_rbt_two_sided_matches_dense(rng):
    from slate_tpu.parallel.dist_lu import dist_rbt_two_sided
    n, nb = 16, 4
    g = st.Grid(2, 2, devices=jax.devices()[:4])
    a = rng.standard_normal((n, n))
    A = st.Matrix.from_numpy(a, nb, grid=g)
    u = rbt.generate(n, seed=11, dtype=np.float64)
    v = rbt.generate(n, seed=12, dtype=np.float64)
    data = dist_rbt_two_sided(A.storage.data, u, v, g, n)
    got = st.Matrix(st.TileStorage(data, n, n, nb, nb, g)).to_numpy()
    np.testing.assert_allclose(got, np.asarray(rbt.transform(a, u, v)),
                               rtol=1e-13, atol=1e-13)


@pytest.mark.slow
def test_gesv_speculate_mesh(rng):
    n, nb = 16, 4
    g = st.Grid(2, 2, devices=jax.devices()[:4])
    a = rng.standard_normal((n, n)) + n * np.eye(n)
    b = rng.standard_normal((n, 3))
    A = st.Matrix.from_numpy(a, nb, grid=g)
    B = st.Matrix.from_numpy(b, nb, grid=g)
    o = dict(SPEC_INFO)
    o[st.Option.Target] = "mesh"
    F, X, h = st.gesv(A, B, o)
    assert bool(h.ok)
    np.testing.assert_allclose(X.to_numpy(), np.linalg.solve(a, b),
                               rtol=1e-10, atol=1e-10)
