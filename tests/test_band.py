"""Band solver/multiply tests vs scipy banded references
(analog of ref test/test_gbsv.cc, test_pbsv.cc, test_tbsm.cc,
test_gbmm.cc, test_hbmm.cc)."""

import numpy as np
import pytest
import scipy.linalg as sla

import slate_tpu as st


def band_mask(n, kl, ku):
    i = np.arange(n)[:, None]
    j = np.arange(n)[None, :]
    return (j - i <= ku) & (i - j <= kl)


def make_band(rng, n, kl, ku, dtype=np.float64):
    a = rng.standard_normal((n, n)).astype(dtype)
    if np.issubdtype(dtype, np.complexfloating):
        a = a + 1j * rng.standard_normal((n, n))
    return np.where(band_mask(n, kl, ku), a, 0)


def make_spd_band(rng, n, kd, dtype=np.float64):
    a = make_band(rng, n, kd, kd, dtype)
    a = (a + a.conj().T) / 2
    return a + n * np.eye(n)


@pytest.mark.parametrize("n,kd,nb", [(16, 3, 4), (25, 5, 8), (10, 0, 4),
                                     (23, 9, 4)])
def test_pbsv(rng, n, kd, nb):
    a = make_spd_band(rng, n, kd)
    b = rng.standard_normal((n, 3))
    A = st.HermitianBandMatrix.from_numpy(a, kd, nb)
    B = st.Matrix.from_numpy(b, nb, nb)
    F, X = st.pbsv(A, B)
    np.testing.assert_allclose(a @ X.to_numpy(), b, atol=1e-10)


def test_pbsv_complex(rng):
    n, kd, nb = 18, 4, 5
    a = make_spd_band(rng, n, kd, np.complex128)
    b = rng.standard_normal((n, 2)) + 1j * rng.standard_normal((n, 2))
    F, X = st.pbsv(st.HermitianBandMatrix.from_numpy(a, kd, nb),
                   st.Matrix.from_numpy(b, nb, nb))
    np.testing.assert_allclose(a @ X.to_numpy(), b, atol=1e-10)


def test_pbsv_vs_scipy(rng):
    n, kd, nb = 20, 3, 8
    a = make_spd_band(rng, n, kd)
    b = rng.standard_normal((n, 2))
    # scipy solveh_banded wants upper packed
    ab = np.zeros((kd + 1, n))
    for o in range(kd + 1):
        ab[kd - o, o:] = np.diagonal(a, o)
    xs = sla.solveh_banded(ab, b)
    _, X = st.pbsv(st.HermitianBandMatrix.from_numpy(a, kd, nb),
                   st.Matrix.from_numpy(b, nb, nb))
    np.testing.assert_allclose(X.to_numpy(), xs, atol=1e-10)


def test_pbtrf_not_pd(rng):
    n, kd, nb = 12, 2, 4
    a = make_spd_band(rng, n, kd) - 3 * n * np.eye(n)   # indefinite
    with pytest.raises(st.SlateNotPositiveDefiniteError):
        st.pbtrf(st.HermitianBandMatrix.from_numpy(a, kd, nb))


@pytest.mark.parametrize("n,kl,ku,nb", [(16, 2, 3, 4), (25, 5, 1, 8),
                                        (20, 0, 4, 4), (23, 7, 7, 4),
                                        (10, 3, 0, 4)])
def test_gbsv(rng, n, kl, ku, nb):
    a = make_band(rng, n, kl, ku) + np.diag(np.sign(
        rng.standard_normal(n)) * 2)
    b = rng.standard_normal((n, 3))
    A = st.BandMatrix.from_numpy(a, kl, ku, nb)
    F, X = st.gbsv(A, st.Matrix.from_numpy(b, nb, nb))
    np.testing.assert_allclose(a @ X.to_numpy(), b, atol=1e-9)


def test_gbsv_vs_scipy(rng):
    n, kl, ku, nb = 30, 4, 2, 8
    a = make_band(rng, n, kl, ku)
    a += np.diag(np.sign(np.diagonal(a)) + np.diagonal(a))
    b = rng.standard_normal((n, 2))
    ab = np.zeros((kl + ku + 1, n))
    for o in range(-kl, ku + 1):
        if o >= 0:
            ab[ku - o, o:] = np.diagonal(a, o)
        else:
            ab[ku - o, :n + o] = np.diagonal(a, o)
    xs = sla.solve_banded((kl, ku), ab, b)
    _, X = st.gbsv(st.BandMatrix.from_numpy(a, kl, ku, nb),
                   st.Matrix.from_numpy(b, nb, nb))
    np.testing.assert_allclose(X.to_numpy(), xs, atol=1e-9)


def test_gbsv_complex(rng):
    n, kl, ku, nb = 15, 3, 2, 4
    a = make_band(rng, n, kl, ku, np.complex128)
    a += 2 * np.eye(n)
    b = rng.standard_normal((n, 2)) + 1j * rng.standard_normal((n, 2))
    _, X = st.gbsv(st.BandMatrix.from_numpy(a, kl, ku, nb),
                   st.Matrix.from_numpy(b, nb, nb))
    np.testing.assert_allclose(a @ X.to_numpy(), b, atol=1e-9)


def test_gbsv_needs_pivoting(rng):
    # leading diagonal zero: partial pivoting must kick in
    n, kl, ku, nb = 12, 2, 2, 4
    a = make_band(rng, n, kl, ku)
    a[0, 0] = 0.0
    b = rng.standard_normal((n, 1))
    _, X = st.gbsv(st.BandMatrix.from_numpy(a, kl, ku, nb),
                   st.Matrix.from_numpy(b, nb, nb))
    np.testing.assert_allclose(a @ X.to_numpy(), b, atol=1e-8)


@pytest.mark.parametrize("uplo", [st.Uplo.Lower, st.Uplo.Upper])
@pytest.mark.parametrize("op", ["n", "t", "c"])
def test_tbsm(rng, uplo, op):
    n, kd, nb = 18, 3, 4
    a = make_band(rng, n, kd if uplo is st.Uplo.Lower else 0,
                  0 if uplo is st.Uplo.Lower else kd, np.complex128)
    a += np.diag(2 + np.abs(np.diagonal(a)))
    b = rng.standard_normal((n, 2)) + 1j * rng.standard_normal((n, 2))
    A = st.TriangularBandMatrix.from_numpy(a, kd, nb, uplo)
    if op == "t":
        A = A.transpose()
        ae = a.T
    elif op == "c":
        A = A.conj_transpose()
        ae = a.conj().T
    else:
        ae = a
    X = st.tbsm("l", 2.0, A, st.Matrix.from_numpy(b, nb, nb))
    np.testing.assert_allclose(ae @ X.to_numpy(), 2.0 * b, atol=1e-9)


def test_tbsm_right(rng):
    n, kd, nb = 12, 2, 4
    a = np.tril(make_band(rng, n, kd, 0)) + 3 * np.eye(n)
    b = rng.standard_normal((4, n))
    A = st.TriangularBandMatrix.from_numpy(a, kd, nb, st.Uplo.Lower)
    X = st.tbsm("r", 1.0, A, st.Matrix.from_numpy(b, nb, nb))
    np.testing.assert_allclose(X.to_numpy() @ a, b, atol=1e-9)


def test_gbsv_op(rng):
    # gbsv on a transposed view must solve A^T X = B
    n, kl, ku, nb = 15, 3, 2, 4
    a = make_band(rng, n, kl, ku, np.complex128) + 3 * np.eye(n)
    b = rng.standard_normal((n, 2)) + 1j * rng.standard_normal((n, 2))
    A = st.BandMatrix.from_numpy(a, kl, ku, nb)
    _, Xt = st.gbsv(A.transpose(), st.Matrix.from_numpy(b, nb, nb))
    np.testing.assert_allclose(a.T @ Xt.to_numpy(), b, atol=1e-9)
    _, Xh = st.gbsv(A.conj_transpose(), st.Matrix.from_numpy(b, nb, nb))
    np.testing.assert_allclose(a.conj().T @ Xh.to_numpy(), b, atol=1e-9)


def test_pbsv_op_complex(rng):
    # A^T = conj(A) for Hermitian: the transposed view must not alias A
    n, kd, nb = 14, 3, 4
    a = make_spd_band(rng, n, kd, np.complex128)
    b = rng.standard_normal((n, 2)) + 1j * rng.standard_normal((n, 2))
    A = st.HermitianBandMatrix.from_numpy(a, kd, nb)
    _, X = st.pbsv(A.transpose(), st.Matrix.from_numpy(b, nb, nb))
    np.testing.assert_allclose(a.T @ X.to_numpy(), b, atol=1e-10)


def test_pbtrf_jittable(rng):
    import jax
    n, kd, nb = 12, 2, 4
    a = make_spd_band(rng, n, kd)

    def f(ad):
        A = st.HermitianBandMatrix.from_numpy(ad, kd, nb)
        return st.pbtrf(A).L_band

    lb = jax.jit(f)(a)
    assert np.isfinite(np.asarray(lb)).all()


def test_gbmm_rectangular(rng):
    m, n, kl, ku, nb = 6, 8, 2, 1, 4
    i = np.arange(m)[:, None]
    j = np.arange(n)[None, :]
    a = np.where((j - i <= ku) & (i - j <= kl),
                 rng.standard_normal((m, n)), 0)
    b = rng.standard_normal((n, 3))
    A = st.BandMatrix.from_numpy(a, kl, ku, nb)
    out = st.gbmm(1.0, A, b)
    np.testing.assert_allclose(np.asarray(out), a @ b, atol=1e-12)
    # tall case
    at = np.where((i.T - j.T <= 2) & (j.T - i.T <= 1),
                  rng.standard_normal((n, m)), 0)
    out2 = st.gbmm(1.0, st.BandMatrix.from_numpy(at, 2, 1, nb),
                   rng.standard_normal((m, 2)))
    assert out2.shape == (n, 2)


def test_gbmm(rng):
    n, kl, ku, nb = 20, 3, 2, 4
    a = make_band(rng, n, kl, ku)
    b = rng.standard_normal((n, 5))
    c = rng.standard_normal((n, 5))
    A = st.BandMatrix.from_numpy(a, kl, ku, nb)
    out = st.gbmm(1.5, A, st.Matrix.from_numpy(b, nb, nb), 0.5,
                  st.Matrix.from_numpy(c, nb, nb))
    np.testing.assert_allclose(out.to_numpy(), 1.5 * a @ b + 0.5 * c,
                               atol=1e-11)
    # transposed band
    out_t = st.gbmm(1.0, A.transpose(), st.Matrix.from_numpy(b, nb, nb))
    np.testing.assert_allclose(out_t.to_numpy(), a.T @ b, atol=1e-11)


def test_hbmm(rng):
    n, kd, nb = 16, 3, 4
    a = make_spd_band(rng, n, kd, np.complex128)
    b = rng.standard_normal((n, 4)) + 1j * rng.standard_normal((n, 4))
    A = st.HermitianBandMatrix.from_numpy(a, kd, nb)
    out = st.hbmm("l", 1.0, A, st.Matrix.from_numpy(b, nb, nb))
    np.testing.assert_allclose(out.to_numpy(), a @ b, atol=1e-10)
    outr = st.hbmm("r", 1.0, A, st.Matrix.from_numpy(b.conj().T, nb, nb))
    np.testing.assert_allclose(outr.to_numpy(), b.conj().T @ a, atol=1e-10)
