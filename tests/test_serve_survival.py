"""Serving survival-layer tests (docs/SERVING.md "Survival"): the
background flush loop, deadline-aware admission control, SLO-driven
backpressure, poison quarantine, and the chaos harness.

The load-bearing guarantees:

- the background loop delivers correct results to tickets while
  callers keep submitting from multiple threads, and shutdown() drains
  in-flight work or fails it loudly — never leaking a daemon thread or
  leaving a ticket unsettled;
- the watchdog converts a wedged flush (injected compile stall) into
  typed ``SlateServeTimeoutError`` failures on every pending request,
  and the wedged server refuses new work instead of queueing it into
  a black hole;
- overflow policies and deadline shedding are typed and accounted: a
  shed request's ticket holds the error, a ``serve_shed`` obs record
  is emitted, and under 2x overload the admitted requests' p99 still
  passes the declared SLO budget;
- a poisoned problem (escalation ladder exhausted) is retried in
  exactly one fresh batch, then quarantined to a singleton slow path
  with a ``serve_quarantine`` record — its neighbors' results stay
  correct throughout;
- request-id accounting: every admitted ticket settles exactly once
  (no request lost, none answered twice), including under chaos;
- a failed background flush is sticky: the next ``drain()`` re-raises
  the typed error even when the queue is already empty.

Everything here is deterministic on CPU: chaos comes from seeded
``robust.faults`` plans and the seeded Poisson workload generator, not
from real device failures.
"""

import json
import threading
import time

import numpy as np
import pytest

from slate_tpu import obs, serve
from slate_tpu.exceptions import (SlateServeError, SlateServeOverloadError,
                                  SlateServeTimeoutError)
from slate_tpu.obs import __main__ as obs_cli
from slate_tpu.obs import slo
from slate_tpu.robust import faults


def _rng():
    return np.random.default_rng(77)


def _mk_solve(rng, n, k=2, dtype=np.float32):
    a = rng.standard_normal((n, n)).astype(dtype)
    a += np.eye(n, dtype=dtype) * 4
    return a, rng.standard_normal((n, k)).astype(dtype)


def _poison_solve(n=8, k=2, dtype=np.float32):
    """A singular system: escalates in-graph AND stays unhealthy —
    deterministically exhausts the escalation ladder."""
    return np.zeros((n, n), dtype), np.ones((n, k), dtype)


def _check_solve(a, b, res, tol=1e-3):
    assert np.allclose(res.x, np.linalg.solve(
        a.astype(np.float64), b.astype(np.float64)), atol=tol)


def _serve_threads():
    return [t for t in threading.enumerate()
            if t.name.startswith("slate-serve-")]


def _shed_events(recs):
    return [e for e in recs if e.get("kind") == "serve_shed"]


# ------------------------------------------------------ background loop


def test_background_loop_delivers_correct_results():
    rng = _rng()
    cfg = serve.AdmissionConfig(flush_occupancy=4, max_batch_delay_ms=10.0)
    srv = serve.Server(cache=serve.ExecutableCache(), admission=cfg)
    srv.start()
    assert srv.running()
    try:
        probs = [_mk_solve(rng, n) for n in (8, 8, 12, 12, 20, 20)]
        tickets = [srv.submit("solve", a, b) for a, b in probs]
        for (a, b), t in zip(probs, tickets):
            _check_solve(a, b, t.result(timeout=120.0))
            assert t.done() and t.error() is None
    finally:
        srv.shutdown()
    assert not srv.running()


def test_start_is_idempotent():
    srv = serve.Server(cache=serve.ExecutableCache())
    srv.start()
    try:
        before = _serve_threads()
        srv.start()                      # no second pair of threads
        assert _serve_threads() == before
    finally:
        srv.shutdown()


def test_concurrent_submit_under_live_loop_accounts_every_request():
    """4 threads pound submit() under the live loop: every ticket
    settles exactly once with a correct result, tids are unique, and a
    late duplicate delivery is dropped (first-write-wins)."""
    rng = _rng()
    cfg = serve.AdmissionConfig(max_queue=1024, flush_occupancy=6,
                                max_batch_delay_ms=2.0)
    srv = serve.Server(cache=serve.ExecutableCache(), admission=cfg)
    probs = [_mk_solve(rng, n) for n in (8, 12, 20, 28)]
    srv.serve_batch([("solve", a, b) for a, b in probs])  # warm buckets
    srv.start()
    done, errs = [], []
    lock = threading.Lock()

    def pound(wid):
        try:
            local = []
            for i in range(8):
                a, b = probs[(wid + i) % len(probs)]
                local.append((a, b, srv.submit("solve", a, b)))
            for a, b, t in local:
                _check_solve(a, b, t.result(timeout=120.0))
                with lock:
                    done.append(t)
        except Exception as e:          # surfaced below, not swallowed
            with lock:
                errs.append(e)

    threads = [threading.Thread(target=pound, args=(w,)) for w in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(180.0)
    srv.shutdown()
    assert errs == []
    assert len(done) == 32
    assert len({t.tid for t in done}) == 32          # no double-admission
    # no request answered twice: a late write is refused
    assert all(not t.deliver("late") for t in done)


def test_shutdown_drains_queued_requests():
    rng = _rng()
    # occupancy watermark unreachably high: requests sit queued until
    # shutdown's drain settles them
    cfg = serve.AdmissionConfig(flush_occupancy=1000,
                                max_batch_delay_ms=60_000.0)
    srv = serve.Server(cache=serve.ExecutableCache(), admission=cfg)
    srv.start()
    a, b = _mk_solve(rng, 8)
    tickets = [srv.submit("solve", a, b) for _ in range(3)]
    srv.shutdown(drain=True)
    for t in tickets:
        _check_solve(a, b, t.result(timeout=1.0))


def test_shutdown_without_drain_fails_loudly():
    rng = _rng()
    cfg = serve.AdmissionConfig(flush_occupancy=1000,
                                max_batch_delay_ms=60_000.0)
    srv = serve.Server(cache=serve.ExecutableCache(), admission=cfg)
    srv.start()
    a, b = _mk_solve(rng, 8)
    with obs.recording() as recs:
        tickets = [srv.submit("solve", a, b) for _ in range(3)]
        srv.shutdown(drain=False)
    for t in tickets:
        with pytest.raises(SlateServeTimeoutError) as ei:
            t.result(timeout=1.0)
        assert ei.value.reason == "shutdown"
    assert len(_shed_events(recs)) == 3
    assert srv.queue.stats()["shed"] >= 3


def test_shutdown_never_leaks_daemon_threads():
    srv = serve.Server(cache=serve.ExecutableCache())
    assert _serve_threads() == []
    srv.start()
    assert len(_serve_threads()) == 2        # flush loop + watchdog
    srv.shutdown()
    assert _serve_threads() == []
    # submitting after shutdown is a typed closed-queue error
    a, b = _mk_solve(_rng(), 8)
    with pytest.raises(SlateServeTimeoutError) as ei:
        srv.submit("solve", a, b)
    assert ei.value.reason == "shutdown"


def test_warm_server_async_path_is_retrace_free():
    """The background path reuses the synchronous executables: a server
    warmed via serve_batch compiles nothing and retraces nothing when
    the same workload arrives through the live loop.  The occupancy
    watermark equals the workload size, so the loop flushes ONE batch
    with the same per-bucket group sizes the warm pass compiled."""
    rng = _rng()
    probs = [_mk_solve(rng, n) for n in (8, 8, 20, 20)]
    cfg = serve.AdmissionConfig(flush_occupancy=4,
                                max_batch_delay_ms=60_000.0)
    srv = serve.Server(cache=serve.ExecutableCache(), admission=cfg)
    srv.serve_batch([("solve", a, b) for a, b in probs])   # warm
    srv.start()
    try:
        with obs.recording() as recs:
            tickets = [srv.submit("solve", a, b) for a, b in probs]
            for (a, b), t in zip(probs, tickets):
                _check_solve(a, b, t.result(timeout=120.0))
        evs = [e for e in recs if e.get("kind") == "serve_batch"]
        assert evs and all(not e["compiled"] for e in evs)
        assert all(e["retraces"] == 0 for e in evs)
    finally:
        srv.shutdown()


# ------------------------------------------------- watchdog / wedging


def test_watchdog_fails_wedged_flush_with_typed_error():
    """Injected compile stall >> watchdog budget: every pending ticket
    fails with SlateServeTimeoutError, the server reports wedged, and
    new submits are refused instead of silently queued."""
    rng = _rng()
    cfg = serve.AdmissionConfig(flush_occupancy=1, max_batch_delay_ms=1.0,
                                watchdog_timeout_s=0.2)
    srv = serve.Server(cache=serve.ExecutableCache(), admission=cfg)
    srv.start()
    a, b = _mk_solve(rng, 8)
    try:
        with obs.recording() as recs:
            with faults.inject(faults.FaultPlan(
                    "serve_compile_stall", transient=True, delay_s=2.0)):
                t = srv.submit("solve", a, b)
                with pytest.raises(SlateServeTimeoutError) as ei:
                    t.result(timeout=30.0)
        assert ei.value.reason == "watchdog"
        assert srv.wedged() is not None
        info = srv.health_info()
        assert info["wedged"] is not None
        with pytest.raises(SlateServeTimeoutError) as ei2:
            srv.submit("solve", a, b)
        assert ei2.value.reason == "wedged"
        sheds = _shed_events(recs)
        assert any(e["reason"] == "watchdog" for e in sheds)
    finally:
        # the wedged flush thread is still sleeping through the injected
        # stall; wait it out so its late (dropped) delivery cannot leak
        # obs events into the next test's recording
        zombies = _serve_threads()
        srv.shutdown()
        for z in zombies:
            z.join(120.0)
        assert _serve_threads() == []


# ------------------------------------------- admission control policies


def test_overflow_reject_is_typed():
    rng = _rng()
    cfg = serve.AdmissionConfig(max_queue=4, overflow="reject")
    srv = serve.Server(cache=serve.ExecutableCache(), admission=cfg)
    a, b = _mk_solve(rng, 8)
    with obs.recording() as recs:
        for _ in range(4):
            srv.submit("solve", a, b)
        with pytest.raises(SlateServeOverloadError) as ei:
            srv.submit("solve", a, b)
    assert ei.value.policy == "reject"
    (shed,) = _shed_events(recs)
    assert shed["reason"] == "overflow_reject"
    for res in srv.drain():
        _check_solve(a, b, res)


def test_overflow_shed_oldest_fails_victim_ticket():
    rng = _rng()
    cfg = serve.AdmissionConfig(max_queue=4, overflow="shed_oldest")
    srv = serve.Server(cache=serve.ExecutableCache(), admission=cfg)
    a, b = _mk_solve(rng, 8)
    with obs.recording() as recs:
        tickets = [srv.submit("solve", a, b) for _ in range(5)]
    victim, survivors = tickets[0], tickets[1:]
    assert victim.done()
    with pytest.raises(SlateServeOverloadError) as ei:
        victim.result(timeout=0.1)
    assert ei.value.policy == "shed_oldest"
    (shed,) = _shed_events(recs)
    assert shed["reason"] == "overflow_shed_oldest"
    srv.drain()
    for t in survivors:
        _check_solve(a, b, t.result(timeout=1.0))


def test_overflow_block_times_out_typed():
    rng = _rng()
    cfg = serve.AdmissionConfig(max_queue=2, overflow="block",
                                block_timeout_s=0.05)
    srv = serve.Server(cache=serve.ExecutableCache(), admission=cfg)
    a, b = _mk_solve(rng, 8)
    srv.submit("solve", a, b)
    srv.submit("solve", a, b)
    t0 = time.perf_counter()
    with pytest.raises(SlateServeOverloadError) as ei:
        srv.submit("solve", a, b)
    assert ei.value.policy == "block"
    assert time.perf_counter() - t0 >= 0.04


def test_overflow_block_unblocks_when_space_frees():
    rng = _rng()
    cfg = serve.AdmissionConfig(max_queue=2, overflow="block",
                                block_timeout_s=30.0)
    srv = serve.Server(cache=serve.ExecutableCache(), admission=cfg)
    a, b = _mk_solve(rng, 8)
    srv.submit("solve", a, b)
    srv.submit("solve", a, b)
    admitted = threading.Event()

    def blocked_submit():
        srv.submit("solve", a, b)
        admitted.set()

    t = threading.Thread(target=blocked_submit)
    t.start()
    assert not admitted.wait(0.05)       # genuinely blocked on the full
    srv.drain()                          # queue; take_all frees space
    assert admitted.wait(10.0)
    t.join(10.0)
    for res in srv.drain():
        _check_solve(a, b, res)


def test_deadline_shed_at_admission_uses_governor_estimate():
    """A request whose deadline is tighter than the rolling service
    estimate is shed at submit — it never occupies a queue slot."""
    rng = _rng()
    cfg = serve.AdmissionConfig(slo_budget_ms=100.0)
    srv = serve.Server(cache=serve.ExecutableCache(), admission=cfg)
    for _ in range(16):
        srv.queue.governor.observe(50.0)     # rolling p50 = 50ms
    a, b = _mk_solve(rng, 8)
    with obs.recording() as recs:
        with pytest.raises(SlateServeTimeoutError) as ei:
            srv.submit("solve", a, b, deadline_ms=1.0)
    assert ei.value.reason == "deadline"
    assert srv.queue.depth() == 0
    (shed,) = _shed_events(recs)
    assert shed["reason"] == "deadline"
    # a deadline wider than the estimate is admitted
    t = srv.submit("solve", a, b, deadline_ms=10_000.0)
    srv.drain()
    _check_solve(a, b, t.result(timeout=1.0))


def test_deadline_expiry_in_queue_sheds_at_flush():
    rng = _rng()
    srv = serve.Server(cache=serve.ExecutableCache())
    a, b = _mk_solve(rng, 8)
    t = srv.submit("solve", a, b, deadline_ms=1.0)
    time.sleep(0.02)
    with obs.recording() as recs:
        assert srv.drain() == []
    with pytest.raises(SlateServeTimeoutError) as ei:
        t.result(timeout=0.1)
    assert ei.value.reason == "deadline"
    (shed,) = _shed_events(recs)
    assert shed["reason"] == "deadline" and shed["age_ms"] > 0


def test_slo_backpressure_halves_capacity():
    gov = slo.LatencyGovernor(budget_ms=10.0, window=8)
    q = serve.AdmissionQueue(serve.AdmissionConfig(max_queue=8), gov)
    assert q.capacity() == 8
    for _ in range(8):
        gov.observe(50.0)                # p99 blows the 10ms budget
    assert gov.overloaded()
    assert q.capacity() == 4
    gov2 = slo.LatencyGovernor(budget_ms=None)
    for _ in range(8):
        gov2.observe(1e9)
    assert not gov2.overloaded()         # no budget -> no backpressure


def test_two_x_overload_shed_keeps_admitted_p99_in_budget():
    """The acceptance scenario: 2x the queue capacity offered under
    shed_oldest.  Exactly half is shed (typed + accounted) and the
    ADMITTED requests' p99 latency still passes the declared budget —
    shedding is how the server keeps its latency promise."""
    rng = _rng()
    budget_ms = 60_000.0                 # generous: CPU CI boxes vary
    cfg = serve.AdmissionConfig(max_queue=8, overflow="shed_oldest",
                                slo_budget_ms=budget_ms)
    srv = serve.Server(cache=serve.ExecutableCache(), admission=cfg)
    a, b = _mk_solve(rng, 8)
    srv.serve_batch([("solve", a, b)])   # warm: steady-state latencies
    with obs.recording() as recs:
        tickets = [srv.submit("solve", a, b) for _ in range(16)]
        srv.drain()
    shed = [t for t in tickets if t.error() is not None]
    served = [t for t in tickets if t.error() is None]
    assert len(shed) == 8 and len(served) == 8
    assert all(isinstance(t.error(), SlateServeOverloadError)
               for t in shed)
    for t in served:
        _check_solve(a, b, t.result(timeout=1.0))
    stats = slo.aggregate(list(recs))
    union = stats["*"]
    assert union["problems"] == 8 and union["shed"] == 8
    assert union["shed_per_1k"] == 500.0   # 8 shed per 16 offered
    verdicts = slo.evaluate(stats, {"*": {"latency_p99_ms": budget_ms}})
    assert all(v["ok"] for v in verdicts)


# --------------------------------------------------- poison quarantine


def test_poison_quarantined_after_exactly_one_fresh_batch_retry():
    """A deterministic poison (singular system) rides the original
    batch, one fresh-batch retry, then the singleton quarantine path:
    three serve_batch records plus one serve_quarantine, neighbors
    correct the whole way."""
    rng = _rng()
    good_a, good_b = _mk_solve(rng, 8)
    bad_a, bad_b = _poison_solve(8)
    srv = serve.Server(cache=serve.ExecutableCache())
    with obs.recording() as recs:
        res = srv.serve_batch([("solve", good_a, good_b),
                               ("solve", bad_a, bad_b),
                               ("solve", good_a, good_b)])
    batches = [e for e in recs if e.get("kind") == "serve_batch"]
    quars = [e for e in recs if e.get("kind") == "serve_quarantine"]
    assert [e["problems"] for e in batches] == [3, 1, 1]
    (quar,) = quars
    assert quar["reason"] == "escalation_exhausted"
    assert quar["retries"] == 1          # exactly one fresh-batch retry
    assert not quar["ok"]
    # neighbors never see the poison: correct results, healthy flags
    _check_solve(good_a, good_b, res[0])
    _check_solve(good_a, good_b, res[2])
    assert bool(res[0].health.ok) and bool(res[2].health.ok)
    # the poisoned slot reports its own exhaustion, loudly
    assert res[1].escalated and not bool(res[1].health.ok)
    assert srv.health_info()["quarantined"] == 1


def test_poison_quarantine_on_background_path():
    rng = _rng()
    good_a, good_b = _mk_solve(rng, 8)
    bad_a, bad_b = _poison_solve(8)
    cfg = serve.AdmissionConfig(flush_occupancy=3,
                                max_batch_delay_ms=10.0)
    srv = serve.Server(cache=serve.ExecutableCache(), admission=cfg)
    srv.start()
    try:
        with obs.recording() as recs:
            tg1 = srv.submit("solve", good_a, good_b)
            tp = srv.submit("solve", bad_a, bad_b)
            tg2 = srv.submit("solve", good_a, good_b)
            _check_solve(good_a, good_b, tg1.result(timeout=120.0))
            _check_solve(good_a, good_b, tg2.result(timeout=120.0))
            poisoned = tp.result(timeout=120.0)
        assert poisoned.escalated and not bool(poisoned.health.ok)
        assert [e["kind"] for e in recs].count("serve_quarantine") == 1
    finally:
        srv.shutdown()


# -------------------------------------------------------- sticky errors


def test_failed_background_flush_is_sticky_on_empty_drain(monkeypatch):
    """A flush that dies in the loop must not evaporate: the ticket
    holds the typed error AND the next drain() re-raises it even though
    the queue is empty by then — then clears it (raise once)."""
    rng = _rng()
    cfg = serve.AdmissionConfig(flush_occupancy=1, max_batch_delay_ms=1.0)
    srv = serve.Server(cache=serve.ExecutableCache(), admission=cfg)

    def boom(*args, **kwargs):
        raise RuntimeError("injected flush failure")

    monkeypatch.setattr(srv, "_run_group", boom)
    srv.start()
    a, b = _mk_solve(rng, 8)
    try:
        t = srv.submit("solve", a, b)
        with pytest.raises(SlateServeError):
            t.result(timeout=30.0)
        assert srv.queue.depth() == 0
        # the ticket settles inside the flush; the server-level sticky
        # error lands when the flush returns — wait for that handoff
        deadline = time.perf_counter() + 10.0
        while srv._flush_error is None and time.perf_counter() < deadline:
            time.sleep(0.005)
        with pytest.raises(SlateServeError, match="injected"):
            srv.drain()
        assert srv.drain() == []         # sticky error raises ONCE
    finally:
        srv.shutdown()


def test_sync_drain_group_failure_lands_on_tickets(monkeypatch):
    rng = _rng()
    srv = serve.Server(cache=serve.ExecutableCache())

    def boom(*args, **kwargs):
        raise RuntimeError("injected group failure")

    monkeypatch.setattr(srv, "_run_group", boom)
    a, b = _mk_solve(rng, 8)
    t = srv.submit("solve", a, b)
    with pytest.raises(SlateServeError, match="injected"):
        srv.drain()
    assert isinstance(t.error(), SlateServeError)


# --------------------------------------------------------- chaos harness


def test_chaos_flush_delay_ages_the_batch():
    rng = _rng()
    srv = serve.Server(cache=serve.ExecutableCache())
    a, b = _mk_solve(rng, 8)
    srv.serve_batch([("solve", a, b)])   # warm
    srv.submit("solve", a, b)
    with obs.recording() as recs:
        with faults.inject(faults.FaultPlan("serve_flush_delay",
                                            delay_s=0.05)):
            (res,) = srv.drain()
    _check_solve(a, b, res)
    (ev,) = [e for e in recs if e.get("kind") == "serve_batch"]
    assert all(age >= 50.0 for age in ev["age_at_flush_ms"])


def test_chaos_cache_evict_forces_recompile_but_serves():
    rng = _rng()
    cache = serve.ExecutableCache()
    srv = serve.Server(cache=cache)
    a, b = _mk_solve(rng, 8)
    srv.serve_batch([("solve", a, b)])   # warm
    assert cache.stats()["entries"] == 1
    with obs.recording() as recs:
        with faults.inject(faults.FaultPlan("serve_cache_evict",
                                            transient=True)):
            (res,) = srv.serve_batch([("solve", a, b)])
    _check_solve(a, b, res)
    (ev,) = [e for e in recs if e.get("kind") == "serve_batch"]
    assert ev["compiled"]                # eviction forced the recompile
    assert cache.stats()["entries"] == 1


def test_host_fire_transient_consumes_once_per_activation():
    plan = faults.FaultPlan("serve_compile_stall", transient=True,
                            delay_s=0.1)
    assert faults.host_fire("serve_compile_stall") is None  # inactive
    with faults.inject(plan):
        assert faults.host_fire("serve_compile_stall") is plan
        assert faults.host_fire("serve_compile_stall") is None  # spent
    with faults.inject(plan):            # fresh activation, fresh strike
        assert faults.host_fire("serve_compile_stall") is plan
    persistent = faults.FaultPlan("serve_flush_delay", delay_s=0.1)
    with faults.inject(persistent):
        assert faults.host_fire("serve_flush_delay") is persistent
        assert faults.host_fire("serve_flush_delay") is persistent
    # traced sites never leak through the host hook
    with faults.inject(faults.FaultPlan("input")):
        assert faults.host_fire("input") is None


def test_poisson_workload_is_deterministic_and_well_formed():
    w1 = faults.poisson_workload(42, 12, 200.0, (8, 16))
    w2 = faults.poisson_workload(42, 12, 200.0, (8, 16))
    assert len(w1) == 12
    arrivals = [t for t, _, _, _ in w1]
    assert arrivals == sorted(arrivals)
    for (t1, op1, a1, b1), (t2, op2, a2, b2) in zip(w1, w2):
        assert t1 == t2 and op1 == op2
        assert np.array_equal(a1, a2) and np.array_equal(b1, b2)
    assert [t for t, *_ in faults.poisson_workload(
        43, 12, 200.0, (8, 16))] != arrivals
    # every request round-trips the server healthily (well-conditioned)
    srv = serve.Server(cache=serve.ExecutableCache())
    results = srv.serve_batch([(op, a, b) for _, op, a, b in w1[:6]])
    assert all(bool(r.health.ok) for r in results)


# ------------------------------------------------------- obs / CLI table


def test_cli_serving_table_renders_shed_and_quarantine_columns(
        tmp_path, capsys):
    """The metrics CLI smoke test: a stream with batches, sheds and a
    quarantine renders the serving table with the shed/1k and quar/1k
    columns populated."""
    rng = _rng()
    good_a, good_b = _mk_solve(rng, 8)
    bad_a, bad_b = _poison_solve(8)
    cfg = serve.AdmissionConfig(max_queue=2, overflow="shed_oldest")
    srv = serve.Server(cache=serve.ExecutableCache(), admission=cfg)
    with obs.recording() as recs:
        for _ in range(4):               # 2 admitted, 2 shed
            srv.submit("solve", good_a, good_b)
        srv.submit("solve", bad_a, bad_b)  # sheds one more, then poisons
        srv.drain()
    path = tmp_path / "events.jsonl"
    path.write_text("".join(json.dumps(e) + "\n" for e in recs))

    row = obs.summarize([str(path)])["serve"]["solve/float32"]
    assert row["shed"] == 3 and row["quarantined"] == 1
    # served problems count every executed batch slot: the original
    # pair, the poison's fresh-batch retry, and its quarantine singleton
    assert row["problems"] == 4
    assert row["shed_per_1k"] == round(1000.0 * 3 / 7, 2)
    assert row["quar_per_1k"] == 250.0   # 1 quarantined per 4 served
    assert obs_cli.main([str(path)]) == 0
    out = capsys.readouterr().out
    assert "shed/1k" in out and "quar/1k" in out
    assert "428.57" in out and " 250 " in out   # _fmt drops trailing .0


def test_compare_classifies_survival_metrics():
    """shed/quar metrics are lower-better and survival lines get the
    widest noise band (first-match ordering: 'survival' before
    'serve')."""
    from slate_tpu.obs import compare
    assert compare.direction("serve_survival_shed_per_1k") == "lower"
    assert compare.direction("serve_survival_quar_per_1k") == "lower"
    assert compare.noise_pct("serve_survival_problems_per_s") == 20.0
    assert compare.noise_pct("serve_mixed_problems_per_s") == 15.0


def test_health_info_reports_front_door_state():
    cfg = serve.AdmissionConfig(slo_budget_ms=250.0)
    srv = serve.Server(cache=serve.ExecutableCache(), admission=cfg)
    info = srv.health_info()
    assert info["queue"]["depth"] == 0 and not info["queue"]["closed"]
    assert info["running"] is False and info["wedged"] is None
    assert info["quarantined"] == 0
    assert info["slo_budget_ms"] == 250.0 and info["slo_p99_ms"] is None
