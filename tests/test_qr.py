"""QR / LQ / least-squares tests: geqrf/unmqr/gels/cholqr residuals vs numpy
on single device and meshes (analog of ref test/test_geqrf.cc,
test_gels.cc, test_unmqr.cc: orthogonality ||Q^H Q - I|| and factorization
||A - QR|| / (||A|| n) residuals)."""

import jax
import numpy as np
import pytest

import slate_tpu as st


def _thin_q(F, m, r):
    """Materialise thin Q columns by applying Q to the identity."""
    eye = np.eye(m, r)
    E = st.Matrix.from_numpy(eye.astype(F.QR.to_numpy().dtype),
                             F.QR.nb, F.QR.nb, F.QR.grid)
    return st.unmqr("l", "n", F, E).to_numpy()


@pytest.mark.parametrize("m,n,nb", [(24, 24, 8), (30, 18, 7), (40, 12, 4)])
def test_geqrf_single(rng, m, n, nb):
    a = rng.standard_normal((m, n))
    A = st.Matrix.from_numpy(a, nb)
    F = st.geqrf(A)
    r = np.triu(F.QR.to_numpy())[:n]
    q = _thin_q(F, m, n)
    np.testing.assert_allclose(q.T @ q, np.eye(n), atol=1e-12)
    np.testing.assert_allclose(q @ r, a, atol=1e-11)


def test_geqrf_complex(rng):
    m, n, nb = 20, 12, 4
    a = rng.standard_normal((m, n)) + 1j * rng.standard_normal((m, n))
    A = st.Matrix.from_numpy(a, nb)
    F = st.geqrf(A)
    r = np.triu(F.QR.to_numpy())[:n]
    q = _thin_q(F, m, n)
    np.testing.assert_allclose(q.conj().T @ q, np.eye(n), atol=1e-12)
    np.testing.assert_allclose(q @ r, a, atol=1e-11)


@pytest.mark.parametrize("p,q_,m,n,nb", [
    (2, 2, 24, 24, 4),       # square, exact tiling
    (2, 2, 37, 15, 5),       # ragged rows+cols
    (2, 4, 48, 8, 4),        # tall-skinny on a wide grid
])
def test_geqrf_mesh(rng, p, q_, m, n, nb):
    g = st.Grid(p, q_, devices=jax.devices()[: p * q_])
    a = rng.standard_normal((m, n))
    A = st.Matrix.from_numpy(a, nb, nb, g)
    F = st.geqrf(A)
    r = np.triu(F.QR.to_numpy())[:n]
    q = _thin_q(F, m, n)
    np.testing.assert_allclose(q.T @ q, np.eye(n), atol=1e-11)
    np.testing.assert_allclose(q @ r, a, atol=1e-10)


@pytest.mark.parametrize("target,op,side", [
    ("single", "n", "l"), ("single", "c", "l"),
    ("single", "n", "r"), ("single", "c", "r"),
    ("mesh", "c", "l"), ("mesh", "n", "r"),
])
@pytest.mark.slow
def test_unmqr_orthogonal_apply(rng, target, op, side):
    m, n, nb = 24, 16, 4
    g = st.Grid(2, 2, devices=jax.devices()[:4]) if target == "mesh" else None
    a = rng.standard_normal((m, n))
    F = st.geqrf(st.Matrix.from_numpy(a, nb, nb, g))
    cshape = (m, 10) if side == "l" else (10, m)
    cd = rng.standard_normal(cshape)
    C = st.Matrix.from_numpy(cd, nb, nb, g)
    X = st.unmqr(side, op, F, C)
    # Q is orthogonal: applying op then its inverse round-trips
    Y = st.unmqr(side, "n" if op == "c" else "c", F, X)
    np.testing.assert_allclose(Y.to_numpy(), cd, atol=1e-11)
    # and the apply actually changes C (Q != I)
    assert not np.allclose(X.to_numpy(), cd)


@pytest.mark.parametrize("target", ["single", "mesh"])
@pytest.mark.slow
def test_gels_qr_tall(rng, target):
    m, n, nrhs, nb = 36, 12, 3, 4
    g = st.Grid(2, 2, devices=jax.devices()[:4]) if target == "mesh" else None
    a = rng.standard_normal((m, n))
    b = rng.standard_normal((m, nrhs))
    A = st.Matrix.from_numpy(a, nb, nb, g)
    B = st.Matrix.from_numpy(b, nb, nb, g)
    X = st.gels_qr(A, B)
    xref = np.linalg.lstsq(a, b, rcond=None)[0]
    np.testing.assert_allclose(X.to_numpy(), xref, atol=1e-10)


@pytest.mark.parametrize("target", ["single", "mesh"])
@pytest.mark.slow
def test_gels_cholqr_tall(rng, target):
    m, n, nrhs, nb = 48, 8, 3, 4
    g = st.Grid(2, 2, devices=jax.devices()[:4]) if target == "mesh" else None
    a = rng.standard_normal((m, n))
    b = rng.standard_normal((m, nrhs))
    A = st.Matrix.from_numpy(a, nb, nb, g)
    B = st.Matrix.from_numpy(b, nb, nb, g)
    X = st.gels_cholqr(A, B)
    xref = np.linalg.lstsq(a, b, rcond=None)[0]
    np.testing.assert_allclose(X.to_numpy(), xref, atol=1e-9)


def test_gels_auto_dispatch(rng):
    # tall-skinny auto-selects CholQR; mildly rectangular selects QR
    m, n, nb = 40, 10, 5
    a = rng.standard_normal((m, n))
    b = rng.standard_normal((m, 2))
    X = st.gels(st.Matrix.from_numpy(a, nb), st.Matrix.from_numpy(b, nb))
    xref = np.linalg.lstsq(a, b, rcond=None)[0]
    np.testing.assert_allclose(X.to_numpy(), xref, atol=1e-9)


@pytest.mark.slow
def test_gels_minimum_norm(rng):
    m, n, nb = 12, 30, 4
    a = rng.standard_normal((m, n))
    b = rng.standard_normal((m, 2))
    X = st.gels(st.Matrix.from_numpy(a, nb), st.Matrix.from_numpy(b, nb))
    x = X.to_numpy()
    xref = np.linalg.lstsq(a, b, rcond=None)[0]   # minimum-norm solution
    np.testing.assert_allclose(a @ x, b, atol=1e-10)
    np.testing.assert_allclose(x, xref, atol=1e-9)


def test_cholqr(rng):
    m, n, nb = 32, 8, 4
    a = rng.standard_normal((m, n))
    Q, R = st.cholqr(st.Matrix.from_numpy(a, nb))
    q, r = Q.to_numpy(), R.to_numpy()
    np.testing.assert_allclose(q.T @ q, np.eye(n), atol=1e-11)
    np.testing.assert_allclose(q @ r, a, atol=1e-11)
    assert np.allclose(np.tril(r, -1), 0)


def test_gelqf_unmlq(rng):
    m, n, nb = 12, 28, 4
    a = rng.standard_normal((m, n))
    F = st.gelqf(st.Matrix.from_numpy(a, nb))
    packed = F.F.QR.to_numpy()
    ell = np.triu(packed[:m, :m]).T                # L = R^H
    # A = L Q  =>  Q = L^-1 A has orthonormal rows
    q = np.linalg.solve(ell, a)
    np.testing.assert_allclose(q @ q.T, np.eye(m), atol=1e-11)


def test_qr_multiply(rng):
    m, n, nb = 20, 8, 4
    a = rng.standard_normal((m, n))
    F = st.geqrf(st.Matrix.from_numpy(a, nb))
    Q = st.qr_multiply(F)
    q = Q.to_numpy()[:, :n]
    np.testing.assert_allclose(q.T @ q, np.eye(n), atol=1e-12)


def test_geqrf_complex_cholqr_panel(rng):
    # tall complex panel with nb >= 8 drives panel_qr_cholqr (the
    # reconstruction path needs R scaled by conj(S) — S is a unitary
    # phase diagonal for complex data, not just signs)
    m, n, nb = 96, 16, 16
    a = (rng.standard_normal((m, n))
         + 1j * rng.standard_normal((m, n)))
    A = st.Matrix.from_numpy(a, nb, nb)
    F = st.geqrf(A)
    Q = st.qr_multiply(F).to_numpy()
    R = np.triu(F.QR.to_numpy()[:n, :n])
    np.testing.assert_allclose(Q @ R, a, atol=1e-10)
    np.testing.assert_allclose(Q.conj().T @ Q, np.eye(n), atol=1e-11)
