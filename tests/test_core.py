"""Unit tests for the tile-storage / matrix core.

Analog of the reference's per-class unit tests (ref: unit_test/test_Matrix.cc,
test_Tile.cc, test_TrapezoidMatrix.cc, test_BandMatrix.cc).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import slate_tpu as st
from slate_tpu.core import layout


GRIDS = [(1, 1), (2, 2), (2, 4), (4, 2)]


def make_grid(p, q):
    return st.Grid(p, q, devices=jax.devices()[: p * q])


@pytest.mark.parametrize("m,n,mb,nb", [(8, 8, 4, 4), (10, 7, 4, 3),
                                       (5, 13, 4, 4), (64, 64, 16, 16),
                                       (1, 1, 4, 4)])
@pytest.mark.parametrize("p,q", GRIDS)
def test_storage_roundtrip(rng, m, n, mb, nb, p, q):
    a = rng.standard_normal((m, n))
    g = make_grid(p, q)
    s = st.TileStorage.from_dense(a, mb, nb, g)
    np.testing.assert_allclose(np.asarray(s.to_dense()), a)


@pytest.mark.parametrize("p,q", [(2, 4), (1, 1)])
def test_cyclic_tile_placement(rng, p, q):
    """tile(i, j) equals dense block; owner coordinate is (i%p, j%q)
    (ref: MatrixStorage.hh:555-568)."""
    m, n, mb, nb = 20, 12, 4, 4
    a = rng.standard_normal((m, n))
    g = make_grid(p, q)
    s = st.TileStorage.from_dense(a, mb, nb, g)
    for i in range(s.Mt):
        for j in range(s.Nt):
            blk = a[i * mb:(i + 1) * mb, j * nb:(j + 1) * nb]
            got = np.asarray(s.tile(i, j))[: blk.shape[0], : blk.shape[1]]
            np.testing.assert_allclose(got, blk)
            assert g.tile_coords(i, j) == (i % p, j % q)
    # storage is sharded over all p*q devices
    if g.mesh is not None:
        assert len({sh.device for sh in s.data.addressable_shards}) == p * q


def test_padding_is_zero(rng):
    a = rng.standard_normal((10, 7))
    s = st.TileStorage.from_dense(a, 4, 4, make_grid(2, 2))
    canon = np.asarray(s.canonical())
    # last tile row has 2 valid rows, last tile col 3 valid cols
    assert np.all(canon[-1, :, 2:, :] == 0)
    assert np.all(canon[:, -1, :, 3:] == 0)


def test_views_are_zero_copy(rng):
    a = rng.standard_normal((16, 16))
    A = st.Matrix.from_numpy(a, 4)
    v = A.sub(1, 2, 0, 3)
    assert v.storage is A.storage
    assert v.m == 8 and v.n == 16
    np.testing.assert_allclose(v.to_numpy(), a[4:12, :])
    t = A.T
    assert t.storage is A.storage
    np.testing.assert_allclose(t.to_numpy(), a.T)
    tt = t.T
    assert tt.op is st.Op.NoTrans
    sub_t = A.T.sub(0, 1, 1, 2)
    np.testing.assert_allclose(sub_t.to_numpy(), a.T[0:8, 4:12])


def test_uneven_view_dims(rng):
    a = rng.standard_normal((10, 7))
    A = st.Matrix.from_numpy(a, 4, 4)
    v = A.sub(1, 2, 1, 1)          # rows 4..9 (ragged), cols 4..6
    assert v.m == 6 and v.n == 3
    np.testing.assert_allclose(v.to_numpy(), a[4:10, 4:7])
    assert v.tile_mb(1) == 2 and v.tile_nb(0) == 3


def test_with_dense_writeback(rng):
    a = rng.standard_normal((12, 12))
    A = st.Matrix.from_numpy(a, 4)
    v = A.sub(1, 2, 1, 2)
    new = v.with_dense(jnp.zeros((8, 8)))
    # view region zeroed, parent region preserved, original untouched
    full = np.asarray(new.storage.to_dense())
    expect = a.copy()
    expect[4:12, 4:12] = 0
    np.testing.assert_allclose(full, expect)
    np.testing.assert_allclose(A.to_numpy(), a)


@pytest.mark.parametrize("uplo", [st.Uplo.Lower, st.Uplo.Upper])
def test_structured_expand(rng, uplo):
    a = rng.standard_normal((9, 9))
    tri = st.TriangularMatrix.from_numpy(a, 4, uplo)
    ref = np.tril(a) if uplo is st.Uplo.Lower else np.triu(a)
    np.testing.assert_allclose(tri.to_numpy(), ref)
    uni = st.TriangularMatrix.from_numpy(a, 4, uplo, st.Diag.Unit)
    ref_u = ref.copy()
    np.fill_diagonal(ref_u, 1.0)
    np.testing.assert_allclose(uni.to_numpy(), ref_u)

    sym = st.SymmetricMatrix.from_numpy(a, 4, uplo)
    t = np.tril(a) if uplo is st.Uplo.Lower else np.triu(a)
    ref_s = t + t.T - np.diag(np.diag(a))
    np.testing.assert_allclose(sym.to_numpy(), ref_s)


def test_hermitian_expand(rng):
    a = rng.standard_normal((8, 8)) + 1j * rng.standard_normal((8, 8))
    he = st.HermitianMatrix.from_numpy(a, 4, st.Uplo.Lower)
    t = np.tril(a)
    ref = t + t.conj().T
    np.fill_diagonal(ref, np.real(np.diag(a)))
    np.testing.assert_allclose(he.to_numpy(), ref)
    # conj_transpose of hermitian equals itself
    np.testing.assert_allclose(he.H.to_numpy(), ref)


def test_band_expand(rng):
    a = rng.standard_normal((12, 12))
    bd = st.BandMatrix.from_numpy(a, 2, 3, 4)
    i, j = np.indices(a.shape)
    ref = np.where((j - i <= 3) & (i - j <= 2), a, 0.0)
    np.testing.assert_allclose(bd.to_numpy(), ref)


def test_matrix_as_pytree(rng):
    a = rng.standard_normal((8, 8))
    A = st.Matrix.from_numpy(a, 4)

    @jax.jit
    def f(M):
        return M.with_dense(M.to_dense() * 2.0)

    out = f(A)
    np.testing.assert_allclose(out.to_numpy(), 2 * a)


def test_grid_rank_order():
    g = st.Grid(2, 3, devices=jax.devices()[:6], order=st.GridOrder.Col)
    assert g.tile_rank(0, 0) == 0 and g.tile_rank(1, 0) == 1
    assert g.tile_rank(0, 1) == 2
    g2 = st.Grid(2, 3, devices=jax.devices()[:6], order=st.GridOrder.Row)
    assert g2.tile_rank(0, 1) == 1 and g2.tile_rank(1, 0) == 3
