"""LU tests: getrf/getrs/gesv across methods and targets, incl. an
adversarial row-scaled matrix that fails without pivoting (analog of ref
test/test_gesv.cc residual checks)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import slate_tpu as st


def adversarial(rng, n):
    """Row-scaled so no-pivot LU loses many digits: tiny leading pivot."""
    a = rng.standard_normal((n, n)) + n * np.eye(n)
    a[0, 0] = 1e-14
    return a


@pytest.mark.parametrize("n,nb", [(24, 8), (30, 7)])
def test_getrf_single(rng, n, nb):
    a = rng.standard_normal((n, n))
    A = st.Matrix.from_numpy(a, nb)
    F = st.getrf(A)
    l = np.tril(F.LU.to_numpy(), -1) + np.eye(n)
    u = np.triu(F.LU.to_numpy())
    perm = np.asarray(F.perm)
    np.testing.assert_allclose(l @ u, a[perm], rtol=1e-11, atol=1e-11)


def test_getrf_rectangular(rng):
    m, n, nb = 20, 12, 4
    a = rng.standard_normal((m, n))
    F = st.getrf(st.Matrix.from_numpy(a, nb))
    lu = F.LU.to_numpy()
    l = np.tril(lu, -1)[:, :n] + np.eye(m, n)
    u = np.triu(lu)[:n]
    np.testing.assert_allclose(l @ u, a[np.asarray(F.perm)],
                               rtol=1e-11, atol=1e-11)


@pytest.mark.parametrize("method", ["partial", "tntpiv"])
def test_gesv_adversarial_single(rng, method):
    n, nb = 24, 8
    a = adversarial(rng, n)
    b = rng.standard_normal((n, 3))
    A = st.Matrix.from_numpy(a, nb)
    B = st.Matrix.from_numpy(b, nb)
    opts = {st.Option.MethodLU:
            st.MethodLU.CALU if method == "tntpiv" else st.MethodLU.PartialPiv}
    F, X = st.gesv(A, B, opts)
    x = X.to_numpy()
    resid = np.linalg.norm(a @ x - b) / (np.linalg.norm(a) *
                                         np.linalg.norm(x))
    assert resid < 1e-13
    # no-pivot on the same matrix must be catastrophically worse
    Fn, Xn = st.gesv_nopiv(A, B)
    xn = Xn.to_numpy()
    residn = np.linalg.norm(a @ xn - b) / (np.linalg.norm(a) *
                                           np.linalg.norm(xn))
    assert residn > 1e-8


@pytest.mark.parametrize("p,q", [(2, 2), (2, 4)])
@pytest.mark.parametrize("n,nb", [(24, 4), (22, 5)])
@pytest.mark.slow
def test_gesv_mesh(rng, p, q, n, nb):
    g = st.Grid(p, q, devices=jax.devices()[: p * q])
    a = adversarial(rng, n)
    b = rng.standard_normal((n, 4))
    A = st.Matrix.from_numpy(a, nb, nb, g)
    B = st.Matrix.from_numpy(b, nb, nb, g)
    F, X = st.gesv(A, B)
    x = X.to_numpy()
    resid = np.linalg.norm(a @ x - b) / (np.linalg.norm(a) *
                                         np.linalg.norm(x) * n)
    assert resid < 1e-14


@pytest.mark.slow
def test_getrf_mesh_factors(rng):
    """Mesh factors reproduce A[perm] = L U exactly, pads clean."""
    n, nb, p, q = 18, 4, 2, 2
    g = st.Grid(p, q, devices=jax.devices()[: p * q])
    a = rng.standard_normal((n, n))
    F = st.getrf(st.Matrix.from_numpy(a, nb, nb, g))
    lu = F.LU.to_numpy()
    l = np.tril(lu, -1) + np.eye(n)
    u = np.triu(lu)
    np.testing.assert_allclose(l @ u, a[np.asarray(F.perm)],
                               rtol=1e-11, atol=1e-11)
    canon = np.asarray(F.LU.storage.canonical())
    assert np.all(canon[-1, :, 2:, :] == 0)      # pad rows zero
    assert np.all(canon[:, -1, :, :, ][..., 2:] == 0)


@pytest.mark.slow
def test_gesv_nopiv_mesh(rng):
    n, nb = 16, 4
    g = st.Grid(2, 2, devices=jax.devices()[:4])
    a = rng.standard_normal((n, n)) + n * np.eye(n)   # diagonally dominant
    b = rng.standard_normal((n, 2))
    F, X = st.gesv_nopiv(st.Matrix.from_numpy(a, nb, nb, g),
                         st.Matrix.from_numpy(b, nb, nb, g))
    x = X.to_numpy()
    assert np.linalg.norm(a @ x - b) / np.linalg.norm(b) < 1e-12


@pytest.mark.slow
def test_gesv_tntpiv_mesh(rng):
    n, nb = 16, 4
    g = st.Grid(2, 2, devices=jax.devices()[:4])
    a = adversarial(rng, n)
    b = rng.standard_normal((n, 2))
    opts = {st.Option.MethodLU: st.MethodLU.CALU}
    F, X = st.gesv(st.Matrix.from_numpy(a, nb, nb, g),
                   st.Matrix.from_numpy(b, nb, nb, g), opts)
    x = X.to_numpy()
    assert np.linalg.norm(a @ x - b) / np.linalg.norm(b) < 1e-11


@pytest.mark.slow
def test_mesh_getrs_mismatched_b_tiling(rng):
    """Mesh getrs fast path with B.mb != LU.nb (B pads differently):
    dist_permute_rows builds perm_pad over B's own padded row space, so
    mismatched tilings must still solve exactly (ADVICE r3 invariant)."""
    n, nb, mbB = 22, 5, 4         # LU tiles 5x5, B tiles 4-wide rows
    g = st.Grid(2, 2, devices=jax.devices()[:4])
    a = adversarial(rng, n)
    b = rng.standard_normal((n, 3))
    F = st.getrf(st.Matrix.from_numpy(a, nb, nb, g))
    B = st.Matrix.from_numpy(b, mbB, 3, g)
    x = st.getrs(F, B).to_numpy()
    resid = np.linalg.norm(a @ x - b) / (np.linalg.norm(a) *
                                         np.linalg.norm(x) * n)
    assert resid < 1e-14


def test_getri(rng):
    n = 16
    a = rng.standard_normal((n, n)) + n * np.eye(n)
    Ainv = st.getriOOP(st.Matrix.from_numpy(a, 4))
    np.testing.assert_allclose(Ainv.to_numpy() @ a, np.eye(n),
                               rtol=1e-11, atol=1e-10)


def test_gesv_under_jit(rng):
    n = 16
    a = rng.standard_normal((n, n)) + n * np.eye(n)
    b = rng.standard_normal((n, 2))
    A = st.Matrix.from_numpy(a, 4)
    B = st.Matrix.from_numpy(b, 4)

    @jax.jit
    def solve(A, B):
        _, X = st.gesv(A, B)
        return X

    x = solve(A, B).to_numpy()
    assert np.linalg.norm(a @ x - b) / np.linalg.norm(b) < 1e-12
