"""Mesh tests for the triangle-aware rank-k/rank-2k kernels, the packed
triangle trmm, and stationary-A gemmA (ref: internal_herk.cc,
internal_her2k.cc, internal_trmm.cc, gemmA.cc)."""

import jax
import numpy as np
import pytest

import slate_tpu as st


def _grid(p, q):
    return st.Grid(p, q, devices=jax.devices()[: p * q])


@pytest.mark.parametrize("p,q", [
    (2, 2), pytest.param(2, 4, marks=pytest.mark.slow)])
@pytest.mark.parametrize("uplo", [st.Uplo.Lower, st.Uplo.Upper])
@pytest.mark.parametrize("n,k,nb", [
    (24, 16, 4), pytest.param(22, 13, 5, marks=pytest.mark.slow)])
def test_herk_mesh(rng, p, q, uplo, n, k, nb):
    g = _grid(p, q)
    a = rng.standard_normal((n, k))
    c = rng.standard_normal((n, n))
    c = (c + c.T) / 2
    A = st.Matrix.from_numpy(a, nb, nb, g)
    C = st.HermitianMatrix.from_numpy(c, nb, uplo, g)
    out = st.herk(1.0, A, 0.5, C)
    np.testing.assert_allclose(out.to_numpy(), a @ a.T + 0.5 * c,
                               rtol=1e-11, atol=1e-11)


def test_herk_mesh_complex(rng):
    g = _grid(2, 2)
    n, k, nb = 16, 12, 4
    a = rng.standard_normal((n, k)) + 1j * rng.standard_normal((n, k))
    h = rng.standard_normal((n, n)) + 1j * rng.standard_normal((n, n))
    h = (h + h.conj().T) / 2
    A = st.Matrix.from_numpy(a, nb, nb, g)
    C = st.HermitianMatrix.from_numpy(h, nb, st.Uplo.Lower, g)
    out = st.herk(1.0, A, 1.0, C)
    np.testing.assert_allclose(out.to_numpy(), a @ a.conj().T + h,
                               rtol=1e-11, atol=1e-11)


@pytest.mark.parametrize("uplo", [st.Uplo.Lower, st.Uplo.Upper])
def test_her2k_syr2k_mesh(rng, uplo):
    g = _grid(2, 2)
    n, k, nb = 20, 12, 4
    a = rng.standard_normal((n, k))
    b = rng.standard_normal((n, k))
    c = rng.standard_normal((n, n))
    c = (c + c.T) / 2
    A = st.Matrix.from_numpy(a, nb, nb, g)
    B = st.Matrix.from_numpy(b, nb, nb, g)
    C = st.HermitianMatrix.from_numpy(c, nb, uplo, g)
    out = st.her2k(1.0, A, B, 1.0, C)
    np.testing.assert_allclose(out.to_numpy(), a @ b.T + b @ a.T + c,
                               rtol=1e-11, atol=1e-11)
    Cs = st.SymmetricMatrix.from_numpy(c, nb, uplo, g)
    out2 = st.syr2k(2.0, A, B, 0.0, Cs)
    np.testing.assert_allclose(out2.to_numpy(), 2 * (a @ b.T + b @ a.T),
                               rtol=1e-11, atol=1e-11)


def test_herk_leaves_other_triangle_untouched(rng):
    """The packed kernel must only write the stored triangle's tiles."""
    g = _grid(2, 2)
    n, k, nb = 16, 8, 4
    a = rng.standard_normal((n, k))
    c = rng.standard_normal((n, n))
    A = st.Matrix.from_numpy(a, nb, nb, g)
    C = st.HermitianMatrix.from_numpy(c, nb, st.Uplo.Lower, g)
    out = st.herk(1.0, A, 0.0, C)
    dense_store = np.asarray(out.storage.to_dense())   # raw tiles, no expand
    # strictly-upper TILES (full tiles above the diagonal) kept old junk =
    # original c values there (beta doesn't touch them)
    for it in range(n // nb):
        for jt in range(n // nb):
            if jt > it:
                blk = np.s_[it * nb:(it + 1) * nb, jt * nb:(jt + 1) * nb]
                np.testing.assert_array_equal(dense_store[blk], c[blk])


@pytest.mark.parametrize("side", ["l", "r"])
@pytest.mark.parametrize("uplo", [st.Uplo.Lower, st.Uplo.Upper])
@pytest.mark.parametrize("diag", [st.Diag.NonUnit, st.Diag.Unit])
def test_trmm_mesh(rng, side, uplo, diag):
    g = _grid(2, 2)
    n, m, nb = 20, 12, 4
    a = rng.standard_normal((n, n))
    A = st.TriangularMatrix.from_numpy(a, nb, uplo, diag, g)
    tri = np.tril(a) if uplo is st.Uplo.Lower else np.triu(a)
    if diag is st.Diag.Unit:
        tri = tri - np.diag(np.diag(tri)) + np.eye(n)
    if side == "l":
        b = rng.standard_normal((n, m))
        ref = 2.0 * tri @ b
    else:
        b = rng.standard_normal((m, n))
        ref = 2.0 * b @ tri
    B = st.Matrix.from_numpy(b, nb, nb, g)
    out = st.trmm(side, 2.0, A, B, {st.Option.Target: st.Target.mesh})
    np.testing.assert_allclose(out.to_numpy(), ref, rtol=1e-11, atol=1e-11)


def test_trmm_mesh_ragged(rng):
    g = _grid(2, 2)
    n, m, nb = 18, 10, 4                    # ragged last tiles
    a = rng.standard_normal((n, n))
    b = rng.standard_normal((n, m))
    A = st.TriangularMatrix.from_numpy(a, nb, st.Uplo.Lower,
                                       st.Diag.NonUnit, g)
    B = st.Matrix.from_numpy(b, nb, nb, g)
    out = st.trmm("l", 1.0, A, B, {st.Option.Target: st.Target.mesh})
    np.testing.assert_allclose(out.to_numpy(), np.tril(a) @ b,
                               rtol=1e-11, atol=1e-11)


@pytest.mark.parametrize("p,q", [
    (2, 2), pytest.param(2, 4, marks=pytest.mark.slow)])
def test_gemmA_mesh(rng, p, q):
    g = _grid(p, q)
    m, k, nb = 32, 24, 4
    a = rng.standard_normal((m, k))
    b = rng.standard_normal((k, nb))        # single block column: gemmA turf
    A = st.Matrix.from_numpy(a, nb, nb, g)
    B = st.Matrix.from_numpy(b, nb, nb, g)
    out = st.gemmA(1.0, A, B)
    np.testing.assert_allclose(out.to_numpy(), a @ b, rtol=1e-11, atol=1e-11)
    # auto-selection picks gemmA for nt < 2 (method.hh:87-98): same result
    out2 = st.gemm(1.0, A, B)
    np.testing.assert_allclose(out2.to_numpy(), a @ b, rtol=1e-11,
                               atol=1e-11)


def test_gemmA_mesh_wide_and_beta(rng):
    g = _grid(2, 2)
    m, k, n, nb = 16, 24, 12, 4
    a = rng.standard_normal((m, k))
    b = rng.standard_normal((k, n))
    c = rng.standard_normal((m, n))
    A = st.Matrix.from_numpy(a, nb, nb, g)
    B = st.Matrix.from_numpy(b, nb, nb, g)
    C = st.Matrix.from_numpy(c, nb, nb, g)
    out = st.gemmA(0.5, A, B, 2.0, C)
    np.testing.assert_allclose(out.to_numpy(), 0.5 * a @ b + 2.0 * c,
                               rtol=1e-11, atol=1e-11)


def test_hemmA_mesh(rng):
    g = _grid(2, 2)
    n, nb = 16, 4
    a = rng.standard_normal((n, n))
    b = rng.standard_normal((n, nb))
    H = st.HermitianMatrix.from_numpy(a, nb, grid=g)
    B = st.Matrix.from_numpy(b, nb, nb, g)
    hd = np.tril(a) + np.tril(a, -1).T
    out = st.hemmA("l", 1.0, H, B)
    np.testing.assert_allclose(out.to_numpy(), hd @ b, rtol=1e-11,
                               atol=1e-11)
