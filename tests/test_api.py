"""Simplified-API tests: verb names dispatch to the right driver per
structure (analog of ref include/slate/simplified_api.hh overload set)."""

import jax
import numpy as np
import pytest

import slate_tpu as st
from slate_tpu import api


def test_multiply_dispatch(rng):
    n, nb = 16, 4
    a = rng.standard_normal((n, n))
    b = rng.standard_normal((n, n))
    A = st.Matrix.from_numpy(a, nb)
    B = st.Matrix.from_numpy(b, nb)
    np.testing.assert_allclose(api.multiply(1.0, A, B).to_numpy(), a @ b,
                               atol=1e-12)
    # Hermitian A -> hemm (expanded triangle)
    H = st.HermitianMatrix.from_numpy(a, nb)
    hd = np.tril(a) + np.tril(a, -1).T
    np.testing.assert_allclose(api.multiply(1.0, H, B).to_numpy(), hd @ b,
                               atol=1e-12)
    # Hermitian B -> right-side hemm
    np.testing.assert_allclose(api.multiply(1.0, B, H).to_numpy(), b @ hd,
                               atol=1e-12)
    # band A -> gbmm
    kl = ku = 2
    band = np.triu(np.tril(a, kl), -ku).T * 0 + np.triu(np.tril(a, kl), -ku)
    Ab = st.BandMatrix.from_numpy(band, kl, ku, nb)
    np.testing.assert_allclose(api.multiply(1.0, Ab, B).to_numpy(),
                               band @ b, atol=1e-12)


def test_triangular_verbs(rng):
    n, nb = 16, 4
    a = rng.standard_normal((n, n)) + n * np.eye(n)
    b = rng.standard_normal((n, 2))
    L = st.TriangularMatrix.from_numpy(a, nb, uplo=st.Uplo.Lower)
    B = st.Matrix.from_numpy(b, nb)
    ld = np.tril(a)
    X = api.triangular_solve(1.0, L, B)
    np.testing.assert_allclose(ld @ X.to_numpy(), b, atol=1e-10)
    Y = api.triangular_multiply(1.0, L, B)
    np.testing.assert_allclose(Y.to_numpy(), ld @ b, atol=1e-12)
    # triangular operand second -> right side
    C = st.Matrix.from_numpy(b.T, nb)
    Z = api.triangular_multiply(1.0, C, L)
    np.testing.assert_allclose(Z.to_numpy(), b.T @ ld, atol=1e-12)


def test_rank_k_updates(rng):
    n, nb = 16, 4
    a = rng.standard_normal((n, n))
    b = rng.standard_normal((n, n))
    A = st.Matrix.from_numpy(a, nb)
    B = st.Matrix.from_numpy(b, nb)
    C = st.HermitianMatrix.from_numpy(np.zeros((n, n)), nb)
    np.testing.assert_allclose(
        api.rank_k_update(1.0, A, 0.0, C).to_numpy(), a @ a.T, atol=1e-12)
    np.testing.assert_allclose(
        api.rank_2k_update(1.0, A, B, 0.0, C).to_numpy(),
        a @ b.T + b @ a.T, atol=1e-12)


def test_solve_verbs(rng):
    n, nb = 16, 4
    a = rng.standard_normal((n, n)) + n * np.eye(n)
    spd = a @ a.T + n * np.eye(n)
    sym = (a + a.T) / 2
    b = rng.standard_normal((n, 2))
    B = st.Matrix.from_numpy(b, nb)

    x = api.lu_solve(st.Matrix.from_numpy(a, nb), B).to_numpy()
    np.testing.assert_allclose(a @ x, b, atol=1e-9)

    x = api.chol_solve(st.HermitianMatrix.from_numpy(spd, nb), B).to_numpy()
    np.testing.assert_allclose(spd @ x, b, atol=1e-8)

    x = api.indefinite_solve(st.HermitianMatrix.from_numpy(sym, nb),
                             B).to_numpy()
    np.testing.assert_allclose(sym @ x, b, atol=1e-8)

    m = 32
    atall = rng.standard_normal((m, n))
    btall = rng.standard_normal((m, 2))
    x = api.least_squares_solve(st.Matrix.from_numpy(atall, nb),
                                st.Matrix.from_numpy(btall, nb)).to_numpy()
    x_ref = np.linalg.lstsq(atall, btall, rcond=None)[0]
    np.testing.assert_allclose(x[:n], x_ref, atol=1e-9)


def test_eig_svd_verbs(rng):
    n, nb = 16, 4
    a = rng.standard_normal((n, n))
    sym = (a + a.T) / 2
    H = st.HermitianMatrix.from_numpy(sym, nb)
    lam = np.asarray(api.eig_vals(H))
    np.testing.assert_allclose(lam, np.linalg.eigvalsh(sym), atol=1e-10)
    s = np.asarray(api.svd_vals(st.Matrix.from_numpy(a, nb)))
    np.testing.assert_allclose(s, np.linalg.svd(a, compute_uv=False),
                               atol=1e-10)


def test_lapack_shims(rng):
    from slate_tpu.compat import lapack
    n = 12
    a = rng.standard_normal((n, n)) + n * np.eye(n)
    b = rng.standard_normal((n, 2))
    x, perm = lapack.gesv(a, b)
    np.testing.assert_allclose(a @ x, b, atol=1e-9)
    spd = a @ a.T
    np.testing.assert_allclose(spd @ lapack.posv(spd, b), b, atol=1e-8)
    u, s, vh = lapack.gesvd(a)
    np.testing.assert_allclose(u[:, :n] * s @ vh[:n], a, atol=1e-9)
    rc = lapack.gecon(a)
    assert 0 < rc <= 1


def test_lapack_shims_blas3(rng):
    # the BLAS-3 tier of the LAPACK compat shims vs numpy
    from slate_tpu.compat import lapack as lp
    m, k, n = 12, 9, 10
    a = rng.standard_normal((m, k))
    b = rng.standard_normal((k, n))
    c = rng.standard_normal((m, n))
    np.testing.assert_allclose(lp.gemm("n", "n", 2.0, a, b, 0.5, c),
                               2 * a @ b + 0.5 * c, atol=1e-12)
    np.testing.assert_allclose(lp.gemm("t", "n", 1.0, a.T.copy(), b),
                               a @ b, atol=1e-12)
    h = rng.standard_normal((m, m))
    h = (h + h.T) / 2
    np.testing.assert_allclose(lp.hemm("l", "l", 1.0, h, a),
                               h @ a, atol=1e-12)
    np.testing.assert_allclose(lp.syrk("l", 1.0, a), a @ a.T, atol=1e-12)
    bb = rng.standard_normal((m, k))
    np.testing.assert_allclose(lp.syr2k("u", 1.0, a, bb),
                               a @ bb.T + bb @ a.T, atol=1e-12)
    t = np.tril(rng.standard_normal((m, m))) + m * np.eye(m)
    x = lp.trsm("l", "l", "n", "n", 1.0, t, c)
    np.testing.assert_allclose(t @ x, c, atol=1e-10)
    np.testing.assert_allclose(lp.trmm("l", "l", "t", "n", 1.0, t, c),
                               t.T @ c, atol=1e-12)


def test_lapack_shims_norms_and_factors(rng):
    from slate_tpu.compat import lapack as lp
    n = 12
    a = rng.standard_normal((n, n)) + n * np.eye(n)
    np.testing.assert_allclose(lp.lange("1", a),
                               np.abs(a).sum(axis=0).max(), atol=1e-12)
    np.testing.assert_allclose(lp.lange("f", a),
                               np.linalg.norm(a), atol=1e-12)
    h = (a + a.T) / 2
    np.testing.assert_allclose(lp.lanhe("i", "l", h),
                               np.abs(h).sum(axis=1).max(), atol=1e-12)
    t = np.tril(a)
    np.testing.assert_allclose(lp.lantr("m", "l", "n", t),
                               np.abs(t).max(), atol=1e-12)
    # getrs (incl. transpose) / getri from getrf factors
    lu, perm = lp.getrf(a)
    b = rng.standard_normal((n, 3))
    np.testing.assert_allclose(a @ lp.getrs(lu, perm, b), b, atol=1e-9)
    np.testing.assert_allclose(a.T @ lp.getrs(lu, perm, b, trans="t"),
                               b, atol=1e-9)
    np.testing.assert_allclose(a @ lp.getri(lu, perm), np.eye(n),
                               atol=1e-9)
    # potri from the Cholesky factor
    s = a @ a.T + n * np.eye(n)
    L = lp.potrf(s)
    np.testing.assert_allclose(s @ lp.potri(L), np.eye(n), atol=1e-8)
    # mixed-precision refinement solve
    x, its = lp.gesv_mixed(s, b)
    np.testing.assert_allclose(s @ x, b, atol=1e-8)
    assert its >= 1
