"""Pallas TPU kernel tests, run in interpret mode on the CPU backend
(the real-TPU lowering is exercised by bench.py / the driver rounds)."""

import numpy as np
import jax.numpy as jnp
import pytest

from slate_tpu.internal.pallas_chol import chol_tile_pallas


@pytest.mark.parametrize("n,bw", [(128, 128), (512, 128), (256, 8)])
def test_pallas_chol_interpret(rng, n, bw):
    a0 = rng.standard_normal((n, n)).astype(np.float32) * 0.01
    a = a0 @ a0.T + 4 * np.eye(n, dtype=np.float32)
    L = np.asarray(chol_tile_pallas(jnp.asarray(a), bw=bw, interpret=True))
    np.testing.assert_allclose(L, np.linalg.cholesky(a), atol=5e-6)
    assert np.all(np.triu(L, 1) == 0)      # exact-zero upper contract


@pytest.mark.parametrize("W,nb", [(256, 32), (1024, 128)])
def test_pallas_lu_select_interpret(rng, W, nb):
    # pivot order must match the XLA LU oracle exactly
    from jax import lax
    from slate_tpu.internal.pallas_lu import lu_select_pallas
    a = jnp.asarray(rng.standard_normal((W, nb)).astype(np.float32))
    piv = np.asarray(lu_select_pallas(a, interpret=True))
    ref = np.asarray(lax.linalg.lu(a)[2])[:nb]
    np.testing.assert_array_equal(piv, ref)


def test_pallas_lu_select_ragged_interpret(rng):
    from jax import lax
    from slate_tpu.internal.pallas_lu import lu_select_pallas
    a = jnp.asarray(rng.standard_normal((160, 32)).astype(np.float32))
    ap = jnp.zeros((256, 32), jnp.float32).at[:160].set(a)
    piv = np.asarray(lu_select_pallas(ap, nrows=160, interpret=True))
    ref = np.asarray(lax.linalg.lu(a)[2])[:32]
    np.testing.assert_array_equal(piv, ref)


# ---- fused panel kernels (PR 7) ------------------------------------------


def _spd_panel(rng, m, nb, k):
    """(col, left, lead) such that col - left @ lead has an SPD top block;
    returns the expected fused outputs from a NumPy oracle too."""
    base = rng.standard_normal((m, nb)).astype(np.float32)
    top = base[:nb] @ base[:nb].T / nb + nb * np.eye(nb, dtype=np.float32)
    target = np.concatenate([top, base[nb:]], axis=0)
    left = rng.standard_normal((m, k)).astype(np.float32) * 0.01
    lead = left[:nb].T.copy()
    col = target + left @ lead
    lkk = np.linalg.cholesky(target[:nb])
    l21 = target[nb:] @ np.linalg.inv(lkk).T
    fac = np.concatenate([lkk, l21], axis=0)
    return col, left, lead, target, fac


@pytest.mark.parametrize("nb,bw", [(128, 8), (128, 16), (256, 8), (256, 16)])
def test_chol_panel_fused_interpret(rng, nb, bw):
    from slate_tpu.internal.pallas_chol import chol_panel_fused
    m, k = 3 * nb, nb
    col, left, lead, target, fac_ref = _spd_panel(rng, m, nb, k)
    upd, fac = chol_panel_fused(jnp.asarray(col), jnp.asarray(left),
                                jnp.asarray(lead), bw=bw, interpret=True)
    np.testing.assert_allclose(np.asarray(upd), target, atol=1e-4)
    np.testing.assert_allclose(np.asarray(fac), fac_ref,
                               rtol=2e-5, atol=1e-4)


def test_chol_panel_fused_empty_history_interpret(rng):
    """k=0 (first panel): no history, fused output is just the factor."""
    from slate_tpu.internal.pallas_chol import chol_panel_fused
    nb, m = 128, 256
    col, _, _, target, fac_ref = _spd_panel(rng, m, nb, nb)
    left = jnp.zeros((m, 0), jnp.float32)
    lead = jnp.zeros((0, nb), jnp.float32)
    upd, fac = chol_panel_fused(jnp.asarray(target), left, lead,
                                bw=8, interpret=True)
    np.testing.assert_allclose(np.asarray(upd), target, atol=1e-4)
    np.testing.assert_allclose(np.asarray(fac), fac_ref,
                               rtol=2e-5, atol=1e-4)


@pytest.mark.parametrize("nb,bw", [(128, 8), (128, 16), (256, 16)])
def test_lu_panel_fused_interpret(rng, nb, bw):
    """Fused no-pivot LU panel matches the XLA panel_lu_nopiv packing."""
    from slate_tpu.internal.getrf import panel_lu_nopiv
    from slate_tpu.internal.pallas_lu import lu_panel_fused
    w = 3 * nb
    a = rng.standard_normal((w, nb)).astype(np.float32)
    a[:nb] += nb * np.eye(nb, dtype=np.float32)       # diagonally dominant
    got = np.asarray(lu_panel_fused(jnp.asarray(a), bw=bw, interpret=True))
    from slate_tpu.tune import XLA_PLAN, plan_override
    with plan_override("getrf_panel", XLA_PLAN):
        ref, perm = panel_lu_nopiv(jnp.asarray(a))
    np.testing.assert_array_equal(np.asarray(perm), np.arange(w))
    np.testing.assert_allclose(got, np.asarray(ref), rtol=2e-5, atol=1e-4)
    # and L\\U actually reconstructs A
    L = np.tril(got[:nb], -1) + np.eye(nb, dtype=np.float32)
    L = np.concatenate([L, got[nb:]], axis=0)
    U = np.triu(got[:nb])
    np.testing.assert_allclose(L @ U, a, rtol=2e-4, atol=5e-4)


@pytest.mark.parametrize("m,w", [(256, 128), (512, 128), (512, 256)])
def test_qr_panel_pallas_interpret(rng, m, w):
    """Pallas Householder panel is bit-compatible with householder_panel
    and its compact-WY T reconstructs Q."""
    from slate_tpu.internal.qr import build_t, householder_panel, unit_lower
    from slate_tpu.internal.pallas_qr import qr_panel_pallas
    a = jnp.asarray(rng.standard_normal((m, w)).astype(np.float32))
    packed, T = qr_panel_pallas(a, interpret=True)
    ref_packed, taus = householder_panel(a)
    np.testing.assert_allclose(np.asarray(packed), np.asarray(ref_packed),
                               rtol=1e-5, atol=1e-5)
    ref_T = build_t(ref_packed, taus)
    np.testing.assert_allclose(np.asarray(T), np.asarray(ref_T),
                               rtol=1e-4, atol=1e-5)
    # Q R == A through the compact-WY form
    V = np.asarray(unit_lower(packed))
    R = np.triu(np.asarray(packed)[:w])
    Q = np.eye(m, dtype=np.float32) - V @ np.asarray(T) @ V.T
    np.testing.assert_allclose(Q @ np.concatenate(
        [R, np.zeros((m - w, w), np.float32)]), np.asarray(a),
        rtol=1e-4, atol=1e-4)


# ---- fused path through the drivers (plan_override) ----------------------


def _pallas_plan(nb, bw=8):
    from slate_tpu.tune import TilePlan
    return TilePlan(kernel="pallas", nb=nb, bw=bw)


@pytest.mark.parametrize("n,nb", [(384, 128), (448, 128), (640, 256)])
def test_driver_chol_fused_parity(rng, n, nb):
    """_potrf_dense_blocked through the fused panel (incl. ragged trailing
    edges) matches jnp.linalg.cholesky."""
    from slate_tpu.drivers.cholesky import _potrf_dense_blocked
    from slate_tpu.tune import plan_override
    a0 = rng.standard_normal((n, n)).astype(np.float32) * 0.1
    a = jnp.asarray(a0 @ a0.T + n * np.eye(n, dtype=np.float32))
    with plan_override("potrf_panel", _pallas_plan(nb)):
        L, _ = _potrf_dense_blocked(a, nb)
    ref = np.asarray(jnp.linalg.cholesky(a))
    np.testing.assert_allclose(np.tril(np.asarray(L)), ref,
                               rtol=2e-4, atol=2e-3)


def test_driver_chol_fused_abft_single_strike(rng):
    """ABFT repairs a single injected fault THROUGH the fused panel step:
    the factor matches the clean run and no residual corruption leaks."""
    from slate_tpu.drivers.cholesky import _potrf_dense_blocked
    from slate_tpu.robust import faults
    from slate_tpu.tune import plan_override
    n, nb = 384, 128
    a0 = rng.standard_normal((n, n)).astype(np.float32) * 0.1
    a = jnp.asarray(a0 @ a0.T + n * np.eye(n, dtype=np.float32))
    # seed chosen to land the strike in the tile's LOWER triangle: on the
    # exact-zero upper half a multiplicative bitflip is a no-op
    plan = faults.FaultPlan("post_panel", kind="bitflip", seed=2,
                            transient=True)
    with plan_override("potrf_panel", _pallas_plan(nb)):
        clean, _ = _potrf_dense_blocked(a, nb, abft=True)
        with faults.inject(plan):
            hit, counts = _potrf_dense_blocked(a, nb, abft=True)
    assert int(counts.detected) == 1 and int(counts.corrected) == 1
    np.testing.assert_allclose(np.tril(np.asarray(hit)),
                               np.tril(np.asarray(clean)),
                               rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("n,nb", [(384, 128), (512, 256)])
def test_driver_lu_nopiv_fused_parity(rng, n, nb):
    """panel_lu_nopiv through the fused kernel matches its XLA path."""
    from slate_tpu.internal.getrf import panel_lu_nopiv
    from slate_tpu.tune import plan_override
    a = rng.standard_normal((n, nb)).astype(np.float32)
    a[:nb] += nb * np.eye(nb, dtype=np.float32)
    from slate_tpu.tune import XLA_PLAN
    with plan_override("getrf_panel", XLA_PLAN):
        ref, ref_perm = panel_lu_nopiv(jnp.asarray(a))
    with plan_override("getrf_panel", _pallas_plan(nb)):
        got, perm = panel_lu_nopiv(jnp.asarray(a))
    np.testing.assert_array_equal(np.asarray(perm), np.asarray(ref_perm))
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-4, atol=5e-4)


def test_driver_qr_fused_parity(rng):
    """geqrf through the tuned Pallas panel matches the XLA R (up to
    column signs) and reconstructs A."""
    from slate_tpu.drivers.qr import _geqrf_dense_blocked
    from slate_tpu.tune import XLA_PLAN, plan_override
    m, n, nb = 384, 128, 128
    a = jnp.asarray(rng.standard_normal((m, n)).astype(np.float32))
    with plan_override("geqrf_panel", XLA_PLAN):
        ref = _geqrf_dense_blocked(a, nb)
    with plan_override("geqrf_panel", _pallas_plan(nb)):
        got = _geqrf_dense_blocked(a, nb)
    np.testing.assert_allclose(np.abs(np.triu(np.asarray(got[0])[:n])),
                               np.abs(np.triu(np.asarray(ref[0])[:n])),
                               rtol=1e-4, atol=1e-4)
