"""Pallas TPU kernel tests, run in interpret mode on the CPU backend
(the real-TPU lowering is exercised by bench.py / the driver rounds)."""

import numpy as np
import jax.numpy as jnp
import pytest

from slate_tpu.internal.pallas_chol import chol_tile_pallas


@pytest.mark.parametrize("n,bw", [(128, 128), (512, 128), (256, 8)])
def test_pallas_chol_interpret(rng, n, bw):
    a0 = rng.standard_normal((n, n)).astype(np.float32) * 0.01
    a = a0 @ a0.T + 4 * np.eye(n, dtype=np.float32)
    L = np.asarray(chol_tile_pallas(jnp.asarray(a), bw=bw, interpret=True))
    np.testing.assert_allclose(L, np.linalg.cholesky(a), atol=5e-6)
    assert np.all(np.triu(L, 1) == 0)      # exact-zero upper contract


@pytest.mark.parametrize("W,nb", [(256, 32), (1024, 128)])
def test_pallas_lu_select_interpret(rng, W, nb):
    # pivot order must match the XLA LU oracle exactly
    from jax import lax
    from slate_tpu.internal.pallas_lu import lu_select_pallas
    a = jnp.asarray(rng.standard_normal((W, nb)).astype(np.float32))
    piv = np.asarray(lu_select_pallas(a, interpret=True))
    ref = np.asarray(lax.linalg.lu(a)[2])[:nb]
    np.testing.assert_array_equal(piv, ref)


def test_pallas_lu_select_ragged_interpret(rng):
    from jax import lax
    from slate_tpu.internal.pallas_lu import lu_select_pallas
    a = jnp.asarray(rng.standard_normal((160, 32)).astype(np.float32))
    ap = jnp.zeros((256, 32), jnp.float32).at[:160].set(a)
    piv = np.asarray(lu_select_pallas(ap, nrows=160, interpret=True))
    ref = np.asarray(lax.linalg.lu(a)[2])[:32]
    np.testing.assert_array_equal(piv, ref)
