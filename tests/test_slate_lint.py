"""slate-lint test suite (tools/slate_lint/).

Every rule has at least one *bad* fixture that demonstrably fires and one
*good* fixture that stays silent, plus: reachability/taint unit coverage,
suppression + baseline + CLI mechanics, legacy seam-report text fidelity,
and the tier-1 repo-wide clean run.

Fixtures are synthesized mini-repos under tmp_path — never the live tree
— so they are free to violate every contract on purpose.
"""

import json
import pathlib
import sys
import textwrap

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from tools.slate_lint import cli, load_project, reachability  # noqa: E402
from tools.slate_lint.model import REGISTRY, parse_suppressions  # noqa: E402
from tools.slate_lint.rules import seams  # noqa: E402

cli.load_rules()

SEAM_IDS = {r for r in REGISTRY if r.startswith("SEAM")}

# --------------------------------------------------------------------------
# helpers


def mini_repo(tmp_path, files):
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return tmp_path


def lint(root, select):
    project = load_project(root)
    return cli.run_rules(project, select=set(select))


def rule_ids(findings):
    return {f.rule for f in findings}


GRID = """\
    AXIS_P = "p"
    AXIS_Q = "q"
    """

# --------------------------------------------------------------------------
# trace-safety pack (TRC001-TRC006)


def _jit_mod(body):
    return ("import jax\nimport jax.numpy as jnp\n"
            "from jax import lax\nimport numpy as np\n\n\n"
            "@jax.jit\ndef entry(x):\n" + textwrap.indent(
                textwrap.dedent(body), "    "))


def test_trc001_fires_on_traced_branch(tmp_path):
    root = mini_repo(tmp_path, {"slate_tpu/mod.py": _jit_mod("""\
        y = jnp.sum(x)
        if y > 0:
            return y
        return -y
        """)})
    fs = lint(root, {"TRC001"})
    assert [f.line for f in fs] == [10]


def test_trc001_silent_on_static_branch(tmp_path):
    root = mini_repo(tmp_path, {"slate_tpu/mod.py": _jit_mod("""\
        if x.ndim > 2:              # .ndim is static under tracing
            return jnp.sum(x)
        if x.shape[0] == 4:         # so is .shape
            return x
        if x is None:               # identity never concretizes
            return x
        return x
        """)})
    assert lint(root, {"TRC001"}) == []


def test_trc002_fires_on_traced_loop(tmp_path):
    root = mini_repo(tmp_path, {"slate_tpu/mod.py": _jit_mod("""\
        while jnp.sum(x) > 0:
            x = x - 1
        for v in jnp.abs(x):
            x = x + v
        return x
        """)})
    fs = lint(root, {"TRC002"})
    assert [f.line for f in fs] == [9, 11]


def test_trc002_silent_on_static_loop(tmp_path):
    root = mini_repo(tmp_path, {"slate_tpu/mod.py": _jit_mod("""\
        for i in range(x.shape[0]):
            x = x + i
        while getattr(x, "ndim", 0) > 3:   # static-result builtin
            x = jnp.sum(x, axis=0)
        return x
        """)})
    assert lint(root, {"TRC002"}) == []


def test_trc003_fires_on_traced_assert(tmp_path):
    root = mini_repo(tmp_path, {"slate_tpu/mod.py": _jit_mod("""\
        assert jnp.all(x > 0)
        return x
        """)})
    assert rule_ids(lint(root, {"TRC003"})) == {"TRC003"}


def test_trc003_silent_on_static_assert(tmp_path):
    root = mini_repo(tmp_path, {"slate_tpu/mod.py": _jit_mod("""\
        assert x.ndim == 2
        return x
        """)})
    assert lint(root, {"TRC003"}) == []


def test_trc004_fires_on_concretization(tmp_path):
    root = mini_repo(tmp_path, {"slate_tpu/mod.py": _jit_mod("""\
        a = float(jnp.sum(x))
        b = x.item()
        return a + b
        """)})
    assert len(lint(root, {"TRC004"})) == 2


def test_trc004_silent_on_static_concretization(tmp_path):
    root = mini_repo(tmp_path, {"slate_tpu/mod.py": _jit_mod("""\
        n = int(x.shape[0])
        return x * float(n)
        """)})
    assert lint(root, {"TRC004"}) == []


def test_trc005_fires_on_numpy_on_traced(tmp_path):
    root = mini_repo(tmp_path, {"slate_tpu/mod.py": _jit_mod("""\
        return np.linalg.norm(jnp.sum(x))
        """)})
    assert rule_ids(lint(root, {"TRC005"})) == {"TRC005"}


def test_trc005_silent_on_numpy_on_static(tmp_path):
    root = mini_repo(tmp_path, {"slate_tpu/mod.py": _jit_mod("""\
        idx = np.arange(x.shape[0])    # static shape math is fine
        return x * jnp.asarray(idx)
        """)})
    assert lint(root, {"TRC005"}) == []


def test_trc006_fires_on_raise_in_traced(tmp_path):
    root = mini_repo(tmp_path, {"slate_tpu/mod.py": _jit_mod("""\
        raise ValueError("boom")
        """)})
    assert rule_ids(lint(root, {"TRC006"})) == {"TRC006"}


def test_trc006_silent_outside_traced_set_and_at_boundaries(tmp_path):
    root = mini_repo(tmp_path, {
        # eager helper: never traced, free to raise
        "slate_tpu/mod.py": "def helper(x):\n    raise ValueError(x)\n",
        # registered eager boundary module: raises allowed
        "slate_tpu/robust/health.py": (
            "import jax\n\n\n@jax.jit\ndef finalize(x):\n"
            "    raise ValueError(x)\n"),
    })
    assert lint(root, {"TRC006"}) == []


def test_traced_set_follows_fori_loop_body(tmp_path):
    """Transitive tracing: a fori_loop body referenced (not called) from a
    jit entry is traced, and its closure inherits the entry's taint."""
    root = mini_repo(tmp_path, {"slate_tpu/mod.py": _jit_mod("""\
        def body(i, c):
            if c > 0:           # c is the traced carry
                return c
            return c + 1
        return lax.fori_loop(0, 3, body, jnp.sum(x))
        """)})
    fs = lint(root, {"TRC001"})
    assert [f.line for f in fs] == [10]


def test_shard_map_lambda_closure_args_stay_static(tmp_path):
    """The repo's shard_map idiom: statics are closure-bound through a
    lambda (``lambda a: body(a, Nt=Nt)``); only lambda params are traced."""
    root = mini_repo(tmp_path, {"slate_tpu/mod.py": """\
        import jax
        import jax.numpy as jnp


        def _local(a, *, Nt, method):
            if Nt > 2:                   # static: closure-bound int
                a = a * 2
            if method == "fast":         # static: closure-bound str
                a = a + 1
            if jnp.sum(a) > 0:           # traced: fed from lambda param
                a = -a
            return a


        def driver(a_data, Nt, method):
            fn = jax.shard_map(lambda a: _local(a, Nt=Nt, method=method),
                               mesh=None, in_specs=(), out_specs=())
            return fn(a_data)
        """})
    fs = lint(root, {"TRC001"})
    assert [f.line for f in fs] == [10]


def test_vmap_is_a_traced_entry(tmp_path):
    """``jax.vmap(f)`` runs f under a batching trace: everything f
    reaches is traced exactly as under jit, so a host branch on its
    argument fires TRC001 (the serving layer enters drivers this way)."""
    root = mini_repo(tmp_path, {"slate_tpu/mod.py": """\
        import jax
        import jax.numpy as jnp


        def solve_one(a, b):
            if jnp.sum(a) > 0:           # traced under the batching trace
                return a + b
            return a - b


        def batched(a, b):
            return jax.vmap(solve_one)(a, b)
        """})
    fs = lint(root, {"TRC001"})
    assert [f.line for f in fs] == [6]


def test_vmap_lambda_closure_args_stay_static(tmp_path):
    """The serve/batched.py idiom — ``jax.vmap(lambda a, b: core(a, b,
    opts))`` — traces only the lambda's params; the closure-bound opts
    stays a static config the core may branch on."""
    root = mini_repo(tmp_path, {"slate_tpu/mod.py": """\
        import jax
        import jax.numpy as jnp


        def core(a, b, opts):
            if opts.get("fast"):         # static: closure-bound dict
                a = a * 2
            if jnp.sum(b) > 0:           # traced: fed from lambda param
                a = -a
            return a


        def make_batched(opts):
            return jax.vmap(lambda a, b: core(a, b, opts))
        """})
    fs = lint(root, {"TRC001"})
    assert [f.line for f in fs] == [8]


def test_defaulted_params_of_loop_bodies_stay_static(tmp_path):
    """``def step(k, c, W0=W0)`` static-capture idiom: defaulted params of
    non-entry nested defs are not tainted."""
    root = mini_repo(tmp_path, {"slate_tpu/mod.py": _jit_mod("""\
        W0 = 4

        def step(k, c, W0=W0):
            if W0 > 2:          # static capture
                return c + k
            return c
        return lax.fori_loop(0, 3, step, jnp.sum(x))
        """)})
    assert lint(root, {"TRC001"}) == []


# --------------------------------------------------------------------------
# collective-discipline pack (COL001-COL004)


COL_HEADER = """\
    from jax import lax

    from .core.grid import AXIS_P, AXIS_Q

    """


def test_col001_fires_on_unknown_axis(tmp_path):
    root = mini_repo(tmp_path, {
        "slate_tpu/core/grid.py": GRID,
        "slate_tpu/mod.py": COL_HEADER + """\

    def f(x):
        ax = mystery()
        return lax.psum(x, ax)
    """})
    assert rule_ids(lint(root, {"COL001"})) == {"COL001"}


def test_col001_silent_on_constants_and_wrapper_params(tmp_path):
    root = mini_repo(tmp_path, {
        "slate_tpu/core/grid.py": GRID,
        "slate_tpu/mod.py": COL_HEADER + """\

    def f(x):
        return lax.psum(lax.psum(x, AXIS_P), AXIS_Q)


    def generic(x, axis):
        # the comm/collectives.py pattern: axis is a wrapper parameter
        return lax.psum(x, axis), lax.axis_index(axis)


    def local_alias(x):
        ax = AXIS_P
        return lax.pmax(x, ax)


    def tuple_axes(x):
        return lax.psum(x, (AXIS_P, AXIS_Q))
    """})
    assert lint(root, {"COL001"}) == []


def test_col002_fires_on_vocabulary_literal(tmp_path):
    root = mini_repo(tmp_path, {
        "slate_tpu/core/grid.py": GRID,
        "slate_tpu/mod.py": COL_HEADER + """\

    def f(x):
        return lax.psum(x, "p")
    """})
    assert rule_ids(lint(root, {"COL002"})) == {"COL002"}


def test_col002_silent_on_constant(tmp_path):
    root = mini_repo(tmp_path, {
        "slate_tpu/core/grid.py": GRID,
        "slate_tpu/mod.py": COL_HEADER + """\

    def f(x):
        return lax.psum(x, AXIS_P)
    """})
    assert lint(root, {"COL002"}) == []


def test_col003_fires_on_one_sided_collective(tmp_path):
    root = mini_repo(tmp_path, {
        "slate_tpu/core/grid.py": GRID,
        "slate_tpu/mod.py": COL_HEADER + """\

    def f(x, pred):
        return lax.cond(pred, lambda c: lax.psum(c, AXIS_P),
                        lambda c: c, x)
    """})
    assert rule_ids(lint(root, {"COL003"})) == {"COL003"}


def test_col003_fires_through_named_branch_functions(tmp_path):
    root = mini_repo(tmp_path, {
        "slate_tpu/core/grid.py": GRID,
        "slate_tpu/mod.py": COL_HEADER + """\

    def hot(c):
        return lax.psum(c, AXIS_P)


    def cold(c):
        return c


    def f(x, pred):
        return lax.cond(pred, hot, cold, x)
    """})
    assert rule_ids(lint(root, {"COL003"})) == {"COL003"}


def test_col003_silent_when_both_branches_collective(tmp_path):
    root = mini_repo(tmp_path, {
        "slate_tpu/core/grid.py": GRID,
        "slate_tpu/mod.py": COL_HEADER + """\

    def f(x, pred):
        return lax.cond(pred, lambda c: lax.psum(c, AXIS_P),
                        lambda c: lax.pmax(c, AXIS_P), x)


    def g(x, pred):
        # collective-free cond: nothing to diverge on
        return lax.cond(pred, lambda c: c + 1, lambda c: c - 1, x)
    """})
    assert lint(root, {"COL003"}) == []


def test_col004_fires_outside_fault_seam(tmp_path):
    root = mini_repo(tmp_path, {"slate_tpu/mod.py": """\
        from jax.experimental import io_callback


        def f(x):
            return io_callback(print, None, x)
        """})
    assert rule_ids(lint(root, {"COL004"})) == {"COL004"}


def test_col004_silent_inside_fault_seam(tmp_path):
    root = mini_repo(tmp_path, {"slate_tpu/robust/faults.py": """\
        from jax.experimental import io_callback


        def consume(x):
            return io_callback(print, None, x)
        """})
    assert lint(root, {"COL004"}) == []


# --------------------------------------------------------------------------
# seam pack (SEAM001-SEAM010): a clean skeleton, mutated per rule


def _driver(fn):
    return (f"from ..robust import health\n\n\n"
            f"def {fn}(a, opts=None):\n    return health.finalize(a)\n")


def seam_skeleton():
    files = {
        "slate_tpu/internal/rbt.py": "def butterfly(a):\n    return a\n",
        "slate_tpu/robust/abft.py": (
            "def tile_check(a):\n    return a, 0\n"),
        "slate_tpu/robust/faults.py": (
            'SITES = ("site_a", "site_b")\n\n\n'
            "def maybe_corrupt(site, x):\n    return x\n"),
        "slate_tpu/robust/recovery.py": """\
            def gesv_with_recovery(a, opts=None):
                spec = resolve_speculate(opts)
                ab = resolve_abft(opts)
                r = bounded_retry(a)
                return finalize(r)


            def gels_with_recovery(a, opts=None):
                spec = resolve_speculate(opts)
                low = resolve_precision(opts)
                r = bounded_retry(a)
                return finalize(r)


            def hesv_with_recovery(a, opts=None):
                spec = resolve_speculate(opts)
                r = bounded_retry(a)
                return finalize(r)


            def posv_with_recovery(a, opts=None):
                spec = resolve_speculate(opts)
                low = resolve_precision(opts)
                ab = resolve_abft(opts)
                r = bounded_retry(a)
                return finalize(r)
            """,
        "slate_tpu/serve/batched.py": """\
            def make_batched(op, shape, dtype, batch, opts=None):
                low = resolve_precision(opts)
                return op
            """,
        "slate_tpu/drivers/blas3.py": """\
            def gemm(a, b):
                ok = resolve_abft(None)
                return a


            def trsm(a, b):
                ok = resolve_abft(None)
                return a
            """,
        "slate_tpu/drivers/lu.py": (
            "from ..robust import health\n\n\n"
            "def _getrf(a):\n    ok = resolve_abft(None)\n    return a\n\n\n"
            "def getrf(a, opts=None):\n    return health.finalize(a)\n"),
        "slate_tpu/drivers/cholesky.py": (
            "from ..robust import health\n\n\n"
            "def potrf(a, opts=None):\n    ok = resolve_abft(None)\n"
            "    return health.finalize(a)\n"),
        "slate_tpu/drivers/mixed.py": (
            "from ..robust import health\n\n\n"
            "def gesv_mixed(a, opts=None):\n"
            "    spec = resolve_speculate(opts)\n"
            "    return health.finalize(a)\n"),
    }
    for name in ("band.py", "qr.py", "heev.py", "svd.py", "stedc.py",
                 "hetrf.py", "inverse.py", "condest.py"):
        files[f"slate_tpu/drivers/{name}"] = _driver(name[:-3])
    return files


def test_seam_skeleton_is_clean(tmp_path):
    root = mini_repo(tmp_path, seam_skeleton())
    assert lint(root, SEAM_IDS) == []


def _mutated(tmp_path, rel, src):
    files = seam_skeleton()
    files[rel] = src
    return mini_repo(tmp_path, files)


def test_seam001_fires_on_driver_without_opts(tmp_path):
    root = _mutated(tmp_path, "slate_tpu/drivers/qr.py",
                    _driver("qr") + "\n\ndef geqrf(a):\n    return a\n")
    fs = lint(root, SEAM_IDS)
    assert rule_ids(fs) == {"SEAM001"}
    assert fs[0].legacy == (
        f"qr.py:{fs[0].line}: public driver `geqrf` does not accept "
        f"`opts` — Option.ErrorPolicy cannot reach it")


def test_seam001_silent_on_exempt_names(tmp_path):
    root = _mutated(tmp_path, "slate_tpu/drivers/qr.py",
                    _driver("qr") + "\n\ndef lower(a):\n    return a\n")
    assert lint(root, SEAM_IDS) == []


def test_seam002_fires_without_robust_import(tmp_path):
    root = _mutated(tmp_path, "slate_tpu/drivers/band.py",
                    "def band(a, opts=None):\n    return a\n")
    fs = lint(root, SEAM_IDS)
    assert rule_ids(fs) == {"SEAM002"}
    assert "does not import the robust layer" in fs[0].legacy


def test_seam003_fires_on_import_without_health_reference(tmp_path):
    root = _mutated(tmp_path, "slate_tpu/drivers/band.py",
                    "from ..robust import health\n\n\n"
                    "def band(a, opts=None):\n    return a\n")
    fs = lint(root, SEAM_IDS)
    assert rule_ids(fs) == {"SEAM003"}


def test_seam004_fires_on_rbt_policy_import(tmp_path):
    root = _mutated(tmp_path, "slate_tpu/internal/rbt.py",
                    "from ..robust import recovery\n\n\n"
                    "def butterfly(a):\n    return a\n")
    fs = lint(root, SEAM_IDS)
    assert rule_ids(fs) == {"SEAM004"}
    assert fs[0].legacy == (
        "internal/rbt.py:1: imports the options/robust layer — the "
        "butterfly mechanism must stay policy-free (the seam is "
        "drivers/lu.py + robust/recovery.py)")


def test_seam005_fires_on_double_resolve(tmp_path):
    files = seam_skeleton()
    src = textwrap.dedent(files["slate_tpu/robust/recovery.py"]).replace(
        "def gesv_with_recovery(a, opts=None):\n"
        "    spec = resolve_speculate(opts)\n",
        "def gesv_with_recovery(a, opts=None):\n"
        "    spec = resolve_speculate(opts)\n"
        "    spec = resolve_speculate(opts)\n", 1)
    root = _mutated(tmp_path, "slate_tpu/robust/recovery.py", src)
    fs = lint(root, SEAM_IDS)
    assert rule_ids(fs) == {"SEAM005"}
    assert "resolve_speculate 2x" in fs[0].legacy


def test_seam005_fires_on_missing_escalation(tmp_path):
    files = seam_skeleton()
    src = textwrap.dedent(files["slate_tpu/robust/recovery.py"]).replace(
        "def hesv_with_recovery(a, opts=None):\n"
        "    spec = resolve_speculate(opts)\n"
        "    r = bounded_retry(a)\n",
        "def hesv_with_recovery(a, opts=None):\n"
        "    spec = resolve_speculate(opts)\n"
        "    r = a\n", 1)
    root = _mutated(tmp_path, "slate_tpu/robust/recovery.py", src)
    fs = lint(root, SEAM_IDS)
    assert "never routes through bounded_retry" in fs[0].legacy


def test_seam006_fires_on_speculate_knob_in_driver(tmp_path):
    root = _mutated(tmp_path, "slate_tpu/drivers/svd.py",
                    _driver("svd") +
                    "\n\ndef peek(a, opts=None):\n"
                    "    return Option.Speculate\n")
    fs = lint(root, SEAM_IDS)
    assert rule_ids(fs) == {"SEAM006"}
    assert fs[0].legacy.startswith("drivers/svd.py:")


def test_seam007_fires_on_abft_raise(tmp_path):
    root = _mutated(tmp_path, "slate_tpu/robust/abft.py",
                    "def tile_check(a):\n"
                    "    raise ValueError('detected')\n")
    fs = lint(root, SEAM_IDS)
    assert rule_ids(fs) == {"SEAM007"}
    assert "detection is DATA" in fs[0].legacy


def test_seam008_fires_on_double_resolve_abft(tmp_path):
    root = _mutated(tmp_path, "slate_tpu/drivers/cholesky.py",
                    "from ..robust import health\n\n\n"
                    "def potrf(a, opts=None):\n"
                    "    ok = resolve_abft(None)\n"
                    "    ok = resolve_abft(None)\n"
                    "    return health.finalize(a)\n")
    fs = lint(root, SEAM_IDS)
    assert rule_ids(fs) == {"SEAM008"}
    assert "resolve_abft 2x" in fs[0].legacy


def test_seam009_fires_on_unknown_or_computed_site(tmp_path):
    root = _mutated(tmp_path, "slate_tpu/drivers/band.py",
                    _driver("band") +
                    "\n\ndef inject(a, s, opts=None):\n"
                    "    a = maybe_corrupt('not_a_site', a)\n"
                    "    return maybe_corrupt(s, a)\n")
    fs = lint(root, SEAM_IDS)
    assert rule_ids(fs) == {"SEAM009"}
    msgs = " ".join(f.legacy for f in fs)
    assert "'not_a_site' not in faults.SITES" in msgs
    assert "not a string literal" in msgs


def test_seam009_silent_on_vocabulary_site(tmp_path):
    root = _mutated(tmp_path, "slate_tpu/drivers/band.py",
                    _driver("band") +
                    "\n\ndef inject(a, opts=None):\n"
                    "    return maybe_corrupt('site_a', a)\n")
    assert lint(root, SEAM_IDS) == []


def test_seam010_fires_on_abft_knob_in_driver(tmp_path):
    root = _mutated(tmp_path, "slate_tpu/drivers/hetrf.py",
                    _driver("hetrf") +
                    "\n\ndef peek(a, opts=None):\n"
                    "    return Option.Abft\n")
    fs = lint(root, SEAM_IDS)
    assert rule_ids(fs) == {"SEAM010"}


def test_legacy_report_order_matches_old_checker(tmp_path):
    """The shim's report groups speculation -> abft -> per-module, exactly
    the pre-migration ordering (tools/check_error_contracts.py)."""
    files = seam_skeleton()
    files["slate_tpu/internal/rbt.py"] = (
        "from ..robust import recovery\n\ndef butterfly(a):\n    return a\n")
    files["slate_tpu/drivers/band.py"] = (
        "def band(a):\n    return a\n")
    root = mini_repo(tmp_path, files)
    report = seams.legacy_report(load_project(root))
    assert len(report) == 3
    assert report[0].startswith("internal/rbt.py:1:")         # point 4
    assert report[1].startswith("band.py: does not import")   # point 2
    assert report[2].startswith("band.py:1: public driver")   # point 1


# --------------------------------------------------------------------------
# suppressions, baseline, CLI


def test_inline_and_standalone_suppressions(tmp_path):
    root = mini_repo(tmp_path, {"slate_tpu/mod.py": _jit_mod("""\
        y = jnp.sum(x)
        if y > 0:  # slate-lint: disable=TRC001 -- demo reason
            x = -x
        # slate-lint: disable=TRC001 -- standalone form
        if y > 1:
            x = x + 1
        if y > 2:
            x = x * 2
        return x
        """)})
    fs = lint(root, {"TRC001"})
    assert [f.line for f in fs] == [15]   # only the unsuppressed branch


def test_suppression_parsing_units():
    sup = parse_suppressions([
        (3, "# slate-lint: disable=TRC001,COL002 -- why", False),
        (7, "# slate-lint: disable=all", True),
    ])
    assert sup[3] == {"TRC001", "COL002"}
    assert sup[7] == {"all"} and sup[8] == {"all"}


def test_cli_baseline_roundtrip(tmp_path, capsys):
    root = mini_repo(tmp_path, {"slate_tpu/mod.py": _jit_mod("""\
        if jnp.sum(x) > 0:
            return x
        return -x
        """)})
    bl = tmp_path / "baseline.json"
    args = ["--root", str(root), "--select", "TRC001",
            "--baseline", str(bl)]
    assert cli.main(args) == 1
    assert cli.main(args + ["--update-baseline"]) == 0
    assert json.loads(bl.read_text())          # non-empty fingerprints
    assert cli.main(args) == 0                 # baselined -> clean
    capsys.readouterr()


def test_cli_json_format(tmp_path, capsys):
    root = mini_repo(tmp_path, {"slate_tpu/mod.py": _jit_mod("""\
        if jnp.sum(x) > 0:
            return x
        return -x
        """)})
    bl = tmp_path / "baseline.json"
    assert cli.main(["--root", str(root), "--select", "TRC001",
                     "--baseline", str(bl), "--format", "json"]) == 1
    out = json.loads(capsys.readouterr().out)
    assert out["findings"][0]["rule"] == "TRC001"
    assert out["baselined"] == 0


def test_cli_rejects_unknown_rule(tmp_path, capsys):
    assert cli.main(["--root", str(tmp_path), "--select", "NOPE9"]) == 2
    capsys.readouterr()


def test_cli_list_rules(capsys):
    assert cli.main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    assert "TRC001" in out and "COL003" in out and "SEAM010" in out


# --------------------------------------------------------------------------
# engine units


def test_reachability_entry_forms(tmp_path):
    root = mini_repo(tmp_path, {"slate_tpu/mod.py": """\
        import jax
        from functools import partial


        @jax.jit
        def a(x):
            return b(x)


        def b(x):
            return x


        @partial(jax.jit, static_argnames=("n",))
        def c(x, n):
            return x


        def never(x):
            return x
        """})
    reach = reachability.compute(load_project(root))
    t = {k.split("::")[1] for k in reach.traced}
    assert t == {"a", "b", "c"}
    assert reach.functions["slate_tpu/mod.py::c"].static_params == {"n"}


PALLAS_PARTIAL = """\
    from functools import partial

    import jax.experimental.pallas as pl


    def _kernel(a_ref, o_ref, *, bw):
        o_ref[...] = a_ref[...] * bw


    def run(a, bw):
        return pl.pallas_call(
            partial(_kernel, bw=bw),
            out_shape=jax.ShapeDtypeStruct(a.shape, a.dtype))(a)
    """


def test_pallas_call_partial_marks_kernel_entry(tmp_path):
    """pallas_call(partial(_kernel, bw=bw), ...) must mark _kernel as a
    traced entry with partial's keywords static — the fused-kernel idiom
    (pallas_chol/pallas_lu) was invisible to reachability before."""
    root = mini_repo(tmp_path, {"slate_tpu/mod.py": PALLAS_PARTIAL})
    reach = reachability.compute(load_project(root))
    info = reach.functions["slate_tpu/mod.py::_kernel"]
    assert info.is_entry
    assert info.static_params == {"bw"}


def test_trc_fires_inside_partial_wrapped_kernel(tmp_path):
    """A trace hazard INSIDE a partial-wrapped kernel body is now caught:
    branching on ref data is TRC001, but branching on the partial-bound
    static keyword is fine."""
    bad = PALLAS_PARTIAL.replace(
        "        o_ref[...] = a_ref[...] * bw\n",
        "        if a_ref[0, 0] > 0:\n"
        "            o_ref[...] = a_ref[...]\n")
    bad_dir = tmp_path / "bad"
    bad_dir.mkdir()
    root = mini_repo(bad_dir, {"slate_tpu/mod.py": bad})
    assert "TRC001" in rule_ids(lint(root, {"TRC001"}))

    good = PALLAS_PARTIAL.replace(
        "        o_ref[...] = a_ref[...] * bw\n",
        "        if bw > 4:\n"
        "            o_ref[...] = a_ref[...]\n")
    good_dir = tmp_path / "good"
    good_dir.mkdir()
    root2 = mini_repo(good_dir, {"slate_tpu/mod.py": good})
    assert lint(root2, {"TRC001"}) == []


PALLAS_PREFETCH = """\
    from functools import partial

    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu


    def _kernel(tiles_ref, a_ref, o_ref, *, bw):
        if bw > 4:
            o_ref[...] = a_ref[...]
        if tiles_ref[0] > 0:
            o_ref[...] = a_ref[...] * 2.0


    def run(a, tiles, bw):
        return pl.pallas_call(
            partial(_kernel, bw=bw),
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1,
                grid=(4,),
                in_specs=[pl.BlockSpec((8, 8), lambda i, tiles: (i, 0))],
                out_specs=pl.BlockSpec((8, 8), lambda i, tiles: (i, 0))),
            out_shape=jax.ShapeDtypeStruct(a.shape, a.dtype))(tiles, a)
    """


def test_prefetch_grid_spec_marks_scalar_refs_static(tmp_path):
    """An inline PrefetchScalarGridSpec(num_scalar_prefetch=N) makes the
    kernel's first N params scalar-prefetch refs: reachability records
    them static alongside partial-bound keywords, so the ragged batched
    kernels' size-vector reads do not fire trace rules."""
    root = mini_repo(tmp_path, {"slate_tpu/mod.py": PALLAS_PREFETCH})
    reach = reachability.compute(load_project(root))
    info = reach.functions["slate_tpu/mod.py::_kernel"]
    assert info.is_entry
    assert {"tiles_ref", "bw"} <= info.static_params
    assert lint(root, {"TRC001"}) == []

    # bare-Name kernels (no partial) get the same treatment
    bare = PALLAS_PREFETCH.replace("partial(_kernel, bw=bw),",
                                   "_kernel,").replace(", *, bw", "")
    bare = bare.replace("        if bw > 4:\n"
                        "            o_ref[...] = a_ref[...]\n", "")
    bare_dir = tmp_path / "bare"
    bare_dir.mkdir()
    root2 = mini_repo(bare_dir, {"slate_tpu/mod.py": bare})
    reach2 = reachability.compute(load_project(root2))
    assert "tiles_ref" in \
        reach2.functions["slate_tpu/mod.py::_kernel"].static_params
    assert lint(root2, {"TRC001"}) == []


def test_seam011_fires_on_raw_plan_cache_outside_tune(tmp_path):
    """A driver touching the raw autotuner plan cache (instead of
    resolve_plan) fires SEAM011; the tune package itself is exempt."""
    files = seam_skeleton()
    files["slate_tpu/drivers/qr.py"] = (
        "from ..robust import health\n"
        "from ..tune.plans import load_cache\n\n\n"
        "def qr(a, opts=None):\n"
        "    plans = load_cache()\n"
        "    return health.finalize(a)\n")
    fs = lint(mini_repo(tmp_path, files), SEAM_IDS)
    assert rule_ids(fs) == {"SEAM011"}
    assert "load_cache" in fs[0].message


def test_seam011_silent_inside_tune_and_via_resolver(tmp_path):
    files = seam_skeleton()
    files["slate_tpu/tune/plans.py"] = (
        "def load_cache():\n    return {}\n\n\n"
        "def resolve_plan(op, n, dtype='float32'):\n"
        "    return load_cache().get(op)\n")
    files["slate_tpu/drivers/qr.py"] = (
        "from ..robust import health\n"
        "from ..tune.plans import resolve_plan\n\n\n"
        "def qr(a, opts=None):\n"
        "    plan = resolve_plan('geqrf_panel', 128)\n"
        "    return health.finalize(a)\n")
    assert lint(mini_repo(tmp_path, files), SEAM_IDS) == []


def test_seam012_fires_on_direct_compile_in_serve(tmp_path):
    """serve/ modules other than cache.py compiling for themselves
    (jax.jit / lower / compile) bypass the executable-cache accounting
    and fire SEAM012."""
    files = seam_skeleton()
    files["slate_tpu/serve/server.py"] = (
        "import jax\n\n\n"
        "def run(fn, a):\n"
        "    exe = jax.jit(fn).lower(a).compile()\n"
        "    return exe(a)\n")
    fs = lint(mini_repo(tmp_path, files), SEAM_IDS)
    assert rule_ids(fs) == {"SEAM012"}
    assert any("jit" in f.message for f in fs)


def test_seam012_silent_in_cache_and_via_cache(tmp_path):
    """serve/cache.py is the one sanctioned compile site; a server that
    gets executables from it stays clean."""
    files = seam_skeleton()
    files["slate_tpu/serve/cache.py"] = (
        "import jax\n\n\n"
        "def get_or_compile(fn, spec):\n"
        "    return jax.jit(fn).lower(spec).compile()\n")
    files["slate_tpu/serve/server.py"] = (
        "from .cache import get_or_compile\n\n\n"
        "def run(fn, a):\n"
        "    exe = get_or_compile(fn, a)\n"
        "    return exe(a)\n")
    assert lint(mini_repo(tmp_path, files), SEAM_IDS) == []


def test_seam014_fires_on_low_precision_cast_in_driver(tmp_path):
    """An astype to a literal low-precision spelling inside drivers/
    bypasses the robust/precision.py seam (and its f32-accumulation
    contract) and fires SEAM014."""
    files = seam_skeleton()
    files["slate_tpu/drivers/qr.py"] = (
        "from ..robust import health\n"
        "import jax.numpy as jnp\n\n\n"
        "def qr(a, opts=None):\n"
        "    low = a.astype(jnp.bfloat16)\n"
        "    return health.finalize(low)\n")
    fs = lint(mini_repo(tmp_path, files), SEAM_IDS)
    assert rule_ids(fs) == {"SEAM014"}
    assert "bfloat16" in fs[0].message


def test_seam014_fires_on_dtype_kwarg_in_serve(tmp_path):
    """A dtype= keyword spelling low precision inside serve/ is the same
    bypass in allocation form ('bf16' string alias included)."""
    files = seam_skeleton()
    files["slate_tpu/serve/server.py"] = (
        "import jax.numpy as jnp\n\n\n"
        "def pack(n):\n"
        "    return jnp.zeros((n, n), dtype='bf16')\n")
    fs = lint(mini_repo(tmp_path, files), SEAM_IDS)
    assert rule_ids(fs) == {"SEAM014"}


def test_seam014_fires_on_raw_precision_knob(tmp_path):
    """Reading Option.Precision outside robust/precision.py (and the enum
    definition in options.py) fires SEAM014: boundaries consume
    resolve_precision's boolean, resolved exactly once."""
    files = seam_skeleton()
    files["slate_tpu/drivers/hetrf.py"] = (
        _driver("hetrf") +
        "\n\ndef peek(a, opts=None):\n"
        "    return opts.get(Option.Precision)\n")
    fs = lint(mini_repo(tmp_path, files), SEAM_IDS)
    assert rule_ids(fs) == {"SEAM014"}


def test_seam014_fires_on_double_resolve_precision(tmp_path):
    """A precision boundary resolving the knob twice breaks the
    resolve-exactly-once contract, same as SEAM005/SEAM008."""
    files = seam_skeleton()
    files["slate_tpu/serve/batched.py"] = (
        "def make_batched(op, shape, dtype, batch, opts=None):\n"
        "    low = resolve_precision(opts)\n"
        "    low2 = resolve_precision(opts)\n"
        "    return op\n")
    fs = lint(mini_repo(tmp_path, files), SEAM_IDS)
    assert rule_ids(fs) == {"SEAM014"}
    assert "EXACTLY once" in fs[0].message


def test_seam014_silent_on_lax_precision_and_high_casts(tmp_path):
    """jax's own lax.Precision attribute and high-precision casts
    (astype(jnp.float32)) must NOT trip the rule — the knob match is
    exact on the `Option` base name, the cast ban only on low spellings.
    The precision seam itself (robust/precision.py) may demote freely."""
    files = seam_skeleton()
    files["slate_tpu/drivers/qr.py"] = (
        "from ..robust import health\n"
        "import jax.numpy as jnp\n"
        "from jax import lax\n\n\n"
        "def qr(a, opts=None):\n"
        "    p = lax.Precision.HIGHEST\n"
        "    up = a.astype(jnp.float32)\n"
        "    return health.finalize(up)\n")
    files["slate_tpu/robust/precision.py"] = (
        "import jax.numpy as jnp\n\n\n"
        "def demote(x):\n"
        "    return x.astype(jnp.bfloat16)\n\n\n"
        "def resolve_precision(opts):\n"
        "    return bool(opts and opts.get(Option.Precision))\n")
    assert lint(mini_repo(tmp_path, files), SEAM_IDS) == []


# --------------------------------------------------------------------------
# observability pack (OBS001)


def test_obs001_fires_on_adhoc_telemetry(tmp_path):
    """print / logging / io_callback in drivers, internal, or parallel
    modules bypass the obs spine and fire OBS001."""
    root = mini_repo(tmp_path, {
        "slate_tpu/drivers/qr.py": (
            "def qr(a, opts=None):\n"
            "    print('factoring', a)\n"
            "    return a\n"),
        "slate_tpu/internal/gemm.py": (
            "import logging\n\n"
            "log = logging.getLogger(__name__)\n\n\n"
            "def gemm(a, b):\n"
            "    log.info('gemm')\n"
            "    return a\n"),
        "slate_tpu/parallel/dist_lu.py": (
            "from jax.experimental import io_callback\n\n\n"
            "def dist_getrf(a):\n"
            "    io_callback(lambda x: x, None, a)\n"
            "    return a\n"),
    })
    fs = lint(root, {"OBS001"})
    assert rule_ids(fs) == {"OBS001"}
    paths = {f.path for f in fs}
    assert paths == {"slate_tpu/drivers/qr.py",
                     "slate_tpu/internal/gemm.py",
                     "slate_tpu/parallel/dist_lu.py"}


def test_obs001_silent_on_obs_spine_and_printing(tmp_path):
    """The sanctioned telemetry routes stay silent: annotate/span from
    util.trace, and drivers/printing.py (stdout IS its contract)."""
    root = mini_repo(tmp_path, {
        "slate_tpu/drivers/qr.py": (
            "from ..util.trace import annotate, span\n\n\n"
            "@annotate('slate.geqrf')\n"
            "def geqrf(a, opts=None):\n"
            "    with span('slate.geqrf/panel'):\n"
            "        return a\n"),
        "slate_tpu/drivers/printing.py": (
            "def pprint(a):\n"
            "    print(a)\n"),
        "slate_tpu/obs/events.py": (
            "def emit(line):\n"
            "    print(line)\n"),
    })
    assert lint(root, {"OBS001"}) == []


def test_registry_has_required_rule_surface():
    assert len(REGISTRY) >= 30
    packs = {"TRC", "COL", "SEAM", "OBS", "CON"}
    assert {r[:3] if not r.startswith("SEAM") else "SEAM"
            for r in REGISTRY} == packs


# --------------------------------------------------------------------------
# tier-1: the live repo is lint-clean with an empty baseline diff


def test_repo_is_lint_clean(tmp_path, capsys):
    """The tier-1 gate AND artifact: the repo is clean under the full
    rule surface (all packs, call graph enabled) and the JSON report CI
    archives says so explicitly."""
    artifact = tmp_path / "slate-lint.json"
    assert cli.main(["--root", str(REPO), "--output", str(artifact)]) == 0
    capsys.readouterr()
    report = json.loads(artifact.read_text())
    assert report["findings"] == []
    assert report["baselined"] == 0 and report["stale_baseline"] == []
    assert len(report["rules"]) >= 30
    for pack in ("TRC", "COL", "SEAM", "OBS", "CON"):
        assert any(r.startswith(pack) for r in report["rules"])


def test_repo_baseline_is_empty():
    assert json.loads(
        (REPO / "tools/slate_lint/baseline.json").read_text()) == []


# --------------------------------------------------------------------------
# observability pack (OBS002)


FLOPS_FIXTURE = """\
    def register(*names):
        def deco(fn):
            return fn
        return deco


    @register("gesv", "posv")
    def _f(shapes, sizes):
        return 1.0
    """


def test_obs002_fires_on_unpriced_driver(tmp_path):
    """An @annotate-decorated driver whose op has no flops model in
    obs/flops.py means a silent `mfu: n/a` forever — OBS002 flags the
    decorator line."""
    root = mini_repo(tmp_path, {
        "slate_tpu/obs/flops.py": FLOPS_FIXTURE,
        "slate_tpu/drivers/qr.py": (
            "from ..util.trace import annotate\n\n\n"
            "@annotate('slate.geqrf')\n"
            "def geqrf(a, opts=None):\n"
            "    return a\n"),
    })
    fs = lint(root, {"OBS002"})
    assert rule_ids(fs) == {"OBS002"}
    (f,) = fs
    assert f.path == "slate_tpu/drivers/qr.py" and f.line == 4
    assert "geqrf" in f.message and "flops model" in f.message


def test_obs002_silent_on_registered_or_disabled(tmp_path):
    """Registered ops pass; unregistered ops with an explicit reasoned
    disable (the band-driver pattern) pass too."""
    root = mini_repo(tmp_path, {
        "slate_tpu/obs/flops.py": FLOPS_FIXTURE,
        "slate_tpu/drivers/lu.py": (
            "from ..util.trace import annotate\n\n\n"
            "@annotate('slate.gesv')\n"
            "def gesv(a, b, opts=None):\n"
            "    return a\n"),
        "slate_tpu/drivers/band.py": (
            "from ..util.trace import annotate\n\n\n"
            "@annotate('slate.pbsv')  "
            "# slate-lint: disable=OBS002 -- needs bandwidth, not shapes\n"
            "def pbsv(a, b, opts=None):\n"
            "    return a\n"),
    })
    assert lint(root, {"OBS002"}) == []


def test_obs002_silent_without_flops_module(tmp_path):
    """Mini-repos with no obs/flops.py have no registry to check against;
    the rule stands down instead of flagging everything."""
    root = mini_repo(tmp_path, {
        "slate_tpu/drivers/qr.py": (
            "from ..util.trace import annotate\n\n\n"
            "@annotate('slate.geqrf')\n"
            "def geqrf(a, opts=None):\n"
            "    return a\n"),
    })
    assert lint(root, {"OBS002"}) == []


def test_obs002_clean_on_live_repo():
    """The real tree holds the invariant: every annotate-decorated driver
    is either priced in obs/flops.py or carries a reasoned disable."""
    assert lint(REPO, {"OBS002"}) == []


# --------------------------------------------------------------------------
# call graph: re-export, dict-dispatch, and method edges


def test_reexport_edge_traces_through_init(tmp_path):
    """pkg.work where pkg/__init__.py merely re-exports work from a
    submodule: dotted resolution follows the import chain to the def."""
    root = mini_repo(tmp_path, {
        "slate_tpu/pkg/__init__.py": "from .impl import work\n",
        "slate_tpu/pkg/impl.py": "def work(x):\n    return x\n",
        "slate_tpu/mod.py": """\
            import jax
            from . import pkg


            @jax.jit
            def entry(x):
                return pkg.work(x)
            """,
    })
    reach = reachability.compute(load_project(root))
    assert "slate_tpu/pkg/impl.py::work" in reach.traced


def test_dispatch_table_call_and_alias_edges(tmp_path):
    """The serve.CORES idiom: CORES[op](...) and the two-step
    core = CORES[op]; vmap(lambda ...: core(...)) both reach EVERY
    table value."""
    root = mini_repo(tmp_path, {"slate_tpu/mod.py": """\
        import jax


        def solve_core(a):
            return a


        def chol_core(a):
            return a


        CORES = {"solve": solve_core, "chol": chol_core}


        def direct(op, a):
            return CORES[op](a)


        def via_alias(op, a):
            core = CORES[op]
            return jax.vmap(lambda ai: core(ai))(a)
        """})
    reach = reachability.compute(load_project(root))
    assert reach.dispatch_tables["slate_tpu/mod.py"]["CORES"] == (
        "slate_tpu/mod.py::solve_core", "slate_tpu/mod.py::chol_core")
    direct = reach.functions["slate_tpu/mod.py::direct"]
    assert {"slate_tpu/mod.py::solve_core",
            "slate_tpu/mod.py::chol_core"} <= direct.resolved_calls
    # the vmap(lambda: core(...)) closure marks the table values ENTRIES
    assert reach.functions["slate_tpu/mod.py::solve_core"].is_entry
    assert "vmap" in reach.entry_kinds["slate_tpu/mod.py::chol_core"]


def test_callgraph_facade_method_and_reverse_edges(tmp_path):
    from tools.slate_lint import callgraph
    root = mini_repo(tmp_path, {"slate_tpu/mod.py": """\
        def helper(x):
            return x


        class Box:
            def outer(self):
                return self.inner()

            def inner(self):
                return helper(1)
        """})
    cg = callgraph.compute(load_project(root))
    outer = "slate_tpu/mod.py::Box.outer"
    inner = "slate_tpu/mod.py::Box.inner"
    helper = "slate_tpu/mod.py::helper"
    assert inner in cg.callees(outer)
    assert helper in cg.callees(inner)
    assert outer in cg.callers(inner)
    assert inner in cg.callers(helper)


# --------------------------------------------------------------------------
# interprocedural taint


def test_interprocedural_taint_crosses_modules(tmp_path):
    """A traced entry passing a traced value into a helper in ANOTHER
    module taints the helper's parameter: the branch inside fires."""
    root = mini_repo(tmp_path, {
        "slate_tpu/helper.py": """\
            def branchy(v):
                if v > 0:
                    return v
                return -v
            """,
        "slate_tpu/mod.py": """\
            import jax
            import jax.numpy as jnp
            from . import helper


            @jax.jit
            def entry(x):
                return helper.branchy(jnp.sum(x))
            """,
    })
    fs = lint(root, {"TRC001"})
    assert [(f.path, f.line) for f in fs] == [("slate_tpu/helper.py", 2)]


def test_interprocedural_taint_respects_annotations(tmp_path):
    """A parameter annotated with a non-array host type (int) is never
    interprocedurally seeded — annotations declare the eager contract."""
    root = mini_repo(tmp_path, {
        "slate_tpu/helper.py": """\
            def branchy(v: int):
                if v > 0:
                    return v
                return -v
            """,
        "slate_tpu/mod.py": """\
            import jax
            from . import helper


            @jax.jit
            def entry(x):
                return helper.branchy(x.shape[0])
            """,
    })
    assert lint(root, {"TRC001"}) == []


def test_return_taint_summary_distinguishes_static(tmp_path):
    """Branching on a callee's return fires only when the callee
    actually returns traced data — a static .shape return stays clean."""
    root = mini_repo(tmp_path, {"slate_tpu/mod.py": """\
        import jax
        import jax.numpy as jnp


        def size_of(x):
            return x.shape[0]


        def total(x):
            return jnp.sum(x)


        @jax.jit
        def entry(x):
            if size_of(x) > 2:
                x = x * 2
            if total(x) > 0:
                x = x + 1
            return x
        """})
    fs = lint(root, {"TRC001"})
    assert [f.line for f in fs] == [17]


def test_return_taint_tuple_elements_are_elementwise(tmp_path):
    """Tuple-returning callees get element-wise summaries: destructured
    static elements never taint."""
    root = mini_repo(tmp_path, {"slate_tpu/mod.py": """\
        import jax


        def padded(x):
            return x * 2, x.shape[0]


        @jax.jit
        def entry(x):
            y, n = padded(x)
            if n > 4:
                y = y + 1
            return y
        """})
    assert lint(root, {"TRC001"}) == []


# --------------------------------------------------------------------------
# collective-sequence pack (COL005-COL008)


COL_GRID = {"slate_tpu/core/grid.py": GRID}


def _col_mod(body):
    return ("import jax\nimport jax.numpy as jnp\n"
            "from jax import lax\n"
            "from .core.grid import AXIS_P\n\n\n" + textwrap.dedent(body))


def test_col005_fires_on_tainted_predicate(tmp_path):
    root = mini_repo(tmp_path, {**COL_GRID, "slate_tpu/mod.py": _col_mod("""\
        def _yes(x):
            return lax.psum(x, AXIS_P)


        def _no(x):
            return x


        @jax.jit
        def entry(x):
            pred = jnp.sum(x) > 0
            return lax.cond(pred, _yes, _no, x)
        """)})
    fs = lint(root, {"COL005"})
    assert [f.rule for f in fs] == ["COL005"]


def test_col005_silent_on_static_predicate(tmp_path):
    root = mini_repo(tmp_path, {**COL_GRID, "slate_tpu/mod.py": _col_mod("""\
        def _yes(x):
            return lax.psum(x, AXIS_P)


        def _no(x):
            return x


        @jax.jit
        def entry(x):
            return lax.cond(x.ndim > 1, _yes, _no, x)
        """)})
    assert lint(root, {"COL005"}) == []


def test_col006_fires_on_differing_branch_sequences(tmp_path):
    root = mini_repo(tmp_path, {**COL_GRID, "slate_tpu/mod.py": _col_mod("""\
        def _a(x):
            return lax.psum(x, AXIS_P)


        def _b(x):
            return lax.pmax(x, AXIS_P)


        @jax.jit
        def entry(x):
            return lax.cond(x.ndim > 1, _a, _b, x)
        """)})
    fs = lint(root, {"COL006"})
    assert [f.rule for f in fs] == ["COL006"]
    assert "psum@p" in fs[0].message and "pmax@p" in fs[0].message


def test_col006_silent_on_matching_sequences(tmp_path):
    root = mini_repo(tmp_path, {**COL_GRID, "slate_tpu/mod.py": _col_mod("""\
        def _a(x):
            return lax.psum(x, AXIS_P) * 2


        def _b(x):
            return lax.psum(x, AXIS_P) + 1


        @jax.jit
        def entry(x):
            return lax.cond(x.ndim > 1, _a, _b, x)
        """)})
    assert lint(root, {"COL006"}) == []


def test_col007_fires_on_collective_in_while_loop(tmp_path):
    root = mini_repo(tmp_path, {**COL_GRID, "slate_tpu/mod.py": _col_mod("""\
        def _cond(s):
            return jnp.sum(s) > 0


        def _body(s):
            return s - lax.psum(s, AXIS_P)


        @jax.jit
        def entry(x):
            return lax.while_loop(_cond, _body, x)
        """)})
    fs = lint(root, {"COL007"})
    assert [f.rule for f in fs] == ["COL007"]


def test_col007_fires_on_fori_with_tainted_bounds(tmp_path):
    root = mini_repo(tmp_path, {**COL_GRID, "slate_tpu/mod.py": _col_mod("""\
        def _body(i, s):
            return s + lax.psum(s, AXIS_P)


        @jax.jit
        def entry(x, n):
            return lax.fori_loop(0, n, _body, x)
        """)})
    fs = lint(root, {"COL007"})
    assert [f.rule for f in fs] == ["COL007"]


def test_col007_silent_on_static_bounds_and_plain_loops(tmp_path):
    root = mini_repo(tmp_path, {**COL_GRID, "slate_tpu/mod.py": _col_mod("""\
        def _body(i, s):
            return s + lax.psum(s, AXIS_P)


        def _dense_cond(s):
            return jnp.sum(s) > 0


        def _dense_body(s):
            return s * 0.5


        @jax.jit
        def entry(x):
            x = lax.fori_loop(0, 8, _body, x)
            return lax.while_loop(_dense_cond, _dense_body, x)
        """)})
    assert lint(root, {"COL007"}) == []


def test_col008_fires_on_mismatched_ring_shifts(tmp_path):
    root = mini_repo(tmp_path, {**COL_GRID, "slate_tpu/mod.py": _col_mod("""\
        def step(x):
            y = lax.ppermute(x, AXIS_P,
                             [(i, (i + 1) % 4) for i in range(4)])
            z = lax.ppermute(x, AXIS_P,
                             [(i, (i - 1) % 4) for i in range(4)])
            return y + z
        """)})
    fs = lint(root, {"COL008"})
    assert [f.rule for f in fs] == ["COL008"]
    assert fs[0].line == 10                  # anchored at the later site


def test_col008_silent_on_consistent_ring(tmp_path):
    root = mini_repo(tmp_path, {**COL_GRID, "slate_tpu/mod.py": _col_mod("""\
        def step(x):
            y = lax.ppermute(x, AXIS_P,
                             [(i, (i + 1) % 4) for i in range(4)])
            z = lax.ppermute(x, AXIS_P,
                             [(i, (i + 1) % 4) for i in range(4)])
            return y + z
        """)})
    assert lint(root, {"COL008"}) == []


def test_col008_silent_on_double_buffered_loop_body(tmp_path):
    # PERF r15 lookahead idiom: the pipeline's loop body ring-shifts BOTH
    # the live panel and the in-flight prefetch buffer along the same +1
    # ring — two same-direction hops per axis are one consistent ring
    root = mini_repo(tmp_path, {**COL_GRID, "slate_tpu/mod.py": _col_mod("""\
        def body(k, carry):
            cur, nxt = carry
            cur = lax.ppermute(cur, AXIS_P,
                               [(i, (i + 1) % 4) for i in range(4)])
            nxt = lax.ppermute(nxt, AXIS_P,
                               [(i, (i + 1) % 4) for i in range(4)])
            return (nxt, cur)


        @jax.jit
        def entry(x):
            return lax.fori_loop(0, 8, body, (x, x))
        """)})
    assert lint(root, {"COL008"}) == []


def test_col008_fires_on_double_buffer_direction_mismatch(tmp_path):
    # ...but a prefetch buffer shifted AGAINST the live panel's ring
    # means the two buffers' send/recv partners never pair up
    root = mini_repo(tmp_path, {**COL_GRID, "slate_tpu/mod.py": _col_mod("""\
        def body(k, carry):
            cur, nxt = carry
            cur = lax.ppermute(cur, AXIS_P,
                               [(i, (i + 1) % 4) for i in range(4)])
            nxt = lax.ppermute(nxt, AXIS_P,
                               [(i, (i - 1) % 4) for i in range(4)])
            return (nxt, cur)


        @jax.jit
        def entry(x):
            return lax.fori_loop(0, 8, body, (x, x))
        """)})
    fs = lint(root, {"COL008"})
    assert [f.rule for f in fs] == ["COL008"]


def test_col006_pipeline_epilogue_must_keep_ring_sequence(tmp_path):
    # lookahead pipeline shape: prologue ring hop, then a steady-state
    # cond whose taken arm rings the NEXT panel and psums the update.
    # An epilogue arm that drops the ring (instead of only local work)
    # diverges the branch collective sequences and fires.
    root = mini_repo(tmp_path, {**COL_GRID, "slate_tpu/mod.py": _col_mod("""\
        def _steady(x):
            nxt = lax.ppermute(x, AXIS_P,
                               [(i, (i + 1) % 4) for i in range(4)])
            return nxt + lax.psum(x, AXIS_P)


        def _epilogue(x):
            return x + lax.psum(x, AXIS_P)


        @jax.jit
        def entry(x):
            x = lax.ppermute(x, AXIS_P,
                             [(i, (i + 1) % 4) for i in range(4)])
            return lax.cond(x.ndim > 1, _steady, _epilogue, x)
        """)})
    fs = lint(root, {"COL006"})
    assert [f.rule for f in fs] == ["COL006"]
    assert "ppermute@p" in fs[0].message


def test_col006_silent_on_uniform_pipeline_sequences(tmp_path):
    # the CORRECT epilogue keeps the ring (a dead hop on zeroed data,
    # exactly how the pipelined kernels retire their final clamped
    # issue) so prologue/steady-state/epilogue all run one sequence
    root = mini_repo(tmp_path, {**COL_GRID, "slate_tpu/mod.py": _col_mod("""\
        def _steady(x):
            nxt = lax.ppermute(x, AXIS_P,
                               [(i, (i + 1) % 4) for i in range(4)])
            return nxt + lax.psum(x, AXIS_P)


        def _epilogue(x):
            dead = lax.ppermute(x * 0.0, AXIS_P,
                                [(i, (i + 1) % 4) for i in range(4)])
            return dead + lax.psum(x, AXIS_P)


        @jax.jit
        def entry(x):
            x = lax.ppermute(x, AXIS_P,
                             [(i, (i + 1) % 4) for i in range(4)])
            return lax.cond(x.ndim > 1, _steady, _epilogue, x)
        """)})
    assert lint(root, {"COL006"}) == []


# --------------------------------------------------------------------------
# lock-discipline pack (CON001-CON003)


EVENTS_FIXTURE_HEADER = """\
import threading

_LOCK = threading.Lock()
_CFG = {"enabled": False}
_RING = []
_COLLECTORS = []


"""


def test_con001_fires_on_unlocked_module_state(tmp_path):
    root = mini_repo(tmp_path, {
        "slate_tpu/obs/events.py": EVENTS_FIXTURE_HEADER + (
            "def toggle(on):\n"
            "    _CFG[\"enabled\"] = on\n"),
    })
    fs = lint(root, {"CON001"})
    assert [f.rule for f in fs] == ["CON001"]
    assert "_CFG" in fs[0].message


def test_con001_silent_when_locked_or_suppressed(tmp_path):
    root = mini_repo(tmp_path, {
        "slate_tpu/obs/events.py": EVENTS_FIXTURE_HEADER + (
            "def toggle(on):\n"
            "    with _LOCK:\n"
            "        _CFG[\"enabled\"] = on\n\n\n"
            "def peek():\n"
            "    # slate-lint: disable=CON001 -- lock-free fast-path peek\n"
            "    return _CFG[\"enabled\"]\n"),
    })
    assert lint(root, {"CON001"}) == []


def test_con001_mutation_of_real_server_is_caught(tmp_path):
    """The acceptance mutation: drop one `with self._lock:` from the real
    server.py and CON001 must fire; the pristine text stays clean."""
    real = (REPO / "slate_tpu/serve/server.py").read_text()
    good = mini_repo(tmp_path / "good",
                     {"slate_tpu/serve/server.py": real})
    assert lint(good, {"CON001"}) == []
    mutated = real.replace("with self._lock:", "if True:", 1)
    assert mutated != real
    bad = mini_repo(tmp_path / "bad",
                    {"slate_tpu/serve/server.py": mutated})
    fs = lint(bad, {"CON001"})
    assert fs and all(f.rule == "CON001" for f in fs)
    guards = ("_inflight", "_flush_deadline", "_wedged", "_flush_error",
              "_quarantined", "_flusher", "_watchdog", "_ladders",
              "_sizes", "_retunes", "_retuning", "_last_retune")
    assert all(any(g in f.message for g in guards) for f in fs)


def test_con001_mutation_of_real_admission_queue_is_caught(tmp_path):
    """Same acceptance mutation for the survival layer's intake: unlock
    take_all()'s item swap in the real admission.py and CON001 fires on
    the queue state."""
    real = (REPO / "slate_tpu/serve/admission.py").read_text()
    good = mini_repo(tmp_path / "good",
                     {"slate_tpu/serve/admission.py": real})
    assert lint(good, {"CON001"}) == []
    locked = ("        with self._lock:\n"
              "            items, self._items = self._items, []")
    assert locked in real
    mutated = real.replace(
        locked, "        if True:\n"
                "            items, self._items = self._items, []", 1)
    bad = mini_repo(tmp_path / "bad",
                    {"slate_tpu/serve/admission.py": mutated})
    fs = lint(bad, {"CON001"})
    assert fs and all(f.rule == "CON001" for f in fs)
    assert all("_items" in f.message for f in fs)


ADMISSION_FIXTURE = """\
import threading


class AdmissionQueue:
    def __init__(self):
        self._lock = threading.Condition()
        self._items = []
        self._shed = 0
        self._closed = None

    def depth(self):
        with self._lock:
            return len(self._items)
"""


def test_con001_fires_on_unlocked_queue_state(tmp_path):
    root = mini_repo(tmp_path, {
        "slate_tpu/serve/admission.py": ADMISSION_FIXTURE + (
            "\n"
            "    def sneak(self):\n"
            "        self._shed += 1\n")})
    fs = lint(root, {"CON001"})
    assert [f.rule for f in fs] == ["CON001"]
    assert "_shed" in fs[0].message


def test_con001_silent_on_locked_queue_state(tmp_path):
    root = mini_repo(tmp_path, {
        "slate_tpu/serve/admission.py": ADMISSION_FIXTURE + (
            "\n"
            "    def sneak(self):\n"
            "        with self._lock:\n"
            "            self._shed += 1\n")})
    assert lint(root, {"CON001"}) == []


def test_con002_fires_on_lock_order_inversion(tmp_path, monkeypatch):
    from tools.slate_lint.rules import concurrency as con
    monkeypatch.setattr(con, "LOCK_REGISTRY", (
        con.LockSpec("slate_tpu/a.py", None, "_LA", ("_SA",)),
        con.LockSpec("slate_tpu/b.py", None, "_LB", ("_SB",)),
    ))
    root = mini_repo(tmp_path, {
        "slate_tpu/a.py": """\
            import threading
            from . import b

            _LA = threading.Lock()
            _SA = []


            def take_a():
                with _LA:
                    _SA.append(1)


            def cross():
                with _LA:
                    b.take_b()
            """,
        "slate_tpu/b.py": """\
            import threading
            from . import a

            _LB = threading.Lock()
            _SB = []


            def take_b():
                with _LB:
                    _SB.append(1)


            def cross():
                with _LB:
                    a.take_a()
            """,
    })
    fs = lint(root, {"CON002"})
    assert [f.rule for f in fs] == ["CON002"]
    assert "inversion" in fs[0].message


def test_con002_fires_on_self_reacquire(tmp_path):
    root = mini_repo(tmp_path, {
        "slate_tpu/obs/events.py": EVENTS_FIXTURE_HEADER + (
            "def set_on():\n"
            "    with _LOCK:\n"
            "        _CFG[\"enabled\"] = True\n\n\n"
            "def flip():\n"
            "    with _LOCK:\n"
            "        set_on()\n"),
    })
    fs = lint(root, {"CON002"})
    assert [f.rule for f in fs] == ["CON002"]
    assert "re-acquires" in fs[0].message


def test_con002_silent_on_consistent_order(tmp_path, monkeypatch):
    from tools.slate_lint.rules import concurrency as con
    monkeypatch.setattr(con, "LOCK_REGISTRY", (
        con.LockSpec("slate_tpu/a.py", None, "_LA", ("_SA",)),
        con.LockSpec("slate_tpu/b.py", None, "_LB", ("_SB",)),
    ))
    root = mini_repo(tmp_path, {
        "slate_tpu/a.py": """\
            import threading
            from . import b

            _LA = threading.Lock()
            _SA = []


            def cross():
                with _LA:
                    b.take_b()
            """,
        "slate_tpu/b.py": """\
            import threading

            _LB = threading.Lock()
            _SB = []


            def take_b():
                with _LB:
                    _SB.append(1)
            """,
    })
    assert lint(root, {"CON002"}) == []


def test_con003_fires_on_compile_under_lock(tmp_path):
    root = mini_repo(tmp_path, {
        "slate_tpu/serve/cache.py": """\
            import threading

            import jax


            class ExecutableCache:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._exes = {}

                def get(self, key, fn, spec):
                    with self._lock:
                        exe = self._exes.get(key)
                        if exe is None:
                            exe = jax.jit(fn).lower(spec)
                            self._exes[key] = exe
                    return exe
            """,
    })
    fs = lint(root, {"CON003"})
    assert [f.rule for f in fs] == ["CON003"]
    assert "lower" in fs[0].message


def test_con003_silent_on_compile_outside_lock(tmp_path):
    root = mini_repo(tmp_path, {
        "slate_tpu/serve/cache.py": """\
            import threading

            import jax


            class ExecutableCache:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._exes = {}

                def get(self, key, fn, spec):
                    with self._lock:
                        exe = self._exes.get(key)
                    if exe is not None:
                        return exe
                    exe = jax.jit(fn).lower(spec)
                    with self._lock:
                        return self._exes.setdefault(key, exe)
            """,
    })
    assert lint(root, {"CON003"}) == []


# ------------------------------------------------------- device pool lock

POOL_FIXTURE = """\
import threading


class DevicePool:
    def __init__(self, devices):
        self._lock = threading.Lock()
        self._members = list(devices)
        self._rr = 0
        self._failovers = 0
        self._quarantines = 0
        self._readmissions = 0

    def stats(self):
        with self._lock:
            return {"failovers": self._failovers,
                    "quarantines": self._quarantines}
"""


def test_con001_fires_on_unlocked_pool_rotation(tmp_path):
    root = mini_repo(tmp_path, {
        "slate_tpu/serve/pool.py": POOL_FIXTURE + (
            "\n"
            "    def select(self):\n"
            "        m = self._members[self._rr]\n"
            "        self._rr += 1\n"
            "        return m\n")})
    fs = lint(root, {"CON001"})
    assert fs and all(f.rule == "CON001" for f in fs)
    assert any("_rr" in f.message for f in fs)
    assert any("_members" in f.message for f in fs)


def test_con001_silent_on_locked_pool_rotation(tmp_path):
    root = mini_repo(tmp_path, {
        "slate_tpu/serve/pool.py": POOL_FIXTURE + (
            "\n"
            "    def select(self):\n"
            "        with self._lock:\n"
            "            m = self._members[self._rr]\n"
            "            self._rr += 1\n"
            "        return m\n")})
    assert lint(root, {"CON001"}) == []


def test_con001_mutation_of_real_pool_is_caught(tmp_path):
    """The acceptance mutation for the device pool: drop one
    `with self._lock:` from the real pool.py and CON001 must fire on
    the member/rotation state; the pristine text stays clean."""
    real = (REPO / "slate_tpu/serve/pool.py").read_text()
    good = mini_repo(tmp_path / "good",
                     {"slate_tpu/serve/pool.py": real})
    assert lint(good, {"CON001"}) == []
    mutated = real.replace("with self._lock:", "if True:", 1)
    assert mutated != real
    bad = mini_repo(tmp_path / "bad",
                    {"slate_tpu/serve/pool.py": mutated})
    fs = lint(bad, {"CON001"})
    assert fs and all(f.rule == "CON001" for f in fs)
    guards = ("_members", "_rr", "_failovers", "_quarantines",
              "_readmissions")
    assert all(any(g in f.message for g in guards) for f in fs)


def test_con003_fires_on_compile_under_pool_lock(tmp_path):
    """A warm-the-executable call under the pool's member lock is the
    compile-under-lock bug class: every dispatcher thread would stall
    behind one cold compile.  get_or_compile IS the serving compile
    entry (SEAM012), so CON003 must treat it as blocking."""
    root = mini_repo(tmp_path, {
        "slate_tpu/serve/pool.py": POOL_FIXTURE + (
            "\n"
            "    def warm(self, cache, op, shape, dtype, batch):\n"
            "        with self._lock:\n"
            "            for m in self._members:\n"
            "                cache.get_or_compile(op, shape, dtype,\n"
            "                                     batch, device=m)\n")})
    fs = lint(root, {"CON003"})
    assert [f.rule for f in fs] == ["CON003"]
    assert "get_or_compile" in fs[0].message


def test_con003_real_pool_and_server_compile_outside_locks():
    """The real serving layer holds no registry lock across a compile:
    the warm pass, the canary probe and the retune warmer all call
    get_or_compile outside critical sections."""
    fs = lint(REPO, {"CON003"})
    assert fs == []


# --------------------------------------------------------------------------
# CLI: findings cache, --changed-only, --output artifact


CACHE_MINI = {
    "slate_tpu/mod.py": (
        "import jax\nimport jax.numpy as jnp\n\n\n"
        "@jax.jit\ndef entry(x):\n"
        "    if jnp.sum(x) > 0:\n"
        "        return x\n"
        "    return -x\n"),
}


def _trc_findings(report):
    """A mini repo also fires the SEAM layout rules (it has none of the
    expected modules); the cache tests key on the TRC001 finding only."""
    return [f for f in report["findings"] if f["rule"] == "TRC001"]


def test_findings_cache_replays_and_invalidates(tmp_path, capsys):
    root = mini_repo(tmp_path, CACHE_MINI)
    cache = tmp_path / "cache.json"
    out = tmp_path / "report.json"
    base = ["--root", str(root), "--cache", str(cache),
            "--output", str(out)]
    assert cli.main(base) == 1
    cold = json.loads(out.read_text())
    assert cold["cached"] is False and len(_trc_findings(cold)) == 1
    assert cli.main(base) == 1                       # warm: replayed
    warm = json.loads(out.read_text())
    assert warm["cached"] is True
    assert warm["findings"] == cold["findings"]
    # ANY file drift invalidates the whole cache (interprocedural safety)
    (root / "slate_tpu/mod.py").write_text(
        CACHE_MINI["slate_tpu/mod.py"].replace("jnp.sum(x) > 0",
                                               "x.ndim > 0"))
    assert cli.main(base) == 1      # SEAM layout findings remain
    fresh = json.loads(out.read_text())
    assert fresh["cached"] is False and _trc_findings(fresh) == []
    capsys.readouterr()


def test_findings_cache_select_runs_bypass(tmp_path, capsys):
    """--select subsets must never write or read the full-run cache."""
    root = mini_repo(tmp_path, CACHE_MINI)
    cache = tmp_path / "cache.json"
    assert cli.main(["--root", str(root), "--cache", str(cache),
                     "--select", "COL001"]) == 0
    assert not cache.exists()
    capsys.readouterr()


def test_findings_cache_wall_time_budget(tmp_path, capsys):
    """The tier-1 budget: a warm full repo run replays from the cache in
    a fraction of the cold analysis time."""
    import time
    cache = tmp_path / "cache.json"
    t0 = time.perf_counter()
    assert cli.main(["--root", str(REPO), "--cache", str(cache)]) == 0
    cold = time.perf_counter() - t0
    t0 = time.perf_counter()
    assert cli.main(["--root", str(REPO), "--cache", str(cache)]) == 0
    warm = time.perf_counter() - t0
    capsys.readouterr()
    assert warm < max(2.5, 0.7 * cold)


def test_changed_only_filters_to_git_diff(tmp_path, capsys):
    import shutil
    import subprocess
    if shutil.which("git") is None:
        pytest.skip("git unavailable")
    root = mini_repo(tmp_path, {
        **CACHE_MINI,
        "slate_tpu/clean.py": "def ok():\n    return 1\n",
    })
    env = {"GIT_AUTHOR_NAME": "t", "GIT_AUTHOR_EMAIL": "t@t",
           "GIT_COMMITTER_NAME": "t", "GIT_COMMITTER_EMAIL": "t@t",
           "HOME": str(tmp_path), "PATH": __import__("os").environ["PATH"]}
    for cmd in (["git", "init", "-q"], ["git", "add", "-A"],
                ["git", "commit", "-qm", "seed"]):
        subprocess.run(cmd, cwd=root, env=env, check=True)
    # committed violation, no changes: --changed-only hides it, exit 0
    assert cli.main(["--root", str(root), "--changed-only"]) == 0
    # touch the offending file: the finding is in the changed set again
    p = root / "slate_tpu/mod.py"
    p.write_text(p.read_text() + "\n")
    assert cli.main(["--root", str(root), "--changed-only"]) == 1
    # a full run still reports it regardless of git state
    assert cli.main(["--root", str(root)]) == 1
    capsys.readouterr()


def test_changed_only_falls_back_without_git(tmp_path, capsys):
    """No git repo: --changed-only degrades to reporting everything
    rather than silently hiding findings."""
    root = mini_repo(tmp_path, CACHE_MINI)
    assert cli.main(["--root", str(root), "--changed-only"]) == 1
    out = capsys.readouterr()
    assert "git unavailable" in out.err


def test_output_artifact_schema(tmp_path, capsys):
    root = mini_repo(tmp_path, CACHE_MINI)
    out = tmp_path / "report.json"
    assert cli.main(["--root", str(root), "--output", str(out)]) == 1
    report = json.loads(out.read_text())
    assert set(report) == {"findings", "baselined", "stale_baseline",
                           "rules", "files", "changed_only", "cached"}
    assert report["rules"] == sorted(REGISTRY)
    assert report["files"] == 1
    assert [f["rule"] for f in _trc_findings(report)] == ["TRC001"]
    capsys.readouterr()


# --------------------------------------------------------------------------
# durability seams (SEAM013) and the TileMap/CheckpointManager locks


def test_seam013_fires_on_raw_checkpoint_io_outside_manager(tmp_path):
    """A driver serializing checkpoint payloads itself (instead of going
    through CheckpointManager) bypasses the verify ladder and fires
    SEAM013."""
    files = seam_skeleton()
    files["slate_tpu/drivers/lu.py"] = (
        "from ..robust import health\n"
        "from ..robust.checkpoint import write_payload\n\n\n"
        "def _getrf(a):\n    ok = resolve_abft(None)\n    return a\n\n\n"
        "def getrf(a, opts=None):\n"
        "    write_payload('/tmp/p', {}, {})\n"
        "    return health.finalize(a)\n")
    fs = lint(mini_repo(tmp_path, files), SEAM_IDS)
    assert rule_ids(fs) == {"SEAM013"}
    assert "write_payload" in fs[0].message


def test_seam013_silent_inside_checkpoint_and_via_manager(tmp_path):
    """robust/checkpoint.py is the one sanctioned serialization site; a
    driver that snapshots through CheckpointManager stays clean."""
    files = seam_skeleton()
    files["slate_tpu/robust/checkpoint.py"] = (
        "def write_payload(path, header, arrays):\n"
        "    return 'sha', 0\n\n\n"
        "def read_manifest(d):\n    return {}\n\n\n"
        "class CheckpointManager:\n"
        "    def save(self, op, step, m):\n"
        "        return write_payload('p', {}, {})\n")
    files["slate_tpu/drivers/lu.py"] = (
        "from ..robust import health\n"
        "from ..robust.checkpoint import CheckpointManager\n\n\n"
        "def _getrf(a):\n    ok = resolve_abft(None)\n    return a\n\n\n"
        "def getrf(a, opts=None, checkpoint=None):\n"
        "    if checkpoint is not None:\n"
        "        checkpoint.save('getrf', 0, a)\n"
        "    return health.finalize(a)\n")
    assert lint(mini_repo(tmp_path, files), SEAM_IDS) == []


TILEMAP_FIXTURE = """\
import threading


class TileMap:
    def __init__(self):
        self._lock = threading.Lock()
        self._res = {}
        self._device = {}
        self._pending = {}

    def residency(self, key):
        with self._lock:
            return self._res.get(key, "host")
"""


def test_con001_fires_on_unlocked_tilemap_residency(tmp_path):
    root = mini_repo(tmp_path, {
        "slate_tpu/core/storage.py": TILEMAP_FIXTURE + (
            "\n"
            "    def sneak(self, key, dev):\n"
            "        self._device[key] = dev\n"
            "        self._res[key] = 'device'\n")})
    fs = lint(root, {"CON001"})
    assert fs and all(f.rule == "CON001" for f in fs)
    assert any("_res" in f.message or "_device" in f.message for f in fs)


def test_con001_silent_on_locked_tilemap_residency(tmp_path):
    root = mini_repo(tmp_path, {
        "slate_tpu/core/storage.py": TILEMAP_FIXTURE + (
            "\n"
            "    def move(self, key, dev):\n"
            "        with self._lock:\n"
            "            self._device[key] = dev\n"
            "            self._res[key] = 'device'\n")})
    assert lint(root, {"CON001"}) == []


def test_con001_mutation_of_real_tilemap_is_caught(tmp_path):
    """Acceptance mutation for the out-of-core layer: unlock one
    residency-map access in the real core/storage.py and CON001 fires on
    the TileMap guard set."""
    real = (REPO / "slate_tpu/core/storage.py").read_text()
    good = mini_repo(tmp_path / "good",
                     {"slate_tpu/core/storage.py": real})
    assert lint(good, {"CON001"}) == []
    mutated = real.replace("with self._lock:", "if True:", 1)
    assert mutated != real
    bad = mini_repo(tmp_path / "bad",
                    {"slate_tpu/core/storage.py": mutated})
    fs = lint(bad, {"CON001"})
    assert fs and all(f.rule == "CON001" for f in fs)
    guards = ("_res", "_device", "_pending")
    assert all(any(g in f.message for g in guards) for f in fs)


def test_con001_mutation_of_real_checkpoint_seq_is_caught(tmp_path):
    """Unlock the manifest sequence counter in the real checkpoint.py:
    a torn _seq is exactly the stale-read hazard the verify ladder keys
    on, so the lint must hold the line."""
    real = (REPO / "slate_tpu/robust/checkpoint.py").read_text()
    good = mini_repo(tmp_path / "good",
                     {"slate_tpu/robust/checkpoint.py": real})
    assert lint(good, {"CON001"}) == []
    mutated = real.replace("with self._lock:", "if True:", 1)
    assert mutated != real
    bad = mini_repo(tmp_path / "bad",
                    {"slate_tpu/robust/checkpoint.py": mutated})
    fs = lint(bad, {"CON001"})
    assert fs and all(f.rule == "CON001" for f in fs)
    assert all("_seq" in f.message for f in fs)
