"""ScaLAPACK descriptor round-trip tests vs the reference layout
(ref: scalapack_api/scalapack_slate.hh; numroc/descinit contracts from
scalapack TOOLS)."""

import jax
import numpy as np
import pytest

import slate_tpu as st
from slate_tpu.compat import descinit, from_scalapack, numroc, to_scalapack


def test_numroc_reference_values():
    # hand-checked numroc values (n, nb, iproc, isrc=0, nprocs)
    assert numroc(10, 2, 0, 0, 2) == 6      # blocks 0,2,4 -> 2+2+2
    assert numroc(10, 2, 1, 0, 2) == 4      # blocks 1,3 -> 2+2
    assert numroc(9, 2, 0, 0, 2) == 5       # blocks 0,2,4(ragged 1) -> 2+2+1
    assert numroc(9, 2, 1, 0, 2) == 4
    assert numroc(7, 3, 0, 0, 3) == 3
    assert numroc(7, 3, 1, 0, 3) == 3
    assert numroc(7, 3, 2, 0, 3) == 1
    # total rows always sum to n
    for n in (1, 5, 16, 37):
        for nb in (1, 3, 8):
            for p in (1, 2, 3):
                assert sum(numroc(n, nb, r, 0, p) for r in range(p)) == n


def test_descinit_layout():
    g = st.Grid(2, 2, devices=jax.devices()[:4])
    d = descinit(36, 28, 8, 4, g)
    assert d[0] == 1                        # dense DTYPE_
    assert d[2:6] == (36, 28, 8, 4)
    assert d[6:8] == (0, 0)
    assert d[8] == numroc(36, 8, 0, 0, 2)   # LLD = max local rows


@pytest.mark.parametrize("m,n,mb,nb", [(36, 28, 8, 4), (17, 13, 5, 3)])
def test_round_trip(rng, m, n, mb, nb):
    g = st.Grid(2, 2, devices=jax.devices()[:4])
    a = rng.standard_normal((m, n))
    A = st.Matrix.from_numpy(a, mb, nb, g)
    desc, locals_ = to_scalapack(A)
    # every local piece is exactly numroc-sized
    for (pr, pc), piece in locals_.items():
        assert piece.shape == (numroc(m, mb, pr, 0, g.p),
                               numroc(n, nb, pc, 0, g.q))
    # local pieces match hand-computed block-cyclic slices of the global
    ml0 = numroc(m, mb, 0, 0, 2)
    piece00 = locals_[(0, 0)]
    rows = np.concatenate([np.arange(i, min(i + mb, m))
                           for i in range(0, m, 2 * mb)])
    cols = np.concatenate([np.arange(j, min(j + nb, n))
                           for j in range(0, n, 2 * nb)])
    np.testing.assert_array_equal(piece00, a[np.ix_(rows, cols)])
    B = from_scalapack(desc, locals_, g)
    np.testing.assert_array_equal(B.to_numpy(), a)


@pytest.mark.parametrize("m,n,mb,nb", [(17, 13, 5, 3), (9, 9, 4, 4),
                                       (11, 7, 4, 2)])
def test_round_trip_lld_padded_ragged(rng, m, n, mb, nb):
    """A real single-descriptor ScaLAPACK program allocates every local
    with LLD rows; at ragged sizes the short-block-row processes have
    ml < LLD.  Import must accept those padded shapes and ignore the pad
    rows (the regression: exact-numroc-only shape checks rejected them)."""
    g = st.Grid(2, 2, devices=jax.devices()[:4])
    a = rng.standard_normal((m, n))
    desc, locals_ = to_scalapack(st.Matrix.from_numpy(a, mb, nb, g))
    lld = desc[8]
    assert any(piece.shape[0] < lld for piece in locals_.values()), \
        "case must actually exercise ml < LLD"
    padded = {}
    for (pr, pc), piece in locals_.items():
        buf = np.full((lld, piece.shape[1]), np.nan, piece.dtype, order="F")
        buf[:piece.shape[0]] = piece
        padded[(pr, pc)] = buf
    B = from_scalapack(desc, padded, g)
    np.testing.assert_array_equal(B.to_numpy(), a)


@pytest.mark.parametrize("dtype", [np.float32, np.float64])
def test_round_trip_preserves_dtype(rng, dtype):
    """The interchange format must not silently promote/demote: the
    checkpoint layer round-trips BOTH compute dtypes bit-identically."""
    from slate_tpu.compat.scalapack import gather_locals, scatter_locals
    a = rng.standard_normal((17, 13)).astype(dtype)
    desc, locals_ = scatter_locals(a, 5, 3, 2, 2)
    for piece in locals_.values():
        assert piece.dtype == dtype
    back = gather_locals(desc, locals_, 2, 2)
    assert back.dtype == dtype
    np.testing.assert_array_equal(back, a)


def test_gather_accepts_both_memory_orders(rng):
    """Shape, not stride, defines a local piece: C-ordered copies of the
    Fortran-ordered export gather to the same dense matrix."""
    from slate_tpu.compat.scalapack import gather_locals, scatter_locals
    a = rng.standard_normal((17, 13))
    desc, locals_ = scatter_locals(a, 5, 3, 2, 2)
    as_c = {k: np.ascontiguousarray(v) for k, v in locals_.items()}
    as_f = {k: np.asfortranarray(v) for k, v in locals_.items()}
    np.testing.assert_array_equal(gather_locals(desc, as_c, 2, 2), a)
    np.testing.assert_array_equal(gather_locals(desc, as_f, 2, 2), a)


def test_scatter_gather_pure_numpy_interchange(rng):
    """The checkpoint layer's serialization pair (scatter_locals /
    gather_locals) is pure numpy — no Grid, no devices — and exact at
    ragged sizes on 1x1 and 2x2 process splits.  This layout is PINNED
    as the checkpoint interchange format (robust/checkpoint.py)."""
    from slate_tpu.compat.scalapack import gather_locals, scatter_locals
    for (p, q) in ((1, 1), (2, 2), (2, 1)):
        for (m, n, mb, nb) in ((9, 9, 4, 4), (17, 13, 5, 3), (8, 8, 8, 8)):
            a = rng.standard_normal((m, n))
            desc, locals_ = scatter_locals(a, mb, nb, p, q)
            assert desc[2:6] == (m, n, mb, nb)
            for piece in locals_.values():
                assert piece.flags["F_CONTIGUOUS"]
            np.testing.assert_array_equal(
                gather_locals(desc, locals_, p, q), a)


@pytest.mark.slow
def test_as_checkpoint_format(rng):
    """to_scalapack doubles as a save/load format: solve after a
    round-trip gives identical results."""
    g = st.Grid(2, 2, devices=jax.devices()[:4])
    n = 16
    a = rng.standard_normal((n, n)) + n * np.eye(n)
    b = rng.standard_normal((n, 2))
    desc, saved = to_scalapack(st.Matrix.from_numpy(a, 4, 4, g))
    A2 = from_scalapack(desc, saved, g)
    _, X = st.gesv(A2, st.Matrix.from_numpy(b, 4, 4, g))
    np.testing.assert_allclose(a @ X.to_numpy(), b, atol=1e-10)


def _dist(a, mb, nb, g):
    d, l = to_scalapack(st.Matrix.from_numpy(a, mb, nb, g))
    return d, l


def test_pdgemm_round_trip(rng):
    # routine-level entry point vs numpy (ref: scalapack_gemm.cc)
    from slate_tpu.compat.scalapack_api import pdgemm
    g = st.Grid(2, 2, devices=jax.devices()[:4])
    m, k, n, mb, nb = 24, 20, 16, 4, 4
    a = rng.standard_normal((m, k))
    b = rng.standard_normal((k, n))
    c = rng.standard_normal((m, n))
    da, la = _dist(a, mb, nb, g)
    db, lb = _dist(b, mb, nb, g)
    dc, lc = _dist(c, mb, nb, g)
    dout, lout = pdgemm("n", "n", m, n, k, 2.0, da, la, db, lb, 0.5,
                        dc, lc, g)
    C = from_scalapack(dout, lout, g).to_numpy()
    np.testing.assert_allclose(C, 2.0 * a @ b + 0.5 * c, atol=1e-12)


def test_pdgemm_trans(rng):
    from slate_tpu.compat.scalapack_api import pdgemm
    g = st.Grid(2, 2, devices=jax.devices()[:4])
    m, k, n, nb = 12, 8, 10, 4
    a = rng.standard_normal((k, m))          # op(A) = A^T
    b = rng.standard_normal((k, n))
    c = np.zeros((m, n))
    da, la = _dist(a, nb, nb, g)
    db, lb = _dist(b, nb, nb, g)
    dc, lc = _dist(c, nb, nb, g)
    dout, lout = pdgemm("t", "n", m, n, k, 1.0, da, la, db, lb, 0.0,
                        dc, lc, g)
    C = from_scalapack(dout, lout, g).to_numpy()
    np.testing.assert_allclose(C, a.T @ b, atol=1e-12)


@pytest.mark.slow
def test_pdgesv_pdposv(rng):
    from slate_tpu.compat.scalapack_api import pdgesv, pdposv
    g = st.Grid(2, 2, devices=jax.devices()[:4])
    n, nrhs, nb = 20, 3, 4
    a = rng.standard_normal((n, n)) + n * np.eye(n)
    b = rng.standard_normal((n, nrhs))
    da, la = _dist(a, nb, nb, g)
    db, lb = _dist(b, nb, nb, g)
    dx, lx = pdgesv(n, nrhs, da, la, db, lb, g)
    x = from_scalapack(dx, lx, g).to_numpy()
    np.testing.assert_allclose(a @ x, b, atol=1e-9)
    s = a @ a.T + n * np.eye(n)
    ds, ls = _dist(s, nb, nb, g)
    dx2, lx2 = pdposv("l", n, nrhs, ds, ls, db, lb, g)
    x2 = from_scalapack(dx2, lx2, g).to_numpy()
    np.testing.assert_allclose(s @ x2, b, atol=1e-8)


@pytest.mark.slow
def test_pdsyev(rng):
    from slate_tpu.compat.scalapack_api import pdsyev
    g = st.Grid(2, 2, devices=jax.devices()[:4])
    n, nb = 16, 4
    a = rng.standard_normal((n, n))
    a = (a + a.T) / 2
    da, la = _dist(a, nb, nb, g)
    w, dz, lz = pdsyev("v", "l", n, da, la, g)
    z = from_scalapack(dz, lz, g).to_numpy()
    np.testing.assert_allclose(np.sort(w), np.linalg.eigvalsh(a), atol=1e-9)
    np.testing.assert_allclose(a @ z, z @ np.diag(w), atol=1e-9)
