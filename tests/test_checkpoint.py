"""Durable-factorization tests: out-of-core drivers, panel-boundary
checkpoints, ABFT-verified resume, and the durability chaos sites.

The contract (docs/ROBUSTNESS.md "Durable jobs"):

- ``potrf_ooc`` / ``getrf_ooc`` match their in-core drivers numerically
  and keep the host TileMap authoritative;
- a run killed right after ANY panel-step checkpoint resumes
  BIT-IDENTICAL to the uninterrupted run, both dtypes;
- every torn-write / stale-read / corrupted snapshot is refused with a
  typed ``SlateCheckpointError`` naming the failed rung — never a silent
  restart or a silent wrong answer;
- checkpoint traffic is observable: ``checkpoint_save`` /
  ``checkpoint_restore`` events with step, bytes, verify result and wall
  ms, aggregated by the metrics CLI into the durability table.
"""

import json

import numpy as np
import pytest

import slate_tpu as st
from slate_tpu import obs
from slate_tpu.exceptions import SlateCheckpointError
from slate_tpu.robust import (CheckpointManager, SimulatedPreemption,
                              faults)
from slate_tpu.robust.checkpoint import MANIFEST_NAME, PAYLOAD_NAME

N, NB = 24, 8
NSTEPS = -(-N // NB)


def _spd(rng, n=N, dtype=np.float64):
    a = rng.standard_normal((n, n)).astype(dtype)
    return a @ a.T + n * np.eye(n, dtype=dtype)


def _gen(rng, n=N, dtype=np.float64):
    return rng.standard_normal((n, n)).astype(dtype)


# ------------------------------------------------- out-of-core drivers


@pytest.mark.parametrize("dtype", [np.float32, np.float64])
def test_potrf_ooc_matches_incore(rng, dtype):
    spd = _spd(rng, dtype=dtype)
    L = st.potrf(st.SymmetricMatrix(
        st.TileStorage.from_dense(spd, NB, NB), uplo=st.Uplo.Lower))
    Lo = st.potrf_ooc(spd, nb=NB)
    assert isinstance(Lo, np.ndarray) and Lo.dtype == dtype
    tol = 1e-4 if dtype == np.float32 else 1e-10
    np.testing.assert_allclose(np.tril(np.asarray(L.to_dense())), Lo,
                               atol=tol)


@pytest.mark.parametrize("dtype", [np.float32, np.float64])
def test_getrf_ooc_factors_correctly(rng, dtype):
    a = _gen(rng, dtype=dtype)
    F = st.getrf_ooc(a, nb=NB)
    assert isinstance(F, st.OocLUFactors)
    L = np.tril(F.LU, -1) + np.eye(N, dtype=dtype)
    U = np.triu(F.LU)
    tol = 1e-4 if dtype == np.float32 else 1e-10
    np.testing.assert_allclose(a[F.perm], L @ U, atol=tol)


def test_getrf_ooc_rectangular_and_ragged(rng):
    a = rng.standard_normal((24, 16))
    F = st.getrf_ooc(a, nb=7)                    # ragged panel width
    kmax = 16
    L = np.tril(F.LU[:, :kmax], -1) + np.eye(24, kmax)
    U = np.triu(F.LU[:kmax])
    np.testing.assert_allclose(a[F.perm], L @ U, atol=1e-10)


def test_ooc_error_policy_info_and_raise(rng):
    from slate_tpu import ErrorPolicy, Option
    spd = _spd(rng)
    r, h = st.potrf_ooc(spd, nb=NB,
                        opts={Option.ErrorPolicy: ErrorPolicy.Info})
    assert bool(h.ok)
    with pytest.raises(st.SlateNotPositiveDefiniteError):
        st.potrf_ooc(-spd, nb=NB)
    with pytest.raises(st.SlateSingularError):
        st.getrf_ooc(np.zeros((N, N)), nb=NB)


def test_ooc_copy_stall_is_correct_merely_late(rng):
    """The ooc_copy_stall chaos site stalls host<->device panel copies;
    the result must be unchanged (the TileMap drains pending writebacks
    before any dependent read)."""
    a = _gen(rng)
    base = st.getrf_ooc(a, nb=NB)
    with faults.inject(faults.FaultPlan(site="ooc_copy_stall",
                                        delay_s=0.005)):
        stalled = st.getrf_ooc(a, nb=NB)
    assert np.array_equal(base.LU, stalled.LU)
    assert np.array_equal(base.perm, stalled.perm)


def test_tilemap_residency_and_roundtrip(rng):
    from slate_tpu.core.storage import TileMap
    a = rng.standard_normal((N, N))
    tm = TileMap(a, NB, NB)
    assert tm.residency(0, 0) == "host"
    dev = tm.fetch(0, N, 0, NB)
    assert tm.residency(0, 0) == "device"
    tm.store(0, N, 0, NB, np.asarray(dev) * 2.0)
    assert tm.residency(0, 0) == "dirty"
    tm.drain()
    assert tm.residency(0, 0) == "host"
    expect = a.copy()
    expect[:, :NB] *= 2.0
    np.testing.assert_array_equal(tm.to_dense(), expect)


# ----------------------------------------- kill-at-every-step resume


@pytest.mark.parametrize("dtype", [np.float32, np.float64])
def test_potrf_ooc_resume_bit_identical_every_step(rng, tmp_path, dtype):
    spd = _spd(rng, dtype=dtype)
    base = st.potrf_ooc(spd, nb=NB)
    for kill in range(NSTEPS):
        d = tmp_path / f"k{kill}"
        cm = CheckpointManager(d, every=1, abort_after_step=kill)
        with pytest.raises(SimulatedPreemption):
            st.potrf_ooc(spd, nb=NB, checkpoint=cm)
        res = st.potrf_ooc(None, checkpoint=CheckpointManager(d),
                           resume=True)
        assert np.array_equal(res, base), f"step {kill} not bit-identical"


@pytest.mark.parametrize("dtype", [np.float32, np.float64])
def test_getrf_ooc_resume_bit_identical_every_step(rng, tmp_path, dtype):
    a = _gen(rng, dtype=dtype)
    base = st.getrf_ooc(a, nb=NB)
    for kill in range(NSTEPS):
        d = tmp_path / f"k{kill}"
        cm = CheckpointManager(d, every=1, abort_after_step=kill)
        with pytest.raises(SimulatedPreemption):
            st.getrf_ooc(a, nb=NB, checkpoint=cm)
        res = st.getrf_ooc(None, checkpoint=CheckpointManager(d),
                           resume=True)
        assert np.array_equal(res.LU, base.LU), f"step {kill}"
        assert np.array_equal(res.perm, base.perm), f"step {kill}"


def test_checkpointing_on_vs_off_bit_identical(rng, tmp_path):
    """Snapshotting must never perturb the numerics: every-step
    checkpointing produces the exact bytes of the checkpoint-free run."""
    spd, a = _spd(rng), _gen(rng)
    on = st.potrf_ooc(spd, nb=NB,
                      checkpoint=CheckpointManager(tmp_path / "p", every=1))
    assert np.array_equal(on, st.potrf_ooc(spd, nb=NB))
    Fon = st.getrf_ooc(a, nb=NB,
                       checkpoint=CheckpointManager(tmp_path / "g",
                                                    every=2))
    Foff = st.getrf_ooc(a, nb=NB)
    assert np.array_equal(Fon.LU, Foff.LU)
    assert np.array_equal(Fon.perm, Foff.perm)


def test_resume_without_checkpoint_refuses_missing(tmp_path):
    cm = CheckpointManager(tmp_path)
    assert not cm.has_checkpoint()
    with pytest.raises(SlateCheckpointError) as ei:
        st.potrf_ooc(None, checkpoint=cm, resume=True)
    assert ei.value.reason == "missing"


# ------------------------------------------------- refusal ladder


def _saved_manager(rng, tmp_path, kill=1):
    """A directory holding the step-``kill`` snapshot of a getrf_ooc run."""
    a = _gen(rng)
    cm = CheckpointManager(tmp_path, every=1, abort_after_step=kill)
    with pytest.raises(SimulatedPreemption):
        st.getrf_ooc(a, nb=NB, checkpoint=cm)
    return a


def test_torn_write_refused(rng, tmp_path):
    """ckpt_torn_write truncates the payload while the manifest digest
    describes the full bytes — the size rung must refuse, typed."""
    a = _gen(rng)
    cm = CheckpointManager(tmp_path, every=1, abort_after_step=0)
    with faults.inject(faults.FaultPlan(site="ckpt_torn_write")):
        with pytest.raises(SimulatedPreemption):
            st.getrf_ooc(a, nb=NB, checkpoint=cm)
    with pytest.raises(SlateCheckpointError) as ei:
        st.getrf_ooc(None, checkpoint=CheckpointManager(tmp_path),
                     resume=True)
    assert ei.value.reason == "torn"


def test_stale_read_refused(rng, tmp_path):
    """ckpt_stale_read republishes the manifest against the PREVIOUS
    payload bytes: the digest rung passes (the manifest describes what is
    on disk) but the step/seq skew rung refuses as stale."""
    from slate_tpu.robust.checkpoint import ooc_fingerprint
    a = _gen(rng)
    cm = CheckpointManager(tmp_path, every=1)
    fp = ooc_fingerprint("getrf_ooc", N, N, NB, "float64")
    cm.save("getrf_ooc", 0, a, NB, NB, fp)
    with faults.inject(faults.FaultPlan(site="ckpt_stale_read")):
        cm.save("getrf_ooc", 1, a, NB, NB, fp)   # manifest says step 1,
    with pytest.raises(SlateCheckpointError) as ei:  # payload is step 0
        st.getrf_ooc(None, checkpoint=CheckpointManager(tmp_path),
                     resume=True)
    assert ei.value.reason == "stale"


def test_truncated_payload_refused_torn(rng, tmp_path):
    """A crash that truncates the payload after the manifest committed
    (disk-level tear, no chaos site) fails the size rung."""
    _saved_manager(rng, tmp_path)
    p = tmp_path / PAYLOAD_NAME
    blob = p.read_bytes()
    p.write_bytes(blob[: len(blob) // 3])
    with pytest.raises(SlateCheckpointError) as ei:
        CheckpointManager(tmp_path).load()
    assert ei.value.reason == "torn"


def test_flipped_byte_refused_corrupt(rng, tmp_path):
    """Bit rot in the payload with an intact manifest fails the SHA-256
    rung before any state is deserialized."""
    _saved_manager(rng, tmp_path)
    p = tmp_path / PAYLOAD_NAME
    blob = bytearray(p.read_bytes())
    blob[-1] ^= 0xFF
    p.write_bytes(bytes(blob))
    with pytest.raises(SlateCheckpointError) as ei:
        CheckpointManager(tmp_path).load()
    assert ei.value.reason == "corrupt"


def test_garbled_manifest_refused_corrupt(rng, tmp_path):
    _saved_manager(rng, tmp_path)
    (tmp_path / MANIFEST_NAME).write_text("{not json")
    with pytest.raises(SlateCheckpointError) as ei:
        CheckpointManager(tmp_path).load()
    assert ei.value.reason == "corrupt"


def test_abft_mismatch_refused(rng, tmp_path):
    """A payload whose digest was re-stamped to hide a flipped matrix
    byte still fails the ABFT rung: the matrix no longer reproduces its
    stored row/column checksums.  This is the rung that catches silent
    host-RAM corruption of the offloaded state."""
    import hashlib
    _saved_manager(rng, tmp_path)
    p = tmp_path / PAYLOAD_NAME
    blob = bytearray(p.read_bytes())
    hlen = int.from_bytes(blob[8:16], "little")
    blob[16 + hlen] ^= 0x01                 # first byte of local_0_0
    p.write_bytes(bytes(blob))
    mpath = tmp_path / MANIFEST_NAME
    manifest = json.loads(mpath.read_text())
    manifest["sha256"] = hashlib.sha256(bytes(blob)).hexdigest()
    mpath.write_text(json.dumps(manifest))
    with pytest.raises(SlateCheckpointError) as ei:
        CheckpointManager(tmp_path).load()
    assert ei.value.reason == "abft"


def test_wrong_op_refused_fingerprint(rng, tmp_path):
    _saved_manager(rng, tmp_path)           # holds a getrf_ooc snapshot
    with pytest.raises(SlateCheckpointError) as ei:
        st.potrf_ooc(None, checkpoint=CheckpointManager(tmp_path),
                     resume=True)
    assert ei.value.reason == "fingerprint"


def test_changed_plan_refused_fingerprint(rng, tmp_path):
    """A resuming run whose tuned plan resolution differs from the
    writing run's (here: a forced plan override, in production a retuned
    cache) cannot be bit-identical, so the fingerprint rung refuses."""
    from slate_tpu.tune import TilePlan, plan_override
    _saved_manager(rng, tmp_path)
    with plan_override("getrf_panel",
                       TilePlan(kernel="pallas", nb=NB, bw=16)):
        with pytest.raises(SlateCheckpointError) as ei:
            st.getrf_ooc(None, checkpoint=CheckpointManager(tmp_path),
                         resume=True)
    assert ei.value.reason == "fingerprint"


def test_ensure_fingerprint_direct():
    from slate_tpu.robust.checkpoint import (Checkpoint,
                                             ensure_fingerprint)
    ck = Checkpoint("op", 0, np.zeros((2, 2)), {},
                    {"fingerprint": {"a": 1}})
    ensure_fingerprint(ck, {"a": 1})        # match: no raise
    with pytest.raises(SlateCheckpointError) as ei:
        ensure_fingerprint(ck, {"a": 2})
    assert ei.value.reason == "fingerprint"
    assert ei.value.step == 0


def test_checkpoint_cadence(tmp_path):
    cm = CheckpointManager(tmp_path, every=3)
    assert [s for s in range(7) if cm.should_save(s)] == [0, 3, 6]


# ------------------------------------------------- observability


def test_checkpoint_events_and_metrics_cli(rng, tmp_path, capsys):
    """Save and restore each emit one event (op, step, bytes, verify,
    wall_ms); the metrics pipeline routes them into the durability table
    and the CLI renders it."""
    a = _gen(rng)
    d = tmp_path / "ck"
    with obs.recording() as recs:
        cm = CheckpointManager(d, every=1, abort_after_step=2)
        with pytest.raises(SimulatedPreemption):
            st.getrf_ooc(a, nb=NB, checkpoint=cm)
        st.getrf_ooc(None, checkpoint=CheckpointManager(d), resume=True)
    evs = [e for e in recs if e.get("kind") in ("checkpoint_save",
                                                "checkpoint_restore")]
    saves = [e for e in evs if e["kind"] == "checkpoint_save"]
    restores = [e for e in evs if e["kind"] == "checkpoint_restore"]
    # the resumed run re-snapshots step 2 before finishing it
    assert [e["step"] for e in saves] == [0, 1, 2, 2]
    assert len(restores) == 1 and restores[0]["verify"] == "ok"
    for e in evs:
        assert e["op"] == "getrf_ooc"
        assert e["bytes"] > 0 and e["wall_ms"] >= 0

    path = tmp_path / "events.jsonl"
    path.write_text("".join(json.dumps(e) + "\n" for e in recs))
    summary = obs.summarize([str(path)])
    assert summary["counts"]["checkpoint"] == len(evs)
    row = summary["checkpoint"]["getrf_ooc/checkpoint_save"]
    assert row["count"] == 4 and row["ok"] == 4 and row["refused"] == 0
    assert row["bytes"] > 0 and row["wall_p50_ms"] is not None
    from slate_tpu.obs.__main__ import main as obs_main
    assert obs_main([str(path)]) == 0
    out = capsys.readouterr().out
    assert "durability" in out
    assert "getrf_ooc/checkpoint_save" in out
    assert "getrf_ooc/checkpoint_restore" in out


def test_refusal_emits_typed_restore_event(rng, tmp_path):
    """A refused resume is observable too: the checkpoint_restore event
    carries the failed rung as its verify value."""
    _saved_manager(rng, tmp_path)
    p = tmp_path / PAYLOAD_NAME
    p.write_bytes(p.read_bytes()[:10])
    with obs.recording() as recs:
        with pytest.raises(SlateCheckpointError):
            CheckpointManager(tmp_path).load(op="getrf_ooc")
    (ev,) = [e for e in recs if e.get("kind") == "checkpoint_restore"]
    assert ev["verify"] == "torn"


def test_scalapack_layout_is_the_payload_format(rng, tmp_path):
    """The pinned interchange format: the snapshot's matrix bytes are the
    compat/scalapack scatter of the host state — a ScaLAPACK program
    could consume the payload without a slate-specific decoder."""
    from slate_tpu.compat.scalapack import scatter_locals
    from slate_tpu.robust.checkpoint import ooc_fingerprint
    a = _gen(rng)
    cm = CheckpointManager(tmp_path)
    fp = ooc_fingerprint("getrf_ooc", N, N, NB, "float64")
    cm.save("getrf_ooc", 0, a, NB, NB, fp)
    ck = cm.load(op="getrf_ooc")
    assert ck.step == 0
    np.testing.assert_array_equal(ck.matrix, a)
    desc, locals_ = scatter_locals(a, NB, NB, 1, 1)
    assert tuple(ck.meta["desc"]) == desc
    assert list(ck.meta["desc"])[4:6] == [NB, NB]
