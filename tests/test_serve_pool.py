"""Elastic device pool tests (docs/SERVING.md "Device pool"): multi-
device serving that survives losing a device, plus online ladder
retuning.

The load-bearing guarantees:

- **kill-a-device drill** (the acceptance drill): with K >= 2 pool
  members and a seeded ``serve_device_fail`` chaos plan killing member
  0, every ticket settles with a correct result (zero lost), the
  failed-over results are BIT-IDENTICAL to a no-fault run (the same
  packed batch redispatches the same executable), the sick member is
  quarantined after ``strike_limit`` strikes, a clean canary probe
  readmits it, and the whole sequence is visible as ``serve_device``
  obs records;
- a warm pool is retrace-free per device, pinned warnings-as-errors;
- a wedged member (``serve_device_slow`` past the dispatch deadline)
  fails over the same way — the zombie dispatch's late result is
  dropped by first-write-wins, never double-delivered;
- with one survivor the pool reports ``degraded()`` and keeps serving;
  with none it raises a loud typed ``SlateServeOverloadError``;
- per-device SLO truth: the governor files latencies per member,
  ``overload_fraction`` scales admission capacity by the sick share
  (not the world), and ``obs --slo`` budgets can target
  ``device:<id>`` rows;
- **online retune drill**: a bimodal size stream triggers EXACTLY one
  ladder hot-swap (``serve_retune`` record), subsequent flushes bucket
  on the fitted ladder, and per-batch ``padding_waste`` drops.

Everything here is deterministic on CPU: K members of the pool are the
same CPU device (tests/conftest.py forces 8 virtual devices), chaos
comes from seeded ``robust.faults`` plans with ``device=i`` targeting.
"""

import json
import threading
import time
import warnings

import jax
import numpy as np
import pytest

from slate_tpu import obs, serve
from slate_tpu.exceptions import SlateServeError, SlateServeOverloadError
from slate_tpu.obs import __main__ as obs_cli
from slate_tpu.obs import slo
from slate_tpu.robust import faults


def _rng():
    return np.random.default_rng(177)


def _mk_solve(rng, n, k=2, dtype=np.float32):
    a = rng.standard_normal((n, n)).astype(dtype)
    a += np.eye(n, dtype=dtype) * (4 + np.sqrt(n))
    return a, rng.standard_normal((n, k)).astype(dtype)


def _check_solve(a, b, res, tol=1e-3):
    assert np.allclose(res.x, np.linalg.solve(
        a.astype(np.float64), b.astype(np.float64)), rtol=tol, atol=tol)


def _pool_server(members=2, strike_limit=1, canary_interval_s=30.0,
                 dispatch_timeout_s=None, cache=None, admission=None):
    """A Server over a K-member pool; every member is the same CPU
    device, which shares executables (one compile warms the pool) while
    keeping the member-level failure machinery fully independent."""
    devs = [jax.local_devices()[0]] * members
    pool = serve.DevicePool(
        devs, serve.PoolConfig(strike_limit=strike_limit,
                               canary_interval_s=canary_interval_s,
                               dispatch_timeout_s=dispatch_timeout_s))
    return serve.Server(cache=cache or serve.ExecutableCache(),
                        admission=admission, pool=pool)


def _device_events(recs, event=None):
    out = [e for e in recs if e.get("kind") == "serve_device"]
    if event is not None:
        out = [e for e in out if e.get("event") == event]
    return out


def _batch_events(recs):
    return [e for e in recs if e.get("kind") == "serve_batch"]


# --------------------------------------------------------- pool basics


def test_pool_defaults_to_local_devices():
    pool = serve.DevicePool()
    assert pool.size() == len(jax.local_devices())
    assert pool.healthy_count() == pool.size()
    assert not pool.degraded()


def test_default_server_is_single_member():
    srv = serve.Server(cache=serve.ExecutableCache())
    assert srv.pool.size() == 1
    assert srv.pool.stats()["failovers"] == 0


def test_pool_config_validates():
    with pytest.raises(ValueError, match="strike_limit"):
        serve.PoolConfig(strike_limit=0)
    with pytest.raises(ValueError, match="canary_interval_s"):
        serve.PoolConfig(canary_interval_s=0.0)
    with pytest.raises(ValueError, match="device"):
        faults.FaultPlan("serve_device_fail", device=-1)


def test_round_robin_spreads_groups_across_members():
    """Two groups in one flush land on two distinct members — batches
    are in flight on different devices, not serialized behind one."""
    rng = _rng()
    srv = _pool_server(members=2)
    with obs.recording() as recs:
        for n in (16, 48):          # buckets 32 and 64 -> two groups
            for _ in range(2):
                srv.submit("solve", *_mk_solve(rng, n))
        srv.drain()
    devs = {e["device_id"] for e in _batch_events(recs)}
    assert devs == {0, 1}
    assert all(e["failovers"] == 0 for e in _batch_events(recs))


# -------------------------------------------------- kill-a-device drill


def _serve_once(srv, reqs):
    tickets = [srv.submit(op, a, b) for op, a, b in reqs]
    results = srv.drain()
    return [results[int(t)] for t in tickets]


@pytest.mark.parametrize("kind", ["nan", "inf"])
def test_kill_a_device_drill(kind):
    """The acceptance drill: kill member 0 (non-finite lie or dispatch
    exception), and the SAME packed batch fails over to member 1 with
    zero lost tickets, bit-identical results, quarantine, and canary
    readmission."""
    rng = _rng()
    reqs = [("solve", *_mk_solve(rng, 12)) for _ in range(4)]
    cache = serve.ExecutableCache()

    # baseline: no fault, same cache -> same executable
    base = _serve_once(_pool_server(members=2, cache=cache), reqs)

    srv = _pool_server(members=2, cache=cache)
    plan = faults.FaultPlan("serve_device_fail", kind=kind,
                            transient=True, device=0)
    with obs.recording() as recs:
        with faults.inject(plan):
            got = _serve_once(srv, reqs)

    # zero lost tickets, correct and BIT-IDENTICAL to the no-fault run
    assert len(got) == len(reqs)
    for (op, a, b), res, ref in zip(reqs, base, got):
        assert res is not None and ref is not None
        _check_solve(a, b, res)
        assert res.x.tobytes() == ref.x.tobytes()
        assert bool(res.health.ok) and not res.escalated

    # the failover ladder ran: strike -> quarantine(0) -> survivor(1)
    st = srv.pool.stats()
    assert st["failovers"] == 1 and st["quarantines"] == 1
    fo = _device_events(recs, "failover")
    assert [e["device_id"] for e in fo] == [0]
    assert fo[0]["reason"] == ("nonfinite" if kind == "nan"
                               else "exception")
    assert _device_events(recs, "quarantine")[0]["device_id"] == 0
    batches = _batch_events(recs)
    assert batches and batches[0]["device_id"] == 1
    assert batches[0]["failovers"] == 1
    assert srv.pool.healthy_count() == 1 and srv.pool.degraded()

    # clean canary -> readmission (the transient strike is spent)
    with obs.recording() as recs2:
        assert srv.pool.probe(0)
    assert srv.pool.healthy_count() == 2 and not srv.pool.degraded()
    assert srv.pool.stats()["readmissions"] == 1
    readmit = _device_events(recs2, "readmit")
    assert readmit and readmit[0]["device_id"] == 0
    assert readmit[0]["quarantined_ms"] is not None

    # the readmitted member serves again
    reqs2 = [("solve", *_mk_solve(rng, 12)) for _ in range(2)]
    for (op, a, b), res in zip(reqs2, _serve_once(srv, reqs2)):
        _check_solve(a, b, res)


def test_targeted_chaos_plan_is_not_eaten_by_other_members():
    """FaultPlan(device=1) misses member 0 WITHOUT consuming the
    transient strike — the kill lands on member 1 even when member 0
    reaches the site first."""
    plan = faults.FaultPlan("serve_device_fail", transient=True, device=1)
    with faults.inject(plan):
        assert faults.host_fire("serve_device_fail", device=0) is None
        assert faults.host_fire("serve_device_fail", device=1) is plan
        # spent: exactly one kill per activation
        assert faults.host_fire("serve_device_fail", device=1) is None
    assert faults.host_fire("serve_device_fail", device=1) is None


def test_warm_pool_is_retrace_free_per_device():
    """Warnings-as-errors pin: after one warm pass, repeat flushes on a
    K-member pool trace and compile NOTHING new on any member."""
    rng = _rng()
    srv = _pool_server(members=2)
    reqs = [("solve", *_mk_solve(rng, 16)) for _ in range(3)]
    _serve_once(srv, reqs)                       # warm every member
    traces0 = sum(s["traces"] for s in obs.sentinel_stats().values())
    with warnings.catch_warnings():
        warnings.simplefilter("error", obs.SlateRetraceWarning)
        with obs.recording() as recs:
            for _ in range(4):
                reqs = [("solve", *_mk_solve(rng, 16)) for _ in range(3)]
                for (op, a, b), res in zip(reqs, _serve_once(srv, reqs)):
                    _check_solve(a, b, res)
    assert sum(s["traces"]
               for s in obs.sentinel_stats().values()) == traces0
    assert all(e["retraces"] == 0 and not e["compiled"]
               for e in _batch_events(recs))


def test_wedged_member_deadline_failover():
    """serve_device_slow past the dispatch deadline reads as a wedged
    device: the pool moves on to a survivor; the zombie's late result
    is dropped (first-write-wins), never double-delivered."""
    rng = _rng()
    srv = _pool_server(members=2, dispatch_timeout_s=0.25)
    a, b = _mk_solve(rng, 12)
    _serve_once(srv, [("solve", a, b)])          # warm; rr now at 1
    plan = faults.FaultPlan("serve_device_slow", transient=True,
                            device=1, delay_s=1.5)
    with obs.recording() as recs:
        with faults.inject(plan):
            (res,) = _serve_once(srv, [("solve", a, b)])
    _check_solve(a, b, res)
    fo = _device_events(recs, "failover")
    assert fo and fo[0]["reason"] == "deadline" and fo[0]["device_id"] == 1
    assert srv.pool.stats()["failovers"] == 1
    # let the zombie dispatch thread drain before the test ends
    time.sleep(1.5)
    assert not [t for t in threading.enumerate()
                if t.name.startswith("slate-serve-dispatch")]


def test_canary_flake_refuses_readmission():
    """A flaky canary keeps the sick member quarantined; a later clean
    probe readmits it."""
    rng = _rng()
    srv = _pool_server(members=2)
    reqs = [("solve", *_mk_solve(rng, 12)) for _ in range(2)]
    kill = faults.FaultPlan("serve_device_fail", kind="inf", device=0)
    flake = faults.FaultPlan("serve_canary_flake", device=0)
    with obs.recording() as recs:
        with faults.inject(kill, flake):
            got = _serve_once(srv, reqs)         # member 0 dies
            assert srv.pool.healthy_count() == 1
            assert not srv.pool.probe(0)         # canary flakes
            assert srv.pool.healthy_count() == 1
    for (op, a, b), res in zip(reqs, got):
        _check_solve(a, b, res)
    pf = _device_events(recs, "probe_fail")
    assert pf and pf[0]["device_id"] == 0 and pf[0]["reason"] == "flake"
    assert srv.pool.probe(0)                     # plan gone: clean probe
    assert srv.pool.healthy_count() == 2


def test_pool_exhausted_raises_typed_overload():
    """Every member dead -> loud typed SlateServeOverloadError on the
    drain AND on every ticket; canary probes bring the pool back."""
    rng = _rng()
    srv = _pool_server(members=2)
    a, b = _mk_solve(rng, 12)
    kill = faults.FaultPlan("serve_device_fail", kind="inf")  # any member
    with faults.inject(kill):
        t = srv.submit("solve", a, b)
        with pytest.raises(SlateServeError):
            srv.drain()
        assert isinstance(t.error(), SlateServeError)
        assert srv.pool.healthy_count() == 0
    # recovery: clean canaries readmit both members
    assert srv.pool.probe(0) and srv.pool.probe(1)
    (res,) = _serve_once(srv, [("solve", a, b)])
    _check_solve(a, b, res)


def test_degraded_single_survivor_keeps_serving():
    rng = _rng()
    srv = _pool_server(members=3)
    kill = faults.FaultPlan("serve_device_fail", kind="inf", device=0)
    reqs = [("solve", *_mk_solve(rng, 12)) for _ in range(2)]
    with faults.inject(kill):
        for (op, a, b), res in zip(reqs, _serve_once(srv, reqs)):
            _check_solve(a, b, res)
    assert srv.pool.healthy_count() == 2
    info = srv.health_info()
    assert info["pool"]["devices"] == 3
    assert info["pool"]["healthy"] == 2
    assert not info["degraded"]


def test_background_loop_kill_drill_zero_lost_tickets():
    """The drill under the background flush loop: a transient device
    kill mid-stream loses nothing — every admitted ticket settles with
    a correct result."""
    rng = _rng()
    cfg = serve.AdmissionConfig(flush_occupancy=4, max_batch_delay_ms=10.0)
    srv = _pool_server(members=2, admission=cfg)
    srv.start()
    try:
        probs = [_mk_solve(rng, 12) for _ in range(12)]
        plan = faults.FaultPlan("serve_device_fail", transient=True,
                                device=0)
        with faults.inject(plan):
            tickets = [(a, b, srv.submit("solve", a, b))
                       for a, b in probs]
            for a, b, t in tickets:
                _check_solve(a, b, t.result(timeout=60.0))
    finally:
        srv.shutdown()
    assert srv.pool.stats()["failovers"] >= 1


# ------------------------------------------------ per-device SLO truth


def test_governor_files_per_device_tails():
    gov = slo.LatencyGovernor(budget_ms=100.0)
    for _ in range(20):
        gov.observe(10.0, device=0)
        gov.observe(400.0, device=1)
    assert gov.p99_ms(0) < 100.0 < gov.p99_ms(1)
    assert gov.overloaded(1) and not gov.overloaded(0)
    assert gov.overload_fraction() == 0.5
    p99s = gov.device_p99s()
    assert set(p99s) == {0, 1}


def test_overload_fraction_scales_capacity_not_halves():
    """One slow member out of four trims capacity by an eighth; the
    union-only stream keeps the pre-pool halving."""
    cfg = serve.AdmissionConfig(max_queue=64, slo_budget_ms=100.0)
    q = serve.AdmissionQueue(cfg)
    for dev in range(4):
        for _ in range(10):
            q.governor.observe(400.0 if dev == 0 else 10.0, device=dev)
    assert q.governor.overload_fraction() == 0.25
    assert q.capacity() == int(64 * (1 - 0.25 / 2))    # 56, not 32
    # union-only governor: fraction collapses to the old halving
    q2 = serve.AdmissionQueue(serve.AdmissionConfig(
        max_queue=64, slo_budget_ms=100.0))
    for _ in range(10):
        q2.governor.observe(400.0)
    assert q2.governor.overload_fraction() == 1.0
    assert q2.capacity() == 32


def test_slo_budgets_target_device_rows():
    """aggregate() grows device:<id> rows from device-stamped batches,
    and --slo budgets can fail a single slow member's own row."""
    rng = _rng()
    srv = _pool_server(members=2)
    with obs.recording() as recs:
        for _ in range(3):
            reqs = [("solve", *_mk_solve(rng, n)) for n in (8, 24)
                    for _ in range(2)]
            _serve_once(srv, reqs)
    stats = slo.aggregate(recs)
    dev_rows = [k for k in stats if k.startswith("device:")]
    assert set(dev_rows) == {"device:0", "device:1"}
    assert sum(stats[k]["problems"] for k in dev_rows) == 12
    verdicts = slo.evaluate(stats, {
        "device:0": {"latency_p99_ms": 1e9},
        "device:1": {"problems": 1},
    })
    assert all(v["ok"] for v in verdicts)
    bad = slo.evaluate(stats, {"device:0": {"latency_p99_ms": 1e-9}})
    assert not bad[0]["ok"]


# ---------------------------------------------------- online retuning


def _bimodal_reqs(rng, count, k=2):
    """Sizes 40/96: the geometric ladder buckets them at 64/128; the
    fitted ladder serves 96 at a 96 rung — padded area drops ~30%."""
    out = []
    for i in range(count):
        n = 40 if i % 2 == 0 else 96
        out.append(("solve", *_mk_solve(rng, n, k)))
    return out


def test_online_retune_hot_swap_drill():
    """The retune acceptance drill: a bimodal size stream triggers
    EXACTLY one ladder hot-swap; subsequent flushes bucket on the
    fitted ladder and padding waste drops."""
    rng = _rng()
    cfg = serve.AdmissionConfig(retune_interval_s=1e9,  # tick off: direct
                                retune_min_samples=16,
                                retune_margin=0.02)
    srv = serve.Server(cache=serve.ExecutableCache(), admission=cfg)
    with obs.recording() as recs:
        pre = _bimodal_reqs(rng, 16)
        for (op, a, b), res in zip(pre, _serve_once(srv, pre)):
            _check_solve(a, b, res)
        pre_batches = _batch_events(recs)
        assert all(e["ladder"] == "geometric" for e in pre_batches)
        assert {tuple(e["bucket"]) for e in pre_batches} == \
            {(64, 2), (128, 2)}

        info = srv.retune_now("float32")
        assert info is not None
        assert info["new"] == [64, 96]
        assert info["waste_fitted"] < info["waste_live"]
        # a second retune without fresh evidence is a no-op: the
        # histogram reset and the margin hold — EXACTLY one swap
        assert srv.retune_now("float32") is None

        post = _bimodal_reqs(rng, 16)
        for (op, a, b), res in zip(post, _serve_once(srv, post)):
            _check_solve(a, b, res)
    retunes = [e for e in recs if e.get("kind") == "serve_retune"]
    assert len(retunes) == 1
    post_batches = _batch_events(recs)[len(pre_batches):]
    assert all(e["ladder"] == "retuned" for e in post_batches)
    assert {tuple(e["bucket"]) for e in post_batches} == \
        {(64, 2), (96, 2)}

    def waste(evs):
        return np.mean([e["padding_waste"] for e in evs])

    assert waste(post_batches) < waste(pre_batches)


def test_background_retune_tick_swaps_once():
    """The background loop's retune tick performs the swap off-thread:
    in-flight tickets settle on the old plan, later flushes use the
    fitted ladder, and exactly one serve_retune record is emitted."""
    rng = _rng()
    cfg = serve.AdmissionConfig(flush_occupancy=4, max_batch_delay_ms=5.0,
                                retune_interval_s=0.05,
                                retune_min_samples=16,
                                retune_margin=0.02)
    srv = serve.Server(cache=serve.ExecutableCache(), admission=cfg)
    srv.start()
    try:
        with obs.recording() as recs:
            reqs = _bimodal_reqs(rng, 24)
            tickets = [(a, b, srv.submit(op, a, b)) for op, a, b in reqs]
            for a, b, t in tickets:
                _check_solve(a, b, t.result(timeout=120.0))
            deadline = time.perf_counter() + 30.0
            while (srv.health_info()["retunes"] < 1
                   and time.perf_counter() < deadline):
                time.sleep(0.02)
            assert srv.health_info()["retunes"] == 1
            reqs2 = _bimodal_reqs(rng, 8)
            tickets = [(a, b, srv.submit(op, a, b)) for op, a, b in reqs2]
            for a, b, t in tickets:
                _check_solve(a, b, t.result(timeout=120.0))
    finally:
        srv.shutdown()
    retunes = [e for e in recs if e.get("kind") == "serve_retune"]
    assert len(retunes) == 1
    assert _batch_events(recs)[-1]["ladder"] == "retuned"


def test_cli_serving_table_renders_pool_columns(tmp_path, capsys):
    """The metrics CLI smoke test: a pooled stream with a failover and a
    retune renders the serving table with dev / failovers / retunes
    columns populated (retunes on their own ladder/<dtype> row)."""
    rng = _rng()
    cfg = serve.AdmissionConfig(retune_interval_s=1e9,
                                retune_min_samples=16,
                                retune_margin=0.02)
    devs = [jax.local_devices()[0]] * 2
    pool = serve.DevicePool(devs, serve.PoolConfig(strike_limit=1))
    srv = serve.Server(cache=serve.ExecutableCache(), admission=cfg,
                       pool=pool)
    kill = faults.FaultPlan("serve_device_fail", kind="inf",
                            transient=True, device=0)
    with obs.recording() as recs:
        with faults.inject(kill):
            reqs = _bimodal_reqs(rng, 16)
            for (op, a, b), res in zip(reqs, _serve_once(srv, reqs)):
                _check_solve(a, b, res)
        assert srv.retune_now("float32") is not None
    path = tmp_path / "events.jsonl"
    path.write_text("".join(json.dumps(e) + "\n" for e in recs))

    table = obs.summarize([str(path)])["serve"]
    row = table["solve/float32"]
    assert row["dev"] >= 1 and row["failovers"] == 1
    assert table["ladder/float32"]["retunes"] == 1
    assert obs_cli.main([str(path)]) == 0
    out = capsys.readouterr().out
    assert "dev" in out and "failovers" in out and "retunes" in out
    assert "ladder/float32" in out


def test_compare_classifies_pool_metrics():
    """Pool bench lines get the wide noise band (first-match ordering:
    'pool' before 'serve') and recovery/latency read lower-better."""
    from slate_tpu.obs import compare
    assert compare.noise_pct("serve_pool_problems_per_s") == 20.0
    assert compare.direction("serve_pool_failover_recovery_ms") == "lower"
    assert compare.direction("serve_pool_problems_per_s") == "higher"


def test_retune_respects_margin_hysteresis():
    """A stream the live ladder already serves well never swaps."""
    rng = _rng()
    cfg = serve.AdmissionConfig(retune_interval_s=1e9,
                                retune_min_samples=8, retune_margin=0.05)
    srv = serve.Server(cache=serve.ExecutableCache(), admission=cfg)
    reqs = [("solve", *_mk_solve(rng, 32)) for _ in range(8)]
    _serve_once(srv, reqs)          # n=32 sits exactly on a rung
    assert srv.retune_now("float32") is None
    assert srv.health_info()["retunes"] == 0
