"""gemm driver tests: residual checks vs numpy on single device and on the
virtual 8-device mesh (analog of ref test/test_gemm.cc:192-262 residual
methodology)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import slate_tpu as st


def resid(C, ref):
    ref = np.asarray(ref)
    den = np.linalg.norm(ref) + 1.0
    return np.linalg.norm(np.asarray(C) - ref) / den


@pytest.mark.parametrize("m,n,k,mb", [(32, 32, 32, 8), (30, 18, 25, 8),
                                      (7, 9, 5, 4)])
def test_gemm_single(rng, m, n, k, mb):
    a = rng.standard_normal((m, k))
    b = rng.standard_normal((k, n))
    c = rng.standard_normal((m, n))
    A = st.Matrix.from_numpy(a, mb)
    B = st.Matrix.from_numpy(b, mb)
    C = st.Matrix.from_numpy(c, mb)
    out = st.gemm(2.0, A, B, -0.5, C)
    assert resid(out.to_numpy(), 2.0 * a @ b - 0.5 * c) < 1e-13


@pytest.mark.parametrize("p,q", [(2, 2), (2, 4), (4, 2)])
@pytest.mark.parametrize("m,n,k", [(32, 32, 32), (36, 20, 28), (17, 23, 9)])
def test_gemm_mesh(rng, p, q, m, n, k):
    g = st.Grid(p, q, devices=jax.devices()[: p * q])
    a = rng.standard_normal((m, k))
    b = rng.standard_normal((k, n))
    c = rng.standard_normal((m, n))
    A = st.Matrix.from_numpy(a, 4, 4, g)
    B = st.Matrix.from_numpy(b, 4, 4, g)
    C = st.Matrix.from_numpy(c, 4, 4, g)
    out = st.gemm(1.5, A, B, 2.0, C)
    assert resid(out.to_numpy(), 1.5 * a @ b + 2.0 * c) < 1e-13


def test_gemm_ops_single(rng):
    a = rng.standard_normal((20, 12))
    b = rng.standard_normal((16, 20))
    A = st.Matrix.from_numpy(a, 4)
    B = st.Matrix.from_numpy(b, 4)
    out = st.gemm(1.0, A.T, B.T)
    assert resid(out.to_numpy(), a.T @ b.T) < 1e-13


def test_gemm_ops_mesh(rng):
    g = st.Grid(2, 2, devices=jax.devices()[:4])
    a = rng.standard_normal((20, 12))
    b = rng.standard_normal((16, 20))
    A = st.Matrix.from_numpy(a, 4, 4, g)
    B = st.Matrix.from_numpy(b, 4, 4, g)
    out = st.gemm(1.0, A.T, B.T)
    assert resid(out.to_numpy(), a.T @ b.T) < 1e-13


def test_gemm_complex(rng):
    a = rng.standard_normal((12, 12)) + 1j * rng.standard_normal((12, 12))
    b = rng.standard_normal((12, 12)) + 1j * rng.standard_normal((12, 12))
    A = st.Matrix.from_numpy(a, 4)
    B = st.Matrix.from_numpy(b, 4)
    out = st.gemm(1.0 + 0j, A.H, B)
    assert resid(out.to_numpy(), a.conj().T @ b) < 1e-13


def test_gemm_methods(rng):
    a = rng.standard_normal((16, 8))
    b = rng.standard_normal((8, 16))
    A = st.Matrix.from_numpy(a, 4)
    B = st.Matrix.from_numpy(b, 4)
    for fn in (st.gemmA, st.gemmC):
        assert resid(fn(1.0, A, B).to_numpy(), a @ b) < 1e-13


def test_gemm_under_jit(rng):
    g = st.Grid(2, 2, devices=jax.devices()[:4])
    a = rng.standard_normal((24, 24))
    b = rng.standard_normal((24, 24))
    A = st.Matrix.from_numpy(a, 4, 4, g)
    B = st.Matrix.from_numpy(b, 4, 4, g)

    @jax.jit
    def run(A, B):
        return st.gemm(1.0, A, B)

    out = run(A, B)
    assert resid(out.to_numpy(), a @ b) < 1e-13


def test_gemm_cross_grid(rng):
    """Operands on a different grid than C are redistributed, not scrambled."""
    g = st.Grid(2, 2, devices=jax.devices()[:4])
    a = rng.standard_normal((16, 16))
    b = rng.standard_normal((16, 16))
    c = rng.standard_normal((16, 16))
    A = st.Matrix.from_numpy(a, 4)              # 1x1 grid
    B = st.Matrix.from_numpy(b, 4)
    C = st.Matrix.from_numpy(c, 4, 4, g)        # 2x2 grid
    out = st.gemm(1.0, A, B, 1.0, C)
    assert resid(out.to_numpy(), a @ b + c) < 1e-13
