"""Generator determinism and kind properties (analog of ref
test/matrix_generator.cc checks)."""

import jax
import numpy as np

import slate_tpu as st
from slate_tpu.util.generator import generate_hermitian, generate_matrix


def test_deterministic_across_distributions():
    """Same seed -> same GLOBAL matrix regardless of grid/tile sizes
    (ref: CHANGELOG.md:9-10 determinism guarantee)."""
    a1 = generate_matrix("randn", 20, 14, 4, seed=7).to_numpy()
    g = st.Grid(2, 4, devices=jax.devices()[:8])
    a2 = generate_matrix("randn", 20, 14, 5, 7, seed=7, grid=g).to_numpy()
    np.testing.assert_allclose(a1, a2)


def test_svd_cond():
    A = generate_matrix("svd", 32, 32, 8, seed=1, cond=1e4)
    s = np.linalg.svd(A.to_numpy(), compute_uv=False)
    np.testing.assert_allclose(s[0] / s[-1], 1e4, rtol=1e-8)


def test_poev_spd():
    A = generate_hermitian("poev", 24, 8, seed=2, cond=100.0)
    w = np.linalg.eigvalsh(A.to_numpy())
    assert w.min() > 0
    np.testing.assert_allclose(w.max() / w.min(), 100.0, rtol=1e-8)


def test_kinds_run():
    for kind in ("zeros", "ones", "identity", "jordan", "rand", "rands",
                 "rand_dominant", "chebspec", "heev"):
        generate_matrix(kind, 9, 9, 4, seed=0)
