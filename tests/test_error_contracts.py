"""Tier-1 shim for tools/check_error_contracts.py: the static assertion
that every public factor/solve driver accepts ``opts`` and routes failures
through the robust layer (docs/ROBUSTNESS.md)."""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent
                       / "tools"))

import check_error_contracts  # noqa: E402


def test_error_contracts_hold():
    assert check_error_contracts.check() == []
