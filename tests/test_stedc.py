"""stedc divide & conquer tridiagonal eigensolver (ref: src/stedc.cc
family): LAPACK-grade eigenvalues, orthogonality, and residuals, including
the deflation-heavy and clustered cases that break naive D&C."""

import numpy as np
import pytest

import slate_tpu as st


def _check(d, e, atol_res=1e-11):
    d = np.asarray(d, float)
    e = np.asarray(e, float)
    n = len(d)
    T = np.diag(d)
    if n > 1:
        T += np.diag(e, 1) + np.diag(e, -1)
    w, Z = st.stedc(d, e)
    w, Z = np.asarray(w), np.asarray(Z)
    wr = np.linalg.eigvalsh(T)
    scale = max(1.0, float(np.max(np.abs(wr))))
    assert np.max(np.abs(w - wr)) / scale < 1e-12
    assert np.linalg.norm(Z.T @ Z - np.eye(n)) < 1e-11
    assert np.linalg.norm(T @ Z - Z * w[None, :]) / scale < atol_res


def test_stedc_random(rng):
    _check(rng.standard_normal(100), rng.standard_normal(99))


@pytest.mark.slow
def test_stedc_odd_size(rng):
    _check(rng.standard_normal(97), rng.standard_normal(96))


@pytest.mark.slow
def test_stedc_near_diagonal(rng):
    _check(np.ones(64), np.full(63, 1e-14))


def test_stedc_exact_diagonal():
    _check(np.arange(48.0), np.zeros(47))


@pytest.mark.slow
def test_stedc_glued_wilkinson():
    # three glued W21+ blocks: clustered pairs + weak coupling, the classic
    # D&C deflation stress (ref: stedc_deflate.cc)
    w21d = np.abs(np.arange(-10, 11)).astype(float)
    d = np.concatenate([w21d, w21d, w21d])
    e = np.ones(len(d) - 1)
    e[20] = 1e-8
    e[41] = 1e-8
    _check(d, e)


def test_stedc_clusters():
    d = np.repeat(np.arange(8.0), 16)
    e = 1e-13 * np.ones(127)
    _check(d, e)


def test_stedc_zero_diag(rng):
    _check(np.zeros(32), np.ones(31))


def test_stedc_single():
    w, Z = st.stedc(np.array([3.0]), np.zeros(0))
    assert float(np.asarray(w)[0]) == 3.0


@pytest.mark.slow
def test_stedc_jits(rng):
    import jax
    d = rng.standard_normal(40)
    e = rng.standard_normal(39)
    w1, Z1 = jax.jit(st.stedc)(d, e)
    w2, Z2 = st.stedc(d, e)
    np.testing.assert_allclose(np.asarray(w1), np.asarray(w2), atol=1e-13)


def test_heev_dc_uses_stedc(rng):
    # MethodEig.DC routes chase -> stedc; must agree with the band seam
    n, nb = 24, 4
    a = rng.standard_normal((n, n))
    a = (a + a.T) / 2
    A = st.HermitianMatrix.from_numpy(a, nb, st.Uplo.Lower)
    w, Z = st.heev(A, {st.Option.MethodEig: st.MethodEig.DC})
    w, z = np.asarray(w), Z.to_numpy()
    np.testing.assert_allclose(np.sort(w), np.linalg.eigvalsh(a), atol=1e-10)
    np.testing.assert_allclose(a @ z, z @ np.diag(w), atol=1e-10)


def test_stedc_float32(rng):
    # dtype-calibrated guards: the f32 path must deliver f32-grade
    # accuracy, not overflow the log-space bisection.  NOTE: conftest pins
    # the CPU backend, so this covers f32 arithmetic, not TPU matmul
    # passes — stedc pins default_matmul_precision("highest") internally
    # precisely because the TPU default bf16-pass merge gemms cost ~2e-2
    # of orthogonality (measured on-device; CI cannot see that backend)
    n = 80
    d = rng.standard_normal(n).astype(np.float32)
    e = rng.standard_normal(n - 1).astype(np.float32)
    T = np.diag(d) + np.diag(e, 1) + np.diag(e, -1)
    w, Z = st.stedc(d, e)
    w, Z = np.asarray(w), np.asarray(Z)
    assert np.max(np.abs(w - np.linalg.eigvalsh(T.astype(np.float64)))) < 1e-4
    assert np.linalg.norm(Z.T @ Z - np.eye(n)) < 1e-4
    assert np.linalg.norm(T @ Z - Z * w[None, :]) < 1e-3


def test_stedc_tiny_scale(rng):
    # deflation tolerance is RELATIVE: a 1e-15-scaled problem must keep
    # full relative accuracy (no absolute tol floor)
    n = 48
    d = rng.standard_normal(n) * 1e-15
    e = rng.standard_normal(n - 1) * 1e-15
    T = np.diag(d) + np.diag(e, 1) + np.diag(e, -1)
    w, Z = st.stedc(d, e)
    w = np.asarray(w)
    wr = np.linalg.eigvalsh(T)
    assert np.max(np.abs(w - wr)) / np.max(np.abs(wr)) < 1e-13


@pytest.mark.slow
def test_stedc_mesh_distributed_merge(rng):
    # merge gemms row-sharded over a 2x4 mesh (ref: stedc_merge.cc rank
    # layout); residual and orthogonality at f64 grade
    import jax
    import slate_tpu as st
    n = 96
    g = st.Grid(2, 4, devices=jax.devices()[:8])
    d = rng.standard_normal(n)
    e = rng.standard_normal(n - 1)
    w, Z = st.stedc(d, e, g)
    T = np.diag(d) + np.diag(e, 1) + np.diag(e, -1)
    w, z = np.asarray(w), np.asarray(Z)
    np.testing.assert_allclose(np.sort(w), np.linalg.eigvalsh(T), atol=1e-10)
    assert np.abs(z.T @ z - np.eye(n)).max() < 1e-11
    assert np.abs(T @ z - z * w[None, :]).max() < 1e-10


@pytest.mark.slow
def test_heev_dc_mesh(rng):
    # full mesh heev through the DC route: dist stage 1 + distributed
    # stedc merges + dist back-transform
    import jax
    import slate_tpu as st
    n, nb = 32, 4
    g = st.Grid(2, 2, devices=jax.devices()[:4])
    a = rng.standard_normal((n, n))
    a = (a + a.T) / 2
    A = st.HermitianMatrix.from_numpy(a, nb, st.Uplo.Lower, g)
    w, Z = st.heev(A, {st.Option.MethodEig: st.MethodEig.DC})
    w, z = np.asarray(w), Z.to_numpy()
    np.testing.assert_allclose(np.sort(w), np.linalg.eigvalsh(a), atol=1e-9)
    np.testing.assert_allclose(a @ z, z @ np.diag(w), atol=1e-9)
