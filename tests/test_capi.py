"""Driver C API tests: build libslate_tpu_capi.so + a real C test
program, run it in a subprocess, and check it solves gesv/posv through
the embedded-interpreter tier (ref: src/c_api/wrappers.cc driver C API;
test analog of the reference's c_api unit tests)."""

import os
import pathlib
import shutil
import subprocess

import numpy as np
import pytest

ROOT = pathlib.Path(__file__).resolve().parents[1]

C_MAIN = r"""
#include <stdio.h>
#include <stdlib.h>
#include <math.h>
#include "slate_tpu_capi.h"

int main(void) {
  const int64_t n = 24, nrhs = 3, nb = 8;
  double *a = (double*)malloc(n * n * sizeof(double));
  double *b = (double*)malloc(n * nrhs * sizeof(double));
  double *x = (double*)malloc(n * nrhs * sizeof(double));
  unsigned s = 12345;
  for (int64_t i = 0; i < n * n; i++) {
    s = s * 1103515245u + 12345u;
    a[i] = ((double)(s >> 8) / (1u << 24)) - 0.5;
  }
  for (int64_t i = 0; i < n; i++) a[i * n + i] += (double)n;
  for (int64_t i = 0; i < n * nrhs; i++) {
    s = s * 1103515245u + 12345u;
    b[i] = ((double)(s >> 8) / (1u << 24)) - 0.5;
  }
  if (slate_tpu_init() != 0) { printf("FAIL init\n"); return 1; }
  if (slate_tpu_dgesv(n, nrhs, a, n, b, nrhs, x, nrhs, nb) != 0) {
    printf("FAIL dgesv rc\n"); return 1;
  }
  double err = 0.0;
  for (int64_t i = 0; i < n; i++)
    for (int64_t j = 0; j < nrhs; j++) {
      double r = -b[i * nrhs + j];
      for (int64_t k = 0; k < n; k++) r += a[i * n + k] * x[k * nrhs + j];
      if (fabs(r) > err) err = fabs(r);
    }
  if (err > 1e-8) { printf("FAIL resid %g\n", err); return 1; }
  /* posv on A A^T + n I */
  double *spd = (double*)malloc(n * n * sizeof(double));
  for (int64_t i = 0; i < n; i++)
    for (int64_t j = 0; j < n; j++) {
      double v = (i == j) ? (double)n : 0.0;
      for (int64_t k = 0; k < n; k++) v += a[i * n + k] * a[j * n + k];
      spd[i * n + j] = v;
    }
  if (slate_tpu_dposv(n, nrhs, spd, n, b, nrhs, x, nrhs, nb) != 0) {
    printf("FAIL dposv rc\n"); return 1;
  }
  err = 0.0;
  for (int64_t i = 0; i < n; i++)
    for (int64_t j = 0; j < nrhs; j++) {
      double r = -b[i * nrhs + j];
      for (int64_t k = 0; k < n; k++) r += spd[i * n + k] * x[k * nrhs + j];
      if (fabs(r) > err) err = fabs(r);
    }
  if (err > 1e-7) { printf("FAIL posv resid %g\n", err); return 1; }
  printf("CAPI_OK\n");
  slate_tpu_finalize();
  return 0;
}
"""


@pytest.mark.slow
def test_c_program_solves_through_capi(tmp_path):
    if shutil.which("g++") is None or shutil.which("python3-config") is None:
        pytest.skip("no native toolchain")
    lib = tmp_path / "libslate_tpu_capi.so"
    r = subprocess.run(["make", "-C", str(ROOT / "native"), "capi",
                        f"CAPI={lib}"], capture_output=True, text=True, errors="replace")
    assert r.returncode == 0, r.stderr
    src = tmp_path / "main.c"
    src.write_text(C_MAIN)
    exe = tmp_path / "capi_test"
    r = subprocess.run(
        ["g++", str(src), "-o", str(exe),
         f"-I{ROOT / 'native'}", str(lib), f"-Wl,-rpath,{tmp_path}"],
        capture_output=True, text=True, errors="replace")
    assert r.returncode == 0, r.stderr
    env = dict(os.environ)
    env["PYTHONPATH"] = f"{ROOT}:{env.get('PYTHONPATH', '')}"
    env["JAX_PLATFORMS"] = "cpu"
    env["SLATE_CAPI_PLATFORM"] = "cpu"
    env.pop("XLA_FLAGS", None)
    r = subprocess.run([str(exe)], capture_output=True, text=True, errors="replace", env=env,
                       timeout=600)
    assert r.returncode == 0, f"stdout={r.stdout} stderr={r.stderr[-2000:]}"
    assert "CAPI_OK" in r.stdout


def test_fortran_module_generated():
    # the committed module must match the generator's output exactly
    import sys
    sys.path.insert(0, str(ROOT / "tools"))
    import generate_fortran
    committed = (ROOT / "slate_tpu" / "compat" / "slate_tpu.f90").read_text()
    assert committed == generate_fortran.emit()


def test_fortran_module_compiles():
    fc = shutil.which("gfortran") or shutil.which("flang")
    if fc is None:
        pytest.skip("no Fortran compiler in image")
    import tempfile
    with tempfile.TemporaryDirectory() as d:
        r = subprocess.run(
            [fc, "-c", str(ROOT / "slate_tpu" / "compat" / "slate_tpu.f90"),
             "-o", f"{d}/slate_tpu.o", "-J", d],
            capture_output=True, text=True, errors="replace")
        assert r.returncode == 0, r.stderr
