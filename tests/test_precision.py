"""Mixed-precision tests (robust/precision.py seam, bf16 batched
kernels, the speculative dense rungs, and the certified serving rung).

The load-bearing guarantees:

- ``normalize_dtype`` is the ONE spelling authority: object / np.dtype /
  alias-string forms canonicalize identically everywhere (plan keys,
  bucket ladders, the serve boundary), and unsupported spellings raise
  the typed ``SlateUnsupportedDtypeError`` instead of routing silently;
- the ragged batched Pallas kernels accept bf16 storage and accumulate
  in f32: the bf16 factor matches the f32 factor of the bf16-rounded
  operand at bf16-storage tolerance, never at bf16-accumulation blowup;
- the dense posv/gels speculative rungs (``Option.Speculate`` +
  ``Option.Precision = bf16``) accept well-conditioned problems on the
  certificate and escalate adversarial ones onto a result BIT-IDENTICAL
  to the rung-disabled route;
- the serving precision rung escalates per problem — an ill-conditioned
  member and a Wilkinson growth adversary fail their certificates while
  their batch neighbors ride bf16 — and escalated problems return the
  f32 route's bits exactly;
- a warm server with the rung enabled never retraces, on BOTH the
  vmapped and the ragged Pallas routes (retrace warnings promoted to
  errors, compiled=False on every warm event).
"""

import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import slate_tpu as st
from slate_tpu import Option, Precision, Speculate, obs, serve, tune
from slate_tpu.exceptions import SlateUnsupportedDtypeError
from slate_tpu.internal import batched
from slate_tpu.robust import precision

BF16_EPS = 2.0 ** -8                       # bf16 storage half-ulp scale


@pytest.fixture
def rng():
    return np.random.default_rng(18)


@pytest.fixture
def plan_cache(tmp_path, monkeypatch):
    path = tmp_path / "plans.json"
    monkeypatch.setenv("SLATE_TUNE_CACHE", str(path))
    tune.reload()
    yield path
    tune.reload()


# --------------------------------------------------------- the seam itself


def test_normalize_dtype_is_the_one_spelling_authority():
    want = "bfloat16"
    for spelling in (jnp.bfloat16, jnp.dtype(jnp.bfloat16), "bfloat16",
                     "bf16", jnp.zeros((1,), jnp.bfloat16).dtype):
        assert precision.normalize_dtype(spelling) == want
    assert precision.normalize_dtype("fp32") == "float32"
    assert precision.normalize_dtype(np.float64) == "float64"
    with pytest.raises(SlateUnsupportedDtypeError):
        precision.normalize_dtype("bfloat61")          # typo, not a route
    with pytest.raises(SlateUnsupportedDtypeError):
        precision.normalize_dtype("float16", supported=("float32",
                                                        "bfloat16"))


def test_resolve_precision_is_explicit_opt_in():
    assert precision.resolve_precision(None) is False
    assert precision.resolve_precision({}) is False
    assert precision.resolve_precision(
        {Option.Precision: Precision.Auto}) is False   # Auto = f32 today
    assert precision.resolve_precision(
        {Option.Precision: Precision.Bf16}) is True


def test_round_through_models_bf16_storage(rng):
    x = jnp.asarray(rng.standard_normal((16, 16)), jnp.float32)
    y = precision.round_through(x)
    assert y.dtype == x.dtype
    assert np.allclose(np.asarray(y), np.asarray(x), rtol=BF16_EPS, atol=0)
    # idempotent, and exact on bf16-representable values (identity pads)
    assert np.array_equal(np.asarray(precision.round_through(y)),
                          np.asarray(y))
    assert np.array_equal(np.asarray(precision.round_through(jnp.eye(8))),
                          np.eye(8, dtype=np.float32))


# ----------------------------------------- bf16 batched kernels (tentpole)


def _spd_stack(rng, n, sizes, dtype=np.float32):
    a = np.zeros((len(sizes), n, n), dtype)
    for i, s in enumerate(sizes):
        if s:
            g = rng.standard_normal((s, s)).astype(dtype)
            a[i, :s, :s] = g @ g.T + s * np.eye(s, dtype=dtype)
            idx = np.arange(s, n)
            a[i, idx, idx] = 1.0
    return a


def test_batch_potrf_bf16_storage_f32_accumulation(rng):
    """The bf16 ragged Cholesky: bf16 factor in, bf16 factor out, with
    error at bf16-STORAGE level against the f32 factor of the rounded
    operand — f32 accumulation inside the panels is what keeps the gap
    from compounding with n."""
    n, nb = 32, 16
    sizes = np.array([24, 32, 16], np.int32)
    a32 = _spd_stack(rng, n, sizes)
    al = jnp.asarray(a32).astype(jnp.bfloat16)
    fa, _ = batched.batch_potrf(al, jnp.asarray(sizes), nb=nb, bw=8,
                                interpret=True)
    assert fa.dtype == jnp.bfloat16
    ref = np.linalg.cholesky(np.asarray(al, np.float64))
    got = np.tril(np.asarray(fa, np.float64))
    assert np.allclose(got, ref, rtol=0, atol=8 * BF16_EPS * n)
    # the solve side promotes: x comes back f32 from a bf16 factor
    b = jnp.asarray(rng.standard_normal((len(sizes), n, 2)), jnp.float32)
    y = jax.lax.linalg.triangular_solve(fa.astype(jnp.float32), b,
                                        left_side=True, lower=True)
    assert y.dtype == jnp.float32


def test_batch_getrf_bf16_roundtrip(rng):
    """bf16 ragged NoPiv LU factors in bf16 storage; batch_getrs promotes
    and returns an f32 solution good to IR-seed quality."""
    n, nb = 32, 16
    sizes = np.array([32, 24], np.int32)
    a = np.zeros((2, n, n), np.float32)
    for i, s in enumerate(sizes):
        g = rng.standard_normal((s, s)).astype(np.float32)
        a[i, :s, :s] = g + s * np.eye(s, dtype=np.float32)
        idx = np.arange(s, n)
        a[i, idx, idx] = 1.0
    al = jnp.asarray(a).astype(jnp.bfloat16)
    fa = batched.batch_getrf(al, jnp.asarray(sizes), nb=nb, bw=8,
                             interpret=True)
    assert fa.dtype == jnp.bfloat16
    b = jnp.asarray(rng.standard_normal((2, n, 2)), jnp.float32)
    x = batched.batch_getrs(fa, b)
    assert x.dtype == jnp.float32
    r = np.asarray(b) - a @ np.asarray(x)
    denom = np.linalg.norm(a, axis=(1, 2)) * np.linalg.norm(
        np.asarray(x), axis=(1, 2)) + np.linalg.norm(np.asarray(b),
                                                     axis=(1, 2))
    assert np.all(np.linalg.norm(r, axis=(1, 2)) / denom < 8 * BF16_EPS)


# ------------------------------------ dense speculative rungs (posv/gels)


BF16_SPEC = {Option.Speculate: Speculate.On,
             Option.Precision: Precision.Bf16}


def _spd(rng, n, cond=1.0, dtype=np.float32):
    u, _ = np.linalg.qr(rng.standard_normal((n, n)))
    vals = np.logspace(0, -np.log10(cond), n) if cond > 1 else np.ones(n)
    return ((u * vals) @ u.T).astype(dtype)


def test_posv_bf16_rung_accepts_well_conditioned(rng):
    n, nb = 24, 8
    a = _spd(rng, n, cond=10.0)
    b = rng.standard_normal((n, 2)).astype(np.float32)
    A = st.HermitianMatrix.from_numpy(a, nb)
    B = st.Matrix.from_numpy(b, nb, nb)
    F, X = st.posv(A, B, BF16_SPEC)
    xd = np.asarray(X.to_dense(), np.float64)
    r = np.linalg.norm(a @ xd - b) / (
        np.linalg.norm(a) * np.linalg.norm(xd) + np.linalg.norm(b))
    # accepted on the certificate: f32-level backward error from a bf16
    # factor + 2 f32 IR sweeps
    assert r < 100 * np.finfo(np.float32).eps * n


def test_posv_bf16_rung_escalation_bit_identical(rng):
    """cond ~ 1e7: the bf16 factor cannot seed convergent IR, the
    certificate fails, and bounded_retry lands on the f32 Cholesky
    attempt — the same code the rung-disabled route runs first, so the
    escalated result is bitwise equal to it."""
    n, nb = 24, 8
    a = _spd(rng, n, cond=1e7)
    b = rng.standard_normal((n, 2)).astype(np.float32)
    A = st.HermitianMatrix.from_numpy(a, nb)
    B = st.Matrix.from_numpy(b, nb, nb)
    _, X_rung = st.posv(A, B, BF16_SPEC)
    _, X_plain = st.posv(A, B)
    assert np.array_equal(np.asarray(X_rung.to_dense()),
                          np.asarray(X_plain.to_dense()))


def _graded(rng, m, n, cond, dtype=np.float32):
    u, _ = np.linalg.qr(rng.standard_normal((m, n)))
    v, _ = np.linalg.qr(rng.standard_normal((n, n)))
    return ((u * np.logspace(0, -np.log10(cond), n)) @ v.T).astype(dtype)


def test_gels_bf16_rung_accepts_well_conditioned(rng):
    m, n, nb = 32, 8, 8
    a = _graded(rng, m, n, cond=10.0)
    b = rng.standard_normal((m, 1)).astype(np.float32)
    A = st.Matrix.from_numpy(a, nb)
    B = st.Matrix.from_numpy(b, nb, nb)
    X = st.gels(A, B, BF16_SPEC)
    xd = np.asarray(X.to_dense(), np.float64)[:n]
    grad = np.linalg.norm(a.T.astype(np.float64)
                          @ (a.astype(np.float64) @ xd
                             - b.astype(np.float64)))
    scale = np.linalg.norm(a) ** 2 * max(np.linalg.norm(xd), 1.0)
    assert grad / scale < 100 * np.finfo(np.float32).eps * n


def test_gels_bf16_rung_escalation_bit_identical():
    """cond ~ 1e4 pushes the bf16 CSNE contraction rate past 1: the rung
    cannot certify and escalates onto the CholQR2 attempt — the identical
    first attempt of the Speculate-only ladder, so the escalated result
    matches it bit for bit.  The escalation is pinned via the flight
    recorder, not assumed."""
    m, n, nb = 32, 8, 8
    grng = np.random.default_rng(27)       # seed where the cert fails
    a = _graded(grng, m, n, cond=1e4)
    b = grng.standard_normal((m, 1)).astype(np.float32)
    A = st.Matrix.from_numpy(a, nb)
    B = st.Matrix.from_numpy(b, nb, nb)
    with obs.recording() as ev_rung:
        X_rung = st.gels(A, B, BF16_SPEC)
    with obs.recording() as ev_spec:
        X_spec = st.gels(A, B, {Option.Speculate: Speculate.On})
    assert [e["path"] for e in ev_rung
            if e.get("path")] == ["escalated:cholqr2"]
    assert [e["path"] for e in ev_spec
            if e.get("path")] == ["speculated:cholqr2"]
    assert np.array_equal(np.asarray(X_rung.to_dense()),
                          np.asarray(X_spec.to_dense()))


# --------------------------------------------- the certified serving rung


BF16_SERVE = {Option.Precision: Precision.Bf16}


def _mk_chol(rng, n, k, cond=1.0):
    return _spd(rng, n, cond), rng.standard_normal((n, k)).astype(
        np.float32)


def _mk_solve(rng, n, k):
    a = rng.standard_normal((n, n)).astype(np.float32)
    a += np.eye(n, dtype=np.float32) * 4
    return a, rng.standard_normal((n, k)).astype(np.float32)


def _wilkinson(n):
    a = np.tril(-np.ones((n, n), np.float32), -1) + np.eye(n,
                                                           dtype=np.float32)
    a[:, -1] = 1.0
    return a


def _workload(rng):
    """One bucket's worth per op: well-conditioned members plus two
    adversaries (indices returned) that MUST fail the bf16 certificate."""
    reqs, adversarial = [], []
    for _ in range(3):
        reqs.append(("chol_solve", *_mk_chol(rng, 24, 2)))
        reqs.append(("solve", *_mk_solve(rng, 24, 2)))
    adversarial.append(len(reqs))
    reqs.append(("chol_solve", *_mk_chol(rng, 24, 2, cond=1e6)))
    adversarial.append(len(reqs))
    reqs.append(("solve", _wilkinson(24),
                 rng.standard_normal((24, 2)).astype(np.float32)))
    return reqs, adversarial


def _residual_ok(req, res):
    op, a, b = req
    a64, b64 = a.astype(np.float64), b.astype(np.float64)
    x = np.asarray(res.x, np.float64)
    r = np.linalg.norm(a64 @ x - b64) / (
        np.linalg.norm(a64) * np.linalg.norm(x) + np.linalg.norm(b64))
    return r < 100 * np.finfo(np.float32).eps * a.shape[1]


def test_serve_bf16_rung_certifies_and_isolates_escalation(rng):
    """The serving acceptance drill: with the rung on, every result still
    meets the f32 certificate; the ill-conditioned member and the
    Wilkinson growth adversary escalate; their well-conditioned batch
    neighbors ride bf16 (escalated=False) — per-problem isolation."""
    reqs, adversarial = _workload(rng)
    srv = serve.Server(opts=BF16_SERVE, cache=serve.ExecutableCache())
    results = srv.serve_batch(reqs)
    assert len(results) == len(reqs)
    for i, (req, res) in enumerate(zip(reqs, results)):
        if i not in adversarial:
            # neighbors converge on bf16 and still meet the f32 cert
            assert res.health.converged and _residual_ok(req, res)
    for i in adversarial:
        assert results[i].escalated, "adversary must fail the certificate"
    # the ill-conditioned SPD member converges once escalated to f32; the
    # Wilkinson growth adversary defeats NoPiv LU in f32 too and is
    # honestly reported unconverged — escalation, not a silent wrong x
    assert results[adversarial[0]].health.converged
    assert _residual_ok(reqs[adversarial[0]], results[adversarial[0]])
    neighbors = [r for i, r in enumerate(results) if i not in adversarial]
    assert neighbors and not any(r.escalated for r in neighbors)


def test_serve_bf16_escalated_results_bit_identical_to_f32_route(rng):
    """Escalated problems land on the f32 ladder's result computed by the
    UNCHANGED f32 code — bitwise equal to serving with the rung off."""
    reqs, adversarial = _workload(rng)
    rung = serve.Server(opts=BF16_SERVE,
                        cache=serve.ExecutableCache()).serve_batch(reqs)
    plain = serve.Server(cache=serve.ExecutableCache()).serve_batch(reqs)
    for i in adversarial:
        assert rung[i].escalated
        assert np.array_equal(np.asarray(rung[i].x),
                              np.asarray(plain[i].x))


def _serve_events(records):
    return [e for e in records if e.get("kind") == "serve_batch"]


def _assert_warm_is_retrace_free(srv, reqs):
    with obs.recording() as cold:
        srv.serve_batch(reqs)
    cold_ev = _serve_events(cold)
    assert cold_ev and all(e["compiled"] for e in cold_ev)
    entries0 = srv.cache.stats()["entries"]
    with warnings.catch_warnings():
        warnings.simplefilter("error", obs.SlateRetraceWarning)
        with obs.recording() as warm:
            results = srv.serve_batch(reqs)
    warm_ev = _serve_events(warm)
    assert len(warm_ev) == len(cold_ev)
    assert not any(e["compiled"] for e in warm_ev)
    assert all(e["retraces"] == 0 for e in warm_ev)
    assert srv.cache.stats()["entries"] == entries0
    return results


def _retrace_workload(rng):
    """The escalation drill minus the Wilkinson member: a poison request
    (escalated AND unhealthy) takes the quarantine's solo-retry path,
    whose second retry is legitimately a cache hit even cold — the
    zero-retrace drill wants steady serving, so it keeps the escalating
    but *convergent* ill-conditioned SPD adversary only."""
    reqs, adversarial = _workload(rng)
    del reqs[adversarial[1]]
    return reqs, adversarial[:1]


def test_serve_bf16_warm_zero_retrace_vmapped_route(rng):
    """Rung enabled, no Pallas plans: the bf16 attempt and its f32 ladder
    share the one fn(a, b, sizes) executable — the warm repeat is all
    cache hits under warnings-as-errors."""
    reqs, _ = _retrace_workload(rng)
    reqs.append(("least_squares_solve",
                 _graded(rng, 34, 24, cond=10.0),
                 rng.standard_normal((34, 2)).astype(np.float32)))
    srv = serve.Server(opts=BF16_SERVE, cache=serve.ExecutableCache())
    results = _assert_warm_is_retrace_free(srv, reqs)
    assert len(results) == len(reqs)


def test_serve_bf16_warm_zero_retrace_ragged_route(rng, plan_cache):
    """Rung enabled WITH Pallas plans persisted under both the f32 and
    bf16 plan keys: the fast rung factors through the bf16 ragged batched
    kernels, the escalation target through the f32 ones, and the warm
    server still never retraces."""
    for op in ("batch_potrf", "batch_getrf", "batch_geqrf"):
        for dtype in ("float32", "bfloat16"):
            tune.record_plan(op, 32, dtype, tune.TilePlan("pallas", 16, 8))
    reqs, adversarial = _retrace_workload(rng)
    srv = serve.Server(opts=BF16_SERVE, cache=serve.ExecutableCache())
    results = _assert_warm_is_retrace_free(srv, reqs)
    for req, res in zip(reqs, results):
        assert _residual_ok(req, res)
    for i in adversarial:
        assert results[i].escalated


def test_serve_bf16_operands_take_the_rung_and_demote_back(rng):
    """bf16 request dtype: served through the rung unconditionally
    (promoted working copies), results demoted back to bf16."""
    a, b = _mk_chol(rng, 16, 2)
    req = ("chol_solve", jnp.asarray(a).astype(jnp.bfloat16),
           jnp.asarray(b).astype(jnp.bfloat16))
    srv = serve.Server(cache=serve.ExecutableCache())
    (res,) = srv.serve_batch([req])
    assert np.asarray(res.x).dtype == jnp.bfloat16
    x = np.asarray(res.x, np.float64)
    r = np.linalg.norm(a @ x - b) / (
        np.linalg.norm(a) * np.linalg.norm(x) + np.linalg.norm(b))
    assert r < 100 * BF16_EPS                  # bf16-storage certificate


def test_serve_boundary_rejects_unsupported_dtype(rng):
    """fp16 is deliberately absent until a driver certifies it: the gate
    is normalize_dtype's typed error, surfaced through the flush-failure
    wrapper rather than a silent slow-route fallback."""
    from slate_tpu.exceptions import SlateServeError
    a = np.eye(8, dtype=np.float16)
    b = np.ones((8, 1), np.float16)
    srv = serve.Server(cache=serve.ExecutableCache())
    with pytest.raises(SlateServeError, match="float16 not supported"):
        srv.serve_batch([("solve", a, b)])


# -------------------------------------------------- dtype-keyed tune plans


def test_plan_key_normalizes_spellings():
    from slate_tpu.tune.plans import plan_key
    assert plan_key(64, jnp.bfloat16) == plan_key(64, "bf16")
    assert plan_key(64, "fp32") == plan_key(64, np.float32)
    with pytest.raises(SlateUnsupportedDtypeError):
        plan_key(64, "bfloat61")


def test_candidates_open_bf16_only_for_batch_ops():
    from slate_tpu.tune import autotune
    for op in ("batch_potrf", "batch_getrf", "batch_geqrf"):
        kinds = {p.kernel for p in autotune.candidates(op, 256, "bfloat16")}
        assert "pallas" in kinds
    # single-shot kernels stay f32-only; f64 is XLA-only everywhere
    assert {p.kernel for p in autotune.candidates("potrf_tile", 256,
                                                  "bfloat16")} == {"xla"}
    assert {p.kernel for p in autotune.candidates("batch_potrf", 256,
                                                  "float64")} == {"xla"}


def test_per_dtype_chip_peak_and_override():
    from slate_tpu.obs import flops
    with flops.peak_override(1e12):
        # the override pins EVERY dtype, so bf16 and f32 MFU agree
        assert flops.mfu(5e11, 1.0, "bfloat16") == pytest.approx(0.5)
        assert flops.mfu(5e11, 1.0, jnp.float32) == pytest.approx(0.5)
    # float64 is deliberately absent from the peak table: mfu reads n/a
    # rather than inventing a peak the MXU does not have
    assert flops.mfu(5e11, 1.0, "float64") is None
