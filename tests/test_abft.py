"""ABFT checksum layer tests (robust/abft.py + Option.Abft wiring).

Coverage map:

- primitives: sum_check / tile_sum_check / left_product_check detect,
  locate, and correct a single strike, and REFUSE multi-element strikes;
- fault targeting: FaultPlan.tile confines a strike to one tile and an
  out-of-range tile is a miss;
- drivers: gesv/posv with a single injected bitflip locate the struck
  tile exactly, repair in place, and report ``abft_corrected == 1`` with
  ``h.ok`` — eager, jit, and mesh;
- double strikes are detected but NOT mis-corrected (``detected >
  corrected``, ``~h.ok``), and with Option.UseFallbackSolver the
  recovery ladder's retry-same-method rung (below method escalation)
  saves a transient double strike;
- gemm/trsm: checksum verification is SILENT repair — a struck SUMMA
  accumulator tile comes back clean with no API change;
- transient plans are consumed at RUN time, once per activation — a
  retrace at a second shape neither eats nor re-fires the strike.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import slate_tpu as st
from slate_tpu.core.storage import TileStorage
from slate_tpu.options import Option
from slate_tpu.robust import abft, faults

INFO = {Option.ErrorPolicy: "info", Option.Abft: "on"}


def _site(h):
    return int(h.abft_site) >> 16, int(h.abft_site) & 0xFFFF


def _counts(h):
    return int(h.abft_detected), int(h.abft_corrected)


# ------------------------------------------------------------ primitives

def test_sum_check_clean_and_single_strike(rng):
    a = rng.standard_normal((12, 8))
    x, ev = abft.sum_check(jnp.asarray(a), jnp.sum(a, axis=1),
                           jnp.sum(a, axis=0))
    assert int(ev.detected) == 0 and int(ev.site) == -1
    for payload in (np.nan, np.inf, 2.0**80):
        bad = a.copy()
        bad[5, 3] = payload
        x, ev = abft.sum_check(jnp.asarray(bad), jnp.sum(a, axis=1),
                               jnp.sum(a, axis=0), nb=4)
        assert int(ev.detected) == 1 and int(ev.corrected) == 1
        assert int(ev.site) == abft.site_code(1, 0)  # element (5,3)//4
        np.testing.assert_allclose(np.asarray(x), a, atol=1e-10)


def test_sum_check_refuses_double_strike(rng):
    a = rng.standard_normal((12, 8))
    bad = a.copy()
    bad[2, 1] = np.nan
    bad[7, 5] = np.nan
    x, ev = abft.sum_check(jnp.asarray(bad), jnp.sum(a, axis=1),
                           jnp.sum(a, axis=0))
    assert int(ev.detected) == 1 and int(ev.corrected) == 0
    # refused: the data is left as-is, never silently mangled
    assert np.isnan(np.asarray(x)[2, 1]) and np.isnan(np.asarray(x)[7, 5])


def test_tile_sum_check_locates_struck_tile(rng):
    a = rng.standard_normal((3, 2, 4, 4))
    exp_r, exp_c = jnp.sum(a, axis=3), jnp.sum(a, axis=2)
    bad = a.copy()
    bad[2, 1, 0, 3] = 2.0**90
    t4, ev, ti, tj = abft.tile_sum_check(jnp.asarray(bad), exp_r, exp_c)
    assert (int(ti), int(tj)) == (2, 1)
    assert int(ev.detected) == 1 and int(ev.corrected) == 1
    np.testing.assert_allclose(np.asarray(t4), a, atol=1e-10)


@pytest.mark.parametrize("payload", [np.nan, np.inf, 2.0**80])
def test_left_product_check_payloads(rng, payload):
    m, ncol = 8, 6
    lmat = np.tril(rng.standard_normal((m, m))) + m * np.eye(m)
    x = rng.standard_normal((m, ncol))
    r = lmat @ x
    bad = x.copy()
    bad[4, 2] = payload
    x2, det, cor, i0, j0 = abft.left_product_check(
        jnp.asarray(lmat), jnp.asarray(bad),
        jnp.sum(r, axis=1), jnp.sum(r, axis=0), unit=False)
    assert bool(det) and bool(cor)
    assert (int(i0), int(j0)) == (4, 2)
    np.testing.assert_allclose(np.asarray(x2), x, atol=1e-9)


def test_fault_tile_targeting_and_miss():
    plan = faults.FaultPlan("input", kind="nan", tile=(1, 2), nb=4)
    y = np.asarray(faults.corrupt(jnp.zeros((12, 16)), plan))
    rows, cols = np.nonzero(np.isnan(y))
    assert len(rows) == 1
    assert 4 <= rows[0] < 8 and 8 <= cols[0] < 12
    y4 = np.asarray(faults.corrupt(
        jnp.zeros((2, 3, 4, 4)), faults.FaultPlan("input", kind="inf",
                                                  tile=(0, 1))))
    assert np.isinf(y4[0, 1]).sum() == 1 and np.isinf(y4).sum() == 1
    miss = faults.FaultPlan("input", kind="nan", tile=(9, 0), nb=4)
    assert np.isfinite(np.asarray(faults.corrupt(jnp.zeros((12, 16)),
                                                 miss))).all()


# ------------------------------------------------- dense gesv/posv paths

def _dense_problem(rng, n=48, nb=16):
    a = rng.standard_normal((n, n)) + n * np.eye(n)
    b = rng.standard_normal((n, 2))
    return a, b


def test_gesv_abft_clean_zero_counters(rng):
    a, b = _dense_problem(rng)
    F, X, h = st.gesv(st.Matrix.from_numpy(a, 16, 16),
                      st.Matrix.from_numpy(b, 16, 16), INFO)
    assert _counts(h) == (0, 0) and int(h.abft_site) == -1
    assert bool(h.ok)
    np.testing.assert_allclose(X.to_numpy(), np.linalg.solve(a, b),
                               atol=1e-9)


@pytest.mark.parametrize("mode", ["eager", "jit"])
def test_gesv_single_bitflip_located_and_corrected(rng, mode):
    n, nb = 48, 16
    a, b = _dense_problem(rng, n, nb)
    plan = faults.FaultPlan("post_panel", kind="bitflip", seed=5,
                            tile=(n // nb - 1, 0), nb=nb)

    def run(a, b):
        F, X, h = st.gesv(st.Matrix(TileStorage.from_dense(a, nb, nb)),
                          st.Matrix(TileStorage.from_dense(b, nb, nb)),
                          INFO)
        return X.to_dense(), h

    with faults.inject(plan):
        x, h = (jax.jit(run) if mode == "jit" else run)(
            jnp.asarray(a), jnp.asarray(b))
    assert _counts(h) == (1, 1)
    assert _site(h) == (2, 0)              # the injected panel tile
    assert bool(h.ok)                      # no escalation was needed
    np.testing.assert_allclose(np.asarray(x), np.linalg.solve(a, b),
                               atol=1e-9)


@pytest.mark.parametrize("kind", ["nan", "inf", "bitflip"])
def test_posv_transient_strike_corrected(rng, kind):
    n, nb = 48, 16
    a, b = _dense_problem(rng, n, nb)
    hpd = a @ a.T / n + n * np.eye(n)
    plan = faults.FaultPlan("post_panel", kind=kind, seed=7, transient=True)
    with faults.inject(plan):
        L, X, h = st.posv(st.HermitianMatrix.from_numpy(hpd, nb),
                          st.Matrix.from_numpy(b, nb, nb), INFO)
    assert _counts(h) == (1, 1)
    assert bool(h.ok)
    np.testing.assert_allclose(X.to_numpy(), np.linalg.solve(hpd, b),
                               atol=1e-8)


@pytest.mark.parametrize("mode", ["eager", "jit"])
@pytest.mark.parametrize("kind", ["nan", "inf", "bitflip"])
def test_gesv_double_strike_detected_not_corrected(rng, kind, mode):
    n, nb = 48, 16
    a, b = _dense_problem(rng, n, nb)
    plan = faults.FaultPlan("post_panel", kind=kind, seed=5, count=2,
                            tile=(n // nb - 1, 0), nb=nb)

    def run(a, b):
        F, X, h = st.gesv(st.Matrix(TileStorage.from_dense(a, nb, nb)),
                          st.Matrix(TileStorage.from_dense(b, nb, nb)),
                          INFO)
        return X.to_dense(), h

    with faults.inject(plan):
        _, h = (jax.jit(run) if mode == "jit" else run)(
            jnp.asarray(a), jnp.asarray(b))
    det, cor = _counts(h)
    assert det >= 1 and cor < det          # refused, never mis-corrected
    assert not bool(h.ok)                  # surfaces as a health failure
    if kind == "bitflip":
        assert (det, cor) == (1, 0)


def test_gesv_transient_double_strike_saved_by_retry_rung(rng):
    """The new ladder rung: localized repair failed (two struck elements),
    so recovery retries the SAME method once — the transient strike is
    spent, the retry is clean — BELOW any method escalation."""
    n, nb = 48, 16
    a, b = _dense_problem(rng, n, nb)
    A = st.Matrix.from_numpy(a, nb, nb)
    B = st.Matrix.from_numpy(b, nb, nb)
    plan = faults.FaultPlan("post_panel", kind="bitflip", seed=5, count=2,
                            transient=True, tile=(n // nb - 1, 0), nb=nb)
    # with the ladder disabled the double strike stays a failure
    with faults.inject(plan):
        _, _, h0 = st.gesv(A, B, {**INFO, Option.UseFallbackSolver: False})
    assert not bool(h0.ok)
    with faults.inject(plan):
        F, X, h = st.gesv(A, B, {**INFO, Option.UseFallbackSolver: True})
    assert bool(h.ok)
    assert _counts(h) == (0, 0)            # the clean retry's health
    np.testing.assert_allclose(X.to_numpy(), np.linalg.solve(a, b),
                               atol=1e-9)


def test_posv_transient_double_strike_retries_cholesky(rng):
    """posv's retry rung keeps the CHOLESKY factor (no hesv/gesv
    escalation): the returned factor object stays triangular."""
    n, nb = 48, 16
    a, b = _dense_problem(rng, n, nb)
    hpd = a @ a.T / n + n * np.eye(n)
    plan = faults.FaultPlan("post_panel", kind="bitflip", seed=3, count=2,
                            transient=True)
    with faults.inject(plan):
        F, X, h = st.posv(st.HermitianMatrix.from_numpy(hpd, nb),
                          st.Matrix.from_numpy(b, nb, nb),
                          {**INFO, Option.UseFallbackSolver: True})
    assert bool(h.ok)
    assert isinstance(F, st.TriangularMatrix)
    np.testing.assert_allclose(X.to_numpy(), np.linalg.solve(hpd, b),
                               atol=1e-8)


def test_transient_strike_survives_retrace(rng):
    """Satellite regression: transient plans are consumed when the
    computation RUNS, not when it is traced.  Tracing the same jitted
    driver at a second shape under one activation must not re-fire (or
    have pre-eaten) the single strike."""
    nb = 8
    opts = INFO

    @jax.jit
    def solve(a, b):
        F, X, h = st.gesv(st.Matrix(TileStorage.from_dense(a, nb, nb)),
                          st.Matrix(TileStorage.from_dense(b, nb, nb)),
                          opts)
        return X.to_dense(), h.abft_detected, h.abft_corrected

    def mk(n):
        a = rng.standard_normal((n, n)) + n * np.eye(n)
        b = rng.standard_normal((n, 2))
        return a, b

    a1, b1 = mk(32)
    a2, b2 = mk(40)
    plan = faults.FaultPlan("post_panel", kind="bitflip", seed=9,
                            transient=True)
    with faults.inject(plan):
        x1, d1, c1 = solve(jnp.asarray(a1), jnp.asarray(b1))
        x2, d2, c2 = solve(jnp.asarray(a2), jnp.asarray(b2))  # retrace
    assert (int(d1), int(c1)) == (1, 1)    # the one strike, repaired
    assert (int(d2), int(c2)) == (0, 0)    # spent — no second strike
    np.testing.assert_allclose(np.asarray(x1), np.linalg.solve(a1, b1),
                               atol=1e-9)
    np.testing.assert_allclose(np.asarray(x2), np.linalg.solve(a2, b2),
                               atol=1e-9)


# ------------------------------------------------------------ mesh paths

def _mesh_grid(p=2, q=2):
    return st.Grid(p, q, devices=jax.devices()[: p * q])


def test_mesh_gesv_abft_clean_and_panel_strike(rng):
    n, nb = 24, 4
    g = _mesh_grid()
    a = rng.standard_normal((n, n)) + n * np.eye(n)
    b = rng.standard_normal((n, 3))
    A = st.Matrix.from_numpy(a, nb, nb, g)
    B = st.Matrix.from_numpy(b, nb, nb, g)
    _, X, h = st.gesv(A, B, INFO)
    assert _counts(h) == (0, 0) and bool(h.ok)
    plan = faults.FaultPlan("post_panel", kind="bitflip", seed=11,
                            tile=(n // nb - 1, 0), nb=nb)
    with faults.inject(plan):
        _, X, h = st.gesv(A, B, INFO)
    assert _counts(h) == (1, 1)
    assert _site(h) == (n // nb - 1, 0)
    assert bool(h.ok)
    np.testing.assert_allclose(X.to_numpy(), np.linalg.solve(a, b),
                               atol=1e-8)


def test_mesh_posv_abft_collective_strike(rng):
    n, nb = 24, 4
    g = _mesh_grid()
    a = rng.standard_normal((n, n)) + n * np.eye(n)
    hpd = a @ a.T / n + n * np.eye(n)
    b = rng.standard_normal((n, 3))
    Ah = st.HermitianMatrix.from_numpy(hpd, nb, grid=g)
    Bm = st.Matrix.from_numpy(b, nb, nb, g)
    _, X, h = st.posv(Ah, Bm, INFO)
    assert _counts(h) == (0, 0) and bool(h.ok)
    plan = faults.FaultPlan("post_collective", kind="bitflip", seed=3,
                            tile=(1, 0))
    with faults.inject(plan):
        _, X, h = st.posv(Ah, Bm, INFO)
    assert _counts(h) == (1, 1)
    assert _site(h) == (1, 0)              # the struck broadcast tile
    assert bool(h.ok)
    np.testing.assert_allclose(X.to_numpy(), np.linalg.solve(hpd, b),
                               atol=1e-8)


# --------------------------------------------- gemm/trsm (silent repair)

@pytest.mark.parametrize("kind", ["nan", "inf", "bitflip"])
def test_mesh_gemm_summa_silent_repair(rng, kind):
    g = _mesh_grid()
    a = rng.standard_normal((24, 20))
    b = rng.standard_normal((20, 28))
    A = st.Matrix.from_numpy(a, 4, 4, g)
    B = st.Matrix.from_numpy(b, 4, 4, g)
    plan = faults.FaultPlan("post_collective", kind=kind, seed=3,
                            tile=(1, 2))
    with faults.inject(plan):
        C = st.gemm(1.0, A, B, opts={Option.Abft: "on"})
        Cr = st.gemm(1.0, A, B)            # unprotected control
    assert np.abs(C.to_numpy() - a @ b).max() < 1e-10
    assert not np.abs(Cr.to_numpy() - a @ b).max() < 1e-10


def test_gemm_trsm_abft_clean_no_false_positive(rng):
    a = rng.standard_normal((24, 20))
    b = rng.standard_normal((20, 28))
    C = st.gemm(1.0, st.Matrix.from_numpy(a, 4),
                st.Matrix.from_numpy(b, 4), opts={Option.Abft: "on"})
    assert np.abs(C.to_numpy() - a @ b).max() < 1e-10
    n, nrhs, nb = 24, 5, 4
    L = np.tril(rng.standard_normal((n, n))) + n * np.eye(n)
    rhs = rng.standard_normal((n, nrhs))
    Lm = st.TriangularMatrix.from_numpy(L, nb)
    Bm = st.Matrix.from_numpy(rhs, nb, nb)
    X = st.trsm("l", 1.0, Lm, Bm, opts={Option.Abft: "on"})
    assert np.abs(L @ X.to_numpy() - rhs).max() < 1e-10
    rhs2 = rng.standard_normal((nrhs, n))
    X2 = st.trsm("r", 1.0, Lm.T, st.Matrix.from_numpy(rhs2, nb, nb),
                 opts={Option.Abft: "on"})
    assert np.abs(X2.to_numpy() @ L.T - rhs2).max() < 1e-10
