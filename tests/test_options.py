"""Every Option enum member has a real consumer (VERDICT r3 item 8):
these tests drive the newly wired ones end-to-end."""

import jax
import numpy as np
import pytest

import slate_tpu as st
from slate_tpu import compat
from slate_tpu.options import Option


def _spd(rng, n):
    a = rng.standard_normal((n, n))
    return a @ a.T + n * np.eye(n)


def test_pivot_threshold_solve(rng):
    # threshold pivoting solves accurately on a matrix that needs pivoting
    n, nb = 24, 8
    a = rng.standard_normal((n, n))
    a[0, 0] = 1e-12                      # force an off-diagonal pivot
    b = rng.standard_normal((n, 2))
    F, X = st.gesv(st.Matrix.from_numpy(a, nb, nb),
                   st.Matrix.from_numpy(b, nb, nb),
                   {Option.PivotThreshold: 0.5})
    np.testing.assert_allclose(a @ X.to_numpy(), b, atol=1e-9)
    # the permutation really moved row 0's pivot
    assert int(np.asarray(F.perm)[0]) != 0


def test_pivot_threshold_prefers_diagonal(rng):
    # tau=0: always accept the diagonal => no row swaps on any nonsingular
    # matrix (the threshold semantics, ref enums.hh PivotThreshold)
    n, nb = 16, 8
    a = rng.standard_normal((n, n)) + n * np.eye(n)
    F = st.getrf(st.Matrix.from_numpy(a, nb, nb),
                 {Option.PivotThreshold: 1e-12})
    np.testing.assert_array_equal(np.asarray(F.perm), np.arange(n))


@pytest.mark.slow
def test_tournament_mpt_depth(rng):
    n, nb = 40, 4
    a = rng.standard_normal((n, n))
    b = rng.standard_normal((n, 1))
    F, X = st.gesv(st.Matrix.from_numpy(a, nb, nb),
                   st.Matrix.from_numpy(b, nb, nb),
                   {Option.MethodLU: st.MethodLU.CALU,
                    Option.MaxPanelThreads: 2, Option.Depth: 3})
    np.testing.assert_allclose(a @ X.to_numpy(), b, atol=1e-9)


def test_tolerance_consumed(rng):
    n, nb = 32, 8
    a = _spd(rng, n)
    b = rng.standard_normal((n, 2))
    A = st.HermitianMatrix.from_numpy(a, nb)
    B = st.Matrix.from_numpy(b, nb, nb)
    res = st.posv_mixed(A, B, {Option.Tolerance: 1e-6})
    np.testing.assert_allclose(a @ res.X.to_numpy(), b, rtol=0, atol=1e-4)


def test_hold_local_workspace_fused_posv(rng):
    n, nb = 24, 8
    a = _spd(rng, n)
    b = rng.standard_normal((n, 2))
    A = st.HermitianMatrix.from_numpy(a, nb)
    B = st.Matrix.from_numpy(b, nb, nb)
    L, X = st.posv(A, B, {Option.HoldLocalWorkspace: True})
    np.testing.assert_allclose(a @ X.to_numpy(), b, atol=1e-9)


@pytest.mark.slow
def test_lookahead_mesh_posv(rng):
    n, nb = 32, 4
    g = st.Grid(2, 2, devices=jax.devices()[:4])
    a = _spd(rng, n)
    b = rng.standard_normal((n, 2))
    A = st.HermitianMatrix.from_numpy(a, nb, grid=g)
    B = st.Matrix.from_numpy(b, nb, nb, g)
    L, X = st.posv(A, B, {Option.Lookahead: 2})
    np.testing.assert_allclose(a @ X.to_numpy(), b, atol=1e-9)


def test_blocksize_compat(rng):
    n = 20
    a = rng.standard_normal((n, n)) + n * np.eye(n)
    b = rng.standard_normal((n, 2))
    x, perm = compat.lapack.gesv(a, b, opts={Option.BlockSize: 16})
    np.testing.assert_allclose(a @ x, b, atol=1e-9)


def test_hemm_right_hemmA_honored(rng):
    # an explicit stationary-A request on the Right side routes through the
    # Hermitian transpose identity instead of being silently dropped
    n, k, nb = 16, 12, 4
    a = rng.standard_normal((n, n))
    h = (a + a.T) / 2
    b = rng.standard_normal((k, n))
    H = st.HermitianMatrix.from_numpy(h, nb)
    B = st.Matrix.from_numpy(b, nb, nb)
    C = st.hemm("r", 2.0, H, B, opts={Option.MethodHemm: st.MethodHemm.hemmA})
    np.testing.assert_allclose(C.to_numpy(), 2.0 * b @ h, atol=1e-10)


def test_every_option_member_consumed():
    """Static check: each Option member is consumed outside options.py —
    either read directly (Option.X) or through its dedicated accessor
    (resolve_target / select_*_method), which itself reads the option."""
    import pathlib
    root = pathlib.Path(st.__file__).parent
    src = ""
    for f in root.rglob("*.py"):
        if f.name != "options.py":
            src += f.read_text()
    accessor = {
        "Target": "resolve_target(",
        "MethodGemm": "select_gemm_method(",
        "MethodTrsm": "select_trsm_method(",
        "MethodGels": "select_gels_method(",
        "MethodLU": "select_lu_method(",
    }
    missing = [m.name for m in Option
               if f"Option.{m.name}" not in src
               and accessor.get(m.name, "\x00") not in src]
    assert not missing, f"inert options: {missing}"
