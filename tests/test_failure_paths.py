"""Failure-path contracts (VERDICT r3 item 10 + the robustness tentpole):
singular gbtrf/getrf eager vs traced, ErrorPolicy routing, fault-injected
SUMMA/mesh-LU, escalation and fallback recovery, and non-converged mixed
without fallback.  docs/ROBUSTNESS.md holds the full contract table."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import slate_tpu as st
from slate_tpu.exceptions import (SlateNotConvergedError,
                                  SlateNotPositiveDefiniteError,
                                  SlateSingularError)
from slate_tpu.options import (ErrorPolicy, MethodEig, MethodLU, MethodSvd,
                               Option, get_option)
from slate_tpu.robust import faults


def _singular_band(rng, n=12, kl=2, ku=2):
    a = np.triu(np.tril(rng.standard_normal((n, n)), kl), -ku)
    a[:, 3] = 0.0
    a[3, :] = 0.0                       # row+col zero => singular
    return a


def _singular_square(rng, n=16):
    # zero row+column: the pivot column at step 5 stays EXACTLY zero
    # through the elimination updates (a duplicated column only gets
    # there up to rounding, ~eps — which is LAPACK-healthy, info=0)
    a = rng.standard_normal((n, n))
    a[:, 5] = 0.0
    a[5, :] = 0.0
    return a


# ---------------------------------------------------------------- band LU

def test_gbtrf_singular_eager_raises(rng):
    # exactly singular band matrix: the eager contract is a typed error
    # with the LAPACK-style 1-based index of the first zero pivot — never
    # a silently-wrong finite answer and never raw NaN garbage
    n, kl, ku, mb = 12, 2, 2, 4
    A = st.BandMatrix.from_numpy(_singular_band(rng), kl, ku, mb)
    with pytest.raises(SlateSingularError) as ei:
        st.gbtrf(A)
    assert ei.value.info >= 1


def test_gbtrf_singular_traced_nonfinite(rng):
    # under jit the check cannot raise: the factor (and any solve through
    # it) is NaN-poisoned instead
    n, kl, ku, mb = 12, 2, 2, 4
    A = st.BandMatrix.from_numpy(_singular_band(rng), kl, ku, mb)
    B = st.Matrix.from_numpy(rng.standard_normal((n, 1)), mb, mb)

    @jax.jit
    def solve(A, B):
        return st.gbtrs(st.gbtrf(A), B)

    X = solve(A, B)
    assert not np.all(np.isfinite(X.to_numpy()))


def test_gbtrf_singular_info_policy(rng):
    F, h = st.gbtrf(st.BandMatrix.from_numpy(_singular_band(rng), 2, 2, 4),
                    {Option.ErrorPolicy: ErrorPolicy.Info})
    assert not bool(h.ok)
    assert int(h.info) >= 1


# --------------------------------------------------------------- dense LU

def test_getrf_singular_eager_raises(rng):
    A = st.Matrix.from_numpy(_singular_square(rng), 8)
    with pytest.raises(SlateSingularError) as ei:
        st.getrf(A)
    assert ei.value.info >= 1


def test_getrf_singular_traced_contracts(rng):
    # a pivoted LU of an exactly-singular matrix stays FINITE (zero U
    # diagonal, the LAPACK convention) — the traced signal is the info
    # code, and any solve through the factor goes non-finite
    A = st.Matrix.from_numpy(_singular_square(rng), 8)
    B = st.Matrix.from_numpy(np.ones((16, 1)), 8, 8)

    @jax.jit
    def factor_info(A):
        F, h = st.getrf(A, {Option.ErrorPolicy: ErrorPolicy.Info})
        return h

    h = factor_info(A)
    assert int(h.info) == 6
    assert float(h.min_pivot) == 0.0

    @jax.jit
    def solve(A, B):
        return st.gesv(A, B, {Option.UseFallbackSolver: False})[1].to_dense()

    assert not bool(jnp.all(jnp.isfinite(solve(A, B))))


def test_getrf_singular_info_string_spelling(rng):
    # enum-valued options accept their string spellings
    A = st.Matrix.from_numpy(_singular_square(rng), 8)
    F, h = st.getrf(A, {Option.ErrorPolicy: "info"})
    assert not bool(h.ok)
    assert int(h.info) >= 1
    assert float(h.min_pivot) == 0.0


def test_gesv_singular_nan_policy_never_raises(rng):
    n = 16
    A = st.Matrix.from_numpy(_singular_square(rng, n), 8)
    B = st.Matrix.from_numpy(rng.standard_normal((n, 2)), 8, 8)
    F, X = st.gesv(A, B, {Option.ErrorPolicy: "nan"})
    assert not np.all(np.isfinite(X.to_numpy()))


# ------------------------------------------------------------- escalation

def _nopiv_hostile(rng, n=16):
    """Well-conditioned but with a zero leading entry: NoPiv divides by
    zero on step one, PartialPiv sails through."""
    a = rng.standard_normal((n, n)) + n * np.eye(n)
    a[0, 0] = 0.0
    return a


def test_gesv_escalation_recovers_eager(rng):
    n = 16
    a = _nopiv_hostile(rng, n)
    b = rng.standard_normal((n, 2))
    A = st.Matrix.from_numpy(a, 8)
    B = st.Matrix.from_numpy(b, 8, 8)
    F, X = st.gesv(A, B, {Option.MethodLU: MethodLU.NoPiv,
                          Option.UseFallbackSolver: True})
    assert np.allclose(X.to_numpy(), np.linalg.solve(a, b), atol=1e-8)


def test_gesv_escalation_traced_reports_health(rng):
    # a traced call cannot branch on health, so it runs NoPiv once and
    # reports the failure through HealthInfo instead of escalating
    n = 16
    A = st.Matrix.from_numpy(_nopiv_hostile(rng, n), 8)
    B = st.Matrix.from_numpy(rng.standard_normal((n, 1)), 8, 8)

    @jax.jit
    def solve(A, B):
        F, X, h = st.gesv(A, B, {Option.MethodLU: MethodLU.NoPiv,
                                 Option.UseFallbackSolver: True,
                                 Option.ErrorPolicy: ErrorPolicy.Info})
        return X.to_dense(), h

    xd, h = solve(A, B)
    assert not bool(h.ok)


def test_posv_fallback_to_indefinite(rng):
    n, nb = 16, 8
    a = rng.standard_normal((n, n))
    a = (a + a.T) / 2 - n * np.eye(n)   # symmetric negative definite
    b = rng.standard_normal((n, 2))
    A = st.HermitianMatrix.from_numpy(a, nb)
    B = st.Matrix.from_numpy(b, nb, nb)
    with pytest.raises(SlateNotPositiveDefiniteError):
        st.posv(A, B, {Option.UseFallbackSolver: False})
    F, X = st.posv(A, B, {Option.UseFallbackSolver: True})
    assert np.allclose(X.to_numpy(), np.linalg.solve(a, b), atol=1e-8)


def test_gels_cholqr_fallback_to_qr(rng):
    # f32 with cond(A) ~ 1e6: the Gram squares that past 1/eps_f32 so
    # CholQR's Cholesky fails, while plain Householder QR is fine — the
    # exact regime the method fallback exists for
    m, n = 24, 8
    from slate_tpu.options import MethodGels
    U, _ = np.linalg.qr(rng.standard_normal((m, n)))
    V, _ = np.linalg.qr(rng.standard_normal((n, n)))
    a = ((U * np.logspace(0, -6, n)) @ V.T).astype(np.float32)
    b = rng.standard_normal((m, 1)).astype(np.float32)
    A = st.Matrix.from_numpy(a, 8)
    B = st.Matrix.from_numpy(b, 8, 8)
    opts = {Option.MethodGels: MethodGels.CholQR}
    with pytest.raises(SlateNotPositiveDefiniteError):
        st.gels(A, B, {**opts, Option.UseFallbackSolver: False})
    X = st.gels(A, B, {**opts, Option.UseFallbackSolver: True})
    xd = np.asarray(X.to_dense(), np.float64)
    x_ref, *_ = np.linalg.lstsq(a.astype(np.float64),
                                b.astype(np.float64), rcond=None)
    r = np.linalg.norm(a @ xd - b) / np.linalg.norm(a @ x_ref - b)
    assert np.all(np.isfinite(xd)) and r < 1.01


# -------------------------------------------------------- fault injection

def test_fault_injector_deterministic():
    x = jnp.ones((6, 6))
    plan = faults.FaultPlan(site="input", kind="nan", seed=7, count=3)
    y1, y2 = faults.corrupt(x, plan), faults.corrupt(x, plan)
    assert int(jnp.sum(jnp.isnan(y1))) == 3
    assert bool(jnp.all(jnp.isnan(y1) == jnp.isnan(y2)))


def test_fault_injected_summa_mesh(rng):
    g = st.Grid(2, 2, devices=jax.devices()[:4])
    a = rng.standard_normal((16, 16))
    b = rng.standard_normal((16, 16))
    A = st.Matrix.from_numpy(a, 4, 4, g)
    B = st.Matrix.from_numpy(b, 4, 4, g)
    with faults.inject(faults.FaultPlan(site="post_collective", kind="nan",
                                        seed=1, count=2)):
        out = st.gemm(1.0, A, B, 0.0, None)
    assert not np.all(np.isfinite(out.to_numpy()))
    # and the same call with no plan active is clean
    out2 = st.gemm(1.0, A, B, 0.0, None)
    assert np.allclose(out2.to_numpy(), a @ b, atol=1e-10)


def test_mesh_getrf_fault_reports_health(rng):
    g = st.Grid(2, 2, devices=jax.devices()[:4])
    n = 16
    a = rng.standard_normal((n, n)) + n * np.eye(n)
    A = st.Matrix.from_numpy(a, 4, 4, g)
    with faults.inject(faults.FaultPlan(site="post_panel", kind="nan",
                                        seed=2, count=1)):
        F, h = st.getrf(A, {Option.ErrorPolicy: ErrorPolicy.Info})
    assert not bool(h.ok)
    # clean rerun is healthy and matches the single-device factor
    F2, h2 = st.getrf(A, {Option.ErrorPolicy: ErrorPolicy.Info})
    assert bool(h2.ok)


def test_fault_injected_gesv_recovers_or_reports(rng):
    # acceptance gate: with a fault at the panel site, gesv either returns
    # a correct recovered answer or reports ill-health — never a silently
    # wrong finite X
    n = 16
    a = rng.standard_normal((n, n)) + n * np.eye(n)
    b = rng.standard_normal((n, 1))
    A = st.Matrix.from_numpy(a, 8)
    B = st.Matrix.from_numpy(b, 8, 8)
    with faults.inject(faults.FaultPlan(site="post_panel", kind="bitflip",
                                        seed=3, count=1)):
        out = st.gesv(A, B, {Option.ErrorPolicy: ErrorPolicy.Info,
                             Option.UseFallbackSolver: True})
    F, X, h = out
    xd = X.to_numpy()
    good = np.allclose(xd, np.linalg.solve(a, b), atol=1e-6)
    assert good or not bool(h.ok)


def test_fault_injected_gesv_mixed_never_silently_wrong(rng):
    # a bit-flipped panel leaves the factor finite with info == 0; the only
    # signal is pivot growth.  The fallback's factor is corrupted too (the
    # fault context is still active), so bounded_retry must demote
    # `converged` on growth rather than trust the fallback's .ok
    n = 16
    a = rng.standard_normal((n, n)) + n * np.eye(n)
    b = rng.standard_normal((n, 1))
    A = st.Matrix.from_numpy(a, 8)
    B = st.Matrix.from_numpy(b, 8, 8)
    with faults.inject(faults.FaultPlan(site="post_panel", kind="bitflip",
                                        seed=9, count=2)):
        res = st.gesv_mixed(A, B)
    xd = np.asarray(res.X.to_dense())
    good = np.allclose(xd, np.linalg.solve(a, b), atol=1e-6)
    assert good or not bool(res.converged)


# ----------------------------------------- certified spectral stack (PR 2)

def _herm(rng, n, dtype=np.float64):
    a = rng.standard_normal((n, n)).astype(dtype)
    if np.issubdtype(dtype, np.complexfloating):
        a = a + 1j * rng.standard_normal((n, n))
    return (a + a.conj().T) / 2


def _singular_herm(rng, n=16, k=5):
    a = _herm(rng, n)
    a[:, k] = 0.0
    a[k, :] = 0.0                        # exactly singular, info = k+1
    return a


# a minimal covering sweep: every route and every new fault site at least
# once.  Auto solves the stage-1 band directly (no chase, no secular
# solve); QR adds the bulge chase; DC adds the chase AND the secular
# equation — the remaining (route, site) pairs traverse code already
# covered by one of these and are left out to keep tier-1 within budget
@pytest.mark.parametrize("meth,site", [
    (MethodEig.Auto, "post_stage1"),
    (MethodEig.Auto, "post_backtransform"),
    (MethodEig.QR, "post_chase"),
    (MethodEig.DC, "post_secular"),
])
def test_heev_fault_detected(rng, meth, site):
    # a fault at ANY spectral pipeline stage must be caught by the
    # a-posteriori certificate — never a silently-wrong finite (w, Z).
    # The secular solve only runs on merges of > LEAF-sized subproblems,
    # so that site needs a larger matrix; count=8 because a corrupted
    # slot can land on a deflated (inactive) entry
    n, nb = (36, 6) if site == "post_secular" else (16, 4)
    a = _herm(rng, n)
    A = st.HermitianMatrix.from_numpy(a, nb)
    with faults.inject(faults.FaultPlan(site=site, kind="nan", seed=11,
                                        count=8)):
        w, Z, h = st.heev(A, {Option.ErrorPolicy: ErrorPolicy.Info,
                              Option.MethodEig: meth,
                              Option.UseFallbackSolver: False})
    assert not bool(h.ok)
    # (clean certification of every route is covered by test_heev.py)


def test_heev_fault_raise_and_nan_policies(rng):
    n, nb = 16, 4
    A = st.HermitianMatrix.from_numpy(_herm(rng, n), nb)
    plan = faults.FaultPlan(site="post_backtransform", kind="bitflip",
                            seed=5, count=1)
    with faults.inject(plan):
        with pytest.raises(SlateNotConvergedError):
            st.heev(A, {Option.UseFallbackSolver: False})
    with faults.inject(plan):
        w, Z = st.heev(A, {Option.ErrorPolicy: ErrorPolicy.Nan,
                           Option.UseFallbackSolver: False})
        assert not np.all(np.isfinite(np.asarray(w)))


def test_heev_escalation_recovers_transient(rng):
    # single-shot SDC at the stage-1 seam: the Auto attempt is corrupted,
    # the certificate rejects it, and the DC retry (fault already spent)
    # returns a certified decomposition
    n, nb = 16, 4
    a = _herm(rng, n)
    A = st.HermitianMatrix.from_numpy(a, nb)
    with faults.inject(faults.FaultPlan(site="post_stage1", kind="bitflip",
                                        seed=3, count=1, transient=True)):
        w, Z = st.heev(A, {Option.UseFallbackSolver: True})
    assert np.allclose(np.sort(np.asarray(w)), np.linalg.eigvalsh(a),
                       atol=1e-8)


def test_heev_escalation_dc_to_qr_persistent(rng):
    # a PERSISTENT fault in the secular solve defeats every DC attempt,
    # but the QR route has no secular equation — method escalation walks
    # DC -> QR and certifies there (n > LEAF so the merge actually runs)
    n, nb = 36, 6
    a = _herm(rng, n)
    A = st.HermitianMatrix.from_numpy(a, nb)
    with faults.inject(faults.FaultPlan(site="post_secular", kind="nan",
                                        seed=7, count=8)):
        w, Z, h = st.heev(A, {Option.ErrorPolicy: ErrorPolicy.Info,
                              Option.MethodEig: MethodEig.DC,
                              Option.UseFallbackSolver: True})
    assert bool(h.ok)
    assert np.allclose(np.sort(np.asarray(w)), np.linalg.eigvalsh(a),
                       atol=1e-8)


def test_stedc_fault_detected_and_raises(rng):
    n = 36                               # > LEAF: the merge path runs
    d = rng.standard_normal(n)
    e = rng.standard_normal(n - 1)
    plan = faults.FaultPlan(site="post_secular", kind="nan", seed=2, count=8)
    with faults.inject(plan):
        w, Z, h = st.stedc(d, e, opts={Option.ErrorPolicy: ErrorPolicy.Info})
    assert not bool(h.ok)
    with faults.inject(plan):
        with pytest.raises(SlateNotConvergedError):
            st.stedc(d, e)
    # (clean stedc certification is covered by test_stedc.py)


@pytest.mark.parametrize("meth,site", [
    (MethodSvd.Auto, "post_stage1"),
    (MethodSvd.Auto, "post_backtransform"),
    (MethodSvd.Bidiag, "post_chase"),
])
def test_svd_fault_detected(rng, meth, site):
    m, n, nb = 20, 16, 4
    a = rng.standard_normal((m, n))
    A = st.Matrix.from_numpy(a, nb)
    with faults.inject(faults.FaultPlan(site=site, kind="nan", seed=13,
                                        count=4)):
        s, U, V, h = st.svd(A, {Option.ErrorPolicy: ErrorPolicy.Info,
                                Option.MethodSvd: meth,
                                Option.UseFallbackSolver: False})
    assert not bool(h.ok)
    # (clean certification of both routes is covered by test_svd.py)


def test_svd_escalation_recovers_transient(rng):
    m, n, nb = 20, 16, 4
    a = rng.standard_normal((m, n))
    A = st.Matrix.from_numpy(a, nb)
    with faults.inject(faults.FaultPlan(site="post_stage1", kind="bitflip",
                                        seed=17, count=1, transient=True)):
        s, U, V = st.svd(A, {Option.UseFallbackSolver: True})
    assert np.allclose(np.asarray(s), np.linalg.svd(a, compute_uv=False),
                       atol=1e-8)
    with faults.inject(faults.FaultPlan(site="post_stage1", kind="nan",
                                        seed=17, count=4)):
        with pytest.raises(SlateNotConvergedError):
            st.svd(A, {Option.UseFallbackSolver: False})


def test_hetrf_singular_band_t(rng):
    # exactly-singular Hermitian input: Aasen's band T is singular too —
    # the eager contract is a typed error with the LAPACK-style info
    n, nb = 16, 4
    a = _singular_herm(rng, n, k=5)
    A = st.HermitianMatrix.from_numpy(a, nb)
    with pytest.raises(SlateSingularError) as ei:
        st.hetrf(A)
    assert ei.value.info >= 1
    F, h = st.hetrf(A, {Option.ErrorPolicy: ErrorPolicy.Info})
    assert not bool(h.ok)
    assert int(h.info) >= 1


def test_hetrf_fault_detected_by_certificate(rng):
    n, nb = 16, 4
    a = _herm(rng, n)
    A = st.HermitianMatrix.from_numpy(a, nb)
    with faults.inject(faults.FaultPlan(site="post_stage1", kind="bitflip",
                                        seed=19, count=1)):
        F, h = st.hetrf(A, {Option.ErrorPolicy: ErrorPolicy.Info})
    assert not bool(h.ok)


def test_hesv_falls_back_to_gesv(rng):
    # hetrf's factor is corrupted at the stage-1 site; with the fallback
    # enabled hesv escalates to a dense LU solve and still returns the
    # right answer
    n, nb = 16, 4
    a = _herm(rng, n) + n * np.eye(n)
    b = rng.standard_normal((n, 2))
    A = st.HermitianMatrix.from_numpy(a, nb)
    B = st.Matrix.from_numpy(b, nb, nb)
    with faults.inject(faults.FaultPlan(site="post_stage1", kind="nan",
                                        seed=23, count=2)):
        F, X = st.hesv(A, B, {Option.UseFallbackSolver: True})
    assert np.allclose(X.to_numpy(), np.linalg.solve(a, b), atol=1e-8)


def test_hesv_truly_singular_raises_after_fallback(rng):
    n, nb = 16, 4
    a = _singular_herm(rng, n)
    b = np.ones((n, 1))
    A = st.HermitianMatrix.from_numpy(a, nb)
    B = st.Matrix.from_numpy(b, nb, nb)
    with pytest.raises(SlateSingularError):
        st.hesv(A, B, {Option.UseFallbackSolver: True})


def test_heev_nan_policy_keeps_static_fields(rng):
    # ErrorPolicy.Nan must NaN-poison array leaves only: HEFactors carries
    # a static int block size that hetrs needs for shape computation
    n, nb = 16, 4
    A = st.HermitianMatrix.from_numpy(_singular_herm(rng, n), nb)
    F = st.hetrf(A, {Option.ErrorPolicy: ErrorPolicy.Nan})
    assert isinstance(F.nb, int)
    assert not np.all(np.isfinite(np.asarray(F.L)))


def test_trtri_singular_contracts(rng):
    n, nb = 16, 4
    r = np.triu(rng.standard_normal((n, n))) + 4 * np.eye(n)
    r[6, 6] = 0.0
    R = st.TriangularMatrix.from_numpy(r, nb, st.Uplo.Upper)
    with pytest.raises(SlateSingularError) as ei:
        st.trtri(R)
    assert ei.value.info == 7            # 1-based index of the zero pivot
    X, h = st.trtri(R, {Option.ErrorPolicy: ErrorPolicy.Info})
    assert int(h.info) == 7 and not bool(h.ok)


def test_getri_singular_factor_raises(rng):
    n, nb = 16, 8
    a = _singular_square(rng, n)
    F, fh = st.getrf(st.Matrix.from_numpy(a, nb),
                     {Option.ErrorPolicy: ErrorPolicy.Info})
    with pytest.raises(SlateSingularError) as ei:
        st.getri(F)
    assert ei.value.info == int(fh.info)
    X, h = st.getriOOP(st.Matrix.from_numpy(a, nb),
                       {Option.ErrorPolicy: ErrorPolicy.Info})
    assert not bool(h.ok)


def test_condest_poisoned_estimate_resolves_to_zero(rng):
    # singular triangular factor poisons the Hager/Higham appliers; the
    # guarded loop must resolve to rcond = 0 (the LAPACK convention) and
    # flag it — never return NaN
    n, nb = 20, 4
    r = np.triu(rng.standard_normal((n, n))) + 4 * np.eye(n)
    r[7, 7] = 0.0
    R = st.TriangularMatrix.from_numpy(r, nb, st.Uplo.Upper)
    rcond = st.trcondest(R)
    assert float(rcond) == 0.0
    rcond2, h = st.trcondest(R, {Option.ErrorPolicy: ErrorPolicy.Info})
    assert float(rcond2) == 0.0 and bool(h.nonfinite)

    # gecondest through a NaN LU factor (Nan-policy getrf of a singular
    # matrix): same resolution
    a = _singular_square(rng, n)
    F = st.getrf(st.Matrix.from_numpy(a, nb),
                 {Option.ErrorPolicy: ErrorPolicy.Nan})
    anorm = np.abs(a).sum(axis=0).max()
    rc, hg = st.gecondest(F, anorm, {Option.ErrorPolicy: ErrorPolicy.Info})
    assert float(rc) == 0.0 and bool(hg.nonfinite)
    assert np.isfinite(float(rc))


def test_certify_clean_decompositions(rng):
    # the certificates themselves: healthy on exact decompositions, not
    # ok when handed a wrong eigenvector basis
    from slate_tpu.robust.certify import certify_eig, certify_svd
    n = 16
    a = _herm(rng, n)
    w, v = np.linalg.eigh(a)
    h = certify_eig(jnp.asarray(a), jnp.asarray(w), jnp.asarray(v))
    assert bool(h.ok)
    vbad = np.roll(v, 1, axis=1)         # right values, wrong pairing
    hb = certify_eig(jnp.asarray(a), jnp.asarray(w), jnp.asarray(vbad))
    assert not bool(hb.ok)
    m = 20
    g = rng.standard_normal((m, n))
    U, s, Vh = np.linalg.svd(g, full_matrices=False)
    hs = certify_svd(jnp.asarray(g), jnp.asarray(s), jnp.asarray(U),
                     jnp.asarray(Vh.conj().T))
    assert bool(hs.ok)


# ----------------------------------------------------------- option plumbing

def test_get_option_explicit_none_default():
    assert get_option(None, Option.MaxIterations, None) is None
    assert get_option(None, Option.MaxIterations) is not None


# ------------------------------------------------- band Cholesky (historic)

def test_pbtrf_not_hpd_eager_raises(rng):
    n, kd, mb = 10, 2, 5
    a = rng.standard_normal((n, n))
    band = np.where(np.abs(np.subtract.outer(np.arange(n), np.arange(n)))
                    <= kd, (a + a.T) / 2, 0.0)
    band -= 10 * np.eye(n)              # negative definite
    HB = st.HermitianBandMatrix.from_numpy(band, kd, mb)
    with pytest.raises(SlateNotPositiveDefiniteError):
        st.pbtrf(HB)


def test_pbtrf_not_hpd_traced_nan(rng):
    # under jit the check cannot raise: the documented contract is the XLA
    # convention — NaNs in the factor
    n, kd, mb = 10, 2, 5
    a = rng.standard_normal((n, n))
    band = np.where(np.abs(np.subtract.outer(np.arange(n), np.arange(n)))
                    <= kd, (a + a.T) / 2, 0.0)
    band -= 10 * np.eye(n)
    HB = st.HermitianBandMatrix.from_numpy(band, kd, mb)

    @jax.jit
    def factor(H):
        return st.pbtrf(H).L_band

    lband = factor(HB)
    assert not bool(jnp.all(jnp.isfinite(lband)))


def test_potrf_not_spd_traced_nan(rng):
    n, nb = 12, 4
    a = rng.standard_normal((n, n))
    nd = -((a @ a.T) + n * np.eye(n))   # negative definite
    A = st.HermitianMatrix.from_numpy(nd, nb)

    @jax.jit
    def factor(H):
        return st.potrf(H).to_dense()

    out = factor(A)
    assert not bool(jnp.all(jnp.isfinite(out)))


@pytest.mark.slow
def test_mixed_no_fallback_reports_nonconvergence(rng):
    # ill-conditioned system: f32-factor IR cannot reach f64 accuracy; with
    # the fallback disabled the documented contract is converged=False with
    # the low-precision-IR iterate returned as-is
    n, nb = 24, 8
    u = np.linalg.qr(rng.standard_normal((n, n)))[0]
    s = np.logspace(0, 14, n)           # cond 1e14
    a = (u * s) @ u.T
    a = (a + a.T) / 2
    b = rng.standard_normal((n, 1))
    A = st.HermitianMatrix.from_numpy(a, nb)
    B = st.Matrix.from_numpy(b, nb, nb)
    res = st.posv_mixed(A, B, {Option.UseFallbackSolver: False,
                               Option.MaxIterations: 3})
    assert not bool(res.converged)
    assert int(res.iters) >= 3
