"""Failure-path contracts (VERDICT r3 item 10): singular gbtrf, non-HPD
pbtrf/potrf eager vs traced, and non-converged mixed without fallback."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import slate_tpu as st
from slate_tpu.exceptions import SlateNotPositiveDefiniteError
from slate_tpu.options import Option


def test_gbtrf_singular_produces_nonfinite(rng):
    # exactly singular band matrix: the unpivoted-across-blocks window LU
    # hits a zero pivot; the documented contract is LAPACK-style garbage-in
    # signalling — non-finite values in the factors/solve, never a wrong
    # finite answer
    n, kl, ku, mb = 12, 2, 2, 4
    a = np.triu(np.tril(rng.standard_normal((n, n)), kl), -ku)
    a[:, 3] = 0.0
    a[3, :] = 0.0                       # row+col zero => singular
    A = st.BandMatrix.from_numpy(a, kl, ku, mb)
    B = st.Matrix.from_numpy(rng.standard_normal((n, 1)), mb, mb)
    F = st.gbtrf(A)
    X = st.gbtrs(F, B)
    assert not np.all(np.isfinite(X.to_numpy()))


def test_pbtrf_not_hpd_eager_raises(rng):
    n, kd, mb = 10, 2, 5
    a = rng.standard_normal((n, n))
    band = np.where(np.abs(np.subtract.outer(np.arange(n), np.arange(n)))
                    <= kd, (a + a.T) / 2, 0.0)
    band -= 10 * np.eye(n)              # negative definite
    HB = st.HermitianBandMatrix.from_numpy(band, kd, mb)
    with pytest.raises(SlateNotPositiveDefiniteError):
        st.pbtrf(HB)


def test_pbtrf_not_hpd_traced_nan(rng):
    # under jit the check cannot raise: the documented contract is the XLA
    # convention — NaNs in the factor
    n, kd, mb = 10, 2, 5
    a = rng.standard_normal((n, n))
    band = np.where(np.abs(np.subtract.outer(np.arange(n), np.arange(n)))
                    <= kd, (a + a.T) / 2, 0.0)
    band -= 10 * np.eye(n)
    HB = st.HermitianBandMatrix.from_numpy(band, kd, mb)

    @jax.jit
    def factor(H):
        return st.pbtrf(H).L_band

    lband = factor(HB)
    assert not bool(jnp.all(jnp.isfinite(lband)))


def test_potrf_not_spd_traced_nan(rng):
    n, nb = 12, 4
    a = rng.standard_normal((n, n))
    nd = -((a @ a.T) + n * np.eye(n))   # negative definite
    A = st.HermitianMatrix.from_numpy(nd, nb)

    @jax.jit
    def factor(H):
        return st.potrf(H).to_dense()

    out = factor(A)
    assert not bool(jnp.all(jnp.isfinite(out)))


@pytest.mark.slow
def test_mixed_no_fallback_reports_nonconvergence(rng):
    # ill-conditioned system: f32-factor IR cannot reach f64 accuracy; with
    # the fallback disabled the documented contract is converged=False with
    # the low-precision-IR iterate returned as-is
    n, nb = 24, 8
    u = np.linalg.qr(rng.standard_normal((n, n)))[0]
    s = np.logspace(0, 14, n)           # cond 1e14
    a = (u * s) @ u.T
    a = (a + a.T) / 2
    b = rng.standard_normal((n, 1))
    A = st.HermitianMatrix.from_numpy(a, nb)
    B = st.Matrix.from_numpy(b, nb, nb)
    res = st.posv_mixed(A, B, {Option.UseFallbackSolver: False,
                               Option.MaxIterations: 3})
    assert not bool(res.converged)
    assert int(res.iters) >= 3
