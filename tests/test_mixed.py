"""Mixed-precision solver tests (analog of ref test/test_gesv.cc --method
mixed / mixed_gmres paths): f32 factor + f64 refinement must reach full f64
residuals; the itermax fallback path must engage on hopeless conditioning."""

import numpy as np
import pytest

import slate_tpu as st
from slate_tpu.util.generator import generate_hermitian, generate_matrix


@pytest.mark.slow
def test_gesv_mixed_reaches_double(rng):
    n, nb = 48, 8
    A = generate_matrix("svd", n, n, nb, seed=1, cond=1e4)
    b = rng.standard_normal((n, 3))
    B = st.Matrix.from_numpy(b, nb)
    res = st.gesv_mixed(A, B)
    a = A.to_numpy()
    x = res.X.to_numpy()
    resid = np.linalg.norm(a @ x - b) / (np.linalg.norm(a) *
                                         np.linalg.norm(x) * n)
    assert res.converged
    assert resid < 1e-15          # full double-precision quality
    assert res.iters <= 30


def test_posv_mixed(rng):
    n, nb = 40, 8
    A = generate_hermitian("poev", n, nb, seed=3, cond=1e5)
    b = rng.standard_normal((n, 2))
    B = st.Matrix.from_numpy(b, nb)
    res = st.posv_mixed(A, B)
    a = A.to_numpy()
    x = res.X.to_numpy()
    resid = np.linalg.norm(a @ x - b) / (np.linalg.norm(a) *
                                         np.linalg.norm(x) * n)
    assert res.converged and resid < 1e-15


def test_gesv_mixed_fallback(rng):
    """cond ~ 1/eps_single: single-precision factor is useless, the solver
    must fall back to the full-precision factorization and still succeed
    (ref: gesv_mixed_gmres.cc:58-77)."""
    n, nb = 32, 8
    A = generate_matrix("svd", n, n, nb, seed=5, cond=1e12)
    b = rng.standard_normal((n, 1))
    B = st.Matrix.from_numpy(b, nb)
    res = st.gesv_mixed(A, B)
    a = A.to_numpy()
    x = res.X.to_numpy()
    resid = np.linalg.norm(a @ x - b) / (np.linalg.norm(a) *
                                         np.linalg.norm(x) * n)
    assert res.converged          # via fallback
    assert resid < 1e-13


def test_gesv_mixed_gmres(rng):
    n, nb = 32, 8
    A = generate_matrix("svd", n, n, nb, seed=7, cond=1e6)
    b = rng.standard_normal((n, 2))
    B = st.Matrix.from_numpy(b, nb)
    res = st.gesv_mixed_gmres(A, B)
    a = A.to_numpy()
    x = res.X.to_numpy()
    resid = np.linalg.norm(a @ x - b) / (np.linalg.norm(a) *
                                         np.linalg.norm(x) * n)
    assert resid < 1e-14


def test_posv_mixed_gmres(rng):
    n, nb = 32, 8
    A = generate_hermitian("poev", n, nb, seed=9, cond=1e6)
    b = rng.standard_normal((n, 1))
    B = st.Matrix.from_numpy(b, nb)
    res = st.posv_mixed_gmres(A, B)
    a = A.to_numpy()
    x = res.X.to_numpy()
    resid = np.linalg.norm(a @ x - b) / (np.linalg.norm(a) *
                                         np.linalg.norm(x) * n)
    assert resid < 1e-14
