"""CPU smoke tests for bench.py: every metric function emits one parseable
JSON line at toy sizes, and the SLATE_BENCH_BUDGET_S harness skips (never
kills) metrics that would blow the budget — the whole run always exits 0
with one line per metric (BENCH_r04 rc=1 / BENCH_r05 rc=124 regressions).
"""

import importlib.util
import json
import sys
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture(scope="module")
def bench():
    spec = importlib.util.spec_from_file_location("bench", REPO / "bench.py")
    mod = importlib.util.module_from_spec(spec)
    saved = sys.modules.get("bench")
    sys.modules["bench"] = mod
    spec.loader.exec_module(mod)
    yield mod
    if saved is not None:
        sys.modules["bench"] = saved
    else:
        sys.modules.pop("bench", None)


def _lines(capsys):
    out = [ln for ln in capsys.readouterr().out.splitlines() if ln.strip()]
    return [json.loads(ln) for ln in out]


TOY = [
    ("bench_potrf_fused", dict(n=256, nb=128, bw=8, iters=1)),
    ("bench_geqrf_panel", dict(m=256, n=128, iters=1)),
    ("bench_gemm", dict(n=64, nb=32, iters=2)),
    ("bench_posv", dict(n=64, nb=32, nrhs=4, iters=1)),
    ("bench_gesv", dict(n=64, nb=32, nrhs=4, iters=1)),
    ("bench_gesv_rbt", dict(n=64, nb=32, nrhs=4, iters=1)),
    ("bench_gesv_abft", dict(n=64, nb=32, nrhs=4, iters=1)),
    ("bench_posv_abft", dict(n=64, nb=32, nrhs=4, iters=1)),
    ("bench_geqrf", dict(m=96, n=32, nb=32, iters=1)),
    ("bench_gels", dict(m=96, n=32, nb=32, nrhs=4, iters=1)),
    ("bench_heev", dict(n=64, nb=32, iters=1)),
    ("bench_svd", dict(n=64, nb=32, iters=1)),
]


@pytest.mark.parametrize("name,kwargs", TOY, ids=[t[0] for t in TOY])
def test_metric_emits_json(bench, capsys, name, kwargs):
    getattr(bench, name)(**kwargs)
    lines = _lines(capsys)
    assert len(lines) == 1
    line = lines[0]
    assert line["schema"] == "slate-bench-v1"
    assert line["unit"] == "GFLOP/s"
    assert "chip" in line
    assert isinstance(line["value"], (int, float)) and line["value"] > 0
    assert isinstance(line["vs_baseline"], (int, float))
    if "abft" in name:
        assert isinstance(line["abft_overhead_pct"], (int, float))
        assert line["plain_gflops"] > 0


def test_potrf_ooc_emits_gflops_and_slowdown(bench, capsys):
    """bench_potrf_ooc self-emits two lines: the streaming path's raw
    GFLOP/s and its slowdown vs the in-core potrf at the same size."""
    bench.bench_potrf_ooc(n=48, nb=16, iters=1)
    by_metric = {ln["metric"]: ln for ln in _lines(capsys)}
    assert set(by_metric) == {"durability_potrf_ooc_gflops",
                              "durability_potrf_ooc_slowdown"}
    gf = by_metric["durability_potrf_ooc_gflops"]
    assert gf["schema"] == "slate-bench-v1" and "chip" in gf
    assert gf["unit"] == "GFLOP/s" and gf["value"] > 0
    slow = by_metric["durability_potrf_ooc_slowdown"]
    assert slow["unit"] == "x" and slow["value"] > 0


def test_checkpoint_overhead_emits_pct_and_save_ms(bench, capsys):
    """bench_checkpoint_overhead self-emits the every-step checkpoint
    cadence's relative cost and the per-snapshot wall cost."""
    bench.bench_checkpoint_overhead(n=48, nb=16, iters=1)
    by_metric = {ln["metric"]: ln for ln in _lines(capsys)}
    assert set(by_metric) == {"durability_ckpt_overhead_pct",
                              "durability_ckpt_save_ms"}
    pct = by_metric["durability_ckpt_overhead_pct"]
    assert pct["schema"] == "slate-bench-v1" and "chip" in pct
    assert pct["unit"] == "%" and isinstance(pct["value"], (int, float))
    ms = by_metric["durability_ckpt_save_ms"]
    assert ms["unit"] == "ms" and isinstance(ms["value"], (int, float))


def test_serve_mixed_emits_throughput_and_waste(bench, capsys):
    """bench_serve_mixed emits its own two lines (problems/s and padding
    waste %) — it bypasses _emit, whose unit is hardwired to GFLOP/s."""
    bench.bench_serve_mixed(problems=9, nrhs=2, reps=1, sizes=(12, 24, 40))
    lines = _lines(capsys)
    by_metric = {ln["metric"]: ln for ln in lines}
    assert set(by_metric) == {"serve_mixed_problems_per_s",
                              "serve_mixed_padding_waste_pct"}
    pps = by_metric["serve_mixed_problems_per_s"]
    assert pps["schema"] == "slate-bench-v1" and "chip" in pps
    assert pps["unit"] == "problems/s" and pps["value"] > 0
    waste = by_metric["serve_mixed_padding_waste_pct"]
    assert waste["unit"] == "%"
    assert 0.0 <= waste["value"] <= 100.0


def test_serve_ragged_emits_both_routes(bench, capsys):
    """bench_serve_ragged emits raw AND waste-adjusted problems/s for the
    ragged and vmapped-XLA routes plus the workload's padding waste and
    the speedup ratio — six lines, self-emitted like bench_serve_mixed."""
    bench.bench_serve_ragged(problems=6, nrhs=2, reps=1, bucket=16)
    by_metric = {ln["metric"]: ln for ln in _lines(capsys)}
    assert set(by_metric) == {
        "serve_ragged_padding_waste_pct",
        "serve_ragged_ragged_problems_per_s",
        "serve_ragged_xla_problems_per_s",
        "serve_ragged_ragged_adjusted_problems_per_s",
        "serve_ragged_xla_adjusted_problems_per_s",
        "serve_ragged_speedup"}
    waste = by_metric["serve_ragged_padding_waste_pct"]
    assert waste["unit"] == "%" and 0.0 <= waste["value"] <= 100.0
    for route in ("ragged", "xla"):
        raw = by_metric[f"serve_ragged_{route}_problems_per_s"]
        adj = by_metric[f"serve_ragged_{route}_adjusted_problems_per_s"]
        assert raw["schema"] == "slate-bench-v1" and "chip" in raw
        assert raw["unit"] == "problems/s" and raw["value"] > 0
        assert adj["unit"] == "problems/s"
        assert adj["value"] >= raw["value"]   # adjusted divides by 1-waste
    assert by_metric["serve_ragged_speedup"]["unit"] == "x"
    assert by_metric["serve_ragged_speedup"]["value"] > 0


def test_serve_bf16_emits_both_routes_and_accept_rate(bench, capsys):
    """bench_serve_bf16 pins the precision-rung line contract: raw AND
    waste-adjusted problems/s for the bf16-rung and f32-only routes, the
    certificate accept-rate over live slots, and the speedup ratio — six
    self-emitted lines carrying the bench schema."""
    bench.bench_serve_bf16(problems=6, nrhs=2, reps=1, bucket=16)
    by_metric = {ln["metric"]: ln for ln in _lines(capsys)}
    assert set(by_metric) == {
        "serve_precision_bf16_problems_per_s",
        "serve_precision_f32_problems_per_s",
        "serve_precision_bf16_adjusted_problems_per_s",
        "serve_precision_f32_adjusted_problems_per_s",
        "serve_precision_accept_rate_pct",
        "serve_precision_bf16_speedup"}
    for route in ("bf16", "f32"):
        raw = by_metric[f"serve_precision_{route}_problems_per_s"]
        adj = by_metric[f"serve_precision_{route}_adjusted_problems_per_s"]
        assert raw["schema"] == "slate-bench-v1" and "chip" in raw
        assert raw["unit"] == "problems/s" and raw["value"] > 0
        assert adj["unit"] == "problems/s"
        assert adj["value"] >= raw["value"]   # adjusted divides by 1-waste
    accept = by_metric["serve_precision_accept_rate_pct"]
    assert accept["unit"] == "%" and 0.0 <= accept["value"] <= 100.0
    # the workload is well-conditioned by construction: the certificate
    # must accept most problems or the rung is not doing its job
    assert accept["value"] >= 50.0
    assert by_metric["serve_precision_bf16_speedup"]["unit"] == "x"
    assert by_metric["serve_precision_bf16_speedup"]["value"] > 0


def test_serve_bf16_skips_clean_under_budget_preemption(bench, capsys):
    """The new metric must honor the rc=0 contract: preempted by the
    budget pool, it reports a skipped line instead of dying."""
    failures = bench._run_isolated(
        [(bench.bench_serve_bf16,
          dict(problems=6, nrhs=2, reps=1, bucket=16))], budget_s=1e-6)
    assert failures == 0
    lines = _lines(capsys)
    assert len(lines) == 1
    assert lines[0]["metric"] == "bench_serve_bf16_skipped"
    assert lines[0]["skipped"] is True
    assert lines[0]["schema"] == "slate-bench-v1"


def test_serve_survival_emits_survival_metrics(bench, capsys):
    """bench_serve_survival replays a Poisson arrival stream against a
    live background-flush Server and self-emits five lines: throughput,
    admitted p99, shed and quarantine rates, and the SLO verdict."""
    bench.bench_serve_survival(problems=8, rate_hz=2000.0, nrhs=2,
                               sizes=(8, 16), budget_ms=60000.0)
    by_metric = {ln["metric"]: ln for ln in _lines(capsys)}
    assert set(by_metric) == {
        "serve_survival_problems_per_s",
        "serve_survival_latency_p99_ms",
        "serve_survival_shed_per_1k",
        "serve_survival_quar_per_1k",
        "serve_survival_slo_pass"}
    pps = by_metric["serve_survival_problems_per_s"]
    assert pps["schema"] == "slate-bench-v1" and "chip" in pps
    assert pps["unit"] == "problems/s" and pps["value"] >= 0
    assert by_metric["serve_survival_latency_p99_ms"]["unit"] == "ms"
    for rate in ("shed_per_1k", "quar_per_1k"):
        line = by_metric[f"serve_survival_{rate}"]
        assert line["unit"] == "per_1k"
        assert 0.0 <= line["value"] <= 1000.0
    gate = by_metric["serve_survival_slo_pass"]
    assert gate["unit"] == "bool" and gate["value"] in (0, 1)


def test_serve_pool_emits_pool_metrics(bench, capsys):
    """bench_serve_pool replays the stream against a 1-member and a
    K-member pool server with a live device kill and self-emits four
    lines: pool throughput, scaling vs one device, failover recovery
    wall, and the retune hot-swap count."""
    bench.bench_serve_pool(problems=8, rate_hz=2000.0, nrhs=2,
                           sizes=(8, 16), members=2)
    by_metric = {ln["metric"]: ln for ln in _lines(capsys)}
    assert set(by_metric) == {
        "serve_pool_problems_per_s",
        "serve_pool_scaling",
        "serve_pool_failover_recovery_ms",
        "serve_pool_retune_swaps"}
    pps = by_metric["serve_pool_problems_per_s"]
    assert pps["schema"] == "slate-bench-v1" and "chip" in pps
    assert pps["unit"] == "problems/s" and pps["value"] > 0
    assert by_metric["serve_pool_scaling"]["unit"] == "x"
    assert by_metric["serve_pool_scaling"]["value"] > 0
    rec = by_metric["serve_pool_failover_recovery_ms"]
    assert rec["unit"] == "ms"
    assert rec["value"] is None or rec["value"] >= 0
    swaps = by_metric["serve_pool_retune_swaps"]
    assert swaps["unit"] == "count" and swaps["value"] >= 0


def test_step_lists_cover_every_metric(bench):
    """Both step lists must include the RBT speculation metric and stay
    callable (functions exist, kwargs are their signature's names)."""
    import inspect
    for steps in (bench.QUICK_STEPS, bench.FULL_STEPS):
        names = [fn.__name__ for fn, _ in steps]
        assert "bench_gesv_rbt" in names
        assert "bench_gesv_abft" in names
        assert "bench_posv_abft" in names
        assert "bench_serve_mixed" in names
        assert "bench_serve_ragged" in names
        assert "bench_serve_bf16" in names
        assert "bench_serve_survival" in names
        assert "bench_serve_pool" in names
        assert "bench_potrf_ooc" in names
        assert "bench_checkpoint_overhead" in names
        for fn, kwargs in steps:
            sig = inspect.signature(fn)
            assert set(kwargs) == set(sig.parameters)


def test_budget_preempts_slow_metric(bench, capsys):
    """A metric that overruns the pool is SIGALRM-preempted and reported
    as skipped; the harness moves on instead of hanging to rc=124."""

    def sleepy():
        time.sleep(30)

    t0 = time.monotonic()
    failures = bench._run_isolated([(sleepy, {})], budget_s=0.3)
    elapsed = time.monotonic() - t0
    assert elapsed < 5
    assert failures == 0
    lines = _lines(capsys)
    assert len(lines) == 1
    assert lines[0]["skipped"] is True
    assert lines[0]["metric"] == "sleepy_skipped"
    assert "preempted" in lines[0]["reason"]
    # triage fields: which phase it died in and how long it got
    assert lines[0]["schema"] == "slate-bench-v1"
    assert lines[0]["phase"] == "compile"
    assert lines[0]["elapsed_s"] >= 0.3


def test_budget_skips_up_front(bench, capsys, monkeypatch):
    """When earlier metrics ate the whole pool, later ones emit a skipped
    line up front — one JSON line per step, no matter what."""
    t = [0.0]

    def fake_clock():
        t[0] += 40.0
        return t[0]

    monkeypatch.setattr(bench.time, "monotonic", fake_clock)
    ran = []

    def quick():
        ran.append(1)

    def never():
        raise AssertionError("must be skipped before running")

    # pool = 2 * 30 = 60s of fake time: quick runs (clock 80 > deadline
    # 100? no: deadline = 40 + 60 = 100, check at 80), never is skipped
    failures = bench._run_isolated([(quick, {}), (never, {})], budget_s=30)
    assert failures == 0
    assert ran == [1]
    lines = _lines(capsys)
    assert len(lines) == 1
    assert lines[0]["skipped"] is True
    assert lines[0]["metric"] == "never_skipped"
    assert lines[0]["reason"] == "time budget exhausted"
    assert lines[0]["schema"] == "slate-bench-v1" and "chip" in lines[0]


def test_no_budget_is_unlimited(bench, capsys):
    ran = []
    bench._run_isolated([(lambda: ran.append(1), {})], budget_s=None)
    assert ran == [1]


def test_failures_are_isolated_and_main_exits_zero(bench, capsys,
                                                   monkeypatch):
    """A raising metric emits an error line; main() still returns 0 (the
    r04 regression was rc=1 after isolated failures)."""

    def boom():
        raise RuntimeError("synthetic")

    ran = []
    monkeypatch.setattr(bench, "QUICK", True)
    monkeypatch.setattr(bench, "QUICK_STEPS",
                        [(boom, {}), (lambda: ran.append(1), {})])
    rc = bench.main()
    assert rc == 0
    assert ran == [1]
    lines = _lines(capsys)
    assert len(lines) == 1                # boom's error line; the lambda
    assert lines[0]["metric"] == "boom_error"   # emits nothing itself
    assert "synthetic" in lines[0]["error"]


def test_watchdog_fires_and_exits_zero(bench, capsys, monkeypatch):
    """The watchdog thread escapes even a stuck C++ compile (where SIGALRM
    is queued but never delivered): past the grace deadline it emits a
    skipped line for every step not yet done and hard-exits 0."""
    monkeypatch.setattr(bench, "_WATCHDOG_GRACE_S", 0.0)
    exited = []
    fired = time.monotonic()

    def fake_exit(rc):
        exited.append((rc, time.monotonic() - fired))

    def stuck():
        pass                              # stands in for a blocked compile

    steps = [(stuck, {}), (stuck, {})]
    done = {0}                            # step 0 already emitted its line
    stop = bench._install_watchdog(steps, deadline=time.monotonic() - 1,
                                   done=done, exit_fn=fake_exit)
    deadline = time.monotonic() + 5
    while not exited and time.monotonic() < deadline:
        time.sleep(0.01)
    stop.set()
    assert exited and exited[0][0] == 0
    lines = _lines(capsys)
    assert len(lines) == 1                # only the NOT-done index reported
    assert lines[0]["metric"] == "stuck_skipped"
    assert "watchdog" in lines[0]["reason"]


def test_watchdog_stands_down_on_stop(bench, capsys):
    """stop.set() before the deadline means no exit and no skip lines."""
    exited = []
    stop = bench._install_watchdog([(time.sleep, {})],
                                   deadline=time.monotonic() + 0.2,
                                   done=set(), exit_fn=exited.append)
    stop.set()
    time.sleep(0.5)
    assert exited == []
    assert _lines(capsys) == []


def test_main_arms_watchdog_before_first_compile(bench, monkeypatch):
    """The r05 stall happened inside the FIRST compile; the watchdog must
    already be armed when _chip_peak (first device contact) runs."""
    order = []
    monkeypatch.setattr(bench, "BUDGET_S", 30.0)
    monkeypatch.setattr(
        bench, "_install_watchdog",
        lambda *a, **k: (order.append("watchdog"),
                         __import__("threading").Event())[1])
    monkeypatch.setattr(
        bench, "_chip_peak",
        lambda: (order.append("chip_peak"), (None, "cpu"))[1])
    monkeypatch.setattr(bench, "_run_isolated", lambda *a, **k: 0)
    assert bench.main() == 0
    assert order == ["watchdog", "chip_peak"]


def test_sweep_nb_mode_emits_candidate_lines(bench, capsys, monkeypatch):
    """--sweep-nb emits one JSON line per candidate plan with the plan
    knobs inline, and main still returns 0."""
    from slate_tpu.tune import TilePlan, autotune

    def fake_sweep(op, n, dtype, iters):
        yield TilePlan(kernel="xla", nb=n, bw=8), 10.0
        yield TilePlan(kernel="pallas", nb=128, bw=16), 20.0

    monkeypatch.setattr(autotune, "sweep", fake_sweep)
    monkeypatch.setattr(bench, "_chip_peak", lambda: (None, "cpu"))
    rc = bench.main(("--sweep-nb",))
    assert rc == 0
    lines = _lines(capsys)
    from slate_tpu.tune import OPS
    assert len(lines) == 2 * len(OPS)
    for line in lines:
        assert line["schema"] == "slate-bench-v1"
        assert line["metric"].startswith("sweep_")
        assert line["kernel"] in ("xla", "pallas")
        assert isinstance(line["nb"], int) and isinstance(line["bw"], int)
        assert line["unit"] == "GFLOP/s"
        assert line["value"] > 0


def test_bench_lines_priced_from_obs_flops_registry(bench, capsys,
                                                    monkeypatch):
    """One registry, two consumers: a bench line's flops count is the
    obs.flops model verbatim, and its mfu agrees with what a timed obs
    event would compute from the same flops/seconds measurement."""
    import math

    from slate_tpu.obs import flops

    monkeypatch.setattr(bench, "PEAK", 1e12)
    bench.bench_gemm(n=64, nb=32, iters=2)
    (line,) = _lines(capsys)
    assert line["flops"] == flops.op_flops("gemm", [(64, 64), (64, 64)])
    assert line["device_ms"] is not None and line["device_ms"] > 0
    assert isinstance(line["mfu"], float) and line["mfu"] > 0
    with flops.peak_override(1e12):
        event_style = flops.mfu(line["flops"], line["device_ms"] * 1e-3)
    assert event_style is not None
    # bench prices from the unrounded seconds; allow the device_ms
    # round-trip (1 µs quantization) plus the two mfu roundings — the
    # line's mfu is rounded to 3 decimals, a 5e-4 quantum, so the
    # absolute band must sit strictly above it
    assert math.isclose(line["mfu"], event_style, rel_tol=0.05,
                        abs_tol=6e-4)


def test_bench_lines_carry_device_ms_and_flops(bench, capsys):
    bench.bench_posv(n=64, nb=32, nrhs=4, iters=1)
    (line,) = _lines(capsys)
    from slate_tpu.obs import flops
    assert line["flops"] == flops.op_flops("posv", [(64, 64), (64, 4)])
    assert line["device_ms"] > 0
    # GFLOP/s, flops and device_ms must be one consistent measurement;
    # value is emitted rounded to 1 decimal, so allow that 0.05 absolute
    # quantum on top of the relative band (at CPU speeds the unrounded
    # GFLOP/s sits near the rounding boundary and rel_tol alone flakes)
    derived = line["flops"] / (line["device_ms"] * 1e-3) / 1e9
    import math
    assert math.isclose(derived, line["value"], rel_tol=0.05,
                        abs_tol=0.051)
