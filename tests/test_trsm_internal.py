"""internal/trsm.py kernel tests: log-depth triangular inversion and the
blocked substitution sweeps at sizes that are NOT a multiple of nb (the
ragged last block is identity-augmented inside the kernels), both dtypes,
against XLA's reference triangular_solve.
"""

import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax

from slate_tpu.internal.trsm import (tri_inv_lower, tri_inv_upper,
                                     trsm_left_blocked, trsm_right_blocked)

# ragged at both dtypes, exact-multiple sanity at f64 only — the blocked
# sweeps compile one program per (shape, dtype) and tier-1 pays every one
SIZES = [(np.float64, 37, 8), (np.float64, 24, 8), (np.float32, 37, 8)]


def _lower(rng, n, dtype):
    a = rng.standard_normal((n, n)).astype(dtype)
    return np.tril(a) + n * np.eye(n, dtype=dtype)


@pytest.mark.parametrize("dtype", [np.float32, np.float64])
@pytest.mark.parametrize("n", [13, 64])
def test_tri_inv_ragged(rng, dtype, n):
    tol = 5e-5 if dtype == np.float32 else 1e-11
    L = _lower(rng, n, dtype)
    np.testing.assert_allclose(np.asarray(tri_inv_lower(jnp.asarray(L))),
                               np.linalg.inv(L), rtol=tol, atol=tol)
    U = L.T.copy()
    np.testing.assert_allclose(np.asarray(tri_inv_upper(jnp.asarray(U))),
                               np.linalg.inv(U), rtol=tol, atol=tol)


@pytest.mark.parametrize("dtype,n,nb", SIZES)
@pytest.mark.parametrize("lower", [True, False])
@pytest.mark.parametrize("trans", [False, True])
def test_trsm_left_blocked_ragged(rng, dtype, n, nb, lower, trans):
    tol = 2e-4 if dtype == np.float32 else 1e-10
    L = _lower(rng, n, dtype)
    a = L if lower else L.T.copy()
    b = rng.standard_normal((n, 5)).astype(dtype)
    got = trsm_left_blocked(jnp.asarray(a), jnp.asarray(b), lower=lower,
                            trans=trans, conj=False, unit=False, nb=nb)
    want = lax.linalg.triangular_solve(
        jnp.asarray(a.T if trans else a), jnp.asarray(b), left_side=True,
        lower=(lower != trans))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=tol, atol=tol)


@pytest.mark.parametrize("dtype,n,nb", SIZES)
@pytest.mark.parametrize("lower", [True, False])
@pytest.mark.parametrize("trans", [False, True])
def test_trsm_right_blocked_ragged(rng, dtype, n, nb, lower, trans):
    tol = 2e-4 if dtype == np.float32 else 1e-10
    L = _lower(rng, n, dtype)
    a = L if lower else L.T.copy()
    b = rng.standard_normal((5, n)).astype(dtype)
    got = trsm_right_blocked(jnp.asarray(a), jnp.asarray(b), lower=lower,
                             trans=trans, conj=False, unit=False, nb=nb)
    want = lax.linalg.triangular_solve(
        jnp.asarray(a.T if trans else a), jnp.asarray(b), left_side=False,
        lower=(lower != trans))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=tol, atol=tol)


@pytest.mark.parametrize("n,nb", [(37, 8)])
def test_trsm_left_blocked_unit_diag(rng, n, nb):
    L = _lower(rng, n, np.float64)
    b = rng.standard_normal((n, 3))
    got = trsm_left_blocked(jnp.asarray(L), jnp.asarray(b), lower=True,
                            trans=False, conj=False, unit=True, nb=nb)
    want = lax.linalg.triangular_solve(jnp.asarray(L), jnp.asarray(b),
                                       left_side=True, lower=True,
                                       unit_diagonal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-11, atol=1e-11)


def test_driver_trsm_ragged_blocked_path(rng):
    """drivers/blas3.trsm now routes ragged n >= 2 nb through the blocked
    kernels; the result must match a dense solve."""
    import slate_tpu as st
    n, nb = 37, 8
    L = _lower(rng, n, np.float64)
    b = rng.standard_normal((n, 4))
    T = st.TriangularMatrix.from_numpy(L, nb, uplo=st.Uplo.Lower)
    B = st.Matrix.from_numpy(b, nb)
    X = st.trsm(st.Side.Left, 1.0, T, B)
    np.testing.assert_allclose(X.to_numpy(), np.linalg.solve(L, b),
                               rtol=1e-11, atol=1e-11)
