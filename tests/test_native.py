"""Native host runtime (native/slate_tpu_native.cc via slate_tpu.native):
tile pack/unpack equivalence with the jnp layout ops, numroc parity, and
the from_numpy/to_numpy fast paths.  Builds the library on the fly when a
toolchain is present; everything else falls back and is skipped."""

import pathlib
import shutil
import subprocess

import jax.numpy as jnp
import numpy as np
import pytest

import slate_tpu as st
from slate_tpu import native
from slate_tpu.core import layout

REPO = pathlib.Path(st.__file__).parent.parent


@pytest.fixture(scope="module")
def lib():
    if not native.available():
        if shutil.which("g++") is None and shutil.which("c++") is None:
            pytest.skip("no C++ toolchain")
        subprocess.run(["make", "-C", str(REPO / "native")], check=True)
        native._LIB = None                      # force reload
    if not native.available():
        pytest.skip("native build failed")
    return native


def test_version(lib):
    assert lib.version() >= 20260730


@pytest.mark.parametrize("shape", [(10, 7, 4, 3, 2, 2), (16, 16, 4, 4, 1, 1),
                                   (33, 29, 8, 8, 2, 4), (5, 5, 8, 8, 2, 2)])
@pytest.mark.parametrize("dt", [np.float64, np.float32])
def test_pack_matches_layout(lib, rng, shape, dt):
    m, n, mb, nb, p, q = shape
    a = rng.standard_normal((m, n)).astype(dt)
    ref = np.asarray(layout.canonical_to_cyclic(
        layout.tile_dense(jnp.asarray(a), mb, nb), p, q))
    got = lib.pack_tiles(a, mb, nb, p, q)
    assert got is not None
    # tolerance only for the jnp path's transfer rounding; the native
    # round-trip below is required to be EXACT
    rtol = 1e-6 if dt == np.float32 else 1e-14
    np.testing.assert_allclose(got, ref, rtol=rtol, atol=0)
    back = lib.unpack_tiles(got, m, n, p, q)
    np.testing.assert_array_equal(back, a)


def test_numroc_parity(lib):
    # three independent implementations must agree: the compat tier's pure
    # Python, native.py's fallback body, and the C library
    from slate_tpu.compat.scalapack import numroc as py_numroc
    saved = native._LIB
    for n in (1, 7, 16, 100):
        for nb in (1, 3, 8):
            for np_ in (1, 2, 5):
                for ip in range(np_):
                    c_val = lib.numroc(n, nb, ip, 0, np_)
                    assert py_numroc(n, nb, ip, 0, np_) == c_val
                    try:
                        native._LIB = False     # force the Python fallback
                        assert native.numroc(n, nb, ip, 0, np_) == c_val
                    finally:
                        native._LIB = saved
                assert sum(py_numroc(n, nb, i, 0, np_)
                           for i in range(np_)) == n


def test_from_numpy_uses_native(lib, rng, monkeypatch):
    # the public import path must actually REACH the native packer (a
    # jnp.asarray pre-conversion once made this path dead code), and the
    # host and jnp paths must build identical storage
    m, n, mb, nb = 23, 17, 8, 8
    a = rng.standard_normal((m, n))
    calls = []
    orig = native.pack_tiles
    monkeypatch.setattr(native, "pack_tiles",
                        lambda *args: calls.append(1) or orig(*args))
    A = st.Matrix.from_numpy(a, mb, nb)
    assert calls, "Matrix.from_numpy did not reach native.pack_tiles"
    np.testing.assert_array_equal(A.to_numpy(), a)   # native round-trip
    B = st.Matrix(st.TileStorage.from_dense(jnp.asarray(a), mb, nb))
    np.testing.assert_allclose(np.asarray(A.storage.data),
                               np.asarray(B.storage.data), rtol=1e-14)


def test_complex_falls_back(lib, rng):
    a = (rng.standard_normal((8, 8))
         + 1j * rng.standard_normal((8, 8)))
    A = st.Matrix.from_numpy(a, 4, 4)               # jnp fallback path
    np.testing.assert_allclose(A.to_numpy(), a, atol=1e-14)
