"""Serving-layer tests (slate_tpu/serve/): bucket ladder, identity-
augmentation packing, the Server front end, the executable cache, and
the observability contract.

The load-bearing guarantees:

- packing is EXACT — a problem served from a bucket matches the
  unpadded solve at rounding level (blockdiag(A, I) decouples);
- bucket-boundary sizes (n exactly at a rung, one above, singleton
  batches) pack and unpack correctly;
- one poisoned problem escalates IN-GRAPH while its batch neighbors
  ride the fast rung, and only its Result says so;
- a warmed server never retraces and never compiles again: the second
  pass over the same workload produces zero retrace-sentinel warnings,
  zero cache misses, and serve_batch events with compiled=False —
  asserted from the obs events, which is how production would see it;
- the tuned serving ladder (tune.serve_buckets) overrides the
  geometric default and is credited in the events;
- ``python -m slate_tpu.obs`` aggregates serve_batch records into the
  serving table.
"""

import json
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from slate_tpu import obs, serve, tune
from slate_tpu.serve import bucket

RES_TOL = 100  # residual < RES_TOL * eps * n — the certificate reading


def _workload_rng():
    return np.random.default_rng(1234)


def _mk_solve(rng, n, k, dtype):
    a = rng.standard_normal((n, n)).astype(dtype)
    a += np.eye(n, dtype=dtype) * 4
    return a, rng.standard_normal((n, k)).astype(dtype)


def _mk_chol(rng, n, k, dtype):
    a = rng.standard_normal((n, n)).astype(dtype)
    spd = (a @ a.T / n + np.eye(n, dtype=dtype)).astype(dtype)
    return spd, rng.standard_normal((n, k)).astype(dtype)


def _mk_gels(rng, n, k, dtype):
    a = rng.standard_normal((n + 10, n)).astype(dtype)
    return a, rng.standard_normal((n + 10, k)).astype(dtype)


def _residual(a, x, b):
    a, x, b = (v.astype(np.float64) for v in (a, x, b))
    denom = np.linalg.norm(a) * np.linalg.norm(x) + np.linalg.norm(b)
    return np.linalg.norm(a @ x - b) / max(denom, 1e-300)


def _check(req, res):
    """Certificate-tolerance check of one served Result."""
    op, a, b = req
    eps = float(np.finfo(a.dtype).eps)
    n = a.shape[1]
    if op == "least_squares_solve":
        # optimality: residual orthogonal to range(A)
        r = (a.astype(np.float64) @ res.x.astype(np.float64)
             - b.astype(np.float64))
        grad = np.linalg.norm(a.T.astype(np.float64) @ r)
        scale = np.linalg.norm(a) ** 2 * max(np.linalg.norm(res.x), 1.0)
        assert grad / scale < RES_TOL * eps * n
    else:
        assert _residual(a, res.x, b) < RES_TOL * eps * n
    assert res.x.shape == (n, b.shape[1])
    assert bool(res.health.ok)


def _serve_events(records):
    return [e for e in records if e.get("kind") == "serve_batch"]


# ------------------------------------------------------------- ladder


def test_geometric_ladder_rounds_up():
    lad = bucket.geometric_ladder(base=32, top=256)
    assert lad.rungs == (32, 64, 128, 256)
    assert lad.source == "geometric"
    assert lad.bucket_for(1) == 32
    assert lad.bucket_for(32) == 32        # exactly at a rung: no pad
    assert lad.bucket_for(33) == 64        # one above: next rung
    assert lad.bucket_for(256) == 256
    assert lad.bucket_for(257) == 512      # beyond top: keep doubling
    assert lad.bucket_for(3000) == 4096
    with pytest.raises(ValueError):
        lad.bucket_for(0)


def test_next_pow2():
    assert [bucket.next_pow2(v) for v in (0, 1, 2, 3, 4, 5, 9)] == \
        [1, 1, 2, 4, 4, 8, 16]


def test_least_squares_buckets_hold_identity_rows():
    lad = bucket.geometric_ladder()
    mb, nb, kb = bucket.least_squares_buckets(lad, 50, 20, 5)
    assert nb == 32 and kb == 8
    assert mb >= 50 + (nb - 20)            # room for the identity block
    a = jnp.asarray(np.random.default_rng(0).standard_normal((50, 20)))
    padded = np.asarray(bucket.pad_tall(a, mb, nb))
    assert np.linalg.matrix_rank(padded) == nb   # stays full column rank


def test_pad_square_is_blockdiag_identity():
    rng = _workload_rng()
    a, b = _mk_solve(rng, 20, 3, np.float64)
    ap = np.asarray(bucket.pad_square(jnp.asarray(a), 32))
    np.testing.assert_array_equal(ap[:20, :20], a)
    np.testing.assert_array_equal(ap[20:, 20:], np.eye(12))
    np.testing.assert_array_equal(ap[:20, 20:], 0)
    # the padded system solves to [x; 0] exactly (decoupled)
    bp = np.asarray(bucket.pad_rows(jnp.asarray(b), 32, 4))
    xp = np.linalg.solve(ap, bp)
    np.testing.assert_allclose(xp[:20, :3], np.linalg.solve(a, b),
                               rtol=1e-12, atol=1e-12)
    np.testing.assert_allclose(xp[20:], 0, atol=1e-300)


# ------------------------------------------------------------- server


@pytest.mark.parametrize("n", [32, 33, 20])
@pytest.mark.parametrize("op,mk", [
    ("solve", _mk_solve), ("chol_solve", _mk_chol),
    ("least_squares_solve", _mk_gels)], ids=["solve", "chol", "gels"])
def test_singleton_and_boundary_sizes(op, mk, n):
    """Bucket-edge sizes (exactly at a rung, one above) and a singleton
    batch unpack to the right shapes and certificate-level accuracy."""
    rng = _workload_rng()
    a, b = mk(rng, n, 3, np.float64)
    srv = serve.Server(cache=serve.ExecutableCache())
    with obs.recording() as recs:
        (res,) = srv.serve_batch([(op, a, b)])
    _check((op, a, b), res)
    assert res.escalated in (False, True)
    (ev,) = _serve_events(recs)
    assert ev["problems"] == 1 and ev["batch"] == 1
    assert ev["occupancy"] == 1.0
    expected_nb = bucket.geometric_ladder().bucket_for(n)
    assert expected_nb in ev["bucket"]


def test_mixed_workload_parity_and_isolated_escalation():
    """The acceptance workload: >= 64 problems, n spanning >= 3 buckets,
    both dtypes, served in bucketed batches — every result within
    certificate tolerance of its per-problem reference, with poisoned
    members escalating independently of their batch neighbors."""
    rng = _workload_rng()
    reqs, poisoned = [], []
    for dtype in (np.float32, np.float64):
        for n in (20, 40, 70):             # buckets 32, 64, 128
            for j in range(4):
                reqs.append(("solve", *_mk_solve(rng, n, 3, dtype)))
                reqs.append(("chol_solve", *_mk_chol(rng, n, 3, dtype)))
                reqs.append(("least_squares_solve",
                             *_mk_gels(rng, n, 2, dtype)))
    # poison one solve member per dtype: row 0 = e_{n-1} kills the NoPiv
    # fast rung (zero leading pivot) but partial pivoting handles it
    for dtype in (np.float32, np.float64):
        n = 40
        a, b = _mk_solve(rng, n, 3, dtype)
        a[0, :] = 0.0
        a[0, n - 1] = 1.0
        poisoned.append(len(reqs))
        reqs.append(("solve", a, b))
    assert len(reqs) >= 64

    srv = serve.Server(cache=serve.ExecutableCache())
    results = srv.serve_batch(reqs)
    assert len(results) == len(reqs)
    for i, (req, res) in enumerate(zip(reqs, results)):
        _check(req, res)
    for i in poisoned:
        assert results[i].escalated, "poisoned member must escalate"
    # escalation stayed per-problem: the healthy solves in the same
    # (op, dtype, bucket) batch as the poisoned ones rode the fast rung
    neighbors = [i for i, r in enumerate(reqs)
                 if r[0] == "solve" and r[1].shape[0] == 40
                 and i not in poisoned]
    assert neighbors and not any(results[i].escalated for i in neighbors)


def test_warm_server_never_retraces_or_recompiles():
    """After warmup, a repeat of the same mixed workload is all cache
    hits: zero retrace-sentinel warnings (filter promoted to error),
    zero new executable-cache entries, compiled=False on every
    serve_batch event — asserted via the obs events."""
    rng = _workload_rng()
    reqs = []
    for n in (20, 40):
        reqs.append(("solve", *_mk_solve(rng, n, 3, np.float64)))
        reqs.append(("chol_solve", *_mk_chol(rng, n, 3, np.float64)))
        reqs.append(("least_squares_solve",
                     *_mk_gels(rng, n, 2, np.float64)))
    srv = serve.Server(cache=serve.ExecutableCache())
    with obs.recording() as cold:
        srv.serve_batch(reqs)
    cold_ev = _serve_events(cold)
    assert cold_ev and all(e["compiled"] for e in cold_ev)
    entries0 = srv.cache.stats()["entries"]
    traces0 = sum(s["traces"] for s in obs.sentinel_stats().values())

    with warnings.catch_warnings():
        warnings.simplefilter("error", obs.SlateRetraceWarning)
        with obs.recording() as warm:
            results = srv.serve_batch(reqs)
    warm_ev = _serve_events(warm)
    assert len(warm_ev) == len(cold_ev)
    assert not any(e["compiled"] for e in warm_ev)
    assert all(e["retraces"] == 0 for e in warm_ev)
    assert all(e["cache"]["entries"] == entries0 for e in warm_ev)
    traces1 = sum(s["traces"] for s in obs.sentinel_stats().values())
    assert traces1 == traces0
    for req, res in zip(reqs, results):
        _check(req, res)


def test_donation_steady_state_submit_loop():
    """The steady-state serving loop — many drains against one warmed
    executable, B donated each call — stays retrace-free and keeps
    producing correct results from the (re)donated buffers."""
    rng = _workload_rng()
    srv = serve.Server(cache=serve.ExecutableCache())
    warm = [("solve", *_mk_solve(rng, 24, 3, np.float64))
            for _ in range(2)]
    srv.serve_batch(warm)
    traces0 = sum(s["traces"] for s in obs.sentinel_stats().values())
    with warnings.catch_warnings():
        warnings.simplefilter("error", obs.SlateRetraceWarning)
        for _ in range(5):
            reqs = [("solve", *_mk_solve(rng, 24, 3, np.float64))
                    for _ in range(2)]
            for req, res in zip(reqs, srv.serve_batch(reqs)):
                _check(req, res)
    assert sum(s["traces"] for s in obs.sentinel_stats().values()) == traces0
    st = srv.cache.stats()
    assert st["entries"] == 1 and st["misses"] == 1 and st["hits"] == 5


def test_submit_validation():
    srv = serve.Server(cache=serve.ExecutableCache())
    a, b = _mk_solve(_workload_rng(), 8, 2, np.float64)
    with pytest.raises(ValueError, match="unknown op"):
        srv.submit("qr", a, b)
    with pytest.raises(ValueError, match="2-D"):
        srv.submit("solve", a[0], b)
    with pytest.raises(ValueError, match="dtypes differ"):
        srv.submit("solve", a, b.astype(np.float32))
    with pytest.raises(ValueError, match="square"):
        srv.submit("solve", a[:6], b[:6])
    with pytest.raises(ValueError, match="row"):
        srv.submit("solve", a, b[:6])
    with pytest.raises(ValueError, match="m >= n"):
        srv.submit("least_squares_solve", a[:6], b[:6])
    assert srv.drain() == []               # nothing valid was queued


# ------------------------------------------------- tuned ladder override


@pytest.fixture
def plan_cache(tmp_path, monkeypatch):
    path = tmp_path / "plans.json"
    monkeypatch.setenv("SLATE_TUNE_CACHE", str(path))
    tune.reload()
    yield path
    tune.reload()


def test_tuned_ladder_overrides_geometric(plan_cache):
    for rung in (48, 96, 192):
        tune.record_plan(tune.SERVE_BUCKET_OP, rung, "float64",
                         tune.XLA_PLAN)
    lad = bucket.default_ladder("float64")
    assert lad.source == "tuned"
    assert lad.rungs == (48, 96, 192)
    assert lad.bucket_for(50) == 96
    # untouched dtype falls back to geometric
    assert bucket.default_ladder("float32").source == "geometric"

    rng = _workload_rng()
    a, b = _mk_solve(rng, 40, 3, np.float64)
    srv = serve.Server(cache=serve.ExecutableCache())
    with obs.recording() as recs:
        (res,) = srv.serve_batch([("solve", a, b)])
    _check(("solve", a, b), res)
    (ev,) = _serve_events(recs)
    assert ev["ladder"] == "tuned"
    assert ev["bucket"][0] == 48           # tuned rung, not geometric 64


# ------------------------------------------------------ ragged fast rungs


def _record_ragged_plans(buckets=(32, 64)):
    """Persist Pallas plans for the batch kernels at the given bucket
    sizes, so `_ragged_plan` re-resolves them on every trace (including
    the warm pass) without a live override context."""
    for op in ("batch_potrf", "batch_getrf", "batch_geqrf"):
        for nb in buckets:
            tune.record_plan(op, nb, "float32",
                             tune.TilePlan("pallas", nb // 2, 8))


def test_ragged_route_selected_only_through_plan_cache(plan_cache):
    """SEAM011: make_batched routes the fast rung through the ragged
    batched Pallas kernels IFF tune.resolve_plan hands back a Pallas
    plan for the op's batch kernel at the bucket size — and the dtype /
    Abft gates fall back to the vmapped cores."""
    from slate_tpu.options import Abft, Option
    from slate_tpu.serve.batched import make_batched
    rng = _workload_rng()
    a32 = jnp.asarray(rng.standard_normal((2, 32, 32)), jnp.float32)
    b32 = jnp.asarray(rng.standard_normal((2, 32, 2)), jnp.float32)
    sz = jnp.asarray([20, 32], jnp.int32)

    def routes_ragged(op, a, b, opts=None):
        with warnings.catch_warnings():
            # repeated abstract traces of the same signature are the
            # point of this test, not a serving regression
            warnings.simplefilter("ignore", obs.SlateRetraceWarning)
            jaxpr = jax.make_jaxpr(make_batched(op, opts))(a, b, sz)
        return "pallas_call" in str(jaxpr)

    assert not routes_ragged("solve", a32, b32)   # plan miss -> vmapped
    _record_ragged_plans()
    assert routes_ragged("solve", a32, b32)
    assert routes_ragged("chol_solve", a32, b32)
    # dtype gate: float64 stays on the vmapped route even with plans
    assert not routes_ragged("solve", a32.astype(jnp.float64),
                             b32.astype(jnp.float64))
    # Abft gate: only batch_potrf carries the checksum rungs in-batch
    abft = {Option.Abft: Abft.On}
    assert not routes_ragged("solve", a32, b32, abft)
    assert routes_ragged("chol_solve", a32, b32, abft)


def test_warm_server_ragged_route_never_retraces(plan_cache):
    """The acceptance drill for the ragged serving rung: with Pallas
    plans persisted for the batch kernels, a float32 workload's fast
    rung runs as the ragged batched kernels (`sizes` traced, one
    executable per bucket), every result holds the certificate, and the
    warm repeat is all cache hits — zero retrace-sentinel warnings,
    zero new executables, compiled=False on every serve_batch event."""
    _record_ragged_plans()
    rng = _workload_rng()
    reqs = []
    for n in (20, 40):
        reqs.append(("solve", *_mk_solve(rng, n, 3, np.float32)))
        reqs.append(("chol_solve", *_mk_chol(rng, n, 3, np.float32)))
        reqs.append(("least_squares_solve",
                     *_mk_gels(rng, n, 2, np.float32)))
    srv = serve.Server(cache=serve.ExecutableCache())
    with obs.recording() as cold:
        results = srv.serve_batch(reqs)
    for req, res in zip(reqs, results):
        _check(req, res)
    cold_ev = _serve_events(cold)
    assert cold_ev and all(e["compiled"] for e in cold_ev)
    entries0 = srv.cache.stats()["entries"]
    traces0 = sum(s["traces"] for s in obs.sentinel_stats().values())

    with warnings.catch_warnings():
        warnings.simplefilter("error", obs.SlateRetraceWarning)
        with obs.recording() as warm:
            results = srv.serve_batch(reqs)
    warm_ev = _serve_events(warm)
    assert len(warm_ev) == len(cold_ev)
    assert not any(e["compiled"] for e in warm_ev)
    assert all(e["retraces"] == 0 for e in warm_ev)
    assert srv.cache.stats()["entries"] == entries0
    assert sum(s["traces"] for s in obs.sentinel_stats().values()) == traces0
    for req, res in zip(reqs, results):
        _check(req, res)


# ------------------------------------------------------- obs aggregation


def test_metrics_serving_table(tmp_path):
    rng = _workload_rng()
    reqs = []
    for n in (20, 40):
        for _ in range(2):
            reqs.append(("solve", *_mk_solve(rng, n, 3, np.float32)))
            reqs.append(("chol_solve", *_mk_chol(rng, n, 3, np.float32)))
    srv = serve.Server(cache=serve.ExecutableCache())
    with obs.recording() as recs:
        srv.serve_batch(reqs)
        srv.serve_batch(reqs)              # a warm round too
    path = tmp_path / "events.jsonl"
    path.write_text("".join(json.dumps(e) + "\n" for e in recs))

    summary = obs.summarize([str(path)])
    assert summary["counts"]["serve"] == len(_serve_events(recs))
    table = summary["serve"]
    assert "solve/float32" in table and "chol_solve/float32" in table
    row = table["solve/float32"]
    assert row["problems"] == 8            # 4 per round, 2 rounds
    assert row["batches"] == 4             # 2 buckets x 2 rounds
    assert 0.0 < row["occupancy_p50"] <= 1.0
    assert row["occupancy_p99"] <= 1.0
    assert 0.0 <= row["padding_waste_p50"] < 1.0
    assert row["esc_per_1k"] == 0.0
    assert row["compiles"] == 2            # cold round only
    assert row["retraces"] >= 0
    # waste-adjusted problems/s: batches carry dur_ms, so the column is
    # populated and exceeds the raw rate (waste > 0 at these sizes)
    assert row["wa_pps"] is not None and row["wa_pps"] > 0

    from slate_tpu.obs import metrics
    text = metrics.render(summary)
    assert "serving" in text and "solve/float32" in text
    assert "esc/1k" in text and "wa_pps" in text


# ---------------------------------------------------- flight recorder


def test_flight_recorder_stamps_every_problem():
    """Every serve_batch event carries the drain-time queue depth and
    per-problem submit->flush age / submit->result latency lists — the
    tail-latency inputs obs.slo aggregates."""
    rng = _workload_rng()
    srv = serve.Server(cache=serve.ExecutableCache())
    reqs = [("solve", *_mk_solve(rng, n, 2, np.float64))
            for n in (20, 24, 40)]          # buckets 32, 32, 64
    with obs.recording() as recs:
        srv.serve_batch(reqs)
    evs = _serve_events(recs)
    assert len(evs) == 2                    # two buckets
    assert sum(e["problems"] for e in evs) == 3
    for e in evs:
        assert e["queue_depth"] == 3        # whole drain, not this batch
        assert len(e["age_at_flush_ms"]) == e["problems"]
        assert len(e["latency_ms"]) == e["problems"]
        for age, lat in zip(e["age_at_flush_ms"], e["latency_ms"]):
            assert 0.0 <= age < lat         # result lands after flush


def test_flight_recorder_latency_reaches_serving_table(tmp_path):
    rng = _workload_rng()
    srv = serve.Server(cache=serve.ExecutableCache())
    with obs.recording() as recs:
        srv.serve_batch([("solve", *_mk_solve(rng, 20, 2, np.float64))
                         for _ in range(3)])
    path = tmp_path / "events.jsonl"
    path.write_text("".join(json.dumps(e) + "\n" for e in recs))
    row = obs.summarize([str(path)])["serve"]["solve/float64"]
    assert row["latency_p50_ms"] is not None and row["latency_p50_ms"] > 0
    assert row["latency_p99_ms"] >= row["latency_p50_ms"]
    assert row["age_p99_ms"] is not None
    from slate_tpu.obs import metrics
    text = metrics.render(obs.summarize([str(path)]))
    assert "lat_p50_ms" in text and "lat_p99_ms" in text


def test_warm_server_zero_retrace_with_timing_on():
    """Timing mode is serving-safe: the block_until_ready sync happens
    after execution, outside tracing, so a warmed server stays warm with
    timing ON — and its events carry device_ms plus a waste-adjusted mfu
    priced over live problem flops only."""
    from slate_tpu.obs import flops
    rng = _workload_rng()
    srv = serve.Server(cache=serve.ExecutableCache())
    reqs = [("solve", *_mk_solve(rng, 20, 2, np.float64))
            for _ in range(2)]
    srv.serve_batch(reqs)                    # warm (timing off)
    entries0 = srv.cache.stats()["entries"]
    with warnings.catch_warnings():
        warnings.simplefilter("error", obs.SlateRetraceWarning)
        with flops.peak_override(1e12), obs.timing():
            with obs.recording() as recs:
                results = srv.serve_batch(reqs)
    for req, res in zip(reqs, results):
        _check(req, res)
    (ev,) = _serve_events(recs)
    assert not ev["compiled"] and ev["retraces"] == 0
    assert srv.cache.stats()["entries"] == entries0
    assert ev["device_ms"] is not None and ev["device_ms"] > 0
    # waste-adjusted by construction: live flops only, never the bucket's
    with flops.peak_override(1e12):
        expected = flops.mfu(
            flops.serve_flops("solve", [(a.shape, b.shape)
                                        for _, a, b in reqs]),
            ev["device_ms"] * 1e-3)
    assert expected is not None and ev["mfu"] == expected
    assert ev["achieved_gbps"] is not None


def test_serve_events_timing_off_fields_none():
    rng = _workload_rng()
    srv = serve.Server(cache=serve.ExecutableCache())
    with obs.recording() as recs:
        srv.serve_batch([("solve", *_mk_solve(rng, 20, 2, np.float64))])
    (ev,) = _serve_events(recs)
    assert ev["device_ms"] is None
    assert ev["mfu"] is None and ev["achieved_gbps"] is None


def test_concurrent_submit_while_draining():
    """submit/drain hold the queue lock: threads hammering submit while
    drains flush never tear tickets or lose problems."""
    import threading
    rng = _workload_rng()
    a, b = _mk_solve(rng, 16, 2, np.float64)
    srv = serve.Server(cache=serve.ExecutableCache())
    srv.serve_batch([("solve", a, b)])       # compile outside the race
    per_thread, n_threads = 8, 4
    start = threading.Barrier(n_threads)

    def pound():
        start.wait()
        for _ in range(per_thread):
            srv.submit("solve", a, b)

    threads = [threading.Thread(target=pound) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    results = srv.drain()
    assert len(results) == per_thread * n_threads
    want = np.linalg.solve(a, b)
    for res in results:
        assert res is not None
        np.testing.assert_allclose(res.x, want, rtol=1e-9, atol=1e-9)
    assert srv.drain() == []                 # queue fully swapped out


def test_cache_stats_report_compile_time():
    rng = _workload_rng()
    srv = serve.Server(cache=serve.ExecutableCache())
    assert srv.cache.stats()["compile_ms"] == 0.0
    srv.serve_batch([("solve", *_mk_solve(rng, 20, 2, np.float64))])
    cold_ms = srv.cache.stats()["compile_ms"]
    assert cold_ms > 0
    srv.serve_batch([("solve", *_mk_solve(rng, 20, 2, np.float64))])
    assert srv.cache.stats()["compile_ms"] == cold_ms   # hits are free
