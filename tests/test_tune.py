"""Autotuner plan store tests (slate_tpu/tune/): schema validation, the
record -> persist -> reload -> resolve round trip (including under jit,
where the resolved plan must lower to a pallas_call), nearest-n lookup,
the plan_override test seam, and the SLATE_PALLAS removal warning."""

import json
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from slate_tpu import tune
from slate_tpu.tune import (OPS, SCHEMA_VERSION, TilePlan, XLA_PLAN,
                            plan_override, record_plan, resolve_plan,
                            validate_cache)


@pytest.fixture
def cache(tmp_path, monkeypatch):
    """Point the plan cache at a fresh temp file for the test's scope."""
    path = tmp_path / "plans.json"
    monkeypatch.setenv("SLATE_TUNE_CACHE", str(path))
    monkeypatch.delenv("SLATE_PALLAS", raising=False)
    tune.reload()
    yield path
    tune.reload()


# ---- schema -------------------------------------------------------------


def _good_cache():
    return {"version": SCHEMA_VERSION, "chips": {"cpu": {
        "potrf_tile": {"n=512,dtype=float32":
                       {"kernel": "pallas", "nb": 512, "bw": 8,
                        "gflops": 123.4}}}}}


def test_schema_accepts_good_cache():
    validate_cache(_good_cache())                 # must not raise
    validate_cache({"version": SCHEMA_VERSION, "chips": {}})


@pytest.mark.parametrize("mutate,msg", [
    (lambda o: o.update(version=99), "version"),
    (lambda o: o.update(extra=1), "unknown top-level"),
    (lambda o: o.pop("chips"), "chips"),
    (lambda o: o["chips"].update(cpu={"bogus_op": {}}), "unknown op"),
    (lambda o: o["chips"]["cpu"]["potrf_tile"].update(
        {"n=1,dtype=f32": {"kernel": "magic", "nb": 1, "bw": 1}}), "kernel"),
    (lambda o: o["chips"]["cpu"]["potrf_tile"].update(
        {"n=1,dtype=f32": {"kernel": "xla", "nb": -4, "bw": 1}}), "nb"),
    (lambda o: o["chips"]["cpu"]["potrf_tile"].update(
        {"badkey": {"kernel": "xla", "nb": 1, "bw": 1}}), "key"),
], ids=["version", "extra-key", "no-chips", "bad-op", "bad-kernel",
        "bad-nb", "bad-entry-key"])
def test_schema_rejects_bad_cache(mutate, msg):
    obj = _good_cache()
    mutate(obj)
    with pytest.raises(ValueError):
        validate_cache(obj)


def test_repo_ships_no_invalid_default_cache(cache):
    """A fresh (missing) cache file resolves every op to the XLA plan."""
    for op in OPS:
        assert resolve_plan(op, 512) == XLA_PLAN


# ---- round trip ---------------------------------------------------------


def test_record_reload_resolve_roundtrip(cache):
    plan = TilePlan(kernel="pallas", nb=256, bw=16)
    record_plan("potrf_tile", 512, "float32", plan, gflops=42.0)
    assert cache.exists()
    on_disk = json.loads(cache.read_text())
    validate_cache(on_disk)
    chip = tune.chip_kind()
    ent = on_disk["chips"][chip]["potrf_tile"]["n=512,dtype=float32"]
    assert ent == {"kernel": "pallas", "nb": 256, "bw": 16, "gflops": 42.0}
    assert resolve_plan("potrf_tile", 512) == plan
    # other ops stay untuned
    assert resolve_plan("geqrf_panel", 512) == XLA_PLAN


def test_nearest_n_lookup(cache):
    near = TilePlan(kernel="pallas", nb=128, bw=8)
    far = TilePlan(kernel="pallas", nb=512, bw=16)
    record_plan("potrf_tile", 256, "float32", near)
    record_plan("potrf_tile", 4096, "float32", far)
    assert resolve_plan("potrf_tile", 384) == near     # log2-nearest
    assert resolve_plan("potrf_tile", 3000) == far
    # dtype must match exactly: no f32 plan leaks onto f64 calls
    assert resolve_plan("potrf_tile", 256, "float64") == XLA_PLAN


def test_resolved_plan_routes_pallas_under_jit(cache):
    """The cached plan is read at TRACE time: a jitted driver seam lowers
    to a pallas_call when the plan says pallas, with no cache access in
    the compiled program."""
    from slate_tpu.internal.potrf import potrf_tile
    record_plan("potrf_tile", 128, "float32",
                TilePlan(kernel="pallas", nb=128, bw=8))
    rng = np.random.default_rng(0)
    a0 = rng.standard_normal((128, 128)).astype(np.float32) * 0.1
    a = jnp.asarray(a0 @ a0.T + 128 * np.eye(128, dtype=np.float32))
    # fresh lambdas per trace: make_jaxpr caches by function identity +
    # avals, which would otherwise replay the first route
    jaxpr = str(jax.make_jaxpr(lambda x: potrf_tile(x))(a))
    assert "pallas_call" in jaxpr
    L = np.asarray(jax.jit(potrf_tile)(a))
    np.testing.assert_allclose(L, np.linalg.cholesky(np.asarray(a)),
                               rtol=2e-5, atol=5e-5)
    # and the XLA route stays pallas-free
    tune.reload()
    with plan_override("potrf_tile", XLA_PLAN):
        assert "pallas_call" not in str(
            jax.make_jaxpr(lambda x: potrf_tile(x))(a))


def test_corrupt_cache_file_warns_and_falls_back(cache):
    cache.write_text('{"version": 99}')
    tune.reload()
    with pytest.warns(UserWarning, match="ignoring bad plan cache"):
        assert resolve_plan("potrf_tile", 512) == XLA_PLAN


# ---- overrides and the deprecated env knob ------------------------------


def test_plan_override_scopes_and_restores(cache):
    forced = TilePlan(kernel="pallas", nb=128, bw=16)
    with plan_override("getrf_panel", forced):
        assert resolve_plan("getrf_panel", 384) == forced
        with plan_override("getrf_panel", XLA_PLAN):
            assert resolve_plan("getrf_panel", 384) == XLA_PLAN
        assert resolve_plan("getrf_panel", 384) == forced
    assert resolve_plan("getrf_panel", 384) == XLA_PLAN
    with pytest.raises(ValueError):
        with plan_override("bogus", forced):
            pass


def test_slate_pallas_env_is_removed_and_ignored(cache, monkeypatch):
    """SLATE_PALLAS no longer forces kernel routes: setting it warns once
    (pointing at plan_override / the tuner) and has NO effect on
    resolution in either direction."""
    monkeypatch.setenv("SLATE_PALLAS", "1")
    monkeypatch.setattr(tune.plans, "_WARNED", False)
    with pytest.warns(UserWarning, match="SLATE_PALLAS has been removed"):
        plan = resolve_plan("potrf_tile", 256)
    assert plan == XLA_PLAN                  # no force-on: untuned -> XLA
    # nor does force-off beat a cached pallas plan
    record_plan("potrf_tile", 256, "float32",
                TilePlan(kernel="pallas", nb=256, bw=8))
    monkeypatch.setenv("SLATE_PALLAS", "0")
    assert resolve_plan("potrf_tile", 256).kernel == "pallas"
    # the warning fired once per process: silent from here on
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        resolve_plan("potrf_tile", 256)


# ---- autotune measurement layer -----------------------------------------


def test_candidates_cover_xla_and_legal_pallas():
    from slate_tpu.tune import autotune
    cands = list(autotune.candidates("potrf_panel", 512, "float32"))
    assert XLA_PLAN in cands
    pallas = [c for c in cands if c.kernel == "pallas"]
    assert pallas and all(512 % c.nb == 0 for c in pallas)
    # geqrf_panel has no bw knob: one pallas candidate per nb
    qr = list(autotune.candidates("geqrf_panel", 512, "float32"))
    assert len({(c.kernel, c.nb) for c in qr}) == len(qr)


@pytest.mark.slow
def test_tune_op_persists_winner(cache):
    from slate_tpu.tune import autotune
    plan, gflops = autotune.tune_op("potrf_tile", 128, "float32", iters=1)
    assert gflops > 0
    assert resolve_plan("potrf_tile", 128) == plan


def test_candidates_cover_batch_ops():
    """The ragged serving kernels are tuned through the same candidate
    sweep: XLA baseline plus legal pallas (nb | n), batch_geqrf without
    a bw axis."""
    from slate_tpu.tune import autotune
    for op in ("batch_potrf", "batch_getrf"):
        cands = list(autotune.candidates(op, 256, "float32"))
        assert any(c.kernel == "xla" for c in cands)
        pallas = [c for c in cands if c.kernel == "pallas"]
        assert pallas and all(256 % c.nb == 0 for c in pallas)
    qr = list(autotune.candidates("batch_geqrf", 256, "float32"))
    assert any(c.kernel == "xla" for c in qr)
    assert len({(c.kernel, c.nb) for c in qr}) == len(qr)


@pytest.mark.slow
def test_measure_batch_ops_both_routes(cache):
    """Every batch-op candidate route actually runs and reports a
    positive live-work rate (pallas in interpret mode on CPU)."""
    from slate_tpu.tune import autotune
    for op in ("batch_potrf", "batch_getrf", "batch_geqrf"):
        for plan in (XLA_PLAN, TilePlan("pallas", 64, 8)):
            gf = autotune.measure(op, plan, 128, iters=1)
            assert gf > 0, (op, plan)


# ---- serve-bucket ladder fitting ----------------------------------------


def test_serve_ladder_from_sizes_dp():
    """The fitted ladder covers the max size, respects max_rungs, and
    never wastes more padded area than the geometric ladder."""
    from slate_tpu.tune import autotune
    rng = np.random.default_rng(7)
    sizes = ([int(x) for x in rng.integers(8, 120, 300)]
             + [500] * 40 + [700] * 3)
    ladder = autotune.serve_ladder_from_sizes(sizes, max_rungs=4)
    assert len(ladder) <= 4
    assert ladder == tuple(sorted(ladder))
    assert ladder[-1] >= max(sizes)
    assert all(r % 32 == 0 for r in ladder)
    from slate_tpu.serve import bucket
    tuned = autotune.ladder_waste(sizes, bucket.BucketLadder(ladder,
                                                             "tuned"))
    geo = autotune.ladder_waste(sizes, bucket.geometric_ladder())
    assert 0.0 <= tuned <= geo < 1.0
    # few distinct sizes: every edge becomes a rung, zero waste beyond
    # the 32-multiple roundup
    small = autotune.serve_ladder_from_sizes([64, 64, 128], max_rungs=8)
    assert small == (64, 128)
    with pytest.raises(ValueError):
        autotune.serve_ladder_from_sizes([0, -3])


def test_tune_serve_buckets_persists_and_serves(cache):
    """tune_serve_buckets round trip: persisted rungs come back through
    tune.serve_buckets and flip default_ladder to the tuned source."""
    from slate_tpu.serve import bucket
    from slate_tpu.tune import autotune
    sizes = [24, 24, 40, 90, 90, 200]
    rungs, w_geo, w_tuned = autotune.tune_serve_buckets(
        sizes, dtype="float32", max_rungs=3)
    assert len(rungs) <= 3 and rungs[-1] >= 200
    assert w_tuned <= w_geo
    assert tune.serve_buckets("float32") == rungs
    lad = bucket.default_ladder("float32")
    assert lad.source == "tuned" and lad.rungs == rungs


def test_cli_serve_hist_fits_and_persists(cache, tmp_path, capsys):
    """`python -m slate_tpu.tune --serve-hist` reads a request-size
    JSONL (bare ints and {"n": ...} records), prints one line per rung
    plus a summary, and persists unless --dry-run."""
    from slate_tpu.tune.__main__ import main
    hist = tmp_path / "hist.jsonl"
    hist.write_text("\n".join(["17", '{"n": 48}', '{"size": 48}',
                               "100", "100", "130"]) + "\n")
    assert main(["--serve-hist", str(hist), "--hist-rungs", "3"]) == 0
    lines = [json.loads(ln) for ln in
             capsys.readouterr().out.strip().splitlines()]
    summary = lines[-1]
    assert summary["op"] == tune.SERVE_BUCKET_OP
    assert summary["persisted"] is True
    assert summary["sizes"] == 6
    assert tuple(summary["rungs"]) == tune.serve_buckets("float32")
    assert (summary["padding_waste_tuned"]
            <= summary["padding_waste_geometric"])
    assert len(lines) == len(summary["rungs"]) + 1

    tune.reload()
    cache.unlink()
    tune.reload()
    assert main(["--serve-hist", str(hist), "--dry-run"]) == 0
    assert json.loads(capsys.readouterr().out.strip().splitlines()
                      [-1])["persisted"] is False
    assert tune.serve_buckets("float32") is None
    bad = tmp_path / "bad.jsonl"
    bad.write_text('{"rows": 3}\n')
    with pytest.raises(ValueError, match="n/size"):
        main(["--serve-hist", str(bad)])
