"""Autotuner plan store tests (slate_tpu/tune/): schema validation, the
record -> persist -> reload -> resolve round trip (including under jit,
where the resolved plan must lower to a pallas_call), nearest-n lookup,
the plan_override test seam, and the SLATE_PALLAS removal warning."""

import json
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from slate_tpu import tune
from slate_tpu.tune import (OPS, SCHEMA_VERSION, TilePlan, XLA_PLAN,
                            plan_override, record_plan, resolve_plan,
                            validate_cache)


@pytest.fixture
def cache(tmp_path, monkeypatch):
    """Point the plan cache at a fresh temp file for the test's scope."""
    path = tmp_path / "plans.json"
    monkeypatch.setenv("SLATE_TUNE_CACHE", str(path))
    monkeypatch.delenv("SLATE_PALLAS", raising=False)
    tune.reload()
    yield path
    tune.reload()


# ---- schema -------------------------------------------------------------


def _good_cache():
    return {"version": SCHEMA_VERSION, "chips": {"cpu": {
        "potrf_tile": {"n=512,dtype=float32":
                       {"kernel": "pallas", "nb": 512, "bw": 8,
                        "gflops": 123.4}}}}}


def test_schema_accepts_good_cache():
    validate_cache(_good_cache())                 # must not raise
    validate_cache({"version": SCHEMA_VERSION, "chips": {}})


@pytest.mark.parametrize("mutate,msg", [
    (lambda o: o.update(version=99), "version"),
    (lambda o: o.update(extra=1), "unknown top-level"),
    (lambda o: o.pop("chips"), "chips"),
    (lambda o: o["chips"].update(cpu={"bogus_op": {}}), "unknown op"),
    (lambda o: o["chips"]["cpu"]["potrf_tile"].update(
        {"n=1,dtype=f32": {"kernel": "magic", "nb": 1, "bw": 1}}), "kernel"),
    (lambda o: o["chips"]["cpu"]["potrf_tile"].update(
        {"n=1,dtype=f32": {"kernel": "xla", "nb": -4, "bw": 1}}), "nb"),
    (lambda o: o["chips"]["cpu"]["potrf_tile"].update(
        {"badkey": {"kernel": "xla", "nb": 1, "bw": 1}}), "key"),
], ids=["version", "extra-key", "no-chips", "bad-op", "bad-kernel",
        "bad-nb", "bad-entry-key"])
def test_schema_rejects_bad_cache(mutate, msg):
    obj = _good_cache()
    mutate(obj)
    with pytest.raises(ValueError):
        validate_cache(obj)


def test_repo_ships_no_invalid_default_cache(cache):
    """A fresh (missing) cache file resolves every op to the XLA plan."""
    for op in OPS:
        assert resolve_plan(op, 512) == XLA_PLAN


# ---- round trip ---------------------------------------------------------


def test_record_reload_resolve_roundtrip(cache):
    plan = TilePlan(kernel="pallas", nb=256, bw=16)
    record_plan("potrf_tile", 512, "float32", plan, gflops=42.0)
    assert cache.exists()
    on_disk = json.loads(cache.read_text())
    validate_cache(on_disk)
    chip = tune.chip_kind()
    ent = on_disk["chips"][chip]["potrf_tile"]["n=512,dtype=float32"]
    assert ent == {"kernel": "pallas", "nb": 256, "bw": 16, "gflops": 42.0}
    assert resolve_plan("potrf_tile", 512) == plan
    # other ops stay untuned
    assert resolve_plan("geqrf_panel", 512) == XLA_PLAN


def test_nearest_n_lookup(cache):
    near = TilePlan(kernel="pallas", nb=128, bw=8)
    far = TilePlan(kernel="pallas", nb=512, bw=16)
    record_plan("potrf_tile", 256, "float32", near)
    record_plan("potrf_tile", 4096, "float32", far)
    assert resolve_plan("potrf_tile", 384) == near     # log2-nearest
    assert resolve_plan("potrf_tile", 3000) == far
    # dtype must match exactly: no f32 plan leaks onto f64 calls
    assert resolve_plan("potrf_tile", 256, "float64") == XLA_PLAN


def test_resolved_plan_routes_pallas_under_jit(cache):
    """The cached plan is read at TRACE time: a jitted driver seam lowers
    to a pallas_call when the plan says pallas, with no cache access in
    the compiled program."""
    from slate_tpu.internal.potrf import potrf_tile
    record_plan("potrf_tile", 128, "float32",
                TilePlan(kernel="pallas", nb=128, bw=8))
    rng = np.random.default_rng(0)
    a0 = rng.standard_normal((128, 128)).astype(np.float32) * 0.1
    a = jnp.asarray(a0 @ a0.T + 128 * np.eye(128, dtype=np.float32))
    # fresh lambdas per trace: make_jaxpr caches by function identity +
    # avals, which would otherwise replay the first route
    jaxpr = str(jax.make_jaxpr(lambda x: potrf_tile(x))(a))
    assert "pallas_call" in jaxpr
    L = np.asarray(jax.jit(potrf_tile)(a))
    np.testing.assert_allclose(L, np.linalg.cholesky(np.asarray(a)),
                               rtol=2e-5, atol=5e-5)
    # and the XLA route stays pallas-free
    tune.reload()
    with plan_override("potrf_tile", XLA_PLAN):
        assert "pallas_call" not in str(
            jax.make_jaxpr(lambda x: potrf_tile(x))(a))


def test_corrupt_cache_file_warns_and_falls_back(cache):
    cache.write_text('{"version": 99}')
    tune.reload()
    with pytest.warns(UserWarning, match="ignoring bad plan cache"):
        assert resolve_plan("potrf_tile", 512) == XLA_PLAN


# ---- overrides and the deprecated env knob ------------------------------


def test_plan_override_scopes_and_restores(cache):
    forced = TilePlan(kernel="pallas", nb=128, bw=16)
    with plan_override("getrf_panel", forced):
        assert resolve_plan("getrf_panel", 384) == forced
        with plan_override("getrf_panel", XLA_PLAN):
            assert resolve_plan("getrf_panel", 384) == XLA_PLAN
        assert resolve_plan("getrf_panel", 384) == forced
    assert resolve_plan("getrf_panel", 384) == XLA_PLAN
    with pytest.raises(ValueError):
        with plan_override("bogus", forced):
            pass


def test_slate_pallas_env_is_removed_and_ignored(cache, monkeypatch):
    """SLATE_PALLAS no longer forces kernel routes: setting it warns once
    (pointing at plan_override / the tuner) and has NO effect on
    resolution in either direction."""
    monkeypatch.setenv("SLATE_PALLAS", "1")
    monkeypatch.setattr(tune.plans, "_WARNED", False)
    with pytest.warns(UserWarning, match="SLATE_PALLAS has been removed"):
        plan = resolve_plan("potrf_tile", 256)
    assert plan == XLA_PLAN                  # no force-on: untuned -> XLA
    # nor does force-off beat a cached pallas plan
    record_plan("potrf_tile", 256, "float32",
                TilePlan(kernel="pallas", nb=256, bw=8))
    monkeypatch.setenv("SLATE_PALLAS", "0")
    assert resolve_plan("potrf_tile", 256).kernel == "pallas"
    # the warning fired once per process: silent from here on
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        resolve_plan("potrf_tile", 256)


# ---- autotune measurement layer -----------------------------------------


def test_candidates_cover_xla_and_legal_pallas():
    from slate_tpu.tune import autotune
    cands = list(autotune.candidates("potrf_panel", 512, "float32"))
    assert XLA_PLAN in cands
    pallas = [c for c in cands if c.kernel == "pallas"]
    assert pallas and all(512 % c.nb == 0 for c in pallas)
    # geqrf_panel has no bw knob: one pallas candidate per nb
    qr = list(autotune.candidates("geqrf_panel", 512, "float32"))
    assert len({(c.kernel, c.nb) for c in qr}) == len(qr)


@pytest.mark.slow
def test_tune_op_persists_winner(cache):
    from slate_tpu.tune import autotune
    plan, gflops = autotune.tune_op("potrf_tile", 128, "float32", iters=1)
    assert gflops > 0
    assert resolve_plan("potrf_tile", 128) == plan
