"""SVD tests: singular values vs numpy and ||A - U S V^H|| residuals
(analog of ref test/test_svd.cc residual + ortho checks)."""

import numpy as np
import pytest

import slate_tpu as st


def _mat(rng, m, n, dtype=np.float64):
    a = rng.standard_normal((m, n)).astype(dtype)
    if np.issubdtype(dtype, np.complexfloating):
        a = a + 1j * rng.standard_normal((m, n))
    return a


@pytest.mark.parametrize("m,n,nb", [(16, 16, 4), (24, 13, 5), (13, 24, 5),
                                    (8, 8, 8), (30, 7, 4)])
@pytest.mark.slow
def test_svd_values(rng, m, n, nb):
    a = _mat(rng, m, n)
    A = st.Matrix.from_numpy(a, nb, nb)
    s = st.svd_vals(A)
    np.testing.assert_allclose(np.asarray(s), np.linalg.svd(a, compute_uv=False),
                               atol=1e-10)


@pytest.mark.parametrize("m,n,nb", [(16, 16, 4), (20, 11, 5), (11, 20, 5)])
@pytest.mark.slow
def test_svd_vectors(rng, m, n, nb):
    a = _mat(rng, m, n)
    A = st.Matrix.from_numpy(a, nb, nb)
    s, U, V = st.svd(A)
    s = np.asarray(s)
    u = U.to_numpy()
    v = V.to_numpy()
    r = min(m, n)
    np.testing.assert_allclose(u.conj().T @ u, np.eye(u.shape[1]), atol=1e-11)
    np.testing.assert_allclose(v.conj().T @ v, np.eye(v.shape[1]), atol=1e-11)
    np.testing.assert_allclose(u[:, :r] * s[None, :r] @ v[:, :r].conj().T, a,
                               atol=1e-10)
    np.testing.assert_allclose(s[:r], np.linalg.svd(a, compute_uv=False),
                               atol=1e-10)


@pytest.mark.slow
def test_svd_complex(rng):
    m, n, nb = 14, 10, 4
    a = _mat(rng, m, n, np.complex128)
    A = st.Matrix.from_numpy(a, nb, nb)
    s, U, V = st.svd(A)
    s = np.asarray(s)
    u, v = U.to_numpy(), V.to_numpy()
    np.testing.assert_allclose(u * s[None, :] @ v.conj().T, a, atol=1e-10)
    np.testing.assert_allclose(np.asarray(st.svd_vals(A)),
                               np.linalg.svd(a, compute_uv=False), atol=1e-10)


@pytest.mark.slow
def test_svd_mesh_grid(rng):
    # distributed stage 1 (dist_ge2tb); only the band is gathered for
    # stage 2 (ref svd.cc ge2tbGather)
    m = n = 16
    g = st.make_grid(4)
    a = _mat(rng, m, n)
    A = st.Matrix.from_numpy(a, 4, 4, g)
    s = st.svd_vals(A)
    np.testing.assert_allclose(np.asarray(s),
                               np.linalg.svd(a, compute_uv=False), atol=1e-10)


@pytest.mark.slow
def test_svd_mesh_vectors_rect_ragged(rng):
    import jax
    m, n, nb = 37, 23, 5
    g = st.Grid(2, 4, devices=jax.devices()[:8])
    a = _mat(rng, m, n)
    A = st.Matrix.from_numpy(a, nb, nb, g)
    s, U, V = st.svd(A)
    s = np.asarray(s)
    u, v = U.to_numpy(), V.to_numpy()
    np.testing.assert_allclose(u.conj().T @ u, np.eye(n), atol=1e-10)
    np.testing.assert_allclose(v.conj().T @ v, np.eye(n), atol=1e-10)
    np.testing.assert_allclose(u * s[None, :] @ v.conj().T, a, atol=1e-9)


@pytest.mark.slow
def test_svd_mesh_complex(rng):
    import jax
    m, n, nb = 24, 24, 4
    g = st.Grid(2, 2, devices=jax.devices()[:4])
    a = (rng.standard_normal((m, n))
         + 1j * rng.standard_normal((m, n))).astype(np.complex128)
    A = st.Matrix.from_numpy(a, nb, nb, g)
    s, U, V = st.svd(A)
    s = np.asarray(s)
    u, v = U.to_numpy(), V.to_numpy()
    np.testing.assert_allclose(u * s[None, :] @ v.conj().T, a, atol=1e-9)


def test_svd_chase_parity(rng):
    # the bidiagonal parity route (tb2bd bulge chase) must agree with the
    # default band seam
    m, n, nb = 19, 13, 4
    a = _mat(rng, m, n)
    A = st.Matrix.from_numpy(a, nb, nb)
    s, U, V = st.svd(A, {st.Option.MethodSvd: st.MethodSvd.Bidiag})
    s = np.asarray(s)
    u, v = U.to_numpy(), V.to_numpy()
    np.testing.assert_allclose(u * s[None, :] @ v.conj().T, a, atol=1e-10)
    np.testing.assert_allclose(s, np.linalg.svd(a, compute_uv=False),
                               atol=1e-10)


def test_bdsqr_tb2bd_public(rng):
    n = 12
    d = rng.standard_normal(n)
    e = rng.standard_normal(n - 1)
    B = np.diag(d) + np.diag(e, 1)
    s, U, Vh = st.bdsqr(d, e)
    np.testing.assert_allclose(np.asarray(s),
                               np.linalg.svd(B, compute_uv=False), atol=1e-12)
    kd, mb = 3, 4
    bu = np.triu(np.tril(rng.standard_normal((n, n)), kd), 0)
    bu = np.triu(bu)  # upper band, bandwidth kd
    bu = np.where(np.subtract.outer(np.arange(n), np.arange(n)) >= -kd, bu, 0)
    TB = st.TriangularBandMatrix.from_numpy(bu, kd, mb, st.Uplo.Upper)
    d2, e2, U2, V2 = st.tb2bd(TB)
    B2 = np.diag(np.asarray(d2)) + np.diag(np.asarray(e2), 1)
    u2, v2 = np.asarray(U2), np.asarray(V2)
    np.testing.assert_allclose(u2 @ B2 @ v2.conj().T, bu, atol=1e-11)
