"""Condition-estimation tests vs exact numpy 1-norm condition numbers
(analog of ref test/test_gecondest.cc, test_trcondest.cc)."""

import numpy as np
import pytest

import slate_tpu as st


@pytest.mark.parametrize("n,nb", [(16, 4), (30, 8)])
def test_gecondest(rng, n, nb):
    a = rng.standard_normal((n, n)) + n * np.eye(n)
    A = st.Matrix.from_numpy(a, nb, nb)
    anorm = np.abs(a).sum(axis=0).max()
    F = st.getrf(A)
    rcond = float(st.gecondest(F, anorm))
    exact = 1.0 / (anorm * np.abs(np.linalg.inv(a)).sum(axis=0).max())
    # Higham estimator: within a small factor of (and almost always equal
    # to) the exact value, never an overestimate of rcond by much
    assert exact / 3 <= rcond <= exact * 3
    assert 0 < rcond < 1


def test_gecondest_illconditioned(rng):
    n, nb = 24, 8
    u, _ = np.linalg.qr(rng.standard_normal((n, n)))
    v, _ = np.linalg.qr(rng.standard_normal((n, n)))
    s = np.logspace(0, -10, n)
    a = (u * s) @ v.T
    F = st.getrf(st.Matrix.from_numpy(a, nb, nb))
    anorm = np.abs(a).sum(axis=0).max()
    rcond = float(st.gecondest(F, anorm))
    exact = 1.0 / (anorm * np.abs(np.linalg.inv(a)).sum(axis=0).max())
    assert rcond < 1e-8                      # detects the ill-conditioning
    assert exact / 10 <= rcond <= exact * 10


def test_gecondest_inf(rng):
    n, nb = 16, 4
    a = rng.standard_normal((n, n)) + n * np.eye(n)
    F = st.getrf(st.Matrix.from_numpy(a, nb, nb))
    anorm = np.abs(a).sum(axis=1).max()
    rcond = float(st.gecondest(F, anorm, norm=st.Norm.Inf))
    exact = 1.0 / (anorm * np.abs(np.linalg.inv(a)).sum(axis=1).max())
    assert exact / 3 <= rcond <= exact * 3


def test_trcondest(rng):
    n, nb = 20, 4
    r = np.triu(rng.standard_normal((n, n))) + 4 * np.eye(n)
    R = st.TriangularMatrix.from_numpy(r, nb, st.Uplo.Upper)
    rcond = float(st.trcondest(R))
    rnorm = np.abs(r).sum(axis=0).max()
    exact = 1.0 / (rnorm * np.abs(np.linalg.inv(r)).sum(axis=0).max())
    assert exact / 3 <= rcond <= exact * 3


def test_trcondest_complex(rng):
    n, nb = 14, 4
    r = np.triu(rng.standard_normal((n, n))
                + 1j * rng.standard_normal((n, n))) + 4 * np.eye(n)
    R = st.TriangularMatrix.from_numpy(r, nb, st.Uplo.Upper)
    rcond = float(st.trcondest(R))
    rnorm = np.abs(r).sum(axis=0).max()
    exact = 1.0 / (rnorm * np.abs(np.linalg.inv(r)).sum(axis=0).max())
    assert exact / 3 <= rcond <= exact * 3
