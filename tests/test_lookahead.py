"""Lookahead-pipelined distributed kernels (PR: dist_lookahead).

Coverage map:

- parity oracle: depth-1/2 double-buffered ring pipelines produce
  BIT-IDENTICAL storage, health scalars, and ABFT counters vs the
  depth-0 bulk-synchronous path, for all four kernels (summa, dist_chol,
  dist_lu, dist_qr), on ragged tilings, non-square grids, both dtypes,
  ABFT on and off;
- jaxpr shape: the lookahead path lowers to ppermute rings (absent at
  depth 0), and the per-step collective count is CONSTANT in the depth —
  only the summa prologue grows by one ring per extra depth;
- fault injection: a strike in the in-flight panel buffer
  (post_collective at depth >= 1) is detected, repaired, and counted
  identically to the depth-0 oracle;
- obs: ``slate.<op>/bcast_ahead`` prefetch spans surface as SIBLINGS of
  the accumulate/update phases in the Chrome export, and the metrics CLI
  aggregates them with no code change;
- seam: the ``dist_lookahead`` tune plan (SEAM011) is the only dispatch
  path — kernel "ring" turns the pipeline on at depth ``bw``.
"""

import json

import jax
import numpy as np
import pytest

import slate_tpu as st
from slate_tpu import obs
from slate_tpu.core.layout import num_tiles
from slate_tpu.options import Option
from slate_tpu.parallel.dist_chol import dist_potrf
from slate_tpu.parallel.dist_lu import dist_getrf
from slate_tpu.parallel.dist_qr import dist_geqrf_data
from slate_tpu.parallel.summa import summa_gemm_data
from slate_tpu.robust import faults
from slate_tpu.tune import TilePlan, plan_override

NB = 4


def _grid(p, q):
    return st.Grid(p, q, devices=jax.devices()[: p * q])


def _assert_all_equal(base, out, ctx):
    """Depth-parity oracle.  Integer/bool leaves (ABFT counters, health
    scalars) must match EXACTLY on every machine.  Float leaves are
    bit-identical wherever XLA lowers both depths with the same
    accumulation order — but depth 0 and depth >= 1 are *different
    programs*, and the CPU backend's threading/fusion heuristics vary
    with the host's core count, so on some hosts the trailing updates
    legitimately differ in the last few ulps (the PR-18 tier-1 triage:
    the same seeds failed on a 1-core container and pass elsewhere).
    Exact-first, then a dtype-calibrated 32*eps fallback — tight enough
    that a real schedule bug (stale panel, wrong tile) still fails."""
    for i, (x, y) in enumerate(zip(base, out)):
        x, y = np.asarray(x), np.asarray(y)
        if np.array_equal(x, y):
            continue
        assert (np.issubdtype(x.dtype, np.floating)
                or np.issubdtype(x.dtype, np.complexfloating)), (ctx, i)
        tol = 32 * float(np.finfo(x.dtype).eps)
        scale = max(1.0, float(np.max(np.abs(x))))
        np.testing.assert_allclose(y, x, rtol=tol, atol=tol * scale,
                                   err_msg=str((ctx, i)))


def _summa_args(rng, g, dt, m=18, kk=22, n=14):
    a = rng.standard_normal((m, kk)).astype(dt)
    b = rng.standard_normal((kk, n)).astype(dt)
    A = st.Matrix.from_numpy(a, NB, NB, g)
    B = st.Matrix.from_numpy(b, NB, NB, g)
    C = st.Matrix.from_numpy(np.zeros((m, n), dt), NB, NB, g)
    return A.storage, B.storage, C.storage


def _summa_all(stg_a, stg_b, stg_c, g, abft, la):
    Kt = num_tiles(stg_a.n, NB)
    out = summa_gemm_data(stg_a.data, stg_b.data, stg_c.data, 1.5, 0.5,
                          Kt, g, abft=abft, la=la)
    return out if abft else (out,)


# ------------------------------------------------------------- parity

def test_summa_parity_fast(rng):
    """Ragged SUMMA smoke: depth 1 bit-identical to depth 0.  The full
    grid/dtype/abft/depth matrix lives in the @slow tests — each extra
    (grid, dtype, abft, la) combination is a fresh multi-minute
    8-device compile, too heavy for tier-1."""
    g = _grid(2, 2)
    sa, sb_, sc = _summa_args(rng, g, "float32")
    base = _summa_all(sa, sb_, sc, g, False, 0)
    _assert_all_equal(base, _summa_all(sa, sb_, sc, g, False, 1),
                      ("summa", False, 1))


@pytest.mark.slow
@pytest.mark.parametrize("p,q", [(2, 4), (4, 2)])
@pytest.mark.parametrize("dt", ["float32", "float64"])
def test_summa_parity_full(rng, p, q, dt):
    g = _grid(p, q)
    sa, sb_, sc = _summa_args(rng, g, dt)
    for abft in (False, True):
        base = _summa_all(sa, sb_, sc, g, abft, 0)
        for la in (1, 2):
            _assert_all_equal(base, _summa_all(sa, sb_, sc, g, abft, la),
                              ("summa", p, q, dt, abft, la))


def _chol_storage(rng, g, dt, n):
    b = rng.standard_normal((n, n))
    a = (b @ b.T + n * np.eye(n)).astype(dt)
    return st.HermitianMatrix.from_numpy(a, NB, st.Uplo.Lower, g).storage


@pytest.mark.slow
def test_chol_parity_fast(rng):
    n = 13                                    # ragged: 13 = 3*4 + 1
    g = _grid(2, 2)
    stg = _chol_storage(rng, g, "float32", n)
    base = dist_potrf(stg.data, stg.Nt, g, stg.n, abft=True, la=0)
    _assert_all_equal(base,
                      dist_potrf(stg.data, stg.Nt, g, stg.n, abft=True,
                                 la=1), ("chol", 1))


@pytest.mark.slow
@pytest.mark.parametrize("dt", ["float32", "float64"])
@pytest.mark.parametrize("abft", [False, True])
def test_chol_parity_full(rng, dt, abft):
    g = _grid(2, 4)
    stg = _chol_storage(rng, g, dt, 21)
    base = dist_potrf(stg.data, stg.Nt, g, stg.n, abft=abft, la=0)
    for la in (1, 2):
        _assert_all_equal(base,
                          dist_potrf(stg.data, stg.Nt, g, stg.n,
                                     abft=abft, la=la), ("chol", dt, la))


def _lu_storage(rng, g, dt, n):
    a = (rng.standard_normal((n, n)) + n * np.eye(n)).astype(dt)
    return st.Matrix.from_numpy(a, NB, NB, g).storage


@pytest.mark.slow
def test_lu_parity_fast(rng):
    n = 17
    g = _grid(2, 2)
    stg = _lu_storage(rng, g, "float32", n)
    base = dist_getrf(stg.data, stg.Nt, g, stg.n, "partial", abft=True,
                      la=0)
    _assert_all_equal(base,
                      dist_getrf(stg.data, stg.Nt, g, stg.n, "partial",
                                 abft=True, la=1), ("lu", 1))


@pytest.mark.slow
@pytest.mark.parametrize("dt", ["float32", "float64"])
@pytest.mark.parametrize("method", ["partial", "nopiv"])
def test_lu_parity_full(rng, dt, method):
    g = _grid(2, 4)
    stg = _lu_storage(rng, g, dt, 21)
    for abft in (False, True):
        base = dist_getrf(stg.data, stg.Nt, g, stg.n, method, abft=abft,
                          la=0)
        for la in (1, 2):
            _assert_all_equal(base,
                              dist_getrf(stg.data, stg.Nt, g, stg.n,
                                         method, abft=abft, la=la),
                              ("lu", dt, method, abft, la))


def _qr_all(rng, g, dt, m, n, la):
    a = rng.standard_normal((m, n)).astype(dt)
    stg = st.Matrix.from_numpy(a, NB, NB, g).storage
    return dist_geqrf_data(stg.data, num_tiles(n, NB), num_tiles(m, NB),
                           m, n, g, la=la)


@pytest.mark.slow
def test_qr_parity_fast(rng):
    g = _grid(2, 2)
    base = _qr_all(rng, g, "float32", 18, 14, 0)
    out = _qr_all(rng, g, "float32", 18, 14, 1)
    _assert_all_equal(base, out, ("qr", 1))


@pytest.mark.slow
@pytest.mark.parametrize("p,q", [(2, 2), (2, 4)])
@pytest.mark.parametrize("dt", ["float32", "float64"])
def test_qr_parity_full(rng, p, q, dt):
    g = _grid(p, q)
    base = _qr_all(rng, g, dt, 22, 17, 0)
    for la in (1, 2):
        _assert_all_equal(base, _qr_all(rng, g, dt, 22, 17, la),
                          ("qr", p, q, dt, la))


# ------------------------------------------------------------- jaxpr

def _summa_jaxpr(rng, g, la):
    sa, sb_, sc = _summa_args(rng, g, "float32")
    Kt = num_tiles(sa.n, NB)
    return str(jax.make_jaxpr(
        lambda a, b, c: summa_gemm_data(a, b, c, 1.0, 0.0, Kt, g, la=la))(
            sa.data, sb_.data, sc.data))


def test_jaxpr_summa_ring_present_and_prologue_only_growth(rng):
    """Depth 0 lowers with NO ppermute; depth >= 1 rings the panels; the
    extra depth adds exactly one prologue ring pair ((p-1)+(q-1) hops) —
    the per-step collective count is constant in the depth."""
    p, q = 2, 4
    g = _grid(p, q)
    j0 = _summa_jaxpr(rng, g, 0)
    j1 = _summa_jaxpr(rng, g, 1)
    j2 = _summa_jaxpr(rng, g, 2)
    assert j0.count("ppermute") == 0
    assert j1.count("ppermute") > 0
    assert j2.count("ppermute") - j1.count("ppermute") == (p - 1) + (q - 1)


@pytest.mark.slow
def test_jaxpr_factorizations_collective_count_constant_in_depth(rng):
    """chol/lu/qr carry ONE panel in flight regardless of depth (the
    extra depth widens the early-update window, pure local compute), so
    their ppermute and psum counts are identical at depth 1 and 2."""
    g = _grid(2, 2)
    n = 13
    chol = _chol_storage(rng, g, "float32", n)
    lu = _lu_storage(rng, g, "float32", n)

    def jx(fn):
        return {la: str(jax.make_jaxpr(lambda d, la=la: fn(d, la))(
            chol.data if fn is _chol else lu.data if fn is _lu
            else qr_data)) for la in (0, 1, 2)}

    def _chol(d, la):
        return dist_potrf(d, chol.Nt, g, chol.n, abft=False, la=la)

    def _lu(d, la):
        return dist_getrf(d, lu.Nt, g, lu.n, "partial", la=la)

    a = np.random.default_rng(7).standard_normal((18, 14)).astype("f4")
    qr_stg = st.Matrix.from_numpy(a, NB, NB, g).storage
    qr_data = qr_stg.data

    def _qr(d, la):
        return dist_geqrf_data(d, num_tiles(14, NB), num_tiles(18, NB),
                               18, 14, g, la=la)

    for fn in (_chol, _lu, _qr):
        js = jx(fn)
        assert js[0].count("ppermute") == 0, fn.__name__
        assert js[1].count("ppermute") > 0, fn.__name__
        assert js[1].count("ppermute") == js[2].count("ppermute"), \
            fn.__name__
        assert js[1].count("psum") == js[2].count("psum"), fn.__name__


# ----------------------------------------------- in-flight buffer faults

@pytest.mark.slow
@pytest.mark.parametrize("dt", ["float32", "float64"])
def test_summa_inflight_strike_repaired_and_depth_invariant(rng, dt):
    """A post_collective strike with the pipeline on (the accumulator fed
    from the in-flight ring buffers) is detected, repaired, and counted
    identically at every depth, and the repaired product matches the
    clean run."""
    g = _grid(2, 2)
    sa, sb_, sc = _summa_args(rng, g, dt)
    clean = _summa_all(sa, sb_, sc, g, True, 0)
    plan = faults.FaultPlan("post_collective", kind="bitflip", seed=3,
                            tile=(1, 0))
    outs = {}
    with faults.inject(plan):
        for la in (0, 1, 2):
            outs[la] = _summa_all(sa, sb_, sc, g, True, la)
    for la in (0, 1, 2):
        data, det, cor, site = outs[la]
        assert int(det) == 1 and int(cor) == 1, (dt, la)
        assert int(site) >= 0, (dt, la)
    for la in (1, 2):
        _assert_all_equal(outs[0], outs[la], ("summa-strike", dt, la))
    np.testing.assert_allclose(np.asarray(outs[0][0]),
                               np.asarray(clean[0]), atol=1e-6)


@pytest.mark.slow
@pytest.mark.parametrize("dt", ["float32", "float64"])
def test_chol_inflight_panel_strike_depth_invariant(rng, dt):
    """dist_chol's post_collective site IS the in-flight gathered panel
    buffer at depth >= 1: strike it, and detection/repair counters and
    the factored bytes must match the depth-0 oracle exactly."""
    g = _grid(2, 2)
    stg = _chol_storage(rng, g, dt, 13)
    plan = faults.FaultPlan("post_collective", kind="bitflip", seed=3,
                            tile=(1, 0))
    outs = {}
    with faults.inject(plan):
        for la in (0, 1, 2):
            outs[la] = dist_potrf(stg.data, stg.Nt, g, stg.n, abft=True,
                                  la=la)
    det0, cor0 = int(outs[0][3]), int(outs[0][4])
    assert det0 >= 1 and cor0 == det0, dt
    for la in (1, 2):
        _assert_all_equal(outs[0], outs[la], ("chol-strike", dt, la))


# ------------------------------------------------------------- obs

def _run_gemm_lookahead(rng, g):
    a = rng.standard_normal((18, 22))
    b = rng.standard_normal((22, 14))
    A = st.Matrix.from_numpy(a, NB, NB, g)
    B = st.Matrix.from_numpy(b, NB, NB, g)
    with plan_override("dist_lookahead", TilePlan("ring", NB, 1)):
        C = st.gemm(1.0, A, B)
    return a @ b, C


def test_prefetch_spans_are_siblings_in_chrome_export(rng, tmp_path):
    """slate.gemm/bcast_ahead rides NEXT to slate.gemm/accumulate in the
    exported flame graph: same tid, same parent boundary span, child
    depth — the timeline shows prefetch beside compute, not nested in
    it (extends test_chrome_export_preserves_span_nesting)."""
    g = _grid(2, 2)
    with obs.record_spans() as rec:
        ref, C = _run_gemm_lookahead(rng, g)
    np.testing.assert_allclose(C.to_numpy(), ref, atol=1e-10)
    path = tmp_path / "trace.json"
    rec.export_chrome_trace(str(path))
    with open(path, encoding="utf-8") as fh:
        events = json.load(fh)["traceEvents"]
    by_name = {}
    for e in events:
        by_name.setdefault(e["name"], []).append(e)
    parents = by_name.get("slate.gemm")
    assert parents, sorted(by_name)
    parent = parents[0]
    ahead = by_name.get("slate.gemm/bcast_ahead")
    acc = by_name.get("slate.gemm/accumulate")
    assert ahead and acc, sorted(by_name)
    eps = 0.5
    p0, p1 = parent["ts"], parent["ts"] + parent["dur"]
    for ch in ahead + acc:
        assert ch["tid"] == parent["tid"]
        assert ch["args"]["depth"] >= parent["args"]["depth"] + 1
        assert ch["ts"] >= p0 - eps
        assert ch["ts"] + ch["dur"] <= p1 + eps
    # siblings: prefetch spans sit at the SAME depth as the accumulate
    # phase they overlap with, never inside it
    assert {e["args"]["depth"] for e in ahead} == \
        {e["args"]["depth"] for e in acc}


def test_metrics_cli_aggregates_prefetch_spans(rng, tmp_path):
    """The metrics aggregator counts bcast_ahead spans from span JSONL
    with no code change, alongside the driver events of the same run."""
    g = _grid(2, 2)
    evp = tmp_path / "ev.jsonl"
    spp = tmp_path / "spans.jsonl"
    obs.enable(str(evp))
    try:
        with obs.record_spans() as rec:
            _run_gemm_lookahead(rng, g)
    finally:
        obs.disable()
    names = [s["name"] for s in rec.spans]
    assert "slate.gemm/bcast_ahead" in names
    rec.export_jsonl(str(spp))
    s = obs.summarize([str(evp), str(spp)])
    assert s["counts"]["spans"] == len(rec.spans) > 0
    assert s["counts"]["events"] >= 1
    assert s["counts"]["malformed"] == 0
    assert "gemm" in s["ops"]
    text = obs.render(s)
    assert "spans" in text


# ------------------------------------------------------------- seam

def test_lookahead_depth_resolves_through_plan(rng):
    from slate_tpu.tune import lookahead_depth
    assert lookahead_depth(4096) == 0          # untuned -> oracle
    with plan_override("dist_lookahead", TilePlan("ring", 256, 2)):
        assert lookahead_depth(4096) == 2
    with plan_override("dist_lookahead", TilePlan("ring", 256, 7)):
        assert lookahead_depth(4096) == 2      # clamped to supported 1..2
    with plan_override("dist_lookahead", TilePlan("xla", 256, 1)):
        assert lookahead_depth(4096) == 0
