"""Test configuration: CPU backend with 8 virtual devices, float64 on.

Mirrors the reference's strategy of testing distributed semantics with MPI
oversubscription on one node (ref: docs/usage.md:32-42, Jenkinsfile-mpi:186):
here an 8-device virtual CPU mesh stands in for the TPU pod, per SURVEY.md §4.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

# Before every backend_compile, jax walks the lowered MLIR module's first
# ops through the Python bindings to pick an XLA logging verbosity
# (compiler.use_detailed_logging).  Each op visit degrades as live MLIR
# contexts accumulate over the session (~17 ms/op by the suite's tail vs
# microseconds fresh), which made alphabetically-late test files measure
# 4-5x slower in-suite than in isolation (stedc: 30 s vs 7 s).  Threshold
# 0 classifies every module as "interesting" without walking any ops;
# xla_detailed_logging only gates VLOG output, which the suite never
# enables.
os.environ.setdefault("JAX_COMPILER_DETAILED_LOGGING_MIN_OPS", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(42)
