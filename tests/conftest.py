"""Test configuration: CPU backend with 8 virtual devices, float64 on.

Mirrors the reference's strategy of testing distributed semantics with MPI
oversubscription on one node (ref: docs/usage.md:32-42, Jenkinsfile-mpi:186):
here an 8-device virtual CPU mesh stands in for the TPU pod, per SURVEY.md §4.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(42)
