"""Observability spine tests (slate_tpu/obs + util.trace wiring).

Coverage map:

- jaxpr identity: enabling events + span recording produces a
  byte-identical jaxpr for gesv / posv / gels — the zero-overhead
  contract (no io_callback, nothing rides in the computation);
- one event per public driver call: nested internal drivers collapse
  into the boundary's single event; a jitted driver emits exactly one
  (traced) event at trace time and none on cache hits;
- decision capture: resolved speculate/abft knobs, the path taken
  (speculated vs escalated), ABFT detect/correct counters from
  fault-injected runs, and resolve_plan decisions all land in the event;
- the retrace sentinel warns (once, rate-limited) on same-signature
  retrace churn and reports per-op stats;
- the span tracer records nested phase timings and exports valid
  Chrome trace JSON and span JSONL;
- metrics: summarize() aggregates event + bench JSONL into per-op
  latency/rate tables and the ``python -m slate_tpu.obs`` CLI renders
  them (text and --json).
"""

import json
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import slate_tpu as st
from slate_tpu import obs
from slate_tpu.core.storage import TileStorage
from slate_tpu.obs import __main__ as obs_cli
from slate_tpu.obs import events as obs_events
from slate_tpu.options import Option
from slate_tpu.robust import faults

INFO = {Option.ErrorPolicy: "info"}
ABFT_INFO = {Option.ErrorPolicy: "info", Option.Abft: "on"}
SPEC_INFO = {Option.Speculate: "on", Option.ErrorPolicy: "info"}


def _problem(rng, n=32, nb=16, nrhs=4):
    a = rng.standard_normal((n, n)) + n * np.eye(n)
    b = rng.standard_normal((n, nrhs))
    return a, b


def _hpd(rng, n=32):
    a = rng.standard_normal((n, n))
    return a @ a.T / n + n * np.eye(n)


# ------------------------------------------------------ jaxpr identity


def _gesv_fn(nb):
    def run(a, b):
        F, X = st.gesv(st.Matrix(TileStorage.from_dense(a, nb, nb)),
                       st.Matrix(TileStorage.from_dense(b, nb, nb)))
        return X.to_dense()
    return run


def _posv_fn(nb):
    def run(a, b):
        M = st.Matrix(TileStorage.from_dense(a, nb, nb))
        L, X = st.posv(st.HermitianMatrix._from_view(M, st.Uplo.Lower),
                       st.Matrix(TileStorage.from_dense(b, nb, nb)))
        return X.to_dense()
    return run


def _gels_fn(nb):
    def run(a, b):
        X = st.gels(st.Matrix(TileStorage.from_dense(a, nb, nb)),
                    st.Matrix(TileStorage.from_dense(b, nb, nb)))
        return X.to_dense()
    return run


@pytest.mark.parametrize("maker,shape", [
    (_gesv_fn, ((32, 32), (32, 4))),
    (_posv_fn, ((32, 32), (32, 4))),
    (_gels_fn, ((48, 16), (48, 4))),
])
def test_jaxpr_identity_obs_on_vs_off(rng, maker, shape):
    """Enabling the full observability stack must not change the traced
    computation by a single equation — recording is host-side only."""
    (m, n), (bm, bn) = shape
    a = jnp.asarray(rng.standard_normal((m, n)) + np.eye(m, n) * m)
    if maker is _posv_fn:
        a = jnp.asarray(_hpd(rng, m))
    b = jnp.asarray(rng.standard_normal((bm, bn)))
    run = maker(16)
    off = str(jax.make_jaxpr(run)(a, b))
    with obs.recording():
        with obs.record_spans():
            on = str(jax.make_jaxpr(run)(a, b))
    assert on == off


# --------------------------------------------------- one event per call


def test_one_event_per_eager_call(rng):
    a, b = _problem(rng)
    A = st.Matrix.from_numpy(a, 16)
    B = st.Matrix.from_numpy(b, 16)
    with obs.recording() as ev:
        st.gesv(A, B)
        st.gesv(A, B)
    assert [e["op"] for e in ev] == ["gesv", "gesv"]
    for e in ev:
        assert e["traced"] is False
        assert e["status"] == "ok"
        assert e["dur_ms"] > 0
        assert e["shapes"] == [[32, 32], [32, 4]]
        assert e["policy"] == "Raise"
        assert e["path"].startswith(("direct:", "speculated:"))
        assert e["health"] is not None and e["health"]["ok"] is True


def test_one_event_per_jit_trace_none_on_cache_hit(rng):
    a, b = _problem(rng)
    run = jax.jit(_gesv_fn(16))
    aj, bj = jnp.asarray(a), jnp.asarray(b)
    with obs.recording() as ev:
        run(aj, bj)                      # traces once, then executes
        run(aj, bj)                      # cache hit: never re-enters python
        run(aj, bj)
    assert len(ev) == 1
    assert ev[0]["op"] == "gesv" and ev[0]["traced"] is True
    assert ev[0]["health"] is None       # tracers have no values


def test_nested_drivers_collapse_into_boundary_event(rng):
    """posv internally routes through potrf/trsm-family drivers; only the
    posv boundary may emit."""
    hpd, b = _hpd(rng), _problem(rng)[1]
    with obs.recording() as ev:
        st.posv(st.HermitianMatrix.from_numpy(hpd, 16),
                st.Matrix.from_numpy(b, 16))
    assert [e["op"] for e in ev] == ["posv"]


def test_event_on_driver_error(rng):
    n, nb = 16, 4
    r = np.triu(rng.standard_normal((n, n))) + 4 * np.eye(n)
    r[6, 6] = 0.0                        # exactly singular triangle
    R = st.TriangularMatrix.from_numpy(r, nb, st.Uplo.Upper)
    with obs.recording() as ev:
        with pytest.raises(st.SlateSingularError):
            st.trtri(R)
    assert len(ev) == 1
    assert ev[0]["op"] == "trtri"
    assert ev[0]["status"] == "error:SlateSingularError"


# ------------------------------------------------- decision capture


def test_event_captures_speculated_path(rng):
    a, b = _problem(rng, n=24, nb=8)
    with obs.recording() as ev:
        F, X, h = st.gesv(st.Matrix.from_numpy(a, 8),
                          st.Matrix.from_numpy(b, 8), SPEC_INFO)
    (e,) = ev
    assert e["speculate"] is True
    assert e["path"] == "speculated:rbt"
    assert e["policy"] == "Info"
    assert e["health"]["ok"] is True


def test_event_captures_escalation(rng):
    """A post_rbt strike defeats the speculative fast path; the event
    must show the escalated rung, not the primary attempt."""
    a, b = _problem(rng, n=24, nb=8)
    A = st.Matrix.from_numpy(a, 8)
    B = st.Matrix.from_numpy(b, 8)
    with obs.recording() as ev:
        with faults.inject(faults.FaultPlan(site="post_rbt",
                                            kind="bitflip")):
            F, X, h = st.gesv(A, B, SPEC_INFO)
    (e,) = ev
    assert bool(h.ok)
    assert e["path"].startswith("escalated:")
    assert e["escalations"] >= 1


def test_event_captures_abft_counters(rng):
    """A single injected bitflip must surface in the event's health as
    abft_detected/corrected == 1 with the struck tile located."""
    n, nb = 48, 16
    a, b = _problem(rng, n, nb)
    plan = faults.FaultPlan("post_panel", kind="bitflip", seed=5,
                            tile=(n // nb - 1, 0), nb=nb)
    with obs.recording() as ev:
        with faults.inject(plan):
            F, X, h = st.gesv(st.Matrix.from_numpy(a, nb),
                              st.Matrix.from_numpy(b, nb), ABFT_INFO)
    (e,) = ev
    assert e["abft"] is True
    assert e["health"]["abft_detected"] == 1
    assert e["health"]["abft_corrected"] == 1
    assert e["health"]["abft_site"] == [n // nb - 1, 0]
    assert e["health"]["ok"] is True


def test_event_captures_resolved_plan(rng):
    """potrf consults resolve_plan on the f32 128-multiple tile seam;
    the decision (here a test override) must land in the event."""
    from slate_tpu.tune.plans import TilePlan, plan_override
    n = 128
    hpd = _hpd(rng, n).astype(np.float32)
    b = rng.standard_normal((n, 4)).astype(np.float32)
    with plan_override("potrf_tile", TilePlan("xla", 128, 8)):
        with obs.recording() as ev:
            st.posv(st.HermitianMatrix.from_numpy(hpd, n),
                    st.Matrix.from_numpy(b, n))
    (e,) = ev
    ops = {p["op"]: p for p in e["plans"]}
    assert ops["potrf_tile"]["source"] == "override"
    assert ops["potrf_tile"]["kernel"] == "xla"


def test_ring_buffer_and_enable_disable(rng):
    a, b = _problem(rng)
    A = st.Matrix.from_numpy(a, 16)
    B = st.Matrix.from_numpy(b, 16)
    obs.clear()
    assert not obs.enabled()
    obs.enable()
    try:
        assert obs.enabled()
        st.gesv(A, B)
    finally:
        obs.disable()
    assert not obs.enabled()
    recent = obs.recent(1)
    assert recent and recent[0]["op"] == "gesv"
    obs.clear()
    assert obs.recent() == []


# ------------------------------------------------------------ sentinel


def test_sentinel_warns_on_retrace_churn(rng, monkeypatch):
    monkeypatch.setenv("SLATE_OBS_RETRACE_LIMIT", "2")
    obs.reset_sentinel()
    a, b = _problem(rng)
    aj, bj = jnp.asarray(a), jnp.asarray(b)
    try:
        with pytest.warns(obs.SlateRetraceWarning, match="re-jitting"):
            for _ in range(3):           # fresh jit each time: retraces
                jax.jit(_gesv_fn(16))(aj, bj)
        stats = obs.sentinel_stats()
        key = [k for k in stats if k.endswith("gesv")]
        assert key and stats[key[0]]["traces"] >= 3
        assert stats[key[0]]["max_per_signature"] >= 3
        # once per op: a fourth retrace must stay silent
        with warnings.catch_warnings():
            warnings.simplefilter("error", obs.SlateRetraceWarning)
            jax.jit(_gesv_fn(16))(aj, bj)
    finally:
        obs.reset_sentinel()


def test_sentinel_warns_on_signature_explosion(rng, monkeypatch):
    monkeypatch.setenv("SLATE_OBS_SIGNATURE_LIMIT", "2")
    obs.reset_sentinel()
    try:
        with pytest.warns(obs.SlateRetraceWarning, match="signatures"):
            for n in (16, 24, 32):       # distinct shapes: new signatures
                a, b = _problem(rng, n=n, nb=8)
                jax.jit(_gesv_fn(8))(jnp.asarray(a), jnp.asarray(b))
    finally:
        obs.reset_sentinel()


# -------------------------------------------------------------- tracer


def test_record_spans_and_exports(rng, tmp_path):
    hpd, b = _hpd(rng), _problem(rng)[1]
    with obs.record_spans() as rec:
        st.posv(st.HermitianMatrix.from_numpy(hpd, 16),
                st.Matrix.from_numpy(b, 16))
    names = {s["name"] for s in rec.spans}
    assert "slate.posv" in names
    assert all(s["dur_ms"] >= 0 for s in rec.spans)
    boundary = [s for s in rec.spans if s["name"] == "slate.posv"]
    assert boundary and boundary[0]["depth"] == 1

    chrome = tmp_path / "trace.json"
    rec.export_chrome_trace(str(chrome))
    doc = json.loads(chrome.read_text())
    assert doc["traceEvents"] and all(e["ph"] == "X"
                                      for e in doc["traceEvents"])
    assert {e["name"] for e in doc["traceEvents"]} == names

    jsonl = tmp_path / "spans.jsonl"
    rec.export_jsonl(str(jsonl))
    lines = [json.loads(ln) for ln in jsonl.read_text().splitlines()]
    assert len(lines) == len(rec.spans)
    assert all(ln["kind"] == "span" and ln["schema"] == obs.SCHEMA
               for ln in lines)


def test_spans_record_phase_breakdown_under_heev(rng):
    n = 32
    a = rng.standard_normal((n, n))
    A = st.HermitianMatrix.from_numpy(a + a.T, 16, st.Uplo.Lower)
    with obs.record_spans() as rec:
        st.heev(A)
    names = {s["name"] for s in rec.spans}
    assert {"slate.heev", "slate.heev/he2hb",
            "slate.heev/stage2"} <= names


def test_span_zero_overhead_without_recorder(rng):
    """No active recorder: span() must not allocate tokens or records."""
    from slate_tpu.obs import tracer
    assert tracer.active() is None
    hpd, b = _hpd(rng), _problem(rng)[1]
    st.posv(st.HermitianMatrix.from_numpy(hpd, 16),
            st.Matrix.from_numpy(b, 16))   # would crash if span needed one


# ------------------------------------------------------- metrics + CLI


def _write_events(path, rng):
    a, b = _problem(rng)
    A = st.Matrix.from_numpy(a, 16)
    B = st.Matrix.from_numpy(b, 16)
    obs.enable(str(path))
    try:
        st.gesv(A, B)
        st.gesv(A, B, SPEC_INFO)
        hpd = _hpd(rng)
        st.posv(st.HermitianMatrix.from_numpy(hpd, 16),
                st.Matrix.from_numpy(b, 16))
    finally:
        obs.disable()


def test_metrics_summarize_events(rng, tmp_path):
    p = tmp_path / "events.jsonl"
    _write_events(p, rng)
    s = obs.summarize([str(p)])
    assert s["counts"]["events"] == 3
    assert s["ops"]["gesv"]["count"] == 2
    assert s["ops"]["posv"]["count"] == 1
    assert s["ops"]["gesv"]["p50_ms"] > 0
    assert s["ops"]["gesv"]["error_rate"] == 0.0
    text = obs.render(s)
    assert "gesv" in text and "p50" in text


def test_metrics_summarize_bench_lines(tmp_path):
    p = tmp_path / "bench.jsonl"
    lines = [
        {"schema": "slate-bench-v1", "metric": "gemm_n4096_gflops_per_chip",
         "value": 123.4, "unit": "GFLOP/s", "chip": "cpu"},
        {"schema": "slate-bench-v1", "metric": "bench_svd_skipped",
         "value": None, "skipped": True, "reason": "time budget exceeded "
         "(watchdog)", "phase": "compile", "elapsed_s": 41.0,
         "chip": "cpu"},
        {"metric": "legacy_metric", "value": 7.0},   # pre-schema line
    ]
    p.write_text("\n".join(json.dumps(x) for x in lines) + "\n")
    s = obs.summarize([str(p)])
    assert s["counts"]["bench"] == 3
    assert len(s["bench"]["metrics"]) == 2
    (skip,) = s["bench"]["skipped"]
    assert skip["phase"] == "compile" and skip["elapsed_s"] == 41.0


def test_cli_text_and_json(rng, tmp_path, capsys):
    p = tmp_path / "events.jsonl"
    _write_events(p, rng)
    assert obs_cli.main([str(p)]) == 0
    out = capsys.readouterr().out
    assert "gesv" in out

    assert obs_cli.main(["--json", str(p)]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["ops"]["gesv"]["count"] == 2


def test_cli_missing_file_is_reported(tmp_path, capsys):
    assert obs_cli.main([str(tmp_path / "nope.jsonl")]) == 2
    assert "nope.jsonl" in capsys.readouterr().err


def test_env_var_configures_recording(tmp_path, monkeypatch):
    monkeypatch.setenv("SLATE_OBS_EVENTS", str(tmp_path / "ev.jsonl"))
    try:
        obs_events._init_from_env()
        assert obs.enabled()
    finally:
        obs.disable()
        obs_events.configure(path="")


def test_chrome_export_preserves_span_nesting(rng):
    """The Perfetto/Chrome export must keep nested phase spans INSIDE
    their boundary span on the timeline: depth parent+1, same tid, and
    the child's [ts, ts+dur] interval contained in the parent's — that
    containment is what makes the rendered flame graph truthful."""
    import tempfile
    n = 32
    a = rng.standard_normal((n, n))
    A = st.HermitianMatrix.from_numpy(a + a.T, 16, st.Uplo.Lower)
    with obs.record_spans() as rec:
        st.heev(A)
    with tempfile.TemporaryDirectory() as d:
        path = d + "/trace.json"
        rec.export_chrome_trace(path)
        with open(path, encoding="utf-8") as fh:
            events = json.load(fh)["traceEvents"]

    by_name = {}
    for e in events:
        by_name.setdefault(e["name"], []).append(e)
    (parent,) = by_name["slate.heev"]
    assert parent["args"]["depth"] == 1
    children = [e for name, evs in by_name.items() if name != "slate.heev"
                and name.startswith("slate.heev/") for e in evs]
    assert {e["name"] for e in children} >= {"slate.heev/he2hb",
                                             "slate.heev/stage2"}
    eps = 0.5                               # µs: ts/dur each round to 0.1
    p0, p1 = parent["ts"], parent["ts"] + parent["dur"]
    for ch in children:
        assert ch["args"]["depth"] >= parent["args"]["depth"] + 1
        assert ch["tid"] == parent["tid"]
        assert ch["ts"] >= p0 - eps
        assert ch["ts"] + ch["dur"] <= p1 + eps
        assert ch["dur"] <= parent["dur"]
    # phases must not overlap each other: he2hb finishes before stage2
    he2hb = [c for c in children if c["name"] == "slate.heev/he2hb"]
    stage2 = [c for c in children if c["name"] == "slate.heev/stage2"]
    assert he2hb and stage2
    assert he2hb[0]["ts"] + he2hb[0]["dur"] <= stage2[0]["ts"] + eps
