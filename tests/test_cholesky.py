"""Cholesky chain tests: potrf/potrs/posv/potri + trsm/trmm/herk residuals on
single device and 2x2 / 2x4 meshes (analog of ref test/test_posv.cc,
test_potrf.cc residual methodology: ||Ax-b|| / (||A|| ||x|| n)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import slate_tpu as st


def spd(rng, n, dtype=np.float64):
    a = rng.standard_normal((n, n)).astype(dtype)
    if np.issubdtype(dtype, np.complexfloating):
        a = a + 1j * rng.standard_normal((n, n))
    return a @ a.conj().T + n * np.eye(n)


@pytest.mark.parametrize("n,nb", [(16, 4), (23, 5), (32, 8)])
def test_potrf_single(rng, n, nb):
    a = spd(rng, n)
    A = st.HermitianMatrix.from_numpy(a, nb, st.Uplo.Lower)
    L = st.potrf(A)
    l = L.to_numpy()
    np.testing.assert_allclose(l @ l.T, a, rtol=1e-12, atol=1e-10)


def test_potrf_upper(rng):
    a = spd(rng, 12)
    A = st.HermitianMatrix.from_numpy(a, 4, st.Uplo.Upper)
    U = st.potrf(A)
    u = U.to_numpy()
    assert np.allclose(np.tril(u, -1), 0)
    np.testing.assert_allclose(u.T @ u, a, rtol=1e-12, atol=1e-10)


@pytest.mark.parametrize("p,q", [(2, 2), (2, 4)])
@pytest.mark.parametrize("n,nb", [(24, 4), (18, 5)])
def test_potrf_mesh(rng, p, q, n, nb):
    g = st.Grid(p, q, devices=jax.devices()[: p * q])
    a = spd(rng, n)
    A = st.HermitianMatrix.from_numpy(a, nb, st.Uplo.Lower, g)
    L = st.potrf(A)
    l = L.to_numpy()
    np.testing.assert_allclose(l @ l.T, a, rtol=1e-12, atol=1e-9)


def test_potrf_complex(rng):
    a = spd(rng, 12, np.complex128)
    A = st.HermitianMatrix.from_numpy(a, 4, st.Uplo.Lower)
    L = st.potrf(A)
    l = L.to_numpy()
    np.testing.assert_allclose(l @ l.conj().T, a, rtol=1e-12, atol=1e-10)


@pytest.mark.parametrize("uplo,op", [
    ("lower", "n"), ("lower", "t"), ("upper", "n"), ("upper", "t")])
@pytest.mark.parametrize("target", ["single", "mesh"])
def test_trsm_left(rng, uplo, op, target):
    n, nrhs, nb = 20, 12, 4
    lower = uplo == "lower"
    a = rng.standard_normal((n, n)) + n * np.eye(n)
    tri = np.tril(a) if lower else np.triu(a)
    b = rng.standard_normal((n, nrhs))
    if target == "mesh":
        g = st.Grid(2, 2, devices=jax.devices()[:4])
    else:
        g = None
    A = st.TriangularMatrix.from_numpy(
        a, nb, st.Uplo.Lower if lower else st.Uplo.Upper, grid=g)
    if op == "t":
        A = A.transpose()
    B = st.Matrix.from_numpy(b, nb, nb, g)
    X = st.trsm("l", 2.0, A, B)
    eff = tri.T if op == "t" else tri
    np.testing.assert_allclose(eff @ X.to_numpy(), 2.0 * b,
                               rtol=1e-10, atol=1e-10)


@pytest.mark.parametrize("target", ["single", "mesh"])
def test_trsm_right(rng, target):
    n, m, nb = 16, 12, 4
    a = rng.standard_normal((n, n)) + n * np.eye(n)
    tri = np.tril(a)
    b = rng.standard_normal((m, n))
    g = st.Grid(2, 2, devices=jax.devices()[:4]) if target == "mesh" else None
    A = st.TriangularMatrix.from_numpy(a, nb, st.Uplo.Lower, grid=g)
    B = st.Matrix.from_numpy(b, nb, nb, g)
    X = st.trsm("r", 1.0, A, B)
    np.testing.assert_allclose(X.to_numpy() @ tri, b, rtol=1e-10, atol=1e-10)


def test_trsm_unit_diag(rng):
    n = 12
    a = rng.standard_normal((n, n))
    b = rng.standard_normal((n, 4))
    A = st.TriangularMatrix.from_numpy(a, 4, st.Uplo.Lower, st.Diag.Unit)
    X = st.trsm("l", 1.0, A, st.Matrix.from_numpy(b, 4))
    tri = np.tril(a, -1) + np.eye(n)
    np.testing.assert_allclose(tri @ X.to_numpy(), b, rtol=1e-11, atol=1e-11)


def test_trmm(rng):
    n, m = 12, 8
    a = rng.standard_normal((n, n))
    b = rng.standard_normal((n, m))
    A = st.TriangularMatrix.from_numpy(a, 4, st.Uplo.Upper)
    B = st.Matrix.from_numpy(b, 4)
    out = st.trmm("l", 1.0, A, B)
    np.testing.assert_allclose(out.to_numpy(), np.triu(a) @ b, atol=1e-12)


def test_herk_syrk(rng):
    mkn = 12
    a = rng.standard_normal((mkn, 8))
    c = spd(rng, mkn)
    A = st.Matrix.from_numpy(a, 4)
    C = st.SymmetricMatrix.from_numpy(c, 4, st.Uplo.Lower)
    out = st.syrk(1.0, A, 0.5, C)
    np.testing.assert_allclose(out.to_numpy(), a @ a.T + 0.5 * c,
                               rtol=1e-12, atol=1e-10)
    Ch = st.HermitianMatrix.from_numpy(c, 4, st.Uplo.Lower)
    outh = st.herk(1.0, A, 0.5, Ch)
    np.testing.assert_allclose(outh.to_numpy(), a @ a.T + 0.5 * c,
                               rtol=1e-12, atol=1e-10)


def test_her2k_symm(rng):
    n, k = 10, 6
    a = rng.standard_normal((n, k))
    b = rng.standard_normal((n, k))
    c = spd(rng, n)
    A, B = st.Matrix.from_numpy(a, 4), st.Matrix.from_numpy(b, 4)
    C = st.HermitianMatrix.from_numpy(c, 4, st.Uplo.Lower)
    out = st.her2k(1.0, A, B, 1.0, C)
    np.testing.assert_allclose(out.to_numpy(), a @ b.T + b @ a.T + c,
                               rtol=1e-12, atol=1e-10)
    s = st.SymmetricMatrix.from_numpy(c, 4, st.Uplo.Lower)
    d = rng.standard_normal((n, 7))
    D = st.Matrix.from_numpy(d, 4)
    out2 = st.symm("l", 1.0, s, D)
    np.testing.assert_allclose(out2.to_numpy(), s.to_numpy() @ d,
                               rtol=1e-12, atol=1e-10)


@pytest.mark.parametrize("target,pq", [("single", None), ("mesh", (2, 2)),
                                       ("mesh", (2, 4))])
@pytest.mark.slow
def test_posv(rng, target, pq):
    n, nrhs, nb = 24, 8, 4
    g = st.Grid(*pq, devices=jax.devices()[: pq[0] * pq[1]]) if pq else None
    a = spd(rng, n)
    b = rng.standard_normal((n, nrhs))
    A = st.HermitianMatrix.from_numpy(a, nb, st.Uplo.Lower, g)
    B = st.Matrix.from_numpy(b, nb, nb, g)
    L, X = st.posv(A, B)
    x = X.to_numpy()
    resid = np.linalg.norm(a @ x - b) / (
        np.linalg.norm(a) * np.linalg.norm(x) * n)
    assert resid < 1e-15


def test_potri(rng):
    n = 12
    a = spd(rng, n)
    A = st.HermitianMatrix.from_numpy(a, 4, st.Uplo.Lower)
    L = st.potrf(A)
    Ainv = st.potri(L)
    np.testing.assert_allclose(Ainv.to_numpy() @ a, np.eye(n),
                               rtol=1e-10, atol=1e-9)


def test_posv_under_jit(rng):
    n = 16
    a = spd(rng, n)
    b = rng.standard_normal((n, 4))
    A = st.HermitianMatrix.from_numpy(a, 4, st.Uplo.Lower)
    B = st.Matrix.from_numpy(b, 4)

    @jax.jit
    def solve(A, B):
        _, X = st.posv(A, B)
        return X

    x = solve(A, B).to_numpy()
    assert np.linalg.norm(a @ x - b) / np.linalg.norm(b) < 1e-12


def test_trsm_right_conjtrans_mesh(rng):
    """Right-side solve against A^H on the mesh (regression: op composition
    rejected ConjTrans∘Trans)."""
    n, m, nb = 12, 8, 4
    g = st.Grid(2, 2, devices=jax.devices()[:4])
    a = rng.standard_normal((n, n)) + 1j * rng.standard_normal((n, n))
    a = a + n * np.eye(n)
    b = rng.standard_normal((m, n)) + 1j * rng.standard_normal((m, n))
    A = st.TriangularMatrix.from_numpy(a, nb, st.Uplo.Lower, grid=g)
    B = st.Matrix.from_numpy(b, nb, nb, g)
    X = st.trsm("r", 1.0, A.conj_transpose(), B)
    np.testing.assert_allclose(X.to_numpy() @ np.tril(a).conj().T, b,
                               rtol=1e-10, atol=1e-10)


def test_herk_rejects_general_C(rng):
    A = st.Matrix.from_numpy(rng.standard_normal((8, 4)), 4)
    C = st.Matrix.zeros(8, 8, 4)
    try:
        st.herk(1.0, A, 0.0, C)
        assert False, "expected SlateValueError"
    except st.SlateValueError:
        pass


@pytest.mark.slow
def test_potri_getri_mesh(rng):
    # inverses ride the distributed trsm/herk kernels on a mesh
    # (ref: src/trtri.cc, src/getri.cc distribute)
    import jax
    n, nb = 24, 4
    g = st.Grid(2, 2, devices=jax.devices()[:4])
    a0 = rng.standard_normal((n, n))
    s = a0 @ a0.T + n * np.eye(n)
    S = st.HermitianMatrix.from_numpy(s, nb, st.Uplo.Lower, g)
    L = st.potrf(S)
    Sinv = st.potri(L)
    np.testing.assert_allclose(s @ Sinv.general().to_numpy(), np.eye(n),
                               atol=1e-9)
    A = st.Matrix.from_numpy(a0 + n * np.eye(n), nb, nb, g)
    X = st.getriOOP(A)
    np.testing.assert_allclose((a0 + n * np.eye(n)) @ X.to_numpy(),
                               np.eye(n), atol=1e-9)
