"""Device-time truth tests (obs.flops / obs.slo / obs.compare + the
timing mode wired through util.trace.annotate and serve/server.py).

The load-bearing guarantees:

- the flops registry prices every public op analytically, and BOTH
  consumers — timed driver events and bench.py lines — derive mfu from
  the SAME model (the bench side is asserted in test_bench_smoke.py);
- ``obs.timing()`` stamps ``device_ms`` on the outermost EAGER boundary
  only: traced frames never sync, and the jaxpr is byte-identical with
  timing on or off (the jaxpr-identity guarantee extends to timing);
- the perf-regression sentinel (``--compare``) classifies the real
  checked-in rounds BENCH_r04 -> r05 (all shared metrics improved,
  exit 0) and gates the reverse diff (exit 1);
- SLO budgets evaluate against the serving aggregate with metric-owned
  directions, fail LOUDLY on missing data, and export Prometheus text;
- malformed/truncated JSONL is counted and reported, never fatal.
"""

import json
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import slate_tpu as st
from slate_tpu import obs
from slate_tpu.obs import __main__ as obs_cli
from slate_tpu.obs import compare as obs_compare
from slate_tpu.obs import events as obs_events
from slate_tpu.obs import flops, metrics, slo

REPO = Path(__file__).resolve().parent.parent


def _hpd(rng, n=32):
    a = rng.standard_normal((n, n))
    return a @ a.T / n + n * np.eye(n)


def _posv(rng, n=32, nb=16, k=4):
    return st.posv(st.HermitianMatrix.from_numpy(_hpd(rng, n), nb),
                   st.Matrix.from_numpy(rng.standard_normal((n, k)), nb))


# ------------------------------------------------------- flops registry


def test_flop_models_match_classic_counts():
    assert flops.op_flops("gemm", [(64, 32), (32, 48)]) == \
        2.0 * 64 * 32 * 48
    assert flops.op_flops("potrf", [(96, 96)]) == 96 ** 3 / 3.0
    assert flops.op_flops("posv", [(32, 32), (32, 4)]) == \
        32 ** 3 / 3.0 + 2.0 * 32 * 32 * 4
    assert flops.op_flops("gesv", [(32, 32), (32, 4)]) == \
        2.0 * 32 ** 3 / 3.0 + 2.0 * 32 * 32 * 4
    assert flops.op_flops("geqrf", [(96, 32)]) == \
        2.0 * 96 * 32 ** 2 - 2.0 * 32 ** 3 / 3.0
    assert flops.op_flops("gels", [(96, 32), (96, 4)]) == \
        2.0 * 96 * 32 ** 2 - 2.0 * 32 ** 3 / 3.0 + 4.0 * 96 * 32 * 4


def test_registry_is_total_over_serve_ops_and_rejects_garbage():
    # every serving op maps onto a registered dense model
    for model in flops.SERVE_OP_MODEL.values():
        assert model in flops.registered_ops()
    assert flops.op_flops("not_an_op", [(8, 8)]) is None
    assert flops.op_flops("gemm", []) is None          # shape-starved
    assert flops.op_flops("gemm", [("x", 3), (3, 3)]) is None
    assert flops.mfu(None, 1.0) is None
    assert flops.mfu(1e9, None) is None
    assert flops.achieved_gbps(None, 1.0) is None


def test_op_bytes_counts_operands_plus_result():
    # gemm f64: A(64x32) + B(32x48) read, C(64x32-result=first operand)
    nbytes = flops.op_bytes("gemm", [(64, 32), (32, 48)], "float64")
    assert nbytes == (64 * 32 + 32 * 48 + 64 * 32) * 8
    # unknown dtype falls back to 4-byte items
    assert flops.op_bytes("gemm", [(8, 8)], None) == (8 * 8 + 8 * 8) * 4


def test_peak_override_scopes():
    with flops.peak_override(1e12):
        assert flops.peak() == 1e12
        assert flops.mfu(5e11, 1.0) == 0.5
        assert flops.mfu(5e11, 0.5) == 1.0


def test_serve_flops_prices_live_problems_only():
    probs = [((32, 32), (32, 4)), ((20, 20), (20, 3))]
    want = (flops.op_flops("gesv", [(32, 32), (32, 4)])
            + flops.op_flops("gesv", [(20, 20), (20, 3)]))
    assert flops.serve_flops("solve", probs) == want
    assert flops.serve_flops("chol_solve", [((16, 16), (16, 2))]) == \
        flops.op_flops("posv", [(16, 16), (16, 2)])
    assert flops.serve_flops("unknown_op", probs) is None


# ----------------------------------------------------------- timing mode


def test_timing_event_fields_eager(rng):
    """Under obs.timing() an eager boundary blocks to device-ready and
    the event's mfu is EXACTLY the registry model over device_ms — the
    one-registry contract, asserted from the event itself."""
    with flops.peak_override(1e12):
        with obs.recording() as ev, obs.timing():
            _posv(rng)
        (e,) = ev
        assert e["device_ms"] is not None and e["device_ms"] > 0
        assert e["device_ms"] <= e["dur_ms"]
        secs = e["device_ms"] * 1e-3
        assert e["mfu"] == flops.mfu(
            flops.op_flops("posv", e["shapes"]), secs)
        assert e["achieved_gbps"] == flops.achieved_gbps(
            flops.op_bytes("posv", e["shapes"], e["dtype"]), secs)


def test_timing_off_leaves_fields_none(rng):
    with obs.recording() as ev:
        _posv(rng)
    (e,) = ev
    assert e["device_ms"] is None
    assert e["mfu"] is None and e["achieved_gbps"] is None


def test_traced_boundaries_never_sync(rng):
    """A jitted driver traces once; tracers hold no buffers, so the
    traced event must carry device_ms=None even with timing on."""
    a = jnp.asarray(_hpd(rng))
    b = jnp.asarray(rng.standard_normal((32, 4)))

    @jax.jit
    def run(a, b):
        from slate_tpu.core.storage import TileStorage
        M = st.Matrix(TileStorage.from_dense(a, 16, 16))
        L, X = st.posv(st.HermitianMatrix._from_view(M, st.Uplo.Lower),
                       st.Matrix(TileStorage.from_dense(b, 16, 16)))
        return X.to_dense()

    with obs.recording() as ev, obs.timing():
        run(a, b)
    (e,) = ev
    assert e["traced"] is True
    assert e["device_ms"] is None and e["mfu"] is None


def test_jaxpr_identity_timing_on_vs_off(rng):
    """Timing changes how the HOST waits, never what is traced."""
    from slate_tpu.core.storage import TileStorage

    def run(a, b):
        F, X = st.gesv(st.Matrix(TileStorage.from_dense(a, 16, 16)),
                       st.Matrix(TileStorage.from_dense(b, 16, 16)))
        return X.to_dense()

    a = jnp.asarray(rng.standard_normal((32, 32)) + 32 * np.eye(32))
    b = jnp.asarray(rng.standard_normal((32, 4)))
    off = str(jax.make_jaxpr(run)(a, b))
    with obs.recording(), obs.timing():
        on = str(jax.make_jaxpr(run)(a, b))
    assert on == off


def test_timing_env_var(monkeypatch):
    monkeypatch.delenv("SLATE_OBS_EVENTS", raising=False)
    monkeypatch.setenv("SLATE_OBS_TIMING", "1")
    try:
        obs_events._init_from_env()
        assert obs.timing_enabled()
    finally:
        obs.set_timing(False)
    assert not obs.timing_enabled()


def test_metrics_aggregate_device_time_columns(rng, tmp_path):
    path = tmp_path / "ev.jsonl"
    with flops.peak_override(1e12):
        obs.enable(str(path))
        try:
            with obs.timing():
                _posv(rng)
        finally:
            obs.disable()
    s = obs.summarize([str(path)])
    row = s["ops"]["posv"]
    assert row["device_p50_ms"] > 0
    assert row["mfu"] is not None
    text = metrics.render(s)
    assert "dev_p50_ms" in text and "mfu" in text


# --------------------------------------------- perf-regression sentinel


def test_compare_direction_and_noise_model():
    assert obs_compare.direction("gemm_n4096_gflops_per_chip") == "higher"
    assert obs_compare.direction("abft_overhead_pct") == "lower"
    assert obs_compare.direction("serve_latency_p99") == "lower"
    assert obs_compare.direction("roundtrip", "ms") == "lower"
    assert obs_compare.noise_pct("serve_mixed_problems_per_s") == 15.0
    assert obs_compare.noise_pct("sweep_potrf_xla") == 10.0
    assert obs_compare.noise_pct("gemm_n4096_gflops_per_chip") == \
        obs_compare.DEFAULT_NOISE_PCT
    # PERF r15 pipeline metrics ride the wider multi-device noise band,
    # and the speedup/overlap ratios count as higher-is-better
    assert obs_compare.noise_pct("summa_lookahead_d1_n8192_gflops") == 10.0
    assert obs_compare.noise_pct("dist_chol_lookahead_speedup_n16384") == \
        10.0
    assert obs_compare.direction("summa_lookahead_overlap_pct_n8192") == \
        "higher"


def _round(tmp_path, name, values):
    p = tmp_path / name
    p.write_text("".join(
        json.dumps({"schema": "slate-bench-v1", "metric": m, "value": v,
                    "unit": "GFLOP/s", "chip": "cpu"}) + "\n"
        for m, v in values.items()))
    return str(p)


def test_compare_classifies_and_gates(tmp_path):
    old = _round(tmp_path, "old.jsonl",
                 {"gemm": 100.0, "potrf": 100.0, "gone": 1.0})
    new = _round(tmp_path, "new.jsonl",
                 {"gemm": 120.0, "potrf": 97.0, "fresh": 2.0})
    r = obs_compare.compare(old, new)
    by = {row["metric"]: row for row in r["rows"]}
    assert by["gemm"]["class"] == "improved" and not by["gemm"]["gated"]
    assert by["potrf"]["class"] == "flat"     # -3% inside the 5% band
    assert r["only_old"] == ["gone"] and r["only_new"] == ["fresh"]
    assert r["regressions"] == []

    # -20% blows through max(gate, noise): regressed AND gated
    worse = _round(tmp_path, "worse.jsonl", {"gemm": 80.0, "potrf": 99.0})
    r = obs_compare.compare(old, worse)
    (bad,) = r["regressions"]
    assert bad["metric"] == "gemm" and bad["gated"]
    assert bad["delta_pct"] == -20.0


def test_compare_gate_threshold_is_the_ci_knob(tmp_path):
    """-6% is past the 5% noise band (regressed) but inside the default
    10% gate — tightening --gate is what turns it into a CI failure."""
    old = _round(tmp_path, "old.jsonl", {"gemm": 100.0})
    new = _round(tmp_path, "new.jsonl", {"gemm": 94.0})
    loose = obs_compare.compare(old, new)
    assert loose["rows"][0]["class"] == "regressed"
    assert not loose["regressions"]
    tight = obs_compare.compare(old, new, gate=5.0)
    assert tight["regressions"]
    assert obs_cli.main(["--compare", old, new]) == 0
    assert obs_cli.main(["--compare", old, new, "--gate", "5"]) == 1


def test_compare_noisy_metrics_get_wider_bands(tmp_path):
    # -12% on a serve metric stays flat (15% band); on a dense metric
    # it regresses
    old = _round(tmp_path, "old.jsonl",
                 {"serve_mixed_problems_per_s": 100.0, "gemm": 100.0})
    new = _round(tmp_path, "new.jsonl",
                 {"serve_mixed_problems_per_s": 88.0, "gemm": 88.0})
    by = {r["metric"]: r for r in obs_compare.compare(old, new)["rows"]}
    assert by["serve_mixed_problems_per_s"]["class"] == "flat"
    assert by["gemm"]["class"] == "regressed"


def test_compare_lower_better_metrics(tmp_path):
    old = _round(tmp_path, "old.jsonl", {"abft_overhead_pct": 20.0})
    new = _round(tmp_path, "new.jsonl", {"abft_overhead_pct": 10.0})
    (row,) = obs_compare.compare(old, new)["rows"]
    assert row["better"] == "lower" and row["class"] == "improved"
    (row,) = obs_compare.compare(new, old)["rows"]
    assert row["class"] == "regressed" and row["gated"]


def test_cli_compare_real_rounds_r04_to_r05(capsys):
    """The acceptance drill: diff the checked-in pre-schema wrapper
    rounds.  Every shared metric improved r04 -> r05, so the gate passes;
    the reverse diff is 3 gated regressions and exit 1."""
    r04 = str(REPO / "BENCH_r04.json")
    r05 = str(REPO / "BENCH_r05.json")
    assert obs_cli.main(["--compare", r04, r05]) == 0
    out = capsys.readouterr().out
    assert "gemm_n4096_gflops_per_chip" in out
    assert "improved" in out and "(0 gated)" in out

    assert obs_cli.main(["--compare", r05, r04]) == 1
    out = capsys.readouterr().out
    assert "[GATED]" in out and "regressed" in out


def test_cli_compare_json_and_missing_file(tmp_path, capsys):
    r04 = str(REPO / "BENCH_r04.json")
    r05 = str(REPO / "BENCH_r05.json")
    assert obs_cli.main(["--json", "--compare", r04, r05]) == 0
    doc = json.loads(capsys.readouterr().out)
    shared = {r["metric"] for r in doc["rows"]}
    assert {"gemm_n4096_gflops_per_chip", "gemm_n8192_gflops_per_chip",
            "posv_n16384_gflops_per_chip"} <= shared
    assert all(r["class"] == "improved" for r in doc["rows"])
    assert obs_cli.main(["--compare", r04,
                         str(tmp_path / "nope.json")]) == 2


# ------------------------------------------------------------ SLO budgets


def _serve_rec(op="solve", dtype="float32", lat=(5.0, 7.0), **kw):
    rec = {"schema": "slate-obs-v1", "kind": "serve_batch", "op": op,
           "dtype": dtype, "bucket": [32, 8], "batch": 4,
           "problems": len(lat), "occupancy": len(lat) / 4,
           "padding_waste": 0.2, "escalated": 0, "compiled": False,
           "retraces": 0, "ladder": "geometric", "dur_ms": 2.0,
           "device_ms": None, "mfu": 0.25, "achieved_gbps": None,
           "queue_depth": len(lat),
           "age_at_flush_ms": [0.5] * len(lat), "latency_ms": list(lat)}
    rec.update(kw)
    return rec


def test_slo_aggregate_builds_union_row():
    recs = [_serve_rec(), _serve_rec(op="chol_solve", lat=(3.0,))]
    stats = slo.aggregate(recs)
    assert set(stats) == {"solve/float32", "chol_solve/float32", "*"}
    assert stats["*"]["problems"] == 3
    assert stats["*"]["latency_p99_ms"] is not None
    assert stats["solve/float32"]["latency_p50_ms"] == 6.0


def test_slo_evaluate_directions_and_loud_missing_data():
    stats = slo.aggregate([_serve_rec()])
    verdicts = slo.evaluate(stats, {
        "*": {"latency_p99_ms": 10.0},          # max bound: 7 <= 10 PASS
        "solve": {"mfu": 0.5},                  # min bound: 0.25 < 0.5 FAIL
        "solve/float32": {"esc_per_1k": 5.0},   # 0 <= 5 PASS
        "qr/float64": {"latency_p99_ms": 1.0},  # no such row: FAIL
    })
    by = {(v["target"], v["metric"]): v for v in verdicts}
    assert by[("*", "latency_p99_ms")]["ok"]
    assert not by[("solve", "mfu")]["ok"]
    assert by[("solve", "mfu")]["row"] == "solve/float32"   # bare-op match
    assert by[("solve/float32", "esc_per_1k")]["ok"]
    missing = by[("qr/float64", "latency_p99_ms")]
    assert not missing["ok"] and missing["value"] is None

    # a budget naming a metric the stream never measured must FAIL
    (v,) = slo.evaluate(stats, {"*": {"no_such_metric": 1.0}})
    assert not v["ok"] and v["value"] is None


def _write_serve_stream(tmp_path):
    p = tmp_path / "serve.jsonl"
    p.write_text("".join(json.dumps(_serve_rec()) + "\n"
                         for _ in range(3)))
    return str(p)


def test_cli_slo_exit_codes_pinned(tmp_path, capsys):
    stream = _write_serve_stream(tmp_path)
    good = tmp_path / "good.json"
    good.write_text(json.dumps({"*": {"latency_p99_ms": 100.0,
                                      "esc_per_1k": 5.0}}))
    assert obs_cli.main(["--slo", str(good), stream]) == 0
    out = capsys.readouterr().out
    assert "PASS" in out and "2/2 budget check(s) passed" in out

    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"*": {"latency_p99_ms": 1.0}}))
    assert obs_cli.main(["--slo", str(bad), stream]) == 1
    assert "FAIL" in capsys.readouterr().out

    garbled = tmp_path / "garbled.json"
    garbled.write_text(json.dumps(["not", "a", "mapping"]))
    assert obs_cli.main(["--slo", str(garbled), stream]) == 2
    assert "budgets" in capsys.readouterr().err


def test_cli_prometheus_export(tmp_path, capsys):
    stream = _write_serve_stream(tmp_path)
    assert obs_cli.main(["--prom", stream]) == 0
    out = capsys.readouterr().out
    assert '# TYPE slate_serve_latency_p99_ms gauge' in out
    assert 'slate_serve_latency_p99_ms{op="solve",dtype="float32"} 7' \
        in out
    assert 'op="*"' in out                     # the union row exports too
    # every sample line parses as NAME{labels} VALUE
    for line in out.splitlines():
        if line.startswith("#") or not line:
            continue
        name_labels, value = line.rsplit(" ", 1)
        assert name_labels.startswith("slate_serve_")
        float(value)


# ------------------------------------------- malformed-input hardening


def test_load_records_counts_truncated_json(tmp_path):
    p = tmp_path / "events.jsonl"
    p.write_text(
        json.dumps(_serve_rec()) + "\n"
        "INFO some interleaved log line\n"
        '{"schema": "slate-obs-v1", "kind": "event", "op": "ges\n'
        '{"metric": "gemm", "value": 1.0}\n'
        '["a", "json", "array", "line"]\n')
    records, malformed = metrics.load_records([str(p)])
    # truncated dict line counts; the log line does not; the non-dict
    # array line counts (it parses but is not a record)
    assert malformed == 2
    assert len(records) == 2
    s = obs.summarize([str(p)])
    assert s["counts"]["malformed"] == 2
    text = metrics.render(s)
    assert "malformed=2 truncated/garbled line(s) skipped" in text


def test_render_omits_malformed_footer_when_clean(tmp_path):
    p = tmp_path / "events.jsonl"
    p.write_text(json.dumps(_serve_rec()) + "\n")
    assert "malformed" not in metrics.render(obs.summarize([str(p)]))


def test_load_records_harvests_wrapper_tail(tmp_path):
    p = tmp_path / "BENCH_rXX.json"
    p.write_text(json.dumps({
        "cmd": "python bench.py",
        "rc": 0,
        "tail": ("warming up...\n"
                 '{"schema": "slate-bench-v1", "metric": "gemm", '
                 '"value": 42.0, "unit": "GFLOP/s"}\n'),
    }, indent=1))
    records, malformed = metrics.load_records([str(p)])
    assert malformed == 0
    assert [r["metric"] for r in records] == ["gemm"]
