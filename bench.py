"""Headline benchmark for the driver: prints ONE JSON line.

Measures framework gemm throughput on the available accelerator (BASELINE.md
config #1 family).  Baseline: the reference's only in-repo absolute number —
dgemm n=10000, 4 ranks x 1 GPU, 0.712 s (docs/usage.md:41-42) = 2*n^3/t/4 ≈
702 GFLOP/s per GPU.  We report GFLOP/s per chip for the framework's gemm at
n=4096 (f32 — TPU v5e has no native f64; the mixed-precision solvers are the
f64-accuracy path, see slate_tpu/drivers/mixed.py).

Timing: the remote-tunnel platform makes block_until_ready a no-op and a
host fetch costs ~70 ms round trip, so we chain ``iters`` dependent gemms
inside one jitted scan and fetch one element — the round trip is amortised
and each step truly depends on the previous (no dead-code elimination).
"""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

import slate_tpu as st

BASELINE_GFLOPS_PER_CHIP = 702.0  # ref docs/usage.md:41-42, per-GPU dgemm


def bench_gemm(n=4096, nb=256, iters=50, reps=3):
    rng = np.random.default_rng(0)
    a = rng.standard_normal((n, n)).astype(np.float32)
    b = rng.standard_normal((n, n)).astype(np.float32)
    A = st.Matrix.from_numpy(a, nb, nb)
    B = st.Matrix.from_numpy(b, nb, nb)

    def chained(A, B):
        def body(carry, _):
            C = st.gemm(1.0 / n, A, st.Matrix(st.TileStorage(
                carry, B.storage.m, B.storage.n, B.storage.mb,
                B.storage.nb, B.storage.grid)))
            return C.storage.data, None
        out, _ = lax.scan(body, B.storage.data, None, length=iters)
        return out

    run = jax.jit(chained)
    np.asarray(jax.device_get(run(A, B)[0, 0, 0, 0]))  # compile + warmup

    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        np.asarray(jax.device_get(run(A, B)[0, 0, 0, 0]))
        times.append(time.perf_counter() - t0)
    t = min(times)
    return 2.0 * n * n * n * iters / t / 1e9


def main():
    gflops = bench_gemm()
    print(json.dumps({
        "metric": "gemm_n4096_gflops_per_chip",
        "value": round(gflops, 1),
        "unit": "GFLOP/s",
        "vs_baseline": round(gflops / BASELINE_GFLOPS_PER_CHIP, 2),
    }))


if __name__ == "__main__":
    main()
