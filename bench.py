"""Headline benchmarks for the driver: prints one JSON line PER metric.

Covers BASELINE.md configs 1-4 (single-chip, single-target — the per-chip
building block of the 2D-grid configs) plus raw-MXU context:

  gemm   n=4096  f32  (config #1, kept for cross-round continuity)
  gemm   n=8192  f32  (larger-tile point where the chip leaves dispatch
                       overhead behind; closer to the chip's real ceiling)
  gemm   n=16384 f32  (near-peak point: raw dot measures ~0.6 MFU here)
  posv   n=16384 f32  (config #2 family: potrf + potrs, nrhs=256)
  gesv   n=16384 f32  (config #3 family: getrf partial pivot + getrs)
  geqrf  131072x1024  (config #4: tall-skinny Householder QR)
  gels   131072x1024  (config #4: least squares, auto method = CholQR)

Each line reports GFLOP/s/chip, ``mfu`` — the fraction of the chip's
dense-matmul peak — ``device_ms`` (best-rep seconds per chained solve) and
``flops`` (the per-iteration analytic count).  Both the flop formulas and
the chip-peak table come from slate_tpu.obs.flops — the SAME registry that
prices driver events under ``obs.timing()`` — so a bench line and a
production event can never disagree about an op's MFU (on TPU the MXU
computes bf16 x bf16 -> f32, and XLA's default f32 matmul runs single-pass
at that same rate, so one peak number applies to both precisions).  The
registered counts follow the reference tester: gemm 2mnk (ref:
src/gemm.cc:24), potrf n^3/3 + solve 2n^2*nrhs (ref: src/potrf.cc:334),
getrf 2n^3/3 + solve, geqrf 2mn^2 - 2n^3/3 (testsweeper gflop helpers);
gels reports the same nominal flops as the QR path regardless of method,
as the reference tester does.

Timing: the remote-tunnel platform makes block_until_ready a no-op and a
host fetch costs ~70 ms round trip, so each benchmark chains ``iters``
DEPENDENT solves inside one jitted lax.scan (a scalar distilled from each
result perturbs the next input, so nothing is dead code and steps cannot
overlap) and fetches one element once.

``vs_baseline`` is value / 702 GFLOP/s — the only absolute number the
reference repo publishes (dgemm n=10000, 4 ranks x 1 GPU, 0.712 s =
702 GFLOP/s per GPU, ref docs/usage.md:41-42).  Set SLATE_BENCH_QUICK=1 for
a seconds-scale smoke run of the same harness at toy sizes.

``--sweep-nb`` switches to the autotuner's search space instead of the
headline metrics: one JSON line per candidate (kernel, nb, bw) plan per
op (slate_tpu.tune.autotune), so BENCH rounds record what the tuner saw.
"""

import argparse
import json
import os
import signal
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

import slate_tpu as st
from slate_tpu.core.storage import TileStorage
from slate_tpu.obs import flops as _flops
from slate_tpu.obs.metrics import BENCH_SCHEMA

BASELINE_GFLOPS_PER_CHIP = 702.0  # ref docs/usage.md:41-42, per-GPU dgemm
QUICK = bool(int(os.environ.get("SLATE_BENCH_QUICK", "0")))
# per-metric time budget in seconds (0 = unlimited).  The run gets a total
# pool of BUDGET_S * n_metrics; a metric that would start with the pool
# exhausted, or that overruns it mid-flight (SIGALRM preemption), emits an
# explicit "skipped" JSON line instead of eating the remaining metrics'
# time — every invocation emits one line per metric and exits 0.
BUDGET_S = float(os.environ.get("SLATE_BENCH_BUDGET_S", "0") or 0)


def _chip_peak():
    """(dense matmul peak FLOP/s, device_kind) for MFU; None if unknown.
    Delegates to obs.flops.chip_peak — ONE peak table for bench lines and
    timed driver events alike."""
    return _flops.chip_peak()


PEAK, CHIP = None, "cpu"

# Live progress shared with the watchdog thread: which step index is in
# flight, whether it is compiling or running timed reps, and when it
# started — so a budget skip line can say WHERE the time went (a stall in
# a 400 s compile reads very differently from a slow run phase).
_PROGRESS = {"idx": None, "phase": None, "t0": None}


def _mat(dense, mb, nb):
    return st.Matrix(TileStorage.from_dense(dense, mb, nb))


def _time_chain(body, init, args, iters, flops_per_iter, reps=3):
    """Best-of-reps (GFLOP/s, seconds-per-iteration) for ``iters``
    dependent body applications.

    ``args`` (the big operands) are jit ARGUMENTS, not closure constants —
    the remote-compile tunnel serializes closed-over arrays into the compile
    request, which both bloats it past the request-size limit and bakes the
    data into the program."""

    def chained(c0, *ops):
        c, _ = lax.scan(lambda c, _: (body(c, *ops), None), c0, None,
                        length=iters)
        # distil to ONE scalar: fetching a large result through the tunnel
        # costs seconds and would dominate the measurement
        while getattr(c, "ndim", 0) > 0:
            c = c[(0,) * c.ndim]
        return c

    run = jax.jit(chained)
    _PROGRESS["phase"] = "compile"
    np.asarray(jax.device_get(run(init, *args)))   # compile + warmup
    _PROGRESS["phase"] = "run"
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        np.asarray(jax.device_get(run(init, *args)))
        times.append(time.perf_counter() - t0)
    sec = min(times) / iters
    return flops_per_iter / sec / 1e9, sec


def _emit(metric, timed, flops=None, extra=None):
    """One bench line.  ``timed`` is a _time_chain result — (GFLOP/s,
    sec-per-iter) — or a bare GFLOP/s number; ``flops`` the analytic
    per-iteration count (from the obs.flops registry) recorded so any
    consumer can re-derive mfu = value*1e9 / PEAK without re-implementing
    the model."""
    gflops, sec = timed if isinstance(timed, tuple) else (timed, None)
    line = {
        "schema": BENCH_SCHEMA,
        "metric": metric,
        "value": round(float(gflops), 1),
        "unit": "GFLOP/s",
        "vs_baseline": round(float(gflops) / BASELINE_GFLOPS_PER_CHIP, 2),
        "mfu": (round(gflops * 1e9 / PEAK, 3) if PEAK else None),
        "chip": CHIP,
        "device_ms": (round(sec * 1e3, 3) if sec is not None else None),
        "flops": flops,
    }
    if extra:
        line.update(extra)
    print(json.dumps(line), flush=True)


def bench_gemm(n, nb, iters):
    rng = np.random.default_rng(0)
    a = rng.standard_normal((n, n)).astype(np.float32)
    b = rng.standard_normal((n, n)).astype(np.float32)
    A = st.Matrix.from_numpy(a, nb, nb)
    B = st.Matrix.from_numpy(b, nb, nb)

    def body(carry, adata):
        # carry IS the tile storage of the running product (no re-tiling)
        C = st.gemm(1.0 / n, st.Matrix(TileStorage(
            adata, A.storage.m, A.storage.n, nb, nb, A.storage.grid)),
            st.Matrix(TileStorage(carry, B.storage.m, B.storage.n, nb, nb,
                                  B.storage.grid)))
        return C.storage.data

    flops = _flops.op_flops("gemm", [(n, n), (n, n)])
    timed = _time_chain(body, B.storage.data, (A.storage.data,), iters,
                        flops)
    _emit(f"gemm_n{n}_gflops_per_chip", timed, flops, {"nb": nb})


def bench_posv(n, nb, nrhs, iters):
    rng = np.random.default_rng(1)
    # SPD without an O(n^3) host product: symmetrize + diagonal dominance
    a0 = rng.standard_normal((n, n)).astype(np.float32)
    a = jnp.asarray(a0 + a0.T) * 0.001 + jnp.eye(n, dtype=jnp.float32) * 4.0
    b = jnp.asarray(rng.standard_normal((n, nrhs)).astype(np.float32))

    def body(carry, a, b):
        H = st.HermitianMatrix._from_view(
            _mat(a * (1.0 + carry), nb, nb), st.Uplo.Lower)
        _, X = st.posv(H, _mat(b, nb, nb))
        return X.to_dense()[0, 0] * 1e-24      # data dependence, ~0

    flops = _flops.op_flops("posv", [(n, n), (n, nrhs)])
    timed = _time_chain(body, jnp.float32(0.0), (a, b), iters, flops)
    _emit(f"posv_n{n}_gflops_per_chip", timed, flops,
          {"nb": nb, "nrhs": nrhs})


def bench_gesv(n, nb, nrhs, iters):
    rng = np.random.default_rng(2)
    a = jnp.asarray(rng.standard_normal((n, n)).astype(np.float32))
    b = jnp.asarray(rng.standard_normal((n, nrhs)).astype(np.float32))
    # CALU tournament pivoting — BASELINE config #3 specifies the tntpiv
    # variant (and its bounded-height chunk LUs fit TPU scoped VMEM, which
    # XLA's monolithic tall-panel LU custom call does not at this size).
    # Depth=4 flattens the reduction tree to ONE batched merge level —
    # each level is a latency-bound batched LU, so fewer levels win.
    opts = {st.Option.MethodLU: st.MethodLU.CALU, st.Option.Depth: 4}

    def body(carry, a, b):
        A = _mat(a * (1.0 + carry), nb, nb)
        _, X = st.gesv(A, _mat(b, nb, nb), opts)
        return X.to_dense()[0, 0] * 1e-24

    flops = _flops.op_flops("gesv", [(n, n), (n, nrhs)])
    timed = _time_chain(body, jnp.float32(0.0), (a, b), iters, flops)
    _emit(f"gesv_n{n}_gflops_per_chip", timed, flops,
          {"nb": nb, "nrhs": nrhs, "method": "tntpiv"})


def bench_geqrf(m, n, nb, iters):
    rng = np.random.default_rng(3)
    a = jnp.asarray(rng.standard_normal((m, n)).astype(np.float32))

    def body(carry, a):
        F = st.geqrf(_mat(a * (1.0 + carry), nb, nb))
        return F.QR.to_dense()[0, 0] * 1e-24

    flops = _flops.op_flops("geqrf", [(m, n)])
    timed = _time_chain(body, jnp.float32(0.0), (a,), iters, flops)
    _emit(f"geqrf_tall_{m}x{n}_gflops_per_chip", timed, flops, {"nb": nb})


def bench_gels(m, n, nb, nrhs, iters):
    rng = np.random.default_rng(4)
    a = jnp.asarray(rng.standard_normal((m, n)).astype(np.float32))
    b = jnp.asarray(rng.standard_normal((m, nrhs)).astype(np.float32))

    def body(carry, a, b):
        X = st.gels(_mat(a * (1.0 + carry), nb, nb), _mat(b, nb, nb))
        return X.to_dense()[0, 0] * 1e-24

    # nominal QR-path flops, as the reference tester reports for any method
    flops = _flops.op_flops("gels", [(m, n), (m, nrhs)])
    timed = _time_chain(body, jnp.float32(0.0), (a, b), iters, flops)
    _emit(f"gels_tall_{m}x{n}_gflops_per_chip", timed, flops,
          {"nb": nb, "nrhs": nrhs, "method": "cholqr"})


def bench_gesv_rbt(n, nb, nrhs, iters):
    """gesv under Option.Speculate: RBT-preconditioned NoPiv LU + 2 IR
    steps + residual certificate (robust/recovery.py) — the pivot-free
    fast path that targets posv's regime instead of the CALU pivoting
    wall (docs/PERF.md round 6).  Under jit the whole speculative attempt
    traces into one program (certification rides along as data; the
    escalation branch is eager-only), so this measures the honest
    fast-path cost including its certificate."""
    rng = np.random.default_rng(7)
    a = jnp.asarray(rng.standard_normal((n, n)).astype(np.float32))
    b = jnp.asarray(rng.standard_normal((n, nrhs)).astype(np.float32))
    opts = {st.Option.Speculate: "on", st.Option.ErrorPolicy: "info"}

    def body(carry, a, b):
        A = _mat(a * (1.0 + carry), nb, nb)
        _, X, h = st.gesv(A, _mat(b, nb, nb), opts)
        return X.to_dense()[0, 0] * 1e-24

    flops = _flops.op_flops("gesv", [(n, n), (n, nrhs)])
    timed = _time_chain(body, jnp.float32(0.0), (a, b), iters, flops)
    _emit(f"gesv_rbt_n{n}_gflops_per_chip", timed, flops,
          {"nb": nb, "nrhs": nrhs, "method": "rbt+nopiv"})


def bench_gesv_abft(n, nb, nrhs, iters):
    """gesv under Option.Abft (Huang-Abraham checksum verification of the
    panel, the U12 solve, and the trailing update — robust/abft.py) timed
    against the identical plain run: the emitted value is the protected
    GFLOP/s, ``abft_overhead_pct`` the wall-clock cost of the O(n^2)
    checksum shadow over the O(n^3) it guards."""
    rng = np.random.default_rng(8)
    a = jnp.asarray(rng.standard_normal((n, n)).astype(np.float32))
    b = jnp.asarray(rng.standard_normal((n, nrhs)).astype(np.float32))

    def body_for(opts):
        def body(carry, a, b):
            A = _mat(a * (1.0 + carry), nb, nb)
            out = st.gesv(A, _mat(b, nb, nb), opts)
            return out[1].to_dense()[0, 0] * 1e-24
        return body

    flops = _flops.op_flops("gesv", [(n, n), (n, nrhs)])
    plain, _ = _time_chain(body_for(None), jnp.float32(0.0), (a, b), iters,
                           flops)
    prot = _time_chain(
        body_for({st.Option.Abft: "on", st.Option.ErrorPolicy: "info"}),
        jnp.float32(0.0), (a, b), iters, flops)
    _emit(f"gesv_abft_n{n}_gflops_per_chip", prot, flops,
          {"nb": nb, "nrhs": nrhs, "plain_gflops": round(float(plain), 1),
           "abft_overhead_pct": round((plain / prot[0] - 1.0) * 100.0, 1)})


def bench_posv_abft(n, nb, nrhs, iters):
    """posv under Option.Abft vs plain (see bench_gesv_abft)."""
    rng = np.random.default_rng(9)
    a0 = rng.standard_normal((n, n)).astype(np.float32)
    a = jnp.asarray(a0 + a0.T) * 0.001 + jnp.eye(n, dtype=jnp.float32) * 4.0
    b = jnp.asarray(rng.standard_normal((n, nrhs)).astype(np.float32))

    def body_for(opts):
        def body(carry, a, b):
            H = st.HermitianMatrix._from_view(
                _mat(a * (1.0 + carry), nb, nb), st.Uplo.Lower)
            out = st.posv(H, _mat(b, nb, nb), opts)
            return out[1].to_dense()[0, 0] * 1e-24
        return body

    flops = _flops.op_flops("posv", [(n, n), (n, nrhs)])
    plain, _ = _time_chain(body_for(None), jnp.float32(0.0), (a, b), iters,
                           flops)
    prot = _time_chain(
        body_for({st.Option.Abft: "on", st.Option.ErrorPolicy: "info"}),
        jnp.float32(0.0), (a, b), iters, flops)
    _emit(f"posv_abft_n{n}_gflops_per_chip", prot, flops,
          {"nb": nb, "nrhs": nrhs, "plain_gflops": round(float(plain), 1),
           "abft_overhead_pct": round((plain / prot[0] - 1.0) * 100.0, 1)})


def bench_heev(n, nb, iters):
    """Two-stage eigensolver, values only (BASELINE config #5 family).

    Stage 2 is the MethodEig.Auto band seam: jitted end-to-end this runs
    ~62x faster than routing through the bulge-chase scan (39.8 s -> 0.64 s
    at n=4096 on one v5e chip; the chase's sequential rank-1 scan steps are
    pure dispatch latency when the tridiagonal kernel is dense eigh anyway).
    """
    rng = np.random.default_rng(5)
    a0 = rng.standard_normal((n, n)).astype(np.float32)
    a = jnp.asarray((a0 + a0.T) / 2)

    def body(carry, a):
        H = st.HermitianMatrix._from_view(
            _mat(a * (1.0 + carry), nb, nb), st.Uplo.Lower)
        w = st.heev_vals(H)
        return w[0] * 1e-24

    flops = _flops.op_flops("heev_vals", [(n, n)])
    timed = _time_chain(body, jnp.float32(0.0), (a,), iters, flops)
    _emit(f"heev_vals_n{n}_gflops_per_chip", timed, flops, {"nb": nb})


def bench_svd(n, nb, iters):
    """Two-stage SVD, values only (BASELINE config #5 family): ge2tb band
    reduction + the MethodSvd.Auto band seam."""
    rng = np.random.default_rng(6)
    a = jnp.asarray(rng.standard_normal((n, n)).astype(np.float32))

    def body(carry, a):
        s = st.svd_vals(_mat(a * (1.0 + carry), nb, nb))
        return s[0] * 1e-24

    flops = _flops.op_flops("svd_vals", [(n, n)])
    timed = _time_chain(body, jnp.float32(0.0), (a,), iters, flops)
    _emit(f"svd_vals_n{n}_gflops_per_chip", timed, flops, {"nb": nb})


def _kernel_interpret():
    """Fused Pallas kernels run in interpret mode off-TPU (CPU smoke)."""
    try:
        return jax.default_backend() != "tpu"
    except Exception:  # noqa: BLE001 — no backend at all
        return True


def bench_potrf_fused(n, nb, bw, iters):
    """Fused Cholesky panel step (PERF r7): one pallas_call doing the
    trailing update (col - left @ lead), the nb x nb tile factor, and the
    L21 panel solve, MXU-resident.  Measures the panel seam in isolation
    so sweeps can compare (nb, bw) plans without full-driver noise."""
    from slate_tpu.internal.pallas_chol import chol_panel_fused

    rng = np.random.default_rng(7)
    k = nb                                  # one prior panel of history
    base = rng.standard_normal((n, nb)).astype(np.float32)
    top = base[:nb] @ base[:nb].T / nb + nb * np.eye(nb, dtype=np.float32)
    target = np.concatenate([top, base[nb:]], axis=0)
    left = (rng.standard_normal((n, k)).astype(np.float32) * 0.01)
    lead = left[:nb].T.copy()
    col = jnp.asarray(target + left @ lead)
    left, lead = jnp.asarray(left), jnp.asarray(lead)
    interp = _kernel_interpret()

    def body(carry, col, left, lead):
        upd, fac = chol_panel_fused(col * (1.0 + carry), left, lead,
                                    bw=bw, interpret=interp)
        return fac[0, 0] * 1e-24

    # update 2*n*nb*k + tile factor nb^3/3 + panel solve (n-nb)*nb^2 —
    # a kernel-seam cost, not a public op, so no registry entry applies
    flops = 2.0 * n * nb * k + nb**3 / 3.0 + (n - nb) * nb**2
    timed = _time_chain(body, jnp.float32(0.0), (col, left, lead),
                        iters, flops)
    _emit(f"potrf_fused_n{n}_gflops_per_chip", timed, flops,
          {"nb": nb, "bw": bw})


def bench_geqrf_panel(m, n, iters):
    """Pallas Householder QR panel (PERF r7): panel factor + compact-WY T
    in one kernel.  The panel is the latency-bound piece of tall-skinny
    geqrf, so its throughput bounds the gels MFU target."""
    from slate_tpu.internal.pallas_qr import qr_panel_pallas

    rng = np.random.default_rng(8)
    a = jnp.asarray(rng.standard_normal((m, n)).astype(np.float32))
    interp = _kernel_interpret()

    def body(carry, a):
        packed, t = qr_panel_pallas(a * (1.0 + carry), interpret=interp)
        return packed[0, 0] * 1e-24

    flops = 2.0 * m * n**2            # dominant term of 2mn^2 - 2n^3/3
    timed = _time_chain(body, jnp.float32(0.0), (a,), iters, flops)
    _emit(f"geqrf_panel_m{m}_n{n}_gflops_per_chip", timed, flops)


def _lookahead_grid():
    """Largest supported process grid on this host: (2,2) with >=4
    devices, a 1-D ring with 2, degenerate (1,1) otherwise (rings of
    size 1 have zero hops — the bench still runs and reports)."""
    devs = jax.devices()
    if len(devs) >= 4:
        p, q = 2, 2
    elif len(devs) >= 2:
        p, q = 1, 2
    else:
        p, q = 1, 1
    return st.Grid(p, q, devices=devs[: p * q])


def _overlap_probe(g, mtl, ntl, nb, op, both_axes=True, reps=5):
    """overlap_pct for the PERF r15 pipeline: the share of one step's
    panel ring-broadcast wall time that the same step's local
    accumulate can hide — sum(min(bcast_i, acc_i)) / sum(bcast_i) over
    ``reps`` eagerly timed phase pairs.  100% means depth-1 lookahead
    fully hides the broadcast; the phases are timed under the SAME span
    names the jitted pipeline emits (slate.<op>/bcast_ahead vs
    /accumulate) so the flight recorder and this probe agree on
    vocabulary.  ``both_axes`` times the SUMMA pair of rings (A panel
    along q, B panel along p); off, the factorization single col-ring."""
    from slate_tpu import obs
    from slate_tpu.comm.collectives import (ring_bcast_from_col,
                                            ring_bcast_from_row)
    from slate_tpu.core.grid import TILE_SPEC
    from slate_tpu.util.trace import span

    spec = TILE_SPEC
    p, q = g.p, g.q

    def _bcast(apan, bpan):
        out = ring_bcast_from_col(apan, 0, q)
        if both_axes:
            return out, ring_bcast_from_row(bpan, 0, p)
        return out, bpan

    def _acc(apan, bpan, c):
        return c + jnp.einsum("mkab,knbc->mnac", apan, bpan)

    bc = jax.jit(jax.shard_map(_bcast, mesh=g.mesh, in_specs=(spec, spec),
                               out_specs=(spec, spec)))
    ac = jax.jit(jax.shard_map(_acc, mesh=g.mesh,
                               in_specs=(spec, spec, spec),
                               out_specs=spec))
    rng = np.random.default_rng(15)
    apan = jnp.asarray(rng.standard_normal(
        (p * mtl, q, nb, nb)).astype(np.float32))
    bpan = jnp.asarray(rng.standard_normal(
        (p, q * ntl, nb, nb)).astype(np.float32))
    c = jnp.zeros((p * mtl, q * ntl, nb, nb), jnp.float32)
    jax.block_until_ready(bc(apan, bpan))          # compile outside timing
    jax.block_until_ready(ac(apan, bpan, c))
    with obs.record_spans() as rec:
        for _ in range(reps):
            with span(f"slate.{op}/bcast_ahead"):
                jax.block_until_ready(bc(apan, bpan))
            with span(f"slate.{op}/accumulate"):
                jax.block_until_ready(ac(apan, bpan, c))
    bts = [s["dur_ms"] for s in rec.spans
           if s["name"].endswith("/bcast_ahead")]
    ats = [s["dur_ms"] for s in rec.spans
           if s["name"].endswith("/accumulate")]
    hidden = sum(min(b, a) for b, a in zip(bts, ats))
    return 100.0 * hidden / max(sum(bts), 1e-12)


def bench_summa_lookahead(n, nb, iters):
    """Lookahead-pipelined SUMMA (PERF r15): GFLOP/s at depth 0 (the
    bulk-synchronous oracle) vs the tuned ring-pipeline depth, their
    ratio, and overlap_pct — how much of the per-step panel broadcast
    the trailing accumulate can hide.  Depths produce bit-identical
    output (tests/test_lookahead.py), so the speedup line is pure
    schedule, no numerics."""
    from slate_tpu.core.layout import num_tiles
    from slate_tpu.parallel.summa import summa_gemm_data
    from slate_tpu.tune import lookahead_depth

    g = _lookahead_grid()
    p, q = g.p, g.q
    rng = np.random.default_rng(15)
    a = rng.standard_normal((n, n)).astype(np.float32)
    b = rng.standard_normal((n, n)).astype(np.float32)
    A = st.Matrix.from_numpy(a, nb, nb, g)
    B = st.Matrix.from_numpy(b, nb, nb, g)
    C = st.Matrix.from_numpy(np.zeros((n, n), np.float32), nb, nb, g)
    Kt = num_tiles(n, nb)
    la = max(1, lookahead_depth(n, "float32"))

    flops = _flops.op_flops("gemm", [(n, n), (n, n)])
    gf = {}
    for depth in (0, la):
        def body(carry, ad, bd, depth=depth):
            return summa_gemm_data(ad, bd, carry, 1.0 / n, 0.0, Kt, g,
                                   la=depth)
        timed = _time_chain(body, C.storage.data,
                            (A.storage.data, B.storage.data), iters,
                            flops)
        gf[depth] = timed[0]
        _emit(f"summa_lookahead_d{depth}_n{n}_gflops", timed, flops,
              {"nb": nb, "grid": f"{p}x{q}", "la": depth})
    base = {"schema": BENCH_SCHEMA, "chip": CHIP}
    print(json.dumps({**base, "metric": f"summa_lookahead_speedup_n{n}",
                      "value": round(gf[la] / max(gf[0], 1e-9), 3),
                      "unit": "x", "la": la, "grid": f"{p}x{q}"}),
          flush=True)
    mtl = A.storage.data.shape[0] // p
    ntl = C.storage.data.shape[1] // q
    ov = _overlap_probe(g, mtl, ntl, nb, "gemm", both_axes=True)
    print(json.dumps({**base,
                      "metric": f"summa_lookahead_overlap_pct_n{n}",
                      "value": round(float(ov), 1), "unit": "%",
                      "grid": f"{p}x{q}", "nb": nb}), flush=True)


def bench_dist_chol_lookahead(n, nb, iters):
    """Lookahead-pipelined distributed Cholesky (PERF r15): same
    depth-0-vs-tuned pair as bench_summa_lookahead for dist_potrf —
    here the lookahead additionally pulls the NEXT panel's column
    factor forward, so the critical path drops by the panel latency,
    not just the broadcast."""
    from slate_tpu.parallel.dist_chol import dist_potrf
    from slate_tpu.tune import lookahead_depth

    g = _lookahead_grid()
    p, q = g.p, g.q
    rng = np.random.default_rng(16)
    # SPD without an O(n^3) host product (bench_posv idiom)
    a0 = rng.standard_normal((n, n)).astype(np.float32)
    a = (a0 + a0.T) * 0.001 + np.eye(n, dtype=np.float32) * 4.0
    H = st.HermitianMatrix.from_numpy(a, nb, st.Uplo.Lower, g)
    stg = H.storage
    la = max(1, lookahead_depth(n, "float32"))

    flops = _flops.op_flops("potrf", [(n, n)])
    gf = {}
    for depth in (0, la):
        def body(carry, data, depth=depth):
            out = dist_potrf(data * (1.0 + carry), stg.Nt, g, stg.n,
                             abft=False, la=depth)
            return out[0][0, 0, 0, 0] * 1e-24
        timed = _time_chain(body, jnp.float32(0.0), (stg.data,), iters,
                            flops)
        gf[depth] = timed[0]
        _emit(f"dist_chol_lookahead_d{depth}_n{n}_gflops", timed, flops,
              {"nb": nb, "grid": f"{p}x{q}", "la": depth})
    base = {"schema": BENCH_SCHEMA, "chip": CHIP}
    print(json.dumps({**base,
                      "metric": f"dist_chol_lookahead_speedup_n{n}",
                      "value": round(gf[la] / max(gf[0], 1e-9), 3),
                      "unit": "x", "la": la, "grid": f"{p}x{q}"}),
          flush=True)
    mtl = stg.data.shape[0] // p
    ntl = stg.data.shape[1] // q
    ov = _overlap_probe(g, mtl, ntl, nb, "potrf", both_axes=False)
    print(json.dumps({**base,
                      "metric": f"dist_chol_lookahead_overlap_pct_n{n}",
                      "value": round(float(ov), 1), "unit": "%",
                      "grid": f"{p}x{q}", "nb": nb}), flush=True)


def bench_serve_mixed(problems, nrhs, reps, sizes):
    """Serving throughput (PR 10): a fixed seeded mixed workload — three
    ops round-robin over ``sizes`` — through serve.Server.  The first
    pass compiles every bucket executable (the "compile" phase the
    watchdog may preempt); the timed passes are pure cache hits, so the
    problems/s number is steady-state serving throughput.  Padding
    waste is the workload-weighted mean of the per-batch obs events.
    Emits its own lines: _emit hardcodes the GFLOP/s unit and these
    metrics are problems/s and %."""
    from slate_tpu import obs, serve

    rng = np.random.default_rng(10)
    ops = ("solve", "chol_solve", "least_squares_solve")
    reqs = []
    for i in range(problems):
        n = int(sizes[i % len(sizes)])
        op = ops[i % len(ops)]
        dt = np.float32
        if op == "least_squares_solve":
            a = rng.standard_normal((n + 8, n)).astype(dt)
            b = rng.standard_normal((n + 8, nrhs)).astype(dt)
        else:
            a = rng.standard_normal((n, n)).astype(dt)
            if op == "chol_solve":
                a = (a @ a.T / n + np.eye(n, dtype=dt)).astype(dt)
            else:
                a = a + np.eye(n, dtype=dt) * 4.0
            b = rng.standard_normal((n, nrhs)).astype(dt)
        reqs.append((op, a, b))

    srv = serve.Server(cache=serve.ExecutableCache())
    _PROGRESS["phase"] = "compile"
    with obs.recording() as warm_events:
        srv.serve_batch(reqs)              # compiles every bucket
    _PROGRESS["phase"] = "run"
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        srv.serve_batch(reqs)
        times.append(time.perf_counter() - t0)
    pps = problems / min(times)
    ev = [e for e in warm_events if e.get("kind") == "serve_batch"]
    waste = (sum(e["padding_waste"] * e["problems"] for e in ev)
             / max(sum(e["problems"] for e in ev), 1))
    base = {"schema": BENCH_SCHEMA, "chip": CHIP}
    print(json.dumps({**base, "metric": "serve_mixed_problems_per_s",
                      "value": round(float(pps), 2), "unit": "problems/s",
                      "n": problems}), flush=True)
    print(json.dumps({**base, "metric": "serve_mixed_padding_waste_pct",
                      "value": round(100.0 * float(waste), 2),
                      "unit": "%", "n": problems}), flush=True)


def bench_serve_ragged(problems, nrhs, reps, bucket):
    """Ragged vs vmapped-XLA serving cores (PERF r11): one seeded
    mixed-size workload (sizes spanning 1 .. the full bucket) through
    two Servers on a single-rung ladder — one with Pallas plans
    overridden onto the batch_* ops so `tune.resolve_plan` routes the
    fast rung through the ragged batched kernels, one resolving the
    default XLA plans (vmapped full-bucket route).  Reports raw and
    padding-waste-adjusted problems/s per route — adjusted = raw /
    (1 - waste), throughput per unit of LIVE work, the number the
    ragged grids improve — plus the raw ragged/xla speedup.  Emits its
    own lines: these metrics are problems/s, % and x, not GFLOP/s."""
    import contextlib

    from slate_tpu import obs, serve, tune
    from slate_tpu.serve import bucket as _bucket
    from slate_tpu.tune import TilePlan

    rng = np.random.default_rng(11)
    ops = ("solve", "chol_solve", "least_squares_solve")
    szs = (1, max(bucket // 3, 1), max(bucket - 17, 1), bucket)
    reqs = []
    for i in range(problems):
        n = int(szs[i % len(szs)])
        op = ops[i % len(ops)]
        dt = np.float32
        a = rng.standard_normal((n, n)).astype(dt)
        if op == "chol_solve":
            a = (a @ a.T / n + np.eye(n, dtype=dt)).astype(dt)
        elif op == "solve":
            a = a + np.eye(n, dtype=dt) * 4.0
        # least squares keeps m = n so all three ops share the single
        # bucket (mb = bucket_for(m + nb - n) = the one rung)
        b = rng.standard_normal((n, nrhs)).astype(dt)
        reqs.append((op, a, b))

    ladder = _bucket.BucketLadder((int(bucket),), "tuned")
    plan = TilePlan("pallas", min(128, int(bucket)), 8)
    stats = {}
    for route in ("ragged", "xla"):
        srv = serve.Server(ladder=ladder, cache=serve.ExecutableCache())
        _PROGRESS["phase"] = f"compile:{route}"
        with contextlib.ExitStack() as stack:
            if route == "ragged":
                for bop in ("batch_potrf", "batch_getrf", "batch_geqrf"):
                    stack.enter_context(tune.plan_override(bop, plan))
            with obs.recording() as warm_events:
                srv.serve_batch(reqs)      # compiles every bucket
        _PROGRESS["phase"] = f"run:{route}"
        times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            srv.serve_batch(reqs)
            times.append(time.perf_counter() - t0)
        ev = [e for e in warm_events if e.get("kind") == "serve_batch"]
        waste = (sum(e["padding_waste"] * e["problems"] for e in ev)
                 / max(sum(e["problems"] for e in ev), 1))
        stats[route] = (problems / min(times), float(waste))

    base = {"schema": BENCH_SCHEMA, "chip": CHIP}
    print(json.dumps({**base, "metric": "serve_ragged_padding_waste_pct",
                      "value": round(100.0 * stats["ragged"][1], 2),
                      "unit": "%", "n": problems}), flush=True)
    for route, (raw, waste) in stats.items():
        print(json.dumps({
            **base, "metric": f"serve_ragged_{route}_problems_per_s",
            "value": round(float(raw), 2), "unit": "problems/s",
            "n": problems}), flush=True)
        print(json.dumps({
            **base,
            "metric": f"serve_ragged_{route}_adjusted_problems_per_s",
            "value": round(float(raw / max(1.0 - waste, 1e-9)), 2),
            "unit": "problems/s", "n": problems}), flush=True)
    print(json.dumps({**base, "metric": "serve_ragged_speedup",
                      "value": round(stats["ragged"][0]
                                     / max(stats["xla"][0], 1e-9), 3),
                      "unit": "x", "n": problems}), flush=True)


def bench_serve_bf16(problems, nrhs, reps, bucket):
    """Certified bf16 serving rung vs the f32-only route (PERF r18): one
    seeded mixed workload (three ops, sizes spanning the bucket, all
    f32 requests) through two Servers on a single-rung ladder — one with
    ``Option.Precision = bf16`` (the certified low-precision rung below
    the f32 ladder, serve/batched.py) and one f32-only.  Reports raw and
    padding-waste-adjusted problems/s for BOTH routes, the certificate
    accept-rate over live slots (accepted = not escalated; escalations
    land on results bit-identical to the f32 route), and the bf16/f32
    speedup.  On CPU the rung computes both the bf16 attempt and its f32
    escalation target, so the speedup reads BELOW 1 there — the honest
    number; the >= 1.6x target is a TPU goal (docs/PERF.md round 18).
    Emits its own lines: problems/s, % and x, not GFLOP/s."""
    from slate_tpu import Option, Precision, obs, serve
    from slate_tpu.serve import bucket as _bucket

    rng = np.random.default_rng(18)
    ops = ("solve", "chol_solve", "least_squares_solve")
    szs = (max(bucket // 4, 1), max(bucket // 2, 1), max(bucket - 9, 1),
           bucket)
    reqs = []
    for i in range(problems):
        n = int(szs[i % len(szs)])
        op = ops[i % len(ops)]
        dt = np.float32
        a = rng.standard_normal((n, n)).astype(dt)
        if op == "chol_solve":
            a = (a @ a.T / n + np.eye(n, dtype=dt)).astype(dt)
        elif op == "solve":
            a = a + np.eye(n, dtype=dt) * 4.0
        # least squares keeps m = n so all three ops share the one rung
        b = rng.standard_normal((n, nrhs)).astype(dt)
        reqs.append((op, a, b))

    ladder = _bucket.BucketLadder((int(bucket),), "tuned")
    opts_by_route = {"bf16": {Option.Precision: Precision.Bf16},
                     "f32": None}
    stats, accept = {}, None
    for route, opts in opts_by_route.items():
        srv = serve.Server(opts=opts, ladder=ladder,
                           cache=serve.ExecutableCache())
        _PROGRESS["phase"] = f"compile:{route}"
        with obs.recording() as warm_events:
            srv.serve_batch(reqs)          # compiles every bucket
        _PROGRESS["phase"] = f"run:{route}"
        times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            srv.serve_batch(reqs)
            times.append(time.perf_counter() - t0)
        ev = [e for e in warm_events if e.get("kind") == "serve_batch"]
        waste = (sum(e["padding_waste"] * e["problems"] for e in ev)
                 / max(sum(e["problems"] for e in ev), 1))
        stats[route] = (problems / min(times), float(waste))
        if route == "bf16":
            live = max(sum(e["problems"] for e in ev), 1)
            esc = sum(e["escalated"] for e in ev)
            accept = 1.0 - esc / live

    base = {"schema": BENCH_SCHEMA, "chip": CHIP}
    for route, (raw, waste) in stats.items():
        print(json.dumps({
            **base, "metric": f"serve_precision_{route}_problems_per_s",
            "value": round(float(raw), 2), "unit": "problems/s",
            "n": problems}), flush=True)
        print(json.dumps({
            **base,
            "metric": f"serve_precision_{route}_adjusted_problems_per_s",
            "value": round(float(raw / max(1.0 - waste, 1e-9)), 2),
            "unit": "problems/s", "n": problems}), flush=True)
    print(json.dumps({**base, "metric": "serve_precision_accept_rate_pct",
                      "value": round(100.0 * float(accept), 2),
                      "unit": "%", "n": problems}), flush=True)
    print(json.dumps({**base, "metric": "serve_precision_bf16_speedup",
                      "value": round(stats["bf16"][0]
                                     / max(stats["f32"][0], 1e-9), 3),
                      "unit": "x", "n": problems}), flush=True)


def bench_serve_survival(problems, rate_hz, nrhs, sizes, budget_ms):
    """Survival-layer throughput (robustness PR): a seeded Poisson
    arrival stream (robust.faults.poisson_workload) replayed against a
    LIVE Server — background flush loop, deadline-aware admission,
    shed_oldest overflow, SLO governor — instead of the offline
    serve_batch path the other serve benches time.  Reports admitted
    problems/s over the replay wall time, delivered p99 latency, the
    shed and quarantine rates per 1k, and an ``slo_pass`` verdict from
    slo.evaluate over the recorded event stream (p99 must hold the
    declared budget for what the server chose to ADMIT — shedding is
    how it keeps that promise under overload).  Emits its own lines:
    these metrics are problems/s, ms and per-1k rates, not GFLOP/s."""
    from slate_tpu import obs, serve
    from slate_tpu.obs import slo as _slo
    from slate_tpu.robust import faults as _faults

    work = _faults.poisson_workload(16, problems, rate_hz, sizes,
                                    nrhs=nrhs)
    cfg = serve.AdmissionConfig(
        max_queue=max(problems // 4, 8), overflow="shed_oldest",
        flush_occupancy=max(problems // 8, 4), max_batch_delay_ms=10.0,
        slo_budget_ms=float(budget_ms), watchdog_timeout_s=120.0)
    srv = serve.Server(cache=serve.ExecutableCache(), admission=cfg)
    _PROGRESS["phase"] = "compile"
    srv.serve_batch([(op, a, b) for _, op, a, b in work])  # warm buckets
    _PROGRESS["phase"] = "run"
    srv.start()
    tickets, shed = [], 0
    t0 = time.perf_counter()
    with obs.recording() as events:
        for t_arr, op, a, b in work:
            lag = t_arr - (time.perf_counter() - t0)
            if lag > 0:
                time.sleep(lag)
            try:
                tickets.append(srv.submit(op, a, b))
            except Exception:          # typed shed/overflow: counted
                shed += 1
        for tk in tickets:
            try:
                tk.result(timeout=60.0)
            except Exception:
                shed += 1
        wall = time.perf_counter() - t0
        srv.shutdown()
    stats = _slo.aggregate(list(events))
    union = stats.get("*", {})
    verdicts = _slo.evaluate(stats, {"*": {"latency_p99_ms": budget_ms}})
    served = union.get("problems", 0)
    base = {"schema": BENCH_SCHEMA, "chip": CHIP}
    print(json.dumps({**base, "metric": "serve_survival_problems_per_s",
                      "value": round(served / max(wall, 1e-9), 2),
                      "unit": "problems/s", "n": problems}), flush=True)
    print(json.dumps({**base, "metric": "serve_survival_latency_p99_ms",
                      "value": union.get("latency_p99_ms"),
                      "unit": "ms", "n": problems}), flush=True)
    print(json.dumps({**base, "metric": "serve_survival_shed_per_1k",
                      "value": round(1000.0 * shed
                                     / max(problems, 1), 2),
                      "unit": "per_1k", "n": problems}), flush=True)
    print(json.dumps({**base, "metric": "serve_survival_quar_per_1k",
                      "value": union.get("quar_per_1k", 0.0),
                      "unit": "per_1k", "n": problems}), flush=True)
    print(json.dumps({**base, "metric": "serve_survival_slo_pass",
                      "value": int(all(v["ok"] for v in verdicts)),
                      "unit": "bool", "n": problems}), flush=True)


def bench_serve_pool(problems, rate_hz, nrhs, sizes, members):
    """Elastic device pool (robustness PR): the same seeded Poisson
    mixed-size stream replayed against a 1-member server and a
    ``members``-wide DevicePool server, with a transient device kill and
    online retuning live on the pool run.  Reports the pool's admitted
    problems/s and its scaling over one device (on a single-chip host
    the members share the device, so ~1.0x is the honest answer — the
    line exists to price the pool machinery, not to fake speedup), the
    failover recovery wall (failover record -> the survivor's completed
    redispatch), and the retune hot-swap count.  Emits its own lines:
    problems/s, x, ms and a count, not GFLOP/s."""
    from slate_tpu import obs, serve
    from slate_tpu.robust import faults as _faults

    def replay(srv, plans=()):
        work = _faults.poisson_workload(16, problems, rate_hz, sizes,
                                        nrhs=nrhs)
        srv.serve_batch([(op, a, b) for _, op, a, b in work])  # warm
        srv.start()
        t0 = time.perf_counter()
        with obs.recording() as events:
            with _faults.inject(*plans):
                tickets = []
                for t_arr, op, a, b in work:
                    lag = t_arr - (time.perf_counter() - t0)
                    if lag > 0:
                        time.sleep(lag)
                    tickets.append(srv.submit(op, a, b))
                done = sum(tk.result(timeout=120.0) is not None
                           for tk in tickets)
            wall = time.perf_counter() - t0
            srv.shutdown()
        return done / max(wall, 1e-9), list(events)

    cfg = dict(max_queue=max(problems, 8),
               flush_occupancy=max(problems // 8, 4),
               max_batch_delay_ms=10.0, watchdog_timeout_s=120.0)
    _PROGRESS["phase"] = "compile"
    one, _ = replay(serve.Server(
        cache=serve.ExecutableCache(),
        admission=serve.AdmissionConfig(**cfg)))
    _PROGRESS["phase"] = "run"
    devs = jax.local_devices()
    devs = (devs * members)[:members] if len(devs) < members \
        else devs[:members]
    pool = serve.DevicePool(devs, serve.PoolConfig(strike_limit=1))
    srv = serve.Server(
        cache=serve.ExecutableCache(), pool=pool,
        admission=serve.AdmissionConfig(
            **cfg, retune_interval_s=0.25, retune_min_samples=32))
    kill = _faults.FaultPlan("serve_device_fail", transient=True,
                             device=0)
    rate, events = replay(srv, plans=(kill,))
    fo = [e for e in events if e.get("kind") == "serve_device"
          and e.get("event") == "failover"]
    recovery = None
    if fo:
        after = [e["ts"] for e in events if e.get("kind") == "serve_batch"
                 and e["ts"] >= fo[0]["ts"]]
        if after:
            recovery = round(1e3 * (after[0] - fo[0]["ts"]), 2)
    swaps = sum(1 for e in events if e.get("kind") == "serve_retune")
    base = {"schema": BENCH_SCHEMA, "chip": CHIP}
    print(json.dumps({**base, "metric": "serve_pool_problems_per_s",
                      "value": round(rate, 2),
                      "unit": "problems/s", "n": problems}), flush=True)
    print(json.dumps({**base, "metric": "serve_pool_scaling",
                      "value": round(rate / max(one, 1e-9), 3),
                      "unit": "x", "n": members}), flush=True)
    print(json.dumps({**base, "metric": "serve_pool_failover_recovery_ms",
                      "value": recovery, "unit": "ms",
                      "n": problems}), flush=True)
    print(json.dumps({**base, "metric": "serve_pool_retune_swaps",
                      "value": swaps, "unit": "count",
                      "n": problems}), flush=True)


def bench_potrf_ooc(n, nb, iters):
    """Out-of-core Cholesky throughput (durability PR): the host-resident
    TileMap streaming path — every panel round-trips host<->device with
    the next left panel prefetched behind the trailing update — against
    the in-core potrf at the same size, so the line prices what the
    host-offload axis costs.  Emits its own lines: the absolute GFLOP/s
    of the streaming factorization and its slowdown vs in-core."""
    rng = np.random.default_rng(0)
    a = rng.standard_normal((n, n)).astype(np.float32)
    spd = a @ a.T + n * np.eye(n, dtype=np.float32)
    flops = n ** 3 / 3.0
    _PROGRESS["phase"] = "compile"
    st.potrf_ooc(spd, nb=nb)                    # compile + warmup
    A = st.SymmetricMatrix(TileStorage.from_dense(spd, nb, nb),
                           uplo=st.Uplo.Lower)
    st.potrf(A)
    _PROGRESS["phase"] = "run"
    t_ooc = min(_walltime(lambda: st.potrf_ooc(spd, nb=nb))
                for _ in range(iters))
    t_inc = min(_walltime(lambda: np.asarray(st.potrf(A).to_dense()))
                for _ in range(iters))
    base = {"schema": BENCH_SCHEMA, "chip": CHIP}
    print(json.dumps({**base, "metric": "durability_potrf_ooc_gflops",
                      "value": round(flops / t_ooc / 1e9, 2),
                      "unit": "GFLOP/s", "n": n}), flush=True)
    print(json.dumps({**base, "metric": "durability_potrf_ooc_slowdown",
                      "value": round(t_ooc / max(t_inc, 1e-9), 3),
                      "unit": "x", "n": n}), flush=True)


def bench_checkpoint_overhead(n, nb, iters):
    """Panel-boundary checkpoint cost (durability PR): the same
    out-of-core Cholesky with a CheckpointManager snapshotting at EVERY
    panel step (the worst-case cadence) vs checkpointing off.  Reports
    the relative overhead and the per-snapshot wall cost — the number a
    user trades against their preemption rate when picking ``every``."""
    import shutil
    import tempfile
    from slate_tpu.robust import CheckpointManager

    rng = np.random.default_rng(0)
    a = rng.standard_normal((n, n)).astype(np.float32)
    spd = a @ a.T + n * np.eye(n, dtype=np.float32)
    nsteps = -(-n // nb)
    _PROGRESS["phase"] = "compile"
    st.potrf_ooc(spd, nb=nb)                    # compile + warmup
    _PROGRESS["phase"] = "run"
    t_off = min(_walltime(lambda: st.potrf_ooc(spd, nb=nb))
                for _ in range(iters))
    t_on = []
    for _ in range(iters):
        d = tempfile.mkdtemp(prefix="slate_bench_ckpt_")
        try:
            cm = CheckpointManager(d, every=1)
            t_on.append(_walltime(
                lambda: st.potrf_ooc(spd, nb=nb, checkpoint=cm)))
        finally:
            shutil.rmtree(d, ignore_errors=True)
    t_on = min(t_on)
    base = {"schema": BENCH_SCHEMA, "chip": CHIP}
    print(json.dumps({**base, "metric": "durability_ckpt_overhead_pct",
                      "value": round(100.0 * (t_on - t_off)
                                     / max(t_off, 1e-9), 2),
                      "unit": "%", "n": n}), flush=True)
    print(json.dumps({**base, "metric": "durability_ckpt_save_ms",
                      "value": round(1e3 * (t_on - t_off)
                                     / max(nsteps, 1), 3),
                      "unit": "ms", "n": n}), flush=True)


def _walltime(fn):
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


QUICK_STEPS = [
    (bench_gemm, dict(n=512, nb=128, iters=4)),
    (bench_posv, dict(n=768, nb=128, nrhs=64, iters=2)),
    (bench_gesv, dict(n=768, nb=128, nrhs=64, iters=2)),
    (bench_gesv_rbt, dict(n=768, nb=128, nrhs=64, iters=2)),
    (bench_gesv_abft, dict(n=768, nb=128, nrhs=64, iters=2)),
    (bench_posv_abft, dict(n=768, nb=128, nrhs=64, iters=2)),
    (bench_geqrf, dict(m=4096, n=256, nb=128, iters=2)),
    (bench_gels, dict(m=4096, n=256, nb=128, nrhs=16, iters=2)),
    (bench_heev, dict(n=512, nb=128, iters=2)),
    (bench_svd, dict(n=512, nb=128, iters=2)),
    (bench_potrf_fused, dict(n=256, nb=128, bw=8, iters=2)),
    (bench_geqrf_panel, dict(m=512, n=128, iters=2)),
    (bench_summa_lookahead, dict(n=512, nb=128, iters=2)),
    (bench_dist_chol_lookahead, dict(n=768, nb=128, iters=2)),
    (bench_serve_mixed, dict(problems=24, nrhs=4, reps=2,
                             sizes=(24, 48, 96))),
    (bench_serve_ragged, dict(problems=12, nrhs=4, reps=2, bucket=32)),
    (bench_serve_bf16, dict(problems=12, nrhs=4, reps=2, bucket=32)),
    (bench_serve_survival, dict(problems=24, rate_hz=400.0, nrhs=4,
                                sizes=(24, 48), budget_ms=5000.0)),
    (bench_serve_pool, dict(problems=24, rate_hz=400.0, nrhs=4,
                            sizes=(40, 96), members=2)),
    (bench_potrf_ooc, dict(n=192, nb=64, iters=2)),
    (bench_checkpoint_overhead, dict(n=192, nb=64, iters=2)),
]

FULL_STEPS = [
    (bench_gemm, dict(n=4096, nb=256, iters=50)),
    (bench_gemm, dict(n=8192, nb=512, iters=20)),
    (bench_gemm, dict(n=16384, nb=1024, iters=8)),
    (bench_posv, dict(n=16384, nb=512, nrhs=256, iters=5)),
    (bench_gesv, dict(n=16384, nb=512, nrhs=256, iters=4)),
    (bench_gesv_rbt, dict(n=16384, nb=512, nrhs=256, iters=4)),
    (bench_gesv_abft, dict(n=16384, nb=512, nrhs=256, iters=3)),
    (bench_posv_abft, dict(n=16384, nb=512, nrhs=256, iters=3)),
    (bench_geqrf, dict(m=131072, n=1024, nb=256, iters=4)),
    (bench_gels, dict(m=131072, n=1024, nb=256, nrhs=64, iters=4)),
    (bench_heev, dict(n=4096, nb=256, iters=3)),
    (bench_svd, dict(n=2048, nb=256, iters=3)),
    (bench_potrf_fused, dict(n=4096, nb=256, bw=8, iters=10)),
    (bench_geqrf_panel, dict(m=8192, n=256, iters=10)),
    (bench_summa_lookahead, dict(n=8192, nb=256, iters=8)),
    (bench_dist_chol_lookahead, dict(n=16384, nb=512, iters=3)),
    (bench_serve_mixed, dict(problems=96, nrhs=16, reps=3,
                             sizes=(48, 96, 160, 320))),
    (bench_serve_ragged, dict(problems=48, nrhs=16, reps=3, bucket=256)),
    (bench_serve_bf16, dict(problems=48, nrhs=16, reps=3, bucket=256)),
    (bench_serve_survival, dict(problems=192, rate_hz=800.0, nrhs=16,
                                sizes=(48, 96, 160), budget_ms=2000.0)),
    (bench_serve_pool, dict(problems=192, rate_hz=800.0, nrhs=16,
                            sizes=(96, 160, 320), members=4)),
    (bench_potrf_ooc, dict(n=4096, nb=512, iters=3)),
    (bench_checkpoint_overhead, dict(n=4096, nb=512, iters=3)),
]


class _BudgetExceeded(Exception):
    """Raised by the SIGALRM handler when a metric overruns the pool."""


def _skip_line(fn, reason, phase=None, elapsed_s=None):
    line = {
        "schema": BENCH_SCHEMA,
        "metric": f"{fn.__name__}_skipped", "value": None,
        "unit": "GFLOP/s", "vs_baseline": None,
        "skipped": True, "reason": reason, "chip": CHIP,
    }
    if phase is not None:
        line["phase"] = phase
    if elapsed_s is not None:
        line["elapsed_s"] = round(float(elapsed_s), 1)
    print(json.dumps(line), flush=True)


# Test seam: the watchdog's hard exit.  os._exit (not sys.exit) because the
# whole point is escaping a thread blocked inside a C++ compile that Python
# exceptions and SIGALRM cannot reach (the BENCH r05 rc=124 stall).
_EXIT = os._exit
_WATCHDOG_GRACE_S = 10.0


def _install_watchdog(steps, deadline, done, exit_fn=None):
    """Arm a daemon thread that hard-exits 0 just past ``deadline``.

    SIGALRM preemption (below) only works when the main thread is running
    Python bytecode; the r05 rc=124 came from a metric stuck inside a
    blocking C++ compile, where the alarm is queued but never delivered.
    The watchdog runs on its own thread, so it fires regardless: it emits
    a "skipped" line for every step index not yet in ``done`` (index, not
    fn — FULL_STEPS repeats bench_gemm) and then exits 0 so the external
    GNU ``timeout`` never gets the chance to return 124.

    Returns a threading.Event; set() it to stand the watchdog down.
    """
    stop = threading.Event()
    grace_deadline = deadline + _WATCHDOG_GRACE_S

    def _watch():
        while not stop.is_set():
            remaining = grace_deadline - time.monotonic()
            if remaining <= 0:
                break
            stop.wait(min(remaining, 1.0))
        if stop.is_set():
            return
        for idx, (fn, _) in enumerate(steps):
            if idx not in done:
                if idx == _PROGRESS["idx"] and _PROGRESS["t0"] is not None:
                    _skip_line(fn, "time budget exceeded (watchdog)",
                               phase=_PROGRESS["phase"],
                               elapsed_s=time.monotonic() - _PROGRESS["t0"])
                else:
                    _skip_line(fn, "time budget exceeded (watchdog)")
        (exit_fn or _EXIT)(0)

    threading.Thread(target=_watch, name="bench-watchdog",
                     daemon=True).start()
    return stop


def _run_isolated(steps, budget_s=None, done=None, deadline=None):
    """Run each benchmark in isolation: one flake (e.g. a remote-compile
    tunnel error) must still let every other metric emit — the r04 run lost
    heev AND svd to a single transient (VERDICT r4 weak #3).

    ``budget_s`` (SLATE_BENCH_BUDGET_S) grants the run a pool of
    budget_s * len(steps) seconds.  A metric facing an exhausted pool is
    skipped up front; one that overruns the pool mid-flight is preempted
    by SIGALRM (main thread only — signals cannot interrupt other
    threads).  Either way the metric emits an explicit "skipped" JSON
    line, so the output always has one line per step and the r05 timeout
    (rc=124, zero lines after the stall) cannot recur.

    ``done``/``deadline`` let main() share progress with the watchdog
    thread (_install_watchdog): completed step INDICES are added to
    ``done`` so a watchdog firing mid-run only skip-reports the metrics
    that have not emitted yet."""
    failures = 0
    can_alarm = (budget_s and hasattr(signal, "setitimer")
                 and threading.current_thread() is threading.main_thread())
    if deadline is None:
        deadline = (time.monotonic() + budget_s * len(steps)
                    if budget_s else None)
    for idx, (fn, kwargs) in enumerate(steps):
        if deadline is not None:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                _skip_line(fn, "time budget exhausted")
                if done is not None:
                    done.add(idx)
                continue
        if can_alarm:
            def _on_alarm(signum, frame):
                raise _BudgetExceeded
            prev = signal.signal(signal.SIGALRM, _on_alarm)
            signal.setitimer(signal.ITIMER_REAL, remaining)
        _PROGRESS.update(idx=idx, phase="compile", t0=time.monotonic())
        try:
            fn(**kwargs)
        except _BudgetExceeded:
            _skip_line(fn, "time budget exceeded (preempted)",
                       phase=_PROGRESS["phase"],
                       elapsed_s=time.monotonic() - _PROGRESS["t0"])
        except Exception as exc:  # noqa: BLE001 — isolate, report, continue
            failures += 1
            print(json.dumps({
                "schema": BENCH_SCHEMA,
                "metric": f"{fn.__name__}_error", "value": None,
                "unit": "GFLOP/s", "vs_baseline": None, "chip": CHIP,
                "error": f"{type(exc).__name__}: {exc}"[:300],
            }), flush=True)
        finally:
            if done is not None:
                done.add(idx)
            if can_alarm:
                signal.setitimer(signal.ITIMER_REAL, 0)
                signal.signal(signal.SIGALRM, prev)
    return failures


def sweep_nb():
    """Emit one JSON line per candidate (kernel, nb, bw) plan per op —
    the autotuner's raw search space (slate_tpu.tune.autotune.sweep), so
    BENCH rounds record what the tuner saw, not just the winner."""
    from slate_tpu.tune import autotune, chip_kind

    chip = chip_kind()
    sizes = {
        "potrf_tile": 256 if QUICK else 512,
        "potrf_panel": 512 if QUICK else 2048,
        "getrf_panel": 512 if QUICK else 2048,
        "lu_select": 512 if QUICK else 2048,
        "geqrf_panel": 512 if QUICK else 8192,
        "batch_potrf": 128 if QUICK else 256,
        "batch_getrf": 128 if QUICK else 256,
        "batch_geqrf": 128 if QUICK else 256,
    }
    iters = 1 if QUICK else 3
    from slate_tpu.tune import OPS
    for op in OPS:
        n = sizes[op]
        try:
            for plan, gflops in autotune.sweep(op, n, "float32",
                                               iters=iters):
                print(json.dumps({
                    "schema": BENCH_SCHEMA,
                    "metric": f"sweep_{op}_n{n}", "op": op, "n": n,
                    "kernel": plan.kernel, "nb": plan.nb, "bw": plan.bw,
                    "value": round(float(gflops), 1), "unit": "GFLOP/s",
                    "chip": chip,
                }), flush=True)
        except Exception as exc:  # noqa: BLE001 — isolate, report, continue
            print(json.dumps({
                "schema": BENCH_SCHEMA,
                "metric": f"sweep_{op}_n{n}_error", "value": None,
                "unit": "GFLOP/s", "vs_baseline": None, "chip": chip,
                "error": f"{type(exc).__name__}: {exc}"[:300],
            }), flush=True)


def main(argv=()):
    """Always exits 0: per-metric failures and budget skips are REPORTED
    (their JSON lines carry "error"/"skipped"), not escalated to a
    process failure — a harness that dies with rc=1/rc=124 loses every
    remaining metric (BENCH_r04/r05).

    The watchdog is armed BEFORE the first device contact (_chip_peak,
    i.e. before any compile can block), so even a stall inside the very
    first compilation self-terminates with rc=0 and explicit skip lines
    instead of tripping the external timeout's rc=124."""
    global PEAK, CHIP
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--sweep-nb", action="store_true",
                        help="emit one line per candidate autotuner plan "
                             "instead of the headline metrics")
    args = parser.parse_args(list(argv))

    steps = [] if args.sweep_nb else (QUICK_STEPS if QUICK else FULL_STEPS)
    done, stop = set(), None
    if BUDGET_S:
        deadline = time.monotonic() + BUDGET_S * max(len(steps), 1)
        stop = _install_watchdog(steps, deadline, done)
    else:
        deadline = None

    try:
        PEAK, CHIP = _chip_peak()
        if args.sweep_nb:
            sweep_nb()
        else:
            _run_isolated(steps, budget_s=BUDGET_S or None,
                          done=done, deadline=deadline)
    finally:
        if stop is not None:
            stop.set()
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main(sys.argv[1:]))
